"""Change-log replay engine: framed wire bytes -> columnar batches.

BASELINE.json config 2 is "1M-row change-log replay (varint framing +
protobuf decode)".  The reference replays logs through its streaming
decoder one callback at a time (reference: decode.js:144-169); at 1M-row
scale the TPU framework replays a *resident log buffer* instead:

* the native frame splitter / record decoder (:mod:`.native`, C++ via
  ctypes) parses the whole buffer in two tight loops;
* results are **columnar, zero-copy**: uint32 columns for
  ``change/from/to`` and (offset, length) views into the log buffer for
  ``key/subset/value`` — exactly the ragged layout the device feed packs
  from without re-touching each record in Python;
* pure-Python fallbacks (driven by the same tests) cover toolchain-less
  hosts.

The columns feed both device pipelines: record payloads -> batched
BLAKE2b -> Merkle leaves (configs 3/5), values -> content chunking
(config 4).
"""

from __future__ import annotations

import ctypes
import dataclasses

import numpy as np

from ..obs.device import note_engine as _note_engine
from ..obs.metrics import OBS as _OBS
from ..wire.change_codec import Change, decode_change
from ..wire.framing import TYPE_BLOB, TYPE_CHANGE, TYPE_CHANGE_BATCH, \
    ProtocolError
from ..wire.varint import NeedMoreData, decode_uvarint
from . import native


@dataclasses.dataclass
class FrameIndex:
    """All complete frames of a log buffer (zero-copy offsets)."""

    buf: np.ndarray  # uint8 view of the log
    starts: np.ndarray  # int64 payload offsets
    lens: np.ndarray  # int64 payload lengths
    ids: np.ndarray  # uint8 type ids
    consumed: int  # bytes covered by complete frames (tail may be partial)

    def __len__(self) -> int:
        return len(self.starts)


@dataclasses.dataclass
class ChangeColumns:
    """Columnar decoded Change records over a shared log buffer.

    String/bytes fields are (offset, len) views; ``len == -1`` marks an
    absent optional (decoded as ``''``/``b''``, matching the reference's
    observed defaults, reference: test/basic.js:16).
    """

    buf: np.ndarray
    change: np.ndarray  # uint32
    from_: np.ndarray  # uint32
    to: np.ndarray  # uint32
    key_off: np.ndarray
    key_len: np.ndarray
    sub_off: np.ndarray
    sub_len: np.ndarray
    val_off: np.ndarray
    val_len: np.ndarray

    def __len__(self) -> int:
        return len(self.change)

    def _text(self, off: int, ln: int) -> str:
        return bytes(self.buf[off : off + ln]).decode("utf-8")

    def row(self, i: int) -> Change:
        """Materialize record ``i`` as a Change object (lazy, per row)."""
        return Change(
            key=self._text(self.key_off[i], self.key_len[i]),
            change=int(self.change[i]),
            from_=int(self.from_[i]),
            to=int(self.to[i]),
            value=(
                b""
                if self.val_len[i] < 0
                else bytes(self.buf[self.val_off[i] : self.val_off[i] + self.val_len[i]])
            ),
            subset=(
                "" if self.sub_len[i] < 0 else self._text(self.sub_off[i], self.sub_len[i])
            ),
        )


def _as_u8(data) -> np.ndarray:
    if isinstance(data, np.ndarray):
        return np.ascontiguousarray(data, dtype=np.uint8)
    return np.frombuffer(data, dtype=np.uint8)


def split_frames(data, allow_partial_tail: bool = False) -> FrameIndex:
    """Index every complete frame of a multibuffer stream.

    Raises ProtocolError on malformed varints or empty framed lengths;
    with ``allow_partial_tail=False`` a trailing incomplete frame is also
    an error (a *replay* log should be whole; streaming callers pass
    True and re-feed the tail).
    """
    buf = _as_u8(data)
    lib = native.get_lib()
    if _OBS.on:
        _note_engine("replay.split", "native" if lib is not None
                     else "python")
    if lib is not None:
        n, starts, lens, ids, consumed = _split_native(lib, buf)
    else:
        n, starts, lens, ids, consumed = _split_python(buf)
    if not allow_partial_tail and consumed != len(buf):
        raise ProtocolError(
            f"truncated frame at byte {consumed} of {len(buf)}"
        )
    return FrameIndex(buf, starts[:n], lens[:n], ids[:n], consumed)


def _split_native(lib, buf):
    # capacity: worst case one frame per 2 bytes (varint 1 + id, empty)
    cap = len(buf) // 2 + 1
    starts = np.empty(cap, dtype=np.int64)
    lens = np.empty(cap, dtype=np.int64)
    ids = np.empty(cap, dtype=np.uint8)
    consumed = ctypes.c_int64(0)
    err = ctypes.c_int64(0)
    n = lib.dat_split_frames(
        buf, len(buf), starts, lens, ids, cap,
        ctypes.byref(consumed), ctypes.byref(err),
    )
    if err.value == native.ERR_BAD_VARINT:
        raise ProtocolError("malformed varint in frame header")
    if err.value == native.ERR_BAD_RECORD:
        raise ProtocolError("framed length 0 (must include the id byte)")
    if n == native.ERR_CAPACITY:
        raise ProtocolError(
            f"frame count exceeds capacity estimate ({cap})"
        )
    if n < 0 or err.value != 0:
        raise ProtocolError(f"frame split failed (code {n}, err {err.value})")
    return int(n), starts, lens, ids, int(consumed.value)


def _split_python(buf):
    starts, lens, ids = [], [], []
    view = memoryview(buf)
    i, n = 0, len(buf)
    consumed = 0
    while i < n:
        try:
            framed, used = decode_uvarint(view, i)
        except NeedMoreData:
            break
        except ValueError as e:
            raise ProtocolError(str(e)) from e
        if framed == 0:
            raise ProtocolError("framed length 0 (must include the id byte)")
        end = i + used + framed
        if end > n:
            break
        ids.append(view[i + used])
        starts.append(i + used + 1)
        lens.append(framed - 1)
        i = end
        consumed = i
    return (
        len(starts),
        np.asarray(starts, dtype=np.int64),
        np.asarray(lens, dtype=np.int64),
        np.asarray(ids, dtype=np.uint8),
        consumed,
    )


def decode_change_columns(buf: np.ndarray, starts: np.ndarray,
                          lens: np.ndarray) -> ChangeColumns:
    """Decode the given record extents as Change rows, columnar."""
    n = len(starts)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    cols = ChangeColumns(
        buf=buf,
        change=np.zeros(n, dtype=np.uint32),
        from_=np.zeros(n, dtype=np.uint32),
        to=np.zeros(n, dtype=np.uint32),
        key_off=np.zeros(n, dtype=np.int64),
        key_len=np.full(n, -1, dtype=np.int64),
        sub_off=np.zeros(n, dtype=np.int64),
        sub_len=np.full(n, -1, dtype=np.int64),
        val_off=np.zeros(n, dtype=np.int64),
        val_len=np.full(n, -1, dtype=np.int64),
    )
    if n == 0:
        # nothing to decode — and the Python fallback below would copy
        # the WHOLE buffer just to build its memoryview (measured 50 ms
        # on a 40 MiB batch-framed log with zero per-record frames)
        return cols
    lib = native.get_lib()
    if lib is not None and n:
        err = ctypes.c_int64(-1)
        rc = lib.dat_decode_changes_mt(
            buf, starts, lens, n,
            cols.change, cols.from_, cols.to,
            cols.key_off, cols.key_len,
            cols.sub_off, cols.sub_len,
            cols.val_off, cols.val_len,
            ctypes.byref(err), native._nthreads(),
        )
        if rc != 0:
            raise ProtocolError(
                f"corrupt Change record at index {err.value}"
            )
        return cols
    # Python fallback: reuse the tested scalar codec per record
    view = memoryview(bytes(buf))
    for r in range(n):
        i, ln = int(starts[r]), int(lens[r])
        try:
            ch = decode_change(view[i : i + ln])
        except ValueError as e:
            raise ProtocolError(
                f"corrupt Change record at index {r}"
            ) from e
        cols.change[r] = ch.change
        cols.from_[r] = ch.from_
        cols.to[r] = ch.to
        # offsets for the fallback point at per-record copies; keep the
        # same (off, len) contract by re-locating within the buffer slice
        _fallback_locate(cols, r, buf, i, ln, ch)
    return cols


def _fallback_locate(cols, r, buf, start, ln, ch):
    """Populate (off, len) views for the Python path by re-scanning tags."""
    view = memoryview(buf)[start : start + ln]
    i, n = 0, ln
    while i < n:
        tag, used = decode_uvarint(view, i)
        i += used
        wt = tag & 7
        if wt == 0:
            _, used = decode_uvarint(view, i)
            i += used
        elif wt == 2:
            fl, used = decode_uvarint(view, i)
            i += used
            fno = tag >> 3
            if fno == 1:
                cols.sub_off[r], cols.sub_len[r] = start + i, fl
            elif fno == 2:
                cols.key_off[r], cols.key_len[r] = start + i, fl
            elif fno == 6:
                cols.val_off[r], cols.val_len[r] = start + i, fl
            i += fl
        elif wt == 5:
            i += 4
        else:
            i += 8


def encode_change_columns(cols: ChangeColumns) -> bytes:
    """Frame decoded columns straight back to wire bytes — zero Python
    per row.

    The true inverse of :func:`replay_log` for change frames:
    :class:`ChangeColumns` already holds exactly the layout the native
    bulk encoder consumes (one shared buffer + per-field extents, -1 =
    absent optional), so re-encoding a million-row log is a single C
    call — no Change objects, no per-row string encoding.  Byte-exact
    with the per-record codec (tested).  Blob frames are not part of
    the columns; a mixed log re-encodes as its change frames only.
    """
    from ..wire.change_codec import _encode_change_with, _fastpath_mod
    from ..wire.framing import TYPE_CHANGE, frame

    n = len(cols)
    if n == 0:
        return b""
    lib = native.get_lib()
    if lib is None:
        fp = _fastpath_mod()  # gate resolved once for the whole log
        # NOT cols.row(): that maps absent optionals to ''/b'' (the
        # reference's decoded defaults), which would re-encode them as
        # present-empty and break byte-exactness with the original wire
        def exact_row(r: int) -> Change:
            return Change(
                key=cols._text(cols.key_off[r], cols.key_len[r]),
                change=int(cols.change[r]),
                from_=int(cols.from_[r]),
                to=int(cols.to[r]),
                value=None if cols.val_len[r] < 0 else bytes(
                    cols.buf[cols.val_off[r]:cols.val_off[r] + cols.val_len[r]]
                ),
                subset=None if cols.sub_len[r] < 0 else cols._text(
                    cols.sub_off[r], cols.sub_len[r]
                ),
            )

        return b"".join(
            frame(TYPE_CHANGE, _encode_change_with(fp, exact_row(r)))
            for r in range(n)
        )
    total_payload = (
        int(cols.key_len.sum())
        + int(np.where(cols.sub_len > 0, cols.sub_len, 0).sum())
        + int(np.where(cols.val_len > 0, cols.val_len, 0).sum())
    )
    return _native_encode(
        lib, np.ascontiguousarray(cols.buf, dtype=np.uint8), total_payload, n,
        np.ascontiguousarray(cols.change, np.uint32),
        np.ascontiguousarray(cols.from_, np.uint32),
        np.ascontiguousarray(cols.to, np.uint32),
        np.ascontiguousarray(cols.key_off, np.int64),
        np.ascontiguousarray(cols.key_len, np.int64),
        np.ascontiguousarray(cols.sub_off, np.int64),
        np.ascontiguousarray(cols.sub_len, np.int64),
        np.ascontiguousarray(cols.val_off, np.int64),
        np.ascontiguousarray(cols.val_len, np.int64),
    )


def _native_encode(lib, src, payload_bytes: int, n, chg, frm, tov,
                   koff, klen, soff, slen, voff, vlen) -> bytes:
    """One owner of the dat_encode_changes call: capacity bound
    (header <= 6 + per-field tags/varints <= 1+5 each x 6 fields, so
    64/record is safe) + error check."""
    cap = int(payload_bytes + n * 64 + 64)
    dst = np.empty(cap, np.uint8)
    w = lib.dat_encode_changes_mt(
        src, n, chg, frm, tov, koff, klen, soff, slen, voff, vlen, dst, cap,
        native._nthreads(),
    )
    if w < 0:
        raise RuntimeError(f"native encode failed (code {w})")
    return dst[:w].tobytes()


def encode_change_log(records: list[Change | dict]) -> bytes:
    """Bulk-encode Change records as a framed wire log (replay_log's
    inverse; the high-rate encode path for log construction at 1M-row
    scale, where per-record Python framing costs more than everything
    downstream).  Uses the native columnar encoder when available, the
    scalar Python codec otherwise — byte-identical output either way
    (tested)."""
    from ..wire.change_codec import (
        _check_uint32,
        _encode_change_with,
        _fastpath_mod,
    )
    from ..wire.framing import frame

    # gate resolved ONCE for the whole log: the per-record env re-read
    # inside encode_change() is ~40% of a C-path record encode at this
    # loop's 1M-row scale (flip visibility stays per-bulk-call)
    fp = _fastpath_mod()
    if fp is not None:
        # with the C record serializer, a straight join beats the
        # columnar heap build below 2.4x (973k vs 400k rows/s measured):
        # the per-row Python there (from_dict + heap appends + array
        # stores) costs more than just encoding each record in C
        return b"".join(
            frame(TYPE_CHANGE, _encode_change_with(fp, r)) for r in records
        )
    lib = native.get_lib()
    if lib is None:
        return b"".join(
            frame(TYPE_CHANGE, _encode_change_with(fp, r)) for r in records
        )
    n = len(records)
    chg = np.empty(n, np.uint32)
    frm = np.empty(n, np.uint32)
    tov = np.empty(n, np.uint32)
    koff = np.empty(n, np.int64)
    klen = np.empty(n, np.int64)
    soff = np.empty(n, np.int64)
    slen = np.full(n, -1, np.int64)
    voff = np.empty(n, np.int64)
    vlen = np.full(n, -1, np.int64)
    heap = bytearray()
    for r, rec in enumerate(records):
        if isinstance(rec, dict):
            rec = Change.from_dict(rec)
        if rec.key is None:
            raise ValueError("Change.key is required")
        kb = rec.key.encode("utf-8")
        koff[r], klen[r] = len(heap), len(kb)
        heap += kb
        if rec.subset is not None:
            sb = rec.subset.encode("utf-8")
            soff[r], slen[r] = len(heap), len(sb)
            heap += sb
        else:
            soff[r] = 0
        if rec.value is not None:
            voff[r], vlen[r] = len(heap), len(rec.value)
            heap += bytes(rec.value)
        else:
            voff[r] = 0
        chg[r] = _check_uint32("change", rec.change)
        frm[r] = _check_uint32("from", rec.from_)
        tov[r] = _check_uint32("to", rec.to)
    # np.frombuffer reads the bytearray zero-copy (the C side takes
    # const uint8*); heap stays alive via src for the call's duration
    src = np.frombuffer(heap, np.uint8) if heap else np.zeros(1, np.uint8)
    return _native_encode(
        lib, src, len(heap), n, chg, frm, tov,
        koff, klen, soff, slen, voff, vlen,
    )


def replay_log(data) -> tuple[ChangeColumns, FrameIndex]:
    """Replay a whole change-log buffer: config-2's engine.

    Returns the decoded change columns plus the full frame index (blob
    frames stay as extents in the index for the blob pipelines).
    Handles per-record ``Change`` frames, negotiated columnar
    ``ChangeBatch`` frames, and any interleaving of the two — rows come
    back in wire order either way, with every string/bytes extent
    addressing the ONE log buffer (batch extents are decoded with their
    payload's absolute base offset).  Unknown frame type ids raise
    ProtocolError, mirroring the decoder's fail-fast
    (reference: decode.js:159-161).
    """
    frames = split_frames(data)
    known = ((frames.ids == TYPE_CHANGE) | (frames.ids == TYPE_BLOB)
             | (frames.ids == TYPE_CHANGE_BATCH))
    if not bool(known.all()):
        bad = int(frames.ids[~known][0])
        raise ProtocolError(f"Protocol error, unknown type: {bad}")
    sel = frames.ids == TYPE_CHANGE
    bsel = frames.ids == TYPE_CHANGE_BATCH
    if not bool(bsel.any()):
        cols = decode_change_columns(
            frames.buf, frames.starts[sel], frames.lens[sel]
        )
        return cols, frames
    cols = _replay_with_batches(frames, sel, bsel)
    return cols, frames


def _replay_with_batches(frames: FrameIndex, sel: np.ndarray,
                         bsel: np.ndarray) -> ChangeColumns:
    """Stitch per-record and batch-frame rows back into wire order.

    Only the batch frames cost Python (one decode each — there are few:
    that is the point of batching); per-record rows decode in one native
    pass and slice into the stitched output as runs.
    """
    from ..wire.batch_codec import decode_change_batch

    cols_pr = decode_change_columns(
        frames.buf, frames.starts[sel], frames.lens[sel]
    )
    # frames contributing rows, in wire order; change-frame runs between
    # batch frames map to consecutive cols_pr row ranges
    row_frames = np.nonzero(sel | bsel)[0]
    is_batch = bsel[row_frames]
    batch_at = np.nonzero(is_batch)[0]
    parts: list[tuple] = []  # (cols-like, lo, hi)
    pr_done = 0
    prev = 0
    for k in batch_at.tolist():
        run = k - prev  # change frames before this batch frame
        if run:
            parts.append((cols_pr, pr_done, pr_done + run))
            pr_done += run
        fi = int(row_frames[k])
        start = int(frames.starts[fi])
        flen = int(frames.lens[fi])
        try:
            bc = decode_change_batch(
                frames.buf[start:start + flen], base=start,
                buf=frames.buf)
        except ValueError as e:
            raise ProtocolError(str(e)) from e
        parts.append((bc, 0, len(bc.change)))
        prev = k + 1
    tail = len(row_frames) - prev
    if tail:
        parts.append((cols_pr, pr_done, pr_done + tail))

    def cat(field: str, dtype) -> np.ndarray:
        if not parts:
            return np.zeros(0, dtype)
        return np.concatenate(
            [np.asarray(getattr(c, field)[lo:hi]) for c, lo, hi in parts]
        ).astype(dtype, copy=False)

    return ChangeColumns(
        buf=frames.buf,
        change=cat("change", np.uint32),
        from_=cat("from_", np.uint32),
        to=cat("to", np.uint32),
        key_off=cat("key_off", np.int64),
        key_len=cat("key_len", np.int64),
        sub_off=cat("sub_off", np.int64),
        sub_len=cat("sub_len", np.int64),
        val_off=cat("val_off", np.int64),
        val_len=cat("val_len", np.int64),
    )


def _slice_columns(cols: ChangeColumns, lo: int, hi: int) -> ChangeColumns:
    """Row-range view of decoded columns (numpy slices, shared buf)."""
    return ChangeColumns(
        buf=cols.buf,
        change=cols.change[lo:hi], from_=cols.from_[lo:hi],
        to=cols.to[lo:hi],
        key_off=cols.key_off[lo:hi], key_len=cols.key_len[lo:hi],
        sub_off=cols.sub_off[lo:hi], sub_len=cols.sub_len[lo:hi],
        val_off=cols.val_off[lo:hi], val_len=cols.val_len[lo:hi],
    )


def encode_batch_frames(cols: ChangeColumns,
                        rows_per_batch: int = 65536) -> bytes:
    """Frame decoded columns as ``TYPE_CHANGE_BATCH`` wire bytes — the
    columnar counterpart of :func:`encode_change_columns` (the bulk
    replay encode path; ROADMAP item 5).  One frame per
    ``rows_per_batch`` rows: bigger batches amortize the dictionary
    further but hold more memory per frame on the receiver."""
    from ..wire.batch_codec import encode_columns
    from ..wire.framing import frame

    n = len(cols)
    if n == 0:
        return b""
    out = []
    for lo in range(0, n, rows_per_batch):
        payload = encode_columns(_slice_columns(cols, lo,
                                                min(n, lo + rows_per_batch)))
        out.append(frame(TYPE_CHANGE_BATCH, payload))
    return b"".join(out)


def canonical_change_extents(cols: ChangeColumns):
    """Canonical per-record payload extents for decoded columns:
    ``(buf, offs, lens)`` where ``buf[offs[i]:offs[i]+lens[i]]`` is row
    i's per-record protobuf encoding.  The digest/merkle contract is
    framing-independent — batch-framed rows hash the SAME bytes a
    per-record peer put on the wire — so consumers re-encode through
    the native columnar encoder (one C pass) and index the result."""
    wire = encode_change_columns(cols)
    idx = split_frames(np.frombuffer(wire, dtype=np.uint8))
    return idx.buf, idx.starts, idx.lens


def canonical_change_payloads(cols: ChangeColumns) -> list[bytes]:
    """Row-order list of canonical per-record payload bytes (the digest
    pipeline's submit unit) for decoded columns."""
    buf, offs, lens = canonical_change_extents(cols)
    data = buf.tobytes()
    return [data[o:o + ln]
            for o, ln in zip(offs.tolist(), lens.tolist())]
