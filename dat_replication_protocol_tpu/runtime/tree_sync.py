"""Interactive Merkle descent: find differing leaves across a network.

:mod:`..ops.reconcile` exchanges O(n)-sized sketch tables; this module
is the complementary *interactive* protocol: two replicas that each
hold a built tree (:func:`..ops.merkle.build_tree`) walk it top-down in
rounds, descending only into subtrees whose digests differ — the
classic remote-sync descent (dat core resumes replicas this way above
the reference wire; reference: messages/schema.proto:4-5 carries the
version fields it steers by).  Transfer is O(diff · log n) bytes in
log n round trips, independent of snapshot size.

The protocol is modeled as explicit request/response byte messages so
transports can carry them as opaque blobs and tests can meter exactly
what crosses the wire:

* round k request (initiator -> responder): the initiator's digests of
  the current frontier's children, 64 bytes per frontier node;
* round k response: one bit per child — differs or not — packed, which
  becomes the next frontier.

Both trees must have equal (power-of-two) width; pad with
:func:`..ops.merkle.pad_leaves` first (same policy on both replicas,
exactly like the positional diff).
"""

from __future__ import annotations

import numpy as np

from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter

_M_D2H = _counter("device.d2h.bytes")

_DIGEST = 32


class TreeSyncSession:
    """One replica's side of the descent over its built tree levels."""

    def __init__(self, levels_hh, levels_hl):
        self._hh = levels_hh
        self._hl = levels_hl
        self.nlevels = len(levels_hh)
        self.width = levels_hh[0].shape[0]

    def root(self) -> bytes:
        # jax rides in via ops.merkle, imported lazily: the session layer
        # imports the runtime package (native splitter), and a module-
        # level jax import here would force device init — slow always,
        # a hang when the device tunnel is wedged
        from ..ops import merkle

        (d,) = merkle.digests_from_device(self._hh[-1], self._hl[-1])
        return d

    def _digests(self, level: int, idxs: list[int]) -> list[bytes]:
        from ..ops import merkle

        if not idxs:
            return []
        if _OBS.on:
            # frontier digests leave the device to go on the wire
            _M_D2H.inc(_DIGEST * len(idxs))
        at = np.asarray(idxs, dtype=np.int64)
        return merkle.digests_from_device(
            np.asarray(self._hh[level])[at], np.asarray(self._hl[level])[at]
        )

    # -- initiator side ------------------------------------------------------

    def request(self, level: int, frontier: list[int]) -> bytes:
        """Round message: our digests of the frontier nodes' children."""
        kids = [c for i in frontier for c in (2 * i, 2 * i + 1)]
        return b"".join(self._digests(level, kids))

    def next_frontier(self, frontier: list[int], reply: bytes) -> list[int]:
        """Decode the responder's differ-bitmap into child indices."""
        kids = [c for i in frontier for c in (2 * i, 2 * i + 1)]
        # symmetric to respond()'s request-length check: a truncated
        # bitmap would zip() short and silently report the dropped tail
        # as in-sync
        if len(reply) != (len(kids) + 7) // 8:
            raise ValueError(
                f"differ-bitmap holds {len(reply)} bytes; frontier of "
                f"{len(frontier)} nodes needs {(len(kids) + 7) // 8}"
            )
        bits = np.unpackbits(
            np.frombuffer(reply, np.uint8), bitorder="little"
        )[: len(kids)]
        return [k for k, b in zip(kids, bits) if b]

    # -- responder side ------------------------------------------------------

    def respond(self, level: int, frontier: list[int],
                request: bytes) -> bytes:
        """Compare the initiator's child digests with ours; packed bits."""
        kids = [c for i in frontier for c in (2 * i, 2 * i + 1)]
        if len(request) != _DIGEST * len(kids):
            raise ValueError(
                f"round message holds {len(request)} bytes; frontier of "
                f"{len(frontier)} nodes needs {_DIGEST * len(kids)}"
            )
        mine = self._digests(level, kids)
        theirs = [
            request[k * _DIGEST:(k + 1) * _DIGEST] for k in range(len(kids))
        ]
        bits = np.array(
            [a != b for a, b in zip(theirs, mine)], dtype=np.uint8
        )
        return np.packbits(bits, bitorder="little").tobytes()


def sync(a: TreeSyncSession, b: TreeSyncSession,
         transcript: list | None = None) -> list[int]:
    """Run the full descent between two in-memory parties.

    Returns the differing leaf indices (ascending).  ``transcript``, if
    given, receives ``(direction, nbytes)`` tuples for every message —
    the test meters O(diff · log n) with it.  Real deployments pump the
    same request/respond calls through any byte transport (each message
    is a self-contained blob).
    """
    if a.width != b.width or a.nlevels != b.nlevels:
        raise ValueError("trees must have equal (padded) width")

    def note(direction: str, payload: bytes) -> bytes:
        if transcript is not None:
            transcript.append((direction, len(payload)))
        return payload

    # root handshake: a ships its root, b replies one differ byte — the
    # initiator's descend-or-stop decision is wire-derived, so a real
    # transport can reproduce every round from the transcript alone
    ra = note("a->b", a.root())
    differs = note("b->a", b"\x01" if b.root() != ra else b"\x00")
    if differs == b"\x00":
        return []
    frontier = [0]
    for level in range(a.nlevels - 2, -1, -1):
        req = note("a->b", a.request(level, frontier))
        reply = note("b->a", b.respond(level, frontier, req))
        frontier = a.next_frontier(frontier, reply)
        if not frontier:
            return []
    return frontier
