"""Host runtime: native (C++) parsing loops + change-log replay engine."""

from .replay import (
    ChangeColumns,
    FrameIndex,
    decode_change_columns,
    replay_log,
    split_frames,
)

__all__ = [
    "ChangeColumns",
    "FrameIndex",
    "decode_change_columns",
    "replay_log",
    "split_frames",
]
