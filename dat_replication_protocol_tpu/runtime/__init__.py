"""Host runtime: native (C++) parsing loops, change-log replay engine,
and the composed content-addressing pipeline."""

from .content import ContentSummary, content_address, delta, reassemble
from .tree_sync import TreeSyncSession, sync as tree_sync
from .replay import (
    ChangeColumns,
    FrameIndex,
    decode_change_columns,
    encode_change_columns,
    encode_change_log,
    replay_log,
    split_frames,
)

__all__ = [
    "ChangeColumns",
    "ContentSummary",
    "FrameIndex",
    "content_address",
    "decode_change_columns",
    "encode_change_columns",
    "encode_change_log",
    "delta",
    "reassemble",
    "replay_log",
    "split_frames",
    "TreeSyncSession",
    "tree_sync",
]
