"""Content-addressed snapshot transfer (ISSUE 12, ROADMAP item 3).

A late joiner trimmed past the BroadcastLog retention window used to
get a structured :class:`~..fanout.log.SnapshotNeeded` refusal and was
stranded — the one scenario where the stack refused to replicate.
This module is the bootstrap path that answers it:

* the **responder** materializes its dataset as CDC chunks addressed by
  their fused1p digests (:func:`..runtime.content.content_digests` —
  one read, one hash pass, device route when available) and serves them
  over negotiated ``TYPE_SNAPSHOT`` frames;
* the **joiner** reconciles its chunk *set* against the source first —
  the weighted (variable-size element) rateless extension of
  :mod:`..ops.rateless` streams O(diff) coded symbols, so a 2% stale
  joiner moves ~2% of the bytes; a cold joiner short-circuits to the
  plain full-manifest ``WANT all`` fallback;
* chunk ORDER ships as the ``DONE`` assembly plan: ranks into the
  lexicographically sorted unique digest set, an order both sides
  compute locally — ~log2(n)/7 bytes per chunk slot instead of 32;
* a flash crowd of cold joiners shares ONE hash+read+encode pass: the
  full chunk stream is framed once into a per-manifest
  :class:`~..fanout.log.BroadcastLog` (:meth:`SnapshotSource.cold_log`)
  and every cold session is answered with zero-copy slices of it
  (hash-once economics, proven by counters exactly like fan-out).

Layering (the reconcile-driver doctrine):

* :class:`SnapshotSource` — the shared per-manifest state (chunks,
  digests, ranks, the cold log).  Build it once, serve N sessions.
* :class:`SnapshotResponder` / :class:`SnapshotJoiner` — transport-free
  protocol cores: feed decoded
  :class:`~..wire.snapshot_codec.SnapshotMsg` messages, collect reply
  payloads.  The chaos suite drives THESE against the fault injector.
* :func:`snapshot_local` — both sides in one process with exact wire
  metering; the bench's A/B harness.
* :func:`run_snapshot_responder` / :func:`run_snapshot_joiner` — live
  duplex drivers over blocking byte pairs (the
  :mod:`..session.transport` contract).  The sidecar serves the
  responder under ``--snapshot``.

Failure contract (ROBUSTNESS.md): the joiner verifies EVERY chunk
digest on receipt, and a session either assembles the byte-exact
dataset (root + length verified against the manifest) or raises ONE
structured :class:`~..wire.framing.ProtocolError`.  Resume is
exactly-once: checkpoint/journal/reconnect replay the wire byte-exactly
and the joiner's verified-chunk set absorbs any frame the transport
re-delivers — a verified chunk is never verified (or counted) twice.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable

import numpy as np

from ..obs.events import emit as _emit
from ..obs.metrics import OBS as _OBS, counter as _counter, gauge as _gauge
from ..obs.watermarks import WATERMARKS as _WATERMARKS
from ..ops import rateless
from ..session.decoder import Decoder
from ..session.encoder import Encoder
from ..session.transport import recv_over, send_over
from ..utils.trace import span
from ..wire import snapshot_codec as sn
from ..wire.framing import CAP_SNAPSHOT, ProtocolError, TYPE_SNAPSHOT, \
    frame_header, frame_wire_len, iter_frames

__all__ = ["SnapshotSource", "SnapshotResponder", "SnapshotJoiner",
           "LogSlice", "snapshot_local", "run_snapshot_responder",
           "snapshot_responder_machine", "run_snapshot_joiner",
           "symbol_cap", "DEFAULT_SYMBOL_BATCH0", "DEFAULT_MAX_SYMBOLS"]

# first symbol batch; each round doubles (the reconcile-driver schedule)
DEFAULT_SYMBOL_BATCH0 = 64

# absolute per-session symbol budget (the reconcile doctrine: the cap
# scaled off claimed set sizes is advisory, this bound is this
# process's memory).  1M weighted symbols = 48 MiB of cells.
DEFAULT_MAX_SYMBOLS = 1 << 20

# one CHUNKS payload stays below this (frame granularity: resume
# checkpoints land between frames, so smaller frames = finer resume)
DEFAULT_CHUNK_PAYLOAD = 1 << 20

# snapshot telemetry (OBSERVABILITY.md "snapshot.*")
_M_SESSIONS = _counter("snapshot.sessions")
_M_CHUNKS_SENT = _counter("snapshot.chunks.sent")
_M_BYTES_SENT = _counter("snapshot.chunks.sent_bytes")
_M_COLD_BYTES = _counter("snapshot.cold.bytes")  # served from the shared log
_M_CHUNKS_VERIFIED = _counter("snapshot.chunks.verified")
_M_CHUNKS_REUSED = _counter("snapshot.chunks.reused")
_M_CHUNKS_DUP = _counter("snapshot.chunks.duplicate")  # absorbed re-delivery
_G_SYMBOLS = _gauge("snapshot.symbols.seen")
_G_MISSING = _gauge("snapshot.decoded.missing")


def symbol_cap(n_chunks: int,
               max_symbols: int = DEFAULT_MAX_SYMBOLS) -> int:
    """Per-session symbol budget, computed from the manifest by BOTH
    sides: a healthy chunk-set decode needs ~1.35-2.2x the diff, which
    is <= n_chunks + the joiner's set; the absolute ``max_symbols``
    budget wins.  The joiner mirrors this bound so its full-manifest
    degrade fires BEFORE the responder would refuse the next batch —
    the two sides must agree on ``max_symbols`` (the default does) or
    a heavily divergent joiner is stranded by the responder's FAIL."""
    return min(max(4 * n_chunks + 256, 512), max_symbols)


def _as_u8(data) -> np.ndarray:
    return np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)
    ) else np.ascontiguousarray(data, dtype=np.uint8)


def _lex_order(digests: np.ndarray) -> np.ndarray:
    """Indices sorting digest rows lexicographically (byte order).

    The big-endian u64 view of each 8-byte quarter compares exactly
    like the bytes it covers, so a 4-key lexsort is the whole 32-byte
    comparison — no 'S32' flexible dtype (numpy strips trailing NULs
    there, silently merging digests that differ only in a trailing
    zero byte)."""
    d = np.ascontiguousarray(digests, dtype=np.uint8)
    if len(d) == 0:
        return np.empty(0, np.int64)
    w = d.view(">u8")
    return np.lexsort((w[:, 3], w[:, 2], w[:, 1], w[:, 0])).astype(np.int64)


class LogSlice:
    """Reply directive: write ``log[start:end)`` — PRE-FRAMED snapshot
    frames from the shared per-manifest broadcast log — to the peer
    verbatim.  Drivers stream it in bounded zero-copy slices."""

    __slots__ = ("log", "start", "end")

    def __init__(self, log, start: int, end: int):
        self.log = log
        self.start = start
        self.end = end

    def __len__(self) -> int:
        return self.end - self.start


class SnapshotSource:
    """One materialized dataset, shared by every responder session.

    Chunks the dataset ONCE (``content_digests`` — the fused single-
    pass route: cuts and per-chunk BLAKE2b in one sweep, device
    single-residency pipeline when a backend is up), computes the
    Merkle root over the position digests, the unique-chunk set, and
    the ``DONE`` assembly ranks.  ``wire_offset`` is the live-log
    offset this dataset materializes — the joiner attaches its live
    session there after assembly (0 for a standalone dataset).
    """

    def __init__(self, data, *, avg_bits: int = 13,
                 min_size: int | None = None, max_size: int | None = None,
                 wire_offset: int = 0):
        from ..ops import merkle
        from .content import content_digests

        self._buf = _as_u8(data)
        if min_size is None:
            min_size = 1 << (avg_bits - 2)
        if max_size is None:
            max_size = 1 << (avg_bits + 2)
        with span("snapshot.materialize"):
            cuts, digests = content_digests(
                self._buf, avg_bits, min_size, max_size)
        ends = np.asarray(cuts, dtype=np.int64)
        self.offs = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
        self.lens = ends - self.offs
        self.digests = np.ascontiguousarray(digests, dtype=np.uint8)
        root = merkle.root_host(self.digests) if len(ends) else b"\0" * 32
        # unique chunk set (manifest positions may repeat a digest; the
        # wire ships each unique chunk at most once) + the assembly
        # ranks: position i holds the chunk at sorted-set rank[i]
        uniq, first = rateless.dedupe_digests(self.digests)
        self.uniq_digests = uniq
        self.uniq_offs = self.offs[first]
        self.uniq_lens = self.lens[first]
        # position -> lex rank of its chunk, fully vectorized: np.unique
        # over the void view compares byte-lexicographically (memcmp),
        # so its inverse IS each position's rank in the sorted unique
        # set — the same order :func:`_lex_order` computes (equality
        # pinned by test), with no per-position Python work on the
        # materialize path
        if len(self.digests):
            void = self.digests.view([("v", "V32")]).ravel()
            self.ranks = np.unique(void, return_inverse=True)[1].astype(
                np.int64, copy=False).reshape(-1)
        else:
            self.ranks = np.empty(0, np.int64)
        self._uniq_index = {uniq[i].tobytes(): i for i in range(len(uniq))}
        self.manifest = sn.SnapshotManifest(
            n_positions=len(self.digests), n_chunks=len(uniq),
            total_bytes=int(self._buf.size), root=root,
            wire_offset=int(wire_offset), avg_bits=avg_bits,
            min_size=min_size, max_size=max_size)
        self._lock = threading.Lock()
        self._cold_log = None
        self._symbol_cache: rateless.WeightedSymbols | None = None
        self._symbol_cache_lock = threading.Lock()
        self._done_tail: bytes | None = None
        self._done_tail_lock = threading.Lock()

    # -- chunk access --------------------------------------------------------

    def chunk_view(self, uidx: int) -> memoryview:
        """Unique chunk ``uidx``'s bytes as a zero-copy view over the
        dataset (the responder's read path: slices, never copies,
        until the wire codec assembles a payload)."""
        o = int(self.uniq_offs[uidx])
        ln = int(self.uniq_lens[uidx])
        return memoryview(self._buf)[o:o + ln].cast("B")

    def uniq_rows_for(self, digests: np.ndarray) -> np.ndarray:
        """Unique-chunk indices for digest queries; -1 where unknown
        (a WANT naming a chunk outside the manifest is byzantine)."""
        q = np.ascontiguousarray(digests, dtype=np.uint8)
        out = np.empty(len(q), dtype=np.int64)
        idx = self._uniq_index
        for i in range(len(q)):
            out[i] = idx.get(q[i].tobytes(), -1)
        return out

    def weighted_symbols(self) -> rateless.WeightedSymbols:
        """The SHARED weighted coded-symbol prefix over the unique
        chunk set: symbol batches are computed once per manifest and
        every session's stream is a slice of the same prefix (the
        hash-once doctrine applied to symbol work)."""
        with self._symbol_cache_lock:
            if self._symbol_cache is None:
                self._symbol_cache = rateless.WeightedSymbols(
                    self.uniq_digests, self.uniq_lens)
            return self._symbol_cache

    def done_payload(self, symbols_used: int) -> bytes:
        # the ranks section is constant per manifest: encode it once
        # and prepend the per-session prefix — a flash crowd must not
        # redo ~n_positions Python varint encodes per session
        with self._done_tail_lock:
            if self._done_tail is None:
                self._done_tail = sn.encode_done_tail(self.ranks)
            tail = self._done_tail
        return sn.encode_done(symbols_used, tail=tail)

    def chunk_payloads(self, uidxs, max_payload: int):
        """Yield CHUNKS payloads covering unique-chunk indices
        ``uidxs`` in order, each grouping at most ``max_payload`` chunk
        bytes (frame granularity = resume granularity).  The ONE owner
        of the grouping rule — the per-session WANT answer and the
        cold-log framing must never diverge."""
        group: list = []
        group_bytes = 0
        for uidx in uidxs:
            ln = int(self.uniq_lens[uidx])
            if group and group_bytes + ln > max_payload:
                yield sn.encode_chunks(group)
                group, group_bytes = [], 0
            group.append((self.uniq_digests[uidx].tobytes(),
                          self.chunk_view(uidx)))
            group_bytes += ln
        if group:
            yield sn.encode_chunks(group)

    # -- the shared cold stream ---------------------------------------------

    def cold_log(self, max_payload: int = DEFAULT_CHUNK_PAYLOAD):
        """The full-manifest answer, framed ONCE into a sealed
        :class:`~..fanout.log.BroadcastLog`: every unique chunk (in
        dataset order — sequential reads) grouped into CHUNKS frames,
        then the DONE frame.  N cold joiners are served slices of this
        log — one hash+read+encode pass however large the flash crowd
        (``snapshot.cold.bytes`` counts the bytes leaving; the digest
        counters stay flat, which is the bench's hash-once proof)."""
        from ..fanout.log import BroadcastLog

        with self._lock:
            if self._cold_log is None:
                log = BroadcastLog(
                    retention_budget=max(
                        1, int(self.manifest.total_bytes) * 2 + (64 << 20)))
                order = np.argsort(self.uniq_offs, kind="stable")
                for payload in self.chunk_payloads(order.tolist(),
                                                   max_payload):
                    log.append(frame_header(len(payload),
                                            TYPE_SNAPSHOT) + payload)
                payload = self.done_payload(0)
                log.append(frame_header(len(payload),
                                        TYPE_SNAPSHOT) + payload)
                log.seal()
                self._cold_log = log
            return self._cold_log


class SnapshotResponder:
    """Transport-free responder core for ONE joiner session.

    :meth:`begin_payloads` opens the session (the manifest travels
    first); :meth:`handle` consumes each decoded joiner message and
    returns replies — payload ``bytes`` to be framed, or a
    :class:`LogSlice` of the shared cold stream.  ``chunk_budget``
    bounds the total chunk bytes one session may pull (the per-session
    FAIL arm: past it the session fails STRUCTURED, never grows).
    """

    def __init__(self, source: SnapshotSource, *,
                 batch0: int = DEFAULT_SYMBOL_BATCH0,
                 max_symbols: int = DEFAULT_MAX_SYMBOLS,
                 chunk_budget: int | None = None,
                 max_payload: int = DEFAULT_CHUNK_PAYLOAD):
        self.source = source
        self.batch0 = batch0
        self.max_symbols = max_symbols
        self.chunk_budget = chunk_budget
        self.max_payload = max_payload
        self.symbols_sent = 0
        self.rounds = 0
        self.chunks_sent = 0
        self.chunk_bytes_sent = 0
        self.cold = False
        self.finished = False
        self.failed: ProtocolError | None = None

    def begin_payloads(self) -> list:
        if _OBS.on:
            _M_SESSIONS.inc()
            _emit("snapshot.begin",
                  chunks=self.source.manifest.n_chunks,
                  total_bytes=self.source.manifest.total_bytes)
        return [sn.encode_begin(self.source.manifest)]

    def _fail(self, message: str) -> list:
        self.failed = ProtocolError(message, offset=self.symbols_sent)
        if _OBS.on:
            _emit("snapshot.fail", symbols=self.symbols_sent,
                  chunks=self.chunks_sent, message=message)
        return [sn.encode_fail(self.chunks_sent, message)]

    def _symbol_cap(self) -> int:
        return symbol_cap(self.source.manifest.n_chunks, self.max_symbols)

    def _chunks_replies(self, uidxs: np.ndarray) -> list:
        src = self.source
        out = list(src.chunk_payloads(uidxs.tolist(), self.max_payload))
        self.chunks_sent += len(uidxs)
        sent = int(src.uniq_lens[uidxs].sum()) if len(uidxs) else 0
        self.chunk_bytes_sent += sent
        if _OBS.on:
            _M_CHUNKS_SENT.inc(len(uidxs))
            _M_BYTES_SENT.inc(sent)
        return out

    def handle(self, msg: sn.SnapshotMsg) -> list:
        if self.failed is not None or self.finished:
            return []
        if msg.kind == sn.SN_WANT and msg.mode == sn.WANT_MORE:
            if msg.n > self.symbols_sent:
                return self._fail(
                    f"joiner claims {msg.n} symbols, {self.symbols_sent} "
                    "sent")
            if self.symbols_sent >= self._symbol_cap():
                return self._fail(
                    f"no decode after {self.symbols_sent} symbols "
                    f"({self.source.manifest.n_chunks} chunks)")
            m = self.batch0 if self.symbols_sent == 0 \
                else self.symbols_sent * 2
            m = min(m, self.max_symbols)
            cells = self.source.weighted_symbols().extend(m)[
                self.symbols_sent:]
            reply = sn.encode_symbols(self.symbols_sent, cells)
            self.symbols_sent = m
            self.rounds += 1
            return [reply]
        if msg.kind == sn.SN_WANT and msg.mode == sn.WANT_DIGESTS:
            want = msg.digests if msg.digests is not None \
                else np.empty((0, 32), np.uint8)
            uidxs = self.source.uniq_rows_for(want)
            if (uidxs < 0).any():
                return self._fail(
                    "joiner requested a chunk outside the manifest")
            # the WANT set is semantically a SET: dedupe before billing
            # or serving, so a byzantine joiner repeating one digest k
            # times cannot amplify the reply past one copy per chunk
            uidxs = np.unique(uidxs)
            need = int(self.source.uniq_lens[uidxs].sum()) \
                if len(uidxs) else 0
            if self.chunk_budget is not None and \
                    self.chunk_bytes_sent + need > self.chunk_budget:
                return self._fail(
                    f"chunk budget exceeded: {need} requested bytes "
                    f"(+{self.chunk_bytes_sent} sent) over "
                    f"{self.chunk_budget}")
            replies = self._chunks_replies(uidxs)
            replies.append(self.source.done_payload(self.symbols_sent))
            self.finished = True
            if _OBS.on:
                _emit("snapshot.done", chunks=self.chunks_sent,
                      bytes=self.chunk_bytes_sent,
                      symbols=self.symbols_sent)
            return replies
        if msg.kind == sn.SN_WANT and msg.mode == sn.WANT_ALL:
            # the cold log ships each UNIQUE chunk once; total_bytes
            # sums positions and would over-bill duplicated content
            total = int(self.source.uniq_lens.sum())
            if self.chunk_budget is not None and \
                    self.chunk_bytes_sent + total > self.chunk_budget:
                return self._fail(
                    f"chunk budget exceeded: full manifest is {total} "
                    f"bytes over {self.chunk_budget}")
            log = self.source.cold_log(self.max_payload)
            self.cold = True
            self.finished = True
            self.chunks_sent += self.source.manifest.n_chunks
            self.chunk_bytes_sent += total
            if _OBS.on:
                _M_CHUNKS_SENT.inc(self.source.manifest.n_chunks)
                _M_BYTES_SENT.inc(total)
                _M_COLD_BYTES.inc(log.end - log.start)
                _emit("snapshot.done", chunks=self.chunks_sent,
                      bytes=total, symbols=self.symbols_sent, cold=True)
            return [LogSlice(log, log.start, log.end)]
        if msg.kind == sn.SN_FAIL:
            self.failed = ProtocolError(
                f"snapshot failed at joiner: {msg.reason}",
                offset=self.symbols_sent)
            return []
        # BEGIN/SYMBOLS/CHUNKS/DONE are joiner-bound
        return self._fail(
            f"unexpected snapshot message {msg.kind_name!r} at responder")


class SnapshotJoiner:
    """Transport-free joiner core: decide cold vs reconcile, peel the
    weighted symbol stream, verify every chunk on receipt, assemble.

    ``have`` is the joiner's stale dataset (bytes-like / uint8 array,
    or ``None``/empty for a cold join); its chunks are cut with the
    manifest's own CDC parameters so shared content shares digests.
    :meth:`result` is the failure-contract choke point: the assembled
    byte-exact dataset, or ONE structured ProtocolError."""

    def __init__(self, have=None, *, engine: str = "auto",
                 max_symbols: int = DEFAULT_MAX_SYMBOLS,
                 fallback_all: bool = True):
        self._have = have
        self._engine = engine
        self.max_symbols = max_symbols
        self._cap = max_symbols  # tightened from the manifest at BEGIN
        self.fallback_all = fallback_all
        self.manifest: sn.SnapshotManifest | None = None
        self.peeler: rateless.WeightedPeelDecoder | None = None
        # local unique chunks: digest -> (offset, length) into _have_buf
        self._have_buf: np.ndarray | None = None
        self._local: dict[bytes, tuple[int, int]] = {}
        self._local_only: set[bytes] = set()  # sign -1: not at responder
        self._wanted: dict[bytes, int] | None = None  # None = cold (all)
        self._verified: dict[bytes, bytes] = {}
        self.chunks_verified = 0
        self.chunk_bytes_verified = 0
        self.chunks_reused = 0
        self.symbols_seen = 0
        self.rounds = 0
        self.ranks: np.ndarray | None = None
        self.data: bytes | None = None
        self.assembled = False
        self.failed: ProtocolError | None = None

    # -- failure choke point -------------------------------------------------

    def _fail(self, message: str) -> list:
        self.failed = ProtocolError(message, offset=self.symbols_seen)
        if _OBS.on:
            _emit("snapshot.fail", symbols=self.symbols_seen,
                  chunks=self.chunks_verified, message=message)
        return [sn.encode_fail(self.chunks_verified, message)]

    # -- protocol ------------------------------------------------------------

    def _on_begin(self, man: sn.SnapshotManifest) -> list:
        if self.manifest is not None:
            return self._fail("duplicate snapshot begin")
        self.manifest = man
        have = self._have
        if have is not None:
            buf = _as_u8(have)
            if buf.size:
                from .content import content_digests

                cuts, digests = content_digests(
                    buf, man.avg_bits, man.min_size, man.max_size)
                ends = np.asarray(cuts, dtype=np.int64)
                offs = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
                lens = ends - offs
                uniq, first = rateless.dedupe_digests(
                    np.ascontiguousarray(digests, np.uint8))
                self._have_buf = buf
                self._local = {
                    uniq[i].tobytes(): (int(offs[first[i]]),
                                        int(lens[first[i]]))
                    for i in range(len(uniq))}
        if not self._local or man.n_chunks == 0:
            # cold joiner (or empty manifest): the plain full-manifest
            # fallback — no symbol stream, every chunk wanted
            self._wanted = None
            return [sn.encode_want_all()]
        # mirror the responder's per-session symbol budget: the degrade
        # below must fire before the responder refuses a WANT_MORE, or
        # its FAIL strands the session with the fallback still unused
        self._cap = symbol_cap(man.n_chunks, self.max_symbols)
        local_digests = np.frombuffer(
            b"".join(self._local.keys()), np.uint8).reshape(-1, 32)
        local_lens = np.array([ln for _, ln in self._local.values()],
                              dtype=np.int64)
        self.peeler = rateless.WeightedPeelDecoder(
            local_digests, local_lens, engine=self._engine,
            assume_unique=True)
        return [sn.encode_want_more(0)]

    def _on_symbols(self, msg: sn.SnapshotMsg) -> list:
        if self.manifest is None:
            return self._fail("snapshot symbols before begin")
        if self.peeler is None:
            return []  # cold path never asked for symbols: stray frame
        if self._wanted is not None:
            return []  # late batch after decode: ignorable
        try:
            self.peeler.add_symbols(msg.start, msg.cells)
        except ValueError as e:
            return self._fail(str(e))
        self.symbols_seen = self.peeler.symbols_seen
        self.rounds += 1
        if _OBS.on:
            _G_SYMBOLS.set(self.symbols_seen)
        out = self.peeler.try_decode()
        if out is None:
            if self.symbols_seen >= self._cap:
                if self.fallback_all:
                    # decode exhausted: degrade to the full-manifest
                    # fetch instead of stranding the joiner (correct,
                    # just without the dedup savings)
                    self._wanted = None
                    return [sn.encode_want_all()]
                return self._fail(
                    f"no decode after {self.symbols_seen} symbols")
            return [sn.encode_want_more(self.symbols_seen)]
        digests, lens, signs = out
        plus = signs == 1
        missing = digests[plus]
        self._wanted = {missing[i].tobytes(): int(lens[plus][i])
                        for i in range(len(missing))}
        self._local_only = {bytes(d) for d in digests[signs == -1]}
        if _OBS.on:
            _G_MISSING.set(len(missing))
            _emit("snapshot.decoded", missing=len(missing),
                  local_only=int((signs == -1).sum()),
                  symbols=self.symbols_seen)
        return [sn.encode_want_digests(missing)]

    def _on_chunks(self, msg: sn.SnapshotMsg) -> list:
        if self.manifest is None:
            return self._fail("snapshot chunks before begin")
        for digest, data in msg.chunks:
            digest = bytes(digest)
            if digest in self._verified:
                # exactly-once resume: a replayed frame re-delivers a
                # chunk the journal already carried past us — absorb,
                # never re-verify or double-count
                if _OBS.on:
                    _M_CHUNKS_DUP.inc()
                continue
            if self._wanted is not None and digest not in self._wanted:
                return self._fail(
                    "unsolicited chunk (digest outside the WANT set)")
            if hashlib.blake2b(data, digest_size=32).digest() != digest:
                return self._fail(
                    f"chunk digest mismatch at chunk {self.chunks_verified}"
                )
            self._verified[digest] = data
            self.chunks_verified += 1
            self.chunk_bytes_verified += len(data)
            if _OBS.on:
                _M_CHUNKS_VERIFIED.inc()
        return []

    def _on_done(self, msg: sn.SnapshotMsg) -> list:
        man = self.manifest
        if man is None:
            return self._fail("snapshot done before begin")
        if self.assembled:
            return []
        if self._wanted is not None:
            got = set(self._verified)
            miss = [d for d in self._wanted if d not in got]
            if miss:
                return self._fail(
                    f"done with {len(miss)} wanted chunks undelivered")
        if len(msg.ranks) != man.n_positions:
            return self._fail(
                f"done names {len(msg.ranks)} positions, manifest has "
                f"{man.n_positions}")
        # the responder's unique set, reconstructed locally: received
        # chunks + the local chunks the reconcile proved SHARED (every
        # local chunk except the sign -1 local-only ones — those are
        # not at the responder and must not enter the sorted order).
        # On the cold/fallback path (_wanted is None) the received
        # chunks ARE the exact set.
        entries: list[tuple[bytes, object]] = list(self._verified.items())
        if self._wanted is not None and self._local:
            hb = self._have_buf
            for digest, (off, ln) in self._local.items():
                if digest in self._local_only or digest in self._verified:
                    continue
                entries.append((digest, memoryview(hb)[off:off + ln]))
                self.chunks_reused += 1
        if len(entries) != man.n_chunks:
            return self._fail(
                f"assembled set has {len(entries)} chunks, manifest "
                f"names {man.n_chunks}")
        digests_arr = np.frombuffer(
            b"".join(d for d, _ in entries), np.uint8).reshape(-1, 32)
        order = _lex_order(digests_arr)
        ranks = np.ascontiguousarray(msg.ranks, dtype=np.int64)
        if len(ranks) and (ranks.max() >= len(entries)):
            return self._fail("done rank outside the chunk set")
        # verify the manifest root over the per-position digests BEFORE
        # exporting a single byte: the plan itself is untrusted
        from ..ops import merkle

        pos_digests = digests_arr[order][ranks] if len(ranks) \
            else np.empty((0, 32), np.uint8)
        root = merkle.root_host(pos_digests) if len(ranks) else b"\0" * 32
        if root != man.root:
            return self._fail("assembled root does not match manifest")
        out = bytearray()
        chunk_at = [entries[i][1] for i in order.tolist()]
        for r in ranks.tolist():
            out += chunk_at[r]
        if len(out) != man.total_bytes:
            return self._fail(
                f"assembled {len(out)} bytes, manifest says "
                f"{man.total_bytes}")
        self.data = bytes(out)
        self.assembled = True
        if _OBS.on:
            _M_CHUNKS_REUSED.inc(self.chunks_reused)
            _emit("snapshot.assembled", bytes=len(self.data),
                  received=self.chunks_verified,
                  reused=self.chunks_reused,
                  wire_offset=man.wire_offset)
        return []

    def handle(self, msg: sn.SnapshotMsg) -> list:
        """Consume one decoded snapshot message; returns reply payloads
        (joiner replies are always plain payload bytes)."""
        if self.failed is not None:
            return []
        if msg.kind == sn.SN_BEGIN:
            return self._on_begin(msg.manifest)
        if msg.kind == sn.SN_SYMBOLS:
            return self._on_symbols(msg)
        if msg.kind == sn.SN_CHUNKS:
            return self._on_chunks(msg)
        if msg.kind == sn.SN_DONE:
            return self._on_done(msg)
        if msg.kind == sn.SN_FAIL:
            self.failed = ProtocolError(
                f"snapshot failed at responder: {msg.reason}",
                offset=self.symbols_seen)
            return []
        # WANT is responder-bound
        return self._fail(
            f"unexpected snapshot message {msg.kind_name!r} at joiner")

    @property
    def done(self) -> bool:
        return self.assembled or self.failed is not None

    def result(self) -> dict:
        """The assembled dataset + session stats; raises the session's
        ONE structured ProtocolError when the stream failed or ended
        before assembly completed."""
        if self.failed is not None:
            raise self.failed
        if not self.assembled:
            raise ProtocolError(
                "snapshot stream ended before assembly completed",
                offset=self.symbols_seen)
        return {
            "ok": True,
            "data": self.data,
            "wire_offset": self.manifest.wire_offset,
            "chunks_received": self.chunks_verified,
            "chunks_reused": self.chunks_reused,
            "bytes_received": self.chunk_bytes_verified,
            "symbols": self.symbols_seen,
            "rounds": self.rounds,
        }


# -- in-memory harness -------------------------------------------------------


def snapshot_local(source, have=None, *, engine: str = "auto",
                   batch0: int = DEFAULT_SYMBOL_BATCH0,
                   chunk_budget: int | None = None) -> dict:
    """Run the full protocol between an in-memory responder and joiner
    with exact wire metering — every message round-trips the real
    payload codec and is billed at its framed wire length; cold-log
    slices are billed at their raw (pre-framed) byte length.

    ``source`` is a :class:`SnapshotSource` (share it across calls to
    model a flash crowd).  Returns the joiner's :meth:`result` dict
    plus ``wire_s2j`` / ``wire_j2s`` / ``wire_bytes`` and the
    responder's stats; raises the structured ProtocolError on
    failure."""
    if not isinstance(source, SnapshotSource):
        source = SnapshotSource(source)
    resp = SnapshotResponder(source, batch0=batch0,
                             chunk_budget=chunk_budget)
    joiner = SnapshotJoiner(have, engine=engine)
    wire = {"s2j": 0, "j2s": 0}
    pending = list(resp.begin_payloads())
    guard = 0
    while pending and not joiner.done:
        replies: list = []
        for item in pending:
            if isinstance(item, LogSlice):
                wire["s2j"] += len(item)
                # decode the pre-framed stream through the real codec
                raw = item.log.read_from(item.start)
                for _start, _tid, p0, end in iter_frames(raw):
                    replies.extend(joiner.handle(
                        sn.decode_snapshot(raw[p0:end])))
            else:
                wire["s2j"] += frame_wire_len(len(item))
                replies.extend(joiner.handle(sn.decode_snapshot(item)))
        pending = []
        for r in replies:
            wire["j2s"] += frame_wire_len(len(r))
            pending.extend(resp.handle(sn.decode_snapshot(r)))
        guard += 1
        if guard > 10_000:
            raise ProtocolError("snapshot_local failed to converge")
    out = joiner.result()
    out.update({
        "wire_s2j": wire["s2j"],
        "wire_j2s": wire["j2s"],
        "wire_bytes": wire["s2j"] + wire["j2s"],
        "chunks_sent": resp.chunks_sent,
        "cold": resp.cold,
        "responder_symbols": resp.symbols_sent,
    })
    return out


# -- live duplex drivers -----------------------------------------------------


def _send_replies(enc: Encoder, replies, chunk_size: int,
                  on_done: Callable[[], None] | None = None) -> None:
    """Queue responder/joiner replies on the session encoder, in
    order: payload bytes ride :meth:`Encoder.snapshot_frame`; a
    :class:`LogSlice` is PRE-FRAMED shared-log wire, pushed verbatim in
    bounded zero-copy slices (same queue, so frame order is reply
    order; the journal tee sees every byte either way).

    LogSlice pushes are PACED by the encoder's high-water mark: each
    ``_push`` materializes its view (the queue owns bytes), so queueing
    a whole cold dataset at once would buffer it all in memory — the
    flash-crowd economics this module exists for.  Past the mark the
    pump parks and resumes via :meth:`Encoder.on_drain` (fired from the
    sender's ``read``), keeping the queue near ``high_water`` while the
    log itself stays the single shared copy.  ``on_done`` fires once
    every reply is fully queued — callers must defer ``finalize()``
    into it or a parked slice would be truncated at the EOF marker."""
    replies = list(replies)

    def pump(idx: int = 0, at: int | None = None) -> None:
        while idx < len(replies):
            if enc.destroyed:
                return  # peer went away mid-slice; nothing to finish
            item = replies[idx]
            if isinstance(item, LogSlice):
                if at is None:
                    at = item.start
                while at < item.end:
                    views = item.log.read_slices(
                        at, min(chunk_size, item.end - at))
                    if not views:
                        break
                    writable = True
                    for v in views:
                        writable = enc._push(v, None)
                        at += len(v)
                    if not writable and at < item.end:
                        # one-shot resume hook fired from the sender's
                        # read (thread pump) or send turn (edge loop):
                        # it only re-queues bounded slices, never blocks
                        # datlint: allow-callback-escape
                        enc.on_drain(lambda i=idx, a=at: pump(i, a))
                        return
                at = None
            else:
                enc.snapshot_frame(item)
            idx += 1
        if on_done is not None:
            # completion hook: the callers' _finish only calls
            # enc.finalize() — queue state flips, no blocking
            # datlint: allow-callback-escape
            on_done()

    pump()


def snapshot_responder_machine(source, *,
                               batch0: int = DEFAULT_SYMBOL_BATCH0,
                               chunk_budget: int | None = None,
                               link: str | None = None,
                               chunk_size: int = 64 * 1024) -> tuple:
    """The snapshot responder's protocol machine, factored off its
    threads (ISSUE 17): encoder/decoder pair with BEGIN already queued
    and the WANT/DONE/FAIL exchange wired, returned as ``(enc, dec,
    finish)``.  The caller owns byte movement — the threaded
    :func:`run_snapshot_responder` pumps them, the event-driven edge
    steps them per selector turn; LogSlice pacing via
    :meth:`Encoder.on_drain` works under both (the hook fires from
    whichever side drains the queue).  ``finish()`` is idempotent:
    tears down a half-open encoder, releases the watermark link,
    raises ``resp.failed`` if the session failed, and returns the
    stats record both callers emit."""
    if not isinstance(source, SnapshotSource):
        source = SnapshotSource(source)
    resp = SnapshotResponder(source, batch0=batch0,
                             chunk_budget=chunk_budget)
    enc = Encoder(peer_caps=CAP_SNAPSHOT)
    dec = Decoder()

    def on_snapshot(msg, done) -> None:
        replies = resp.handle(msg)

        def _finish() -> None:
            if (resp.finished or resp.failed is not None) \
                    and not enc.finalized and not enc.destroyed:
                enc.finalize()

        _send_replies(enc, replies, chunk_size, on_done=_finish)
        done()

    dec.snapshot(on_snapshot)
    # error hook, not user code: destroy() only flips state and wakes
    # watchers — it never blocks the registering loop
    # datlint: allow-callback-escape
    dec.on_error(lambda _e: None if enc.destroyed else enc.destroy())
    if link is not None:
        _WATERMARKS.track("snapshot.chunks.sent", link,
                          lambda: resp.chunk_bytes_sent)
    _send_replies(enc, resp.begin_payloads(), chunk_size)

    def finish() -> dict:
        if not enc.destroyed and not enc.finalized:
            # joiner went away before the session completed: release
            # the reply pump / drop the reply tail
            enc.destroy()
        if link is not None:
            _WATERMARKS.untrack(link)  # idempotent (dict pop)
        if resp.failed is not None:
            raise resp.failed
        return {"ok": resp.finished, "chunks_sent": resp.chunks_sent,
                "chunk_bytes_sent": resp.chunk_bytes_sent,
                "symbols": resp.symbols_sent, "rounds": resp.rounds,
                "cold": resp.cold}

    return enc, dec, finish


def run_snapshot_responder(source, read_bytes, write_bytes,
                           close_write=None, *,
                           batch0: int = DEFAULT_SYMBOL_BATCH0,
                           chunk_budget: int | None = None,
                           link: str | None = None,
                           chunk_size: int = 64 * 1024) -> dict:
    """Serve one snapshot session as the responder over a duplex byte
    pair (the :mod:`..session.transport` contract).  Sends BEGIN, then
    answers the joiner's WANTs until DONE/FAIL; finalizes after the
    last word.  ``link`` registers the ``snapshot.chunks.sent``
    watermark role on the fleet plane (PR 11) for live scrapes."""
    enc, dec, finish = snapshot_responder_machine(
        source, batch0=batch0, chunk_budget=chunk_budget, link=link,
        chunk_size=chunk_size)

    sender = threading.Thread(
        target=lambda: send_over(enc, write_bytes, close_write,
                                 chunk_size=chunk_size),
        name="snapshot-resp-send", daemon=True)
    sender.start()
    try:
        recv_over(dec, read_bytes, chunk_size=chunk_size)
    except Exception as e:
        if not dec.destroyed:
            dec.destroy(e)
        if not enc.destroyed:
            enc.destroy(e)
        raise
    finally:
        if not enc.destroyed and not enc.finalized:
            # joiner went away before the session completed: release
            # the reply pump so the thread does not park forever
            enc.destroy()
        sender.join(timeout=30)
    return finish()


def run_snapshot_joiner(read_bytes, write_bytes, close_write=None, *,
                        have=None, engine: str = "auto",
                        max_symbols: int = DEFAULT_MAX_SYMBOLS,
                        link: str | None = None,
                        chunk_size: int = 64 * 1024) -> dict:
    """Fetch one snapshot as the joiner over a duplex byte pair:
    receive the manifest, reconcile (or WANT all when cold), verify
    every chunk on receipt, assemble, and return :meth:`result` —
    ``result["wire_offset"]`` is where the caller attaches its live
    session next.  ``link`` registers the ``snapshot.chunks.verified``
    watermark role on the fleet plane.  Raises the session's ONE
    structured ProtocolError on failure."""
    joiner = SnapshotJoiner(have, engine=engine, max_symbols=max_symbols)
    enc = Encoder(peer_caps=CAP_SNAPSHOT)
    dec = Decoder()

    def on_snapshot(msg, done) -> None:
        replies = joiner.handle(msg)
        for r in replies:
            enc.snapshot_frame(r)
        if joiner.done and not enc.finalized and not enc.destroyed:
            enc.finalize()
        done()

    dec.snapshot(on_snapshot)
    dec.on_error(lambda _e: None if enc.destroyed else enc.destroy())
    if link is not None:
        _WATERMARKS.track("snapshot.chunks.verified", link,
                          lambda: joiner.chunk_bytes_verified)

    sender = threading.Thread(
        target=lambda: send_over(enc, write_bytes, close_write,
                                 chunk_size=chunk_size),
        name="snapshot-join-send", daemon=True)
    sender.start()
    try:
        recv_over(dec, read_bytes, chunk_size=chunk_size)
    except Exception as e:
        if not dec.destroyed:
            dec.destroy(e)
        if not enc.destroyed:
            enc.destroy(e)
        raise
    finally:
        if not enc.destroyed and not enc.finalized:
            enc.destroy()
        sender.join(timeout=30)
        if link is not None:
            _WATERMARKS.untrack(link)
    return joiner.result()
