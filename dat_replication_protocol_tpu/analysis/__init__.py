"""datlint — protocol-invariant static analysis for this package.

The test suite exercises *behavior*; this package checks *structure*:
cross-path invariants that a reviewer can verify on any one diff but
that silently rot as the same protocol logic is duplicated across the
pure-Python, C, and Pallas fast paths (the round-5 advisor's
bulk-cursor desync is the type specimen — see ANALYSIS.md for each
rule's motivating incident).

Usage::

    python -m dat_replication_protocol_tpu.analysis [paths...]

or programmatically::

    from dat_replication_protocol_tpu.analysis import run_paths
    findings = run_paths(["dat_replication_protocol_tpu"])

Findings are suppressible per line with ``# datlint: disable=<rule>``
(``// datlint: disable=<rule>`` in C sources) and per file with
``# datlint: disable-file=<rule>``; every suppression should carry a
trailing justification.
"""

from __future__ import annotations

from .engine import Finding, Project, run_paths, run_project
from .rules import ALL_RULES, rule_by_name

__all__ = [
    "ALL_RULES",
    "Finding",
    "Project",
    "rule_by_name",
    "run_paths",
    "run_project",
]
