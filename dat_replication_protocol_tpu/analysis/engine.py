"""datlint core: sources, findings, suppressions, and the rule runner.

The engine is deliberately dependency-free (``ast`` + ``tokenize`` +
``re``): it must run in the same stripped CI image as the tier-1 tests,
before any native toolchain or JAX initialization.

Two source kinds flow through a :class:`Project`:

* Python files are parsed to AST once and shared by every rule;
  comments (for rule declarations and suppressions) come from
  ``tokenize`` so that string literals containing ``datlint:`` markers
  can never activate or suppress anything.
* C/C++ files are kept as raw text; rules that read them (the
  wire-constant parity check) do their own regex extraction, and
  suppressions are recognized in ``//`` / ``/* */`` comments.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Iterator

_PY_SUFFIXES = (".py",)
_C_SUFFIXES = (".c", ".cc", ".cpp", ".h", ".hpp")
# build products and caches never carry protocol logic
_SKIP_DIRS = {"_build", "__pycache__", ".git", ".pytest_cache"}

_SUPPRESS_RE = re.compile(r"datlint:\s*disable=([\w,*-]+)")
_SUPPRESS_FILE_RE = re.compile(r"datlint:\s*disable-file=([\w,*-]+)")
_C_COMMENT_RE = re.compile(r"//.*$|/\*.*?\*/")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at a specific source location.

    ``chains`` is optional evidence: for whole-program rules (the
    concurrency pass) each chain is a tuple of ``file:line who does
    what`` steps tracing one path from a thread entry to the violation
    — the human message folds them in, and ``--json`` emits them
    structured so CI annotations can cite both sides of an inversion.
    """

    path: str
    line: int
    rule: str
    message: str
    chains: tuple = ()

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "chains": [list(c) for c in self.chains],
        }

    def key(self) -> str:
        """Location-stable identity for ``--baseline`` accept-lists:
        rule + the path's LAST TWO components (checkout-independent) +
        first message sentence, NO line number — a baseline must
        survive unrelated edits shifting lines and must not embed the
        runner's absolute checkout path.  The path is RESOLVED first so
        'm.py' and '/abs/dir/m.py' spell the same key (a CI job and a
        local run must not flip the gate on invocation style)."""
        try:
            tail = "/".join(Path(self.path).resolve().parts[-2:])
        except OSError:
            tail = "/".join(Path(self.path).parts[-2:])
        head = self.message.split(" — ")[0].split(".  ")[0]
        return f"{self.rule}:{tail}:{head}"


class SourceFile:
    """A lazily-parsed source file plus its datlint comment markers."""

    def __init__(self, path: Path, text: str, is_python: bool):
        self.path = path
        self.text = text
        self.is_python = is_python
        self._tree: ast.Module | None = None
        self._parse_error: SyntaxError | None = None
        # line -> set of rule names suppressed on that line
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        # every suppression marker as written, for the stale-suppression
        # audit: {"line", "rules", "file", "covers", "reason", "used"}
        self.suppress_markers: list[dict] = []
        # line -> raw comment text (Python only; rules parse declarations
        # such as coupled-state sets out of these)
        self.comments: dict[int, str] = {}
        self._scan_markers()

    # -- parsing -----------------------------------------------------------

    @property
    def tree(self) -> ast.Module | None:
        """The module AST, or None for C sources / unparsable Python."""
        if not self.is_python:
            return None
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as e:
                self._parse_error = e
        return self._tree

    @property
    def parse_error(self) -> SyntaxError | None:
        _ = self.tree
        return self._parse_error

    # -- markers -----------------------------------------------------------

    def _scan_markers(self) -> None:
        lines = self.text.splitlines()
        if self.is_python:
            try:
                tokens = tokenize.generate_tokens(
                    io.StringIO(self.text).readline)
                for tok in tokens:
                    if tok.type == tokenize.COMMENT:
                        line = tok.start[0]
                        self.comments[line] = tok.string
                        covers = [line]
                        # a comment-only line also covers the line below,
                        # so long statements can carry a suppression
                        # without blowing the line length
                        if lines[line - 1][:tok.start[1]].strip() == "":
                            covers.append(line + 1)
                        for c in covers:
                            self._note_suppressions(c, tok.string)
                        self._note_marker(line, tok.string, covers)
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass  # rules that need the AST will surface the error
        else:
            for i, line in enumerate(lines, start=1):
                for m in _C_COMMENT_RE.finditer(line):
                    covers = [i]
                    if line[:m.start()].strip() == "":
                        covers.append(i + 1)
                    for c in covers:
                        self._note_suppressions(c, m.group(0))
                    self._note_marker(i, m.group(0), covers)

    def _note_suppressions(self, line: int, comment: str) -> None:
        m = _SUPPRESS_FILE_RE.search(comment)
        if m:
            self.file_suppressions.update(m.group(1).split(","))
        m = _SUPPRESS_RE.search(comment)
        if m:
            self.line_suppressions.setdefault(line, set()).update(
                m.group(1).split(","))

    def _note_marker(self, line: int, comment: str, covers: list) -> None:
        for regex, file_level in ((_SUPPRESS_FILE_RE, True),
                                  (_SUPPRESS_RE, False)):
            m = regex.search(comment)
            if not m:
                continue
            # the reason is whatever human text shares the comment with
            # the marker (before or after) — the audited-exception bar
            # from ANALYSIS.md, now machine-checked
            rest = comment[:m.start()] + comment[m.end():]
            self.suppress_markers.append({
                "line": line,
                "rules": set(m.group(1).split(",")),
                "file": file_level,
                "covers": set(covers),
                "reason": bool(re.search(r"\w", rest.replace("datlint", ""))),
                "used": False,
            })

    def note_suppression_use(self, rule: str, line: int) -> None:
        """Credit every marker that suppresses ``rule`` at ``line`` —
        the stale-suppression audit flags whatever earns no credit."""
        for m in self.suppress_markers:
            if not ({rule, "all", "*"} & m["rules"]):
                continue
            if m["file"] or line in m["covers"]:
                m["used"] = True

    def suppressed(self, rule: str, line: int) -> bool:
        if {rule, "all", "*"} & self.file_suppressions:
            return True
        on_line = self.line_suppressions.get(line, ())
        return rule in on_line or "all" in on_line or "*" in on_line


class Project:
    """The file set one analysis run operates over."""

    def __init__(self, py_sources: list[SourceFile],
                 c_sources: list[SourceFile]):
        self.py_sources = py_sources
        self.c_sources = c_sources

    @property
    def sources(self) -> list[SourceFile]:
        return self.py_sources + self.c_sources

    @classmethod
    def from_paths(cls, paths: Iterable[str | Path]) -> "Project":
        py: list[SourceFile] = []
        cc: list[SourceFile] = []
        seen: set[Path] = set()
        for root in paths:
            root = Path(root)
            files: Iterator[Path]
            if root.is_file():
                files = iter([root])
            else:
                files = (p for p in sorted(root.rglob("*")) if p.is_file())
            for p in files:
                if p in seen or any(part in _SKIP_DIRS for part in p.parts):
                    continue
                seen.add(p)
                if p.suffix in _PY_SUFFIXES:
                    kind = py, True
                elif p.suffix in _C_SUFFIXES:
                    kind = cc, False
                else:
                    continue
                try:
                    text = p.read_text(encoding="utf-8", errors="replace")
                except OSError:
                    continue
                kind[0].append(SourceFile(p, text, kind[1]))
        return cls(py, cc)


def run_project(project: Project, rules: Iterable,
                stats: dict | None = None) -> list[Finding]:
    """Run ``rules`` over ``project``; returns unsuppressed findings,
    sorted by (path, line).  Pass a dict as ``stats`` to collect
    per-rule wall seconds (``--stats`` / the tier-1 runtime budget);
    whichever rule runs first pays any shared-index build, so the
    registry keeps index-sharing rules adjacent."""
    import time as _time

    by_path = {str(s.path): s for s in project.sources}
    rules = list(rules)
    out: list[Finding] = []
    for rule in rules:
        t0 = _time.perf_counter()
        for f in rule.check(project):
            src = by_path.get(f.path)
            if src is not None and src.suppressed(f.rule, f.line):
                src.note_suppression_use(f.rule, f.line)
                continue
            out.append(f)
        if stats is not None:
            stats[rule.name] = stats.get(rule.name, 0.0) \
                + _time.perf_counter() - t0
    out.extend(_audit_suppressions(project, rules))
    # a Python file the analyzer cannot parse hides every AST rule: that
    # is itself a finding, not a silent skip
    for s in project.py_sources:
        if s.parse_error is not None:
            out.append(Finding(
                path=str(s.path),
                line=s.parse_error.lineno or 1,
                rule="parse-error",
                message=f"unparsable Python: {s.parse_error.msg}",
            ))
    return sorted(out)


class StaleSuppression:
    """A suppression that suppresses nothing is itself a finding.

    ``check`` yields nothing: staleness is only decidable AFTER every
    other rule has run (a marker is stale when no finding of its rules
    hit its lines in THIS run), so :func:`run_project` performs the
    audit as a post-pass — see :func:`_audit_suppressions` — gated on
    this rule being in the registry.  The post-pass also enforces the
    ANALYSIS.md audited-exception bar mechanically: every marker must
    carry a written reason in the same comment.
    """

    name = "stale-suppression"
    description = ("a datlint suppression must suppress at least one "
                   "finding of a rule that ran, and must carry a "
                   "written reason in the same comment")

    def check(self, project: Project) -> Iterator[Finding]:
        return iter(())


def _audit_suppressions(project: Project, rules: list) -> list[Finding]:
    names = {r.name for r in rules}
    if StaleSuppression.name not in names:
        return []
    out: list[Finding] = []
    for s in project.sources:
        for m in s.suppress_markers:
            path = str(s.path)
            if not m["reason"]:
                f = Finding(
                    path=path, line=m["line"], rule=StaleSuppression.name,
                    message=("suppression without a written reason — an "
                             "audited exception states its why in the "
                             "same comment (see ANALYSIS.md), or gets "
                             "deleted"))
                if not s.suppressed(f.rule, f.line):
                    out.append(f)
            specific = m["rules"] - {"all", "*"}
            # wildcards and rules that did not run this invocation are
            # not judgeable for staleness — never guess
            if m["used"] or not specific or not specific <= names:
                continue
            f = Finding(
                path=path, line=m["line"], rule=StaleSuppression.name,
                message=(f"datlint: disable="
                         f"{','.join(sorted(m['rules']))} suppressed "
                         f"zero findings this run — the code it excused "
                         f"is gone (or the rule name is wrong): delete "
                         f"the marker"))
            if not s.suppressed(f.rule, f.line):
                out.append(f)
    return out


def run_paths(paths: Iterable[str | Path], rules=None) -> list[Finding]:
    from .rules import ALL_RULES

    return run_project(Project.from_paths(paths),
                       ALL_RULES if rules is None else rules)


# -- shared AST helpers used by several rules -------------------------------

def canonical(expr: str | ast.AST) -> str:
    """Canonical source form of an expression (quote/space normalized),
    so declared coupled-state members compare equal to AST targets."""
    if isinstance(expr, str):
        expr = ast.parse(expr, mode="eval").body
    return ast.unparse(expr)


def assign_targets(node: ast.AST) -> Iterator[ast.expr]:
    """Flattened assignment targets of one statement (tuple unpacking
    included); empty for non-assignment statements."""
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    else:
        return
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            yield from t.elts
        else:
            yield t


def walk_function_body(fn: ast.AST) -> Iterator[ast.AST]:
    """Every node lexically inside ``fn``'s own body, NOT descending into
    nested function/class definitions (those are separate scopes and are
    analyzed on their own)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
