"""obs-discipline: telemetry names are literals; stdout is not a log.

Motivating design contract (ISSUE 3, OBSERVABILITY.md): the metric
catalog is only auditable if every name that can ever reach the
registry is greppable — ``grep -r '"decoder.bytes"'`` must find the
instrumentation site.  A name built at runtime (f-string, variable,
concatenation) silently forks the catalog: dashboards and the
conformance oracle reference names that may never exist, and a typo'd
dynamic name becomes a brand-new metric instead of an error.

Flagged shapes (Python sources only):

* a call to a registry factory, event emitter, span opener, jit-site
  registration, or watermark registration — ``counter(...)``,
  ``gauge(...)``, ``histogram(...)``, ``emit(...)``,
  ``trace_span(...)``, ``trace_instant(...)``, ``jit_site(...)``,
  ``track(...)`` (bare, aliased with leading underscores, or as an
  attribute like ``EVENTS.emit``) — whose first argument is not a
  string literal: span names carry the SAME greppability contract as
  event names (ISSUE 4), the recompile sentinel's per-site names
  (ISSUE 5) the same again, and a watermark's ROLE (its first
  argument, ISSUE 11) once more — the fleet aggregator's lag join
  keys on the role vocabulary, so a runtime-built role is a silent
  fork of the join itself (the LINK argument is runtime by design: it
  names a session, like a collector label);
* a bare ``print(...)`` (no ``file=`` keyword, i.e. stdout) anywhere
  in the package: stdout belongs to the wire/CLI protocol, and
  diagnostics belong in the structured event log (:mod:`...obs.events`)
  or explicitly on stderr;
* in ``obs/http.py`` only: a ``/healthz``-serving function (name
  contains ``healthz``) that takes ANY lock via ``with`` or makes a
  device-dispatch-shaped call (the hub-isolation vocabulary).  The
  health probe exists to detect a wedged engine; a probe that blocks
  behind the engine's lock — or worse, touches the device — inverts
  its purpose.  Owners feed admission state through LOCK-FREE
  callables (``ReplicationHub.admission_state``) instead.

Exemptions:

* ``obs/metrics.py`` and ``obs/events.py`` themselves — the registry
  and the log legitimately forward ``name`` parameters; they are the
  plumbing, not instrumentation sites (likewise ``obs/watermarks.py``,
  ``obs/http.py``, and ``obs/fleet.py``: the board renders labeled
  names from tracked state, the endpoint and aggregator ship whole
  snapshots — their callers hold the greppable literals);
* ``__main__.py`` modules for the bare-print check — a CLI's stdout IS
  its interface (the datlint CLI prints findings there by design);
* the standard ``# datlint: disable=obs-discipline`` suppression.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project
from .hub_isolation import _dispatchy_call, _is_lock_ctx

_TELEMETRY_FNS = {"counter", "gauge", "histogram", "emit",
                  "trace_span", "trace_instant", "jit_site", "track",
                  "phase", "account"}
# attribute-call receivers that denote the obs layer (normalized:
# underscores stripped, lowercased) — `EVENTS.emit(...)`,
# `obs_metrics.counter(...)`, `registry.histogram(...)`,
# `prof.phase(...)` (the ISSUE 18 loop profiler).  Unrelated APIs
# sharing a method name (`handler.emit(record)`,
# `np.histogram(data, bins)`) must NOT trip the rule.
_TELEMETRY_RECEIVERS = {"events", "metrics", "obs", "obs_events",
                        "obs_metrics", "obs_tracing", "registry", "reg",
                        "spans", "tracing", "device", "obs_device",
                        "watermarks", "obs_watermarks", "board",
                        "prof", "profiler", "loopprof", "wirecost"}
# the obs plumbing itself: (parent dir, filename) pairs exempt from the
# literal-name check (they forward `name` parameters by design; the
# greppable sites are their callers)
_PLUMBING = {("obs", "metrics.py"), ("obs", "events.py"),
             ("obs", "tracing.py"), ("obs", "flight.py"),
             ("obs", "device.py"), ("obs", "__init__.py"),
             ("obs", "watermarks.py"), ("obs", "http.py"),
             ("obs", "fleet.py"), ("obs", "loopprof.py"),
             ("obs", "propagation.py"), ("obs", "wirecost.py")}
# the /healthz lock-discipline check applies to the endpoint module
_HEALTHZ_MODULE = ("obs", "http.py")


def _telemetry_fn_name(call: ast.Call) -> str | None:
    """The normalized telemetry function name for a call, or None.
    Leading underscores are stripped so the hoisted-handle idiom
    (``from ..obs.metrics import counter as _counter``) still matches;
    attribute calls additionally require a telemetry-shaped receiver."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        recv = fn.value
        recv_name = recv.id if isinstance(recv, ast.Name) else (
            recv.attr if isinstance(recv, ast.Attribute) else None)
        if recv_name is None or recv_name.lstrip("_").lower() \
                not in _TELEMETRY_RECEIVERS:
            return None
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    else:
        return None
    name = name.lstrip("_")
    return name if name in _TELEMETRY_FNS else None


class ObsDiscipline:
    name = "obs-discipline"
    description = (
        "metric/event names at instrumentation sites must be string "
        "literals (the catalog must be greppable), and bare print() is "
        "not a log — use the event log or write to stderr explicitly"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            tree = src.tree
            if tree is None:
                continue
            parts = src.path.parts
            is_plumbing = tuple(parts[-2:]) in _PLUMBING
            is_cli = src.path.name == "__main__.py"
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                if not is_plumbing:
                    yield from self._check_literal_name(src, node)
                if not is_cli:
                    yield from self._check_bare_print(src, node)
            if tuple(parts[-2:]) == _HEALTHZ_MODULE:
                yield from self._check_healthz_lockfree(src, tree)

    def _check_healthz_lockfree(self, src, tree) -> Iterator[Finding]:
        """The /healthz lock discipline (module docstring): any
        function whose name mentions healthz must not take a lock or
        make a device-dispatch-shaped call — reusing the hub-isolation
        vocabulary for what 'dispatch-shaped' means."""
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if "healthz" not in fn.name.lower():
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.With) and \
                        any(_is_lock_ctx(i) for i in sub.items):
                    yield Finding(
                        path=str(src.path), line=sub.lineno,
                        rule=self.name,
                        message=(
                            f"{fn.name}() takes a lock: the /healthz "
                            "probe must stay lock-free — a wedged "
                            "engine holding that lock would wedge the "
                            "very probe meant to detect it (owners "
                            "expose lock-free admission_state views "
                            "instead)"),
                    )
                elif isinstance(sub, ast.Call):
                    offender = _dispatchy_call(sub)
                    if offender is not None:
                        yield Finding(
                            path=str(src.path), line=sub.lineno,
                            rule=self.name,
                            message=(
                                f"{offender}(...) in {fn.name}(): the "
                                "/healthz probe must never touch the "
                                "device or hub dispatch path — health "
                                "is read from already-maintained "
                                "state, not probed by new work"),
                        )

    def _check_literal_name(self, src, call: ast.Call) -> Iterator[Finding]:
        fn_name = _telemetry_fn_name(call)
        if fn_name is None or not call.args:
            return
        first = call.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return
        yield Finding(
            path=str(src.path),
            line=call.lineno,
            rule=self.name,
            message=(
                f"{fn_name}() called with a non-literal name: metric and "
                "event names must be string literals so the catalog in "
                "OBSERVABILITY.md stays greppable (a runtime-built name "
                "is an unauditable fork of the catalog)"
            ),
        )

    def _check_bare_print(self, src, call: ast.Call) -> Iterator[Finding]:
        fn = call.func
        if not (isinstance(fn, ast.Name) and fn.id == "print"):
            return
        if any(kw.arg == "file" for kw in call.keywords):
            return  # an explicit stream (stderr) is a deliberate choice
        yield Finding(
            path=str(src.path),
            line=call.lineno,
            rule=self.name,
            message=(
                "bare print() writes to stdout, which belongs to the "
                "wire/CLI protocol: emit a structured event "
                "(obs.events.emit) or pass file=sys.stderr explicitly"
            ),
        )
