"""The datlint rule registry.

Each rule is distilled from a real incident in this repo (ANALYSIS.md
links each to its ADVICE.md finding); adding a rule means adding a
module here plus a known-bad/known-good fixture pair in
``tests/test_datlint.py``.
"""

from __future__ import annotations

from ..concurrency import BlockingReachability, BlockingUnderLock, \
    CallbackEscape, GuardedState, LockOrder
from ..engine import StaleSuppression
from .bounded_wait import BoundedWait
from .cursor_coherence import CursorCoherence
from .env_cache import EnvCachePolicy
from .fanout_hot_path import FanoutHotPath
from .hub_isolation import HubIsolation
from .jit_purity import JitPurity
from .obs_discipline import ObsDiscipline
from .structured_errors import StructuredErrorParity
from .unbounded_join import UnboundedJoin
from .wire_constants import WireConstantParity
from .wire_dispatch import WireDispatchParity

ALL_RULES = (
    CursorCoherence(),
    EnvCachePolicy(),
    UnboundedJoin(),
    BoundedWait(),
    JitPurity(),
    WireConstantParity(),
    WireDispatchParity(),
    ObsDiscipline(),
    HubIsolation(),
    FanoutHotPath(),
    StructuredErrorParity(),
    # whole-program concurrency pass (analysis/concurrency/): these
    # three share one ProgramIndex per run — keep them adjacent so the
    # --stats attribution reads sensibly (the first of them pays the
    # index build)
    LockOrder(),
    BlockingUnderLock(),
    GuardedState(),
    # event-loop readiness certifier (ISSUE 16): shares the same
    # ProgramIndex, adds its own ReadinessIndex on top
    BlockingReachability(),
    CallbackEscape(),
    # engine post-pass: must run with the full registry to judge
    # staleness, so it lives last (position is cosmetic — run_project
    # audits after ALL rules regardless)
    StaleSuppression(),
)


def rule_by_name(name: str):
    for rule in ALL_RULES:
        if rule.name == name:
            return rule
    raise KeyError(name)
