"""cursor-coherence: coupled cursors must be written back atomically.

Motivating incident (ADVICE.md round 5, high): the decoder's bulk
dispatch loops advance ``st["row"]`` without advancing ``st["f"]`` when
a change handler raises — on resume, frame payloads pair with the wrong
row's columns (silent corruption), then duplicate deliveries, then
IndexError.  The C loop writes both cursors back unconditionally; the
two pure-Python paths each forgot one half, and no test could catch it
until the exact raise-then-resume schedule was replayed.

The invariant is declarative.  A module states which pieces of state
form one atomic cursor with a comment::

    # datlint: coupled-state st["f"], st["row"]

and the rule enforces, for every function in that module that mutates
any member of a declared set:

* at least one ``try/finally`` in the function writes back EVERY member
  of the set inside the same ``finally`` suite (the atomic write-back
  that makes handler exceptions resumable), and
* no ``finally`` in the function writes back a proper subset of the set
  (the half-write-back that caused the incident).

Functions are separate scopes: nested defs are analyzed independently.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..engine import (
    Finding,
    Project,
    assign_targets,
    canonical,
    walk_function_body,
)

_DECL_RE = re.compile(r"datlint:\s*coupled-state\s+(.+)$")


def _declared_sets(src) -> tuple[list[frozenset[str]],
                                 list[tuple[int, str]]]:
    """Parse coupled-state declarations; a declaration the rule cannot
    honor is itself a finding — silently dropping it would turn the
    rule OFF for the file while datlint still reports clean (the
    treacherous failure mode for a linter guarding silent corruption)."""
    sets: list[frozenset[str]] = []
    bad: list[tuple[int, str]] = []
    for line, comment in src.comments.items():
        m = _DECL_RE.search(comment)
        if not m:
            continue
        members = set()
        ok = True
        for part in m.group(1).split(","):
            part = part.strip()
            if not part:
                continue
            try:
                members.add(canonical(part))
            except SyntaxError:
                ok = False
                bad.append((line, (
                    f"coupled-state declaration has an unparsable member "
                    f"{part!r} — the whole set is ignored and the rule is "
                    f"OFF for this file until the declaration is fixed"
                )))
                break
        if not ok:
            continue
        if len(members) < 2:
            bad.append((line, (
                f"coupled-state declares {len(members)} member(s); a "
                f"coupling needs at least two — declaration ignored, the "
                f"rule is OFF for this file until it is fixed"
            )))
            continue
        sets.append(frozenset(members))
    return sets, bad


def _coupled_writes(node: ast.AST, members: frozenset[str]) -> set[str]:
    """Members of ``members`` assigned anywhere in ``node``'s statements
    (not descending into nested defs)."""
    hit: set[str] = set()
    for child in walk_function_body(node):
        for target in assign_targets(child):
            try:
                c = canonical(target)
            except ValueError:
                continue
            if c in members:
                hit.add(c)
    return hit


class _FinallyCollector(ast.NodeVisitor):
    """Try statements with a finalbody, lexically inside one function."""

    def __init__(self) -> None:
        self.tries: list[ast.Try] = []

    def generic_visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return  # separate scope
        if isinstance(node, ast.Try) and node.finalbody:
            self.tries.append(node)
        super().generic_visit(node)


class CursorCoherence:
    name = "cursor-coherence"
    description = (
        "functions mutating a declared coupled-state set must write back "
        "every member in one finally suite"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            tree = src.tree
            if tree is None:
                continue
            sets, bad = _declared_sets(src)
            for line, message in bad:
                yield Finding(path=str(src.path), line=line,
                              rule=self.name, message=message)
            if not sets:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                yield from self._check_function(src, node, sets)

    def _check_function(self, src, fn: ast.AST,
                        sets: list[frozenset[str]]) -> Iterator[Finding]:
        collector = _FinallyCollector()
        for stmt in fn.body:
            collector.visit(stmt)
        for members in sets:
            touched = _coupled_writes(fn, members)
            if not touched:
                continue
            complete = False
            for t in collector.tries:
                # a finally is one suite: look only at what the
                # finalbody itself writes
                wrapper = ast.Module(body=t.finalbody, type_ignores=[])
                in_finally = _coupled_writes(wrapper, members)
                if not in_finally:
                    continue
                if in_finally == members:
                    complete = True
                else:
                    missing = ", ".join(sorted(members - in_finally))
                    yield Finding(
                        path=str(src.path),
                        line=t.finalbody[0].lineno,
                        rule=self.name,
                        message=(
                            f"finally writes back "
                            f"{', '.join(sorted(in_finally))} but not "
                            f"{missing}: an exception between the coupled "
                            f"mutations desyncs the cursor on resume"
                        ),
                    )
            if not complete:
                yield Finding(
                    path=str(src.path),
                    line=fn.lineno,
                    rule=self.name,
                    message=(
                        f"{fn.name} mutates coupled state "
                        f"{{{', '.join(sorted(members))}}} with no "
                        f"try/finally writing back the full set — a raising "
                        f"handler leaves the members out of step"
                    ),
                )
