"""structured-error-parity: cluster-layer errors carry the full
structured context or they do not ship.

Motivating incident (ISSUE 15): the gossip mesh's whole failure
contract rests on errors that NAME things — which peer diverged, at
which wire offset, in which frame.  ``ProtocolError`` set the precedent
(frame/offset/cause folded into ``str()``), ``SessionShed``/``PeerShed``
added the actor key; a cluster-layer error type that drops any of
those fields degrades a byzantine post-mortem to "something failed
somewhere", and nothing at runtime notices — the error still raises,
the test still sees an exception, only the attribution is gone.

For every exception class defined in a module under a ``cluster/``
directory (a class whose base name ends in ``Error``, ``Exception``,
``Fault``, or is a known structured base like ``SnapshotNeeded``):

1. it must define ``__init__`` (inheriting one silently inherits the
   base's field set, which is exactly how a field goes missing);
2. ``__init__`` must take a ``peer`` parameter AND assign
   ``self.peer`` (the actor: who diverged / who is refused);
3. ``offset`` and ``frame`` must each be wired: either an ``__init__``
   parameter (passed through to a structured base's ``super().__init__``)
   or an explicit ``self.<field>`` assignment.

Escapes: the standard ``# datlint: disable=structured-error-parity``
on the class line, next to a written justification.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project

_EXC_SUFFIXES = ("Error", "Exception", "Fault")
_EXC_BASES = {"SnapshotNeeded", "ByzantineDivergence", "PeerQuarantined",
              "TransportFault"}
_REQUIRED = ("peer", "offset", "frame")


def _is_exception_class(node: ast.ClassDef) -> bool:
    for base in node.bases:
        name = base.id if isinstance(base, ast.Name) else (
            base.attr if isinstance(base, ast.Attribute) else None)
        if name is None:
            continue
        if name in _EXC_BASES or name.endswith(_EXC_SUFFIXES):
            return True
    return False


def _init_of(node: ast.ClassDef):
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and stmt.name == "__init__":
            return stmt
    return None


def _param_names(fn) -> set:
    args = fn.args
    names = {a.arg for a in args.args} | {a.arg for a in args.kwonlyargs}
    names |= {a.arg for a in args.posonlyargs}
    return names


def _self_assigned(fn) -> set:
    out: set = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign):
            targets = sub.targets
        elif isinstance(sub, ast.AnnAssign):
            targets = [sub.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                out.add(t.attr)
    return out


class StructuredErrorParity:
    name = "structured-error-parity"
    description = (
        "cluster-layer error types carry peer/offset/frame like "
        "ProtocolError and the shed errors do"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            parts = src.path.parts
            if "cluster" not in parts[:-1]:
                continue
            tree = src.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef) \
                        or not _is_exception_class(node):
                    continue
                init = _init_of(node)
                if init is None:
                    yield Finding(
                        path=str(src.path), line=node.lineno,
                        rule=self.name,
                        message=(
                            f"error class {node.name} defines no "
                            f"__init__: the structured field set "
                            f"(peer/offset/frame) is inherited blind — "
                            f"declare it so the contract is visible "
                            f"and checkable"
                        ),
                    )
                    continue
                params = _param_names(init)
                assigned = _self_assigned(init)
                missing = []
                if "peer" not in params or "peer" not in assigned:
                    missing.append(
                        "peer (parameter + self.peer assignment)")
                for field in ("offset", "frame"):
                    if field not in params and field not in assigned:
                        missing.append(
                            f"{field} (parameter passed to a structured "
                            f"base or an explicit self.{field})")
                if missing:
                    yield Finding(
                        path=str(src.path), line=node.lineno,
                        rule=self.name,
                        message=(
                            f"error class {node.name} is missing "
                            f"structured context: {'; '.join(missing)} — "
                            f"cluster errors carry frame/offset/peer "
                            f"like ProtocolError and the shed errors do"
                        ),
                    )
