"""wire-constant-parity: one wire format, N implementations, 0 drift.

The frame-type ids, header limits, and proto2 field tags are written
down independently in ``wire/framing.py`` / ``wire/varint.py`` /
``wire/change_codec.py``, in the streaming decoder, and in BOTH C
translation units (``native/dat_native.cpp`` frame splitter + columnar
decoder, ``native/dat_fastpath.cpp`` dispatch loop + C codec).  A
constant edited in one place ships a protocol fork that only manifests
as silent cross-path divergence under a toolchain the editor may not
even have (the exact failure mode the both-dispatch-paths test fixture
exists for, generalized to constants).

Extraction:

* Python — module-level ``NAME = <expr>`` assignments, constant-folded
  (so ``MAX_HEADER_LEN = MAX_VARINT_LEN + 1`` and the shifted proto
  tags resolve to numbers); a leading underscore is stripped when
  matching the watchlist, so ``_TAG_KEY`` and C's ``TAG_KEY`` compare.
* C — regex over the raw text: enum/#define values, literals annotated
  ``1 /* TYPE_CHANGE */`` or ``= 1;  // TYPE_CHANGE``, and explicit
  ``// wire: NAME = N`` markers for limits that appear only as bare
  loop bounds (dat_native.cpp's varint reader).

Only names on the watchlist participate; a name seen in a single file
constrains nothing.  Divergence yields one finding per constant,
anchored at the first site and listing every value observed.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..engine import Finding, Project

WATCHLIST = frozenset({
    "TYPE_HEADER", "TYPE_CHANGE", "TYPE_BLOB", "TYPE_CHANGE_BATCH",
    "MAX_VARINT_LEN", "MAX_HEADER_LEN",
    "TAG_SUBSET", "TAG_KEY", "TAG_CHANGE", "TAG_FROM", "TAG_TO",
    "TAG_VALUE",
    # ChangeBatch extension: the frame's payload version byte and the
    # capability bit that gates emitting it (negotiation constants —
    # a fork here is a peer that silently stops understanding itself)
    "BATCH_VERSION", "CAP_CHANGE_BATCH",
    # gear CDC scramble constants (ISSUE 7): written down independently
    # in ops/rabin.py and in BOTH native scan loops (dat_gear_candidates
    # and the fused dat_cdc_hash).  A fork here is not a wire fork but a
    # ROUTE fork — two "equivalent" CDC engines silently cutting
    # different chunks, the exact divergence the fused1p cross-checks
    # exist to refuse
    "GEAR_C1", "GEAR_C2",
    # rateless reconciliation (ISSUE 10): the frame type + capability
    # bit + payload version (negotiation constants, same failure class
    # as the ChangeBatch trio), and the splitmix64 mapping constants —
    # written down independently in ops/rateless.py and the native
    # dat_rateless_build engine; a fork maps elements to DIFFERENT
    # coded symbols per engine (the GEAR route-fork class: a sketch
    # that silently never decodes against itself)
    "TYPE_RECONCILE", "CAP_RECONCILE", "RECONCILE_VERSION",
    "RATELESS_GAMMA", "RATELESS_MIX1", "RATELESS_MIX2",
    # snapshot bootstrap (ISSUE 12): the frame type + capability bit +
    # payload version (negotiation constants, the ChangeBatch/Reconcile
    # failure class), and the weighted-participation constants — the
    # variable-size extension's cell mapping is written down
    # independently in ops/rateless.py and the native
    # dat_rateless_build_w twin (`// wire:` markers); a fork maps
    # chunks to DIFFERENT cells per engine (the GEAR route-fork class:
    # a chunk-set sketch that silently never decodes against itself)
    "TYPE_SNAPSHOT", "CAP_SNAPSHOT", "SNAPSHOT_VERSION",
    "RATELESS_W_SHIFT", "RATELESS_W_CAP",
})

_C_PATTERNS = (
    # enum entry / assignment with a (possibly arithmetic) value; the
    # capture is loose — _safe_eval's charset gate rejects non-arithmetic
    re.compile(r"\b([A-Z][A-Z0-9_]{2,})\s*=\s*([^,;{}]+?)\s*[,;}]"),
    # #define NAME VALUE
    re.compile(r"#define\s+([A-Z][A-Z0-9_]{2,})\s+([0-9][0-9xa-fA-F]*)"),
    # literal annotated with a block comment: 1 /* TYPE_CHANGE */
    re.compile(r"\b([0-9][0-9xa-fA-F]*)\s*/\*\s*([A-Z][A-Z0-9_]{2,})\s*\*/"),
    # assignment annotated with a line comment: = 1;  // TYPE_CHANGE
    re.compile(r"=\s*([0-9][0-9xa-fA-F]*)\s*;?\s*//\s*([A-Z][A-Z0-9_]{2,})"
               r"\s*$"),
    # explicit marker: // wire: NAME = N
    re.compile(r"//\s*wire:\s*([A-Z][A-Z0-9_]{2,})\s*=\s*"
               r"([0-9][0-9xa-fA-F]*)"),
)
# patterns where group 1 is the VALUE and group 2 the NAME
_VALUE_FIRST = {2, 3}

_SAFE_EXPR = re.compile(r"^[0-9xXa-fA-F\s()|<<>>+*-]+$")


def _safe_eval(expr: str) -> int | None:
    expr = expr.strip()
    if not _SAFE_EXPR.match(expr):
        return None
    try:
        v = eval(expr, {"__builtins__": {}}, {})  # noqa: S307 — charset-gated
    except Exception:
        return None
    return v if isinstance(v, int) else None


def _extract_c(src) -> Iterator[tuple[str, int, int]]:
    """(name, value, line) triples from one C source."""
    for lineno, line in enumerate(src.text.splitlines(), start=1):
        for i, pat in enumerate(_C_PATTERNS):
            for m in pat.finditer(line):
                if i in _VALUE_FIRST:
                    raw_value, name = m.group(1), m.group(2)
                else:
                    name, raw_value = m.group(1), m.group(2)
                if name.lstrip("_") not in WATCHLIST:
                    continue
                value = _safe_eval(raw_value)
                if value is not None:
                    yield name.lstrip("_"), value, lineno


class _PyFolder(ast.NodeVisitor):
    """Constant-fold module-level integer assignments."""

    def __init__(self, external: dict[str, int]):
        self.external = external  # watchlist values seen in other modules
        self.local: dict[str, int] = {}
        self.found: list[tuple[str, int, int]] = []

    def fold(self, node: ast.expr) -> int | None:
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in self.local:
                return self.local[node.id]
            return self.external.get(node.id.lstrip("_"))
        if isinstance(node, ast.BinOp):
            left, right = self.fold(node.left), self.fold(node.right)
            if left is None or right is None:
                return None
            try:
                if isinstance(node.op, ast.Add):
                    return left + right
                if isinstance(node.op, ast.Sub):
                    return left - right
                if isinstance(node.op, ast.Mult):
                    return left * right
                if isinstance(node.op, ast.LShift):
                    return left << right
                if isinstance(node.op, ast.RShift):
                    return left >> right
                if isinstance(node.op, ast.BitOr):
                    return left | right
                if isinstance(node.op, ast.BitAnd):
                    return left & right
            except (ValueError, OverflowError):
                return None
        return None

    def scan(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                name = stmt.targets[0].id
                value = self.fold(stmt.value)
                if value is None:
                    continue
                self.local[name] = value
                if name.lstrip("_") in WATCHLIST:
                    self.found.append((name.lstrip("_"), value, stmt.lineno))


class WireConstantParity:
    name = "wire-constant-parity"
    description = (
        "frame-type ids, header limits, and proto tags must agree "
        "across the Python and C implementations"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        # sites: name -> list of (path, line, value)
        sites: dict[str, list[tuple[str, int, int]]] = {}
        resolved: dict[str, int] = {}
        # two passes so cross-module references (MAX_VARINT_LEN imported
        # into framing.py) fold regardless of scan order
        for _ in range(2):
            sites.clear()
            for src in project.py_sources:
                tree = src.tree
                if tree is None:
                    continue
                folder = _PyFolder(resolved)
                folder.scan(tree)
                for name, value, line in folder.found:
                    sites.setdefault(name, []).append(
                        (str(src.path), line, value))
                    resolved.setdefault(name, value)
            for src in project.c_sources:
                for name, value, line in _extract_c(src):
                    sites.setdefault(name, []).append(
                        (str(src.path), line, value))
        for name in sorted(sites):
            entries = sites[name]
            values = {v for _, _, v in entries}
            if len(values) <= 1:
                continue
            where = "; ".join(f"{p}:{ln}={v}" for p, ln, v in entries)
            path, line, _ = entries[0]
            yield Finding(
                path=path,
                line=line,
                rule=self.name,
                message=(
                    f"wire constant {name} diverges across "
                    f"implementations: {where} — every copy of the wire "
                    f"format must agree"
                ),
            )
