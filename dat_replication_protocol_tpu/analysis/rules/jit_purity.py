"""jit-purity: no host effects inside traced function bodies.

Motivating pattern (PERF.md rounds 3-5): a ``jax.jit`` / Pallas body
executes at trace time, then replays as compiled XLA.  Host-side
effects inside one are at best silent no-ops after the first call and
at worst synchronization points that stall the dispatch pipeline:

* ``os.environ`` reads — traced once, frozen into the compiled
  program; the env-var toggle "works" until the cache warms, then
  never again (the same split-brain class env-cache-policy catches on
  the host side);
* host syncs — ``.block_until_ready()``, ``jax.device_get`` or
  ``np.asarray``/``np.array``/``np.frombuffer`` applied to a traced
  parameter force a device round-trip per call inside what should be
  one fused dispatch;
* Python-side mutation — ``global``/``nonlocal`` rebinding inside a
  traced body runs once at trace time, not per execution.

A function counts as traced when decorated with ``jit`` /
``jax.jit`` / ``functools.partial(jax.jit, ...)``, passed by name to
``jax.jit(...)`` / ``pl.pallas_call(...)``, or nested inside one that
is.  Helpers called *from* traced code are deliberately out of scope
(no call-graph analysis): the rule polices the bodies where tracing
demonstrably begins.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project, dotted_name, walk_function_body

_JIT_TAILS = ("jit",)
_TRACER_CALL_TAILS = ("jit", "pallas_call")
_SYNC_CALL_TAILS = ("block_until_ready", "device_get")
_HOST_MATERIALIZERS = ("asarray", "array", "frombuffer")


def _ends_with(name: str | None, tails: tuple[str, ...]) -> bool:
    return name is not None and name.rsplit(".", 1)[-1] in tails


def _is_jit_decorator(dec: ast.expr) -> bool:
    name = dotted_name(dec)
    if _ends_with(name, _JIT_TAILS):
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if _ends_with(fname, _JIT_TAILS + ("pallas_call",)):
            return True
        # functools.partial(jax.jit, ...): the first argument is the tracer
        if _ends_with(fname, ("partial",)) and dec.args:
            return _ends_with(dotted_name(dec.args[0]), _JIT_TAILS)
    return False


def _traced_function_names(tree: ast.Module) -> set[str]:
    """Names of functions handed to jax.jit(...) / pl.pallas_call(...)
    as call arguments anywhere in the module."""
    named: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if not _ends_with(dotted_name(node.func), _TRACER_CALL_TAILS):
            continue
        for arg in node.args[:1]:  # the traced callable is the first arg
            if isinstance(arg, ast.Name):
                named.add(arg.id)
    return named


class JitPurity:
    name = "jit-purity"
    description = (
        "no environment reads, host syncs, or Python-side mutation "
        "inside jit/Pallas-traced function bodies"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            tree = src.tree
            if tree is None:
                continue
            by_call = _traced_function_names(tree)
            # walk with an explicit stack so nesting inside a traced
            # function marks the whole subtree as traced
            stack: list[tuple[ast.AST, bool]] = [(tree, False)]
            while stack:
                node, in_traced = stack.pop()
                for child in ast.iter_child_nodes(node):
                    traced_here = in_traced
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        traced_here = (
                            in_traced
                            or child.name in by_call
                            or any(_is_jit_decorator(d)
                                   for d in child.decorator_list)
                        )
                        if traced_here:
                            yield from self._check_body(src, child)
                            continue  # _check_body covered the subtree
                    stack.append((child, traced_here))

    def _check_body(self, src, fn: ast.AST) -> Iterator[Finding]:
        params = {a.arg for a in list(fn.args.args)
                  + list(fn.args.posonlyargs) + list(fn.args.kwonlyargs)}

        def _visit(scope: ast.AST) -> Iterator[Finding]:
            for node in walk_function_body(scope):
                yield from self._check_node(src, fn, node, params)
                # nested defs inside a traced body are traced too
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield from _visit(node)
        yield from _visit(fn)

    def _check_node(self, src, fn, node: ast.AST,
                    params: set[str]) -> Iterator[Finding]:
        def finding(msg: str) -> Finding:
            return Finding(path=str(src.path), line=node.lineno,
                           rule=self.name,
                           message=f"in traced function {fn.name}: {msg}")

        if isinstance(node, ast.Attribute) and \
                dotted_name(node) in ("os.environ", "environ"):
            yield finding(
                "os.environ read is evaluated once at trace time and "
                "frozen into the compiled program")
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if _ends_with(name, ("getenv",)) and (name or "").startswith(
                    ("os.", "getenv")):
                yield finding(
                    "os.getenv is evaluated once at trace time and frozen "
                    "into the compiled program")
            elif _ends_with(name, _SYNC_CALL_TAILS):
                yield finding(
                    f"{(name or '').rsplit('.', 1)[-1]}() is a host "
                    f"synchronization point inside a traced body")
            elif (name is not None and "." in name
                  and name.rsplit(".", 1)[0] in ("np", "numpy")
                  and _ends_with(name, _HOST_MATERIALIZERS)
                  and node.args
                  and isinstance(node.args[0], ast.Name)
                  and node.args[0].id in params):
                yield finding(
                    f"{name}() on a traced argument forces a device->host "
                    f"transfer every call; use jnp or hoist it out of the "
                    f"traced body")
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            yield finding(
                f"{kind} rebinding executes at trace time only — the "
                f"mutation will not happen on later compiled calls")
