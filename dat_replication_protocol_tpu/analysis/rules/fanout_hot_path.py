"""fanout-hot-path: the broadcast write path is O(1) in peers.

Motivating design contract (ISSUE 9, DESIGN.md fan-out): the fan-out
converts per-peer marginal cost from "full hash + full copy" to
"windowed writev of already-framed bytes" — and that economics only
holds while the *writer section* (``append`` / ``publish`` on the
broadcast log/server) does NO per-peer work.  One careless edit — a
"small" notification loop over peers in ``publish``, a per-peer copy in
``append`` — silently turns every produced byte back into O(peers)
writer cost, the exact regression the fan-out exists to remove.  The
dispatcher is where O(peers) bookkeeping lives; it never touches
payload bytes.

Flagged shapes (Python sources under a ``fanout/`` directory only),
inside any function named ``append`` or ``publish``:

* ANY loop (``for`` / ``while``) or comprehension/generator
  expression: the writer section must be O(1) — a loop is either
  per-peer (forbidden) or per-segment (belongs in the dispatcher/read
  path);
* any attribute or subscript whose dotted name mentions ``peer``,
  ``cursor``, or ``reader`` state (``self._peers``,
  ``peer.notify()``): reaching per-peer state from the writer is the
  per-peer-work smell even without a loop.

Escapes: the standard ``# datlint: disable=fanout-hot-path``
suppression (justify next to it).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project, dotted_name

_WRITER_SECTION = {"append", "publish"}
_PEER_STATE_MARKERS = ("peer", "cursor", "reader")
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _mentions_peer_state(node: ast.AST) -> str | None:
    """The offending dotted name when ``node`` reaches peer/cursor
    state, else None."""
    if isinstance(node, ast.Attribute):
        name = dotted_name(node)
        probe = name if name is not None else node.attr
        if any(m in probe.lower() for m in _PEER_STATE_MARKERS):
            return probe
    elif isinstance(node, ast.Subscript):
        name = dotted_name(node.value)
        if name is not None and \
                any(m in name.lower() for m in _PEER_STATE_MARKERS):
            return f"{name}[...]"
    elif isinstance(node, ast.Name):
        if any(m in node.id.lower() for m in _PEER_STATE_MARKERS):
            return node.id
    return None


class FanoutHotPath:
    name = "fanout-hot-path"
    description = (
        "in fanout/: the broadcast writer section (append/publish) must "
        "be O(1) in peers — no loops, no reach into per-peer state; "
        "per-peer work belongs in the dispatcher"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            if "fanout" not in src.path.parts[:-1]:
                continue
            tree = src.tree
            if tree is None:
                continue
            for fn in ast.walk(tree):
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name not in _WRITER_SECTION:
                    continue
                yield from self._check_writer(src, fn)

    def _check_writer(self, src, fn) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(fn):
            yield from self._visit(src, fn, child)

    def _visit(self, src, fn, node) -> Iterator[Finding]:
        """Report the OUTERMOST offending node, then stop descending —
        a loop over peers is one finding, not one per statement inside
        it, and ``self._peers.values()`` is one reach, not two."""
        if isinstance(node, _LOOP_NODES):
            yield Finding(
                path=str(src.path),
                line=node.lineno,
                rule=self.name,
                message=(
                    f"loop inside the broadcast writer section "
                    f"{fn.name}(): the write path must be O(1) in "
                    "peers — per-peer (or per-segment) iteration "
                    "belongs in the dispatcher (DESIGN.md fan-out)"
                ),
            )
            return
        offender = _mentions_peer_state(node)
        if offender is not None:
            yield Finding(
                path=str(src.path),
                line=node.lineno,
                rule=self.name,
                message=(
                    f"{offender} reached from the broadcast writer "
                    f"section {fn.name}(): per-peer state is the "
                    "dispatcher's business — the writer must never "
                    "touch it (DESIGN.md fan-out)"
                ),
            )
            return
        for child in ast.iter_child_nodes(node):
            yield from self._visit(src, fn, child)
