"""unbounded-join: blocking waits in daemon/server code need deadlines.

Motivating incident (ADVICE.md round 5, low): ``sidecar.run_session``'s
healthy path ended with a bare ``sender.join()`` — a client that
finished sending but never read its reply parked the reply thread in a
blocked write and the session thread in ``join()`` forever: a
per-connection thread/memory leak in ``--tcp`` mode, a permanent hang
in ``--stdio`` mode.

Flagged shapes:

* ``x.join()`` with no arguments.  A ``Thread.join`` without a timeout
  can block forever; ``str.join`` / ``os.path.join`` / ``Path.join``
  always take an argument, so the zero-arg form is reliably the
  blocking one.  Pass a timeout (looping if needed, so stall detection
  stays possible) or suppress with a justification.
* ``sock.settimeout(None)`` — explicitly switching a socket back to
  unbounded blocking mode.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project


class UnboundedJoin:
    name = "unbounded-join"
    description = (
        "zero-argument .join() and settimeout(None) block forever; "
        "give daemon waits a deadline"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            tree = src.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                if attr == "join" and not node.args and not node.keywords:
                    yield Finding(
                        path=str(src.path),
                        line=node.lineno,
                        rule=self.name,
                        message=(
                            ".join() with no timeout can block this thread "
                            "forever on a stalled peer; join in a bounded "
                            "loop and act on the stall"
                        ),
                    )
                elif attr == "settimeout" and len(node.args) == 1 and \
                        isinstance(node.args[0], ast.Constant) and \
                        node.args[0].value is None:
                    yield Finding(
                        path=str(src.path),
                        line=node.lineno,
                        rule=self.name,
                        message=(
                            "settimeout(None) makes every subsequent socket "
                            "op block unboundedly; use a finite timeout"
                        ),
                    )
