"""env-cache-policy: never freeze an os.environ decision into a cache.

Motivating incident (ADVICE.md round 5, low): ``wire/change_codec`` and
``session/decoder`` each grew a private ``_fastpath_mod`` cache.  One
cached the ``DAT_FASTPATH_DISABLE`` decision forever, the other re-read
it per call — so flipping the env var mid-process disabled the dispatch
loop while silently leaving the C codec active.  Tests that set the
variable to force the pure-Python path were exercising half of it.
The sanctioned policy lives in ``runtime.fastpath.get()`` /
``runtime.native.get_lib()``: re-read the gating variable on every
call, cache only the expensive import/build.

The rule flags the two shapes that freeze an environment read:

* a function that both assigns a ``global``-declared name (a module
  cache) and reads ``os.environ`` / ``os.getenv`` — the decision ends
  up inside the cache;
* a module-level assignment whose right-hand side reads the
  environment — frozen at first import, invisible to later ``setenv``.

Reading the environment fresh per call, or caching state that is not
derived from an environment read, is fine.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project, assign_targets, dotted_name, \
    walk_function_body


def _env_reads(node: ast.AST) -> Iterator[ast.AST]:
    """os.environ / os.getenv read sites lexically under ``node``
    (not descending into nested defs)."""
    for child in walk_function_body(node):
        if isinstance(child, ast.Attribute) and \
                dotted_name(child) in ("os.environ", "environ"):
            yield child
        elif isinstance(child, ast.Call) and \
                dotted_name(child.func) in ("os.getenv", "getenv"):
            yield child


class EnvCachePolicy:
    name = "env-cache-policy"
    description = (
        "os.environ reads must not be frozen into module-level caches; "
        "route gating through the shared runtime helpers"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            tree = src.tree
            if tree is None:
                continue
            # module-level: RHS of a top-level assignment reads the env
            for stmt in tree.body:
                if not list(assign_targets(stmt)):
                    continue
                value = getattr(stmt, "value", None)
                if value is None:
                    continue
                holder = ast.Module(body=[ast.Expr(value=value)],
                                    type_ignores=[])
                for read in _env_reads(holder):
                    yield Finding(
                        path=str(src.path),
                        line=stmt.lineno,
                        rule=self.name,
                        message=(
                            "environment read frozen into a module-level "
                            "value at import time; later setenv calls are "
                            "silently ignored — read it inside the using "
                            "function instead"
                        ),
                    )
                    break
            # function-level: global cache assigned + env read in one body
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                yield from self._check_function(src, node)

    def _check_function(self, src, fn: ast.AST) -> Iterator[Finding]:
        global_names: set[str] = set()
        for child in walk_function_body(fn):
            if isinstance(child, ast.Global):
                global_names.update(child.names)
        if not global_names:
            return
        caches_global = any(
            isinstance(t, ast.Name) and t.id in global_names
            for child in walk_function_body(fn)
            for t in assign_targets(child)
        )
        if not caches_global:
            return
        for read in _env_reads(fn):
            yield Finding(
                path=str(src.path),
                line=read.lineno,
                rule=self.name,
                message=(
                    f"{fn.name} reads os.environ while populating a module "
                    f"cache ({', '.join(sorted(global_names))}): the env "
                    f"decision gets frozen into the cache (split-brain when "
                    f"set mid-process).  Cache only the import; re-read the "
                    f"variable per call (see runtime.fastpath.get)"
                ),
            )
            return  # one finding per function is enough
