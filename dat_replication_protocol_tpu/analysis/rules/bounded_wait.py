"""bounded-wait: blocking event/condition waits need a bound or a reason.

Motivating incidents: the threaded transport pump's lost-wakeup hang
(transport.recv_over relied on a per-write completion callback a
cross-thread ``done()`` could skip — ADVICE.md round 5's stall family)
and the asyncio sender's bare ``await readable.wait()`` — an encoder
whose producer died without finalizing parked the pump task forever.
The robustness doctrine (ROBUSTNESS.md): every blocking wait either
carries a timeout (re-checking its condition in a loop) or carries an
explicit, audited justification.

Flagged shapes (Python sources only):

* ``x.wait()`` with no arguments — ``threading.Event.wait`` /
  ``Condition.wait`` block forever without a timeout, and
  ``asyncio.Event.wait`` (awaited or not) has no timeout parameter at
  all, so the zero-arg form is reliably unbounded.
* ``x.drain()`` with no arguments — ``asyncio.StreamWriter.drain``
  blocks until the peer reads; a peer that never reads parks the task
  forever.

Escapes:

* any argument or keyword (a timeout was passed);
* the call is wrapped in ``asyncio.wait_for(...)`` (the only way to
  bound the asyncio forms);
* a ``# datlint: allow-unbounded-wait`` comment on the call's line (or
  the comment line above) — the audited-justification escape hatch;
  write the reason next to it.

``x.join()`` is the companion ``unbounded-join`` rule's territory; this
rule deliberately does not double-report it.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project

_ALLOW_MARKER = "allow-unbounded-wait"
_WAIT_ATTRS = ("wait", "drain")


def _wait_for_protected(tree: ast.Module) -> set[int]:
    """ids of Call nodes that appear inside an ``asyncio.wait_for(...)``
    (or bare ``wait_for(...)``) argument list — those waits are bounded
    by the wrapper."""
    protected: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name != "wait_for":
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Call):
                    protected.add(id(sub))
    return protected


class BoundedWait:
    name = "bounded-wait"
    description = (
        "zero-argument .wait()/.drain() block forever; bound them with "
        "a timeout (or asyncio.wait_for) and re-check in a loop, or "
        "justify with '# datlint: allow-unbounded-wait'"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            tree = src.tree
            if tree is None:
                continue
            protected = _wait_for_protected(tree)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                if node.func.attr not in _WAIT_ATTRS:
                    continue
                if node.args or node.keywords:
                    continue  # a timeout (or equivalent) was passed
                if id(node) in protected:
                    continue  # bounded by asyncio.wait_for
                if self._allowed(src, node):
                    continue
                yield Finding(
                    path=str(src.path),
                    line=node.lineno,
                    rule=self.name,
                    message=(
                        f".{node.func.attr}() with no timeout can park "
                        "this thread/task forever on a stalled peer or a "
                        "lost wakeup; pass a timeout (or wrap in "
                        "asyncio.wait_for) and re-check the condition in "
                        "a loop, or justify with "
                        "'# datlint: allow-unbounded-wait'"
                    ),
                )

    @staticmethod
    def _allowed(src, node: ast.Call) -> bool:
        """The audited-justification escape: an allow marker in a comment
        on any line the call spans, or on the comment line above."""
        first = node.lineno
        last = getattr(node, "end_lineno", None) or first
        for line in range(first - 1, last + 1):
            if _ALLOW_MARKER in src.comments.get(line, ""):
                return True
        return False
