"""hub-isolation: the shared engine's two structural invariants.

Motivating design contract (ISSUE 8, ROBUSTNESS.md overload behavior):
the hub multiplexes every session onto ONE device pipeline, so two
whole-class failure modes live one careless edit away:

1. **A lock held across a device dispatch.**  The hub lock serializes
   per-session accounting; a device call (pipeline dispatch/flush, a
   ``hash_begin``/``collect`` closure, a ``device_put``) can block for
   milliseconds to seconds.  Holding the lock across one turns every
   co-resident session's submit into a convoy behind the device — the
   exact cross-session stall the hub exists to exclude.  The dispatcher
   composes batches UNDER the lock and dispatches OUTSIDE it; this rule
   keeps that shape honest.

2. **Per-session state reached around the session-keyed accessor.**
   Session state is keyed by session; every key-addressed reach into
   the table must go through the accessor (``_session_state``) so there
   is exactly one place where "which session?" is answered (and where a
   future generation/tombstone check would live).  A raw
   ``self._sessions[key]`` scattered through the engine is how a shed
   or closed session's state gets resurrected by a stale key.

Flagged shapes (Python sources under a ``hub/`` directory only):

* inside any ``with`` statement whose context expression's dotted name
  contains ``lock`` (``self._lock``, ``hub._lock``): a call whose
  receiver's dotted name contains ``pipeline``, or whose attribute name
  is one of the device-dispatch set (``dispatch``, ``flush``,
  ``hash_begin``, ``hash_batch``, ``collect``, ``start_d2h``,
  ``device_put``, ``block_until_ready``);
* a subscript on an attribute named ``_sessions`` (read, write, or
  delete) in any function OTHER than the accessor itself or the
  registration pair (``_session_state``, ``register``, ``_unregister``).

Escapes: the standard ``# datlint: disable=hub-isolation`` suppression
(justify next to it).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project, dotted_name

_DISPATCH_ATTRS = {
    "dispatch", "flush", "hash_begin", "hash_batch", "collect",
    "start_d2h", "device_put", "block_until_ready",
}
_ACCESSOR_METHODS = {"_session_state", "register", "_unregister"}


def _is_lock_ctx(item: ast.withitem) -> bool:
    name = dotted_name(item.context_expr)
    if name is None and isinstance(item.context_expr, ast.Call):
        name = dotted_name(item.context_expr.func)
    return name is not None and "lock" in name.lower()


def _dispatchy_call(node: ast.Call) -> str | None:
    """The offending call's rendered name when it looks like a device
    dispatch, else None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        recv = dotted_name(fn.value)
        if recv is not None and "pipeline" in recv.lower():
            return f"{recv}.{fn.attr}"
        if fn.attr.lstrip("_") in _DISPATCH_ATTRS:
            full = dotted_name(fn)
            return full or fn.attr
    elif isinstance(fn, ast.Name) and fn.id.lstrip("_") in _DISPATCH_ATTRS:
        return fn.id
    return None


class HubIsolation:
    name = "hub-isolation"
    description = (
        "in hub/: no device dispatch (pipeline call, hash_begin/collect, "
        "device_put) may run while a lock is held, and _sessions[...] is "
        "only touched inside the session-keyed accessor"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        for src in project.py_sources:
            if "hub" not in src.path.parts[:-1]:
                continue
            tree = src.tree
            if tree is None:
                continue
            yield from self._check_lock_spans(src, tree)
            yield from self._check_accessor(src, tree)

    def _check_lock_spans(self, src, tree: ast.Module) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.With) or \
                    not any(_is_lock_ctx(i) for i in node.items):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                offender = _dispatchy_call(sub)
                if offender is None:
                    continue
                yield Finding(
                    path=str(src.path),
                    line=sub.lineno,
                    rule=self.name,
                    message=(
                        f"{offender}(...) inside a with-lock block: a "
                        "device dispatch under the hub lock convoys "
                        "every co-resident session behind the device — "
                        "compose under the lock, dispatch outside it "
                        "(ROBUSTNESS.md overload behavior)"
                    ),
                )

    def _check_accessor(self, src, tree: ast.Module) -> Iterator[Finding]:
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in _ACCESSOR_METHODS:
                continue
            for sub in ast.iter_child_nodes(fn):
                yield from self._subscripts_in(src, fn, sub)

    def _subscripts_in(self, src, fn, node) -> Iterator[Finding]:
        # don't descend into nested defs: they are checked on their own
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Attribute) and \
                node.value.attr == "_sessions":
            yield Finding(
                path=str(src.path),
                line=node.lineno,
                rule=self.name,
                message=(
                    f"_sessions[...] reached directly in {fn.name}(): "
                    "per-session state must go through the session-keyed "
                    "accessor (_session_state) so stale keys cannot "
                    "resurrect shed/closed sessions"
                ),
            )
        for child in ast.iter_child_nodes(node):
            yield from self._subscripts_in(src, fn, child)
