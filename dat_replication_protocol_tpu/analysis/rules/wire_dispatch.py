"""wire-dispatch-parity: a frame type is wired EVERYWHERE or nowhere.

Motivating incident (ISSUE 13, riding on PR 12): landing TYPE_SNAPSHOT
meant touching four dispatch surfaces by hand — the streaming header
scanner, the bulk frame-index dispatch, the ``_frames_delivered``
checkpoint arithmetic, and the tracing ``kind=`` vocabulary — and the
review round was the only thing standing between frame 5 and shipping
half-wired (parsed on one path, miscounted on the other; checkpoints
and structured errors silently disagreeing about frame indices).
wire-constant-parity keeps the *values* in sync across languages; this
rule keeps the *dispatch matrix* filled in across surfaces, so frame 6
cannot ship half-wired.

For every ``TYPE_*`` constant the framing module (the module defining
``KNOWN_TYPES``) lists in ``KNOWN_TYPES``:

1. **streaming scanner** — the constant is referenced in a function
   named ``_scan_header`` (the byte-at-a-time header dispatch);
2. **bulk-index dispatch** — referenced in ``_run_indexed`` (the
   native frame-index fast path must know every type the streaming
   path knows, or the two paths diverge on the same wire);
3. **accounting** — ``_frames_delivered`` (the single frame-index
   authority for checkpoints and structured errors) mentions a counter
   named after the frame kind (``changes``, ``blobs``,
   ``reconcile_frames``, ``_batch_frames_done``, ...);
4. **tracing** — the scanner's module emits a ``kind="<kind>"``
   literal for it (the causal-tracing vocabulary, obs/tracing.py),
   where ``<kind>`` is the constant name lowercased sans ``TYPE_``.

A ``TYPE_*`` constant defined but missing from ``KNOWN_TYPES``, and a
framing module with no reachable scanner/bulk/accounting surface at
all, are LOUD findings — the matrix check must never silently disarm
because a refactor renamed its anchors (the cursor-coherence lesson).

Escapes: the standard ``# datlint: disable=wire-dispatch-parity`` on
the constant's definition line, next to a written justification (e.g.
a type that is deliberately scanner-only during a migration window).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..engine import Finding, Project

_SCANNER = "_scan_header"
_BULK = "_run_indexed"
_ACCOUNTING = "_frames_delivered"


def _module_types(tree: ast.Module) -> tuple[dict, list, int]:
    """(TYPE_* name -> line, KNOWN_TYPES member names, KNOWN_TYPES line)
    for one module; ([], -1) when the module defines no KNOWN_TYPES."""
    types: dict[str, int] = {}
    known: list[str] = []
    known_line = -1
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1 \
                or not isinstance(stmt.targets[0], ast.Name):
            continue
        name = stmt.targets[0].id
        if name.startswith("TYPE_") and isinstance(stmt.value, ast.Constant):
            types[name] = stmt.lineno
        elif name == "KNOWN_TYPES" and isinstance(stmt.value,
                                                  (ast.Tuple, ast.List)):
            known_line = stmt.lineno
            for elt in stmt.value.elts:
                if isinstance(elt, ast.Name):
                    known.append(elt.id)
    return types, known, known_line


def _names_in_function(tree: ast.Module, fn_name: str) -> set | None:
    """Every Name/attribute identifier inside the first function named
    ``fn_name``, or None when no such function exists."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == fn_name:
            out: set = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
                elif isinstance(sub, ast.Attribute):
                    out.add(sub.attr)
            return out
    return None


def _kind_literals(tree: ast.Module) -> set:
    """String values passed as ``kind=`` keywords anywhere in a module."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    out.add(kw.value.value)
    return out


class WireDispatchParity:
    name = "wire-dispatch-parity"
    description = (
        "every KNOWN_TYPES frame type is wired into the streaming "
        "scanner, the bulk-index dispatch, _frames_delivered "
        "accounting, and the tracing kind= vocabulary"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        framing = None  # (src, types, known, known_line)
        for src in project.py_sources:
            tree = src.tree
            if tree is None:
                continue
            types, known, known_line = _module_types(tree)
            if known_line >= 0 and types:
                framing = (src, types, known, known_line)
                break
        if framing is None:
            return  # no wire layer in this project: nothing to certify
        src, types, known, known_line = framing

        # surfaces, wherever they live in the project
        scanner = bulk = accounting = None
        kinds: set = set()
        for other in project.py_sources:
            tree = other.tree
            if tree is None:
                continue
            s = _names_in_function(tree, _SCANNER)
            if s is not None and scanner is None:
                scanner = (other, s)
                kinds = _kind_literals(tree)
            b = _names_in_function(tree, _BULK)
            if b is not None and bulk is None:
                bulk = (other, b)
            a = _names_in_function(tree, _ACCOUNTING)
            if a is not None and accounting is None:
                accounting = (other, a)

        for surface, fn_name in ((scanner, _SCANNER), (bulk, _BULK),
                                 (accounting, _ACCOUNTING)):
            if surface is None:
                yield Finding(
                    path=str(src.path), line=known_line, rule=self.name,
                    message=(
                        f"no function named {fn_name} anywhere in the "
                        f"analyzed project: the dispatch-parity matrix "
                        f"lost its anchor and certifies nothing — "
                        f"re-point the rule at the renamed surface"
                    ),
                )
        if scanner is None or bulk is None or accounting is None:
            return

        for tname, line in sorted(types.items(), key=lambda kv: kv[1]):
            if tname == "TYPE_HEADER":
                continue  # parser state, never a wire frame id
            if tname not in known:
                yield Finding(
                    path=str(src.path), line=line, rule=self.name,
                    message=(
                        f"{tname} is defined but not listed in "
                        f"KNOWN_TYPES — a frame type outside the registry "
                        f"dodges every parity surface"
                    ),
                )
                continue
            kind = tname[len("TYPE_"):].lower()
            token = kind.rsplit("_", 1)[-1]
            missing = []
            if tname not in scanner[1]:
                missing.append(f"streaming scanner ({_SCANNER})")
            if tname not in bulk[1]:
                missing.append(f"bulk-index dispatch ({_BULK})")
            if not any(kind in n or token in n for n in accounting[1]):
                missing.append(
                    f"{_ACCOUNTING} accounting (no counter mentioning "
                    f"'{kind}' or '{token}')")
            if kind not in kinds:
                missing.append(
                    f'tracing vocabulary (no kind="{kind}" literal in '
                    f'the scanner module)')
            if missing:
                yield Finding(
                    path=str(src.path), line=line, rule=self.name,
                    message=(
                        f"{tname} is half-wired: missing from "
                        f"{'; '.join(missing)} — every frame type is "
                        f"wired into all four dispatch surfaces or none"
                    ),
                )
