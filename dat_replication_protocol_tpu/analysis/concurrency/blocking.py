"""blocking-under-lock: no lock-holding region may block.

Motivating incidents (ISSUE 13; ANALYSIS.md has the table): every
review round since the hub landed has caught one of these by hand —
the dispatcher composing a batch under ``self._lock`` and then writing
the socket before releasing it, an event emitter invoking a user sink
inside its registry lock (the sink re-enters ``emit`` → self-deadlock;
or merely blocks → every emitting thread convoys), the obs HTTP
handler reading a file under the collector lock.  The hub-isolation
rule hard-codes ONE instance of the contract (no device dispatch under
the hub lock); this rule is that contract generalized to the whole
program, with the call graph carried along: a helper only ever invoked
under a lock is analyzed as running locked even though it contains no
``with`` itself.

Blocked-call classes (the ``cls`` vocabulary, used by the scoped
allowlist):

* ``sleep`` — ``time.sleep``
* ``socket`` — send/recv/sendall/accept/connect/select on any
  socket-shaped receiver
* ``os-io`` — ``os.write/writev/read/...`` (raw fd I/O)
* ``file-io`` — ``open()`` and file-object read/write on a file-shaped
  receiver
* ``subprocess`` — any ``subprocess.*`` entry point
* ``callback`` — invoking user-supplied code (``on_*``/``*_cb``/
  ``*_hook``/``sink`` attributes, callable parameters, loop-unpacked
  callback tuples).  User code under YOUR lock is the worst class:
  it can block forever AND re-enter the lock.

Escape: ``# datlint: allow-blocking-under-lock`` on (or immediately
above) the call line accepts the site; ``allow-blocking-under-lock
(socket,file-io)`` scopes the acceptance to the named classes.  Every
allow must sit next to a written justification — the fixture suite
keeps the marker honest.

Findings cite the full chain: entry function → call steps → the lock
acquisition → the blocking call, so the reader sees both WHY the lock
is held and WHAT blocks under it.
"""

from __future__ import annotations

from typing import Iterator

from ..engine import Finding, Project
from .model import ProgramIndex

_CHAIN_SEP = " -> "


class BlockingUnderLock:
    name = "blocking-under-lock"
    description = (
        "no socket/file/os I/O, sleep, subprocess, or user-callback "
        "invocation while a lock is held (directly or through the "
        "call graph); escape: allow-blocking-under-lock + justification"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        index = ProgramIndex.get(project)
        for sid in sorted(index.blocked):
            site, fn, chain, held = index.blocked[sid]
            roots = sorted({index.root_lock(h) for h in held
                            if not h.startswith("?")})
            unknown = [h for h in held if h.startswith("?")]
            if not roots and unknown:
                # only unresolvable lock-like regions hold here; still a
                # finding (conservative), but say so
                held_desc = "an unresolved lock-like region"
            else:
                held_desc = ", ".join(roots)
                if unknown:
                    held_desc += " (+ an unresolved lock-like region)"
            yield Finding(
                path=index.src_path(fn.module.relpath),
                line=site.line,
                rule=self.name,
                message=(
                    f"{site.rendered} [{site.cls}] runs while holding "
                    f"{held_desc}: a blocking call under a lock convoys "
                    f"every thread contending for it"
                    + (" — and user code under your lock can re-enter "
                       "it (self-deadlock)" if site.cls == "callback"
                       else "")
                    + f".  Path: {_CHAIN_SEP.join(chain)}"
                ),
                chains=(chain,),
            )
