"""guarded-state: declared fields are only written under their lock.

Motivating incident class (ISSUE 13): split-brain shared state — a
field that every *documented* path mutates under ``self._lock``, plus
one forgotten path (a late-added close(), a stats probe, a reconnect
handler running on the pump thread) that writes it bare.  No seed
sweep reliably catches the torn interleaving; review rounds caught
three of these by hand.  Like cursor-coherence, the invariant is
declarative — state it once, next to the lock that owns it::

    # datlint: guarded-by(self._lock): self._peers, self._retired

and the rule enforces, for every function the whole-program index can
see: a write (assignment, ``del``, or container mutation —
``.append``/``.pop``/``.update``/...) to a declared field counts as
guarded only when the guarding lock is held at the write, either
lexically (an enclosing ``with``) or at function entry on EVERY known
call path (the ``*_locked``-helper idiom, proven through the call
graph — not assumed from the name).

Scope and placement: a declaration inside a ``class`` body covers that
class's ``self.<field>`` members; ``__init__`` is exempt (construction
happens before the object is shared).  A module-level declaration
covers bare module-global names.

The cursor-coherence lesson, inherited verbatim: a declaration this
rule cannot honor — unparsable member, a lock name that resolves to no
known lock, ``self.`` members declared outside any class, a member no
function ever writes (stale/typo'd spelling) — is itself a LOUD
finding.  A linter guarding silent corruption must never silently
disarm.

Escape: the standard ``# datlint: disable=guarded-state`` on the
writing line, next to a written justification (e.g. a single-threaded
teardown that provably happens after every worker joined).
"""

from __future__ import annotations

import re
from typing import Iterator, Optional

from ..engine import Finding, Project, canonical
from .model import FunctionInfo, ModuleInfo, ProgramIndex

_DECL_RE = re.compile(r"datlint:\s*guarded-by\(\s*([^)]*?)\s*\)\s*:\s*(.+)$")


class _Decl:
    def __init__(self, line: int, lock_expr: str, members: tuple,
                 cls: Optional[str], lock_root: Optional[str]):
        self.line = line
        self.lock_expr = lock_expr
        self.members = members        # canonical member expressions
        self.cls = cls                # enclosing class, if any
        self.lock_root = lock_root    # resolved ROOT lock id


class GuardedState:
    name = "guarded-state"
    description = (
        "fields declared '# datlint: guarded-by(lock): fields' are "
        "only written while that lock is held (lexically or at entry "
        "on every known call path); unhonorable declarations are loud"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        index = ProgramIndex.get(project)
        for relpath in sorted(index.modules):
            mod = index.modules[relpath]
            decls, bad = self._declarations(index, mod)
            path = index.src_path(relpath)
            for line, message in bad:
                yield Finding(path=path, line=line, rule=self.name,
                              message=message)
            for decl in decls:
                yield from self._check_decl(index, mod, path, decl)

    # -- declaration parsing -------------------------------------------------

    def _declarations(self, index: ProgramIndex, mod: ModuleInfo
                      ) -> tuple[list, list]:
        decls: list[_Decl] = []
        bad: list[tuple[int, str]] = []
        for line in sorted(mod.src.comments):
            m = _DECL_RE.search(mod.src.comments[line])
            if m is None:
                continue
            lock_expr, member_src = m.group(1), m.group(2)
            cls = self._enclosing_class(mod, line)
            members = []
            ok = True
            for part in member_src.split(","):
                part = part.strip()
                if not part:
                    continue
                try:
                    members.append(canonical(part))
                except SyntaxError:
                    bad.append((line, (
                        f"guarded-by declaration has an unparsable member "
                        f"{part!r} — the whole declaration is ignored and "
                        f"the rule is OFF for these fields until it is "
                        f"fixed")))
                    ok = False
                    break
            if not ok:
                continue
            if not members:
                bad.append((line, (
                    "guarded-by declaration names no fields — declaration "
                    "ignored, the rule is OFF until it is fixed")))
                continue
            selfish = [mm for mm in members if mm.startswith("self.")]
            if selfish and cls is None:
                bad.append((line, (
                    f"guarded-by declares {', '.join(selfish)} outside any "
                    f"class body — 'self.' members need the owning class; "
                    f"declaration ignored until it is moved")))
                continue
            if not lock_expr:
                bad.append((line, (
                    "guarded-by() names no lock — declaration ignored "
                    "until it is fixed")))
                continue
            root = index._resolve_lock_name(lock_expr, mod, cls, ())
            if root is None:
                bad.append((line, (
                    f"guarded-by({lock_expr}) does not resolve to any "
                    f"known threading.Lock/RLock/Condition — declaration "
                    f"ignored (and the rule silently OFF) until the lock "
                    f"name is fixed")))
                continue
            decls.append(_Decl(line, lock_expr, tuple(members), cls, root))
        return decls, bad

    @staticmethod
    def _enclosing_class(mod: ModuleInfo, line: int) -> Optional[str]:
        best = None
        for cinfo in mod.classes.values():
            if cinfo.lineno <= line <= cinfo.end_lineno:
                if best is None or cinfo.lineno > best.lineno:
                    best = cinfo
        return best.name if best is not None else None

    # -- enforcement ---------------------------------------------------------

    def _check_decl(self, index: ProgramIndex, mod: ModuleInfo, path: str,
                    decl: _Decl) -> Iterator[Finding]:
        in_scope = [fn for fn in index.functions.values()
                    if fn.module is mod]
        seen_write = {m: False for m in decl.members}
        for fn in sorted(in_scope, key=lambda f: f.key):
            if decl.cls is not None and fn.cls == decl.cls \
                    and fn.name == f"{decl.cls}.__init__":
                # construction happens-before publication
                for w in self._member_writes(index, fn, decl):
                    seen_write[w[0]] = True
                continue
            for member, write in self._member_writes(index, fn, decl):
                seen_write[member] = True
                if self._guarded(index, fn, write.held, decl.lock_root):
                    continue
                held_roots = sorted({index.root_lock(h) for h in write.held
                                     if not h.startswith("?")})
                under = (f" (holds {', '.join(held_roots)} — not the "
                         f"declared guard)" if held_roots else
                         " with no lock held")
                yield Finding(
                    path=path, line=write.line, rule=self.name,
                    message=(
                        # the declaration site lives in the SECOND
                        # sentence: Finding.key() keeps only the first,
                        # and baseline keys must survive unrelated
                        # edits shifting line numbers
                        f"{fn.name} writes {member} ({write.via}) outside "
                        f"its declared guard {decl.lock_root}{under}.  "
                        f"Declared guarded-by({decl.lock_expr}) at "
                        f"{mod.relpath}:{decl.line}; entry-held on every "
                        f"known call path: "
                        f"{sorted(index.entry_held(fn.key)) or 'nothing'}"
                    ),
                )
        for member in decl.members:
            if not seen_write[member]:
                yield Finding(
                    path=path, line=decl.line, rule=self.name,
                    message=(
                        f"guarded-by declares {member} but no function in "
                        f"{mod.relpath} ever writes it — a stale or "
                        f"typo'd declaration guards nothing (fix the "
                        f"spelling or drop the member)"
                    ),
                )

    def _member_writes(self, index: ProgramIndex, fn: FunctionInfo,
                       decl: _Decl) -> Iterator[tuple]:
        members = set(decl.members)
        if decl.cls is not None and fn.cls != decl.cls:
            # self.X members belong to the declaring class; bare-name
            # members still apply module-wide
            members = {m for m in members if not m.startswith("self.")}
        if not members:
            return
        for write in fn.writes:
            if write.target in members:
                yield write.target, write
        for write in index.mutator_calls(fn):
            if write.target in members:
                yield write.target, write

    @staticmethod
    def _guarded(index: ProgramIndex, fn: FunctionInfo, held: tuple,
                 guard_root: str) -> bool:
        for h in held:
            if not h.startswith("?") and index.root_lock(h) == guard_root:
                return True
        return guard_root in index.entry_held(fn.key)
