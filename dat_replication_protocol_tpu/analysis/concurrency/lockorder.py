"""lock-order: no two locks may be acquired in both orders.

Motivating contract (ISSUE 13, ROBUSTNESS.md): the stack runs ~22
threaded modules whose locks compose across files — the fan-out server
takes its own lock and then the broadcast log's (``attach`` / ``ack``
under ``self._lock``); the hub's ``*_locked`` helpers emit events whose
sink has its own two locks; the watermark board registers registry
collectors.  Each pairing is safe ONLY while every thread acquires the
pair in the same order.  A cycle in the acquired-while-held graph is a
deadlock that no seed sweep reliably reproduces (both threads must hit
the window), which is exactly the kind of property a whole-program
pass can prove absent — and the event-loop refactor (ROADMAP item 2)
is only safe to attempt against a certified-acyclic web.

Findings:

* **Inversion** — a cycle ``A -> B -> ... -> A`` in the lock graph;
  the finding cites every edge's acquisition chain (file:line steps
  from the function that takes the first lock to the ``with`` that
  takes the next).
* **Self-re-acquisition** — an ``A -> A`` edge where ``A`` is a plain
  ``threading.Lock``: re-entering a non-reentrant lock is a guaranteed
  single-thread deadlock.  The same edge on an ``RLock`` (or a
  ``Condition`` wrapping one) is a NON-finding by construction —
  re-entry is what RLock is for.

Escapes: the standard ``# datlint: disable=lock-order`` suppression at
the edge's acquisition site (justify next to it).
"""

from __future__ import annotations

from typing import Iterator

from ..engine import Finding, Project
from .model import ProgramIndex

_CHAIN_SEP = " -> "


def _chain_anchor(chain: tuple) -> tuple:
    """(path, line) of a chain's FIRST step — where the outer lock is
    taken; that is the line an auditor looks at first."""
    head = chain[0]
    loc = head.split(" ", 1)[0]
    path, _, line = loc.rpartition(":")
    try:
        return path, int(line)
    except ValueError:
        return loc, 1


class LockOrder:
    name = "lock-order"
    description = (
        "no lock-acquisition cycles: two locks taken in both orders "
        "(or a plain Lock re-acquired while held) deadlock under the "
        "right interleaving"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        index = ProgramIndex.get(project)
        yield from self._self_edges(index)
        yield from self._cycles(index)

    def _self_edges(self, index: ProgramIndex) -> Iterator[Finding]:
        for (a, b), chain in sorted(index.lock_edges.items()):
            if a != b:
                continue
            root = index.locks.get(index.root_lock(a))
            kind = root.kind if root is not None else "lock"
            if kind == "rlock":
                continue  # re-entry is what RLock is for
            if kind == "condition":
                # a Condition with no resolvable wrapped lock: its own
                # internal RLock-like semantics are unknowable here —
                # do not cry deadlock on it
                continue
            rel, line = _chain_anchor(chain)
            path = index.src_path(rel)
            yield Finding(
                path=path, line=line, rule=self.name,
                message=(
                    f"{a} is re-acquired while already held and is a "
                    f"non-reentrant threading.Lock — a guaranteed "
                    f"self-deadlock on this path: "
                    f"{_CHAIN_SEP.join(chain)}"
                ),
                chains=(chain,),
            )

    def _cycles(self, index: ProgramIndex) -> Iterator[Finding]:
        graph: dict[str, list] = {}
        for (a, b) in index.lock_edges:
            if a != b:
                graph.setdefault(a, []).append(b)
        for succs in graph.values():
            succs.sort()
        reported: set = set()
        for start in sorted(graph):
            cycle = self._find_cycle(graph, start)
            if cycle is None:
                continue
            canon = self._canonical(cycle)
            if canon in reported:
                continue
            reported.add(canon)
            chains = tuple(index.lock_edges[(canon[i],
                                             canon[(i + 1) % len(canon)])]
                           for i in range(len(canon)))
            rel, line = _chain_anchor(chains[0])
            path = index.src_path(rel)
            order = " -> ".join(canon + (canon[0],))
            detail = "; ".join(
                f"[{canon[i]} before {canon[(i + 1) % len(canon)]}: "
                f"{_CHAIN_SEP.join(chains[i])}]"
                for i in range(len(canon)))
            yield Finding(
                path=path, line=line, rule=self.name,
                message=(
                    f"lock-order inversion {order}: these locks are "
                    f"acquired in conflicting orders — a deadlock under "
                    f"the right thread interleaving.  Acquisition "
                    f"chains: {detail}"
                ),
                chains=chains,
            )

    @staticmethod
    def _find_cycle(graph: dict, start: str):
        """A simple cycle through ``start`` (DFS, deterministic), or
        None.  Only cycles CONTAINING start are found from start; every
        cycle contains its own lexicographically-smallest node, which
        the sorted outer loop reaches."""
        stack = [(start, iter(graph.get(start, ())))]
        on_path = {start}
        path = [start]
        while stack:
            node, it = stack[-1]
            advanced = False
            for succ in it:
                if succ == start:
                    return tuple(path)
                if succ in on_path or succ not in graph:
                    continue
                on_path.add(succ)
                path.append(succ)
                stack.append((succ, iter(graph.get(succ, ()))))
                advanced = True
                break
            if not advanced:
                stack.pop()
                on_path.discard(path.pop())
        return None

    @staticmethod
    def _canonical(cycle: tuple) -> tuple:
        i = cycle.index(min(cycle))
        return cycle[i:] + cycle[:i]
