"""Event-loop readiness certifier (ISSUE 16): may-block summaries,
blocking-reachability, and callback-escape over the shared ProgramIndex.

ROADMAP item 2 rebuilds the edge onto ONE selector/epoll dispatch loop.
The proof obligation that blocks it is not code but *knowledge*: which
functions may block, for how long, and which user-supplied callables can
end up running on the dispatcher thread.  This module computes that
knowledge as a whole-program pass and freezes it into a reviewable
certificate:

* **May-block summaries.**  Every function (plus every lambda literal)
  is classified on a three-level lattice::

      nonblocking < bounded-blocking < unbounded-blocking

  A *site* is bounded when the call itself carries its bound — a
  ``timeout=``/``deadline=`` keyword, a positional duration on
  ``wait``/``join``/``acquire``/``sleep``, ``acquire(blocking=False)``,
  a 4-argument ``select.select`` — and unbounded otherwise (bare
  ``recv``/``accept``/``sendall``, raw ``os.read``/``os.write``, file
  I/O, subprocess without ``timeout=``, bare ``wait()``/``join()``/
  ``acquire()``).  ``time.sleep(t)`` is bounded by construction: its
  argument IS the bound.  A function's summary is the max over its own
  sites and its callees', computed to fixpoint over the call graph
  (monotone on a finite lattice, so recursion cycles terminate and stay
  sound: a cycle member inherits the worst site anywhere on the cycle).

* **Thread and stored-callback propagation.**  ``Thread(target=f)``
  records a *spawn edge*: the target's classification is computed and
  reported (the spawned thread's readiness), but does NOT raise the
  spawner's summary — starting a thread is nonblocking.  A callable
  stored into an attribute or container (``self._handlers[k] = lambda:
  ...``, ``self._cb = self._on_bytes``) is registered under the stored
  expression; a later dynamic call through that expression
  (``self._handlers[k](...)``) links to the registered callables and
  inherits their summaries.  A dynamic call with NO registered target is
  conservatively a user callback (unbounded).

* **Entry points.**  The table below names the edge's dispatch surfaces
  (hub/fanout dispatchers, sidecar session threads, transport pumps,
  gossip and stats drivers).  The certificate
  (``artifacts/event_loop_surface.json``, written by
  ``--write-artifacts``) lists, per entry point, every reachable
  blocking site — unbounded ones with a full ``file:line`` evidence
  chain — plus the threads it spawns and the callback sites that can
  run on its thread.  Functions named ``_dispatch_loop`` are *enforced*
  dispatchers wherever they appear (fixtures included).

* **Rules.**  :class:`BlockingReachability` — no unbounded-blocking
  site may be reachable from an enforced dispatch loop (escape:
  ``# datlint: allow-blocking-reachable(class)`` next to a written
  justification, e.g. a syscall on an fd the code keeps nonblocking).
  :class:`CallbackEscape` — no user-callback invocation may be
  reachable on the dispatcher thread (escape: ``# datlint:
  allow-callback-escape`` with justification; the audited cases are the
  fanout sink-peer delivery surface and the obs event sinks).

Known under-approximation (same doctrine as the lock model, see
ANALYSIS.md): unresolvable calls contribute no edges, native pump
entry points (``dat_pump_*`` — MSG_DONTWAIT batched turns) are invisible
to the AST and therefore classified by their Python-side wait loops, and
a socket timeout set via ``settimeout``/``SO_RCVTIMEO`` is not visible
at the recv site — such sites stay "unbounded" and carry an audited
allow marker where the bound is real.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterator, Optional

from ..engine import Finding, Project, SourceFile, dotted_name, \
    walk_function_body
from .model import ProgramIndex

LEVELS = ("nonblocking", "bounded-blocking", "unbounded-blocking")
_LEVEL_NUM = {name: i for i, name in enumerate(LEVELS)}

_ALLOW_REACH = re.compile(r"allow-blocking-reachable(?:\(([\w,*-]+)\))?")
_ALLOW_ESCAPE = re.compile(r"allow-callback-escape")

# names whose single positional argument is a duration even without a
# timeout= keyword: thread.join(5), ev.wait(0.1), time.sleep(x)
_TIMEOUTISH_NAME = re.compile(
    r"timeout|deadline|interval|linger|poll|delay|grace|backoff",
    re.IGNORECASE)

# entry points of the edge, named for the certificate.  role:
# "dispatcher" rows are ALSO enforced by the rules below (via the
# _dispatch_loop name pattern); the rest are enumerated so the item-2
# rewrite absorbs a KNOWN surface.  Specs missing from the analyzed
# tree are reported loudly in the certificate, never silently dropped.
ENTRY_SPECS = (
    ("hub-dispatch", "hub/engine.py", "ReplicationHub._dispatch_loop",
     "dispatcher"),
    ("edge-dispatch", "edge/loop.py", "EdgeLoop._dispatch_loop",
     "dispatcher"),
    ("fanout-dispatch", "fanout/server.py", "FanoutServer._dispatch_loop",
     "dispatcher"),
    ("sidecar-session", "sidecar.py", "run_session", "session"),
    ("sidecar-subscriber", "sidecar.py", "run_subscriber", "session"),
    ("sidecar-accept", "sidecar.py", "serve_tcp", "acceptor"),
    ("sidecar-snapshot-accept", "sidecar.py", "SnapshotListener._loop",
     "acceptor"),
    ("sidecar-stats", "sidecar.py", "StatsEmitter._run", "driver"),
    ("transport-send-pump", "session/transport.py", "send_over", "pump"),
    ("transport-recv-pump", "session/transport.py", "recv_over", "pump"),
    ("native-send-pump", "session/pump.py", "send_pump", "pump"),
    ("native-recv-pump", "session/pump.py", "recv_pump", "pump"),
    ("gossip-driver", "cluster/live.py", "GossipDriver._run", "driver"),
)

_DISPATCH_NAME = re.compile(r"^_?dispatch_loop$")


@dataclasses.dataclass
class ReadySite:
    """One blocking/wait/callback site in readiness vocabulary."""

    line: int
    cls: str        # model classes + wait | join | lock-acquire | dynamic
    bound: str      # "bounded" | "unbounded"
    rendered: str
    allowed: bool = False      # allow-blocking-reachable covers it
    cb_allowed: bool = False   # allow-callback-escape covers it


@dataclasses.dataclass
class ThreadSpawn:
    line: int
    target: Optional[str]      # resolved function key, or None
    rendered: str


@dataclasses.dataclass
class ReadyFn:
    key: str
    relpath: str
    name: str
    sites: list = dataclasses.field(default_factory=list)
    edges: list = dataclasses.field(default_factory=list)  # (line, key, txt)
    spawns: list = dataclasses.field(default_factory=list)
    summary: str = "nonblocking"


def _is_none(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _timeout_kw(node: ast.Call) -> Optional[bool]:
    """True: an explicit non-None timeout bound.  False: explicit
    ``timeout=None`` (explicitly unbounded).  None: no timeout kw."""
    for kw in node.keywords:
        if kw.arg in ("timeout", "deadline"):
            return not _is_none(kw.value)
    return None


def _timeoutish(arg: ast.AST) -> bool:
    if isinstance(arg, ast.Constant):
        return isinstance(arg.value, (int, float)) \
            and not isinstance(arg.value, bool)
    name = dotted_name(arg)
    if name is not None:
        return bool(_TIMEOUTISH_NAME.search(name.rsplit(".", 1)[-1]))
    # an expression (min(...), self._linger_s * 2): durations are the
    # codebase idiom for wait arguments; count it as a bound
    return isinstance(arg, (ast.BinOp, ast.Call, ast.IfExp))


class ReadinessIndex:
    """Per-function may-block summaries over one :class:`ProgramIndex`.

    Build once per project via :meth:`get` (memoized alongside the
    concurrency index, so the rules and the artifact writer share it).
    """

    @classmethod
    def get(cls, project: Project) -> "ReadinessIndex":
        idx = getattr(project, "_readiness_index", None)
        if idx is None:
            idx = cls(project)
            project._readiness_index = idx
        return idx

    def __init__(self, project: Project):
        self.base = ProgramIndex.get(project)
        self.fns: dict[str, ReadyFn] = {}
        # (relpath, class-or-None, stored expr) -> sorted keys
        self._stored: dict[tuple, list] = {}
        self._dynamic: list = []   # (ReadyFn, line, expr, rendered, node)
        self._reports: dict[str, dict] = {}
        self._scan()
        self._link_dynamic()
        self._fixpoint()

    # -- scan ---------------------------------------------------------------

    def _scan(self) -> None:
        for key in sorted(self.base.functions):
            fn = self.base.functions[key]
            rf = ReadyFn(key, fn.module.relpath, fn.name)
            self.fns[key] = rf
            aliases = self.base._local_aliases(fn.node)
            loops = self.base._loop_and_unpack_locals(fn.node)
            lambdas = [n for n in walk_function_body(fn.node)
                       if isinstance(n, ast.Lambda)]
            lam_keys = {}
            for lam in sorted(lambdas, key=lambda n: (n.lineno,
                                                      n.col_offset)):
                lk = f"{key}.<lambda>:{lam.lineno}:{lam.col_offset}"
                lam_keys[id(lam)] = lk
                lrf = ReadyFn(lk, fn.module.relpath,
                              f"{fn.name}.<lambda>")
                self.fns[lk] = lrf
                for sub in ast.walk(lam.body):
                    if isinstance(sub, ast.Call):
                        self._classify_call(lrf, fn, sub, aliases, loops,
                                            lam_keys)
            for node in walk_function_body(fn.node):
                if isinstance(node, ast.Call):
                    self._classify_call(rf, fn, node, aliases, loops,
                                        lam_keys)
                elif isinstance(node, ast.Assign) and \
                        len(node.targets) == 1:
                    self._note_stored(rf, fn, node, aliases, lam_keys)
            rf.edges.extend((c.line, c.callee, c.rendered)
                            for c in fn.calls)
            rf.sites.sort(key=lambda s: (s.line, s.rendered))
            rf.edges.sort()
            rf.spawns.sort(key=lambda s: (s.line, s.rendered))
        for k in self._stored:
            self._stored[k] = sorted(set(self._stored[k]))

    def _classify_call(self, rf: ReadyFn, fn, node: ast.Call,
                       aliases: dict, loops: set, lam_keys: dict) -> None:
        base = self.base
        rendered = ast.unparse(node.func)
        # thread spawn: propagate the TARGET's readiness as a spawn
        # edge, not through the (nonblocking) constructor call
        cname = dotted_name(node.func)
        if cname is not None and cname.rsplit(".", 1)[-1] == "Thread":
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    tkeys = self._callable_keys(fn, kw.value, aliases,
                                                lam_keys)
                    target = tkeys[0] if tkeys else None
                    rf.spawns.append(ThreadSpawn(
                        node.lineno, target,
                        ast.unparse(kw.value)))
            return
        if base._resolve_call(fn, node, aliases) is not None:
            return  # a call-graph edge (fn.calls) carries it
        src = fn.module.src
        # stored-callable dynamic dispatch: self._handlers[key](...)
        f = node.func
        if isinstance(f, ast.Subscript):
            recv = dotted_name(f.value)
            if recv is not None:
                recv = aliases.get(recv, recv)
                self._dynamic.append((rf, node.lineno, recv,
                                      f"{recv}[...](...)", fn, node))
                return
        w = self._classify_wait(node)
        if w is not None:
            cls_, bound = w
            rf.sites.append(ReadySite(
                node.lineno, cls_, bound, f"{rendered}(...)",
                self._marker(src, node, _ALLOW_REACH, cls_),
                self._marker(src, node, _ALLOW_ESCAPE, cls_)))
            return
        # stored-attribute dispatch: self._cb(...) where some method
        # assigned self._cb = <callable>
        name = dotted_name(f)
        if name is not None:
            name = aliases.get(name, name)
            skey = (fn.module.relpath, fn.cls, name)
            if skey in self._stored or self._might_store(skey):
                self._dynamic.append((rf, node.lineno, name,
                                      f"{name}(...)", fn, node))
                return
        b = base._classify_blocking(fn, node, aliases, loops)
        if b is None:
            return
        cls_, desc = b
        if cls_ == "socket" and dotted_name(f) == "select.select":
            bound = "bounded" if len(node.args) >= 4 else "unbounded"
        elif cls_ == "sleep":
            bound = ("bounded" if node.args
                     and not _is_none(node.args[0]) else "unbounded")
        elif _timeout_kw(node) is True:
            bound = "bounded"   # create_connection/subprocess timeout=
        else:
            bound = "unbounded"
        rf.sites.append(ReadySite(
            node.lineno, cls_, bound, desc,
            self._marker(src, node, _ALLOW_REACH, cls_),
            self._marker(src, node, _ALLOW_ESCAPE, cls_)))

    def _might_store(self, skey: tuple) -> bool:
        # scan ordering: a dynamic site can precede the method that
        # stores into the attribute; defer ALL dotted-receiver linking
        # to _link_dynamic, which runs after every store is known.
        # Here only self-attribute receivers qualify (a plain dotted
        # call like time.monotonic() must not become "dynamic").
        relpath, cls_, name = skey
        return cls_ is not None and name.startswith("self.") \
            and name.count(".") == 1 and self._stores_into(relpath, cls_,
                                                           name)

    def _stores_into(self, relpath: str, cls_: str, name: str) -> bool:
        mod = self.base.modules.get(relpath)
        if mod is None:
            return False
        attr = name.split(".", 1)[1]
        for fn in mod.functions.values():
            if fn.cls != cls_:
                continue
            for node in walk_function_body(fn.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = dotted_name(node.targets[0])
                    if t == f"self.{attr}":
                        return True
        return False

    def _classify_wait(self, node: ast.Call) -> Optional[tuple]:
        f = node.func
        if not isinstance(f, ast.Attribute):
            return None
        attr = f.attr
        tkw = _timeout_kw(node)
        if attr == "wait" or attr.startswith("wait_"):
            if tkw is not None:
                return ("wait", "bounded" if tkw else "unbounded")
            # wait_for(pred[, timeout]): the FIRST positional is the
            # predicate, only a second one is a bound
            duration_pos = 1 if attr.startswith("wait_") else 0
            if len(node.args) > duration_pos \
                    and not _is_none(node.args[duration_pos]):
                return ("wait", "bounded")
            return ("wait", "unbounded")
        if attr == "join":
            if tkw is not None:
                return ("join", "bounded" if tkw else "unbounded")
            if not node.args and not node.keywords:
                return ("join", "unbounded")
            if len(node.args) == 1 and not node.keywords \
                    and _timeoutish(node.args[0]):
                return ("join", "bounded")
            return None   # str.join / os.path.join shapes
        if attr == "acquire":
            if tkw is True:
                return ("lock-acquire", "bounded")
            for kw in node.keywords:
                if kw.arg == "blocking" and isinstance(kw.value,
                                                       ast.Constant) \
                        and kw.value.value is False:
                    return ("lock-acquire", "bounded")
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and node.args[0].value is False:
                return ("lock-acquire", "bounded")
            return ("lock-acquire", "unbounded")
        return None

    @staticmethod
    def _marker(src: SourceFile, node: ast.AST, regex: re.Pattern,
                cls_: str) -> bool:
        first = node.lineno
        last = getattr(node, "end_lineno", None) or first
        for line in range(first - 1, last + 1):
            m = regex.search(src.comments.get(line, ""))
            if m:
                scope = m.group(1) if m.groups() else None
                if scope is None:
                    return True
                names = set(scope.split(","))
                if cls_ in names or "*" in names or "all" in names:
                    return True
        return False

    def _note_stored(self, rf: ReadyFn, fn, node: ast.Assign,
                     aliases: dict, lam_keys: dict) -> None:
        target = node.targets[0]
        if isinstance(target, ast.Subscript):
            expr = dotted_name(target.value)
        elif isinstance(target, ast.Attribute):
            expr = dotted_name(target)
        else:
            return
        if expr is None:
            return
        values = (list(node.value.values)
                  if isinstance(node.value, ast.Dict) else [node.value])
        keys: list = []
        for value in values:
            keys.extend(self._callable_keys(fn, value, aliases, lam_keys))
        if keys:
            self._stored.setdefault(
                (fn.module.relpath, fn.cls, expr), []).extend(keys)

    def _callable_keys(self, fn, value: ast.AST, aliases: dict,
                       lam_keys: dict) -> list:
        """Function keys a stored/spawned value may refer to."""
        if isinstance(value, ast.Lambda):
            lk = lam_keys.get(id(value))
            return [lk] if lk is not None else []
        name = dotted_name(value)
        if name is None:
            return []
        name = aliases.get(name, name)
        base = self.base
        mod = fn.module
        if name.startswith("self.") and name.count(".") == 1 \
                and fn.cls is not None:
            k = base._lookup_method(mod, fn.cls, name.split(".", 1)[1])
            return [k] if k is not None else []
        if "." not in name:
            # a local def is registered under the enclosing qualname
            local = mod.functions.get(f"{fn.name}.{name}")
            if local is not None:
                return [local.key]
            k = base._resolve_bare(mod, name)
            return [k] if k is not None else []
        k = base._resolve_bare(mod, name)
        return [k] if k is not None else []

    # -- dynamic linking ----------------------------------------------------

    def _link_dynamic(self) -> None:
        for rf, line, expr, rendered, fn, node in self._dynamic:
            targets = self._stored.get((rf.relpath, fn.cls, expr)) \
                or self._stored.get((rf.relpath, None, expr), [])
            if targets:
                for t in targets:
                    rf.edges.append((line, t, rendered))
            else:
                src = fn.module.src
                rf.sites.append(ReadySite(
                    line, "callback", "unbounded", rendered,
                    self._marker(src, node, _ALLOW_REACH, "callback"),
                    self._marker(src, node, _ALLOW_ESCAPE, "callback")))
            rf.edges.sort()
            rf.sites.sort(key=lambda s: (s.line, s.rendered))

    # -- summaries ----------------------------------------------------------

    def _fixpoint(self) -> None:
        level = {k: 0 for k in self.fns}
        for k, rf in self.fns.items():
            for site in rf.sites:
                # an allow marker is an AUDITED bound (the written
                # justification asserts where the bound really lives —
                # a nonblocking fd, a kernel SO_*TIMEO, an attacher
                # contract): audited sites classify bounded, so the
                # summary states what the code + its audits guarantee
                audited = site.allowed or (site.cls == "callback"
                                           and site.cb_allowed)
                level[k] = max(level[k],
                               2 if site.bound == "unbounded"
                               and not audited else 1)
        changed = True
        while changed:
            changed = False
            for k in sorted(self.fns):
                rf = self.fns[k]
                new = level[k]
                for _line, callee, _r in rf.edges:
                    new = max(new, level.get(callee, 0))
                if new != level[k]:
                    level[k] = new
                    changed = True
        for k, rf in self.fns.items():
            rf.summary = LEVELS[level[k]]

    def summary(self, key: str) -> str:
        rf = self.fns.get(key)
        return rf.summary if rf is not None else "nonblocking"

    # -- reachability -------------------------------------------------------

    def dispatchers(self) -> list:
        """Keys of enforced dispatch loops (name pattern, so fixtures
        and the real tree are held to the same contract)."""
        return sorted(
            k for k, rf in self.fns.items()
            if _DISPATCH_NAME.match(rf.name.rsplit(".", 1)[-1]))

    def entry_report(self, key: str) -> dict:
        """Reachable sites/spawns from ``key`` with evidence chains:
        ``{"sites": [(relpath, ReadySite, chain)], "spawns":
        [(relpath, ThreadSpawn, chain)]}`` — deterministic (sorted
        edges, first chain wins)."""
        rep = self._reports.get(key)
        if rep is not None:
            return rep
        sites: list = []
        spawns: list = []
        seen_sites: set = set()
        visited: set = set()

        def visit(k: str, chain: tuple, depth: int) -> None:
            rf = self.fns.get(k)
            if rf is None or k in visited or depth > 64:
                return
            visited.add(k)
            for site in rf.sites:
                sid = (rf.relpath, site.line, site.rendered)
                if sid in seen_sites:
                    continue
                seen_sites.add(sid)
                step = (f"{rf.relpath}:{site.line} {rf.name} calls "
                        f"{site.rendered} [{site.cls}, {site.bound}]")
                sites.append((rf.relpath, site, chain + (step,)))
            for spawn in rf.spawns:
                step = (f"{rf.relpath}:{spawn.line} {rf.name} spawns "
                        f"Thread(target={spawn.rendered})")
                spawns.append((rf.relpath, spawn, chain + (step,)))
            for line, callee, rendered in rf.edges:
                step = f"{rf.relpath}:{line} {rf.name} calls {rendered}"
                visit(callee, chain + (step,), depth + 1)

        visit(key, (), 0)
        rep = {"sites": sites, "spawns": spawns}
        self._reports[key] = rep
        return rep


# -- the enforced rules ------------------------------------------------------

_CHAIN_SEP = " -> "


class BlockingReachability:
    name = "blocking-reachability"
    description = (
        "no unbounded-blocking call (bare recv/accept/join/wait/"
        "lock-acquire, raw fd or file I/O without a bound) reachable "
        "from a certified dispatch loop; escape: "
        "allow-blocking-reachable(class) + justification"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        idx = ReadinessIndex.get(project)
        reported: set = set()
        for key in idx.dispatchers():
            rep = idx.entry_report(key)
            for relpath, site, chain in rep["sites"]:
                if site.bound != "unbounded" or site.cls == "callback":
                    continue   # callbacks are callback-escape's domain
                if site.allowed:
                    continue
                sid = (relpath, site.line, site.rendered)
                if sid in reported:
                    continue
                reported.add(sid)
                yield Finding(
                    path=idx.base.src_path(relpath),
                    line=site.line,
                    rule=self.name,
                    message=(
                        f"{site.rendered} [{site.cls}] is unbounded-"
                        f"blocking and reachable from the dispatch loop "
                        f"{idx.fns[key].name}: one stuck turn parks "
                        f"every session behind the dispatcher.  "
                        f"Path: {_CHAIN_SEP.join(chain)}"
                    ),
                    chains=(chain,),
                )


class CallbackEscape:
    name = "callback-escape"
    description = (
        "no user-supplied callback may run on a certified dispatcher "
        "thread (it can block forever and re-enter the loop's state); "
        "escape: allow-callback-escape + justification"
    )

    def check(self, project: Project) -> Iterator[Finding]:
        idx = ReadinessIndex.get(project)
        reported: set = set()
        for key in idx.dispatchers():
            rep = idx.entry_report(key)
            for relpath, site, chain in rep["sites"]:
                if site.cls != "callback" or site.cb_allowed:
                    continue
                sid = (relpath, site.line, site.rendered)
                if sid in reported:
                    continue
                reported.add(sid)
                yield Finding(
                    path=idx.base.src_path(relpath),
                    line=site.line,
                    rule=self.name,
                    message=(
                        f"{site.rendered} invokes a user-supplied "
                        f"callable on the dispatch-loop thread of "
                        f"{idx.fns[key].name}: user code there can "
                        f"block the whole loop or re-enter its state.  "
                        f"Path: {_CHAIN_SEP.join(chain)}"
                    ),
                    chains=(chain,),
                )


# -- the certificate (artifacts/event_loop_surface.json) ---------------------

def render_event_loop_surface(index: ReadinessIndex) -> dict:
    """JSON-able, deterministic, checkout-location-independent — the
    same byte-stability contract as :func:`..model.render_lock_graph`.
    Unbounded sites carry full evidence chains (they are what the
    item-2 rewrite must bound or absorb); bounded sites are enumerated
    compactly."""
    entries = []
    missing = []
    by_key = {f"{rel}::{qual}": (name, role)
              for name, rel, qual, role in ENTRY_SPECS}
    named_keys = set()
    for name, rel, qual, role in ENTRY_SPECS:
        key = f"{rel}::{qual}"
        if key in index.fns:
            named_keys.add(key)
        else:
            missing.append({"entry": name, "function": key})
    # enforced dispatchers outside the spec table (fixtures, future
    # loops) still certify
    extra = [k for k in index.dispatchers() if k not in named_keys]
    ordered = sorted(named_keys) + sorted(extra)
    for key in ordered:
        rf = index.fns[key]
        name, role = by_key.get(key, (rf.name, "dispatcher"))
        rep = index.entry_report(key)
        unbounded = []
        bounded = []
        callbacks = []
        for relpath, site, chain in rep["sites"]:
            loc = f"{relpath}:{site.line}"
            if site.cls == "callback":
                callbacks.append({
                    "site": loc, "call": site.rendered,
                    "allowed": site.cb_allowed,
                    "chain": list(chain),
                })
            elif site.bound == "unbounded":
                unbounded.append({
                    "site": loc, "call": site.rendered,
                    "class": site.cls, "allowed": site.allowed,
                    "chain": list(chain),
                })
            else:
                bounded.append({
                    "site": loc, "call": site.rendered,
                    "class": site.cls,
                })
        spawns = []
        for relpath, spawn, chain in rep["spawns"]:
            spawns.append({
                "site": f"{relpath}:{spawn.line}",
                "target": spawn.target,
                "classification": (index.summary(spawn.target)
                                   if spawn.target else "unknown"),
            })
        entries.append({
            "entry": name,
            "function": key,
            "role": role,
            "enforced": bool(
                _DISPATCH_NAME.match(rf.name.rsplit(".", 1)[-1])),
            "classification": rf.summary,
            # clean under both rules: every reachable unbounded site
            # and callback invocation carries an audited allow marker
            "certified": (all(d["allowed"] for d in unbounded)
                          and all(d["allowed"] for d in callbacks)),
            "unbounded": sorted(unbounded, key=lambda d: (d["site"],
                                                          d["call"])),
            "bounded": sorted(bounded, key=lambda d: (d["site"],
                                                      d["call"])),
            "callbacks": sorted(callbacks, key=lambda d: (d["site"],
                                                          d["call"])),
            "spawns": sorted(spawns, key=lambda d: (d["site"],
                                                    str(d["target"]))),
        })
    counts = {lvl: 0 for lvl in LEVELS}
    unbounded_fns = []
    for k in sorted(index.fns):
        rf = index.fns[k]
        counts[rf.summary] += 1
        if rf.summary == "unbounded-blocking" and "<lambda>" not in k:
            unbounded_fns.append(k)
    return {
        "version": 1,
        "generator": "python -m dat_replication_protocol_tpu.analysis "
                     "--write-artifacts",
        "levels": list(LEVELS),
        "summary": {"functions": len(index.fns), **counts},
        "entry_points": entries,
        "missing_entry_points": sorted(missing,
                                       key=lambda d: d["entry"]),
        "unbounded_functions": unbounded_fns,
    }
