"""The shared whole-program index the concurrency rules run over.

One :class:`ProgramIndex` is built per analysis run (memoized on the
:class:`~..engine.Project`) and shared by every concurrency rule — the
single-file rules parse each file once via the engine; this layer does
the same for the *cross-file* facts:

* **Lock identities.**  Every ``threading.Lock()`` / ``RLock()`` /
  ``Condition()`` creation site becomes a :class:`LockDef` with a
  stable id: ``<relpath>::<Class>.<attr>`` for instance locks,
  ``<relpath>::<name>`` for module-level locks,
  ``<relpath>::<func>.<name>`` for function-local locks.  A
  ``Condition(existing_lock)`` *aliases* the lock it wraps — acquiring
  the condition IS acquiring that lock, so both resolve to one root
  identity.
* **Regions.**  ``with <expr>:`` items are resolved against the lock
  table (``self._lock`` through the enclosing class, bare names through
  enclosing-function locals and module globals, local aliases like
  ``lock = self._ack_lock``, and — when all else fails — a unique
  attribute-name match across the whole program).  ``.acquire()`` /
  ``.release()`` pairs are NOT modeled; the codebase convention is
  ``with`` (the one non-with user, ``transport.once``, is a
  non-blocking try-acquire).
* **Call graph.**  Direct calls resolve through: same-module functions,
  ``from x import y`` (relative imports resolved against the project
  file tree), ``self.method`` (single-inheritance method lookup within
  the project), module-level singletons (``EVENTS = EventLog()`` makes
  ``EVENTS.emit`` resolvable, also across modules), and instance
  attributes whose constructor is visible in ``__init__``
  (``self.log = BroadcastLog(...)`` makes ``self.log.append``
  resolvable).  Unresolvable calls simply contribute no edges — the
  index is a best-effort under-approximation, documented in
  ANALYSIS.md.
* **Held-lock propagation.**  A deterministic DFS from every function
  (entry held-set empty — any function may be a thread entry point)
  carries the held set through regions and call edges, recording (a)
  ``acquired-while-held`` lock edges with one representative
  acquisition chain each, and (b) for every *blocking* call site, the
  chain by which a lock is held around it.
* **Entry-held closure.**  A greatest-fixpoint over the call graph
  computes, per function, the set of locks held at entry on EVERY
  known call path (functions with no known callers hold nothing) —
  what lets ``guarded-by`` accept a ``*_locked`` helper's writes
  without a lexical ``with``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import posixpath
import re
from typing import Iterator, Optional

from ..engine import Project, SourceFile, dotted_name

LOCK_FACTORIES = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# with-item names that look like locks even when unresolvable; an
# unresolved lock-like region still counts as "a lock is held" for the
# blocking rule (conservative) but never enters the ordering graph
_LOCKISH = re.compile(r"(?:^|[._])(?:[a-z_]*lock|mutex|guard|cv|cond)\w*$",
                      re.IGNORECASE)

# -- blocking-call classification (the documented set, ANALYSIS.md) ---------

# dotted-prefix classes
_BLOCKING_DOTTED = {
    "time.sleep": "sleep",
    "select.select": "socket",
    "select.poll": "socket",
    "socket.create_connection": "socket",
}
_OS_IO = {"write", "writev", "read", "readv", "pread", "pwrite",
          "sendfile", "fsync", "fdatasync"}
# attribute names that are socket operations on ANY receiver
_SOCKET_ATTRS = {"sendall", "sendmsg", "sendto", "recvfrom", "recv_into",
                 "recvfrom_into", "recvmsg", "accept", "connect"}
# send/recv are socket ops only when the receiver's name says so
# (generators have .send; queues and pipes have their own vocabulary)
_SOCKET_RECV_HINTS = ("sock", "conn", "peer", "client", "chan", "srv")
# file-object I/O needs a file-ish receiver (write()/read() are too
# generic to flag on arbitrary objects)
_FILE_ATTRS = {"write", "read", "readline", "readinto", "flush"}
_FILE_RECV_HINTS = ("file", "sink", "fh", "fp", "stream")
# attribute names that ARE user callbacks wherever they are invoked
_CALLBACK_ATTR = re.compile(r"^on_|_cb$|_callback$|_hook$|^(callback|sink|hook)$")
# bare names that are user callbacks when they do not resolve to a
# known function (parameters and loop-unpacked locals qualify with ANY
# name; otherwise the name itself must look like a callback)
_CALLBACK_NAME = re.compile(
    r"^on_|_cb$|_callback$|_hook$|^(cb|callback|handler|hook|sink|done)$")

_ALLOW_MARKER = re.compile(r"allow-blocking-under-lock(?:\(([\w,*-]+)\))?")

# container-mutator method names that count as WRITES to the receiver
# for guarded-by (rebinding is caught via assignment targets; in-place
# mutation of a guarded dict/list/deque/set goes through these)
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
}


@dataclasses.dataclass
class LockDef:
    id: str
    kind: str             # lock | rlock | condition
    path: str             # project-relative posix path
    line: int
    alias_of: Optional[str] = None  # Condition(wrapped_lock)

    @property
    def attr(self) -> str:
        return self.id.rsplit(".", 1)[-1].rsplit("::", 1)[-1]


@dataclasses.dataclass
class Region:
    lock: Optional[str]   # resolved ROOT lock id; None = lock-like, unknown
    line: int
    rendered: str         # source form of the with-item
    outer: tuple = ()     # lock ids lexically held around this region


@dataclasses.dataclass
class CallSite:
    line: int
    callee: Optional[str]  # resolved function key, or None
    rendered: str
    held: tuple            # lock ids lexically held at the site
    allowed: bool = False  # allow-blocking-under-lock on the call line:
    # the LEXICALLY held locks are accepted around this entire call
    # subtree (locks held further up the chain are NOT excused)


@dataclasses.dataclass
class BlockingSite:
    line: int
    cls: str               # sleep | socket | os-io | subprocess | file-io | callback
    rendered: str
    held: tuple            # lexically held at the site
    allowed: bool          # an allow-blocking-under-lock marker covers it


@dataclasses.dataclass
class Write:
    line: int
    target: str            # canonical written expression (or receiver)
    via: str               # "assign" | "del" | "mutator:<name>"
    held: tuple            # lexically held at the write


@dataclasses.dataclass
class FunctionInfo:
    key: str               # "<relpath>::<Qual>"  (Qual = Class.meth | func)
    module: "ModuleInfo"
    node: ast.AST
    cls: Optional[str]     # enclosing class name, if any
    params: tuple
    regions: list = dataclasses.field(default_factory=list)
    calls: list = dataclasses.field(default_factory=list)
    blocking: list = dataclasses.field(default_factory=list)
    writes: list = dataclasses.field(default_factory=list)
    mutator_writes: list = dataclasses.field(default_factory=list)

    @property
    def name(self) -> str:
        return self.key.split("::", 1)[1]


@dataclasses.dataclass
class ClassInfo:
    name: str
    bases: tuple           # base-class NAMES as written (resolved lazily)
    lineno: int
    end_lineno: int
    methods: dict = dataclasses.field(default_factory=dict)  # name -> fn key
    attr_types: dict = dataclasses.field(default_factory=dict)  # attr -> class key


@dataclasses.dataclass
class ModuleInfo:
    relpath: str
    src: SourceFile
    imports: dict = dataclasses.field(default_factory=dict)   # alias -> (mod, name)
    module_aliases: dict = dataclasses.field(default_factory=dict)  # alias -> mod
    functions: dict = dataclasses.field(default_factory=dict)  # qual -> FunctionInfo
    classes: dict = dataclasses.field(default_factory=dict)    # name -> ClassInfo
    singletons: dict = dataclasses.field(default_factory=dict)  # name -> class key


def _common_root(paths: list) -> str:
    if not paths:
        return ""
    if len(paths) == 1:
        return os.path.dirname(os.path.abspath(str(paths[0])))
    return os.path.commonpath([os.path.abspath(str(p)) for p in paths])


class ProgramIndex:
    """See module docstring.  Build once per project via :meth:`get`."""

    @classmethod
    def get(cls, project: Project) -> "ProgramIndex":
        idx = getattr(project, "_concurrency_index", None)
        if idx is None:
            idx = cls(project)
            project._concurrency_index = idx
        return idx

    def __init__(self, project: Project):
        self.project = project
        self.root = _common_root([s.path for s in project.py_sources])
        self.modules: dict[str, ModuleInfo] = {}
        self.locks: dict[str, LockDef] = {}
        self.functions: dict[str, FunctionInfo] = {}
        # (from_root_id, to_root_id) -> chain (tuple of step strings)
        self.lock_edges: dict[tuple, tuple] = {}
        # blocking-site id -> (site, fn, chain) first found with a lock held
        self.blocked: dict[tuple, tuple] = {}
        self._scan_modules()
        self._scan_locks()
        self._resolve_condition_aliases()
        self._scan_functions()
        self._traverse()
        self._entry_held = self._fixpoint_entry_held()

    # -- paths ---------------------------------------------------------------

    def relpath(self, src: SourceFile) -> str:
        p = os.path.abspath(str(src.path))
        try:
            rel = os.path.relpath(p, self.root)
        except ValueError:
            rel = str(src.path)
        return rel.replace(os.sep, "/")

    def src_path(self, relpath: str) -> str:
        """The engine-side path (``str(SourceFile.path)``) for a
        project-relative path — findings must carry THAT form so the
        engine's suppression lookup and every other rule's rendering
        agree."""
        mod = self.modules.get(relpath)
        return str(mod.src.path) if mod is not None else relpath

    # -- pass 1: module shells, imports, classes, locks ----------------------

    def _scan_modules(self) -> None:
        for src in self.project.py_sources:
            tree = src.tree
            if tree is None:
                continue
            mod = ModuleInfo(self.relpath(src), src)
            self.modules[mod.relpath] = mod
            self._scan_imports(mod, tree)
            for stmt in tree.body:
                if isinstance(stmt, ast.ClassDef):
                    mod.classes[stmt.name] = ClassInfo(
                        stmt.name,
                        tuple(b for b in map(dotted_name, stmt.bases) if b),
                        stmt.lineno,
                        getattr(stmt, "end_lineno", stmt.lineno) or stmt.lineno,
                    )
            # module-level locks and singletons
            for stmt in tree.body:
                self._note_lock_assign(mod, stmt, cls=None, func=None)
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and isinstance(stmt.value, ast.Call):
                    cname = dotted_name(stmt.value.func)
                    if cname and cname not in ("threading.Lock",
                                               "threading.RLock",
                                               "threading.Condition"):
                        mod.singletons[stmt.targets[0].id] = (mod.relpath,
                                                              cname)

    def _scan_imports(self, mod: ModuleInfo, tree: ast.Module) -> None:
        parts = mod.relpath.split("/")[:-1]  # package dirs of this module
        for stmt in ast.walk(tree):
            if isinstance(stmt, ast.ImportFrom):
                base = list(parts)
                if stmt.level:
                    base = parts[:len(parts) - (stmt.level - 1)] \
                        if stmt.level <= len(parts) + 1 else None
                    if base is None:
                        continue
                else:
                    base = []
                modpath = (stmt.module or "").split(".") if stmt.module else []
                # absolute imports may spell the package root's own name
                if not stmt.level and modpath:
                    rootname = posixpath.basename(
                        self.root.replace(os.sep, "/"))
                    if modpath[0] == rootname:
                        modpath = modpath[1:]
                target = "/".join(base + modpath)
                for alias in stmt.names:
                    name = alias.name
                    asname = alias.asname or name
                    # "from pkg import module" vs "from module import name"
                    as_mod = self._module_file(target + "/" + name)
                    if as_mod is not None:
                        mod.module_aliases[asname] = as_mod
                    else:
                        f = self._module_file(target)
                        if f is not None:
                            mod.imports[asname] = (f, name)
            elif isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    f = self._module_file(alias.name.replace(".", "/"))
                    if f is not None:
                        mod.module_aliases[alias.asname or alias.name] = f

    def _module_file(self, stem: str) -> Optional[str]:
        if not stem:
            return None
        for cand in (stem + ".py", stem + "/__init__.py"):
            if cand in self.modules:
                return cand
        # pass-1 ordering: the module map is still filling; fall back to
        # the project file set
        for src in self.project.py_sources:
            rel = self.relpath(src)
            if rel == stem + ".py" or rel == stem + "/__init__.py":
                return rel
        return None

    # -- lock discovery ------------------------------------------------------

    def _lock_factory(self, value: ast.AST) -> Optional[tuple]:
        """(kind, ctor_node) when ``value`` is a lock construction."""
        if not isinstance(value, ast.Call):
            return None
        name = dotted_name(value.func)
        if name is None:
            return None
        last = name.rsplit(".", 1)[-1]
        if last in LOCK_FACTORIES and (
                "." not in name or name.startswith("threading.")
                or name.startswith("_threading.")):
            return LOCK_FACTORIES[last], value
        return None

    def _note_lock_assign(self, mod: ModuleInfo, stmt: ast.AST,
                          cls: Optional[str], func: Optional[str]) -> None:
        if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
            return
        fact = self._lock_factory(stmt.value)
        if fact is None:
            return
        kind, ctor = fact
        target = stmt.targets[0]
        tname = dotted_name(target)
        if tname is None:
            return
        if tname.startswith("self.") and cls is not None:
            lock_id = f"{mod.relpath}::{cls}.{tname[5:]}"
        elif "." not in tname and func is not None:
            lock_id = f"{mod.relpath}::{func}.{tname}"
        elif "." not in tname and cls is None:
            lock_id = f"{mod.relpath}::{tname}"
        else:
            return
        alias = None
        if kind == "condition" and ctor.args:
            # resolved in pass 1.5, once every lock is known; remember
            # the wrapped expression for now
            alias = ("pending", mod.relpath, cls,
                     dotted_name(ctor.args[0]))
        self.locks[lock_id] = LockDef(lock_id, kind, mod.relpath,
                                      stmt.lineno, alias)

    def _scan_locks(self) -> None:
        """Pass 1.5: find EVERY lock construction — module-level ones
        were noted in pass 1; this walk adds instance locks
        (``self._lock = threading.Lock()`` in any method, ``__init__``
        or otherwise) and function-local locks, with the enclosing
        class/function recorded so regions resolve against the right
        identity.  A separate pass so that a region in module A can
        name a lock constructed in module B regardless of scan order."""
        for mod in self.modules.values():
            tree = mod.src.tree
            if tree is None:
                continue
            self._scan_locks_in(mod, tree.body, cls=None, func_chain=())

    def _scan_locks_in(self, mod: ModuleInfo, body, cls, func_chain) -> None:
        for stmt in body:
            if isinstance(stmt, ast.ClassDef):
                # only top-level classes carry lock identities (nested
                # classes are out of the call graph's reach anyway)
                if cls is None and not func_chain:
                    self._scan_locks_in(mod, stmt.body, stmt.name, ())
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_locks_in(mod, stmt.body, cls,
                                    func_chain + (stmt.name,))
                continue
            if func_chain:  # inside a function: note with its qualname
                fname = ".".join(func_chain) if cls is None \
                    else f"{cls}.{'.'.join(func_chain)}"
                self._note_lock_assign(mod, stmt, cls=cls, func=fname)
            handler_bodies = [h.body for h in
                              getattr(stmt, "handlers", [])]
            for sub_body in (getattr(stmt, "body", []),
                             getattr(stmt, "orelse", []),
                             getattr(stmt, "finalbody", []),
                             *handler_bodies):
                if sub_body:
                    self._scan_locks_in(mod, sub_body, cls, func_chain)

    def _resolve_condition_aliases(self) -> None:
        for lock in self.locks.values():
            alias = lock.alias_of
            if not isinstance(alias, tuple):
                continue
            _, relpath, cls, expr = alias
            lock.alias_of = None
            if expr is None:
                continue
            mod = self.modules[relpath]
            # NO unique-attr fallback here: a mis-aliased condition
            # corrupts every ordering fact about the lock it wraps
            resolved = self._resolve_lock_name(expr, mod, cls, (),
                                               fallback=False)
            if resolved is None and cls is not None and "." not in expr:
                # ``Condition(lock)`` wrapping a constructor parameter
                # (the hub/fanout per-session state idiom): resolve
                # through the class's construction sites — when every
                # site passes the SAME lock, the alias is that lock
                resolved = self._alias_via_ctor_sites(mod, cls, expr)
            if resolved is not None and resolved != lock.id:
                lock.alias_of = resolved

    def _alias_via_ctor_sites(self, mod: ModuleInfo, cls: str,
                              param: str) -> Optional[str]:
        tree = mod.src.tree
        init = None
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls:
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) \
                            and sub.name == "__init__":
                        init = sub
                        break
        if init is None:
            return None
        names = [a.arg for a in init.args.args]  # self first
        if param not in names:
            return None
        pos = names.index(param) - 1  # positional index at call sites
        roots: set = set()
        for caller_mod in self.modules.values():
            ctree = caller_mod.src.tree
            if ctree is None:
                continue
            for call, ctx_cls, ctx_chain in self._calls_with_context(ctree):
                cname = dotted_name(call.func)
                if cname is None or \
                        self._resolve_class(caller_mod, cname) != \
                        (mod.relpath, cls):
                    continue
                arg = None
                if 0 <= pos < len(call.args):
                    arg = dotted_name(call.args[pos])
                for kw in call.keywords:
                    if kw.arg == param:
                        arg = dotted_name(kw.value)
                if arg is None:
                    return None  # an unresolvable site poisons the alias
                r = self._resolve_lock_name(arg, caller_mod, ctx_cls,
                                            ctx_chain, fallback=False)
                if r is None:
                    return None
                roots.add(r)
        if len(roots) == 1:
            return next(iter(roots))
        return None

    @staticmethod
    def _calls_with_context(tree: ast.Module) -> Iterator[tuple]:
        """(Call node, enclosing top-level class or None, enclosing
        function-name chain) for every call in a module."""
        def walk(node, cls, chain):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    if cls is None and not chain:
                        yield from walk(child, child.name, ())
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    yield from walk(child, cls, chain + (child.name,))
                    continue
                if isinstance(child, ast.Call):
                    yield child, cls, chain
                yield from walk(child, cls, chain)

        yield from walk(tree, None, ())

    def root_lock(self, lock_id: str) -> str:
        seen = set()
        while lock_id in self.locks and \
                self.locks[lock_id].alias_of is not None and \
                lock_id not in seen:
            seen.add(lock_id)
            lock_id = self.locks[lock_id].alias_of
        return lock_id

    def _resolve_lock_name(self, expr: Optional[str], mod: ModuleInfo,
                           cls: Optional[str], func_chain: tuple,
                           local_aliases: Optional[dict] = None,
                           fallback: bool = True) -> Optional[str]:
        """Resolve a dotted lock expression to a ROOT lock id, or None."""
        if not expr:
            return None
        if local_aliases and expr in local_aliases:
            expr = local_aliases[expr]
            if not expr:
                return None
        head, _, rest = expr.partition(".")
        if head in ("self", "cls") and cls is not None and rest:
            cand = f"{mod.relpath}::{cls}.{rest}"
            if cand in self.locks:
                return self.root_lock(cand)
        if "." not in expr:
            # innermost enclosing scope first; method-local locks are
            # registered class-qualified ("Cls.meth.name")
            for i in range(len(func_chain), 0, -1):
                q = ".".join(func_chain[:i])
                for qual in ((f"{cls}.{q}", q) if cls is not None else (q,)):
                    cand = f"{mod.relpath}::{qual}.{expr}"
                    if cand in self.locks:
                        return self.root_lock(cand)
            cand = f"{mod.relpath}::{expr}"
            if cand in self.locks:
                return self.root_lock(cand)
            if head in mod.imports:
                imod, iname = mod.imports[head]
                cand = f"{imod}::{iname}"
                if cand in self.locks:
                    return self.root_lock(cand)
        if not fallback:
            return None
        # last resort: a unique attribute-name match program-wide
        attr = expr.rsplit(".", 1)[-1]
        matches = sorted(lid for lid, ld in self.locks.items()
                         if ld.attr == attr)
        if len(matches) == 1:
            return self.root_lock(matches[0])
        if matches and cls is not None:
            own = [m for m in matches
                   if m.startswith(f"{mod.relpath}::{cls}.")]
            if len(own) == 1:
                return self.root_lock(own[0])
        return None

    # -- pass 2: functions (regions, calls, blocking sites, writes) ----------

    def _scan_functions(self) -> None:
        # registration FIRST, body walks SECOND: a call site resolves
        # against the complete function/method table, not just the
        # names that happened to be defined earlier in scan order
        pending: list[tuple[FunctionInfo, tuple]] = []
        for mod in self.modules.values():
            tree = mod.src.tree
            if tree is None:
                continue
            self._scan_scope(mod, tree.body, cls=None, qual=(),
                             pending=pending)
        # class attr types from __init__ constructor assignments
        for mod in self.modules.values():
            for cname, cinfo in mod.classes.items():
                init = cinfo.methods.get("__init__")
                if init is None:
                    continue
                self._scan_attr_types(mod, cinfo,
                                      self.functions[init].node)
        for fn, quals in pending:
            self._walk_body(fn, fn.node, held=(), func_chain=quals,
                            local_aliases=self._local_aliases(fn.node),
                            loop_locals=self._loop_and_unpack_locals(
                                fn.node))

    def _scan_scope(self, mod: ModuleInfo, body, cls: Optional[str],
                    qual: tuple, pending: list) -> None:
        for stmt in body:
            if isinstance(stmt, ast.ClassDef) and cls is None and not qual:
                self._scan_scope(mod, stmt.body, cls=stmt.name, qual=(),
                                 pending=pending)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(mod, stmt, cls, qual, pending)
            elif isinstance(stmt, (ast.If, ast.Try, ast.For, ast.While,
                                   ast.With)):
                # defs nested under module-level control flow (version
                # guards, try/except import shims — INCLUDING the
                # except-handler fallback def) still count
                handler_bodies = [h.body for h in
                                  getattr(stmt, "handlers", [])]
                for sub_body in (getattr(stmt, "body", []),
                                 getattr(stmt, "orelse", []),
                                 getattr(stmt, "finalbody", []),
                                 *handler_bodies):
                    self._scan_scope(mod, sub_body, cls, qual, pending)

    def _scan_function(self, mod: ModuleInfo, node, cls: Optional[str],
                       qual: tuple, pending: list) -> None:
        quals = qual + (node.name,)
        name = (f"{cls}.{'.'.join(quals)}" if cls is not None
                else ".".join(quals))
        key = f"{mod.relpath}::{name}"
        args = node.args
        params = tuple(a.arg for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ) + ([args.vararg] if args.vararg else [])
          + ([args.kwarg] if args.kwarg else []))
        fn = FunctionInfo(key, mod, node, cls, params)
        self.functions[key] = fn
        mod.functions[name] = fn
        if cls is not None and len(quals) == 1:
            mod.classes[cls].methods[node.name] = key
        pending.append((fn, quals))
        # nested defs are separate scopes, analyzed on their own
        for sub in self._nested_defs(node):
            self._scan_function(mod, sub, cls, quals, pending)

    @staticmethod
    def _nested_defs(node) -> Iterator[ast.AST]:
        """defs directly inside ``node``'s body (not inside a further
        def/class — those are found by their own parent's scan)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            child = stack.pop()
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child
                continue
            if isinstance(child, (ast.ClassDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(child))

    @staticmethod
    def _local_aliases(node) -> dict:
        """{local_name: dotted_source} for simple aliases like
        ``lock = self._ack_lock`` / ``mka = _FastAck``."""
        out: dict = {}
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not node:
                continue
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                    isinstance(sub.targets[0], ast.Name):
                src = dotted_name(sub.value)
                tgt = sub.targets[0].id
                if src is not None and src != tgt:
                    # last simple alias wins; reassignment from a call
                    # etc. clears the alias
                    out[tgt] = src
                elif tgt in out and src is None:
                    out[tgt] = None
        return {k: v for k, v in out.items() if v}

    @staticmethod
    def _loop_and_unpack_locals(node) -> set:
        """Names bound by for-targets / tuple unpacking — callback
        carriers like ``for cb, tag, ... in ready:``."""
        out: set = set()

        def targets(t):
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    targets(e)

        for sub in ast.walk(node):
            if isinstance(sub, (ast.For, ast.AsyncFor)):
                targets(sub.target)
            elif isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        targets(t)
        return out

    def _walk_body(self, fn: FunctionInfo, node, held: tuple,
                   func_chain: tuple, local_aliases: dict,
                   loop_locals: set) -> None:
        """Dispatch on ``node`` ITSELF, then recurse into children —
        so a ``with`` directly nested in another ``with``'s body is
        region-processed like any other (the dispatch-on-children shape
        silently skipped exactly that case)."""
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                # the context-manager expression itself evaluates while
                # every EARLIER item is already held — its calls are
                # real calls (`with open(...):`, `with helper():`) and
                # must enter blocking classification / the call graph,
                # or context-manager I/O under a lock goes dark
                self._walk_body(fn, item.context_expr, inner,
                                func_chain, local_aliases, loop_locals)
                lid = self._region_lock(fn, item, func_chain,
                                        local_aliases)
                if lid is not False:
                    rendered = ast.unparse(item.context_expr)
                    fn.regions.append(Region(lid, node.lineno,
                                             rendered, inner))
                    lock_id = (lid if lid is not None
                               else f"?{fn.key}:{node.lineno}")
                    if lock_id not in inner:
                        inner = inner + (lock_id,)
            for sub in node.body:
                self._walk_body(fn, sub, inner, func_chain,
                                local_aliases, loop_locals)
            return
        if isinstance(node, ast.Call):
            self._note_call(fn, node, held, func_chain, local_aliases,
                            loop_locals)
        elif isinstance(node, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign, ast.Delete)):
            self._note_writes(fn, node, held)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            self._walk_body(fn, child, held, func_chain, local_aliases,
                            loop_locals)

    def _region_lock(self, fn: FunctionInfo, item: ast.withitem,
                     func_chain: tuple, local_aliases: dict):
        """ROOT lock id for a with-item; None for lock-like-but-unknown;
        False when the item is not a lock at all."""
        expr = item.context_expr
        name = dotted_name(expr)
        if name is None and isinstance(expr, ast.Call):
            # with Lock(): ... (anonymous) — lock-like, unknown identity
            cname = dotted_name(expr.func)
            if cname and cname.rsplit(".", 1)[-1] in LOCK_FACTORIES:
                return None
            return False
        if name is None:
            return False
        resolved = self._resolve_lock_name(name, fn.module, fn.cls,
                                           func_chain, local_aliases)
        if resolved is not None:
            return resolved
        if _LOCKISH.search(name):
            return None
        return False

    # -- calls ---------------------------------------------------------------

    def _note_call(self, fn: FunctionInfo, node: ast.Call, held: tuple,
                   func_chain: tuple, local_aliases: dict,
                   loop_locals: set) -> None:
        rendered = ast.unparse(node.func)
        # container-mutator method calls double as WRITES to the
        # receiver (guarded-state) — recorded HERE, where the main
        # walk's held set / local aliases are correct, instead of a
        # lexical re-walk that missed function-local lock aliases
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATOR_METHODS:
            recv = dotted_name(node.func.value)
            if recv is not None:
                fn.mutator_writes.append(Write(
                    node.lineno, recv, f"mutator:{node.func.attr}",
                    held))
        callee = self._resolve_call(fn, node, local_aliases)
        if callee is not None:
            fn.calls.append(CallSite(
                node.lineno, callee, rendered, held,
                self._allowed(fn.module.src, node, "call")))
            return
        b = self._classify_blocking(fn, node, local_aliases, loop_locals)
        if b is not None:
            cls_, desc = b
            fn.blocking.append(BlockingSite(
                node.lineno, cls_, desc, held,
                self._allowed(fn.module.src, node, cls_)))

    def _resolve_call(self, fn: FunctionInfo, node: ast.Call,
                      local_aliases: dict) -> Optional[str]:
        f = node.func
        mod = fn.module
        if isinstance(f, ast.Name):
            name = local_aliases.get(f.id, f.id)
            return self._resolve_bare(mod, name)
        if not isinstance(f, ast.Attribute):
            return None
        meth = f.attr
        recv = dotted_name(f.value)
        if recv is None:
            return None
        recv = local_aliases.get(recv, recv)
        if recv in ("self", "cls") and fn.cls is not None:
            return self._lookup_method(mod, fn.cls, meth)
        head, _, rest = recv.partition(".")
        if head in ("self", "cls") and fn.cls is not None and rest \
                and "." not in rest:
            cinfo = mod.classes.get(fn.cls)
            if cinfo is not None and rest in cinfo.attr_types:
                tmod, tcls = cinfo.attr_types[rest]
                return self._lookup_method(self.modules.get(tmod), tcls, meth)
            return None
        if "." in recv:
            return None
        # module alias: events.emit(...)
        if recv in mod.module_aliases:
            target = self.modules.get(mod.module_aliases[recv])
            if target is not None:
                fi = target.functions.get(meth)
                return fi.key if fi is not None else None
        # module-level singleton, local or imported
        single = mod.singletons.get(recv)
        if single is None and recv in mod.imports:
            imod, iname = mod.imports[recv]
            target = self.modules.get(imod)
            if target is not None:
                single = target.singletons.get(iname)
        if single is not None:
            smod, scls = single
            owner = self.modules.get(smod)
            if owner is not None:
                key = self._resolve_class(owner, scls)
                if key is not None:
                    return self._lookup_method(self.modules[key[0]],
                                               key[1], meth)
        return None

    def _resolve_bare(self, mod: ModuleInfo, name: str) -> Optional[str]:
        if name is None or "." in name:
            if name and "." in name:
                head, _, rest = name.partition(".")
                if head in mod.module_aliases and "." not in rest:
                    target = self.modules.get(mod.module_aliases[head])
                    if target is not None:
                        fi = target.functions.get(rest)
                        if fi is not None:
                            return fi.key
                        if rest in target.classes:
                            return self._lookup_method(target, rest,
                                                       "__init__")
            return None
        fi = mod.functions.get(name)
        if fi is not None:
            return fi.key
        if name in mod.classes:
            return self._lookup_method(mod, name, "__init__")
        if name in mod.imports:
            imod, iname = mod.imports[name]
            target = self.modules.get(imod)
            if target is not None:
                fi = target.functions.get(iname)
                if fi is not None:
                    return fi.key
                if iname in target.classes:
                    return self._lookup_method(target, iname, "__init__")
        return None

    def _resolve_class(self, mod: ModuleInfo, name: str
                       ) -> Optional[tuple]:
        """(module_relpath, class_name) for a class expression."""
        if name in mod.classes:
            return (mod.relpath, name)
        if name in mod.imports:
            imod, iname = mod.imports[name]
            target = self.modules.get(imod)
            if target is not None and iname in target.classes:
                return (imod, iname)
        if "." in name:
            head, _, rest = name.partition(".")
            if head in mod.module_aliases and "." not in rest:
                target = self.modules.get(mod.module_aliases[head])
                if target is not None and rest in target.classes:
                    return (mod.module_aliases[head], rest)
        return None

    def _lookup_method(self, mod: Optional[ModuleInfo], cls: str,
                       meth: str, _depth: int = 0) -> Optional[str]:
        if mod is None or _depth > 8:
            return None
        cinfo = mod.classes.get(cls)
        if cinfo is None:
            return None
        key = cinfo.methods.get(meth)
        if key is not None:
            return key
        for base in cinfo.bases:
            resolved = self._resolve_class(mod, base)
            if resolved is not None:
                found = self._lookup_method(self.modules.get(resolved[0]),
                                            resolved[1], meth, _depth + 1)
                if found is not None:
                    return found
        return None

    def _scan_attr_types(self, mod: ModuleInfo, cinfo: ClassInfo,
                         init_node) -> None:
        for sub in ast.walk(init_node):
            if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                continue
            t = dotted_name(sub.targets[0])
            if t is None or not t.startswith("self.") or t.count(".") != 1:
                continue
            attr = t[5:]
            for value in self._ctor_candidates(sub.value):
                cname = dotted_name(value.func)
                if cname is None or \
                        cname.rsplit(".", 1)[-1] in LOCK_FACTORIES:
                    continue
                resolved = self._resolve_class(mod, cname)
                if resolved is not None:
                    cinfo.attr_types.setdefault(attr, resolved)
                    break

    @staticmethod
    def _ctor_candidates(value: ast.AST) -> Iterator[ast.Call]:
        if isinstance(value, ast.Call):
            yield value
        elif isinstance(value, ast.IfExp):
            for arm in (value.body, value.orelse):
                if isinstance(arm, ast.Call):
                    yield arm

    # -- blocking classification ---------------------------------------------

    def _classify_blocking(self, fn: FunctionInfo, node: ast.Call,
                           local_aliases: dict, loop_locals: set
                           ) -> Optional[tuple]:
        f = node.func
        if isinstance(f, ast.Name):
            name = f.id
            if name == "open":
                return ("file-io", "open(...)")
            src = local_aliases.get(name)
            if src is not None and self._resolve_bare(fn.module, src):
                return None  # alias of a known function
            if name in fn.params or name in loop_locals:
                return ("callback", f"{name}(...)")
            if src is not None and (src.startswith("self.on_")
                                    or _CALLBACK_ATTR.search(
                                        src.rsplit(".", 1)[-1])):
                return ("callback", f"{name}(...) [= {src}]")
            if _CALLBACK_NAME.search(name):
                return ("callback", f"{name}(...)")
            return None
        if not isinstance(f, ast.Attribute):
            return None
        full = dotted_name(f)
        attr = f.attr
        if full is not None:
            if full in _BLOCKING_DOTTED:
                return (_BLOCKING_DOTTED[full], f"{full}(...)")
            if full.startswith("subprocess."):
                return ("subprocess", f"{full}(...)")
            if full.startswith("os.") and attr in _OS_IO:
                return ("os-io", f"{full}(...)")
        recv = dotted_name(f.value) or ""
        recv_l = recv.lower()
        if attr in _SOCKET_ATTRS:
            return ("socket", f"{recv}.{attr}(...)")
        if attr in ("send", "recv") and any(h in recv_l
                                            for h in _SOCKET_RECV_HINTS):
            return ("socket", f"{recv}.{attr}(...)")
        if attr in _FILE_ATTRS and (
                any(h in recv_l for h in _FILE_RECV_HINTS)
                or recv_l in ("f", "fh", "fp") or recv_l.endswith("._f")):
            return ("file-io", f"{recv}.{attr}(...)")
        if _CALLBACK_ATTR.search(attr):
            return ("callback", f"{recv}.{attr}(...)")
        return None

    @staticmethod
    def _allowed(src: SourceFile, node: ast.AST, cls_: str) -> bool:
        first = node.lineno
        last = getattr(node, "end_lineno", None) or first
        for line in range(first - 1, last + 1):
            m = _ALLOW_MARKER.search(src.comments.get(line, ""))
            if m:
                scope = m.group(1)
                if scope is None:
                    return True
                names = set(scope.split(","))
                if cls_ in names or "*" in names or "all" in names:
                    return True
        return False

    # -- writes (guarded-by's input) -----------------------------------------

    def _note_writes(self, fn: FunctionInfo, node, held: tuple) -> None:
        if isinstance(node, ast.Delete):
            for t in node.targets:
                base = self._write_base(t)
                if base is not None:
                    fn.writes.append(Write(node.lineno, base, "del", held))
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for e in elts:
                    base = self._write_base(e)
                    if base is not None:
                        fn.writes.append(Write(node.lineno, base,
                                               "assign", held))

    @staticmethod
    def _write_base(target: ast.AST) -> Optional[str]:
        """Canonical written expression AND its one-level base: a write
        to ``self._sessions[key]`` is a write to ``self._sessions``."""
        try:
            full = ast.unparse(target)
        except Exception:
            return None
        if isinstance(target, ast.Subscript):
            base = dotted_name(target.value)
            return base if base is not None else full
        return full

    def mutator_calls(self, fn: FunctionInfo) -> Iterator[Write]:
        """Container-mutator method calls as writes — recorded by the
        main walk (`_note_call`) with its factual held set, so aliased
        and function-local locks resolve exactly like any other call."""
        return iter(fn.mutator_writes)

    # -- traversal: lock edges + transitive blocking -------------------------

    def _traverse(self) -> None:
        for key in sorted(self.functions):
            self._visit(self.functions[key], frozenset(), frozenset(),
                        (), set(), 0)

    def _visit(self, fn: FunctionInfo, held: frozenset,
               excused: frozenset, chain: tuple, visited: set,
               depth: int) -> None:
        """``held`` is the factual held set (feeds the ORDERING graph —
        an allow marker cannot erase an acquisition order); ``excused``
        is the subset an allow-blocking-under-lock call-site marker
        accepted, subtracted only from BLOCKING reports."""
        state = (fn.key, held, excused)
        if state in visited or depth > 40:
            return
        visited.add(state)
        path = fn.module.relpath
        for region in fn.regions:
            if region.lock is None:
                continue
            outer = held | set(region.outer)
            step = (f"{path}:{region.line} {fn.name} acquires "
                    f"{region.lock} (with {region.rendered})")
            for lock in sorted(outer):
                if lock.startswith("?"):
                    continue
                edge = (lock, region.lock)
                if edge not in self.lock_edges:
                    self.lock_edges[edge] = chain + (step,)
        for site in fn.blocking:
            total = (held | set(site.held)) - excused
            if site.allowed:
                # the allow excuses ONLY the locks visible at the marked
                # line — a lock smuggled in by a caller still reports,
                # so an audited leaf can never silently cover new
                # callers (fix or mark the caller instead)
                total = total - set(site.held)
            if not total:
                continue
            sid = (fn.key, site.line, site.rendered)
            if sid not in self.blocked:
                step = (f"{path}:{site.line} {fn.name} calls "
                        f"{site.rendered} [{site.cls}]")
                self.blocked[sid] = (site, fn, chain + (step,),
                                     tuple(sorted(total)))
        for call in fn.calls:
            callee = self.functions.get(call.callee)
            if callee is None:
                continue
            nxt = held | set(call.held)
            nxt_excused = excused
            if call.allowed:
                # same lexical-only contract as sites, applied to the
                # whole callee subtree (the sink-serializer idiom: the
                # serializing lock is held around a helper whose entire
                # JOB is the I/O it guards)
                nxt_excused = excused | set(call.held)
            step = (f"{path}:{call.line} {fn.name} calls "
                    f"{callee.name}")
            self._visit(callee, frozenset(nxt), frozenset(nxt_excused),
                        chain + (step,), visited, depth + 1)

    # -- entry-held fixpoint --------------------------------------------------

    def _fixpoint_entry_held(self) -> dict:
        callers: dict[str, list] = {}
        for fn in self.functions.values():
            for call in fn.calls:
                if call.callee in self.functions:
                    callers.setdefault(call.callee, []).append(
                        (fn.key, frozenset(
                            h for h in call.held if not h.startswith("?"))))
        all_locks = frozenset(self.root_lock(l) for l in self.locks)
        # the optimistic all-locks seed is only sound for functions
        # REACHABLE from a zero-caller root: a closed caller-cycle
        # (mutually-recursive helpers with no outside entry) never
        # intersects against a root path and would converge to "all
        # locks held at entry" — disarming guarded-state exactly where
        # nothing is proven.  Unreachable functions stay at the
        # conservative empty set.
        roots = [k for k in self.functions if k not in callers]
        reachable = set(roots)
        stack = list(roots)
        while stack:
            k = stack.pop()
            for call in self.functions[k].calls:
                if call.callee in self.functions and \
                        call.callee not in reachable:
                    reachable.add(call.callee)
                    stack.append(call.callee)
        held = {key: (all_locks if key in callers and key in reachable
                      else frozenset())
                for key in self.functions}
        changed = True
        while changed:
            changed = False
            for key in self.functions:
                if key not in reachable:
                    continue  # frozen at the conservative empty set
                sites = callers.get(key)
                if not sites:
                    continue
                new = None
                for caller_key, lex in sites:
                    s = lex | held.get(caller_key, frozenset())
                    new = s if new is None else (new & s)
                new = new or frozenset()
                if new != held[key]:
                    held[key] = new
                    changed = True
        return held

    def entry_held(self, fn_key: str) -> frozenset:
        return self._entry_held.get(fn_key, frozenset())


# -- the machine-readable lock graph (artifacts/lock_graph.json) -------------

def render_lock_graph(index: ProgramIndex) -> dict:
    """JSON-able, deterministic, checkout-location-independent: lock
    ids and paths are project-relative, orderings are sorted, and the
    representative chains come from the sorted deterministic traversal
    — regenerating on an unchanged tree is byte-stable."""
    locks = []
    for lid in sorted(index.locks):
        ld = index.locks[lid]
        locks.append({
            "id": ld.id,
            "kind": ld.kind,
            "path": ld.path,
            "line": ld.line,
            "alias_of": ld.alias_of,
        })
    edges = []
    for (a, b) in sorted(index.lock_edges):
        edges.append({
            "from": a,
            "to": b,
            "chain": list(index.lock_edges[(a, b)]),
        })
    return {
        "version": 1,
        "generator": "python -m dat_replication_protocol_tpu.analysis "
                     "--lock-graph",
        "locks": locks,
        "edges": edges,
    }
