"""Whole-program concurrency analysis (ISSUE 13).

datlint's original rules are single-file AST passes; the bug classes
the review rounds kept catching by hand — callback-under-lock in a
dispatcher, a blocking write inside a held region, shared state written
from a thread that skipped the lock — are *cross-file* properties of
the thread web.  This package is the whole-program infrastructure that
checks them mechanically before the event-loop refactor (ROADMAP
item 2) rebuilds that web:

* :mod:`.model` builds ONE shared :class:`~.model.ProgramIndex` per
  analysis run: every ``threading.Lock/RLock/Condition`` creation site
  gets a stable identity (``hub/engine.py::ReplicationHub._lock``),
  ``with lock:`` regions are resolved against those identities
  (conditions alias the lock they wrap, local aliases like
  ``lock = self._ack_lock`` follow), and an interprocedural call graph
  propagates held-lock sets through direct calls — so a helper only
  ever called under the hub lock is *known* to run locked.
* :mod:`.lockorder` reports lock-order inversions (cycles in the
  acquired-while-held graph) with both acquisition chains cited, and
  re-acquisition of a non-reentrant lock (RLock re-entry is a
  non-finding by construction).
* :mod:`.blocking` reports blocking calls — socket send/recv,
  ``os.write``/``writev``, ``time.sleep``, ``subprocess``, file I/O,
  and user-callback invocation — made while any lock is held, directly
  or through the call graph, with the holding chain cited.  Escape:
  ``# datlint: allow-blocking-under-lock`` (optionally class-scoped,
  ``allow-blocking-under-lock(file-io)``) next to a written
  justification.
* :mod:`.guarded` enforces ``# datlint: guarded-by(lock): fields``
  declarations (the coupled-state declaration syntax, extended):
  writes to a declared field outside its guarding lock — lexically or
  via the entry-held call-graph closure — are findings, and a
  declaration the rule cannot honor is itself a LOUD finding (the
  cursor-coherence lesson: a linter guarding silent corruption must
  never silently disarm).

* :mod:`.readiness` (ISSUE 16) lifts the same index one level up: an
  interprocedural may-block summary pass (``nonblocking`` /
  ``bounded-blocking`` / ``unbounded-blocking``) feeding two enforced
  rules — :class:`~.readiness.BlockingReachability` (no unbounded
  blocking reachable from a certified dispatch loop) and
  :class:`~.readiness.CallbackEscape` (no user callback on a
  dispatcher thread) — plus the per-entry-point certificate
  ``artifacts/event_loop_surface.json``.

The machine-readable lock-acquisition graph is exported as
``artifacts/lock_graph.json``, and the event-loop readiness
certificate as ``artifacts/event_loop_surface.json`` (both via
``python -m dat_replication_protocol_tpu.analysis --write-artifacts
DIR``) so the item-2 refactor can diff the thread web it inherits.
Rules and incidents: ANALYSIS.md "Concurrency rules".
"""

from __future__ import annotations

from .blocking import BlockingUnderLock
from .guarded import GuardedState
from .lockorder import LockOrder
from .model import ProgramIndex, render_lock_graph
from .readiness import BlockingReachability, CallbackEscape, \
    ReadinessIndex, render_event_loop_surface

__all__ = [
    "BlockingReachability",
    "BlockingUnderLock",
    "CallbackEscape",
    "GuardedState",
    "LockOrder",
    "ProgramIndex",
    "ReadinessIndex",
    "render_event_loop_surface",
    "render_lock_graph",
]
