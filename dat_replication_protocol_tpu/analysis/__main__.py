"""CLI: ``python -m dat_replication_protocol_tpu.analysis [paths...]``.

Exits 0 when clean, 1 on findings, 2 on usage errors — shaped so the
tier-1 suite (tests/test_datlint_repo_clean.py) and any pre-merge hook
can gate on it directly.

Structured surfaces (ISSUE 13 + 16 satellites):

* ``--format json|sarif`` — machine-readable output.  ``json`` is one
  document with ``findings`` (each ``{rule, path, line, message,
  chains}``), counts, and (with ``--stats``) per-rule wall seconds;
  ``--json`` remains as an alias for ``--format json``.  ``sarif`` is
  SARIF 2.1.0 (one run, one result per new finding, evidence chains
  under ``properties.chains``) for CI surfaces that ingest SARIF
  natively.
* ``--baseline FILE`` — accept-list: findings whose stable key (rule +
  trailing path + first message sentence, no line numbers) appears in
  FILE are reported as ``accepted`` and do not fail the run; only NEW
  findings exit 1.  ``--write-baseline FILE`` records the current
  findings as that accept-list.
* ``--stats`` — per-rule wall time (the tier-1 budget gate's input:
  a whole-program pass must not blow the suite's runtime budget).
* ``--lock-graph PATH`` — write the machine-readable lock-acquisition
  graph (deterministic, byte-stable on an unchanged tree) so the
  event-loop refactor (ROADMAP item 2) can diff the thread web it
  inherits; ``artifacts/lock_graph.json`` is the checked-in copy.
* ``--write-artifacts DIR`` — regenerate EVERY checked-in analysis
  artifact (``lock_graph.json`` + ``event_loop_surface.json``) into
  DIR, byte-stably: sorted keys, fixed indent, no timestamps, paths
  project-relative.  The tier-1 suite asserts the ``artifacts/``
  copies match a fresh regeneration, so "regenerate on change" is
  enforced, not aspirational.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .engine import Project, run_project
from .rules import ALL_RULES, rule_by_name


def write_lock_graph(project: Project, out_path: str | Path) -> dict:
    """Render and write the lock graph for ``project``; returns the
    document.  Sorted keys + fixed indent + trailing newline: the
    bytes are a pure function of the analyzed tree."""
    from .concurrency import ProgramIndex, render_lock_graph

    doc = render_lock_graph(ProgramIndex.get(project))
    text = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    Path(out_path).write_text(text, encoding="utf-8")
    return doc


def write_event_loop_surface(project: Project,
                             out_path: str | Path) -> dict:
    """Render and write the event-loop readiness certificate (ISSUE
    16); same byte-stability contract as :func:`write_lock_graph`."""
    from .concurrency import ReadinessIndex, render_event_loop_surface

    doc = render_event_loop_surface(ReadinessIndex.get(project))
    text = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    Path(out_path).write_text(text, encoding="utf-8")
    return doc


def write_artifacts(project: Project, out_dir: str | Path) -> list:
    """Regenerate every checked-in analysis artifact into ``out_dir``;
    returns the written paths (sorted)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    write_lock_graph(project, out_dir / "lock_graph.json")
    write_event_loop_surface(project,
                             out_dir / "event_loop_surface.json")
    return sorted([out_dir / "event_loop_surface.json",
                   out_dir / "lock_graph.json"])


def to_sarif(new: list, accepted: list, rules, n_files: int) -> dict:
    """SARIF 2.1.0: one run; baseline-accepted findings are carried as
    suppressed results (SARIF's native accept-list shape) so ingesting
    CI sees them without failing on them."""
    def result(f, suppressed: bool) -> dict:
        r = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line},
                },
            }],
            "properties": {"chains": [list(c) for c in f.chains]},
        }
        if suppressed:
            r["suppressions"] = [{"kind": "external",
                                  "justification": "baseline accept-list"}]
        return r

    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "datlint",
                "informationUri":
                    "https://github.com/mafintosh/dat-replication-protocol",
                "rules": [{"id": r.name,
                           "shortDescription": {"text": r.description}}
                          for r in rules],
            }},
            "results": [result(f, False) for f in new]
            + [result(f, True) for f in accepted],
            "properties": {"files": n_files},
        }],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dat_replication_protocol_tpu.analysis",
        description="datlint: protocol-invariant static analysis "
                    "(rules and incidents: ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to analyze (default: this package)")
    parser.add_argument(
        "--rule", action="append", default=None, metavar="NAME",
        help="run only this rule (repeatable)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule names and one-line descriptions, then exit")
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default=None,
        help="output format (default text); sarif is SARIF 2.1.0")
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="alias for --format json (kept for ISSUE-13 callers)")
    parser.add_argument(
        "--baseline", metavar="FILE",
        help="accept-list of known findings (see --write-baseline); "
             "only findings NOT in it fail the run")
    parser.add_argument(
        "--write-baseline", metavar="FILE",
        help="write the current findings' keys as a baseline "
             "accept-list, then exit 0")
    parser.add_argument(
        "--stats", action="store_true",
        help="report per-rule wall time")
    parser.add_argument(
        "--lock-graph", metavar="PATH",
        help="also write the machine-readable lock-acquisition graph "
             "(artifacts/lock_graph.json is the checked-in copy)")
    parser.add_argument(
        "--write-artifacts", metavar="DIR",
        help="regenerate every checked-in analysis artifact "
             "(lock_graph.json + event_loop_surface.json) into DIR, "
             "byte-stably")
    args = parser.parse_args(argv)
    if args.format is None:
        args.format = "json" if args.as_json else "text"
    elif args.as_json and args.format != "json":
        print("datlint: --json contradicts --format "
              f"{args.format}", file=sys.stderr)
        return 2
    args.as_json = args.format == "json"

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name}: {rule.description}")
        return 0

    rules = ALL_RULES
    if args.rule:
        try:
            rules = [rule_by_name(name) for name in args.rule]
        except KeyError as e:
            print(f"datlint: unknown rule {e.args[0]!r} "
                  f"(--list-rules shows the registry)", file=sys.stderr)
            return 2

    paths = args.paths or [Path(__file__).resolve().parent.parent]
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"datlint: no such path: {', '.join(map(str, missing))}",
              file=sys.stderr)
        return 2

    baseline: set[str] = set()
    if args.baseline:
        try:
            doc = json.loads(Path(args.baseline).read_text("utf-8"))
            baseline = set(doc["accept"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            # a broken baseline must fail LOUDLY: silently accepting
            # nothing (or everything) would flip the gate's meaning
            print(f"datlint: unreadable baseline {args.baseline!r}: {e}",
                  file=sys.stderr)
            return 2

    project = Project.from_paths(paths)
    stats: dict = {}
    findings = run_project(project, rules, stats if args.stats else None)
    if args.lock_graph:
        write_lock_graph(project, args.lock_graph)
    if args.write_artifacts:
        write_artifacts(project, args.write_artifacts)

    n_files = len(project.sources)

    def print_stats() -> None:
        total = sum(stats.values())
        for name, secs in sorted(stats.items(), key=lambda kv: -kv[1]):
            print(f"datlint: stats: {name}: {secs * 1e3:.1f} ms")
        print(f"datlint: stats: TOTAL: {total * 1e3:.1f} ms "
              f"({n_files} files)")

    if args.write_baseline:
        doc = {"version": 1,
               "accept": sorted({f.key() for f in findings})}
        Path(args.write_baseline).write_text(
            json.dumps(doc, indent=2) + "\n", encoding="utf-8")
        if args.as_json:
            # --json callers parse stdout as ONE document on every
            # invocation, the baseline-refresh run included
            out = {"version": 1, "files": n_files,
                   "wrote_baseline": args.write_baseline,
                   "accepted_keys": len(doc["accept"])}
            if args.stats:
                out["stats_s"] = {k: round(v, 4)
                                  for k, v in sorted(stats.items())}
            print(json.dumps(out, indent=2))
            return 0
        if args.stats:
            print_stats()
        print(f"datlint: wrote {len(doc['accept'])} accepted key(s) to "
              f"{args.write_baseline}")
        return 0

    new = [f for f in findings if f.key() not in baseline]
    accepted = [f for f in findings if f.key() in baseline]

    if args.format == "sarif":
        print(json.dumps(to_sarif(new, accepted, rules, n_files),
                         indent=2))
        return 1 if new else 0

    if args.as_json:
        doc = {
            "version": 1,
            "files": n_files,
            "rules": [r.name for r in rules],
            "findings": [f.to_json() for f in new],
            "accepted": [f.to_json() for f in accepted],
        }
        if args.stats:
            doc["stats_s"] = {k: round(v, 4)
                              for k, v in sorted(stats.items())}
        print(json.dumps(doc, indent=2))
        return 1 if new else 0

    for f in new:
        print(f.render())
    if args.stats:
        print_stats()
    if accepted:
        print(f"datlint: {len(accepted)} baseline-accepted finding(s) "
              f"not shown")
    if new:
        print(f"datlint: {len(new)} finding(s) in {n_files} file(s)")
        return 1
    print(f"datlint: clean ({n_files} files, {len(rules)} rules)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
