"""CLI: ``python -m dat_replication_protocol_tpu.analysis [paths...]``.

Exits 0 when clean, 1 on findings, 2 on usage errors — shaped so the
tier-1 suite (tests/test_datlint_repo_clean.py) and any pre-merge hook
can gate on it directly.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .engine import Project, run_project
from .rules import ALL_RULES, rule_by_name


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dat_replication_protocol_tpu.analysis",
        description="datlint: protocol-invariant static analysis "
                    "(rules and incidents: ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to analyze (default: this package)")
    parser.add_argument(
        "--rule", action="append", default=None, metavar="NAME",
        help="run only this rule (repeatable)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule names and one-line descriptions, then exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name}: {rule.description}")
        return 0

    rules = ALL_RULES
    if args.rule:
        try:
            rules = [rule_by_name(name) for name in args.rule]
        except KeyError as e:
            print(f"datlint: unknown rule {e.args[0]!r} "
                  f"(--list-rules shows the registry)", file=sys.stderr)
            return 2

    paths = args.paths or [Path(__file__).resolve().parent.parent]
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"datlint: no such path: {', '.join(map(str, missing))}",
              file=sys.stderr)
        return 2

    project = Project.from_paths(paths)
    findings = run_project(project, rules)
    for f in findings:
        print(f.render())
    n_files = len(project.sources)
    if findings:
        print(f"datlint: {len(findings)} finding(s) in {n_files} file(s)")
        return 1
    print(f"datlint: clean ({n_files} files, {len(rules)} rules)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
