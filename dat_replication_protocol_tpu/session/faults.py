"""Deterministic fault injection for session transports — the chaos harness.

The reference's only failure semantics is "destroy the stream"
(reference: decode.js:104-110, encode.js:69-75); reproducing and then
*surviving* transport faults needs a way to manufacture them on demand,
repeatably.  This module wraps the byte-level transport contract both
pump families speak — the threaded pumps' ``read_bytes(n) -> bytes`` /
``write_bytes(data)`` callables (:mod:`.transport`) and the asyncio
pumps' ``await reader.read(n)`` (:mod:`.aio`) — with a seed-driven
:class:`FaultPlan` that can:

* **re-segment**: deliver reads in arbitrary-size pieces (down to one
  byte), exercising every header/payload straddle the parser has;
* **truncate**: fake a clean EOF mid-stream (the silent-truncation
  fault — indistinguishable in-band from a finished session, which is
  exactly why the resume layer checks the sender's declared length,
  see ROBUSTNESS.md);
* **drop**: raise :class:`TransportFault` once a chosen byte offset has
  been delivered (the mid-session disconnect);
* **flip**: XOR one byte at a chosen offset (wire corruption; a flipped
  *header* byte surfaces as a structured ProtocolError, a flipped
  *payload* byte is undetectable at the wire layer by design — the
  digest pipeline is the end-to-end integrity answer);
* **stall / latency**: inject one long pause at a chosen offset and/or
  small per-read delays, exercising every bounded-wait path.

Everything is derived from ``random.Random(seed)``: the same plan over
the same bytes produces the same faults, so a failing seed is a
reproducer, not a flake.  :meth:`FaultPlan.for_sweep` is the shared
scenario generator the conformance sweep (tests/test_session_faults.py)
and future robustness work key off.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional

from ..obs.events import emit as _emit
from ..obs.flight import FLIGHT as _FLIGHT
from ..obs.metrics import OBS as _OBS, counter as _counter

# Ground-truth telemetry: the injector records every fault it actually
# fires, so the conformance sweep (tests/test_obs_conformance.py) can
# assert the session layers' own metrics/events agree with what chaos
# really did — the oracle side of the contract (OBSERVABILITY.md).
_M_INJ_DROP = _counter("fault.injected.drop")
_M_INJ_TRUNCATE = _counter("fault.injected.truncate")
_M_INJ_FLIP = _counter("fault.injected.flip")
_M_INJ_STALL = _counter("fault.injected.stall")
_M_INJ_RESEG = _counter("fault.injected.reseg_segments")

__all__ = [
    "TransportFault",
    "FaultPlan",
    "FaultyReader",
    "FaultyWriter",
    "AsyncFaultyReader",
    "bytes_reader",
]


class TransportFault(ConnectionError):
    """An injected (or detected) connection-level failure.

    Distinct from :class:`~..wire.framing.ProtocolError`: a transport
    fault says nothing about the bytes that *did* arrive — the session
    is resumable from the receiver's checkpoint.  ``offset`` is the
    number of bytes this connection delivered before dying.
    """

    def __init__(self, message: str, *, offset: int | None = None):
        super().__init__(message)
        self.offset = offset


@dataclasses.dataclass
class FaultPlan:
    """What one connection will do to the bytes passing through it.

    All offsets are relative to this connection's first delivered byte
    (a resumed connection starts its own plan at 0).  ``None`` disables
    a fault.  The plan is pure data — the wrapper classes below own the
    clock and the randomness (seeded from ``seed``).
    """

    seed: int = 0
    max_segment: Optional[int] = None    # re-segment reads into [1, max_segment]
    drop_at: Optional[int] = None        # raise TransportFault at this offset
    truncate_at: Optional[int] = None    # fake clean EOF at this offset
    flip_at: Optional[int] = None        # XOR one byte at this offset
    flip_mask: int = 0xFF                # never 0 (a 0-mask flips nothing)
    stall_at: Optional[int] = None       # one long pause before this offset
    stall_s: float = 0.0
    latency_prob: float = 0.0            # per-read chance of a small sleep
    latency_s: float = 0.0

    # the disconnect-class scenarios: faults a correct resume layer must
    # absorb without changing the decoded session (corruption is a
    # different class — it must ERROR, and gets targeted tests)
    SWEEP_SCENARIOS = ("drop", "truncate", "stall", "reseg")
    # the multi-session (hub) scenario axis: what the ONE faulty
    # co-resident session does while its neighbors stay healthy.  Flip
    # joins here — isolation must hold even when the faulty session's
    # wire is corrupt (it errors or delivers corrupt content; the
    # neighbors must not care either way), which the 1:1 resume sweep
    # deliberately excludes (flip is not resumable by design).
    SESSION_SCENARIOS = ("stall", "truncate", "flip")
    # the cluster (gossip-mesh) link axis (ISSUE 15): what one sampled
    # gossip link does to ONE exchange, on top of the scheduled
    # partition.  "clean" is deliberately over-weighted — most links in
    # a round behave — and every fault class the 1:1 and per-session
    # axes know reappears here so the convergence contract is proven
    # against the same chaos vocabulary.
    LINK_SCENARIOS = ("clean", "clean", "clean", "reseg", "drop",
                      "stall", "flip")

    @classmethod
    def partition_scenario(cls, seed: int, n_replicas: int) -> dict:
        """Deterministic cluster-partition ground truth for
        ``(seed, n_replicas)`` — the link-set cut the gossip sweep and
        its oracle both key off (mirrors the PR 8 per-session axis:
        the generator IS the ground truth, so tests never guess).

        Returns ``{"groups": (frozenset, frozenset), "cut_round": c,
        "heal_round": h}``: from gossip round ``c`` (inclusive) to
        ``h`` (exclusive) every link crossing the two groups is dead
        (an immediate drop); at ``h`` the cut heals and convergence
        must complete within the sweep's bounded rounds.  The two
        groups partition ``range(n_replicas)``; with fewer than two
        replicas there is nothing to cut and the minority group is
        empty.
        """
        rng = random.Random(seed * 2_654_435_761 + n_replicas)
        cut = rng.randrange(1, 4)
        heal = cut + rng.randrange(2, 6)
        idx = list(range(n_replicas))
        rng.shuffle(idx)
        k = rng.randrange(1, n_replicas) if n_replicas > 1 else 0
        return {
            "groups": (frozenset(idx[:k]), frozenset(idx[k:])),
            "cut_round": cut,
            "heal_round": heal,
        }

    @classmethod
    def partitioned(cls, seed: int, n_replicas: int,
                    link: tuple[int, int], gossip_round: int) -> bool:
        """Whether ``link`` (a replica-index pair) crosses the seeded
        cut during ``gossip_round`` — the oracle-side view of the
        partition axis."""
        sc = cls.partition_scenario(seed, n_replicas)
        if not sc["cut_round"] <= gossip_round < sc["heal_round"]:
            return False
        a, b = link
        minority = sc["groups"][0]
        return (a in minority) != (b in minority)

    @classmethod
    def link_scenario(cls, seed: int, n_replicas: int,
                      link: tuple[int, int]) -> tuple[str, int]:
        """The (scenario, fire_round) ground truth for one undirected
        gossip link: which :data:`LINK_SCENARIOS` arm the link draws
        and the single gossip round it fires in.  Deterministic, so
        the chaos oracle can predict exactly which exchanges were
        corrupted vs merely dropped."""
        a, b = sorted(link)
        rng = random.Random(
            (seed * 5_851 + n_replicas) * 1_000_003 + a * 8_191 + b)
        return rng.choice(cls.LINK_SCENARIOS), rng.randrange(1, 8)

    @classmethod
    def faulty_session(cls, seed: int, n_sessions: int) -> int:
        """Which session index carries the fault for this seed —
        deterministic, so the chaos oracle can predict ground truth."""
        return random.Random(seed * 7_368_787 + n_sessions).randrange(
            max(1, n_sessions))

    @classmethod
    def for_sweep(cls, seed: int, wire_len: int, attempt: int = 0,
                  session: int = 0, n_sessions: int = 1,
                  link: Optional[tuple] = None, n_replicas: int = 1,
                  gossip_round: int = 0) -> "FaultPlan":
        """The conformance-sweep scenario for ``(seed, attempt)``.

        Attempt 0 carries the seed's primary fault, attempt 1 has a 50%
        chance of a second fault (a reconnect that dies too), attempts
        >= 2 are clean apart from aggressive re-segmentation — so every
        seed converges within a bounded number of reconnects while still
        exercising double faults.  Deterministic: same (seed, attempt,
        wire_len) -> same plan.

        **Per-session axis** (ISSUE 8): with ``n_sessions > 1`` this is
        the shared generator for N concurrent plans, one keyed per
        ``session`` index.  Exactly one session — :meth:`faulty_session`
        — draws its primary fault from :data:`SESSION_SCENARIOS`
        (stall / truncate / flip); every other session gets a benign
        plan (re-segmentation and small latency only), so hub chaos
        tests and future fan-out tests can assert the isolation
        contract against known ground truth.  The default
        ``(session=0, n_sessions=1)`` path is byte-identical to the
        pre-axis generator — existing sweeps reproduce unchanged.

        **Partition/link axis** (ISSUE 15): with ``link=(a, b)`` and
        ``n_replicas > 1`` this is the shared generator for a gossip
        mesh's per-exchange plans.  A link crossing the seeded
        partition cut (:meth:`partition_scenario`) during
        ``gossip_round`` is dead — an immediate drop, healing at the
        scenario's ``heal_round``; every other link draws its one
        scenario from :data:`LINK_SCENARIOS` at a seeded round
        (:meth:`link_scenario`) and is otherwise benign delivery
        jitter.  The default ``(link=None, n_replicas=1)`` path is
        byte-identical to the pre-axis generator (golden test).
        """
        if link is not None and n_replicas > 1:
            return cls._for_cluster_sweep(seed, wire_len, link,
                                          n_replicas, gossip_round)
        if n_sessions > 1:
            return cls._for_session_sweep(seed, wire_len, attempt,
                                          session, n_sessions)
        rng = random.Random(seed * 1_000_003 + attempt)
        span = max(1, wire_len)
        plan = cls(
            seed=rng.randrange(1 << 30),
            max_segment=rng.choice([1, 3, 7, 64, 1024, None]),
            latency_prob=rng.choice([0.0, 0.0, 0.05]),
            latency_s=0.001,
        )
        if attempt >= 2 or (attempt == 1 and rng.random() < 0.5):
            return plan
        scenario = rng.choice(cls.SWEEP_SCENARIOS)
        at = rng.randrange(span)
        if scenario == "drop":
            plan.drop_at = at
        elif scenario == "truncate":
            plan.truncate_at = at
        elif scenario == "stall":
            plan.stall_at = at
            plan.stall_s = 0.02
        # "reseg": byte-at-a-time delivery IS the fault
        if scenario == "reseg":
            plan.max_segment = 1
        return plan

    @classmethod
    def session_scenario(cls, seed: int, n_sessions: int) -> str:
        """The faulty session's scenario for this (seed, n_sessions) —
        exposed so the oracle can check telemetry against ground truth."""
        rng = random.Random(seed * 2_246_822_519 + n_sessions)
        return rng.choice(cls.SESSION_SCENARIOS)

    @classmethod
    def _for_session_sweep(cls, seed: int, wire_len: int, attempt: int,
                           session: int, n_sessions: int) -> "FaultPlan":
        rng = random.Random((seed * 1_000_003 + attempt) * 1_789 + session)
        span = max(1, wire_len)
        plan = cls(
            seed=rng.randrange(1 << 30),
            max_segment=rng.choice([3, 7, 64, 1024, None]),
            latency_prob=rng.choice([0.0, 0.0, 0.05]),
            latency_s=0.0005,
        )
        if session != cls.faulty_session(seed, n_sessions):
            return plan  # healthy co-resident: benign delivery jitter only
        if attempt >= 1:
            return plan  # the faulty session's reconnect runs clean
        scenario = cls.session_scenario(seed, n_sessions)
        at = rng.randrange(span)
        if scenario == "truncate":
            plan.truncate_at = at
        elif scenario == "stall":
            plan.stall_at = at
            plan.stall_s = 0.05
        elif scenario == "flip":
            plan.flip_at = at
            plan.flip_mask = rng.choice([0x01, 0x40, 0x80])
        return plan

    @classmethod
    def _for_cluster_sweep(cls, seed: int, wire_len: int,
                           link: tuple, n_replicas: int,
                           gossip_round: int) -> "FaultPlan":
        # the link is ORDERED (sender -> receiver): the two directions
        # of one exchange draw distinct jitter and fault coordinates,
        # while the scheduled scenario and the partition cut are
        # properties of the UNDIRECTED pair (sorted inside the
        # scenario lookups) — one link, one story, two wires
        a, b = link
        rng = random.Random(
            ((seed * 5_851 + n_replicas) * 1_000_003 + a * 8_191 + b)
            * 131 + gossip_round)
        span = max(1, wire_len)
        # gossip exchanges are many and small: segments never drop to
        # byte-at-a-time (that is the 1:1 sweep's job) and latency is
        # token, so a 64-replica sweep stays inside the tier-1 budget
        plan = cls(
            seed=rng.randrange(1 << 30),
            max_segment=rng.choice([64, 256, 1024, None]),
            latency_prob=rng.choice([0.0, 0.0, 0.02]),
            latency_s=0.0002,
        )
        if cls.partitioned(seed, n_replicas, (a, b), gossip_round):
            plan.drop_at = 0  # the cut: the dial itself fails
            return plan
        scenario, fire_round = cls.link_scenario(seed, n_replicas, (a, b))
        if gossip_round != fire_round or scenario == "clean":
            return plan
        at = rng.randrange(span)
        if scenario == "drop":
            plan.drop_at = at
        elif scenario == "stall":
            plan.stall_at = at
            plan.stall_s = 0.01
        elif scenario == "flip":
            plan.flip_at = at
            plan.flip_mask = rng.choice([0x01, 0x40, 0x80])
        elif scenario == "reseg":
            plan.max_segment = 64
        return plan


class _FaultState:
    """Plan execution shared by the sync and async wrappers: decides the
    next segment size (or EOF / fault), applies the byte flip, and keeps
    the delivered-byte offset — everything except the actual pull and
    the actual sleep, which differ between the thread and event-loop
    worlds."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.offset = 0  # bytes delivered downstream on THIS connection
        self._rng = random.Random(plan.seed)
        self._stalled = False
        self._dead = False
        self._truncated = False
        # chaos ground truth rides in every post-mortem bundle: an armed
        # flight recorder notes the plan (seed + fault coordinates) the
        # moment a faulty connection comes up (no-op while disarmed)
        _FLIGHT.note_plan(plan)

    def pre_read(self, n: int) -> tuple[Optional[int], float]:
        """(segment limit, sleep seconds) for the next read; limit None
        means injected clean EOF.  Raises on an injected drop."""
        p = self.plan
        if self._dead:
            raise TransportFault(
                f"connection already dropped at byte {self.offset}",
                offset=self.offset)
        if p.drop_at is not None and self.offset >= p.drop_at:
            self._dead = True
            if _OBS.on:
                _M_INJ_DROP.inc()
                _emit("fault.drop", offset=self.offset)
            raise TransportFault(
                f"injected disconnect at byte {self.offset}",
                offset=self.offset)
        if p.truncate_at is not None and self.offset >= p.truncate_at:
            if not self._truncated:
                self._truncated = True
                if _OBS.on:
                    _M_INJ_TRUNCATE.inc()
                    _emit("fault.truncate", offset=self.offset)
            return None, 0.0
        limit = max(1, n)
        if p.max_segment:
            limit = self._rng.randint(1, max(1, min(limit, p.max_segment)))
            if _OBS.on:
                _M_INJ_RESEG.inc()
        if p.drop_at is not None:
            limit = min(limit, p.drop_at - self.offset)
        if p.truncate_at is not None:
            limit = min(limit, p.truncate_at - self.offset)
        sleep_s = 0.0
        if (p.stall_at is not None and not self._stalled
                and self.offset >= p.stall_at):
            self._stalled = True
            if _OBS.on:
                _M_INJ_STALL.inc()
                _emit("fault.stall", offset=self.offset, seconds=p.stall_s)
            sleep_s += p.stall_s
        if p.latency_prob and self._rng.random() < p.latency_prob:
            sleep_s += p.latency_s
        return limit, sleep_s

    def deliver(self, chunk: bytes) -> bytes:
        """Apply the byte flip (if it lands in this chunk) and advance."""
        p = self.plan
        if (p.flip_at is not None
                and self.offset <= p.flip_at < self.offset + len(chunk)):
            i = p.flip_at - self.offset
            mask = p.flip_mask or 0xFF
            chunk = chunk[:i] + bytes((chunk[i] ^ mask,)) + chunk[i + 1:]
            if _OBS.on:
                _M_INJ_FLIP.inc()
                _emit("fault.flip", offset=p.flip_at, mask=mask)
        self.offset += len(chunk)
        return chunk


class FaultyReader:
    """Pull-side wrapper for the threaded transport contract.

    ``read(n)`` returns up to ``n`` bytes, ``b''`` at (real or injected)
    EOF, and raises :class:`TransportFault` on an injected drop —
    exactly the ``read_bytes`` shape :func:`.transport.recv_over` and
    the reconnect driver consume.
    """

    def __init__(self, read_bytes: Callable[[int], bytes], plan: FaultPlan,
                 sleep: Callable[[float], None] = time.sleep):
        self._read = read_bytes
        self._state = _FaultState(plan)
        self._sleep = sleep
        self._pending = bytearray()  # pulled upstream, not yet delivered

    @property
    def offset(self) -> int:
        return self._state.offset

    def read(self, n: int) -> bytes:
        limit, sleep_s = self._state.pre_read(n)
        if sleep_s:
            self._sleep(sleep_s)
        if limit is None:
            return b""  # injected truncation: a clean-looking EOF
        while not self._pending:
            data = self._read(n)
            if not data:
                return b""  # upstream EOF
            self._pending += data
        take = min(limit, len(self._pending))
        out = bytes(self._pending[:take])
        del self._pending[:take]
        return self._state.deliver(out)


class AsyncFaultyReader:
    """The asyncio twin of :class:`FaultyReader`: wraps any object with
    ``async read(n)`` (e.g. an ``asyncio.StreamReader``); byte-for-byte
    identical fault behavior for the same plan."""

    def __init__(self, reader, plan: FaultPlan):
        self._reader = reader
        self._state = _FaultState(plan)
        self._pending = bytearray()

    @property
    def offset(self) -> int:
        return self._state.offset

    async def read(self, n: int) -> bytes:
        import asyncio

        limit, sleep_s = self._state.pre_read(n)
        if sleep_s:
            await asyncio.sleep(sleep_s)
        if limit is None:
            return b""
        while not self._pending:
            data = await self._reader.read(n)
            if not data:
                return b""
            self._pending += data
        take = min(limit, len(self._pending))
        out = bytes(self._pending[:take])
        del self._pending[:take]
        return self._state.deliver(out)


class FaultyWriter:
    """Push-side wrapper: re-segments, delays, flips, and drops writes.

    Wraps a ``write_bytes(data)`` callable (the :func:`.transport.send_over`
    sink).  A drop surfaces as :class:`TransportFault` from ``write``,
    which the sending pump treats like any transport error.
    """

    def __init__(self, write_bytes: Callable[[bytes], None], plan: FaultPlan,
                 sleep: Callable[[float], None] = time.sleep):
        self._write = write_bytes
        self._state = _FaultState(plan)
        self._sleep = sleep

    @property
    def offset(self) -> int:
        return self._state.offset

    def write(self, data) -> None:
        view = memoryview(data)
        while len(view):
            limit, sleep_s = self._state.pre_read(len(view))
            if sleep_s:
                self._sleep(sleep_s)
            if limit is None:
                return  # truncated: silently swallow the tail
            chunk = self._state.deliver(bytes(view[:limit]))
            self._write(chunk)
            view = view[limit:]


def bytes_reader(data: bytes) -> Callable[[int], bytes]:
    """A ``read_bytes``-shaped source over an in-memory byte string —
    the journal-replay / test-harness building block."""
    view = memoryview(data)
    pos = [0]

    def read(n: int) -> bytes:
        i = pos[0]
        j = min(len(view), i + max(1, n))
        pos[0] = j
        return bytes(view[i:j])

    return read
