"""Byte-transport adapters: run a session over real OS byte streams.

The reference's L0 is *any* Node stream — its two ends meet a TCP socket,
a pipe, or a file equally well via ``encode.pipe(socket)`` /
``socket.pipe(decode)`` (reference: example.js:53), with backpressure
propagating end-to-end through the stream machinery
(reference: decode.js:87-99,168 -> Writable cb withheld -> pipe pauses ->
encode.js:139-151 drain).  This module is the Python analogue for the
pull-based Encoder / push-based Decoder: blocking pump loops that move
wire bytes across a socket or file descriptor while honoring both sides'
flow control.

How backpressure crosses the OS boundary:

* **Sender**: :func:`send_over` pulls from :meth:`Encoder.read` and writes
  to the transport.  A full kernel send buffer blocks the write, which
  stops the pull, which leaves the encoder's queue above its high-water
  mark, which makes producer ``write()`` calls return ``False`` — the
  app-visible stall.
* **Receiver**: :func:`recv_over` stops reading from the transport
  whenever :meth:`Decoder.write` reports a stall (an outstanding app
  ``done``), resuming on the parked write-completion callback.  While it
  is not reading, the kernel receive buffer fills, the peer's sends
  block, and the stall propagates back to the producer — exactly the
  reference's end-to-end valve, with the OS socket buffers as the pipe.

The pumps are blocking by design (run each in a thread, or a process per
end): a session end is single-threaded state, so each pump owns its end
and apps must issue ``done`` acks from the delivering thread or an
external serializer.  :func:`session_over_socketpair` wires two ends of
an in-process socketpair for tests and examples; the conformance suite
also runs the encoder in a *separate process* over a pipe
(tests/test_transport.py), crossing a real process boundary.

**These loops are the PORTABLE REFERENCE pumps** (ISSUE 14): the
batched-syscall native twins live in :mod:`.pump` behind the
``DAT_PUMP`` route selector — byte-identical deliveries, digests,
checkpoints, and structured errors on every chaos seed
(tests/test_pump_parity.py), an order less interpreter work per wire
byte.  Callers with raw fds should go through the selector; callers
with only callables (custom transports, fault injectors) use these
directly and lose nothing but batching.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Callable

from ..obs.metrics import OBS as _OBS, counter as _counter
from .decoder import Decoder, DecoderDestroyedError
from .encoder import Encoder, EncoderDestroyedError

DEFAULT_CHUNK = 64 * 1024

# Wakeup attribution (OBSERVABILITY.md): `.event` counts waits ended by
# the drain-watcher / readable-hook actually firing, `.poll` counts
# WAKE_FALLBACK expiries — quantifying whether the event plumbing from
# PR 2 really carries the wakeups or the guarded poll is doing the work.
_M_RECV_WAKE_EVENT = _counter("transport.recv.wake.event")
_M_RECV_WAKE_POLL = _counter("transport.recv.wake.poll")
_M_SEND_WAKE_EVENT = _counter("transport.send.wake.event")
_M_SEND_WAKE_POLL = _counter("transport.send.wake.poll")

# Guarded-fallback poll period: wakeups are event-driven (the encoder's
# readable hook / the decoder's drain watchers), so this bound only
# matters if a wakeup is ever lost to an unknown race — the pump then
# rediscovers the state within one period instead of hanging forever.
WAKE_FALLBACK = 0.5


def send_over(
    encoder: Encoder,
    write_bytes: Callable[[bytes], None],
    close: Callable[[], None] | None = None,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Pump ``encoder`` to a blocking byte sink until EOF or destroy.

    ``write_bytes`` must block when the transport is congested (that is
    the backpressure).  ``close`` (e.g. ``sock.shutdown(SHUT_WR)``) runs
    on the way out so the peer observes EOF.

    Readiness certificate (``artifacts/event_loop_surface.json``, entry
    ``transport-send-pump``): the pump's OWN waits are bounded
    (``readable.wait(WAKE_FALLBACK)``); its remaining unbounded surface
    is exactly the injected ``write_bytes`` callable — blocking there is
    the backpressure contract above, and every caller that needs a bound
    owns it at the fd/socket layer (``SO_SNDTIMEO``, ``settimeout``,
    nonblocking-fd deadline loops) rather than inside this pump.
    """
    readable = threading.Event()
    encoder._attach_readable(readable.set)
    # wake hook only: sets an Event, never blocks (ISSUE 17 satellite)
    # datlint: allow-callback-escape
    encoder.on_error(lambda _e: readable.set())
    try:
        while True:
            try:
                data = encoder.read(chunk_size)
            except EncoderDestroyedError:
                break
            if data is None:  # finalized and drained
                break
            if not data:
                # bounded: the readable hook fires on every push, but a
                # hang here has no recovery path at all — re-check on the
                # fallback period rather than trusting a single wakeup
                woke = readable.wait(WAKE_FALLBACK)
                if _OBS.on:
                    (_M_SEND_WAKE_EVENT if woke
                     else _M_SEND_WAKE_POLL).inc()
                readable.clear()
                continue
            # ABSORBED into the certificate (docstring above): blocking
            # here IS the backpressure contract; the bound belongs to
            # the fd owner (SO_SNDTIMEO, stall teardown), not the pump
            # datlint: allow-callback-escape
            write_bytes(bytes(data))
    finally:
        encoder._detach_readable()
        if close is not None:
            try:
                # a shutdown/close syscall on the way out — bounded
                # datlint: allow-callback-escape
                close()
            except OSError:
                pass


def recv_over(
    decoder: Decoder,
    read_bytes: Callable[[int], bytes],
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Pump a blocking byte source into ``decoder`` until EOF or destroy.

    ``read_bytes(n)`` returns up to n bytes, or ``b''`` at EOF.  When the
    decoder stalls on an outstanding app ``done``, reading is suspended
    until the decoder's drain watcher fires — so the kernel receive
    buffer (not host RAM) absorbs the in-flight window and the peer's
    sends eventually block.

    Readiness certificate (``artifacts/event_loop_surface.json``, entry
    ``transport-recv-pump``): the stall loop is bounded
    (``wake.wait(WAKE_FALLBACK)``); the unbounded surface the
    certificate enumerates is the injected ``read_bytes`` callable — a
    silent peer parks the pump by design until the session owner tears
    it down (stall teardown in the sidecar, ``SO_RCVTIMEO`` on gossip
    dials), so the bound lives with whoever owns the fd.
    """
    # Persistent drain watcher, not a per-write on_consumed callback: a
    # done() ack landing on another thread while THIS thread is still
    # inside _consume used to be a lost wakeup (the acking thread's
    # _resume saw _consuming and returned without firing anything; the
    # consuming thread had already taken its stall exit).  The watcher
    # fires from the acking thread the moment the stall clears, so the
    # pump wakes immediately; the bounded wait below stays only as a
    # guarded fallback for wakeup paths not yet mapped.
    wake = threading.Event()
    decoder._add_drain_watcher(wake.set)
    try:
        while not decoder.destroyed:
            # ABSORBED into the certificate (docstring above): a silent
            # peer parks the pump by design; the bound lives with the
            # fd owner (sidecar stall teardown, gossip SO_RCVTIMEO)
            # datlint: allow-callback-escape
            data = read_bytes(chunk_size)
            if not data:
                if not decoder.destroyed and not decoder.finished:
                    decoder.end()
                return
            wake.clear()
            try:
                consumed = decoder.write(data)
            except DecoderDestroyedError:
                return
            if not consumed:
                while not (decoder.writable() or decoder.destroyed
                           or decoder.finished):
                    woke = wake.wait(WAKE_FALLBACK)
                    if _OBS.on:
                        (_M_RECV_WAKE_EVENT if woke
                         else _M_RECV_WAKE_POLL).inc()
                    wake.clear()
    finally:
        decoder._remove_drain_watcher(wake.set)


# -- socket / fd bindings ----------------------------------------------------


def send_over_socket(encoder: Encoder, sock: socket.socket,
                     chunk_size: int = DEFAULT_CHUNK) -> None:
    send_over(
        encoder,
        sock.sendall,
        close=lambda: sock.shutdown(socket.SHUT_WR),
        chunk_size=chunk_size,
    )


def recv_over_socket(decoder: Decoder, sock: socket.socket,
                     chunk_size: int = DEFAULT_CHUNK) -> None:
    recv_over(decoder, sock.recv, chunk_size=chunk_size)


def once(close_fn: Callable[[], None]) -> Callable[[], None]:
    """Close-once guard: the returned callable runs ``close_fn`` on the
    first call only, atomically across threads (mirrors the sidecar's
    once-only stdio close).  Share it between a pump's ``close`` hook and
    the caller's own error-path cleanup so neither double-closes — a
    second ``os.close`` on a released fd number can hit an unrelated
    descriptor some other thread was just handed."""
    guard = threading.Lock()

    def _once() -> None:
        if guard.acquire(blocking=False):
            close_fn()

    return _once


def write_all(fd: int, data) -> None:
    """Blocking write loop: every byte of ``data`` reaches ``fd`` or the
    OSError propagates — the ONE owner of this shape (the sidecar's
    stdio writer and the pump module's Python-route fallback both bind
    it; independent copies would drift on the next partial-write
    lesson)."""
    view = memoryview(data)
    while view:
        # ABSORBED: a full pipe/socket blocking here IS the send-side
        # backpressure contract (module docstring); callers owning a
        # bound set it at the fd layer (SO_SNDTIMEO, stall teardown)
        # datlint: allow-blocking-reachable(os-io)
        view = view[os.write(fd, view):]


def send_over_fd(encoder: Encoder, fd: int,
                 chunk_size: int = DEFAULT_CHUNK,
                 close: Callable[[], None] | None = None,
                 ) -> Callable[[], None]:
    """Pump ``encoder`` into a raw fd; closes it exactly once on the way
    out.  ``close`` lets the caller share its own :func:`once` guard (and
    is returned either way, so error-path cleanup can safely invoke it
    again — the old ``close=lambda: os.close(fd)`` double-closed when the
    caller also closed the fd after a pump error)."""
    if close is None:
        close = once(lambda: os.close(fd))
    send_over(encoder, lambda data: write_all(fd, data), close=close,
              chunk_size=chunk_size)
    return close


def recv_over_fd(decoder: Decoder, fd: int,
                 chunk_size: int = DEFAULT_CHUNK) -> None:
    recv_over(decoder, lambda n: os.read(fd, n), chunk_size=chunk_size)


class SocketSession:
    """Both ends of a session wired through an OS socketpair.

    The in-process stand-in for the reference's
    ``encode.pipe(socket) ... socket.pipe(decode)`` wiring: unlike
    :class:`.pipe.Pipe` (a same-call-stack loopback), every byte crosses
    the kernel, both pump loops run on their own threads, and flow
    control is exercised against real, bounded socket buffers.
    """

    def __init__(self, encoder: Encoder, decoder: Decoder,
                 chunk_size: int = DEFAULT_CHUNK,
                 sndbuf: int | None = None):
        self.encoder = encoder
        self.decoder = decoder
        self._a, self._b = socket.socketpair()
        if sndbuf is not None:
            # shrink the kernel window so tests can observe stalls with
            # modest payloads
            self._a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
            self._b.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, sndbuf)
        self._sender = threading.Thread(
            target=send_over_socket, args=(encoder, self._a, chunk_size),
            daemon=True,
        )
        self._receiver = threading.Thread(
            target=recv_over_socket, args=(decoder, self._b, chunk_size),
            daemon=True,
        )
        self._sender.start()
        self._receiver.start()

    def wait(self, timeout: float | None = 30.0) -> None:
        """Join both pumps (the session is over when both return)."""
        self._sender.join(timeout)
        self._receiver.join(timeout)
        if self._sender.is_alive() or self._receiver.is_alive():
            raise TimeoutError("transport pumps did not finish")
        self._a.close()
        self._b.close()


def session_over_socketpair(encoder: Encoder, decoder: Decoder,
                            chunk_size: int = DEFAULT_CHUNK,
                            sndbuf: int | None = None) -> SocketSession:
    """Start pumping ``encoder -> kernel socketpair -> decoder``."""
    return SocketSession(encoder, decoder, chunk_size, sndbuf)
