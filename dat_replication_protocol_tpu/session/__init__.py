"""L2 session layer: Encoder / Decoder objects, the loopback pipe, and
the fault-and-recovery layer (faults / resume / reconnect)."""

from .decoder import BlobReader, Decoder, DecoderDestroyedError
from .encoder import (
    BatchPolicy,
    BlobLengthError,
    BlobWriter,
    Encoder,
    EncoderDestroyedError,
)
from .faults import FaultPlan, FaultyReader, FaultyWriter, TransportFault
from .pipe import Pipe, pipe
from .reconnect import BackoffPolicy, run_resumable
from .resume import ResumeError, SessionCheckpoint, WireJournal

__all__ = [
    "BatchPolicy",
    "BlobReader",
    "Decoder",
    "DecoderDestroyedError",
    "BlobLengthError",
    "BlobWriter",
    "Encoder",
    "EncoderDestroyedError",
    "Pipe",
    "pipe",
    "FaultPlan",
    "FaultyReader",
    "FaultyWriter",
    "TransportFault",
    "BackoffPolicy",
    "run_resumable",
    "ResumeError",
    "SessionCheckpoint",
    "WireJournal",
]
