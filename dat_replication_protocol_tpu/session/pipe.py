"""In-process loopback pump connecting an Encoder to a Decoder.

The reference wires its two ends with Node's ``encode.pipe(decode)``
(reference: example.js:53, test/basic.js:29); loopback piping is also how its
whole test suite exercises the wire format without a socket
(reference: test/basic.js — every test). This module is the Python analogue:
a reactive pump that honors both sides' backpressure without an event loop.

``pipe(encoder, decoder)`` drives bytes until EOF. If the decoder stalls on
an outstanding app ``done``, the pump parks itself and continues when the app
drains; ``pipe`` returns once everything written *so far* has been pushed
(the session finishes when the app releases the last ``done``).
"""

from __future__ import annotations

from .decoder import Decoder
from .encoder import Encoder

DEFAULT_CHUNK = 64 * 1024


class Pipe:
    """Reactive pump with backpressure in both directions."""

    def __init__(self, encoder: Encoder, decoder: Decoder, chunk_size: int = DEFAULT_CHUNK):
        self.encoder = encoder
        self.decoder = decoder
        self.chunk_size = chunk_size
        self._pumping = False
        self._eof_sent = False

    @property
    def done(self) -> bool:
        """True once the session fully completed (or tore down) — live view,
        so a finalize handler acking late still flips this."""
        return (
            self.decoder.finished or self.decoder.destroyed or self.encoder.destroyed
        )

    def pump(self) -> bool:
        """Move bytes until the source is dry, the sink stalls, or EOF.
        Returns True when the session fully completed."""
        if self._pumping:
            return self.done
        if self.done or self._eof_sent:
            self._release()  # a dead pipe must not hold the encoder's
            return self.done  # exclusive hook (destroy between pumps)
        self._pumping = True
        try:
            while True:
                if self.decoder.destroyed or self.encoder.destroyed:
                    self._release()  # the encoder may outlive this pipe
                    break
                if not self.decoder.writable():
                    # Park: continue pumping when the app drains the decoder.
                    self.decoder._write_cbs.append(self._on_drain)
                    break
                data = self.encoder.read(self.chunk_size)
                if data is None:  # EOF
                    self._eof_sent = True
                    self._release()
                    self.decoder.end()
                    break
                if not data:
                    break  # source dry (caller will pump() again after writes)
                self.decoder.write(data)
        finally:
            self._pumping = False
        return self.done

    def _release(self) -> None:
        """Free the encoder's readable-hook slot once this pipe can never
        pump again, so a later pump/transport may claim the encoder
        (attach is exclusive and fails loudly on double-claim)."""
        # == not `is`: each `self.pump` access builds a fresh bound method
        if self.encoder._on_readable == self.pump:
            self.encoder._detach_readable()

    def _on_drain(self) -> None:
        self.pump()


def pipe(encoder: Encoder, decoder: Decoder, chunk_size: int = DEFAULT_CHUNK) -> Pipe:
    """Connect and start pumping. Call after setting up handlers and writes,
    or call ``p.pump()`` again after late writes (mirrors that Node pipes are
    pull-driven and keep flowing as more data is produced)."""
    p = Pipe(encoder, decoder, chunk_size)
    encoder._attach_readable(p.pump)
    # a decoder torn down outside an active pump must still free the
    # encoder's exclusive hook immediately (not on some later pump call)
    decoder.on_error(lambda _e: p._release())
    p.pump()
    return p
