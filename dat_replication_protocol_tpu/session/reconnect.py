"""Reconnect with exponential backoff + full jitter; the resumable driver.

Policy (ROBUSTNESS.md): attempt ``k`` (1-based) sleeps
``uniform(0, min(cap, base * 2**k))`` — "full jitter", the variant that
avoids synchronized reconnect storms when many peers lose the same link
(the thundering-herd argument; AWS architecture blog's exp-backoff
study).  Attempts are bounded: once ``max_retries`` transport faults
accumulate, the driver gives up with ONE structured
:class:`~..wire.framing.ProtocolError` carrying the last checkpoint's
frame index / byte offset and the underlying cause — never a hang,
never a silent partial session.

:func:`run_resumable` is the receive-side driver: it pulls bytes from a
reconnectable source into a decoder, exporting a checkpoint at every
fault and asking the source for a fresh connection that resumes from
it.  The source callable is transport-agnostic — tests hand it a
fault-injected journal replay (:mod:`.faults`), a real deployment hands
it a socket dialer.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from ..obs.events import emit as _emit
from ..obs.flight import FLIGHT as _FLIGHT
from ..obs.metrics import OBS as _OBS, counter as _counter, \
    histogram as _histogram
from ..obs.tracing import trace_span as _trace_span
from ..wire.framing import ProtocolError
from .decoder import Decoder, DecoderDestroyedError
from .faults import TransportFault
from .resume import SessionCheckpoint
from .transport import DEFAULT_CHUNK

__all__ = ["BackoffPolicy", "retrying", "run_resumable"]

# Reconnect telemetry (OBSERVABILITY.md): the conformance oracle
# compares these against the driver's own stats dict — attempt and
# backoff counts must equal the ground truth exactly.
_M_ATTEMPTS = _counter("reconnect.attempts")
_M_FAULTS = _counter("reconnect.faults")
_M_BACKOFFS = _counter("reconnect.backoffs")
_H_BACKOFF = _histogram("reconnect.backoff.seconds")


class BackoffPolicy:
    """Exponential backoff with full jitter, bounded attempts.

    ``seed`` pins the jitter for reproducible tests; ``sleep`` is
    injectable for the same reason.  ``max_retries`` counts *faults
    absorbed*: the first failure is retried while ``faults <=
    max_retries``, so ``max_retries=0`` means fail on the first fault.
    """

    def __init__(self, base: float = 0.05, cap: float = 5.0,
                 max_retries: int = 5, seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep):
        if base < 0 or cap < 0:
            raise ValueError("backoff base/cap must be >= 0")
        self.base = base
        self.cap = cap
        self.max_retries = max_retries
        self._rng = random.Random(seed)
        self._sleep = sleep

    def delay(self, attempt: int) -> float:
        """Full-jitter delay before retry ``attempt`` (1-based)."""
        ceiling = min(self.cap, self.base * (2 ** max(0, attempt)))
        return self._rng.uniform(0.0, ceiling)

    def sleep_before(self, attempt: int) -> float:
        d = self.delay(attempt)
        if _OBS.on:
            # the single backoff choke point: run_resumable, retrying(),
            # and the sidecar's bind/accept retries all sleep HERE, so
            # one site covers every backoff in the stack
            _M_BACKOFFS.inc()
            _H_BACKOFF.observe(d)
            _emit("reconnect.backoff", attempt=attempt, seconds=d)
        if d > 0:
            self._sleep(d)
        return d


def retrying(fn: Callable[[], object], policy: BackoffPolicy,
             retry_on: tuple = (OSError,), describe: str = "operation"):
    """Run ``fn`` with the policy's backoff until it returns or the
    attempts are exhausted; the terminal failure is one structured
    ProtocolError wrapping the last cause."""
    failures = 0
    while True:
        try:
            # ABSORBED (ISSUE 17 satellite): the retried operation is
            # the caller's own bind/accept/dial — its blocking bound is
            # the caller's contract (kernel timeouts at those sites),
            # not this wrapper's; the backoff sleeps here ARE bounded
            # datlint: allow-callback-escape
            return fn()
        except retry_on as e:
            failures += 1
            if failures > policy.max_retries:
                err = ProtocolError(
                    f"{describe} failed after {failures} attempt(s)",
                    cause=e,
                )
                if _FLIGHT.armed:  # retry exhaustion is a post-mortem
                    _FLIGHT.dump("retry-exhausted", error=err)
                raise err from e
            policy.sleep_before(failures)


def _wire_error(errors: list, ckpt: SessionCheckpoint) -> ProtocolError:
    """The decoder destroyed itself: surface its error as ONE structured
    ProtocolError (wrapping non-protocol causes) with session context."""
    err = errors[-1] if errors else None
    if isinstance(err, ProtocolError):
        return err
    return ProtocolError(
        "session destroyed mid-stream",
        frame=ckpt.frame, offset=ckpt.wire_offset, cause=err,
    )


def run_resumable(
    source: Callable[[SessionCheckpoint, int], object],
    decoder: Decoder,
    policy: BackoffPolicy,
    chunk_size: int = DEFAULT_CHUNK,
    expected_total: Optional[int] = None,
    stall_timeout: Optional[float] = None,
    wait_step: float = 0.5,
) -> dict:
    """Drive a resumable receive session to completion.

    ``source(checkpoint, failures)`` opens a connection delivering wire
    bytes from ``checkpoint.wire_offset`` onward, as an object with
    ``read(n) -> bytes`` (``b''`` at EOF).  Connection death — opening
    or reading — may surface as :class:`TransportFault` or as any plain
    ``OSError`` (what a real socket raises: ``ConnectionResetError``,
    ``ETIMEDOUT``, ...); both take the reconnect path.

    Termination is trichotomous, never silent:

    * the decoder finishes with the complete session (returns stats);
    * ONE structured ProtocolError is raised — wire corruption, resume
      window lost, app stall past ``stall_timeout``, or attempts
      exhausted, each with frame/byte/cause context;
    * (there is no third option: every wait is bounded.)

    ``expected_total``, when the sender's produced length is known
    out-of-band, turns silent truncation (a clean EOF short of the
    declared length) into a reconnect instead of a quietly short
    session — see ROBUSTNESS.md on why in-band detection is impossible
    for an EOF-terminated wire format.
    """
    stats = {"attempts": 0, "reconnects": 0, "faults": []}
    errors: list = []
    err_cb = errors.append
    decoder.on_error(err_cb)
    wake = threading.Event()
    decoder._add_drain_watcher(wake.set)
    failures = 0
    try:
        while True:
            ckpt = decoder.checkpoint()
            stats["attempts"] += 1
            if _OBS.on:
                _M_ATTEMPTS.inc()
                _emit("session.connect", attempt=stats["attempts"],
                      wire_offset=ckpt.wire_offset,
                      resumed=stats["attempts"] > 1)
            # The fault catches wrap ONLY the transport calls (source()
            # and reader.read) — catching OSError around decoder.write
            # would misclassify an app handler's own OSError (e.g.
            # ENOSPC while materializing a blob) as a transport fault
            # and "resume" a stream the failed delivery desynchronized.
            # OSError, not just TransportFault: a real socket surfaces
            # peer death as ConnectionResetError / ETIMEDOUT etc.
            # (TransportFault is itself a ConnectionError), and all of
            # it must land in the reconnect path, never escape raw.
            fault: Optional[OSError] = None
            # the attempt span brackets one connection's lifetime (open
            # -> EOF/fault), keyed on the wire offset it resumed from —
            # the exported trace shows each reconnect as its own span
            with _trace_span("reconnect.attempt",
                             attempt=stats["attempts"],
                             offset=ckpt.wire_offset):
                try:
                    reader = source(ckpt, failures)
                except OSError as e:
                    fault = e
                while fault is None:
                    try:
                        data = reader.read(chunk_size)
                    except OSError as e:
                        fault = e
                        break
                    if not data:
                        if (expected_total is not None
                                and decoder.bytes < expected_total):
                            # silent truncation: the connection closed
                            # cleanly short of the sender's declared
                            # length — same recovery path as a drop
                            if _OBS.on:
                                _emit("session.truncated",
                                      at=decoder.bytes,
                                      expected=expected_total)
                            fault = TransportFault(
                                f"truncated: clean EOF at byte "
                                f"{decoder.bytes} of {expected_total}",
                                offset=decoder.bytes)
                        break
                    wake.clear()
                    try:
                        consumed = decoder.write(data)
                    except DecoderDestroyedError:
                        raise _wire_error(errors, decoder.checkpoint())
                    if decoder.destroyed:
                        raise _wire_error(errors, decoder.checkpoint())
                    if not consumed:
                        _wait_writable(decoder, wake, wait_step,
                                       stall_timeout)
            if fault is not None:
                failures += 1
                stats["faults"].append(str(fault))
                if _OBS.on:
                    _M_FAULTS.inc()
                    _emit("reconnect.fault", failures=failures,
                          offset=decoder.bytes, cause=str(fault))
                if failures > policy.max_retries:
                    last = decoder.checkpoint()
                    if _OBS.on:
                        _emit("session.failed", failures=failures,
                              frame=last.frame, offset=last.wire_offset)
                    raise ProtocolError(
                        f"session lost after {failures} transport fault(s)",
                        frame=last.frame, offset=last.wire_offset,
                        cause=fault,
                    ) from fault
                stats["reconnects"] += 1
                policy.sleep_before(failures)
                continue
            # clean EOF this attempt
            if decoder.destroyed:
                raise _wire_error(errors, decoder.checkpoint())
            if not decoder.finished:
                decoder.end()
                if decoder.destroyed:  # e.g. EOF mid-frame
                    raise _wire_error(errors, decoder.checkpoint())
            if _OBS.on:
                _emit("session.complete", bytes=decoder.bytes,
                      reconnects=stats["reconnects"],
                      attempts=stats["attempts"])
            if stats["faults"] and _FLIGHT.armed:
                # the session survived its turbulence, but the faults
                # still deserve a post-mortem: an armed recorder keeps
                # a bundle per recovered incident, so chaos coordinates
                # stay attributable offline even when nothing failed.
                # routine=True: recovered dumps draw from the half of
                # the budget NOT reserved for genuine failures
                _FLIGHT.dump(
                    "recovered",
                    checkpoint=decoder.checkpoint(emit_event=False),
                    extra={"stats": dict(stats)}, routine=True)
            return stats
    except ProtocolError as e:
        # terminal failure (exhaustion, stall, wire error, resume-window
        # miss): ONE bundle for the incident — the decoder's own wire
        # errors were already dumped with this very object, and the
        # recorder dedups on error identity, so this cannot double-dump
        if _FLIGHT.armed:
            _FLIGHT.dump("session-failed", error=e,
                         checkpoint=decoder.checkpoint(emit_event=False))
        raise
    finally:
        decoder._remove_drain_watcher(wake.set)
        # symmetric cleanup: a long-lived decoder driven through this
        # function repeatedly must not accumulate stale error hooks
        try:
            decoder._error_cbs.remove(err_cb)
        except ValueError:
            pass


def _wait_writable(decoder: Decoder, wake: threading.Event,
                   wait_step: float, stall_timeout: Optional[float]) -> None:
    """Bounded wait for the app to drain the decoder: the drain watcher
    wakes us immediately on cross-thread acks; ``stall_timeout`` (when
    set) converts an app that never acks into a structured error
    instead of a parked-forever driver."""
    deadline = (None if stall_timeout is None
                else time.monotonic() + stall_timeout)
    while not (decoder.writable() or decoder.destroyed or decoder.finished):
        if deadline is not None and time.monotonic() > deadline:
            ckpt = decoder.checkpoint()
            if _OBS.on:
                _emit("session.stall", kind="app-ack",
                      seconds=stall_timeout, frame=ckpt.frame,
                      offset=ckpt.wire_offset)
            err = ProtocolError(
                f"app stalled: no ack for {stall_timeout}s",
                frame=ckpt.frame, offset=ckpt.wire_offset,
            )
            decoder.destroy(err)
            raise err
        wake.wait(wait_step)
        wake.clear()
