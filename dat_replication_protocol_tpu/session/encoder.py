"""Encoder — the producing end of a replication session.

Capability parity with the reference Encoder (reference: encode.js:46-151),
re-designed as a pull-based Python object instead of a Node Readable:

* ``change(change, on_flush)`` frames a protobuf Change (type id 1).
* ``blob(length, on_flush)`` opens a streamed blob (type id 2); returns a
  :class:`BlobWriter`. The frame length must be declared up front because the
  wire header precedes the data (reference: encode.js:79).
* **Blob FIFO discipline**: any number of blobs may be *open* concurrently but
  their bytes hit the wire strictly in creation order — the second and later
  blobs are corked at creation and uncorked when the head finishes
  (reference: encode.js:87-96). Writes to a corked blob are parked.
* **Change parking**: a change submitted while any blob is open is parked and
  replayed once the blob queue drains, so changes are ordered after all blobs
  that were open at submit time (reference: encode.js:104-107, replay at :95).
* **Backpressure**: the consumer pulls with :meth:`read`; ``on_flush``
  callbacks fire when the corresponding bytes have actually been pulled —
  the pull is this design's analogue of the Readable drain that times flush
  callbacks in the reference (reference: encode.js:139-151).
* ``finalize()`` marks EOF; :meth:`read` returns ``None`` once drained
  (reference: encode.js:119-122 pushes EOF on the Readable).
* Counters ``bytes`` / ``changes`` / ``blobs`` mirror the reference's passive
  counters (reference: encode.js:51-53).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from time import monotonic as _now
from typing import Callable, Optional

from ..obs.metrics import OBS as _OBS, counter as _counter, \
    histogram as _histogram
from ..obs.tracing import trace_instant as _trace_instant
from ..obs import wirecost as _wirecost
from ..wire.change_codec import Change, _check_uint32, \
    _encode_change_with, _fastpath_mod, encode_change
from ..wire.framing import CAP_CHANGE_BATCH, CAP_RECONCILE, CAP_SNAPSHOT, \
    TYPE_BLOB, TYPE_CHANGE, TYPE_CHANGE_BATCH, TYPE_RECONCILE, \
    TYPE_SNAPSHOT, frame_header, frame_wire_len, header_len as _header_len

OnDone = Optional[Callable[[], None]]

# Telemetry handles, hoisted at import so the disabled path at every
# instrumentation site is one `_OBS.on` attribute load (OBSERVABILITY.md).
_M_ENC_BYTES = _counter("encoder.bytes")
_M_ENC_CHANGES = _counter("encoder.changes")
_M_ENC_BLOBS = _counter("encoder.blobs")
_M_ENC_BLOB_CHUNKS = _counter("encoder.blob.chunks")
_M_ENC_PARKED = _counter("encoder.parked.bytes")
# backpressure park time: how long bytes sat corked/parked behind the
# blob FIFO before reaching the wire queue
_H_ENC_PARK = _histogram("encoder.park.seconds")
# negotiated ChangeBatch frames (OBSERVABILITY.md "wire.batch.*"):
# frames/rows emitted columnar, and the wire bytes the columnar layout
# saved vs framing the same rows per-record (exact arithmetic, not an
# estimate — see batch_codec.estimate_per_record_bytes)
_M_BATCH_FRAMES = _counter("wire.batch.frames")
_M_BATCH_ROWS = _counter("wire.batch.rows")
_M_BATCH_SAVED = _counter("wire.batch.bytes_saved")
# negotiated reconcile frames (OBSERVABILITY.md "reconcile.*"): control
# + symbol-run frames emitted, and their total wire volume — the
# anti-entropy protocol's entire communication cost rides these
_M_RC_FRAMES = _counter("reconcile.frames")
_M_RC_WIRE = _counter("reconcile.wire_bytes")
# snapshot protocol frames emitted (OBSERVABILITY.md "snapshot.*")
_M_SN_FRAMES = _counter("snapshot.frames")
_M_SN_WIRE = _counter("snapshot.wire_bytes")

DEFAULT_HIGH_WATER = 64 * 1024


@dataclasses.dataclass
class BatchPolicy:
    """Flush policy for negotiated columnar ``ChangeBatch`` framing.

    Rows accumulate until any bound trips: ``max_rows`` / ``max_bytes``
    (approximate payload volume), ``max_delay`` seconds since the first
    pending row (checked on the next submit — there is no timer thread;
    latency-sensitive producers call :meth:`Encoder.flush_batch`), or an
    *uncork*: a consumer pulling :meth:`Encoder.read` while the queue is
    otherwise dry flushes what is pending, so a drained transport never
    waits on a half-full batch.  A blob open or ``finalize()`` always
    flushes first (frame order is submission order).
    """

    max_rows: int = 4096
    max_bytes: int = 1 << 20
    max_delay: float | None = None


class EncoderDestroyedError(Exception):
    pass


class BlobLengthError(Exception):
    """Writes did not match the declared blob length."""


class BlobWriter:
    """Write side of one streamed blob.

    Mirrors the encoder-side BlobStream (reference: encode.js:11-44): chunks
    forward into the parent's output queue; while corked (not head of the blob
    FIFO) writes are parked and flushed on uncork. Unlike the reference —
    which never validates payload size against the declared frame length —
    this writer raises :class:`BlobLengthError` on overflow or short ``end()``,
    because a mismatch silently desyncs the wire.
    """

    def __init__(self, encoder: "Encoder", length: int, on_flush: OnDone = None):
        self._encoder = encoder
        self.length = length
        self._on_flush = on_flush
        self._written = 0
        self._corked = False
        self._parked: list[tuple[bytes, OnDone, float | None]] = []
        self._ended = False
        self._finished = False
        self._tag_on_uncork = False  # corked blob: frame span deferred
        self.destroyed = False

    # -- public API ---------------------------------------------------------

    def write(self, data, on_flush: OnDone = None) -> bool:
        """Append blob payload bytes. Returns False when the encoder's output
        buffer is above the high-water mark (the caller should wait for
        :meth:`Encoder.on_drain`)."""
        if self.destroyed or self._encoder.destroyed:
            raise EncoderDestroyedError("write after destroy")
        if self._ended:
            raise BlobLengthError("write after end()")
        if isinstance(data, str):
            data = data.encode("utf-8")
        elif not isinstance(data, (bytes, bytearray, memoryview)):
            data = bytes(data)
        if self._written + len(data) > self.length:
            err = BlobLengthError(
                f"blob overflow: declared {self.length}, writing past it "
                f"({self._written} + {len(data)})"
            )
            self._encoder.destroy(err)
            raise err
        self._written += len(data)
        if _OBS.on:
            _M_ENC_BLOB_CHUNKS.inc()
        if self._corked:
            self._park(bytes(data), on_flush)
            return not self._encoder._above_high_water()
        return self._encoder._push(data, on_flush)

    def end(self, data=None, on_flush: OnDone = None) -> None:
        """Finish the blob (optionally writing a final chunk)."""
        if data is not None:
            self.write(data, on_flush)
        elif on_flush is not None:
            # fire once the blob's bytes are flushed
            prev = self._on_flush
            if prev is None:
                self._on_flush = on_flush
            else:
                def both(a=prev, b=on_flush):
                    a()
                    b()
                self._on_flush = both
        if self._ended:
            return
        self._ended = True
        if self._written != self.length:
            err = BlobLengthError(
                f"blob ended short: declared {self.length}, wrote {self._written}"
            )
            self._encoder.destroy(err)
            raise err
        if not self._corked:
            self._finish()

    def destroy(self, err: Exception | None = None) -> None:
        """Tear down this blob and the whole session — destroying either side
        of a blob destroys its parent (reference: encode.js:22-28)."""
        if self.destroyed:
            return
        self.destroyed = True
        self._encoder.destroy(err)

    # -- internal -----------------------------------------------------------

    def _cork(self) -> None:
        self._corked = True

    def _park(self, data: bytes, cb: OnDone) -> None:
        """Parked bytes count toward the encoder's high-water mark so
        backpressure stays honest while the head blob streams."""
        # third slot: park timestamp (None while telemetry is off) —
        # _uncork turns it into the encoder.park.seconds histogram
        self._parked.append((data, cb, _now() if _OBS.on else None))
        self._encoder._parked_bytes += len(data)
        if _OBS.on:
            _M_ENC_PARKED.inc(len(data))

    def _uncork(self) -> None:
        """Flush parked chunks into the parent; if already ended, finish —
        cascading to the next queued blob (reference: encode.js:30-35,92-96)."""
        if not self._corked:
            return
        self._corked = False
        if self._tag_on_uncork:
            self._tag_on_uncork = False
            if _OBS.on:
                # the first parked chunk is this blob's header: the
                # encoder's byte count right now IS the frame's wire
                # start offset
                _trace_instant("encoder.frame", offset=self._encoder.bytes,
                               kind="blob",
                               wire_len=frame_wire_len(self.length))
                self._encoder._lit_cost_blob(self.length)
        for data, cb, t0 in self._parked:
            self._encoder._parked_bytes -= len(data)
            if t0 is not None and _OBS.on:
                _H_ENC_PARK.observe(_now() - t0)
            self._encoder._push(data, cb)
        self._parked.clear()
        if self._ended:
            self._finish()

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        if self._on_flush is not None:
            # Deliver the blob-level flush when the last pushed byte drains.
            self._encoder._after_flush(self._on_flush)
        self._encoder._blob_finished(self)


class Encoder:
    """Pull-based frame producer. See module docstring for semantics."""

    # the wire cost plane's link label (ISSUE 20): owners carrying more
    # than one session overwrite it per instance (the sidecar names it
    # after the session key) — a collector label, runtime by design
    cost_link = "session"

    def __init__(self, high_water: int = DEFAULT_HIGH_WATER,
                 peer_caps: int = 0,
                 batch_policy: BatchPolicy | None = None):
        self.bytes = 0
        self.changes = 0
        self.blobs = 0
        # capability mask the RECEIVING peer advertised (WIRE.md
        # "Capability negotiation"); 0 = assume a reference peer, emit
        # the reference wire byte-exactly.  CAP_CHANGE_BATCH switches
        # change() to columnar accumulation behind `batch_policy`.
        self.peer_caps = peer_caps
        self._batch_policy = batch_policy if batch_policy is not None \
            else BatchPolicy()
        # pending ChangeBatch rows: prepared (validated, utf-8 encoded)
        # tuples + their flush callbacks; byte volume rides the
        # high-water accounting like parked changes do
        self._batch_rows: list[tuple] = []
        self._batch_cbs: list[Callable[[], None]] = []
        self._batch_pending_bytes = 0
        self._batch_t0: float | None = None
        self.destroyed = False
        self.finalized = False
        self.finished = False  # terminal: drained past finalize, or destroyed
        self._high_water = high_water
        # queue of (payload: bytes, on_consumed: OnDone); payloads are wire
        # bytes (headers and data alike).
        self._queue: deque[tuple[bytes, OnDone]] = deque()
        self._queued_bytes = 0
        self._parked_bytes = 0  # bytes held in corked blobs / parked changes
        self._open_blobs: deque[BlobWriter] = deque()
        # Parked changes are encoded at submit time (catching bad input early
        # and making the parked bytes countable); framed on replay.
        self._parked_changes: list[tuple[bytes, OnDone, float | None]] = []
        self._drain_cbs: list[Callable[[], None]] = []
        self._error_cbs: list[Callable[[Exception | None], None]] = []
        self._finish_cbs: list[Callable[[], None]] = []
        self._finalize_cb: OnDone = None
        # Consumer hook (set by session.pipe.Pipe): called whenever new wire
        # bytes become readable, so a connected pump keeps flowing on late
        # writes — the pull-based stand-in for Node's 'readable' event.
        self._on_readable: Optional[Callable[[], None]] = None
        # Resume tee (see session.resume.WireJournal): every byte read()
        # hands out is also appended here, so a reconnect can replay the
        # bytes a dead transport lost.
        self._journal = None

    def _attach_readable(self, cb: Callable[[], None]) -> None:
        """Claim the single readable-hook slot.  A second pump silently
        overwriting the first would starve it forever — fail loudly."""
        if self._on_readable is not None:
            raise RuntimeError(
                "encoder is already attached to a pump/pipe; detach it first"
            )
        self._on_readable = cb

    def _detach_readable(self) -> None:
        self._on_readable = None

    def attach_journal(self, journal) -> None:
        """Tee every wire byte :meth:`read` returns into ``journal``
        (anything with ``append(bytes)`` — canonically a
        :class:`~.resume.WireJournal`), so the session can resume from a
        receiver checkpoint after a transport failure.  The journal sees
        bytes in exact wire order because ``read`` is the single exit
        point of the output queue.

        Journal positions are ABSOLUTE wire offsets: attaching after
        bytes were already read out aligns the journal's window past
        them (via ``journal.seek``) — silently recording them at offset
        0 would make every ``read_from(checkpoint.wire_offset)`` replay
        the wrong bytes."""
        delivered = self.bytes - self._queued_bytes  # already read out
        if delivered:
            seek = getattr(journal, "seek", None)
            if seek is None:
                raise RuntimeError(
                    f"encoder already emitted {delivered} byte(s) and the "
                    "journal cannot seek; attach before the first read")
            seek(delivered)
        self._journal = journal

    # -- capability negotiation ---------------------------------------------

    def negotiate(self, peer_caps: int) -> None:
        """Adopt the receiving peer's advertised capability mask (learned
        out of band — session setup, app handshake; WIRE.md).  Takes
        effect for subsequent submissions; revoking ``CAP_CHANGE_BATCH``
        re-frames any pending rows as per-record ``Change`` frames —
        the revocation means the peer cannot parse a batch frame, so
        one must never be emitted after it."""
        had_batch = self._batching
        self.peer_caps = peer_caps
        if had_batch and not self._batching:
            self._flush_pending_per_record()

    @property
    def _batching(self) -> bool:
        return bool(self.peer_caps & CAP_CHANGE_BATCH) \
            and not self.destroyed

    # -- public API ---------------------------------------------------------

    def change(self, change: Change | dict, on_flush: OnDone = None) -> bool:
        """Frame a Change. If any blob is open the change is parked and
        replayed when the blob queue drains (reference: encode.js:102-117).

        With ``CAP_CHANGE_BATCH`` negotiated and no blob open, the change
        instead joins the pending columnar batch (validated now, framed
        at flush — see :class:`BatchPolicy` for when that happens)."""
        if self.destroyed:
            raise EncoderDestroyedError("change after destroy")
        if self.finalized:
            raise EncoderDestroyedError("change after finalize")
        if self._batching and not self._open_blobs:
            self._batch_append(self._prepare_row(change), on_flush)
            return not self._above_high_water()
        payload = encode_change(change)
        if self._open_blobs:
            self._parked_changes.append(
                (payload, on_flush, _now() if _OBS.on else None))
            self._parked_bytes += len(payload)
            if _OBS.on:
                _M_ENC_PARKED.inc(len(payload))
            return not self._above_high_water()
        return self._frame_change(payload, on_flush)

    def change_many(self, records, on_flush: OnDone = None) -> bool:
        """Submit a whole run of changes with per-batch (not per-row)
        overhead: the fastpath gate is bound ONCE, the framed bytes land
        in ONE queue entry (one readable wakeup, one journal tee), and
        ``on_flush`` fires when the run's bytes drain.  Wire bytes are
        identical to calling :meth:`change` per record — this is the
        bulk shape of the same API, for log-construction-scale callers.
        """
        if self.destroyed:
            raise EncoderDestroyedError("change after destroy")
        if self.finalized:
            raise EncoderDestroyedError("change after finalize")
        if not isinstance(records, (list, tuple)):
            records = list(records)
        if self._open_blobs:
            # ordering behind the blob FIFO is per-record machinery;
            # park each (rare shape — bulk producers don't interleave)
            ok = True
            for i, rec in enumerate(records):
                ok = self.change(
                    rec, on_flush if i == len(records) - 1 else None)
            return ok
        if self._batching:
            prepared = [self._prepare_row(r) for r in records]
            for i, row in enumerate(prepared):
                self._batch_append(
                    row, on_flush if i == len(prepared) - 1 else None,
                    defer_flush=True)
            self._maybe_flush_batch()
            return not self._above_high_water()
        fp = _fastpath_mod()  # bound once for the whole run
        out = bytearray()
        n = 0
        plen = 0
        obs_on = _OBS.on
        for rec in records:
            payload = _encode_change_with(fp, rec)
            header = frame_header(len(payload), TYPE_CHANGE)
            if obs_on:
                _trace_instant("encoder.frame",
                               offset=self.bytes + len(out),
                               kind="change",
                               wire_len=len(header) + len(payload))
                plen += len(payload)
            out += header
            out += payload
            n += 1
        if not n:
            if on_flush is not None:
                self._after_flush(on_flush)
            return not self._above_high_water()
        self.changes += n
        if obs_on:
            _M_ENC_CHANGES.inc(n)
            # run totals: framing = framed bytes minus payload bytes
            self._lit_cost_change(len(out) - plen, plen, n)
        return self._push(bytes(out), on_flush)

    # -- ChangeBatch accumulation -------------------------------------------

    @staticmethod
    def _prepare_row(change: Change | dict) -> tuple:
        """Validate + normalize one record at SUBMIT time (same doctrine
        as parked changes encoding eagerly: bad input surfaces at the
        call that supplied it, not at some later flush).  Field
        extraction and error classes mirror ``_encode_change_with``."""
        if isinstance(change, dict):
            if "from" in change:
                fr = change["from"]
            elif "from_" in change:
                fr = change["from_"]
            else:
                raise KeyError("from")  # required, same as from_dict
            key = change["key"]
            cg = change["change"]
            to = change["to"]
            value = change.get("value")
            subset = change.get("subset")
        else:
            key = change.key
            cg = change.change
            fr = change.from_
            to = change.to
            value = change.value
            subset = change.subset
        if key is None:
            raise ValueError("Change.key is required")
        return (
            key.encode("utf-8"),
            _check_uint32("change", cg),
            _check_uint32("from", fr),
            _check_uint32("to", to),
            None if value is None else bytes(value),
            None if subset is None else subset.encode("utf-8"),
        )

    def _note_batch_rows(self, rows: list[tuple]) -> None:
        """Hook: one call per batch flush with the prepared row tuples,
        before the frame reaches the queue (the digest encoder submits
        each row's canonical per-record encoding here).  Base: no-op."""

    def _flush_pending_per_record(self) -> None:
        """Capability revocation path: the peer can no longer parse
        batch frames, so pending rows re-frame as per-record ``Change``
        frames (their flush callbacks fire when the run drains, same
        timing a batch flush would have given them)."""
        rows, self._batch_rows = self._batch_rows, []
        if not rows:
            return
        cbs, self._batch_cbs = self._batch_cbs, []
        self._batch_pending_bytes = 0
        self._batch_t0 = None
        fp = _fastpath_mod()  # bound once for the run

        def all_cbs():
            for cb in cbs:
                cb()

        last = len(rows) - 1
        for i, (key, cg, fr, to, val, sub) in enumerate(rows):
            payload = _encode_change_with(fp, {
                "key": key.decode("utf-8"), "change": cg, "from": fr,
                "to": to, "value": val,
                "subset": None if sub is None else sub.decode("utf-8"),
            })
            self._frame_change(
                payload, all_cbs if (i == last and cbs) else None)

    def _batch_append(self, row: tuple, on_flush: OnDone,
                      defer_flush: bool = False) -> None:
        if not self._batch_rows:
            self._batch_t0 = _now()
        self._batch_rows.append(row)
        if on_flush is not None:
            self._batch_cbs.append(on_flush)
        # approximate pending volume: heap bytes + fixed columns
        self._batch_pending_bytes += (
            len(row[0]) + (len(row[4]) if row[4] is not None else 0)
            + (len(row[5]) if row[5] is not None else 0) + 24)
        if not defer_flush:
            self._maybe_flush_batch()

    def _maybe_flush_batch(self) -> None:
        pol = self._batch_policy
        if (len(self._batch_rows) >= pol.max_rows
                or self._batch_pending_bytes >= pol.max_bytes
                or (pol.max_delay is not None and self._batch_t0 is not None
                    and _now() - self._batch_t0 >= pol.max_delay)):
            self.flush_batch()

    # -- wire cost lit helpers (ISSUE 20) ------------------------------------
    # Each hot path forks ONCE on `_OBS.on`; the helper below the fork
    # holds every wirecost symbol, so the dark twin's bytecode provably
    # references none of them (tests/test_wirecost.py asserts it) and
    # the disabled cost stays one attribute load.  The frame CLASS is a
    # string literal at every call (the datlint obs-discipline
    # contract: the class vocabulary must stay greppable).

    def _lit_cost_change(self, framing: int, payload: int,
                         frames: int = 1) -> None:
        _wirecost.account("change", self.cost_link, "tx", payload,
                          framing, frames)

    def _lit_cost_batch(self, framing: int, payload: int,
                        saved: int) -> None:
        _wirecost.account("change_batch", self.cost_link, "tx", payload,
                          framing)
        if saved > 0:
            _wirecost.note_saved(self.cost_link, "tx", saved)

    def _lit_cost_reconcile(self, framing: int, payload: int) -> None:
        _wirecost.account("reconcile", self.cost_link, "tx", payload,
                          framing)

    def _lit_cost_snapshot(self, framing: int, payload: int) -> None:
        _wirecost.account("snapshot", self.cost_link, "tx", payload,
                          framing)

    def _lit_cost_blob(self, length: int) -> None:
        # accrued in full at header time — the same moment the
        # encoder.frame tag prices the whole frame (wire_len includes
        # the declared payload the chunks will stream)
        _wirecost.account("blob", self.cost_link, "tx", length,
                          _header_len(length))

    def flush_batch(self) -> None:
        """Frame every pending batch row NOW as one ``TYPE_CHANGE_BATCH``
        frame (no-op when nothing is pending)."""
        rows, self._batch_rows = self._batch_rows, []
        if not rows:
            return
        cbs, self._batch_cbs = self._batch_cbs, []
        self._batch_pending_bytes = 0
        self._batch_t0 = None
        # flush-side tap BEFORE the frame is queued — the batch twin of
        # _frame_change's submit-before-frame ordering (the TPU encoder
        # submits per-row digests of the canonical encodings here)
        self._note_batch_rows(rows)
        from ..wire import batch_codec

        payload = batch_codec.encode_rows(rows)
        header = frame_header(len(payload), TYPE_CHANGE_BATCH)
        n = len(rows)
        self.changes += n
        if _OBS.on:
            _M_ENC_CHANGES.inc(n)
            _M_BATCH_FRAMES.inc()
            _M_BATCH_ROWS.inc(n)
            import numpy as np

            est = batch_codec.estimate_per_record_bytes(
                np.asarray([len(r[0]) for r in rows], np.int64),
                np.asarray([-1 if r[5] is None else len(r[5])
                            for r in rows], np.int64),
                np.asarray([-1 if r[4] is None else len(r[4])
                            for r in rows], np.int64),
                np.asarray([r[1] for r in rows], np.uint32),
                np.asarray([r[2] for r in rows], np.uint32),
                np.asarray([r[3] for r in rows], np.uint32),
            )
            saved = est - (len(header) + len(payload))
            if saved > 0:
                _M_BATCH_SAVED.inc(saved)
            _trace_instant("encoder.frame", offset=self.bytes,
                           kind="change_batch", rows=n,
                           wire_len=len(header) + len(payload))
            self._lit_cost_batch(len(header), len(payload), int(saved))
        if len(cbs) > 1:
            def all_cbs(cbs=cbs):
                for cb in cbs:
                    cb()
            cb = all_cbs
        else:
            cb = cbs[0] if cbs else None
        self._push(header + payload, cb)

    def _frame_change(self, payload: bytes, on_flush: OnDone) -> bool:
        self.changes += 1
        header = frame_header(len(payload), TYPE_CHANGE)
        if _OBS.on:
            _M_ENC_CHANGES.inc()
            # causal key: self.bytes BEFORE the header push is the wire
            # offset this frame starts at — the same number the peer's
            # decoder computes for the same frame (obs/tracing.py)
            _trace_instant("encoder.frame", offset=self.bytes,
                           kind="change",
                           wire_len=len(header) + len(payload))
            self._lit_cost_change(len(header), len(payload))
        self._push(header, None)
        return self._push(payload, on_flush)

    def reconcile_frame(self, payload, on_flush: OnDone = None) -> bool:
        """Frame one reconcile protocol message (``TYPE_RECONCILE``;
        payload built by :mod:`..wire.reconcile_codec`).

        Strictly negotiated: raises unless the receiving peer advertised
        ``CAP_RECONCILE`` — an un-negotiated encoder therefore emits the
        reference wire byte-exactly (same golden contract as
        ChangeBatch).  Pending batch rows flush first (frame order is
        submission order); an open blob is an API error — a control
        frame cannot be parked behind a streaming payload without
        reordering the wire, and the reconcile driver never interleaves
        the two."""
        if self.destroyed:
            raise EncoderDestroyedError("reconcile_frame after destroy")
        if self.finalized:
            raise EncoderDestroyedError("reconcile_frame after finalize")
        if not (self.peer_caps & CAP_RECONCILE):
            raise ValueError(
                "peer did not advertise CAP_RECONCILE; reconcile frames "
                "cannot be emitted to it (WIRE.md capability negotiation)"
            )
        if self._open_blobs:
            raise ValueError(
                "reconcile_frame with a blob open is unsupported"
            )
        if self._batch_rows:
            self.flush_batch()
        payload = bytes(payload)
        header = frame_header(len(payload), TYPE_RECONCILE)
        if _OBS.on:
            _M_RC_FRAMES.inc()
            _M_RC_WIRE.inc(len(header) + len(payload))
            _trace_instant("encoder.frame", offset=self.bytes,
                           kind="reconcile",
                           wire_len=len(header) + len(payload))
            self._lit_cost_reconcile(len(header), len(payload))
        return self._push(header + payload, on_flush)

    def snapshot_frame(self, payload, on_flush: OnDone = None) -> bool:
        """Frame one snapshot protocol message (``TYPE_SNAPSHOT``;
        payload built by :mod:`..wire.snapshot_codec`).

        Strictly negotiated: raises unless the receiving peer advertised
        ``CAP_SNAPSHOT`` — an un-negotiated encoder therefore emits the
        reference wire byte-exactly (same golden contract as ChangeBatch
        and Reconcile).  Pending batch rows flush first (frame order is
        submission order); an open blob is an API error — the snapshot
        driver never interleaves the two."""
        if self.destroyed:
            raise EncoderDestroyedError("snapshot_frame after destroy")
        if self.finalized:
            raise EncoderDestroyedError("snapshot_frame after finalize")
        if not (self.peer_caps & CAP_SNAPSHOT):
            raise ValueError(
                "peer did not advertise CAP_SNAPSHOT; snapshot frames "
                "cannot be emitted to it (WIRE.md capability negotiation)"
            )
        if self._open_blobs:
            raise ValueError(
                "snapshot_frame with a blob open is unsupported"
            )
        if self._batch_rows:
            self.flush_batch()
        payload = bytes(payload)
        header = frame_header(len(payload), TYPE_SNAPSHOT)
        if _OBS.on:
            _M_SN_FRAMES.inc()
            _M_SN_WIRE.inc(len(header) + len(payload))
            _trace_instant("encoder.frame", offset=self.bytes,
                           kind="snapshot",
                           wire_len=len(header) + len(payload))
            self._lit_cost_snapshot(len(header), len(payload))
        return self._push(header + payload, on_flush)

    def blob(self, length: int, on_flush: OnDone = None) -> BlobWriter:
        """Open a streamed blob of exactly ``length`` bytes. The length is
        required up front — the frame header precedes the data on the wire
        (reference: encode.js:77-100)."""
        if self.destroyed:
            raise EncoderDestroyedError("blob after destroy")
        if self.finalized:
            raise EncoderDestroyedError("blob after finalize")
        if not isinstance(length, int) or length <= 0:
            raise ValueError("blob length is required and must be > 0")
        # frame order is submission order: rows accumulated before this
        # blob must hit the wire before its header
        if self._batch_rows:
            self.flush_batch()
        ws = BlobWriter(self, length, on_flush)
        self.blobs += 1
        if _OBS.on:
            _M_ENC_BLOBS.inc()
        header = frame_header(length, TYPE_BLOB)
        if self._open_blobs:
            ws._cork()
            # the parked header reaches the wire at uncork time — the
            # frame's true wire offset is only known there (_uncork
            # tags it via this flag)
            ws._tag_on_uncork = True
            ws._park(header, None)
        else:
            if _OBS.on:
                _trace_instant("encoder.frame", offset=self.bytes,
                               kind="blob",
                               wire_len=len(header) + length)
                self._lit_cost_blob(length)
            self._push(header, None)
        self._open_blobs.append(ws)
        return ws

    def finalize(self, on_flush: OnDone = None) -> None:
        """Graceful end of session: after the queue drains, :meth:`read`
        reports EOF (reference: encode.js:119-122)."""
        if self.destroyed:
            raise EncoderDestroyedError("finalize after destroy")
        if self._open_blobs:
            raise EncoderDestroyedError(
                f"finalize with {len(self._open_blobs)} blob(s) still open"
            )
        if self._batch_rows:
            self.flush_batch()
        self.finalized = True
        self._finalize_cb = on_flush
        if not self._queue:
            if on_flush is not None:
                cb, self._finalize_cb = self._finalize_cb, None
                cb()
            self._fire_finish()
        if self._on_readable is not None:
            self._on_readable()  # let a connected pump observe EOF

    def read(self, max_bytes: int = -1) -> bytes | None:
        """Pull up to ``max_bytes`` of wire data (all buffered if -1).

        Returns ``b''`` when nothing is buffered yet, or ``None`` for EOF
        (finalized and fully drained). Firing of ``on_flush`` callbacks is
        tied to their bytes leaving this buffer — the pull-based analogue of
        the reference's `_read`-driven drain (reference: encode.js:147-151).
        """
        if self.destroyed:
            raise EncoderDestroyedError("read after destroy")
        if not self._queue and self._batch_rows:
            # uncork: a consumer pulling a dry queue gets what is
            # pending instead of waiting out the batch policy
            self.flush_batch()
        if not self._queue:
            if self.finalized:
                return None
            return b""
        out = bytearray()
        fired: list[Callable[[], None]] = []
        while self._queue and (max_bytes < 0 or len(out) < max_bytes):
            payload, cb = self._queue[0]
            room = len(payload) if max_bytes < 0 else max_bytes - len(out)
            if len(payload) <= room:
                out += payload
                self._queue.popleft()
                self._queued_bytes -= len(payload)
                if cb is not None:
                    fired.append(cb)
            else:
                out += payload[:room]
                self._queue[0] = (payload[room:], cb)
                self._queued_bytes -= room
                break
        data = bytes(out)
        if _OBS.on and data:
            _M_ENC_BYTES.inc(len(data))
        if self._journal is not None and data:
            # journal BEFORE the flush callbacks: when an on_flush hook
            # acks the journal window, the bytes it acks must be there
            self._journal.append(data)
        below = not self._above_high_water()
        for cb in fired:
            cb()
        if below and self._drain_cbs:
            cbs, self._drain_cbs = self._drain_cbs, []
            for cb in cbs:
                cb()
        if self.finalized and not self._queue:
            if self._finalize_cb is not None:
                cb, self._finalize_cb = self._finalize_cb, None
                cb()
            self._fire_finish()
        return data

    @property
    def buffered_bytes(self) -> int:
        return self._queued_bytes

    def writable(self) -> bool:
        return not self._above_high_water()

    def on_drain(self, cb: Callable[[], None]) -> None:
        """One-shot callback when the buffer falls below the high-water mark."""
        if self._above_high_water():
            self._drain_cbs.append(cb)
        else:
            cb()

    def on_error(self, cb: Callable[[Exception | None], None]) -> None:
        self._error_cbs.append(cb)

    def on_finish(self, cb: Callable[[], None]) -> None:
        """Terminal lifecycle hook, the encoder-side 'close': fires exactly
        once, after the finalized session has fully drained OR after destroy
        (in which case error callbacks fire first — the reference's
        'error' then 'close' ordering, reference: encode.js:73-74)."""
        if self.finished:
            cb()
        else:
            self._finish_cbs.append(cb)

    def _fire_finish(self) -> None:
        if self.finished:
            return
        self.finished = True
        cbs, self._finish_cbs = self._finish_cbs, []
        for cb in cbs:
            cb()

    def destroy(self, err: Exception | None = None) -> None:
        """Fail-fast teardown: destroys every open blob writer
        (reference: encode.js:69-75)."""
        if self.destroyed:
            return
        self.destroyed = True
        for ws in list(self._open_blobs):
            ws.destroyed = True
        self._open_blobs.clear()
        self._queue.clear()
        self._queued_bytes = 0
        self._parked_bytes = 0
        self._parked_changes.clear()
        self._batch_rows.clear()
        self._batch_cbs.clear()
        self._batch_pending_bytes = 0
        for cb in self._error_cbs:
            cb(err)
        # Release parked drain callbacks so a producer gated on the drain
        # signal wakes up and observes the destroyed state (mirrors the
        # decoder releasing its parked write callbacks on destroy).
        cbs, self._drain_cbs = self._drain_cbs, []
        for cb in cbs:
            cb()
        self._fire_finish()

    # -- internal -----------------------------------------------------------

    def _above_high_water(self) -> bool:
        return (self._queued_bytes + self._parked_bytes
                + self._batch_pending_bytes >= self._high_water)

    def _push(self, data, on_consumed: OnDone) -> bool:
        data = bytes(data)
        self.bytes += len(data)
        self._queue.append((data, on_consumed))
        self._queued_bytes += len(data)
        if self._on_readable is not None:
            self._on_readable()
        return not self._above_high_water()

    def _after_flush(self, cb: Callable[[], None]) -> None:
        """Run ``cb`` once everything currently queued has been read."""
        if not self._queue:
            cb()
            return
        payload, prev = self._queue[-1]
        if prev is None:
            self._queue[-1] = (payload, cb)
        else:
            def both(a=prev, b=cb):
                a()
                b()
            self._queue[-1] = (payload, both)

    def _blob_finished(self, ws: BlobWriter) -> None:
        """Head-of-line blob completed: uncork the next and replay parked
        changes (which re-park if blobs remain) — reference: encode.js:92-97."""
        if not self._open_blobs or self._open_blobs[0] is not ws:
            err = AssertionError("blob FIFO assertion failed")
            self.destroy(err)
            raise err
        self._open_blobs.popleft()
        if self._open_blobs:
            self._open_blobs[0]._uncork()
        parked, self._parked_changes = self._parked_changes, []
        for payload, cb, t0 in parked:
            if self._open_blobs:  # a later blob is still open: stay parked
                self._parked_changes.append((payload, cb, t0))
            else:
                self._parked_bytes -= len(payload)
                if t0 is not None and _OBS.on:
                    _H_ENC_PARK.observe(_now() - t0)
                self._frame_change(payload, cb)
