"""Session checkpoints and wire journals — resume instead of destroy.

The reference's answer to any mid-session failure is stream destruction
(reference: decode.js:104-110): the session's progress is simply lost.
This module adds the thin recovery layer over the *existing* session
state (no new protocol): the decoder can export a
:class:`SessionCheckpoint` at any instant, and a sender that kept its
produced wire bytes in a :class:`WireJournal` can replay exactly the
bytes past the checkpoint over a fresh connection.

Why a byte-offset checkpoint works: the decoder object survives a
transport failure untouched — its parser state (mid-header bytes,
mid-frame payload cursor, unparsed overflow) is all still there, so the
only thing a reconnect needs is *the next wire byte*.  ``wire_offset``
is ``decoder.bytes``, the count of wire bytes the decoder has accepted;
the journal hands back everything from that offset on.  No frame is
ever re-delivered (no duplicate deliveries) and none is skipped.

The other checkpoint fields — ``frame``, ``row``, ``blob_offset``, and
the per-backend ``digest`` state — are the coupled cursor tuple the
cursor-coherence datlint rule guards, exported for observability and
for the structured :class:`~..wire.framing.ProtocolError` context when
recovery fails.  See ROBUSTNESS.md for the full failure model.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ..obs.events import emit as _emit
from ..obs.metrics import OBS as _OBS, counter as _counter
from ..obs.watermarks import WATERMARKS as _WATERMARKS
from ..wire.framing import ProtocolError

__all__ = ["SessionCheckpoint", "WireJournal", "ResumeError"]

# Journal telemetry (OBSERVABILITY.md): replayed bytes are the resume
# cost a reconnect actually pays on the wire; acked bytes are the
# duplicate-suppressed history a resume can never re-deliver (trimmed,
# so a checkpoint below them is a structured ResumeError, not a silent
# replay from the wrong place).
_M_J_APPEND = _counter("journal.append.bytes")
_M_J_REPLAY = _counter("journal.replay.bytes")
_M_J_ACKED = _counter("journal.acked.bytes")


class ResumeError(ProtocolError):
    """A checkpoint that cannot be honored (e.g. the journal already
    trimmed past it).  Carries the standard structured context."""


@dataclasses.dataclass(frozen=True)
class SessionCheckpoint:
    """One instant of session progress, exported by ``Decoder.checkpoint()``.

    * ``wire_offset`` — wire bytes accepted by the decoder; the resume
      point (the sender replays from exactly here).
    * ``frame`` — frames fully delivered (changes + blobs).
    * ``row`` — change-row cursor (changes delivered so far).
    * ``blob_offset`` — payload bytes already delivered of the blob open
      at checkpoint time (0 at a frame boundary).
    * ``digest`` — backend digest-state (the TPU decoder records its
      emitted change/blob digest sequence counters so a resumed session
      continues numbering without gaps or repeats).
    """

    wire_offset: int
    frame: int = 0
    row: int = 0
    blob_offset: int = 0
    digest: dict = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict[str, Any]:
        """JSON-able form (the out-of-band resume handshake payload)."""
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SessionCheckpoint":
        return cls(
            wire_offset=int(d["wire_offset"]),
            frame=int(d.get("frame", 0)),
            row=int(d.get("row", 0)),
            blob_offset=int(d.get("blob_offset", 0)),
            digest=dict(d.get("digest", {})),
        )


class WireJournal:
    """Sender-side retention of produced wire bytes, replayable by offset.

    Attach to an encoder (``encoder.attach_journal(journal)``) and every
    byte ``read()`` hands to the transport is also recorded here.  On
    reconnect, ``read_from(checkpoint.wire_offset)`` returns the bytes
    the old connection lost.  ``ack(offset)`` trims delivered history
    once the receiver has confirmed it, bounding memory; resuming below
    the trimmed start raises :class:`ResumeError` (the session is then
    unrecoverable and must restart from scratch — the structured error
    says so instead of silently replaying from the wrong place).
    """

    def __init__(self):
        self._buf = bytearray()
        self._start = 0  # wire offset of _buf[0]
        # multi-reader acks (the fan-out precursor): with readers
        # attached, ack() trims only past the MINIMUM acked offset
        # across them — the single-reader assumption the original trim
        # baked in silently dropped a second reader's unread window
        self._readers: dict[str, int] = {}
        # fleet-plane link name (ISSUE 11): set by watermark(); while
        # set, appends note a monotonic mark so lag-in-seconds is
        # derivable entirely on this sender's clock
        self._wm_link: str | None = None

    @property
    def start(self) -> int:
        return self._start

    @property
    def end(self) -> int:
        return self._start + len(self._buf)

    def __len__(self) -> int:
        return len(self._buf)

    def append(self, data) -> None:
        self._buf += data
        if _OBS.on:
            _M_J_APPEND.inc(len(data))
            if self._wm_link is not None:
                _WATERMARKS.mark(self._wm_link, self.end)

    def watermark(self, link: str) -> None:
        """Export this journal's cursors on the fleet plane
        (OBSERVABILITY.md "Fleet plane"): ``append`` (bytes produced)
        and ``acked`` (trim floor) under ``link``, plus an append-time
        mark per journaled write so the aggregator can answer "how old
        is the oldest unreplicated byte" without any clock sync.
        Call :func:`~..obs.watermarks.WATERMARKS.untrack` with the same
        link when the session ends."""
        _WATERMARKS.track("append", link, lambda: self.end)
        _WATERMARKS.track("acked", link, lambda: self.start)
        self._wm_link = link

    def seek(self, offset: int) -> None:
        """Align an EMPTY journal's window to an absolute wire offset —
        used when attaching to an encoder that already emitted bytes
        (those bytes are unrecoverable; the window starts after them)."""
        if self._buf:
            raise ValueError("seek on a non-empty journal")
        self._start = offset

    def attach_reader(self, key: str, offset: int | None = None) -> str:
        """Register a named reader cursor at ``offset`` (default: the
        journal's retained start).  With any readers attached,
        :meth:`ack` becomes min-offset-aware: bytes trim only once
        EVERY reader has acked past them — the multi-reader contract
        the broadcast log builds on.

        Attaching below the retained window raises a structured
        :class:`ResumeError` naming the retained range — never a
        silent short read from the wrong place."""
        off = self._start if offset is None else int(offset)
        if off < self._start:
            if _OBS.on:
                _emit("journal.replay_miss", offset=off,
                      start=self._start)
            raise ResumeError(
                f"reader {key!r} asked for byte {off} below the "
                f"retained range [{self._start}, {self.end})",
                offset=off,
            )
        if off > self.end:
            raise ResumeError(
                f"reader {key!r} asked for byte {off} ahead of "
                f"everything produced (retained range "
                f"[{self._start}, {self.end}))",
                offset=off,
            )
        if key in self._readers:
            raise ValueError(f"reader {key!r} already attached")
        self._readers[key] = off
        return key

    def detach_reader(self, key: str) -> None:
        """Remove a reader cursor; its ack stops constraining the trim
        (re-ack with the remaining floor to release its window)."""
        self._readers.pop(key, None)

    def ack(self, offset: int, reader: str | None = None) -> None:
        """The receiver confirmed bytes below ``offset``: trim them.

        With reader cursors attached (:meth:`attach_reader`) the trim
        is min-offset-aware: a per-reader ack records that reader's
        progress and the journal trims only past the minimum across
        ALL readers; a bare ``ack(offset)`` is likewise floored by the
        slowest reader instead of silently dropping its window."""
        # an ack beyond production is a caller bug on EVERY path — the
        # reader-floor below must not silently mask it
        if offset > self.end:
            raise ValueError(
                f"ack({offset}) beyond journal end {self.end}")
        if reader is not None:
            if reader not in self._readers:
                raise ValueError(f"unknown reader {reader!r}")
            self._readers[reader] = max(self._readers[reader], offset)
            offset = min(self._readers.values())
        elif self._readers:
            offset = min([offset, *self._readers.values()])
        if offset <= self._start:
            return
        if _OBS.on:
            _M_J_ACKED.inc(offset - self._start)
        del self._buf[: offset - self._start]
        self._start = offset

    def read_from(self, offset: int) -> bytes:
        """Every journaled byte at ``offset`` and beyond (a copy: the
        journal may keep growing while the replay is in flight)."""
        if offset < self._start:
            if _OBS.on:
                _emit("journal.replay_miss", offset=offset,
                      start=self._start)
            raise ResumeError(
                "checkpoint predates the journal's retained window "
                f"(asked for byte {offset}, retained range "
                f"[{self._start}, {self.end}))",
                offset=offset,
            )
        if offset > self.end:
            if _OBS.on:
                _emit("journal.replay_miss", offset=offset, end=self.end)
            raise ResumeError(
                f"checkpoint is ahead of everything produced (byte {offset}, "
                f"journal ends at {self.end})",
                offset=offset,
            )
        out = bytes(self._buf[offset - self._start:])
        if _OBS.on:
            _M_J_REPLAY.inc(len(out))
            _emit("journal.replay", offset=offset, bytes=len(out))
        return out
