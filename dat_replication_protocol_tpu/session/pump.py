"""Kernel-bypass wire pump: batched-syscall transport loops (ISSUE 14).

The r06 capture located the host e2e floor in the Python wire path, not
the crypto: native hashing runs GiB/s while the pump loops in
:mod:`.transport` pay one interpreter round-trip per 64 KiB chunk —
``read_bytes`` call, ``decoder.write``, wake bookkeeping — and hold the
GIL for all of it.  Following the SmartNIC replication shape (PAPERS.md:
move the replication data plane below the host CPU), this module routes
the byte loops through the C extension instead:

* **Receive** (:func:`recv_pump`): one ``dat_pump_recv_scan`` call per
  slab — a blocking wakeup ``read``, a ``MSG_DONTWAIT`` ``recvmmsg``
  drain of whatever the kernel already buffered, and the native frame
  scan, all with the GIL released — then ONE
  :meth:`~.decoder.Decoder.write_indexed` hands the decoder the bytes
  plus the finished frame index.  Python sees only coalesced units:
  columnar ChangeBatch runs, blob extents as memoryviews, control
  frames individually (exactly what the decoder's bulk dispatch already
  surfaces).
* **Send** (:func:`send_pump`): megabyte pulls from the encoder pushed
  through ``dat_pump_send``'s gather loop (sendmmsg batches, writev
  fallback, partial acceptance resumed natively).
* **Fan-out gather** (:func:`send_spans_nb`): the broadcast hot path —
  BroadcastLog segment memoryviews go to the kernel as (address,
  length) spans through one non-blocking sendmmsg/writev batch per
  dispatcher turn.  Zero Python-owned payload bytes; the dispatcher
  keeps every window/ack/shed decision (ROBUSTNESS.md: the overload
  contract is unchanged, only the byte mover is).

**Route selection** (the ``DAT_CDC_ROUTE`` pattern): ``DAT_PUMP=python``
pins the portable reference pumps in :mod:`.transport`;
``DAT_PUMP=native`` (and the default, when the native library is
available) takes the batched loops.  Unrecognized values resolve to the
default.  Both routes are byte-identical — deliveries, digests,
checkpoints, and structured errors — enforced by the chaos parity
sweep (tests/test_pump_parity.py); the Python pump stays the portable
reference, never a second protocol.

Backpressure is the transport module's contract verbatim: the receive
pump stops calling into the kernel while the decoder stalls (the
kernel socket buffer absorbs the window), the send pump stops pulling
while the transport blocks.  PERF.md "Wire pump" has the syscall cost
model and the batch-size sweep.
"""

from __future__ import annotations

import os
import threading
from time import perf_counter as _perf
from typing import Callable, Optional

import numpy as np

from ..obs.metrics import OBS as _OBS, counter as _counter, \
    histogram as _histogram
from ..obs import wirecost as _wirecost
from ..runtime import native
from .decoder import Decoder, DecoderDestroyedError
from .encoder import Encoder, EncoderDestroyedError
from .transport import WAKE_FALLBACK, recv_over, send_over, \
    write_all as _write_all

__all__ = [
    "effective_pump_route", "recv_pump", "send_pump", "pump_reader",
    "pump_writer", "io_for_socket", "send_spans_nb", "probe_caps",
    "EdgePump", "recv_step", "send_step",
]

# receive slab geometry: cap bounds one pump call's batch (and the
# decoder's largest single bulk index); slice is the per-message recv
# size inside the batch.  Measured on the dev box (PERF.md sweep):
# 2 MiB / 1 MiB is ~1.3x the Python pump on the digest-session shape;
# smaller slices re-enter the interpreter per ~kernel-buffer-full.
PUMP_BUF = 2 << 20
PUMP_SLICE = 1 << 20
# send pull size: one encoder.read per native gather call
PUMP_SEND_CHUNK = 1 << 20

# transport.pump.* telemetry (OBSERVABILITY.md catalog), hoisted at
# import so the disabled path is one attribute load
_M_BATCHES = _counter("transport.pump.batches")
_M_MSGS = _counter("transport.pump.msgs")
_M_SYSCALLS = _counter("transport.pump.syscalls")
_M_SAVED = _counter("transport.pump.syscalls_saved")
_M_BYTES = _counter("transport.pump.bytes")
_M_GATHER_BYTES = _counter("transport.pump.gather.bytes")
_M_FALLBACK = _counter("transport.pump.route.python")
# time spent inside one native pump call — the GIL is released for the
# whole span, so this histogram IS the GIL-released time the batching
# buys back from the interpreter
_H_NATIVE = _histogram("transport.pump.native.seconds")


def effective_pump_route() -> str:
    """The ONE owner of pump-route resolution (the
    ``DAT_CDC_ROUTE``/``effective_route`` pattern): consult ``DAT_PUMP``
    (``native`` / ``python``), defaulting to ``native`` when the C
    engine is loadable; unrecognized values resolve to the default, and
    ``native`` silently degrades to ``python`` on toolchain-less hosts
    — the route that runs is always a route that exists."""
    route = os.environ.get("DAT_PUMP")
    if route == "python":
        return "python"
    return "native" if native.available() else "python"


def probe_caps() -> dict:
    """Snapshot of the pump's runtime probe — what ``--stats-fd``
    records carry so an operator can see which syscall tier a host
    actually serves (the probe never gates the pump: each call
    degrades per-fd)."""
    caps = native.pump_probe()
    return {
        "route": effective_pump_route(),
        "native_available": caps is not None,
        "recvmmsg": bool(caps & 1) if caps is not None else False,
        "sendmmsg": bool(caps & 2) if caps is not None else False,
    }


class _RecvState:
    """Per-pump-loop native index buffers, allocated once per session.

    The receive SLAB is not here: each batch lands in a fresh
    allocation handed to the decoder as a zero-copy view (the decoder
    may pin slices in its overflow/bulk cursors arbitrarily long, and
    re-reading into a shared buffer under them would corrupt the wire
    — while copying out of it, the alternative, costs a second pass
    over every byte)."""

    __slots__ = ("cap", "starts", "lens", "ids", "stats")

    def __init__(self, cap: int):
        self.cap = cap
        # index capacity is sized for the TYPICAL frame density, not
        # the 2-byte worst case (that would be ~17 bytes of index per
        # 2 wire bytes, per session): a denser slab comes back as a
        # valid partial index and its tail re-enters the decoder's
        # overflow — correctness never depends on icap
        icap = cap // 16 + 1
        self.starts = np.empty(icap, dtype=np.int64)
        self.lens = np.empty(icap, dtype=np.int64)
        self.ids = np.empty(icap, dtype=np.uint8)
        self.stats = np.zeros(2, dtype=np.int64)


def _lit_rx(decoder, nbytes: int) -> None:
    """Lit-side transport ground truth, receive direction (ISSUE 20):
    the pump IS the transport, so raw received bytes anchor the wire
    cost ledger's tiling audit.  Callers hold the ``_OBS.on`` gate —
    the hot loops stay bytecode-free of this module's plane."""
    _wirecost.note_transport(
        getattr(decoder, "cost_link", "session"), "rx", nbytes)


def _lit_tx(encoder, nbytes: int) -> None:
    """Lit-side transport ground truth, send direction (ISSUE 20)."""
    _wirecost.note_transport(
        getattr(encoder, "cost_link", "session"), "tx", nbytes)


def _metered_reader(decoder, read_bytes):
    """Wrap a python-route ``read_bytes`` so the fallback pump reports
    the same transport ground truth the native loop does (per-read
    ``_OBS.on`` fork: the dark path adds one attribute load)."""
    def metered(n: int) -> bytes:
        data = read_bytes(n)
        if data and _OBS.on:
            _lit_rx(decoder, len(data))
        return data

    return metered


def _note_batch(nbytes: int, stats) -> None:
    syscalls = int(stats[0])
    msgs = int(stats[1])
    _M_BATCHES.inc()
    _M_MSGS.inc(msgs)
    _M_SYSCALLS.inc(syscalls)
    if msgs > syscalls:
        _M_SAVED.inc(msgs - syscalls)
    _M_BYTES.inc(nbytes)


def recv_pump(decoder: Decoder, fd: int,
              tap: Optional[Callable[[bytes], None]] = None,
              cap: int = PUMP_BUF) -> None:
    """Pump ``fd`` into ``decoder`` until EOF or destroy, batched.

    The native twin of :func:`.transport.recv_over` (same flow-control
    contract: reading suspends while the decoder stalls, resuming on
    its drain watcher).  ``tap`` observes every received slab as the
    exact ``bytes`` object the decoder is fed — the fan-out source's
    publish hook, byte-identical to wrapping ``read_bytes``.  Falls
    back to the Python pump when the route (or the library) says so.
    """
    if effective_pump_route() != "native":
        if _OBS.on:
            _M_FALLBACK.inc()
        read_bytes = _tapped_reader(fd, tap)
        recv_over(decoder, _metered_reader(decoder, read_bytes))
        return
    st = _RecvState(cap)
    wake = threading.Event()
    decoder._add_drain_watcher(wake.set)
    try:
        while not decoder.destroyed:
            buf = np.empty(st.cap, dtype=np.uint8)  # fresh: see _RecvState
            t0 = _perf()
            r = native.pump_recv_scan(fd, buf, PUMP_SLICE, st.starts,
                                      st.lens, st.ids, st.stats)
            if r is None:  # library vanished mid-session (tests reset)
                recv_over(decoder,
                          _metered_reader(decoder, _tapped_reader(fd, tap)))
                return
            nbytes, nframes, consumed, _err = r
            if _OBS.on:
                _H_NATIVE.observe(_perf() - t0)
            if nbytes == 0:
                if not decoder.destroyed and not decoder.finished:
                    decoder.end()
                return
            if nbytes < 0:
                raise OSError(-nbytes, os.strerror(-nbytes))
            if _OBS.on:
                _note_batch(nbytes, st.stats)
                _lit_rx(decoder, nbytes)
            # zero-copy handoff: the decoder owns this slab's memory
            # from here (its cursors may pin slices of it); the tap
            # sees the same bytes as one read-only view
            data = memoryview(buf)[:nbytes]
            if tap is not None:
                # the broadcast tee (FanoutServer.publish): an append +
                # O(1) mark under the server lock — never blocks
                # datlint: allow-callback-escape
                tap(data)
            wake.clear()
            try:
                ok = decoder.write_indexed(data, st.starts, st.lens,
                                           st.ids, nframes, consumed)
            except DecoderDestroyedError:
                return
            if not ok:
                while not (decoder.writable() or decoder.destroyed
                           or decoder.finished):
                    wake.wait(WAKE_FALLBACK)
                    wake.clear()
    finally:
        decoder._remove_drain_watcher(wake.set)


def _tapped_reader(fd: int, tap) -> Callable[[int], bytes]:
    if tap is None:
        return lambda n: os.read(fd, n)

    def read_bytes(n: int) -> bytes:
        data = os.read(fd, n)
        if data:
            tap(data)
        return data

    return read_bytes


def send_pump(encoder: Encoder, fd: int,
              close: Optional[Callable[[], None]] = None,
              on_progress: Optional[Callable[[], None]] = None) -> None:
    """Pump ``encoder`` to ``fd`` until EOF or destroy, batched.

    The native twin of :func:`.transport.send_over`: megabyte pulls,
    each pushed through one GIL-released native gather call that owns
    the partial-write resume loop.  ``on_progress`` fires after every
    accepted batch (the sidecar's reply-stall clock).  Falls back to
    the Python pump when the route (or the library) says so."""
    if effective_pump_route() != "native":
        if _OBS.on:
            _M_FALLBACK.inc()

        def write_bytes(data) -> None:
            _write_all(fd, data)
            if _OBS.on and len(data):
                _lit_tx(encoder, len(data))
            if on_progress is not None:
                on_progress()

        send_over(encoder, write_bytes, close=close)
        return
    addrs = np.zeros(1, dtype=np.int64)
    lens = np.zeros(1, dtype=np.int64)
    stats = np.zeros(2, dtype=np.int64)
    readable = threading.Event()
    encoder._attach_readable(readable.set)
    # wake hook only: sets an Event, never blocks (ISSUE 17 satellite)
    # datlint: allow-callback-escape
    encoder.on_error(lambda _e: readable.set())
    try:
        while True:
            try:
                data = encoder.read(PUMP_SEND_CHUNK)
            except EncoderDestroyedError:
                break
            if data is None:  # finalized and drained
                break
            if not data:
                readable.wait(WAKE_FALLBACK)
                readable.clear()
                continue
            arr = np.frombuffer(data, dtype=np.uint8)
            addrs[0] = arr.__array_interface__["data"][0]
            lens[0] = len(data)
            t0 = _perf()
            # `data`/`arr` stay referenced (bytes pinned) for the call
            w = native.pump_send_spans(fd, addrs, lens, 1, stats)
            if _OBS.on:
                _H_NATIVE.observe(_perf() - t0)
            if w is None:  # library vanished mid-session: finish plain
                _write_all(fd, data)
                w = len(data)
            elif w < 0:
                raise OSError(-w, os.strerror(-w))
            if _OBS.on:
                _note_batch(int(w), stats)
                _lit_tx(encoder, int(w))
            if on_progress is not None:
                # the sidecar's reply-stall clock: one monotonic read
                # datlint: allow-callback-escape
                on_progress()
    finally:
        encoder._detach_readable()
        if close is not None:
            try:
                # a shutdown/close syscall on the way out — bounded
                # datlint: allow-callback-escape
                close()
            except OSError:
                pass


def pump_reader(fd: int, cap: int = PUMP_BUF) -> Callable[[int], bytes]:
    """A ``read_bytes`` drop-in serving batched native receives — the
    pump selector for callers that feed decoders through callables
    (the reconcile/snapshot drivers' ``recv_over`` surface).  May
    return MORE than the requested hint (every call site feeds a
    decoder, which takes any chunking); EOF is ``b""``, transport
    errors raise ``OSError`` — the ``os.read`` contract."""
    if effective_pump_route() != "native":
        return lambda n: os.read(fd, n)
    # reusable slab: unlike recv_pump's zero-copy handoff, this surface
    # returns an owned bytes per call (the os.read contract), so the
    # buffer can be recycled.  The index arrays are 1-element on
    # purpose: this caller feeds a decoder through write() (the index
    # would be thrown away), and a full index array would make the
    # native call frame-scan every slab for nothing — capacity overflow
    # stops the scan after one frame
    buf = np.empty(cap, dtype=np.uint8)
    starts = np.zeros(1, dtype=np.int64)
    lens = np.zeros(1, dtype=np.int64)
    ids = np.zeros(1, dtype=np.uint8)
    stats = np.zeros(2, dtype=np.int64)

    def read_bytes(_hint: int) -> bytes:
        t0 = _perf()
        r = native.pump_recv_scan(fd, buf, PUMP_SLICE, starts,
                                  lens, ids, stats)
        if r is None:
            return os.read(fd, _hint)
        nbytes = r[0]
        if _OBS.on:
            _H_NATIVE.observe(_perf() - t0)
        if nbytes < 0:
            raise OSError(-nbytes, os.strerror(-nbytes))
        if nbytes == 0:
            return b""
        if _OBS.on:
            _note_batch(nbytes, stats)
        return buf[:nbytes].tobytes()

    return read_bytes


def pump_writer(fd: int) -> Callable[[bytes], None]:
    """A ``write_bytes`` drop-in pushing through the native gather loop
    (blocking; partial writes resumed natively) — the send-side twin of
    :func:`pump_reader`."""
    if effective_pump_route() != "native":
        return lambda data: _write_all(fd, data)
    addrs = np.zeros(1, dtype=np.int64)
    lens = np.zeros(1, dtype=np.int64)
    stats = np.zeros(2, dtype=np.int64)

    def write_bytes(data) -> None:
        if not len(data):
            return
        arr = np.frombuffer(data, dtype=np.uint8)
        addrs[0] = arr.__array_interface__["data"][0]
        lens[0] = len(arr)
        t0 = _perf()
        w = native.pump_send_spans(fd, addrs, lens, 1, stats)
        if w is None:
            _write_all(fd, data)
            return
        if _OBS.on:
            _H_NATIVE.observe(_perf() - t0)
        if w < 0:
            raise OSError(-w, os.strerror(-w))
        if _OBS.on:
            _note_batch(int(w), stats)

    return write_bytes


def io_for_socket(conn) -> tuple:
    """``(read_bytes, write_bytes)`` for a connected socket through the
    pump selector: the batched native reader/writer when routed (the
    reconcile/snapshot drivers' transports upgrade with zero new
    flags), the plain socket calls otherwise."""
    if effective_pump_route() != "native":
        return conn.recv, conn.sendall
    return pump_reader(conn.fileno()), pump_writer(conn.fileno())


class SpanGather:
    """Reusable (address, length) span arrays for the fan-out gather
    path: one instance per dispatcher, refilled per serve turn —
    payload bytes never become Python objects, only their addresses
    do."""

    __slots__ = ("addrs", "lens", "stats", "_arrs")

    def __init__(self, cap: int = 1024):
        self.addrs = np.zeros(cap, dtype=np.int64)
        self.lens = np.zeros(cap, dtype=np.int64)
        self.stats = np.zeros(2, dtype=np.int64)
        self._arrs: list = []  # keeps span buffers pinned across a call

    def fill(self, views) -> int:
        """Load ``views`` (memoryviews/bytes) as spans; returns the
        count.  The numpy wraps are zero-copy — addresses point into
        the callers' buffers, which this object pins until the next
        :meth:`fill`."""
        n = len(views)
        if n > len(self.addrs):
            self.addrs = np.zeros(n, dtype=np.int64)
            self.lens = np.zeros(n, dtype=np.int64)
        arrs = []
        for i, v in enumerate(views):
            a = np.frombuffer(v, dtype=np.uint8)
            arrs.append(a)
            self.addrs[i] = a.__array_interface__["data"][0]
            self.lens[i] = len(a)
        self._arrs = arrs
        return n

    def release(self) -> None:
        self._arrs = []


class EdgePump:
    """Per-session pump state for the event-driven edge (ISSUE 17):
    the batched-syscall primitives of this module, re-cut as ONE
    bounded non-blocking turn per call instead of a thread-owned loop.

    ``fd`` MUST be non-blocking — the edge loop sets ``O_NONBLOCK``
    at admission and never clears it; every kernel call below is
    bounded by that flag (would-block returns immediately), which is
    what lets :meth:`EdgeLoop._dispatch_loop` inline these sites and
    still certify ``bounded-blocking``.  The native route degrades
    per-call to plain ``os.read``/``os.write`` exactly like the
    thread pumps (the route that runs is always a route that
    exists)."""

    __slots__ = ("fd", "cap", "recv_st", "pending", "gather", "native")

    def __init__(self, fd: int, cap: int = PUMP_BUF):
        self.fd = fd
        self.cap = cap
        self.native = effective_pump_route() == "native"
        self.recv_st = _RecvState(cap) if self.native else None
        self.gather = SpanGather(cap=1) if self.native else None
        self.pending: Optional[memoryview] = None  # unsent reply tail


def recv_step(pump: EdgePump, decoder: Decoder, tap=None) -> tuple:
    """ONE bounded receive turn: drain what the kernel already
    buffered on ``pump.fd`` into ``decoder``, never waiting.  Returns
    ``(nbytes, eof)``; ``(0, False)`` means would-block (wait for the
    selector's next READ event).  Native route: one
    ``dat_pump_recv_scan`` batch (its first ``read`` returns
    ``-EAGAIN`` on the non-blocking fd instead of sleeping) feeding
    ``decoder.write_indexed``; Python route: ``os.read`` until
    ``EAGAIN``, EOF, decoder stall, or the ``PUMP_BUF`` turn budget —
    a faulted neighbor can cost this session at most one slab of
    latency per turn."""
    if pump.native:
        st = pump.recv_st
        buf = np.empty(st.cap, dtype=np.uint8)  # fresh: see _RecvState
        t0 = _perf()
        r = native.pump_recv_scan(pump.fd, buf, PUMP_SLICE, st.starts,
                                  st.lens, st.ids, st.stats)
        if r is None:  # library vanished mid-session (tests reset)
            pump.native = False
            return recv_step(pump, decoder, tap)
        nbytes, nframes, consumed, _err = r
        if _OBS.on:
            _H_NATIVE.observe(_perf() - t0)
        if nbytes in (-11, -4):  # EAGAIN / EINTR: retry next turn
            return (0, False)
        if nbytes < 0:
            raise OSError(-nbytes, os.strerror(-nbytes))
        if nbytes == 0:
            return (0, True)
        if _OBS.on:
            _note_batch(nbytes, st.stats)
            _lit_rx(decoder, nbytes)
        data = memoryview(buf)[:nbytes]
        if tap is not None:
            # the broadcast tee (FanoutServer.publish): an append +
            # O(1) mark under the server lock — never blocks the loop
            # datlint: allow-callback-escape
            tap(data)
        try:
            decoder.write_indexed(data, st.starts, st.lens, st.ids,
                                  nframes, consumed)
        except DecoderDestroyedError:
            pass  # the loop's teardown predicate sees dec.destroyed
        return (nbytes, False)
    res = _recv_step_py(pump, decoder, tap)
    if _OBS.on and res[0]:
        _lit_rx(decoder, res[0])
    return res


def _recv_step_py(pump: EdgePump, decoder: Decoder, tap=None) -> tuple:
    """The python arm of :func:`recv_step` (one bounded ``os.read``
    turn); split out so the transport ground-truth noting forks ONCE on
    the final byte total instead of at every return point."""
    total = 0
    while total < pump.cap:
        try:
            # bounded: pump.fd is O_NONBLOCK by the EdgePump contract
            # — a stalled peer surfaces as BlockingIOError, never a
            # sleeping read under the loop
            # datlint: allow-blocking-reachable(os-io)
            data = os.read(pump.fd, PUMP_SLICE)
        except BlockingIOError:
            return (total, False)
        except InterruptedError:
            continue
        if not data:
            return (total, True)
        total += len(data)
        if tap is not None:
            # same broadcast tee as the native arm above
            # datlint: allow-callback-escape
            tap(data)
        try:
            ok = decoder.write(data)
        except DecoderDestroyedError:
            return (total, False)
        if not ok:
            return (total, False)  # decoder stall: the loop gates reads
    return (total, False)


# one send turn pushes at most this many pulls — the encoder's
# high-water mark bounds what it can buffer, this bounds the turn even
# against a pathological producer
_SEND_TURN_PULLS = 8


def send_step(pump: EdgePump, encoder: Encoder) -> tuple:
    """ONE bounded send turn: push encoder output to ``pump.fd`` until
    would-block, the encoder runs dry, or the turn budget.  Returns
    ``(accepted, finished, blocked)`` — ``finished`` means the encoder
    is finalized AND fully drained (reply EOF: the loop may shut down
    the write half); ``blocked`` means the kernel refused bytes we
    still hold (watch ``EVENT_WRITE``).  Native route:
    :func:`send_spans_nb` gather batches; Python route: non-blocking
    ``os.write`` with the partial tail stashed in ``pump.pending``."""
    res = _send_step_impl(pump, encoder)
    if _OBS.on and res[0]:
        _lit_tx(encoder, res[0])
    return res


def _send_step_impl(pump: EdgePump, encoder: Encoder) -> tuple:
    """The engine of :func:`send_step`; split out so the transport
    ground-truth noting forks ONCE on the turn's accepted-byte total
    instead of at every return point."""
    accepted = 0
    for _ in range(_SEND_TURN_PULLS):
        if pump.pending is None:
            try:
                data = encoder.read(PUMP_SEND_CHUNK)
            except EncoderDestroyedError:
                return (accepted, True, False)
            if data is None:  # finalized and drained
                return (accepted, True, False)
            if not data:  # nothing ready (producer still appending)
                return (accepted, False, False)
            pump.pending = memoryview(data) if not isinstance(
                data, memoryview) else data
        view = pump.pending
        if pump.native:
            n = pump.gather.fill([view])
            try:
                w = send_spans_nb(pump.fd, pump.gather, n)
            except OSError as e:
                if e.errno == 38:  # ENOSYS: library vanished, degrade
                    pump.native = False
                    continue
                raise
            finally:
                pump.gather.release()
        else:
            try:
                # bounded: pump.fd is O_NONBLOCK by the EdgePump
                # contract — would-block is an exception, not a sleep
                # datlint: allow-blocking-reachable(os-io)
                w = os.write(pump.fd, view)
            except BlockingIOError:
                w = 0
            except InterruptedError:
                w = 0
        accepted += w
        if w < len(view):
            pump.pending = view[w:] if w else view
            return (accepted, False, True)
        pump.pending = None
    return (accepted, False, False)


def send_spans_nb(fd: int, gather: SpanGather, n: int) -> int:
    """Push ``n`` loaded spans to non-blocking ``fd`` through one
    native gather batch (sendmmsg/writev until EAGAIN).  Returns bytes
    accepted (0 = would-block); raises ``OSError`` on a dead transport
    — exactly the ``os.writev`` contract the fan-out dispatcher's
    bookkeeping is written against."""
    t0 = _perf()
    w = native.pump_send_spans(fd, gather.addrs, gather.lens, n,
                               gather.stats, nonblocking=True)
    if w is None:
        raise OSError(38, "native pump unavailable")  # ENOSYS
    if _OBS.on:
        _H_NATIVE.observe(_perf() - t0)
    if w < 0:
        raise OSError(-w, os.strerror(-w))
    if _OBS.on and w:
        _note_batch(w, gather.stats)
        _M_GATHER_BYTES.inc(w)
    return w
