"""asyncio transport pumps: a session over non-blocking byte streams.

The reference's native habitat is Node's event loop — `pipe()` composes
with any async stream and backpressure propagates through `write()`
return values and `'drain'` events (reference: example.js:53,
decode.js:87-99,168).  :mod:`.transport` covers blocking sockets/fds
with thread pumps; this module is the single-threaded event-loop
equivalent over :mod:`asyncio` streams:

* **Sender**: pulls :meth:`Encoder.read` and writes to a
  ``StreamWriter``; ``await writer.drain()`` is the congestion stall
  (the kernel send buffer pushes back through asyncio's flow control).
  An empty pull awaits the encoder's readable event.
* **Receiver**: feeds ``StreamReader`` chunks to :meth:`Decoder.write`;
  when the decoder stalls on an outstanding app ``done``, the pump
  awaits the write-completion callback before reading on — so the
  kernel receive buffer (not host RAM) absorbs the in-flight window.
  Everything runs on one event loop, so unlike the threaded pump there
  is no lost-wakeup window and no polling fallback.

App callbacks fire on the event loop thread; ``done`` acks may be
issued synchronously or deferred to any later task/callback on the
same loop.
"""

from __future__ import annotations

import asyncio

from .decoder import Decoder, DecoderDestroyedError
from .encoder import Encoder, EncoderDestroyedError
from .transport import DEFAULT_CHUNK


async def send_over_async(
    encoder: Encoder,
    writer: asyncio.StreamWriter,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Pump ``encoder`` into an asyncio writer until EOF or destroy."""
    readable = asyncio.Event()
    encoder._attach_readable(readable.set)
    encoder.on_error(lambda _e: readable.set())
    try:
        while True:
            try:
                data = encoder.read(chunk_size)
            except EncoderDestroyedError:
                break
            if data is None:  # finalized and drained
                break
            if not data:
                await readable.wait()
                readable.clear()
                continue
            try:
                writer.write(bytes(data))
                await writer.drain()  # congestion backpressure
            except OSError as e:  # incl. every ConnectionError subclass
                # peer gone mid-session: nothing downstream will read
                # these bytes — cascade into the encoder (failure
                # semantics: destroy releases parked callbacks) and stop
                if not encoder.destroyed:
                    encoder.destroy(e)
                break
    finally:
        encoder._detach_readable()
        try:
            if writer.can_write_eof():
                writer.write_eof()
        except (OSError, RuntimeError):
            pass


async def recv_over_async(
    decoder: Decoder,
    reader: asyncio.StreamReader,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Pump an asyncio reader into ``decoder`` until EOF or destroy."""
    while not decoder.destroyed:
        try:
            data = await reader.read(chunk_size)
        except OSError as e:
            # peer reset mid-frame: cascade so the app's on_error fires
            # (a decoder already destroyed/finished — e.g. the session's
            # deliberate abort after an app-side destroy — stays as-is)
            if not decoder.destroyed and not decoder.finished:
                decoder.destroy(e)
            return
        if not data:
            if not decoder.destroyed and not decoder.finished:
                decoder.end()
            return
        drained = asyncio.Event()
        try:
            consumed = decoder.write(data, on_consumed=drained.set)
        except DecoderDestroyedError:
            return
        if not consumed:
            # single-threaded: the ack that drains the decoder runs on
            # this loop, so the event cannot be missed (contrast the
            # threaded pump's bounded poll, transport.py:recv_over)
            await drained.wait()


async def session_over_asyncio(
    encoder: Encoder,
    decoder: Decoder,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Run a whole session over a kernel socketpair on the event loop.

    Opens both ends, pumps concurrently, returns when the sender has
    flushed EOF and the receiver has finished (or either destroyed).
    """
    import socket

    a, b = socket.socketpair()
    a.setblocking(False)
    b.setblocking(False)
    writers: list[asyncio.StreamWriter] = []
    send_task = recv_task = None
    try:
        _, writer = await asyncio.open_connection(sock=a)
        writers.append(writer)  # immediately: if the second open raises,
        # the finally must still tear this transport down
        reader, writer_b = await asyncio.open_connection(sock=b)
        writers.append(writer_b)
        send_task = asyncio.ensure_future(
            send_over_async(encoder, writer, chunk_size)
        )
        recv_task = asyncio.ensure_future(
            recv_over_async(decoder, reader, chunk_size)
        )
        done, pending = await asyncio.wait(
            {send_task, recv_task}, return_when=asyncio.FIRST_COMPLETED
        )
        if pending and recv_task in done:
            # receiver exited early (destroy): nothing will ever read
            # the socket again.  Abort the transports (fails a sender
            # blocked in drain()) AND destroy the encoder (wakes a
            # sender parked in readable.wait() on an idle encoder — the
            # destroy releases parked callbacks and fires on_error,
            # which sets the readable event)
            for w in writers:
                w.transport.abort()
            if not encoder.destroyed:
                encoder.destroy(ConnectionAbortedError("receiver gone"))
        await asyncio.gather(send_task, recv_task)
    finally:
        # one pump failing must not orphan the other (asyncio would log
        # "Task exception was never retrieved" when the closed sockets
        # fail it later)
        for t in (send_task, recv_task):
            if t is not None and not t.done():
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        # abort, not close: a flushing close on a congested transport
        # waits for a peer that may never read (teardown must not hang);
        # on the normal path the sender already drained every write, so
        # nothing is discarded
        for w in writers:
            try:
                w.transport.abort()
                w.close()
            except (OSError, RuntimeError):
                pass
        for w in writers:
            try:
                await w.wait_closed()
            except (OSError, RuntimeError):
                pass
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass
