"""asyncio transport pumps: a session over non-blocking byte streams.

The reference's native habitat is Node's event loop — `pipe()` composes
with any async stream and backpressure propagates through `write()`
return values and `'drain'` events (reference: example.js:53,
decode.js:87-99,168).  :mod:`.transport` covers blocking sockets/fds
with thread pumps; this module is the single-threaded event-loop
equivalent over :mod:`asyncio` streams:

* **Sender**: pulls :meth:`Encoder.read` and writes to a
  ``StreamWriter``; ``await writer.drain()`` is the congestion stall
  (the kernel send buffer pushes back through asyncio's flow control).
  An empty pull awaits the encoder's readable event.
* **Receiver**: feeds ``StreamReader`` chunks to :meth:`Decoder.write`;
  when the decoder stalls on an outstanding app ``done``, the pump
  awaits the write-completion callback before reading on — so the
  kernel receive buffer (not host RAM) absorbs the in-flight window.
  Everything runs on one event loop, so unlike the threaded pump there
  is no lost-wakeup window and no polling fallback.

App callbacks fire on the event loop thread; ``done`` acks may be
issued synchronously or deferred to any later task/callback on the
same loop.
"""

from __future__ import annotations

import asyncio

from .decoder import Decoder, DecoderDestroyedError
from .encoder import Encoder, EncoderDestroyedError
from .transport import DEFAULT_CHUNK


async def send_over_async(
    encoder: Encoder,
    writer: asyncio.StreamWriter,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Pump ``encoder`` into an asyncio writer until EOF or destroy."""
    readable = asyncio.Event()
    encoder._on_readable = readable.set
    encoder.on_error(lambda _e: readable.set())
    try:
        while True:
            try:
                data = encoder.read(chunk_size)
            except EncoderDestroyedError:
                break
            if data is None:  # finalized and drained
                break
            if not data:
                await readable.wait()
                readable.clear()
                continue
            writer.write(bytes(data))
            await writer.drain()  # congestion backpressure
    finally:
        try:
            if writer.can_write_eof():
                writer.write_eof()
        except (OSError, RuntimeError):
            pass


async def recv_over_async(
    decoder: Decoder,
    reader: asyncio.StreamReader,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Pump an asyncio reader into ``decoder`` until EOF or destroy."""
    while not decoder.destroyed:
        data = await reader.read(chunk_size)
        if not data:
            if not decoder.destroyed and not decoder.finished:
                decoder.end()
            return
        drained = asyncio.Event()
        try:
            consumed = decoder.write(data, on_consumed=drained.set)
        except DecoderDestroyedError:
            return
        if not consumed:
            # single-threaded: the ack that drains the decoder runs on
            # this loop, so the event cannot be missed (contrast the
            # threaded pump's bounded poll, transport.py:recv_over)
            await drained.wait()


async def session_over_asyncio(
    encoder: Encoder,
    decoder: Decoder,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Run a whole session over a kernel socketpair on the event loop.

    Opens both ends, pumps concurrently, returns when the sender has
    flushed EOF and the receiver has finished (or either destroyed).
    """
    import socket

    a, b = socket.socketpair()
    a.setblocking(False)
    b.setblocking(False)
    writers = []
    send_task = recv_task = None
    try:
        _, writer = await asyncio.open_connection(sock=a)
        reader, writer_b = await asyncio.open_connection(sock=b)
        writers = [writer, writer_b]
        send_task = asyncio.ensure_future(
            send_over_async(encoder, writer, chunk_size)
        )
        recv_task = asyncio.ensure_future(
            recv_over_async(decoder, reader, chunk_size)
        )
        await asyncio.gather(send_task, recv_task)
    finally:
        # one pump failing must not orphan the other (asyncio would log
        # "Task exception was never retrieved" when the closed sockets
        # fail it later)
        for t in (send_task, recv_task):
            if t is not None and not t.done():
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        # close via the transports (closing only the raw sockets leaves
        # the StreamWriter transports registered with the loop)
        for w in writers:
            try:
                w.close()
                await w.wait_closed()
            except (OSError, RuntimeError):
                pass
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass
