"""asyncio transport pumps: a session over non-blocking byte streams.

The reference's native habitat is Node's event loop — `pipe()` composes
with any async stream and backpressure propagates through `write()`
return values and `'drain'` events (reference: example.js:53,
decode.js:87-99,168).  :mod:`.transport` covers blocking sockets/fds
with thread pumps; this module is the single-threaded event-loop
equivalent over :mod:`asyncio` streams:

* **Sender**: pulls :meth:`Encoder.read` and writes to a
  ``StreamWriter``; ``await writer.drain()`` is the congestion stall
  (the kernel send buffer pushes back through asyncio's flow control).
  An empty pull awaits the encoder's readable event.
* **Receiver**: feeds ``StreamReader`` chunks to :meth:`Decoder.write`;
  when the decoder stalls on an outstanding app ``done``, the pump
  awaits the write-completion callback before reading on — so the
  kernel receive buffer (not host RAM) absorbs the in-flight window.
  Everything runs on one event loop, so unlike the threaded pump there
  is no lost-wakeup window and no polling fallback.

App callbacks fire on the event loop thread; ``done`` acks may be
issued synchronously or deferred to any later task/callback on the
same loop.
"""

from __future__ import annotations

import asyncio

from ..obs.events import emit as _emit
from ..obs.metrics import OBS as _OBS, counter as _counter
from ..wire.framing import ProtocolError
from .decoder import Decoder, DecoderDestroyedError
from .encoder import Encoder, EncoderDestroyedError
from .transport import DEFAULT_CHUNK, WAKE_FALLBACK

# Wakeup attribution for the event-loop pumps, the asyncio twin of
# transport.py's recv/send counters (OBSERVABILITY.md)
_M_AIO_WAKE_EVENT = _counter("aio.wake.event")
_M_AIO_WAKE_POLL = _counter("aio.wake.poll")


async def _bounded_wait(event: asyncio.Event) -> None:
    """Await ``event`` with the guarded-fallback bound: the waiter wakes
    on the event OR after :data:`~.transport.WAKE_FALLBACK` seconds and
    re-checks its loop condition — a lost wakeup degrades to a short
    delay instead of a parked-forever pump (the bounded-wait doctrine,
    ROBUSTNESS.md; enforced package-wide by datlint's bounded-wait
    rule)."""
    try:
        await asyncio.wait_for(event.wait(), WAKE_FALLBACK)
        if _OBS.on:
            _M_AIO_WAKE_EVENT.inc()
    except asyncio.TimeoutError:
        if _OBS.on:
            _M_AIO_WAKE_POLL.inc()


async def _drain_with_stall_detect(encoder: Encoder,
                                   writer: asyncio.StreamWriter,
                                   stall_timeout: float) -> bool:
    """Drain with a PROGRESS deadline, not a completion deadline: a
    slow-but-live peer (buffer shrinking) re-arms the stall clock every
    ``stall_timeout`` window; only a peer whose window made no progress
    at all is declared stalled (structured error, encoder destroyed).
    Returns False when the session was failed."""
    while True:
        before = writer.transport.get_write_buffer_size()
        try:
            await asyncio.wait_for(writer.drain(), stall_timeout)
            return True
        except asyncio.TimeoutError:
            if writer.transport.get_write_buffer_size() < before:
                continue  # the peer IS reading, just slowly: re-arm
            if _OBS.on:
                _emit("session.stall", kind="peer-drain",
                      seconds=stall_timeout, offset=encoder.bytes)
            err = ProtocolError(
                f"peer stalled: no drain progress for {stall_timeout}s",
                offset=encoder.bytes,
            )
            if not encoder.destroyed:
                encoder.destroy(err)
            return False


async def send_over_async(
    encoder: Encoder,
    writer: asyncio.StreamWriter,
    chunk_size: int = DEFAULT_CHUNK,
    stall_timeout: float | None = None,
) -> None:
    """Pump ``encoder`` into an asyncio writer until EOF or destroy.

    ``stall_timeout`` bounds drain *progress*, not completion: a peer
    that reads nothing for that long fails the session with a structured
    :class:`~..wire.framing.ProtocolError` instead of parking this task
    forever, while a slow-but-live peer (send buffer still shrinking)
    re-arms the clock each window; ``None`` trusts the peer entirely.
    """
    readable = asyncio.Event()
    encoder._attach_readable(readable.set)
    encoder.on_error(lambda _e: readable.set())
    try:
        while True:
            try:
                data = encoder.read(chunk_size)
            except EncoderDestroyedError:
                break
            if data is None:  # finalized and drained
                break
            if not data:
                await _bounded_wait(readable)
                readable.clear()
                continue
            try:
                writer.write(bytes(data))
                if stall_timeout is None:
                    # congestion backpressure; unbounded by explicit
                    # choice — see stall_timeout in the docstring
                    # datlint: allow-unbounded-wait (opt-in via stall_timeout)
                    await writer.drain()
                elif not await _drain_with_stall_detect(
                        encoder, writer, stall_timeout):
                    break
            except OSError as e:  # incl. every ConnectionError subclass
                # peer gone mid-session: nothing downstream will read
                # these bytes — cascade into the encoder (failure
                # semantics: destroy releases parked callbacks) and stop
                if not encoder.destroyed:
                    encoder.destroy(e)
                break
    finally:
        encoder._detach_readable()
        try:
            if writer.can_write_eof():
                writer.write_eof()
        except (OSError, RuntimeError):
            pass


async def recv_over_async(
    decoder: Decoder,
    reader,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Pump an asyncio reader into ``decoder`` until EOF or destroy.

    ``reader`` is anything with ``async read(n)`` — an
    ``asyncio.StreamReader`` or a fault-injecting wrapper
    (:class:`~.faults.AsyncFaultyReader`).
    """
    while not decoder.destroyed:
        try:
            data = await reader.read(chunk_size)
        except OSError as e:
            # peer reset mid-frame: cascade so the app's on_error fires
            # (a decoder already destroyed/finished — e.g. the session's
            # deliberate abort after an app-side destroy — stays as-is)
            if not decoder.destroyed and not decoder.finished:
                decoder.destroy(e)
            return
        if not data:
            if not decoder.destroyed and not decoder.finished:
                decoder.end()
            return
        drained = asyncio.Event()
        try:
            consumed = decoder.write(data, on_consumed=drained.set)
        except DecoderDestroyedError:
            return
        if not consumed:
            # acks run on this loop so the event itself cannot be
            # missed, but the wait is bounded anyway: one doctrine for
            # every pump (a bug that defers the ack off-loop degrades
            # to a fallback-period delay, not a hang)
            while not (decoder.writable() or decoder.destroyed
                       or decoder.finished):
                await _bounded_wait(drained)
                drained.clear()


async def open_connection_with_retry(
    host: str,
    port: int,
    policy=None,
) -> tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    """``asyncio.open_connection`` under the reconnect backoff policy.

    Retries refused/failed dials with exponential backoff + full jitter
    (:class:`~.reconnect.BackoffPolicy`); exhausting the attempts raises
    ONE structured :class:`~..wire.framing.ProtocolError` wrapping the
    last ``OSError`` — the asyncio face of the reconnect driver.
    """
    from .reconnect import BackoffPolicy

    if policy is None:
        policy = BackoffPolicy()
    failures = 0
    while True:
        try:
            return await asyncio.open_connection(host, port)
        except OSError as e:
            failures += 1
            if failures > policy.max_retries:
                raise ProtocolError(
                    f"connect to {host}:{port} failed after {failures} "
                    f"attempt(s)",
                    cause=e,
                ) from e
            await asyncio.sleep(policy.delay(failures))


async def session_over_asyncio(
    encoder: Encoder,
    decoder: Decoder,
    chunk_size: int = DEFAULT_CHUNK,
) -> None:
    """Run a whole session over a kernel socketpair on the event loop.

    Opens both ends, pumps concurrently, returns when the sender has
    flushed EOF and the receiver has finished (or either destroyed).
    """
    import socket

    a, b = socket.socketpair()
    a.setblocking(False)
    b.setblocking(False)
    writers: list[asyncio.StreamWriter] = []
    send_task = recv_task = None
    try:
        _, writer = await asyncio.open_connection(sock=a)
        writers.append(writer)  # immediately: if the second open raises,
        # the finally must still tear this transport down
        reader, writer_b = await asyncio.open_connection(sock=b)
        writers.append(writer_b)
        send_task = asyncio.ensure_future(
            send_over_async(encoder, writer, chunk_size)
        )
        recv_task = asyncio.ensure_future(
            recv_over_async(decoder, reader, chunk_size)
        )
        done, pending = await asyncio.wait(
            {send_task, recv_task}, return_when=asyncio.FIRST_COMPLETED
        )
        if pending and recv_task in done:
            # receiver exited early (destroy): nothing will ever read
            # the socket again.  Abort the transports (fails a sender
            # blocked in drain()) AND destroy the encoder (wakes a
            # sender parked in readable.wait() on an idle encoder — the
            # destroy releases parked callbacks and fires on_error,
            # which sets the readable event)
            for w in writers:
                w.transport.abort()
            if not encoder.destroyed:
                encoder.destroy(ConnectionAbortedError("receiver gone"))
        await asyncio.gather(send_task, recv_task)
    finally:
        # one pump failing must not orphan the other (asyncio would log
        # "Task exception was never retrieved" when the closed sockets
        # fail it later)
        for t in (send_task, recv_task):
            if t is not None and not t.done():
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        # abort, not close: a flushing close on a congested transport
        # waits for a peer that may never read (teardown must not hang);
        # on the normal path the sender already drained every write, so
        # nothing is discarded
        for w in writers:
            try:
                w.transport.abort()
                w.close()
            except (OSError, RuntimeError):
                pass
        for w in writers:
            try:
                await w.wait_closed()
            except (OSError, RuntimeError):
                pass
        for s in (a, b):
            try:
                s.close()
            except OSError:
                pass
