"""Decoder — the consuming end of a replication session.

Capability parity with the reference Decoder (reference: decode.js:63-262),
re-designed as a push-based incremental parser with an explicit pending
counter instead of Node Writable plumbing:

* :meth:`write` feeds wire bytes; the internal state machine is
  header → (change | blob payload) → header …, slicing without copying on the
  fast path (reference keeps the same discipline, decode.js:217-227,198-201).
* Handlers are registered with :meth:`change` / :meth:`blob` /
  :meth:`finalize` (same registration-style API as the reference,
  decode.js:112-122). Each handler receives a ``done`` callable;
  **backpressure**: while any ``done`` is outstanding, parsing pauses and
  :meth:`write` returns ``False`` — the analogue of the reference withholding
  the Writable's callback (reference: decode.js:87-99,168).
* Unregistered handlers never deadlock the pipeline: changes are dropped,
  blobs drained, finalize auto-acked (reference: decode.js:50-61).
* :meth:`end` invokes the finalize handler after all prior frames are
  consumed, before the session completes — the sentinel-write trick of the
  reference (decode.js:6,124-142) becomes an explicit queued finalization.
* Unknown frame type ids destroy the session with
  :class:`~..wire.framing.ProtocolError` (reference: decode.js:159-161).
* Counters ``bytes`` / ``changes`` / ``blobs`` (reference: decode.js:68-70).
"""

from __future__ import annotations

import threading
from collections import deque
from time import perf_counter as _perf
from typing import Callable, Optional

from .._fastpath_gate import fastpath_mod as _fastpath_mod
from ..obs.events import emit as _emit
from ..obs.flight import FLIGHT as _FLIGHT
from ..obs.metrics import OBS as _OBS, counter as _counter, \
    histogram as _histogram
from ..obs.tracing import trace_instant as _trace_instant
from ..obs.watermarks import WATERMARKS as _WATERMARKS
from ..obs import wirecost as _wirecost
from ..wire.change_codec import Change, decode_change
from ..wire.framing import LOCAL_CAPS, MAX_HEADER_LEN, TYPE_BLOB, \
    TYPE_CHANGE, TYPE_CHANGE_BATCH, TYPE_HEADER, TYPE_RECONCILE, \
    TYPE_SNAPSHOT, ProtocolError
from ..wire.framing import header_len as _header_len
from ..wire.varint import decode_uvarint

OnDone = Optional[Callable[[], None]]

# Telemetry handles, hoisted at import: the disabled path at every
# instrumentation site below is a single `_OBS.on` attribute load — no
# registry lookup, no allocation (OBSERVABILITY.md's budget).
_M_DEC_BYTES = _counter("decoder.bytes")
_M_DEC_CHANGES = _counter("decoder.changes")
_M_DEC_BLOBS = _counter("decoder.blobs")
_M_DEC_BLOB_BYTES = _counter("decoder.blob.bytes")
_M_DEC_REQUEUES = _counter("decoder.requeues")
_M_DEC_ERRORS = _counter("decoder.errors")
# columnar ChangeBatch frames dispatched (rows ride decoder.changes)
_M_DEC_BATCH_FRAMES = _counter("decoder.batch.frames")
# receiver-side mirror of wire.batch.bytes_saved (ISSUE 20 satellite):
# the SAME exact arithmetic run against the decoded columns, so sender
# and receiver agree to the byte (tests/test_wirecost.py cross-check)
_M_BATCH_SAVED_RX = _counter("wire.batch.bytes_saved_rx")
# reconcile protocol frames dispatched (OBSERVABILITY.md "reconcile.*")
_M_DEC_RC_FRAMES = _counter("decoder.reconcile.frames")
# snapshot protocol frames dispatched (OBSERVABILITY.md "snapshot.*")
_M_DEC_SN_FRAMES = _counter("decoder.snapshot.frames")
# per-write() dispatch latency: bytes in -> handlers fired (or stalled)
_H_DEC_DISPATCH = _histogram("decoder.dispatch.seconds")

# The bulk-path cursor: frame index and columnar row MUST advance
# together — a frame paired with the wrong row's columns is silent wire
# corruption (round-5 advisor, high).  Machine-checked:
# datlint: coupled-state st["f"], st["row"]


class DecoderDestroyedError(Exception):
    pass


class BlobReader:
    """Read side of one streamed blob, handed to the app's blob handler.

    Chunks are delivered through :meth:`on_data` as they are parsed; chunks
    arriving before a handler is registered are buffered and replayed at
    registration (the Readable-buffer behavior of the reference's BlobStream,
    reference: decode.js:8-48). :meth:`pause` / :meth:`resume` give the app
    per-chunk backpressure: while paused the decoder stops parsing, which
    propagates to the transport.
    """

    def __init__(self, decoder: "Decoder", length: int):
        self._decoder = decoder
        self.length = length
        self.received = 0
        self.ended = False
        self.destroyed = False
        self._data_cb: Optional[Callable[[bytes], None]] = None
        self._end_cbs: list[Callable[[], None]] = []
        self._buffered: list[bytes] = []
        self._paused = False

    def on_data(self, cb: Callable[[bytes], None]) -> "BlobReader":
        self._data_cb = cb
        if self._buffered:
            chunks, self._buffered = self._buffered, []
            for c in chunks:
                cb(c)
        return self

    def on_end(self, cb: Callable[[], None]) -> "BlobReader":
        if self.ended:
            cb()
        else:
            self._end_cbs.append(cb)
        return self

    def collect(self, cb: Callable[[bytes], None]) -> "BlobReader":
        """Convenience: buffer the whole blob and deliver it once on end —
        the role `concat-stream` plays in the reference suite
        (reference: test/basic.js:36-40)."""
        parts: list[bytes] = []
        self.on_data(parts.append)
        self.on_end(lambda: cb(b"".join(parts)))
        return self

    def pause(self) -> None:
        """Stop the decoder from parsing further input (chunk granularity)
        until :meth:`resume` — per-chunk backpressure, the analogue of the
        reference's Readable drain accounting (reference: decode.js:35-48)."""
        if self._paused:
            return
        self._paused = True
        self._decoder._paused_readers += 1

    def resume(self) -> None:
        if not self._paused:
            return
        self._paused = False
        self._decoder._paused_readers -= 1
        self._decoder._resume()

    def destroy(self, err: Exception | None = None) -> None:
        """Destroying a blob reader tears down the whole session
        (reference: decode.js:20-26)."""
        if self.destroyed:
            return
        self.destroyed = True
        self._decoder.destroy(err)

    # -- driven by the decoder ---------------------------------------------

    def _deliver(self, chunk: bytes) -> None:
        self.received += len(chunk)
        if self._data_cb is not None:
            self._data_cb(chunk)
        else:
            self._buffered.append(chunk)

    def _finish(self) -> None:
        self.ended = True
        cbs, self._end_cbs = self._end_cbs, []
        for cb in cbs:
            cb()


class _FastAck:
    """One-shot ``done`` for the bulk fast path, cheaper than an ``_up``
    closure: the pending counter is only touched if the handler did NOT
    ack synchronously (the overwhelmingly common case never pays the
    increment/decrement/resume round-trip).

    States: 0 fresh -> 1 acked-before-arming (sync; no pending ever
    taken) / 2 armed (handler kept it async; pending incremented by the
    dispatch loop) -> 3 done (armed ack fired; pending released).  All
    transitions run under the decoder's ``_ack_lock`` so an ack landing
    from another thread between the handler returning and the loop
    arming can neither be lost nor double-counted.
    """

    __slots__ = ("dec", "state")

    def __init__(self, dec: "Decoder") -> None:
        self.dec = dec
        self.state = 0

    def __call__(self) -> None:
        dec = self.dec
        with dec._ack_lock:
            st = self.state
            if st == 0:
                self.state = 1  # sync ack: loop sees it, never arms
                return
            if st != 2:
                return  # double ack: no-op (same contract as _up)
            self.state = 3
            dec._pending -= 1
        dec._resume()


def _drain_blob(blob: BlobReader, done: Callable[[], None]) -> None:
    """Default blob handler: consume and discard (reference: decode.js:58-61).

    The discarding data callback matters: without one, BlobReader buffers
    every chunk for later replay and an unconsumed blob accumulates whole
    in host RAM — the opposite of draining.
    """
    blob.on_data(lambda _chunk: None)
    blob.on_end(done)


class Decoder:
    """Push-based incremental wire parser. See module docstring."""

    # the wire cost plane's link label (ISSUE 20): owners carrying more
    # than one session overwrite it per instance (the sidecar names it
    # after the session key) — a collector label, runtime by design
    cost_link = "session"

    def __init__(self):
        self.bytes = 0
        self.changes = 0
        self.blobs = 0
        self.destroyed = False
        self.finished = False
        self._on_change: Callable[[Change, Callable[[], None]], None] | None = None
        self._on_change_batch = None  # whole-batch columnar handler
        self._on_reconcile = None  # reconcile protocol message handler
        self._on_snapshot = None  # snapshot protocol message handler
        # reconcile/snapshot frames delivered: ride _frames_delivered
        # (neither touches the change-row counters)
        self.reconcile_frames = 0
        self.snapshot_frames = 0
        self._on_blob: Callable[[BlobReader, Callable[[], None]], None] | None = None
        self._on_finalize: Callable[[Callable[[], None]], None] | None = None
        self._error_cbs: list[Callable[[Exception | None], None]] = []
        self._finish_cbs: list[Callable[[], None]] = []

        # parser state
        self._state = TYPE_HEADER
        self._header = bytearray()  # accumulating varint+id bytes
        self._missing = 0  # payload bytes still to consume
        self._payload_parts: list[bytes] | None = None  # change slow path
        self._current_blob: BlobReader | None = None
        # wire-position cursor for causal tracing (obs/tracing.py):
        # _parsed counts wire bytes the parser fully consumed (bytes
        # holds ACCEPTED bytes, which includes unparsed overflow);
        # _frame_start is the wire offset of the frame being parsed —
        # the same number the sender's encoder tagged this frame with.
        # Maintained unconditionally (trivial int adds) so the offsets
        # stay coherent across mid-session gate flips; the bulk path
        # tracks its own base and re-syncs _parsed when a run retires.
        self._parsed = 0
        self._frame_start = 0
        # wire offset of the last exported checkpoint (fleet-plane
        # watermark: the resume point a reconnect would pay back to)
        self._ckpt_offset = 0

        # flow control
        self._pending = 0
        self._paused_readers = 0
        self._overflow: deque[memoryview] = deque()  # unparsed input, in order
        self._overflow_bytes = 0  # running total (kept in sync with the deque)
        self._bulk: dict | None = None  # parked native frame-index cursor
        # parked ChangeBatch delivery cursor: a batch frame whose rows
        # could not all dispatch (async ack / pause) resumes here —
        # ordering: nothing after the batch dispatches until it drains
        self._pbatch: dict | None = None
        # batch-frame accounting so _frames_delivered keeps counting
        # FRAMES while self.changes counts ROWS (a batch is one frame)
        self._batch_rows_seen = 0
        self._batch_frames_done = 0
        self._write_cbs: list[Callable[[], None]] = []
        self._end_queued = False
        self._end_cb: OnDone = None
        self._consuming = False  # reentrancy guard for _consume
        # drain watchers: persistent callbacks fired whenever a stall
        # clears (or the decoder dies), so a transport pump parked on
        # "not writable" wakes immediately on a cross-thread ack instead
        # of rediscovering the state on a poll (transport.recv_over)
        self._drain_watchers: list[Callable[[], None]] = []
        # serializes _FastAck state transitions against cross-thread acks
        self._ack_lock = threading.Lock()
        # dat_fastpath AckBoard (outstanding C-side armed acks), created
        # lazily the first time the C dispatch loop runs
        self._ack_board = None

    # -- handler registration (same shape as the reference API) -------------

    def change(self, cb: Callable[[Change, Callable[[], None]], None]) -> "Decoder":
        self._on_change = cb
        return self

    def reconcile(self, cb) -> "Decoder":
        """Register the reconcile-message handler: ``cb(msg, done)``
        receives each ``TYPE_RECONCILE`` frame's decoded
        :class:`~..wire.reconcile_codec.ReconcileMsg` and one ``done``
        per frame (the reconcile driver's receive surface).  Without a
        handler, reconcile frames are dropped — the same
        never-deadlock default as unhandled changes."""
        self._on_reconcile = cb
        return self

    def snapshot(self, cb) -> "Decoder":
        """Register the snapshot-message handler: ``cb(msg, done)``
        receives each ``TYPE_SNAPSHOT`` frame's decoded
        :class:`~..wire.snapshot_codec.SnapshotMsg` and one ``done``
        per frame (the snapshot driver's receive surface).  Without a
        handler, snapshot frames are dropped — the same never-deadlock
        default as unhandled changes."""
        self._on_snapshot = cb
        return self

    def change_batch(self, cb) -> "Decoder":
        """Register a whole-batch handler: ``cb(cols, done)`` receives a
        negotiated ``ChangeBatch`` frame's decoded columns (a
        :class:`~..runtime.replay.ChangeColumns`: ``len()`` rows,
        ``row(i)`` lazy materialization, numpy columns for bulk work)
        and ONE ``done`` for the whole frame — zero per-row Python on
        the decode side.  Without this handler, batch rows are delivered
        through the per-record :meth:`change` handler one
        :class:`Change` at a time (same observable stream as a
        per-record peer).  Per-record frames always go to
        :meth:`change`."""
        self._on_change_batch = cb
        return self

    @staticmethod
    def capabilities() -> int:
        """The capability mask this decoder can parse — what a receiver
        advertises during session setup (WIRE.md "Capability
        negotiation")."""
        return LOCAL_CAPS

    def blob(self, cb: Callable[[BlobReader, Callable[[], None]], None]) -> "Decoder":
        self._on_blob = cb
        return self

    def finalize(self, cb: Callable[[Callable[[], None]], None]) -> "Decoder":
        self._on_finalize = cb
        return self

    def on_error(self, cb: Callable[[Exception | None], None]) -> "Decoder":
        self._error_cbs.append(cb)
        return self

    def on_finish(self, cb: Callable[[], None]) -> "Decoder":
        if self.finished:
            cb()
        else:
            self._finish_cbs.append(cb)
        return self

    # -- write side ---------------------------------------------------------

    def write(self, data, on_consumed: OnDone = None) -> bool:
        """Feed wire bytes. Returns True if fully consumed synchronously;
        False if parsing stalled on an outstanding ``done`` (the
        ``on_consumed`` callback then fires when the app drains —
        reference: decode.js:124-133,168)."""
        if self.destroyed:
            raise DecoderDestroyedError("write after destroy")
        if self.finished or self._end_queued:
            raise DecoderDestroyedError("write after end")
        data = memoryview(data.encode("utf-8") if isinstance(data, str) else data)
        self.bytes += len(data)
        if len(data):
            self._overflow.append(data)
            self._overflow_bytes += len(data)
        # Park the completion callback BEFORE consuming: _consume's
        # drained epilogue is the single place parked callbacks fire, so
        # a done() ack landing on another thread can never slip between
        # a stall check and the parking (the lost-wakeup TOCTOU).  A
        # fresh wrapper keeps the parked entry unique per call.
        entry = None
        if on_consumed is not None:
            entry = lambda cb=on_consumed: cb()  # noqa: E731
            self._write_cbs.append(entry)
        if _OBS.on:
            _M_DEC_BYTES.inc(len(data))
            t0 = _perf()
            try:
                self._consume()
            finally:
                _H_DEC_DISPATCH.observe(_perf() - t0)
        else:
            self._consume()
        if entry is not None:
            return entry not in self._write_cbs  # fired <=> consumed
        return not (
            self._overflow or self._bulk is not None
            or self._pbatch is not None or self._stalled()
        )

    def end(self, on_finished: OnDone = None) -> None:
        """Graceful end: after all prior frames are consumed, the finalize
        handler runs, then the session finishes (reference: decode.js:135-142)."""
        if self.destroyed:
            raise DecoderDestroyedError("end after destroy")
        if self._end_queued or self.finished:
            return
        self._end_queued = True
        self._end_cb = on_finished
        self._maybe_finalize()

    def destroy(self, err: Exception | None = None) -> None:
        """Fail-fast teardown, cascading to a live blob reader
        (reference: decode.js:104-110)."""
        if self.destroyed:
            return
        self.destroyed = True
        blob, self._current_blob = self._current_blob, None
        if blob is not None and not blob.destroyed:
            blob.destroyed = True
        self._overflow.clear()
        self._overflow_bytes = 0
        self._bulk = None
        self._pbatch = None
        for cb in self._error_cbs:
            cb(err)
        # Release parked write-completion callbacks so a transport blocked on
        # "consumed" wakes up and observes the destroyed state (Node errors
        # the pending Writable callback for the same reason).
        cbs, self._write_cbs = self._write_cbs, []
        for cb in cbs:
            cb()
        # ... and wake persistent drain watchers for the same reason
        self._notify_drain_watchers()

    def writable(self) -> bool:
        return not (
            self._stalled()
            or self._overflow
            or self._bulk is not None
            or self._pbatch is not None
            or self.destroyed
            or self.finished
        )

    def checkpoint(self, emit_event: bool = True):
        """Export this instant's session progress (resume support).

        Cheap and side-effect-free: a :class:`~.resume.SessionCheckpoint`
        whose ``wire_offset`` is the count of wire bytes this decoder has
        accepted — the exact byte a reconnecting sender must resume from
        (parser state, including mid-frame cursors and unparsed overflow,
        lives on in this object).  The frame/row/blob cursors and the
        backend digest state ride along for observability and structured
        error context.  See ROBUSTNESS.md.

        ``emit_event=False`` skips the ``session.checkpoint`` telemetry
        event: the flight recorder snapshots a checkpoint as bundle
        CONTEXT, and recording that as a checkpoint event would skew
        any analysis treating the event as "a resume point was taken".
        """
        from .resume import SessionCheckpoint

        self._ckpt_offset = self.bytes
        if emit_event and _OBS.on:
            _emit("session.checkpoint", wire_offset=self.bytes,
                  frame=self._frames_delivered(), row=self.changes)
        blob = self._current_blob
        return SessionCheckpoint(
            wire_offset=self.bytes,
            frame=self._frames_delivered(),
            row=self.changes,
            blob_offset=blob.received if blob is not None else 0,
            digest=self._checkpoint_digest(),
        )

    def watermark(self, link: str) -> None:
        """Export this decoder's wire-position cursors on the fleet
        plane (OBSERVABILITY.md "Fleet plane") under ``link``:
        ``accepted`` (bytes taken from the transport — the resume
        point), ``parsed`` (bytes the parser fully consumed — the lag
        join's receive frontier), and ``checkpoint`` (the last exported
        resume point).  All three already exist for resume/tracing;
        exporting them costs the hot path nothing — values are read
        only at snapshot time.  Call
        ``WATERMARKS.untrack(link)`` when the session ends."""
        _WATERMARKS.track("accepted", link, lambda: self.bytes)
        _WATERMARKS.track("parsed", link, lambda: self._parsed)
        _WATERMARKS.track("checkpoint", link, lambda: self._ckpt_offset)

    def _frames_delivered(self) -> int:
        """Frames fully delivered — the single frame-index authority for
        checkpoints AND structured error context (they must agree).
        ``blobs`` counts at OPEN (header time): a blob mid-payload is
        the frame being parsed, not a delivered one.  A ChangeBatch is
        ONE frame however many rows it carries: its rows are subtracted
        back out of ``changes`` and the frame counts once, at full
        delivery (mid-batch it is the frame being parsed, like a
        mid-payload blob).  A reconcile/snapshot frame counts once, at
        delivery, via its own counter."""
        return (self.changes - self._batch_rows_seen
                + self._batch_frames_done + self.blobs
                + self.reconcile_frames + self.snapshot_frames
                - (1 if self._current_blob is not None else 0))

    def _checkpoint_digest(self) -> dict:
        """Backend hook: running digest state to carry in a checkpoint
        (the TPU decoder records its emitted sequence counters).  Base:
        no digest surface, nothing to record."""
        return {}

    # -- drain watchers ------------------------------------------------------

    def _add_drain_watcher(self, cb: Callable[[], None]) -> None:
        """Register a persistent wakeup hook: fired (possibly from the
        acking thread) whenever parsing becomes unblocked, so a pump
        waiting on ``writable()`` can park on an event instead of
        polling.  Unlike ``write``'s one-shot ``on_consumed`` callbacks
        these survive across writes; remove with
        :meth:`_remove_drain_watcher`."""
        self._drain_watchers.append(cb)

    def _remove_drain_watcher(self, cb: Callable[[], None]) -> None:
        try:
            self._drain_watchers.remove(cb)
        except ValueError:
            pass

    def _notify_drain_watchers(self) -> None:
        for cb in list(self._drain_watchers):
            cb()

    def _protocol_error(self, message: str,
                        cause: BaseException | None = None) -> ProtocolError:
        """Structured wire error: every ProtocolError this decoder
        raises carries the frame index and byte offset where parsing
        stood — the session-context half of the robustness contract
        (ROBUSTNESS.md), so operators see *where* a stream broke instead
        of a bare message.

        This is also the flight recorder's primary hook (obs/flight.py):
        every decoder-side wire error funnels through here, so an armed
        recorder dumps its post-mortem bundle BEFORE destroy() clears
        the parser state the bundle narrates."""
        err = ProtocolError(
            message,
            frame=self._frames_delivered(),
            offset=self.bytes,
            cause=cause,
        )
        if _OBS.on:
            _M_DEC_ERRORS.inc()
            _emit("protocol.error", frame=err.frame, offset=err.offset,
                  message=message)
            self._lit_cost_failure(message)
        if _FLIGHT.armed:
            _FLIGHT.dump("protocol-error", error=err,
                         checkpoint=self.checkpoint(emit_event=False))
        return err

    # -- flow control --------------------------------------------------------

    def _stalled(self) -> bool:
        if self._pending > 0 or self._paused_readers > 0:
            return True
        board = self._ack_board
        return board is not None and board.outstanding > 0

    def _up(self) -> Callable[[], None]:
        """Create a one-shot ``done`` for an app callback; parsing pauses
        while any are outstanding (reference: decode.js:87-99)."""
        self._pending += 1
        fired = False

        def done() -> None:
            nonlocal fired
            if fired:
                return
            fired = True
            self._pending -= 1
            self._resume()

        return done

    def _resume(self) -> None:
        # While _consume is live on the stack, the outer loop may hold a
        # chunk's unparsed remainder in a local — it will keep going (pending
        # just dropped) and run the drained notifications itself, so a nested
        # resume must be a no-op rather than observe a falsely-empty overflow.
        if self.destroyed or self._stalled():
            return
        if self._drain_watchers:
            # fire BEFORE the _consuming check: when the outer loop is
            # live on another thread's stack, it may already be past its
            # own drained-epilogue — this notify is then the only wakeup
            # a parked pump gets (the lost-wakeup the transport's old
            # bounded poll papered over)
            self._notify_drain_watchers()
        if self._consuming:
            return
        self._consume()

    def _maybe_finalize(self) -> None:
        if (
            not self._end_queued
            or self.finished
            or self.destroyed
            or self._overflow
            or self._bulk is not None
            or self._pbatch is not None
            or self._stalled()
            or self._consuming  # drained-check at the end of _consume re-runs this
        ):
            return
        if self._state != TYPE_HEADER or self._header:
            self.destroy(self._protocol_error("stream ended mid-frame"))
            return
        self._end_queued = False  # run once

        def finish() -> None:
            self.finished = True
            cb, self._end_cb = self._end_cb, None
            if cb is not None:
                cb()
            cbs, self._finish_cbs = self._finish_cbs, []
            for fcb in cbs:
                fcb()

        if self._on_finalize is not None:
            self._on_finalize(finish)
        else:
            finish()

    # -- parser --------------------------------------------------------------

    # Subclass opt-in to the bulk fast loop: when True IN THE CLASS'S
    # OWN __dict__ (the gate reads cls.__dict__, so the opt-in does NOT
    # inherit), runs of change frames dispatch through
    # _dispatch_changes_fast even though _deliver_change is overridden,
    # and the raw payload of every dispatched change is handed to
    # _note_change_payloads afterwards (the digest decoder's tap).  The
    # contract: the declaring class's ONLY per-change addition is
    # handler-independent payload work; a subclass must re-declare the
    # flag to re-opt-in after auditing its own overrides.
    _bulk_payload_sink = False

    def _note_change_payloads(self, payloads, count: int) -> None:
        """Bulk-path tap: ``payloads`` is the in-order list of raw change
        payload bytes for the just-dispatched run (None when collection
        was off), ``count`` the number of changes dispatched.  Called
        after EVERY fast-loop run on sink-enabled subclasses — even with
        collection off — so sequence bookkeeping can advance.
        Base: no-op."""

    def _payload_sink_active(self) -> bool:
        """Whether the tap should actually COLLECT payloads (slicing
        costs per frame); sequence accounting happens either way."""
        return True

    # bulk path threshold: below this, the native round-trip (array
    # wrapping + index buffers) costs more than the per-byte scan saves.
    # 2048 measured (round 5): a transport writing ~4 KiB chunks leaves
    # a ~4000-byte remainder after the scanner crosses the straddling
    # frame — at the old 4096 threshold that remainder always rode the
    # scanner (5.5 MiB/s); at 2048 it re-enters the native index
    # (21.7 MiB/s), with large-write throughput unchanged (within noise)
    _NATIVE_MIN = 2048

    def _consume(self) -> None:
        """Main parse loop: drain overflow while the app is keeping up
        (reference: decode.js:144-169).

        When at least a buffer's worth of complete frames is queued and
        the parser sits at a frame boundary, the whole buffer is indexed
        in one native call (``dat_split_frames``,
        native/dat_native.cpp) and frames dispatch from the index —
        the reference's per-byte header scan (decode.js:251-262) drops
        out of the hot path entirely.  The per-byte scanner remains the
        slow/tail path: split headers, partial frames, tiny writes.

        Guarded against reentrancy: a handler that acks synchronously while
        the loop holds a chunk's unparsed remainder in a local must not
        re-enter and pop the *next* queued chunk out of order — the guard
        makes the nested resume a no-op and the outer loop carries on.
        """
        if self._consuming:
            return
        self._consuming = True
        try:
            while not self._stalled() and not self.destroyed:
                if self._pbatch is not None:
                    # resume a parked ChangeBatch dispatch from its row
                    # cursor — nothing else parses until it drains
                    # (frame order is delivery order)
                    self._run_pending_batch()
                    if self._pbatch is not None:
                        return  # still stalled mid-batch
                    continue
                if self._bulk is not None:
                    # resume a parked frame index from its cursor — an
                    # async ack must NOT re-index/re-decode the remainder
                    # (that would make bulk decode O(frames^2))
                    self._run_indexed()
                    continue
                if not self._overflow:
                    break
                if (
                    self._state == TYPE_HEADER
                    and not self._header
                    # O(1) size gate BEFORE merging: joining the backlog
                    # costs O(bytes), and when the native path is
                    # unavailable (_NATIVE_MIN pushed to 2**62) an
                    # unconditional merge would re-copy the whole backlog
                    # on every resume — quadratic on the Python fallback
                    and self._overflow_bytes >= self._NATIVE_MIN
                ):
                    merged = self._merged_overflow()
                    if merged is not None and len(merged) >= self._NATIVE_MIN:
                        if self._start_indexed(merged):
                            continue
                        if self.destroyed:
                            return
                        # no complete frame in the whole buffer (e.g. a
                        # large blob frame still arriving): fall through
                        # to the streaming scanner so it can enter the
                        # frame and consume payload incrementally
                        self._ov_appendleft(merged)
                    elif merged is not None:
                        self._ov_appendleft(merged)
                chunk = self._overflow.popleft()
                self._overflow_bytes -= len(chunk)
                rest = self._consume_chunk(chunk)
                if self.destroyed:
                    return
                if rest is not None and len(rest):
                    self._ov_appendleft(rest)
        finally:
            self._consuming = False
        # Fully drained and nothing outstanding: release parked writers and
        # run a queued finalization. This lives here (not in _resume) so a
        # handler acking synchronously mid-loop cannot finalize while the
        # loop still holds unparsed bytes in a local.
        if (
            not self.destroyed
            and not self._overflow
            and self._bulk is None
            and self._pbatch is None
            and not self._stalled()
        ):
            cbs, self._write_cbs = self._write_cbs, []
            for cb in cbs:
                cb()
            self._maybe_finalize()
            self._notify_drain_watchers()

    def _ov_appendleft(self, mv: memoryview) -> None:
        self._overflow.appendleft(mv)
        self._overflow_bytes += len(mv)

    def _requeue_tail(self, rest) -> None:
        """A handler raised while this chunk's unparsed remainder lived
        only in a delivery-site local: requeue it so a caught
        raise-then-resume continues with the NEXT frame instead of
        silently dropping every frame after the raising one in the same
        write (the streaming analogue of the bulk path's parked cursor,
        which preserves its tail in st)."""
        if len(rest):
            if _OBS.on:
                _M_DEC_REQUEUES.inc()
                _emit("decoder.requeue", bytes=len(rest),
                      offset=self.bytes)
            self._ov_appendleft(rest)

    def _merged_overflow(self) -> memoryview | None:
        """Pop ALL queued overflow as one contiguous memoryview."""
        if not self._overflow:
            return None
        if len(self._overflow) == 1:
            chunk = self._overflow.popleft()
            self._overflow_bytes -= len(chunk)
            return chunk
        chunks = list(self._overflow)
        self._overflow.clear()
        self._overflow_bytes = 0
        return memoryview(b"".join(chunks))

    def _start_indexed(self, buf: memoryview) -> bool:
        """Index ``buf``'s complete frames natively and park a cursor.

        One ``dat_split_frames`` call replaces per-frame header scans,
        and one ``dat_decode_changes`` call pre-decodes every change
        payload columnar-wise (the per-record Python proto parse is ~2/3
        of bulk decode time, measured).  The index + columns + cursor
        live in ``self._bulk`` so an async ack resumes dispatch where it
        stopped instead of re-indexing the remainder.

        Returns False when the bulk path cannot proceed (no native lib,
        or zero complete frames in the buffer) — the caller falls back
        to the streaming scanner.  On a corrupt change payload the
        columns are dropped and the per-frame Python decoder takes over,
        so records before the corrupt one are still delivered and the
        error surfaces with identical semantics.
        """
        from ..runtime import native

        lib = native.get_lib()
        if lib is None:
            self._NATIVE_MIN = 1 << 62  # don't retry every write
            return False
        import ctypes

        import numpy as np

        arr = np.frombuffer(buf, dtype=np.uint8)
        cap = len(arr) // 2 + 1  # a frame is at least 2 bytes
        starts = np.empty(cap, dtype=np.int64)
        lens = np.empty(cap, dtype=np.int64)
        ids = np.empty(cap, dtype=np.uint8)
        consumed = ctypes.c_int64(0)
        err = ctypes.c_int64(0)
        n = lib.dat_split_frames(arr, len(arr), starts, lens, ids, cap,
                                 ctypes.byref(consumed), ctypes.byref(err))
        # A malformed header mid-buffer only STOPS the native scan (err is
        # informational): the valid prefix still dispatches through the
        # bulk path and the streaming scanner re-encounters the bad
        # header in the remainder, destroying at exactly the frame the
        # per-byte path would — delivery-before-error must not depend on
        # how the transport chunked its writes.
        if n <= 0:
            return False
        self._install_index(buf, arr, starts, lens, ids, n,
                            int(consumed.value))
        return True

    def _install_index(self, buf, arr, starts, lens, ids, n: int,
                       consumed: int) -> None:
        """Park a frame index over ``buf`` as the bulk cursor — the
        shared installer behind :meth:`_start_indexed` (scan done here)
        and :meth:`write_indexed` (scan done inside the native pump's
        GIL-released receive call).  ``starts``/``lens``/``ids`` may be
        over-allocated; only ``[:n]`` is the index."""
        import ctypes

        import numpy as np

        from ..runtime import native

        lib = native.get_lib()
        cols_np = None
        cidx = np.nonzero(ids[:n] == TYPE_CHANGE)[0]
        m = len(cidx)
        if m >= 16 and lib is not None:
            chg = np.empty(m, np.uint32)
            frm = np.empty(m, np.uint32)
            tov = np.empty(m, np.uint32)
            koff = np.empty(m, np.int64)
            klen = np.empty(m, np.int64)
            soff = np.empty(m, np.int64)
            slen = np.empty(m, np.int64)
            voff = np.empty(m, np.int64)
            vlen = np.empty(m, np.int64)
            erri = ctypes.c_int64(-1)
            rc = lib.dat_decode_changes(
                arr, np.ascontiguousarray(starts[cidx]),
                np.ascontiguousarray(lens[cidx]), m,
                chg, frm, tov, koff, klen, soff, slen, voff, vlen,
                ctypes.byref(erri),
            )
            if rc == 0:
                # kept as the raw numpy columns: the C dispatch loop
                # reads the buffers directly; the Python loops get
                # list/tuple views lazily (_cols_lists) — converting
                # eagerly cost ~0.5us/frame of tolist/zip
                cols_np = (chg, frm, tov, koff, klen, soff, slen,
                           voff, vlen)
        self._bulk = {
            "buf": buf,
            # wire offset of buf[0]: the indexed buffer is exactly the
            # unconsumed overflow, so it starts where parsing stood
            "base": self._parsed,
            "starts": starts[:n].tolist(),
            "lens": lens[:n].tolist(),
            "ids": ids[:n].tolist(),
            "ids_np": np.ascontiguousarray(ids[:n]),
            "starts_np": np.ascontiguousarray(starts[:n]),
            "lens_np": np.ascontiguousarray(lens[:n]),
            "n": n,
            "consumed": consumed,
            "f": 0,
            "row": 0,
            "cols_np": cols_np,
            "blob_open": False,
        }

    def write_indexed(self, data, starts, lens, ids, n: int,
                      consumed: int) -> bool:
        """Feed wire bytes WITH a pre-computed native frame index — the
        transport pump's bulk entry (session/pump.py): the pump's
        GIL-released receive call already ran ``dat_split_frames`` over
        ``data``, so the index installs directly instead of re-scanning.
        Return contract matches :meth:`write` (True = fully consumed
        synchronously).

        Only valid at a clean frame boundary with nothing parked; any
        other parser state falls back to :meth:`write` (the index is
        then recomputed if the merged backlog qualifies) — byte-stream
        semantics are identical either way, this entry only skips
        redundant work."""
        if (n <= 0 or self._overflow or self._bulk is not None
                or self._pbatch is not None or self._state != TYPE_HEADER
                or self._header or self._consuming or self._stalled()):
            return self.write(data)
        if self.destroyed:
            raise DecoderDestroyedError("write after destroy")
        if self.finished or self._end_queued:
            raise DecoderDestroyedError("write after end")
        import numpy as np

        buf = memoryview(data)
        self.bytes += len(buf)
        self._install_index(buf, np.frombuffer(buf, dtype=np.uint8),
                            starts, lens, ids, n, consumed)
        if _OBS.on:
            _M_DEC_BYTES.inc(len(buf))
            t0 = _perf()
            try:
                self._consume()
            finally:
                _H_DEC_DISPATCH.observe(_perf() - t0)
        else:
            self._consume()
        return not (
            self._overflow or self._bulk is not None
            or self._pbatch is not None or self._stalled()
        )

    @staticmethod
    def _cols_lists(st: dict):
        """Python-loop view of the columnar decode: one tuple per row
        (lazy; the C dispatcher never needs it)."""
        rows = st.get("zrows")
        if rows is None and st["cols_np"] is not None:
            rows = st["zrows"] = list(
                zip(*(a.tolist() for a in st["cols_np"]))
            )
        return rows

    def _run_indexed(self) -> None:
        """Dispatch frames from the parked index until done or stalled.

        Each frame goes through the same change/blob machinery as the
        streaming path (counters, ordering, blob latches, zero-length
        blobs — shared, not duplicated).  Runs of consecutive change
        frames take :meth:`_dispatch_changes_fast` when the columnar
        pre-decode is available and ``_deliver_change`` is not
        subclassed — same observable contract, ~3x less per-frame
        interpreter work (the config-1 decode rate rides this loop).
        """
        st = self._bulk
        assert st is not None
        buf = st["buf"]
        starts, lens, ids = st["starts"], st["lens"], st["ids"]
        have_cols = st["cols_np"] is not None
        rows_l = self._cols_lists(st) if have_cols else None
        f = st["f"]
        row = st["row"]
        n = st["n"]
        cls = type(self)
        # the sink opt-in is deliberately NON-inheritable (__dict__, not
        # attribute lookup): a subclass overriding _deliver_change would
        # otherwise silently lose its override on bulk writes while
        # keeping it on chunked ones
        fast = (have_cols
                and (cls._deliver_change is Decoder._deliver_change
                     or cls.__dict__.get("_bulk_payload_sink", False)))
        try:
            while f < n:
                if self._stalled() or self.destroyed:
                    return
                type_id = ids[f]
                if fast and type_id == TYPE_CHANGE:
                    try:
                        # return value deliberately unused: the st
                        # write-back is the one cursor-handoff channel
                        # (it is what survives handler raises)
                        self._dispatch_changes_fast(st, f)
                    finally:
                        # the fast loops (C and Python) write BOTH
                        # cursors into st — on their raise path too;
                        # resync the locals so the outer finally below
                        # cannot clobber st with stale values
                        f, row = st["f"], st["row"]
                    if self.destroyed:
                        self._bulk = None
                        return
                    continue
                start = starts[f]
                flen = lens[f]
                self._missing = flen
                # the frame's wire start offset (starts[] points at the
                # payload AFTER the id byte; back out the header) — the
                # tracing tag both _deliver_change and the blob open
                # read; unconditional so offsets stay coherent across
                # gate flips mid-run
                self._frame_start = st["base"] + start - _header_len(flen)
                if type_id == TYPE_CHANGE:
                    if have_cols:
                        (cg, fr, to, ko, kl, so, sl, vo, vl) = rows_l[row]
                        if self._on_change is not None:
                            try:
                                change = Change(
                                    key=str(buf[ko : ko + kl], "utf-8"),
                                    change=cg,
                                    from_=fr,
                                    to=to,
                                    value=(bytes(buf[vo : vo + vl])
                                           if vl >= 0 else b""),
                                    subset=(str(buf[so : so + sl], "utf-8")
                                            if sl >= 0 else ""),
                                )
                            except ValueError as e:  # incl. UnicodeDecodeError
                                self._bulk = None
                                self.destroy(self._protocol_error(str(e), cause=e))
                                return
                        else:
                            # no registered handler will ever see the object
                            # (the default drops changes) — but the payload
                            # must still be VALID: the key's UTF-8 check is
                            # the one observable part of construction, and a
                            # digest-only subclass (TpuDecoder with no change
                            # handler — the sidecar's shape) still needs the
                            # wire error.  ``change=None`` is a documented
                            # private contract of _deliver_change.
                            try:
                                str(buf[ko : ko + kl], "utf-8")
                                if sl >= 0:
                                    str(buf[so : so + sl], "utf-8")
                            except ValueError as e:
                                self._bulk = None
                                self.destroy(self._protocol_error(str(e), cause=e))
                                return
                            change = None
                        # delivery consumes the frame: advance BOTH
                        # cursor halves before the handler can raise —
                        # the finally below persists them together, so
                        # a raise-then-resume re-enters at the next
                        # frame with row still paired to it
                        row += 1
                        f += 1
                        self._missing = 0
                        self._deliver_change(change, buf[start : start + flen])
                    else:
                        row += 1
                        f += 1
                        self._state = TYPE_CHANGE
                        self._payload_parts = None
                        self._change_data(buf[start : start + flen])
                elif type_id == TYPE_CHANGE_BATCH:
                    # delivery consumes the frame (the change/blob
                    # doctrine): advance BEFORE dispatch so a handler
                    # raise resumes at the next frame; an async ack
                    # parks the ROW cursor in _pbatch, and _consume
                    # drains it before touching this index again
                    f += 1
                    self._missing = 0
                    self._finish_change_batch(buf[start : start + flen])
                    if self.destroyed:
                        self._bulk = None
                        return
                    if self._pbatch is not None or self._stalled():
                        return
                elif type_id == TYPE_RECONCILE:
                    # same advance-before-dispatch doctrine; delivery is
                    # whole-frame, so only a stall can park the index
                    f += 1
                    self._missing = 0
                    self._finish_reconcile(buf[start : start + flen])
                    if self.destroyed:
                        self._bulk = None
                        return
                    if self._stalled():
                        return
                elif type_id == TYPE_SNAPSHOT:
                    # same whole-frame doctrine as reconcile
                    f += 1
                    self._missing = 0
                    self._finish_snapshot(buf[start : start + flen])
                    if self.destroyed:
                        self._bulk = None
                        return
                    if self._stalled():
                        return
                elif type_id == TYPE_BLOB:
                    if not st["blob_open"]:
                        self._state = TYPE_BLOB
                        self._current_blob = None
                        # opened-state advances WITH the side effect: a
                        # blob handler that raises must not re-open (and
                        # re-count) the same blob on resume
                        st["blob_open"] = True
                        self._open_blob_if_ready()
                        if self.destroyed:
                            self._bulk = None
                            return
                        # a handler that pause()d synchronously must not
                        # receive the payload until it resumes — same as
                        # the streaming path parking the chunk undelivered
                        if flen and self._stalled():
                            return
                    # delivery consumes the frame (same doctrine as the
                    # change path above): advance BEFORE the reader
                    # callbacks can raise, so a caught raise-then-resume
                    # continues at the next frame instead of
                    # re-delivering (and re-digesting) this payload
                    st["blob_open"] = False
                    f += 1
                    if flen:
                        self._blob_data(buf[start : start + flen])
                else:
                    self._bulk = None
                    self.destroy(
                        self._protocol_error(
                            f"Protocol error, unknown type: {type_id}")
                    )
                    return
                if self.destroyed:
                    self._bulk = None
                    return
        finally:
            # single atomic write-back for every exit — returns, handler
            # exceptions, stalls: the cursor halves leave together or
            # not at all (st is dead when _bulk was dropped; the write
            # is then harmless)
            st["f"] = f
            st["row"] = row
        self._bulk = None
        # run retired: re-sync the wire-position cursor to the exact
        # bytes the index covered (interim _blob_data/_change_data adds
        # during the run were provisional; this SET is authoritative)
        self._parsed = st["base"] + st["consumed"]
        tail = buf[st["consumed"]:]
        if len(tail):
            self._ov_appendleft(tail)

    def _dispatch_changes_fast(self, st: dict, f: int) -> int:
        """Deliver the run of consecutive change frames starting at ``f``.

        The hot loop of config-1 bulk decode.  Per frame: one slot-built
        :class:`Change` from the pre-decoded columns, one
        :class:`_FastAck`, one handler call — no ``_up`` closure, no
        pending-counter churn unless the handler actually defers its
        ack, no per-frame parser-state writes (the whole run happens at
        a frame boundary, so ``_state`` stays ``TYPE_HEADER``
        throughout).  Slices come from a one-time ``bytes`` copy of the
        indexed buffer: bytes slicing + decoding is ~2x cheaper than
        going through memoryview objects.

        Returns the index of the first undispatched frame (a non-change
        frame, a stall, or ``n``).  Counters and cursor semantics are
        identical to the general loop; ``self.changes`` is incremented
        before each handler call exactly as ``_deliver_change`` does.
        """
        use_tap = type(self).__dict__.get("_bulk_payload_sink", False)
        collect = use_tap and self._payload_sink_active()
        row0 = st["row"]
        f0 = f
        fp = _fastpath_mod()
        if fp is not None:
            if self._ack_board is None:
                self._ack_board = fp.AckBoard()
            sink = [] if collect else None
            try:
                # handler exceptions propagate from here as themselves
                # (the C loop reports WIRE decode errors via status 2,
                # never as an exception — a handler-raised ValueError
                # must not be misread as a protocol error)
                f, _row, status = fp.dispatch_changes(
                    self, self._ack_board, self._on_change,
                    Change, st["buf"], st["ids_np"], *st["cols_np"],
                    f, st["row"], st["n"], st,
                    st["starts_np"] if collect else None,
                    st["lens_np"] if collect else None,
                    sink,
                )
            finally:
                # the C loop runs at a frame boundary throughout (same
                # invariant as the Python loop's finally below); the
                # sink drains even when a handler raised — those
                # changes WERE delivered, so their digests are owed
                # (matching the streaming path's submit-before-deliver)
                self._missing = 0
                self._state = TYPE_HEADER
                if _OBS.on and st["row"] > row0:
                    _M_DEC_CHANGES.inc(st["row"] - row0)
                    # one run-level tag for the whole C dispatch (the
                    # native loop cannot tag per frame): covers the
                    # contiguous wire range of the dispatched frames
                    k = st["f"] - f0
                    if k > 0:
                        fs0, fl0 = st["starts"][f0], st["lens"][f0]
                        last = f0 + k - 1
                        off0 = st["base"] + fs0 - _header_len(fl0)
                        end = st["base"] + st["starts"][last] \
                            + st["lens"][last]
                        _trace_instant("decoder.frame.run", offset=off0,
                                       kind="change", frames=k,
                                       wire_len=end - off0)
                        self._lit_cost_change_run(
                            end - off0, sum(st["lens"][f0:f0 + k]), k)
                if use_tap:
                    self._note_change_payloads(sink, st["row"] - row0)
            if status == 2:
                self.destroy(self._protocol_error(
                    st.pop("decode_error", "invalid change payload")))
            return f

        bbuf = st.get("bbuf")
        if bbuf is None:
            bbuf = st["bbuf"] = bytes(st["buf"])
        rows = self._cols_lists(st)
        ids = st["ids"]
        fstarts = st["starts"]
        flens = st["lens"]
        sink = [] if collect else None
        n = st["n"]
        row = st["row"]
        on_change = self._on_change
        lock = self._ack_lock
        obs_on = _OBS.on  # hoisted: one load for the whole run
        base = st["base"]
        mk = Change.__new__
        mka = _FastAck.__new__
        Ch = Change
        FA = _FastAck
        TC = TYPE_CHANGE
        try:
            while f < n and ids[f] == TC:
                (cg, fr, to, ko, kl, so, sl, vo, vl) = rows[row]
                try:
                    c = mk(Ch)
                    c.key = bbuf[ko : ko + kl].decode("utf-8")
                    c.change = cg
                    c.from_ = fr
                    c.to = to
                    c.value = bbuf[vo : vo + vl] if vl >= 0 else b""
                    c.subset = (bbuf[so : so + sl].decode("utf-8")
                                if sl >= 0 else "")
                except ValueError as e:  # incl. UnicodeDecodeError
                    self.destroy(self._protocol_error(str(e), cause=e))
                    return f
                if sink is not None:  # valid frame: its digest is owed
                    fs = fstarts[f]
                    sink.append(bbuf[fs : fs + flens[f]])
                row += 1
                f += 1
                self.changes += 1
                if obs_on:
                    fl = flens[f - 1]
                    hl = _header_len(fl)
                    _trace_instant("decoder.frame",
                                   offset=base + fstarts[f - 1] - hl,
                                   kind="change", wire_len=hl + fl)
                if on_change is not None:
                    ack = mka(FA)
                    ack.dec = self
                    ack.state = 0
                    on_change(c, ack)
                    if ack.state != 1:
                        with lock:
                            if ack.state == 0:
                                ack.state = 2  # armed: handler went async
                                self._pending += 1
                    # default: drop (reference: decode.js:54-56)
                if self.destroyed or self._pending > 0 \
                        or self._paused_readers > 0:
                    return f
        finally:
            # BOTH cursor halves, atomically — matching the C loop's
            # unconditional write-back: a handler that raises after
            # row/f advanced must leave them advanced together, or the
            # resume re-pairs frame payloads with the wrong rows
            # (round-5 advisor, high)
            st["f"] = f
            st["row"] = row
            self._missing = 0
            self._state = TYPE_HEADER
            if _OBS.on and row > row0:
                _M_DEC_CHANGES.inc(row - row0)
                ptot = sum(flens[f0:f])
                self._lit_cost_change_run(
                    ptot + sum(_header_len(x) for x in flens[f0:f]),
                    ptot, f - f0)
            if use_tap:
                self._note_change_payloads(sink, row - row0)
        return f

    def _consume_chunk(self, chunk: memoryview) -> memoryview | None:
        if self._state == TYPE_HEADER:
            return self._scan_header(chunk)
        if self._state == TYPE_CHANGE:
            return self._change_data(chunk)
        if self._state == TYPE_BLOB:
            return self._blob_data(chunk)
        if self._state == TYPE_CHANGE_BATCH:
            return self._batch_data(chunk)
        if self._state == TYPE_RECONCILE:
            return self._reconcile_data(chunk)
        if self._state == TYPE_SNAPSHOT:
            return self._snapshot_data(chunk)
        raise AssertionError(f"bad parser state {self._state}")

    def _scan_header(self, chunk: memoryview) -> memoryview | None:
        """Byte-at-a-time varint scan; the byte after the varint is the type
        id (reference: decode.js:251-262). Bounded at MAX_HEADER_LEN."""
        i = 0
        n = len(chunk)
        while i < n:
            self._header.append(chunk[i])
            i += 1
            # varint terminated iff the *previous* byte had its MSB clear and
            # we now also hold the id byte.
            if len(self._header) >= 2 and not (self._header[-2] & 0x80):
                hdr_len = len(self._header)
                self._parsed += i
                # this frame's wire start: where its first header byte
                # was consumed (the causal key both peers share)
                self._frame_start = self._parsed - hdr_len
                try:
                    framed_len, _ = decode_uvarint(self._header)
                except ValueError as e:  # e.g. varint exceeds 64 bits
                    self.destroy(self._protocol_error(str(e), cause=e))
                    return None
                type_id = self._header[-1]
                self._header.clear()
                self._missing = framed_len - 1  # length counts the id byte
                if framed_len < 1:
                    self.destroy(self._protocol_error("frame length must be >= 1"))
                    return None
                if type_id == TYPE_CHANGE:
                    self._state = TYPE_CHANGE
                    self._payload_parts = None
                elif type_id == TYPE_CHANGE_BATCH:
                    self._state = TYPE_CHANGE_BATCH
                    self._payload_parts = None
                elif type_id == TYPE_RECONCILE:
                    self._state = TYPE_RECONCILE
                    self._payload_parts = None
                elif type_id == TYPE_SNAPSHOT:
                    self._state = TYPE_SNAPSHOT
                    self._payload_parts = None
                elif type_id == TYPE_BLOB:
                    self._state = TYPE_BLOB
                    self._current_blob = None
                    try:
                        self._open_blob_if_ready()
                    except BaseException:
                        # handler raise: the chunk's remaining bytes are
                        # only in this local — requeue them or a caught
                        # raise-then-resume silently loses every frame
                        # after this one in the same write
                        self._requeue_tail(chunk[i:])
                        raise
                else:
                    self.destroy(
                        self._protocol_error(
                            f"Protocol error, unknown type: {type_id}")
                    )
                    return None
                return chunk[i:]
            if len(self._header) >= MAX_HEADER_LEN:
                self._parsed += i
                self.destroy(self._protocol_error("frame header too long"))
                return None
        self._parsed += n  # header still accumulating across chunks
        return None

    # -- change frames -------------------------------------------------------

    def _change_data(self, chunk: memoryview) -> memoryview | None:
        if self._payload_parts is None and len(chunk) >= self._missing:
            # fast path: whole payload inside one chunk — zero-copy slice
            # (reference: decode.js:217-227)
            payload = chunk[: self._missing]
            rest = chunk[self._missing :]
            self._parsed += self._missing
            self._missing = 0
            try:
                self._finish_change(payload)
            except BaseException:
                self._requeue_tail(rest)  # handler raise: keep the tail
                raise
            return rest
        # slow path: accumulate across chunk boundaries (reference:
        # decode.js:229-248)
        if self._payload_parts is None:
            self._payload_parts = []
        take = min(len(chunk), self._missing)
        self._payload_parts.append(bytes(chunk[:take]))
        self._parsed += take
        self._missing -= take
        rest = chunk[take:]
        if self._missing == 0:
            parts, self._payload_parts = self._payload_parts, None
            try:
                self._finish_change(b"".join(parts))
            except BaseException:
                self._requeue_tail(rest)  # handler raise: keep the tail
                raise
        return rest

    # -- wire cost lit helpers (ISSUE 20) ------------------------------------
    # Each hot path forks ONCE on `_OBS.on`; the helper below the fork
    # holds every wirecost symbol, so the dark twin's bytecode provably
    # references none of them (tests/test_wirecost.py asserts it) and
    # the disabled cost stays one attribute load.  The frame CLASS is a
    # string literal at every call (the datlint obs-discipline
    # contract).

    def _lit_cost_change(self, plen: int) -> None:
        _wirecost.account("change", self.cost_link, "rx", plen,
                          _header_len(plen))

    def _lit_cost_change_run(self, wire_total: int, payload_total: int,
                             frames: int) -> None:
        _wirecost.account("change", self.cost_link, "rx", payload_total,
                          wire_total - payload_total, frames)

    def _lit_cost_batch(self, plen: int, cols, rows: int) -> None:
        from ..wire import batch_codec

        hl = _header_len(plen)
        _wirecost.account("change_batch", self.cost_link, "rx", plen, hl)
        # satellite: the receiver prices the batch savings with the SAME
        # exact arithmetic the encoder ran pre-encode — decoded column
        # lengths feed the identical per-record estimate, so the two
        # counters agree to the byte
        est = batch_codec.estimate_per_record_bytes(
            cols.key_len, cols.sub_len, cols.val_len,
            cols.change, cols.from_, cols.to)
        saved = int(est) - (hl + plen)
        if saved > 0:
            _M_BATCH_SAVED_RX.inc(saved)
            _wirecost.note_saved(self.cost_link, "rx", saved)

    def _lit_cost_reconcile(self, plen: int) -> None:
        _wirecost.account("reconcile", self.cost_link, "rx", plen,
                          _header_len(plen))

    def _lit_cost_snapshot(self, plen: int) -> None:
        _wirecost.account("snapshot", self.cost_link, "rx", plen,
                          _header_len(plen))

    def _lit_cost_blob(self, length: int) -> None:
        # accrued in full at frame open — the same moment the
        # decoder.frame tag prices the whole frame
        _wirecost.account("blob", self.cost_link, "rx", length,
                          _header_len(length))

    def _lit_cost_failure(self, message: str) -> None:
        # a wire fault: the ledger keeps its last watermarks (the cost
        # did not heal) — only the failure counter moves
        _wirecost.note_failure(self.cost_link, "rx", message)

    def _finish_change(self, payload) -> None:
        try:
            change = decode_change(payload)
        except ValueError as e:
            self.destroy(self._protocol_error(str(e), cause=e))
            return
        self._deliver_change(change, payload)

    def _deliver_change(self, change: Change | None, payload) -> None:
        """Deliver one decoded change: the single hook both parse paths
        (streaming scanner and native bulk index) funnel through, so
        subclasses adding per-change work (the TPU backend hashes every
        payload) override exactly one method.

        Private contract: ``change`` may be ``None`` ONLY when no change
        handler is registered (``self._on_change is None``) — the bulk
        loop skips dead object construction then.  Subclasses must use
        ``payload``, not ``change``, for handler-independent work."""
        self.changes += 1
        if _OBS.on:
            _M_DEC_CHANGES.inc()
            _trace_instant("decoder.frame", offset=self._frame_start,
                           kind="change",
                           wire_len=_header_len(len(payload))
                           + len(payload))
            self._lit_cost_change(len(payload))
        self._state = TYPE_HEADER
        if self._on_change is not None:
            # same deferred-arm ack as the bulk fast loop: a sync ack
            # (the common case) never touches the pending counter, and
            # the lock arbitrates the cross-thread handler-returned vs
            # done() race exactly as there
            ack = _FastAck(self)
            self._on_change(change, ack)
            if ack.state != 1:
                with self._ack_lock:
                    if ack.state == 0:
                        ack.state = 2  # armed: handler went async
                        self._pending += 1
        # default: drop (reference: decode.js:54-56)

    # -- ChangeBatch frames --------------------------------------------------

    def _sized_payload_data(self, chunk: memoryview,
                            finish) -> memoryview | None:
        """Accumulate one whole-payload frame across transport chunks
        and hand the complete payload to ``finish`` — the shared
        parse/requeue discipline of ChangeBatch and reconcile frames
        (same slicing as :meth:`_change_data`, which keeps its own copy:
        per-record changes are the hot path and must not pay a callback
        indirection per frame)."""
        if self._payload_parts is None and len(chunk) >= self._missing:
            payload = chunk[: self._missing]
            rest = chunk[self._missing :]
            self._parsed += self._missing
            self._missing = 0
            try:
                finish(payload)
            except BaseException:
                self._requeue_tail(rest)  # handler raise: keep the tail
                raise
            return rest
        if self._payload_parts is None:
            self._payload_parts = []
        take = min(len(chunk), self._missing)
        self._payload_parts.append(bytes(chunk[:take]))
        self._parsed += take
        self._missing -= take
        rest = chunk[take:]
        if self._missing == 0:
            parts, self._payload_parts = self._payload_parts, None
            try:
                finish(b"".join(parts))
            except BaseException:
                self._requeue_tail(rest)  # handler raise: keep the tail
                raise
        return rest

    def _batch_data(self, chunk: memoryview) -> memoryview | None:
        return self._sized_payload_data(chunk, self._finish_change_batch)

    def _finish_change_batch(self, payload) -> None:
        """Decode one complete ChangeBatch payload and start dispatching
        its rows.  Decode is pure array reinterpretation
        (wire/batch_codec.py) — a structurally corrupt payload (bad
        width, truncated column, out-of-range index, non-UTF-8
        dictionary) destroys the session with a ProtocolError exactly
        like a corrupt per-record Change payload."""
        from ..wire import batch_codec

        try:
            cols = batch_codec.decode_change_batch(payload)
        except ValueError as e:
            self.destroy(self._protocol_error(str(e), cause=e))
            return
        n = len(cols.change)
        if _OBS.on:
            _M_DEC_BATCH_FRAMES.inc()
            _trace_instant("decoder.frame", offset=self._frame_start,
                           kind="change_batch", rows=n,
                           wire_len=_header_len(len(payload))
                           + len(payload))
            self._lit_cost_batch(len(payload), cols, n)
        self._state = TYPE_HEADER
        # digest tap: the whole frame's rows are owed at acceptance (the
        # blob doctrine — one frame, one accounting point), BEFORE any
        # row reaches a handler, keeping submit order = wire order
        self._note_change_batch(cols, n)
        self._pbatch = {"cols": cols, "row": 0, "n": n, "bbuf": None}
        self._run_pending_batch()

    def _note_change_batch(self, cols, n: int) -> None:
        """Hook: one call per accepted ChangeBatch frame with its decoded
        columns, before row dispatch (the digest decoder re-encodes rows
        canonically and submits their digests here).  Base: no-op."""

    def _run_pending_batch(self) -> None:
        """Dispatch rows from the parked batch cursor until done or
        stalled — the per-row half of batch delivery, only as fast as
        the registered handler shape allows (a ``change_batch`` handler
        takes the columns whole; a per-record ``change`` handler gets
        one slot-built :class:`Change` per row, same contract as the
        bulk fast loop)."""
        pb = self._pbatch
        assert pb is not None
        cols = pb["cols"]
        n = pb["n"]
        row = pb["row"]
        on_batch = self._on_change_batch
        if on_batch is not None and row == 0:
            # whole-batch delivery: one handler call, one ack
            self._pbatch = None
            self.changes += n
            self._batch_rows_seen += n
            self._batch_frames_done += 1
            if _OBS.on:
                _M_DEC_CHANGES.inc(n)
            ack = _FastAck(self)
            on_batch(cols, ack)
            if ack.state != 1:
                with self._ack_lock:
                    if ack.state == 0:
                        ack.state = 2  # armed: handler went async
                        self._pending += 1
            return
        on_change = self._on_change
        if on_change is None:
            # no handler: rows drop (reference: decode.js:54-56); the
            # payload was already structurally validated at decode
            k = n - row
            self._pbatch = None
            self.changes += k
            self._batch_rows_seen += k
            self._batch_frames_done += 1
            if _OBS.on and k:
                _M_DEC_CHANGES.inc(k)
            return
        bbuf = pb["bbuf"]
        if bbuf is None:
            # one bytes materialization per batch: bytes slicing +
            # decoding beats going through memoryview objects (same
            # measurement as the bulk fast loop's bbuf)
            bbuf = pb["bbuf"] = cols.buf.tobytes()
        ko, kl = cols.key_off, cols.key_len
        so, sl = cols.sub_off, cols.sub_len
        vo, vl = cols.val_off, cols.val_len
        cg, fr, tv = cols.change, cols.from_, cols.to
        row0 = row
        lock = self._ack_lock
        mk = Change.__new__
        mka = _FastAck.__new__
        Ch = Change
        FA = _FastAck
        try:
            while row < n:
                c = mk(Ch)
                # dictionary UTF-8 was validated at decode; this decode
                # cannot fail structurally
                c.key = bbuf[ko[row] : ko[row] + kl[row]].decode("utf-8")
                c.change = int(cg[row])
                c.from_ = int(fr[row])
                c.to = int(tv[row])
                c.value = (bbuf[vo[row] : vo[row] + vl[row]]
                           if vl[row] >= 0 else b"")
                c.subset = (bbuf[so[row] : so[row] + sl[row]].decode("utf-8")
                            if sl[row] >= 0 else "")
                # delivery consumes the row BEFORE the handler can raise
                # (the bulk-loop doctrine): a caught raise-then-resume
                # re-enters at the next row, never re-delivering
                row += 1
                self.changes += 1
                self._batch_rows_seen += 1
                ack = mka(FA)
                ack.dec = self
                ack.state = 0
                on_change(c, ack)
                if ack.state != 1:
                    with lock:
                        if ack.state == 0:
                            ack.state = 2  # armed: handler went async
                            self._pending += 1
                if self.destroyed or self._pending > 0 \
                        or self._paused_readers > 0:
                    return
        finally:
            pb["row"] = row
            if _OBS.on and row > row0:
                _M_DEC_CHANGES.inc(row - row0)
            if row >= n and self._pbatch is pb:
                self._pbatch = None
                self._batch_frames_done += 1

    # -- reconcile frames ----------------------------------------------------

    def _reconcile_data(self, chunk: memoryview) -> memoryview | None:
        return self._sized_payload_data(chunk, self._finish_reconcile)

    def _finish_reconcile(self, payload) -> None:
        """Decode one complete reconcile payload and dispatch it whole.

        Structural corruption (bad subtype/version, truncated symbol
        run, trailing bytes) destroys the session with a ProtocolError
        exactly like a corrupt Change payload — the fault-injection
        contract: a reconcile session fails STRUCTURED, never decodes a
        wrong diff from a torn frame."""
        from ..wire import reconcile_codec

        try:
            msg = reconcile_codec.decode_reconcile(payload)
        except ValueError as e:
            self.destroy(self._protocol_error(str(e), cause=e))
            return
        if _OBS.on:
            _M_DEC_RC_FRAMES.inc()
            _trace_instant("decoder.frame", offset=self._frame_start,
                           kind="reconcile",
                           wire_len=_header_len(len(payload))
                           + len(payload))
            self._lit_cost_reconcile(len(payload))
        self._state = TYPE_HEADER
        # delivery consumes the frame BEFORE the handler can raise (the
        # change/blob doctrine): a caught raise-then-resume re-enters at
        # the next frame, never re-delivering this message
        self.reconcile_frames += 1
        if self._on_reconcile is not None:
            ack = _FastAck(self)
            self._on_reconcile(msg, ack)
            if ack.state != 1:
                with self._ack_lock:
                    if ack.state == 0:
                        ack.state = 2  # armed: handler went async
                        self._pending += 1
        # default: drop (the unhandled-changes doctrine)

    # -- snapshot frames -----------------------------------------------------

    def _snapshot_data(self, chunk: memoryview) -> memoryview | None:
        return self._sized_payload_data(chunk, self._finish_snapshot)

    def _finish_snapshot(self, payload) -> None:
        """Decode one complete snapshot payload and dispatch it whole.

        Structural corruption (bad subtype/version, truncated chunk
        entry, trailing bytes) destroys the session with a
        ProtocolError exactly like a corrupt Change payload — the
        fault-injection contract: a snapshot session fails STRUCTURED,
        never assembles from a torn frame (a flipped chunk BODY is the
        per-chunk digest verification's job in the joiner)."""
        from ..wire import snapshot_codec

        try:
            msg = snapshot_codec.decode_snapshot(payload)
        except ValueError as e:
            self.destroy(self._protocol_error(str(e), cause=e))
            return
        if _OBS.on:
            _M_DEC_SN_FRAMES.inc()
            _trace_instant("decoder.frame", offset=self._frame_start,
                           kind="snapshot",
                           wire_len=_header_len(len(payload))
                           + len(payload))
            self._lit_cost_snapshot(len(payload))
        self._state = TYPE_HEADER
        # delivery consumes the frame BEFORE the handler can raise (the
        # change/blob doctrine): a caught raise-then-resume re-enters at
        # the next frame, never re-delivering this message
        self.snapshot_frames += 1
        if self._on_snapshot is not None:
            ack = _FastAck(self)
            self._on_snapshot(msg, ack)
            if ack.state != 1:
                with self._ack_lock:
                    if ack.state == 0:
                        ack.state = 2  # armed: handler went async
                        self._pending += 1
        # default: drop (the unhandled-changes doctrine)

    # -- blob frames ---------------------------------------------------------

    def _open_blob_if_ready(self) -> None:
        """Create the reader and invoke the app handler.

        The blob-level ``done`` does NOT gate parsing of the blob's own
        payload — the reference hands the handler ``_down`` without a matching
        ``_up`` and instead increments pending at blob END
        (reference: decode.js:171-177,182), so frames *after* the blob wait
        for the app's ack. The latch below reproduces exactly that pairing.
        (The reference defers reader creation to the first payload byte,
        decode.js:180-184; creating at header time additionally supports
        zero-length blobs.)"""
        blob = BlobReader(self, self._missing)
        self._current_blob = blob
        self.blobs += 1
        if _OBS.on:
            _M_DEC_BLOBS.inc()
            _trace_instant("decoder.frame", offset=self._frame_start,
                           kind="blob",
                           wire_len=_header_len(self._missing)
                           + self._missing)
            self._lit_cost_blob(self._missing)
        latch = {"ended": False, "acked": False}
        blob._pending_latch = latch

        def done() -> None:
            if latch["acked"]:
                return
            latch["acked"] = True
            if latch["ended"]:
                self._pending -= 1
                self._resume()

        handler = self._on_blob if self._on_blob is not None else _drain_blob
        try:
            handler(blob, done)
        finally:
            # a zero-length blob has no payload bytes to route through
            # _blob_data's exception-safe end: if the handler raises,
            # the blob must still END here or _state stays TYPE_BLOB
            # with the reader dangling — a caught raise-then-resume
            # would then fail end() with a spurious mid-frame error
            # (both dispatch paths share this site)
            if self._missing == 0:
                self._end_blob()

    def _blob_data(self, chunk: memoryview) -> memoryview | None:
        blob = self._current_blob
        assert blob is not None
        take = min(len(chunk), self._missing)
        self._parsed += take
        self._missing -= take
        # materialize ONCE; bytes are immutable, so every consumer —
        # the BlobReader and any _note_blob_bytes subscriber (digest
        # buffering) — shares this object instead of re-copying the
        # scratch memoryview
        data = bytes(chunk[:take])
        rest = chunk[take:]
        if _OBS.on:
            _M_DEC_BLOB_BYTES.inc(take)
        try:
            self._note_blob_bytes(data)
            blob._deliver(data)
        except BaseException:
            self._requeue_tail(rest)  # reader raise: keep the tail
            raise
        finally:
            # delivery consumed these bytes even if a reader callback
            # raised: the blob must still END, or _state stays TYPE_BLOB
            # with _current_blob dangling — a caught raise-then-resume
            # on the final chunk would then fail end() with a spurious
            # mid-frame ProtocolError and never fire on_end
            if self._missing == 0:
                self._end_blob()
        return rest

    def _note_blob_bytes(self, data: bytes) -> None:
        """Hook: called with each materialized blob payload piece (exactly
        the bytes object delivered to the BlobReader).  Base: no-op."""

    def _end_blob(self) -> None:
        blob, self._current_blob = self._current_blob, None
        self._state = TYPE_HEADER
        if blob is not None:
            # Hold the pipeline until the app acks the blob — the
            # `_pending++` of the reference's _onblobend (decode.js:171-177).
            latch = blob._pending_latch
            if not latch["acked"]:
                latch["ended"] = True
                self._pending += 1
            blob._finish()
