"""Decoder — the consuming end of a replication session.

Capability parity with the reference Decoder (reference: decode.js:63-262),
re-designed as a push-based incremental parser with an explicit pending
counter instead of Node Writable plumbing:

* :meth:`write` feeds wire bytes; the internal state machine is
  header → (change | blob payload) → header …, slicing without copying on the
  fast path (reference keeps the same discipline, decode.js:217-227,198-201).
* Handlers are registered with :meth:`change` / :meth:`blob` /
  :meth:`finalize` (same registration-style API as the reference,
  decode.js:112-122). Each handler receives a ``done`` callable;
  **backpressure**: while any ``done`` is outstanding, parsing pauses and
  :meth:`write` returns ``False`` — the analogue of the reference withholding
  the Writable's callback (reference: decode.js:87-99,168).
* Unregistered handlers never deadlock the pipeline: changes are dropped,
  blobs drained, finalize auto-acked (reference: decode.js:50-61).
* :meth:`end` invokes the finalize handler after all prior frames are
  consumed, before the session completes — the sentinel-write trick of the
  reference (decode.js:6,124-142) becomes an explicit queued finalization.
* Unknown frame type ids destroy the session with
  :class:`~..wire.framing.ProtocolError` (reference: decode.js:159-161).
* Counters ``bytes`` / ``changes`` / ``blobs`` (reference: decode.js:68-70).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Optional

from ..wire.change_codec import Change, decode_change
from ..wire.framing import MAX_HEADER_LEN, TYPE_BLOB, TYPE_CHANGE, TYPE_HEADER, ProtocolError
from ..wire.varint import decode_uvarint

OnDone = Optional[Callable[[], None]]


class DecoderDestroyedError(Exception):
    pass


class BlobReader:
    """Read side of one streamed blob, handed to the app's blob handler.

    Chunks are delivered through :meth:`on_data` as they are parsed; chunks
    arriving before a handler is registered are buffered and replayed at
    registration (the Readable-buffer behavior of the reference's BlobStream,
    reference: decode.js:8-48). :meth:`pause` / :meth:`resume` give the app
    per-chunk backpressure: while paused the decoder stops parsing, which
    propagates to the transport.
    """

    def __init__(self, decoder: "Decoder", length: int):
        self._decoder = decoder
        self.length = length
        self.received = 0
        self.ended = False
        self.destroyed = False
        self._data_cb: Optional[Callable[[bytes], None]] = None
        self._end_cbs: list[Callable[[], None]] = []
        self._buffered: list[bytes] = []
        self._paused = False

    def on_data(self, cb: Callable[[bytes], None]) -> "BlobReader":
        self._data_cb = cb
        if self._buffered:
            chunks, self._buffered = self._buffered, []
            for c in chunks:
                cb(c)
        return self

    def on_end(self, cb: Callable[[], None]) -> "BlobReader":
        if self.ended:
            cb()
        else:
            self._end_cbs.append(cb)
        return self

    def collect(self, cb: Callable[[bytes], None]) -> "BlobReader":
        """Convenience: buffer the whole blob and deliver it once on end —
        the role `concat-stream` plays in the reference suite
        (reference: test/basic.js:36-40)."""
        parts: list[bytes] = []
        self.on_data(parts.append)
        self.on_end(lambda: cb(b"".join(parts)))
        return self

    def pause(self) -> None:
        """Stop the decoder from parsing further input (chunk granularity)
        until :meth:`resume` — per-chunk backpressure, the analogue of the
        reference's Readable drain accounting (reference: decode.js:35-48)."""
        if self._paused:
            return
        self._paused = True
        self._decoder._paused_readers += 1

    def resume(self) -> None:
        if not self._paused:
            return
        self._paused = False
        self._decoder._paused_readers -= 1
        self._decoder._resume()

    def destroy(self, err: Exception | None = None) -> None:
        """Destroying a blob reader tears down the whole session
        (reference: decode.js:20-26)."""
        if self.destroyed:
            return
        self.destroyed = True
        self._decoder.destroy(err)

    # -- driven by the decoder ---------------------------------------------

    def _deliver(self, chunk: bytes) -> None:
        self.received += len(chunk)
        if self._data_cb is not None:
            self._data_cb(chunk)
        else:
            self._buffered.append(chunk)

    def _finish(self) -> None:
        self.ended = True
        cbs, self._end_cbs = self._end_cbs, []
        for cb in cbs:
            cb()


def _drain_blob(blob: BlobReader, done: Callable[[], None]) -> None:
    """Default blob handler: consume and discard (reference: decode.js:58-61).

    The discarding data callback matters: without one, BlobReader buffers
    every chunk for later replay and an unconsumed blob accumulates whole
    in host RAM — the opposite of draining.
    """
    blob.on_data(lambda _chunk: None)
    blob.on_end(done)


class Decoder:
    """Push-based incremental wire parser. See module docstring."""

    def __init__(self):
        self.bytes = 0
        self.changes = 0
        self.blobs = 0
        self.destroyed = False
        self.finished = False
        self._on_change: Callable[[Change, Callable[[], None]], None] | None = None
        self._on_blob: Callable[[BlobReader, Callable[[], None]], None] | None = None
        self._on_finalize: Callable[[Callable[[], None]], None] | None = None
        self._error_cbs: list[Callable[[Exception | None], None]] = []
        self._finish_cbs: list[Callable[[], None]] = []

        # parser state
        self._state = TYPE_HEADER
        self._header = bytearray()  # accumulating varint+id bytes
        self._missing = 0  # payload bytes still to consume
        self._payload_parts: list[bytes] | None = None  # change slow path
        self._current_blob: BlobReader | None = None

        # flow control
        self._pending = 0
        self._paused_readers = 0
        self._overflow: deque[memoryview] = deque()  # unparsed input, in order
        self._write_cbs: list[Callable[[], None]] = []
        self._end_queued = False
        self._end_cb: OnDone = None
        self._consuming = False  # reentrancy guard for _consume

    # -- handler registration (same shape as the reference API) -------------

    def change(self, cb: Callable[[Change, Callable[[], None]], None]) -> "Decoder":
        self._on_change = cb
        return self

    def blob(self, cb: Callable[[BlobReader, Callable[[], None]], None]) -> "Decoder":
        self._on_blob = cb
        return self

    def finalize(self, cb: Callable[[Callable[[], None]], None]) -> "Decoder":
        self._on_finalize = cb
        return self

    def on_error(self, cb: Callable[[Exception | None], None]) -> "Decoder":
        self._error_cbs.append(cb)
        return self

    def on_finish(self, cb: Callable[[], None]) -> "Decoder":
        if self.finished:
            cb()
        else:
            self._finish_cbs.append(cb)
        return self

    # -- write side ---------------------------------------------------------

    def write(self, data, on_consumed: OnDone = None) -> bool:
        """Feed wire bytes. Returns True if fully consumed synchronously;
        False if parsing stalled on an outstanding ``done`` (the
        ``on_consumed`` callback then fires when the app drains —
        reference: decode.js:124-133,168)."""
        if self.destroyed:
            raise DecoderDestroyedError("write after destroy")
        if self.finished or self._end_queued:
            raise DecoderDestroyedError("write after end")
        data = memoryview(data.encode("utf-8") if isinstance(data, str) else data)
        self.bytes += len(data)
        if len(data):
            self._overflow.append(data)
        self._consume()
        if self._overflow or self._stalled():
            if on_consumed is not None:
                self._write_cbs.append(on_consumed)
            return False
        if on_consumed is not None:
            on_consumed()
        return True

    def end(self, on_finished: OnDone = None) -> None:
        """Graceful end: after all prior frames are consumed, the finalize
        handler runs, then the session finishes (reference: decode.js:135-142)."""
        if self.destroyed:
            raise DecoderDestroyedError("end after destroy")
        if self._end_queued or self.finished:
            return
        self._end_queued = True
        self._end_cb = on_finished
        self._maybe_finalize()

    def destroy(self, err: Exception | None = None) -> None:
        """Fail-fast teardown, cascading to a live blob reader
        (reference: decode.js:104-110)."""
        if self.destroyed:
            return
        self.destroyed = True
        blob, self._current_blob = self._current_blob, None
        if blob is not None and not blob.destroyed:
            blob.destroyed = True
        self._overflow.clear()
        for cb in self._error_cbs:
            cb(err)
        # Release parked write-completion callbacks so a transport blocked on
        # "consumed" wakes up and observes the destroyed state (Node errors
        # the pending Writable callback for the same reason).
        cbs, self._write_cbs = self._write_cbs, []
        for cb in cbs:
            cb()

    def writable(self) -> bool:
        return not (self._stalled() or self._overflow or self.destroyed or self.finished)

    # -- flow control --------------------------------------------------------

    def _stalled(self) -> bool:
        return self._pending > 0 or self._paused_readers > 0

    def _up(self) -> Callable[[], None]:
        """Create a one-shot ``done`` for an app callback; parsing pauses
        while any are outstanding (reference: decode.js:87-99)."""
        self._pending += 1
        fired = False

        def done() -> None:
            nonlocal fired
            if fired:
                return
            fired = True
            self._pending -= 1
            self._resume()

        return done

    def _resume(self) -> None:
        # While _consume is live on the stack, the outer loop may hold a
        # chunk's unparsed remainder in a local — it will keep going (pending
        # just dropped) and run the drained notifications itself, so a nested
        # resume must be a no-op rather than observe a falsely-empty overflow.
        if self.destroyed or self._stalled() or self._consuming:
            return
        self._consume()

    def _maybe_finalize(self) -> None:
        if (
            not self._end_queued
            or self.finished
            or self.destroyed
            or self._overflow
            or self._stalled()
            or self._consuming  # drained-check at the end of _consume re-runs this
        ):
            return
        if self._state != TYPE_HEADER or self._header:
            self.destroy(ProtocolError("stream ended mid-frame"))
            return
        self._end_queued = False  # run once

        def finish() -> None:
            self.finished = True
            cb, self._end_cb = self._end_cb, None
            if cb is not None:
                cb()
            cbs, self._finish_cbs = self._finish_cbs, []
            for fcb in cbs:
                fcb()

        if self._on_finalize is not None:
            self._on_finalize(finish)
        else:
            finish()

    # -- parser --------------------------------------------------------------

    def _consume(self) -> None:
        """Main parse loop: drain overflow while the app is keeping up
        (reference: decode.js:144-169).

        Guarded against reentrancy: a handler that acks synchronously while
        the loop holds a chunk's unparsed remainder in a local must not
        re-enter and pop the *next* queued chunk out of order — the guard
        makes the nested resume a no-op and the outer loop carries on.
        """
        if self._consuming:
            return
        self._consuming = True
        try:
            while self._overflow and not self._stalled() and not self.destroyed:
                chunk = self._overflow.popleft()
                rest = self._consume_chunk(chunk)
                if self.destroyed:
                    return
                if rest is not None and len(rest):
                    self._overflow.appendleft(rest)
        finally:
            self._consuming = False
        # Fully drained and nothing outstanding: release parked writers and
        # run a queued finalization. This lives here (not in _resume) so a
        # handler acking synchronously mid-loop cannot finalize while the
        # loop still holds unparsed bytes in a local.
        if not self.destroyed and not self._overflow and not self._stalled():
            cbs, self._write_cbs = self._write_cbs, []
            for cb in cbs:
                cb()
            self._maybe_finalize()

    def _consume_chunk(self, chunk: memoryview) -> memoryview | None:
        if self._state == TYPE_HEADER:
            return self._scan_header(chunk)
        if self._state == TYPE_CHANGE:
            return self._change_data(chunk)
        if self._state == TYPE_BLOB:
            return self._blob_data(chunk)
        raise AssertionError(f"bad parser state {self._state}")

    def _scan_header(self, chunk: memoryview) -> memoryview | None:
        """Byte-at-a-time varint scan; the byte after the varint is the type
        id (reference: decode.js:251-262). Bounded at MAX_HEADER_LEN."""
        i = 0
        n = len(chunk)
        while i < n:
            self._header.append(chunk[i])
            i += 1
            # varint terminated iff the *previous* byte had its MSB clear and
            # we now also hold the id byte.
            if len(self._header) >= 2 and not (self._header[-2] & 0x80):
                try:
                    framed_len, _ = decode_uvarint(self._header)
                except ValueError as e:  # e.g. varint exceeds 64 bits
                    self.destroy(ProtocolError(str(e)))
                    return None
                type_id = self._header[-1]
                self._header.clear()
                self._missing = framed_len - 1  # length counts the id byte
                if framed_len < 1:
                    self.destroy(ProtocolError("frame length must be >= 1"))
                    return None
                if type_id == TYPE_CHANGE:
                    self._state = TYPE_CHANGE
                    self._payload_parts = None
                elif type_id == TYPE_BLOB:
                    self._state = TYPE_BLOB
                    self._current_blob = None
                    self._open_blob_if_ready()
                else:
                    self.destroy(
                        ProtocolError(f"Protocol error, unknown type: {type_id}")
                    )
                    return None
                return chunk[i:]
            if len(self._header) >= MAX_HEADER_LEN:
                self.destroy(ProtocolError("frame header too long"))
                return None
        return None

    # -- change frames -------------------------------------------------------

    def _change_data(self, chunk: memoryview) -> memoryview | None:
        if self._payload_parts is None and len(chunk) >= self._missing:
            # fast path: whole payload inside one chunk — zero-copy slice
            # (reference: decode.js:217-227)
            payload = chunk[: self._missing]
            rest = chunk[self._missing :]
            self._missing = 0
            self._finish_change(payload)
            return rest
        # slow path: accumulate across chunk boundaries (reference:
        # decode.js:229-248)
        if self._payload_parts is None:
            self._payload_parts = []
        take = min(len(chunk), self._missing)
        self._payload_parts.append(bytes(chunk[:take]))
        self._missing -= take
        rest = chunk[take:]
        if self._missing == 0:
            parts, self._payload_parts = self._payload_parts, None
            self._finish_change(b"".join(parts))
        return rest

    def _finish_change(self, payload) -> None:
        try:
            change = decode_change(payload)
        except ValueError as e:
            self.destroy(ProtocolError(str(e)))
            return
        self.changes += 1
        self._state = TYPE_HEADER
        if self._on_change is not None:
            self._on_change(change, self._up())
        # default: drop (reference: decode.js:54-56)

    # -- blob frames ---------------------------------------------------------

    def _open_blob_if_ready(self) -> None:
        """Create the reader and invoke the app handler.

        The blob-level ``done`` does NOT gate parsing of the blob's own
        payload — the reference hands the handler ``_down`` without a matching
        ``_up`` and instead increments pending at blob END
        (reference: decode.js:171-177,182), so frames *after* the blob wait
        for the app's ack. The latch below reproduces exactly that pairing.
        (The reference defers reader creation to the first payload byte,
        decode.js:180-184; creating at header time additionally supports
        zero-length blobs.)"""
        blob = BlobReader(self, self._missing)
        self._current_blob = blob
        self.blobs += 1
        latch = {"ended": False, "acked": False}
        blob._pending_latch = latch

        def done() -> None:
            if latch["acked"]:
                return
            latch["acked"] = True
            if latch["ended"]:
                self._pending -= 1
                self._resume()

        handler = self._on_blob if self._on_blob is not None else _drain_blob
        handler(blob, done)
        if self._missing == 0:
            self._end_blob()

    def _blob_data(self, chunk: memoryview) -> memoryview | None:
        blob = self._current_blob
        assert blob is not None
        take = min(len(chunk), self._missing)
        self._missing -= take
        blob._deliver(bytes(chunk[:take]))
        rest = chunk[take:]
        if self._missing == 0:
            self._end_blob()
        return rest

    def _end_blob(self) -> None:
        blob, self._current_blob = self._current_blob, None
        self._state = TYPE_HEADER
        if blob is not None:
            # Hold the pipeline until the app acks the blob — the
            # `_pending++` of the reference's _onblobend (decode.js:171-177).
            latch = blob._pending_latch
            if not latch["acked"]:
                latch["ended"] = True
                self._pending += 1
            blob._finish()
