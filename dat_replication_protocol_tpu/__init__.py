"""dat_replication_protocol_tpu — a TPU-native replication-protocol framework.

A ground-up re-design of the capabilities of `dat-replication-protocol`
(the streaming dat replication wire codec) for TPU hardware:

* the varint-framed multibuffer wire format and the `Change` protobuf codec
  (reference: README.md:63-71, messages/schema.proto:1-8) as a host-side
  session layer with the same ordering / backpressure / finalize semantics
  (reference: encode.js, decode.js);
* batched content-hashing (BLAKE2b), Rabin rolling-hash content-defined
  chunking, and Merkle-tree diff / set reconciliation as JAX / Pallas kernels
  that process thousands of blobs per XLA dispatch;
* a ``backend='tpu'`` option on :func:`encode` / :func:`decode` that offloads
  digest work to the device while keeping the callback API unchanged;
* `jax.sharding` mesh parallelism for multi-chip scale-out.

Public entry points mirror the reference's two factories
(reference: index.js:1-2)::

    import dat_replication_protocol_tpu as protocol
    enc = protocol.encode()
    dec = protocol.decode()           # or protocol.decode(backend='tpu')
    protocol.pipe(enc, dec)
"""

from __future__ import annotations

from .session import (
    BatchPolicy,
    BlobLengthError,
    BlobReader,
    BlobWriter,
    Decoder,
    Encoder,
    Pipe,
    pipe,
)
from .wire import (
    CAP_CHANGE_BATCH,
    CAP_RECONCILE,
    CAP_SNAPSHOT,
    Change,
    ProtocolError,
    decode_change,
    encode_change,
)

__version__ = "0.1.0"


def encode(backend: str = "host", **kwargs) -> Encoder:
    """Create the producing end of a session (reference: index.js:1).

    ``backend='tpu'`` attaches a device pipeline that content-hashes outgoing
    blobs in batches (see :mod:`.backend`).
    """
    if backend == "host":
        return Encoder(**kwargs)
    if backend == "tpu":
        from .backend import tpu_backend

        return tpu_backend.TpuEncoder(**kwargs)
    raise ValueError(f"unknown backend {backend!r}")


def decode(backend: str = "host", **kwargs) -> Decoder:
    """Create the consuming end of a session (reference: index.js:2)."""
    if backend == "host":
        return Decoder(**kwargs)
    if backend == "tpu":
        from .backend import tpu_backend

        return tpu_backend.TpuDecoder(**kwargs)
    raise ValueError(f"unknown backend {backend!r}")


__all__ = [
    "encode",
    "decode",
    "pipe",
    "Pipe",
    "BatchPolicy",
    "CAP_CHANGE_BATCH",
    "CAP_RECONCILE",
    "CAP_SNAPSHOT",
    "Change",
    "ProtocolError",
    "encode_change",
    "decode_change",
    "Encoder",
    "Decoder",
    "BlobReader",
    "BlobWriter",
    "BlobLengthError",
]
