"""Batched BLAKE2b as a Pallas TPU kernel.

The XLA-scan formulation in :mod:`.blake2b` leaves VPU throughput on the
table: every scan step re-materializes carries and message slices through
fusion boundaries.  This kernel keeps the whole hash state resident in
VMEM scratch for the lifetime of a batch tile and streams message blocks
HBM -> VMEM with Pallas's pipelined block fetches, so the 12 unrolled
rounds run as straight-line VPU code with no per-block traffic beyond the
message bytes themselves.

Layout (TPU-first):

* Mosaic tiles are (8, 128) for uint32, so the batch axis is reshaped to
  ``(8, B/8)`` — every 64-bit lane-pair op covers full vector registers.
* Messages arrive pre-packed as ``(nblocks, 16, 8, B/8)`` hi/lo uint32
  (word-major), so each of the 16 message words is one contiguous
  ``(8, BTL)`` tile slice: zero strided reads in the hot loop.
* Grid = (batch_tiles, nblocks): batch tiles are embarrassingly parallel;
  the block axis is sequential ("arbitrary") with the chaining state in
  VMEM scratch, initialized at block 0 and emitted at the last block.
* Per-item variable lengths use the same active/final masks as the scan
  version (:func:`.blake2b.blake2b_packed`) — no dynamic shapes.

Round function and masks are shared with :mod:`.blake2b` (they are
shape-polymorphic), so byte-exactness is inherited from the tested scan
path.  reference: the protocol itself does no hashing (SURVEY.md §2);
this kernel serves BASELINE.json's ">= 50 GiB/s batched BLAKE2b" target.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jax_compat import COMPILER_PARAMS as _COMPILER_PARAMS

from .blake2b import _IV_HI, _IV_LO, DIGEST_SIZE, compress_soa
from ..obs.device import jit_site as _jit_site
from .u64 import U32

# batch items per kernel tile: 8 sublanes x BTL lanes
_LANE = 128
_SUBLANE = 8


class _RefLanes:
    """Working-vector lanes resident in VMEM scratch, one load/store per
    G access.

    The 16 v-lanes (32 hi/lo u32 tiles) are the kernel's register
    working set; together with message words they overflow the vector
    register file (measured: doubling the tile width halves
    throughput).  This view lets the unrolled rounds run unchanged
    (``_g`` mutates ``v`` by Python indexing) while each lane's live
    range shrinks to the G mixes that touch it — the scheduler chooses
    VMEM traffic instead of spills.  Correctness relies on Pallas's
    sequential in-kernel semantics: a G's stores are visible to the
    next G's loads.
    """

    def __init__(self, vh_ref, vl_ref):
        self._vh = vh_ref
        self._vl = vl_ref

    def __getitem__(self, i):
        i = int(i)
        return self._vh[i], self._vl[i]

    def __setitem__(self, i, pair):
        i = int(i)
        self._vh[i], self._vl[i] = pair


class _RefState:
    """Lazy chaining-state view: ``h[i]`` loads from VMEM at use site.

    ``compress_soa`` touches h twice — initializing v[0..7] before the
    rounds and xoring into the result after them — yet an eagerly-loaded
    h pins 16 hi/lo vregs across all 12 rounds for those two uses.
    Loading at the use sites makes h's live ranges two short windows the
    scheduler can place freely (the third read, _kernel's active-mask
    select, re-loads the same scratch).
    """

    def __init__(self, sth_ref, stl_ref):
        self._sh = sth_ref
        self._sl = stl_ref

    def __len__(self):
        return 8

    def __getitem__(self, i):
        i = int(i)
        return self._sh[i], self._sl[i]

    def __iter__(self):
        return (self[i] for i in range(8))


class _RefWords:
    """Lazy message-word view: ``m[w]`` issues the VMEM loads at use site.

    The unrolled rounds reference each of the 16 message words twice per
    round; materializing all 32 hi/lo word tiles up front pins 32 vector
    registers for the whole block, which together with the 32 state
    registers overflows the register file and makes the scheduler spill
    *state* (measured: block_items=2048 halves throughput).  Issuing the
    loads where the schedule consumes them leaves liveness decisions to
    Mosaic, which can rematerialize a cheap VMEM load instead of
    spilling a hot value.
    """

    def __init__(self, mh_ref, ml_ref, k: int = 0):
        self._mh = mh_ref
        self._ml = ml_ref
        self._k = k

    def __getitem__(self, w):
        w = int(w)
        return self._mh[self._k, w], self._ml[self._k, w]


def _kernel(*refs, digest_size: int, unroll: bool = True,
            msg_loads: bool = False, vmem_state: bool = False,
            state_loads: bool = False, blocks_per_step: int = 1,
            g_interleave: bool = False):
    if vmem_state:
        (len_ref, mh_ref, ml_ref, outh_ref, outl_ref,
         sth_ref, stl_ref, vh_ref, vl_ref) = refs
        sigma = None
    elif unroll:
        len_ref, mh_ref, ml_ref, outh_ref, outl_ref, sth_ref, stl_ref = refs
        sigma = None
    else:
        # scanned-rounds variant (interpreter): the schedule table rides in
        # as an input ref — pallas kernels may not capture array constants
        (len_ref, mh_ref, ml_ref, sig_ref,
         outh_ref, outl_ref, sth_ref, stl_ref) = refs
        sigma = sig_ref[:]
    j = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        shape = len_ref.shape
        param_lo = np.uint32(0x01010000 ^ digest_size)
        for w in range(8):
            sth_ref[w] = jnp.full(shape, _IV_HI[w], U32)
            lo = _IV_LO[w] ^ param_lo if w == 0 else _IV_LO[w]
            stl_ref[w] = jnp.full(shape, lo, U32)

    lengths = len_ref[:]
    # where-based max/min: Mosaic has no arith.maxui/minui legalization
    nb_ceil = (lengths + U32(127)) >> U32(7)
    item_blocks = jnp.where(nb_ceil == U32(0), U32(1), nb_ceil)

    if blocks_per_step == 1:
        ju = j.astype(U32)
        active = ju < item_blocks
        final = ju == item_blocks - U32(1)
        cap = (ju + U32(1)) << U32(7)
        t_lo = jnp.where(cap < lengths, cap, lengths)

        if msg_loads and unroll:
            m = _RefWords(mh_ref, ml_ref)
        else:
            m = [(mh_ref[0, w], ml_ref[0, w]) for w in range(16)]
        if state_loads and unroll:
            h = _RefState(sth_ref, stl_ref)
        else:
            h = [(sth_ref[w], stl_ref[w]) for w in range(8)]
        lanes = _RefLanes(vh_ref, vl_ref) if vmem_state else None
        nh = compress_soa(h, m, t_lo, final, unroll=unroll, sigma=sigma,
                          lanes=lanes, g_interleave=g_interleave)
        for w in range(8):
            sth_ref[w] = jnp.where(active, nh[w][0], h[w][0])
            stl_ref[w] = jnp.where(active, nh[w][1], h[w][1])
    else:
        # multi-block step: chain h through registers across the
        # sub-blocks, touching the VMEM chaining scratch once per step
        # instead of once per block — the structural variant pricing
        # per-grid-step overhead (mask recompute is per sub-block, but
        # state load/store, pl.when dispatch, and Mosaic's step
        # prologue/epilogue amortize over blocks_per_step compressions)
        h = [(sth_ref[w], stl_ref[w]) for w in range(8)]
        lanes = _RefLanes(vh_ref, vl_ref) if vmem_state else None
        for k in range(blocks_per_step):
            ju = (j * blocks_per_step + k).astype(U32)
            active = ju < item_blocks
            final = ju == item_blocks - U32(1)
            cap = (ju + U32(1)) << U32(7)
            t_lo = jnp.where(cap < lengths, cap, lengths)
            if msg_loads:
                m = _RefWords(mh_ref, ml_ref, k)
            else:
                m = [(mh_ref[k, w], ml_ref[k, w]) for w in range(16)]
            nh = compress_soa(h, m, t_lo, final, unroll=True, lanes=lanes,
                              g_interleave=g_interleave)
            h = [
                (
                    jnp.where(active, nh[w][0], h[w][0]),
                    jnp.where(active, nh[w][1], h[w][1]),
                )
                for w in range(8)
            ]
        for w in range(8):
            sth_ref[w] = h[w][0]
            stl_ref[w] = h[w][1]

    @pl.when(j == nb - 1)
    def _emit():
        for w in range(8):
            outh_ref[w] = sth_ref[w]
            outl_ref[w] = stl_ref[w]


@functools.partial(
    jax.jit,
    static_argnames=("digest_size", "block_items", "interpret", "msg_loads",
                     "vmem_state", "state_loads", "blocks_per_step",
                     "g_interleave"),
)
def blake2b_native(mh, ml, lengths, digest_size: int = DIGEST_SIZE,
                   block_items: int = 1024, interpret: bool = False,
                   msg_loads: bool = True, vmem_state: bool = False,
                   state_loads: bool = False, blocks_per_step: int = 1,
                   g_interleave: bool = False):
    """Hash in the kernel-native layout.

    ``mh``/``ml``: (nblocks, 16, 8, B/8) uint32 message word halves;
    ``lengths``: (8, B/8) uint32.  ``B`` must be a multiple of
    ``block_items`` (and ``block_items`` of 8*128).  Returns digest words
    as ``(hh, hl)``, each (8, 8, B/8): word-major, batch split like the
    input.

    ``blocks_per_step`` > 1 compresses that many consecutive message
    blocks per grid step with the chaining state held in registers
    between them (``nblocks`` must divide evenly); it prices Mosaic's
    per-grid-step overhead against register pressure.
    """
    nb, _, s, bl = mh.shape
    if s != _SUBLANE:
        raise ValueError(f"batch must be split (8, B/8); got sublane {s}")
    if block_items % (_SUBLANE * _LANE):
        raise ValueError(f"block_items must be a multiple of {_SUBLANE * _LANE}")
    btl = block_items // _SUBLANE
    if bl % btl:
        raise ValueError(f"B/8={bl} not a multiple of tile width {btl}")
    if blocks_per_step < 1 or nb % blocks_per_step:
        raise ValueError(
            f"blocks_per_step={blocks_per_step} must divide nblocks={nb}"
        )
    if state_loads and blocks_per_step > 1:
        # the multi-block branch chains h through registers and never
        # consults the lazy-state view; refuse rather than silently
        # benchmark identical code under two variant labels
        raise ValueError("state_loads has no effect with blocks_per_step > 1")

    grid = (bl // btl, nb // blocks_per_step)
    # Mosaic gets the straight-line unrolled rounds; the interpreter (CPU
    # tests) gets the scanned rounds, whose 12x-smaller graph sidesteps
    # the CPU backend's pathological compile of the unrolled chain
    # vmem_state mutates lane refs inside the rounds and state_loads
    # reads h refs lazily — neither has a scanned formulation, so both
    # force unrolled rounds (interpret included; keep interpret shapes
    # tiny there, the CPU compile of the unrolled chain is the slow part
    # the scanned path normally dodges).  Without the state_loads term
    # the interpret-mode tests would silently exercise the eager path.
    unroll = ((not interpret) or vmem_state or state_loads
              or blocks_per_step > 1 or g_interleave)
    kernel = functools.partial(
        _kernel, digest_size=digest_size, unroll=unroll,
        msg_loads=msg_loads, vmem_state=vmem_state,
        state_loads=state_loads, blocks_per_step=blocks_per_step,
        g_interleave=g_interleave,
    )
    bps = blocks_per_step
    in_specs = [
        pl.BlockSpec((_SUBLANE, btl), lambda i, j: (0, i)),
        pl.BlockSpec((bps, 16, _SUBLANE, btl), lambda i, j: (j, 0, 0, i)),
        pl.BlockSpec((bps, 16, _SUBLANE, btl), lambda i, j: (j, 0, 0, i)),
    ]
    inputs = [lengths, mh, ml]
    if not unroll:
        from .blake2b import _ROUND_SIGMA

        in_specs.append(pl.BlockSpec((12, 16), lambda i, j: (0, 0)))
        inputs.append(jnp.asarray(np.stack(_ROUND_SIGMA)))
    outh, outl = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((8, _SUBLANE, btl), lambda i, j: (0, 0, i)),
            pl.BlockSpec((8, _SUBLANE, btl), lambda i, j: (0, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, _SUBLANE, bl), jnp.uint32),
            jax.ShapeDtypeStruct((8, _SUBLANE, bl), jnp.uint32),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((8, _SUBLANE, btl), jnp.uint32),
        ] + (
            [
                pltpu.VMEM((16, _SUBLANE, btl), jnp.uint32),
                pltpu.VMEM((16, _SUBLANE, btl), jnp.uint32),
            ]
            if vmem_state
            else []
        ),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*inputs)
    return outh, outl


# recompile sentinel: the kernel specializes per (nblocks, B) tile shape
# plus every static knob the bench calibrates over
blake2b_native = _jit_site("ops.blake2b_pallas.native", blake2b_native)


def to_native(mh, ml, lengths, block_items: int = 1024):
    """(B, nblocks, 16) padded-batch layout -> kernel-native layout.

    Pads the batch up to a multiple of ``block_items`` (zero payloads are
    valid BLAKE2b inputs; the wrapper drops their digests).  Returns
    (mh_n, ml_n, lengths_n, B).
    """
    B, nb, _ = mh.shape
    Bp = -(-B // block_items) * block_items
    if Bp != B:
        mh = jnp.pad(mh, ((0, Bp - B), (0, 0), (0, 0)))
        ml = jnp.pad(ml, ((0, Bp - B), (0, 0), (0, 0)))
        lengths = jnp.pad(lengths, (0, Bp - B))
    mh_n = jnp.transpose(mh, (1, 2, 0)).reshape(nb, 16, _SUBLANE, Bp // _SUBLANE)
    ml_n = jnp.transpose(ml, (1, 2, 0)).reshape(nb, 16, _SUBLANE, Bp // _SUBLANE)
    len_n = lengths.reshape(_SUBLANE, Bp // _SUBLANE)
    return mh_n, ml_n, len_n, B


def from_native(outh, outl, B: int):
    """Kernel-native digest words -> (B, 8) hi/lo (the scan-path layout)."""
    Bp = outh.shape[1] * outh.shape[2]
    hh = outh.reshape(8, Bp).T[:B]
    hl = outl.reshape(8, Bp).T[:B]
    return hh, hl


def blake2b_packed_pallas(mh, ml, lengths, digest_size: int = DIGEST_SIZE,
                          block_items: int = 1024, interpret: bool = False):
    """Drop-in for :func:`.blake2b.blake2b_packed`, Pallas-accelerated.

    Same (B, nblocks, 16) interface and (B, 8) hi/lo digest outputs.
    """
    mh_n, ml_n, len_n, B = to_native(mh, ml, lengths, block_items)
    outh, outl = blake2b_native(
        mh_n, ml_n, len_n, digest_size, block_items, interpret
    )
    return from_native(outh, outl, B)


# donated twin (see blake2b.blake2b_packed_donated): one jit over the
# whole layout-transpose + kernel chain so the staged (B, nblocks, 16)
# message buffers are donated into the program and their HBM recycles
# into the next batch's staging — the double-buffered upload discipline
blake2b_packed_pallas_donated = functools.partial(
    jax.jit,
    static_argnames=("digest_size", "block_items", "interpret"),
    donate_argnums=(0, 1),
)(blake2b_packed_pallas)
blake2b_packed_pallas_donated = _jit_site(
    "ops.blake2b_pallas.packed_donated", blake2b_packed_pallas_donated
)
