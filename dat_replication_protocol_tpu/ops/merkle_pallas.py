"""Merkle tree-level hashing as a Pallas TPU kernel.

A tree level is the ideal Pallas shape: every parent is exactly ONE
BLAKE2b compression of a fixed 64-byte two-child message (level 0 of the
1M-leaf bench config is a 524288-item batch).  The general batched
kernel (:mod:`.blake2b_pallas`) spends its flexibility on variable
lengths, multi-block chaining, and VMEM state carried across a grid
axis; none of that applies here, so this kernel is the stripped-down
single-block form: no lengths, no masks, no scratch, no block axis —
just IV init, 12 unrolled rounds, and the finalizing XOR, over full
(8, 128) uint32 vregs.

This is the round-3 replacement for the scanned-rounds compromise the
tree build used to make for compile time (``merkle_parent``'s ~2x
runtime cost, ops/merkle.py): levels big enough to matter go through
this kernel; tiny top levels keep the scanned XLA path where compile
time, not throughput, binds.

reference: the protocol has no Merkle machinery (SURVEY.md §2 — dat core
holds it above the wire); this serves BASELINE.json's ">= 10M diff
entries/sec" target.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .blake2b import _IV_HI, _IV_LO, _ROUND_SIGMA, compress_soa
from .merkle import DIGEST_SIZE
from .u64 import U32
from ..obs.device import jit_site as _jit_site

_LANE = 128
_SUBLANE = 8


def _kernel(*refs, unroll: bool):
    if unroll:
        mh_ref, ml_ref, outh_ref, outl_ref = refs
        sigma = None
    else:
        mh_ref, ml_ref, sig_ref, outh_ref, outl_ref = refs
        sigma = sig_ref[:]
    shape = mh_ref.shape[1:]  # (8, btl)
    zero = jnp.zeros(shape, U32)
    m = [(mh_ref[w], ml_ref[w]) for w in range(8)]
    m += [(zero, zero)] * 8  # the 64-byte message fills half the block
    param_lo = np.uint32(0x01010000 ^ DIGEST_SIZE)
    h = []
    for w in range(8):
        lo = _IV_LO[w] ^ param_lo if w == 0 else _IV_LO[w]
        h.append((jnp.full(shape, _IV_HI[w], U32), jnp.full(shape, lo, U32)))
    t_lo = jnp.full(shape, np.uint32(2 * DIGEST_SIZE), U32)
    final = jnp.ones(shape, dtype=bool)
    nh = compress_soa(h, m, t_lo, final, unroll=unroll, sigma=sigma)
    for w in range(4):
        outh_ref[w] = nh[w][0]
        outl_ref[w] = nh[w][1]


@functools.partial(
    jax.jit, static_argnames=("block_items", "interpret")
)
def merkle_level_native(mh, ml, block_items: int = 1024,
                        interpret: bool = False):
    """``mh``/``ml``: (8, 8, P/8) uint32 message word halves (the two
    children's 4 word-pairs each) -> parent digests (4, 8, P/8)."""
    w, s, pl_ = mh.shape
    if w != 8 or s != _SUBLANE:
        raise ValueError(f"expected (8, 8, P/8); got {mh.shape}")
    if block_items % (_SUBLANE * _LANE):
        raise ValueError(f"block_items must be a multiple of {_SUBLANE * _LANE}")
    btl = block_items // _SUBLANE
    if pl_ % btl:
        raise ValueError(f"P/8={pl_} not a multiple of tile width {btl}")

    unroll = not interpret
    kernel = functools.partial(_kernel, unroll=unroll)
    in_specs = [
        pl.BlockSpec((8, _SUBLANE, btl), lambda i: (0, 0, i)),
        pl.BlockSpec((8, _SUBLANE, btl), lambda i: (0, 0, i)),
    ]
    inputs = [mh, ml]
    if not unroll:
        in_specs.append(pl.BlockSpec((12, 16), lambda i: (0, 0)))
        inputs.append(jnp.asarray(np.stack(_ROUND_SIGMA)))
    outh, outl = pl.pallas_call(
        kernel,
        grid=(pl_ // btl,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((4, _SUBLANE, btl), lambda i: (0, 0, i)),
            pl.BlockSpec((4, _SUBLANE, btl), lambda i: (0, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((4, _SUBLANE, pl_), jnp.uint32),
            jax.ShapeDtypeStruct((4, _SUBLANE, pl_), jnp.uint32),
        ],
        interpret=interpret,
    )(*inputs)
    return outh, outl


merkle_level_native = _jit_site("ops.merkle_pallas.level", merkle_level_native)


def merkle_level_pallas(hh, hl, block_items: int = 1024,
                        interpret: bool = False):
    """Drop-in for :func:`.merkle.merkle_level`: (N, 4) digests ->
    (N//2, 4) parents, Pallas-accelerated.

    Children pair even/odd rows (dat's flat in-order convention, same as
    the scanned path).  Pads the parent count up to ``block_items``
    (zero-digest children are valid messages; padding parents are
    dropped).
    """
    n = hh.shape[0]
    P = n // 2
    Pp = -(-P // block_items) * block_items
    # (N, 4) -> (P, 8): row p = left child words || right child words
    mw_h = hh.reshape(P, 8)
    mw_l = hl.reshape(P, 8)
    if Pp != P:
        mw_h = jnp.pad(mw_h, ((0, Pp - P), (0, 0)))
        mw_l = jnp.pad(mw_l, ((0, Pp - P), (0, 0)))
    mh = jnp.transpose(mw_h, (1, 0)).reshape(8, _SUBLANE, Pp // _SUBLANE)
    ml = jnp.transpose(mw_l, (1, 0)).reshape(8, _SUBLANE, Pp // _SUBLANE)
    outh, outl = merkle_level_native(mh, ml, block_items, interpret)
    ph = jnp.transpose(outh.reshape(4, Pp), (1, 0))[:P]
    pdl = jnp.transpose(outl.reshape(4, Pp), (1, 0))[:P]
    return ph, pdl
