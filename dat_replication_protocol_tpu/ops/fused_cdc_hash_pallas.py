"""Single-pass content addressing on device: fused CDC extraction with
an on-chip cross-check, and a single-residency chunk-hash pipeline.

The two-pass device route reads blob bytes twice: once through the gear
CDC kernel (device-resident words), then again through a HOST-side
``pack_ragged`` + re-upload for the BLAKE2b batch — the blob crosses the
host/device boundary twice and the host touches every byte in between.
This module collapses that to ONE residency (ISSUE 7 tentpole):

* :func:`gear_window_first_checked` — the ``fused1p`` extraction kernel:
  the window-first gear scan of :mod:`.rabin_pallas` with an INDEPENDENT
  per-window occupancy reduction fused in, and a consistency flag out.
  The two reductions take different paths through the kernel (packed-
  word first-hit tracking vs an or-accumulate occupancy), so a
  miscompiled or raced reduction surfaces as a flag the host REFUSES to
  cut from (``cdc.fused.crosscheck.refused``; the caller falls back to
  the bitmask route, which recomputes from scratch).
* :func:`pack_extents_device` — ragged chunk extents packed into the
  BLAKE2b batch layout BY THE DEVICE, gathering from the already-
  resident word buffer: no host pack, no second upload.  This is the
  same restructuring for the XLA-scan path (the gather + shift pack is
  portable XLA), so the single-pass win lands on CPU-backed jax too.
* :func:`content_begin` — the composed pipeline: candidates (any
  ``DAT_CDC_ROUTE`` kernel) -> O(candidates) D2H -> native greedy ->
  device-side pack -> batched BLAKE2b -> digests, with the blob words
  uploaded exactly once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..obs.device import jit_site as _jit_site
from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter
from .rabin import GROUP, PACK, _gear_step, _popcount32
from .u64 import U32

from ..utils.jax_compat import COMPILER_PARAMS as _COMPILER_PARAMS

_SUBLANE = 8
_LANE = 128
_SENT_OFF = 1 << 30  # empty-window sentinel (rabin_pallas convention)

# single-residency per-call cap: the device extent pack computes byte
# positions in int32 (jax's default int), and the highest index it forms
# is offs + nblocks*128 (the PADDED chunk width) — so the cap backs off
# int32 range by a 64 MiB margin rather than sitting exactly at 2 GiB,
# where the last chunk's padding indices would wrap negative and slip
# the validity mask (silently corrupting that chunk's digest).
RESIDENCY_CAP = (1 << 31) - (1 << 26)

# fused-route telemetry (OBSERVABILITY.md single-pass catalog; the
# crosscheck-refusal counter lives at its one increment site,
# ops.rabin.candidates_begin)
_M_FUSED_BYTES = _counter("cdc.fused.bytes")
_M_FUSED_CHUNKS = _counter("cdc.fused.chunks")


def _kernel_wfirst_checked(wref, oref, occref, sth_ref, stl_ref, fidx_ref,
                           fval_ref, oany_ref, *, avg_bits: int, ilp: int,
                           gpw: int):
    """Window-first gear scan with an INDEPENDENT occupancy reduction.

    Same gear chain and first-candidate tracking as
    :func:`.rabin_pallas._kernel_wfirst`; additionally every packed
    accumulator word is OR-folded into a per-window occupancy scratch
    that never consults the fidx/fval tracking.  The flush emits both
    the first-candidate offset and the occupancy word — the wrapper's
    invariant ``(occ != 0) == (offset != SENT)`` ties the two reductions
    together, so a defect in either surfaces as a refusable flag rather
    than silently divergent cuts.
    """
    j = pl.program_id(1)
    mask = U32((1 << avg_bits) - 1)
    btl = sth_ref.shape[-1] // ilp
    sent = U32(0xFFFFFFFF)

    @pl.when(j == 0)
    def _init():
        sth_ref[0] = jnp.zeros(sth_ref.shape[1:], U32)
        stl_ref[0] = jnp.zeros(stl_ref.shape[1:], U32)
        fidx_ref[0] = jnp.full(fidx_ref.shape[1:], sent, U32)
        fval_ref[0] = jnp.zeros(fval_ref.shape[1:], U32)
        oany_ref[0] = jnp.zeros(oany_ref.shape[1:], U32)

    def chunk(a, k):
        return a[:, k * btl : (k + 1) * btl]

    hh = [chunk(sth_ref[0], k) for k in range(ilp)]
    hl = [chunk(stl_ref[0], k) for k in range(ilp)]
    fidx = [chunk(fidx_ref[0], k) for k in range(ilp)]
    fval = [chunk(fval_ref[0], k) for k in range(ilp)]
    oany = [chunk(oany_ref[0], k) for k in range(ilp)]
    valid = j > 0  # group 0 is warm-up context: hits there never count
    wphase = jnp.mod(j - 1, gpw).astype(U32)
    vmask = jnp.where(valid, U32(0xFFFFFFFF), U32(0))

    acc = [jnp.zeros_like(hh[0]) for _ in range(ilp)]
    bit = 0
    pword = 0
    for w in range(GROUP // 4):
        word = wref[0, w]
        for s in range(4):
            for k in range(ilp):
                byte = (chunk(word, k) >> U32(8 * s)) & U32(0xFF)
                hh[k], hl[k] = _gear_step(hh[k], hl[k], byte)
                hit = (hh[k] & mask) == U32(0)
                acc[k] = acc[k] | (hit.astype(U32) << U32(bit))
            bit += 1
            if bit == PACK:
                word_idx = wphase * U32(GROUP // PACK) + U32(pword)
                for k in range(ilp):
                    new = (fidx[k] == sent) & (acc[k] != U32(0)) & valid
                    fidx[k] = jnp.where(new, word_idx, fidx[k])
                    fval[k] = jnp.where(new, acc[k], fval[k])
                    # occupancy: a straight OR fold, blind to the
                    # first-hit tracking above
                    oany[k] = oany[k] | (acc[k] & vmask)
                acc = [jnp.zeros_like(hh[0]) for _ in range(ilp)]
                bit = 0
                pword += 1

    sth_ref[0] = jnp.concatenate(hh, axis=-1)
    stl_ref[0] = jnp.concatenate(hl, axis=-1)

    is_flush = valid & (wphase == U32(gpw - 1))

    @pl.when(is_flush)
    def _flush():
        outs = []
        for k in range(ilp):
            lsb = fval[k] & (U32(0) - fval[k])
            bitpos = _popcount32(lsb - U32(1))
            outs.append(jnp.where(
                fidx[k] != sent,
                fidx[k] * U32(PACK) + bitpos,
                U32(_SENT_OFF),
            ))
        oref[0] = jnp.concatenate(outs, axis=-1)
        occref[0] = jnp.concatenate(oany, axis=-1)
        fidx_ref[0] = jnp.full(fidx_ref.shape[1:], sent, U32)
        oany_ref[0] = jnp.zeros(oany_ref.shape[1:], U32)

    @pl.when(jnp.logical_not(is_flush))
    def _keep():
        fidx_ref[0] = jnp.concatenate(fidx, axis=-1)
        fval_ref[0] = jnp.concatenate(fval, axis=-1)
        oany_ref[0] = jnp.concatenate(oany, axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=("avg_bits", "thin_bits", "block_tiles", "interpret",
                     "ilp"),
)
def gear_window_first_checked_native(words, avg_bits: int, thin_bits: int,
                                     block_tiles: int = 8192,
                                     interpret: bool = False, ilp: int = 8):
    """``words``: (ng, GROUP/4, 8, T/8) uint32 (group 0 = warm-up) ->
    ``(firsts, occ)``: per-window first-candidate byte offsets and the
    independent per-window occupancy words, each ``(nwin_per_tile, 8,
    T/8)`` uint32."""
    ng, gw, s, tl = words.shape
    if gw != GROUP // 4 or s != _SUBLANE:
        raise ValueError(f"expected (ng, {GROUP // 4}, 8, T/8); got {words.shape}")
    gpw = (1 << thin_bits) // GROUP
    if gpw < 1 or (ng - 1) % gpw:
        raise ValueError(
            f"window of 2**{thin_bits} B needs payload groups {ng - 1} "
            f"divisible by {gpw}"
        )
    btl = block_tiles // _SUBLANE
    if tl % btl:
        raise ValueError(f"T/8={tl} not a multiple of tile width {btl}")
    if btl % ilp or (btl // ilp) % _LANE:
        raise ValueError(
            f"block_tiles/8={btl} must split into {ilp} lane-multiples"
        )
    nwpt = (ng - 1) // gpw
    grid = (tl // btl, ng)
    kernel = functools.partial(_kernel_wfirst_checked, avg_bits=avg_bits,
                               ilp=ilp, gpw=gpw)
    win_spec = pl.BlockSpec(
        (1, _SUBLANE, btl),
        # groups [1 + w*gpw, 1 + (w+1)*gpw) -> window block w; warm-up
        # step j=0 aliases harmlessly onto block 0 (never written)
        lambda i, j: (jnp.maximum((j - 1) // gpw, 0), 0, i),
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, gw, _SUBLANE, btl), lambda i, j: (j, 0, 0, i)),
        ],
        out_specs=[win_spec, win_spec],
        out_shape=[
            jax.ShapeDtypeStruct((nwpt, _SUBLANE, tl), jnp.uint32),
            jax.ShapeDtypeStruct((nwpt, _SUBLANE, tl), jnp.uint32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(words)


@functools.partial(
    jax.jit,
    static_argnames=("avg_bits", "thin_bits", "block_tiles", "interpret",
                     "ilp"),
)
def gear_window_first_checked(words, avg_bits: int, thin_bits: int,
                              block_tiles: int | None = None,
                              interpret: bool = False,
                              ilp: int | None = None):
    """``fused1p`` extraction: (T, S/4) prefixed tile rows in (group 0 =
    warm-up, per ``rabin._build_rows``), ``(first, viol)`` out —
    ``first`` the stream-ordered per-window first-candidate offsets
    ((T * nwin_per_tile,) int32, ``1 << 30`` = empty) and ``viol`` the
    count of windows whose two on-chip reductions disagree (the host
    refuses the whole extraction when it is nonzero)."""
    from .rabin_pallas import _to_native_layout

    T, _ = words.shape
    native, Tp, ng, block_tiles, ilp = _to_native_layout(
        words, block_tiles, ilp
    )
    firsts, occ = gear_window_first_checked_native(
        native, avg_bits, thin_bits, block_tiles, interpret, ilp
    )
    nwpt = firsts.shape[0]
    out = jnp.transpose(firsts, (1, 2, 0)).reshape(Tp * nwpt)
    occ_flat = jnp.transpose(occ, (1, 2, 0)).reshape(Tp * nwpt)
    first = out[: T * nwpt].astype(jnp.int32)
    occ_flat = occ_flat[: T * nwpt]
    viol = jnp.sum(
        ((occ_flat != 0) != (first != _SENT_OFF)).astype(jnp.int32)
    )
    return first, viol


gear_window_first_checked = _jit_site(
    "ops.fused_cdc_hash.window_first_checked", gear_window_first_checked
)


# ---------------------------------------------------------------------------
# device-side extent packing: the second blob read stays on device
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("nblocks", "chunk_b"))
def _pack_extents_kernel(words, offs, lens, nblocks: int, chunk_b: int):
    """Gather-pack ``chunk_b`` extents of the device-resident word
    buffer into the (B, nblocks, 16) hi/lo BLAKE2b batch layout.

    Byte i of the stream is ``(words[i >> 2] >> (8 * (i & 3))) & 0xFF``;
    the gather runs over word indices (one u32 fetch per output byte's
    word, fused by XLA), masked past each extent's length so padding is
    zero exactly as :func:`..ops.blake2b.pack_payloads` guarantees.
    Positions are int32: the per-call residency cap is < 2 GiB.
    """
    width = nblocks * 128
    idx = offs[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    valid = idx < (offs + lens)[:, None]
    widx = jnp.clip(idx >> 2, 0, words.shape[0] - 1)
    w = jnp.take(words, widx, axis=0)
    byte = (w >> ((idx & 3).astype(U32) << U32(3))) & U32(0xFF)
    byte = jnp.where(valid, byte, U32(0))
    # 4 bytes -> one little-endian u32 word
    b = byte.reshape(chunk_b, nblocks * 32, 4)
    w32 = (b[:, :, 0] | (b[:, :, 1] << U32(8)) | (b[:, :, 2] << U32(16))
           | (b[:, :, 3] << U32(24)))
    w32 = w32.reshape(chunk_b, nblocks, 32)
    return w32[:, :, 1::2], w32[:, :, 0::2]  # (hi, lo)


_pack_extents_kernel = _jit_site("ops.fused_cdc_hash.pack_extents",
                                 _pack_extents_kernel)


def pack_extents_device(words, offs, lens, nblocks: int):
    """(B,) extents over a device-resident u32 word buffer -> device
    (mh, ml, lengths) in the :func:`..ops.blake2b.blake2b_packed`
    contract, without the bytes ever visiting the host."""
    B = len(offs)
    offs_h = np.asarray(offs, dtype=np.int64)
    if B and int(offs_h.max()) + nblocks * 128 >= (1 << 31):
        # int32 position arithmetic would wrap (see RESIDENCY_CAP) —
        # refuse loudly rather than gather garbage into the padding
        raise ValueError(
            f"extent pack positions exceed int32 range "
            f"(max offset {int(offs_h.max())} + padded width "
            f"{nblocks * 128}); keep residencies under RESIDENCY_CAP"
        )
    offs_d = jnp.asarray(offs_h.astype(np.int32))
    lens_d = jnp.asarray(np.asarray(lens, dtype=np.int32))
    mh, ml = _pack_extents_kernel(words, offs_d, lens_d, nblocks, B)
    return mh, ml, lens_d.astype(U32)


def hash_cuts_device(words, cuts, nbytes: int, use_pallas: bool | None = None,
                     pipeline_bytes: int = 64 << 20):
    """Chunk digests for ``cuts`` over a device-resident word buffer.

    The single-residency replacement for host ``pack_ragged`` + upload:
    extents are bucketed by power-of-two block count (the
    :func:`..batch.feed.bucketed_extents` policy), packed on device by
    :func:`pack_extents_device` in bounded pipeline chunks, and hashed
    by the batched BLAKE2b the backend routes to.  Returns ``(hh, hl)``
    device arrays, each (nchunks, 4) uint32, in cut order.
    """
    from ..batch.feed import bucketed_extents
    from . import blake2b

    ends = np.asarray(cuts, dtype=np.int64)
    offs = np.concatenate([np.zeros(1, np.int64), ends[:-1]])
    lens = ends - offs
    n = len(ends)
    out_hh = jnp.zeros((max(1, n), 4), dtype=jnp.uint32)
    out_hl = jnp.zeros((max(1, n), 4), dtype=jnp.uint32)
    if not n:
        return out_hh[:0], out_hl[:0]
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    fences: list = []
    donate = blake2b.donation_supported()
    for nb, idx in bucketed_extents(lens).items():
        B = len(idx)
        chunk_b = max(1, pipeline_bytes // (nb * 128))
        if use_pallas:
            chunk_b = max(chunk_b, blake2b._PALLAS_MIN_ITEMS)
        chunk_b = blake2b._bucket_nblocks(min(chunk_b, max(1, B)))
        # donated dispatch, same routing as feed.hash_extents_device:
        # the device-packed mh/ml are consumed by exactly one program,
        # so their HBM recycles into the next chunk's pack
        if use_pallas and chunk_b >= blake2b._PALLAS_MIN_ITEMS:
            if donate:
                from .blake2b_pallas import (
                    blake2b_packed_pallas_donated as fn,
                )
            else:
                from .blake2b_pallas import blake2b_packed_pallas as fn
        else:
            fn = (blake2b.blake2b_packed_donated if donate
                  else blake2b.blake2b_packed)
        for c0 in range(0, B, chunk_b):
            sub = idx[c0:c0 + chunk_b]
            bs = len(sub)
            po = np.zeros(chunk_b, dtype=np.int64)
            pl_ = np.zeros(chunk_b, dtype=np.int64)
            po[:bs] = offs[sub]
            pl_[:bs] = lens[sub]
            mh, ml, blens = pack_extents_device(words, po, pl_, nb)
            hh, hl = fn(mh, ml, blens)
            at = jnp.asarray(sub)
            out_hh = out_hh.at[at].set(hh[:bs, :4])
            out_hl = out_hl.at[at].set(hl[:bs, :4])
            fences.append(hh)
            while len(fences) > 2:  # bound in-flight packed batches
                np.asarray(fences.pop(0)[:1, :1])
    return out_hh, out_hl


def content_begin(buf: np.ndarray, avg_bits: int = 13,
                  min_size: int | None = None, max_size: int | None = None,
                  tile_bytes: int = 1 << 17):
    """Single-residency device content addressing for one buffer.

    Uploads the blob words ONCE; the CDC extraction (whatever
    ``DAT_CDC_ROUTE`` kernel, ``fused1p`` included) and the chunk
    BLAKE2b both read the same resident buffer — the device analogue of
    the native engine's one-sweep ``dat_cdc_hash``.  Returns a zero-arg
    ``collect()`` -> ``(cuts, hh, hl)``: cut end-offsets (host list) and
    digest word columns (DEVICE arrays, (nchunks, 4) u32 each), so a
    merkle consumer folds them without a D2H round-trip.

    Per-call limit 2 GiB (the candidate extractor's cap); multi-slab
    streams compose :func:`..ops.rabin.chunk_stream` + repeated calls.
    """
    from .rabin import _clamp_thin_bits, _greedy_select, candidates_begin

    if min_size is None:
        min_size = 1 << (avg_bits - 2)
    if max_size is None:
        max_size = 1 << (avg_bits + 2)
    nbytes = len(buf)
    thin_bits = _clamp_thin_bits(max(min_size, 1).bit_length() - 1,
                                 tile_bytes)
    staged = np.zeros(-(-nbytes // 4), dtype="<u4")
    staged.view(np.uint8)[:nbytes] = buf
    words = jnp.asarray(staged)  # the ONE upload
    cand = candidates_begin(words, nbytes, avg_bits, tile_bytes,
                            thin_bits=thin_bits)

    def collect():
        cuts = _greedy_select(cand(), nbytes, min_size, max_size)
        hh, hl = hash_cuts_device(words, cuts, nbytes)
        if _OBS.on:
            _M_FUSED_BYTES.inc(nbytes)
            _M_FUSED_CHUNKS.inc(len(cuts))
        return cuts, hh, hl

    return collect
