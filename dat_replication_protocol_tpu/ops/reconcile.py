"""Key-addressed set reconciliation of divergent change logs.

The positional Merkle diff (:mod:`.merkle`) compares equal-width,
aligned snapshots: one inserted record shifts every later leaf and the
diff degenerates to "everything differs".  The reference never solves
this in-protocol — it carries ``from``/``to`` version fields and lets
dat core resume divergent replicas above the wire (reference:
messages/schema.proto:4-5).  This module pulls that capability into the
data plane with a **key-addressed sketch**, the rateless-IBLT idea
(PAPERS.md) specialized for TPU batch shapes:

* Every record is summarized by a 32-byte BLAKE2b digest of its
  serialized bytes (the batched leaf hasher's output).
* A replica's **sketch** is a fixed table of ``2**log2_slots`` cells;
  record r lands in cell ``slot(r) = key_digest(r) mod nslots`` —
  a function of the record's *key*, so it is **stable under insertion,
  deletion, and reordering** of other records.
* A cell holds the component-wise wrapping-u32 **sum** of its records'
  digests (order-independent, like an IBLT cell's checksum; addition
  instead of XOR so value flips that come in pairs — old+new — still
  perturb the cell).  Empty cells are zero.
* Two sketches of divergent replicas therefore differ in exactly the
  cells owning a differing/inserted/deleted record — O(diff) cells, not
  O(log).  Cell-level comparison rides the existing Merkle tree diff
  (:func:`..ops.merkle.diff_root_guided_packed`), so finding the
  differing cells costs one tree build + top-down walk per sketch.
* Reconciliation: each side sends the records whose slot is in the
  differing set — a superset of the true diff only by slot-collision
  (load factor picks the overhead; 2x slots per record ~= 39% extra
  records exchanged at random load, amortizing to O(diff) as sketch
  size tracks diff size — the rateless regime).

All device math is scatter-add + elementwise (TPU-friendly); the only
sequential work is the host-side bucketing of records by differing
slot, O(records in differing slots).
"""

from __future__ import annotations

import numpy as np

from ..utils.trace import span

DIGEST_WORDS = 8  # 32-byte digests as 8 uint32 words


def table_leaves(table):
    """Sketch-table cells as Merkle leaf digest columns ``(hh, hl)``.

    A cell is 32 bytes of wrapping-u32 sums — exactly digest-shaped —
    so a sketch table is directly a Merkle leaf layer: build a tree
    over it and two replicas can locate their differing cells REMOTELY
    via :mod:`..runtime.tree_sync` in O(diff · log nslots) wire bytes,
    instead of exchanging the O(nslots) table (the rateless-regime
    refinement of the sketch protocol).  Word convention matches
    :func:`sketch_table` ([lo k, hi k] interleave).
    """
    import jax.numpy as jnp

    table = jnp.asarray(table)
    return table[:, 1::2], table[:, 0::2]


def diff_sketches(table_a, table_b) -> np.ndarray:
    """Differing slot indices between two LOCAL sketches (sorted ascending).

    Both tables are in this process's memory here, so the optimal compare
    is one vectorized elementwise pass — O(nslots) cheap work with no
    tree build (round-3 verdict weak #3: the tree walk priced every local
    reconcile at the device diff's latency).  The O(diff · log n)
    tree-guided descent is the *remote* story: :func:`table_leaves` turns
    a sketch into Merkle leaves and :mod:`..runtime.tree_sync` walks two
    of them across a wire without ever exchanging the tables.
    """
    n = table_a.shape[0]
    if table_b.shape[0] != n:
        raise ValueError("sketches must have equal slot counts")
    with span("reconcile.diff"):
        a = np.asarray(table_a)
        b = np.asarray(table_b)
        dense = (a != b).any(axis=1)
    return np.nonzero(dense)[0]


_SUMMARIZE_JIT = None  # lazy: keep jax out of module import


def sketch_table(rec_hh, rec_hl, slots, nslots: int):
    """The sketch kernel: (B, 4) digest word columns + (B,) cell indices
    -> (nslots, 8) wrapping-u32 cell table.

    One owner of the word interleave ([lo k, hi k] — the host digest
    byte order) and the scatter-add; the single-device summary and the
    sharded mesh build (:func:`..parallel.mesh.sharded_sketch`) both
    call this, which is what makes them byte-identical by construction.

    Slots are masked to the table width here: an unmasked out-of-range
    value would alias (negative int32 wraps to the table tail) or be
    silently dropped by XLA's OOB-scatter semantics — either way a
    corrupt sketch with no error.
    """
    import jax.numpy as jnp

    words = jnp.stack([rec_hl, rec_hh], axis=2).reshape(-1, DIGEST_WORDS)
    table = jnp.zeros((nslots, DIGEST_WORDS), dtype=jnp.uint32)
    slots = slots.astype(jnp.uint32) & jnp.uint32(nslots - 1)
    return table.at[slots.astype(jnp.int32)].add(words)


def _summarize(all_hh, all_hl, n: int, log2_slots: int):
    """Device-fused summary: record digests -> sketch table, key digests
    -> slot indices.  Runs jitted so only the (tiny) slot vector and the
    (nslots, 8) table ever exist as outputs; the 2n digests stay in HBM.
    """
    import jax.numpy as jnp

    nslots = 1 << log2_slots
    # slot = key-digest first-8-bytes (LE u64) & (nslots-1); for
    # log2_slots <= 31 that mask only touches the low u32 word (and the
    # int32 scatter index below stays non-negative), so the u64
    # lane-pair never needs materializing
    slots = all_hl[n:, 0] & jnp.uint32(nslots - 1)
    return sketch_table(all_hh[:n], all_hl[:n], slots, nslots), slots


class LogSummary:
    """One replica's reconciliation state: key slots + digest sketch.

    Engines (``engine=``):

    * ``'host'`` — the native C digest+scatter pass
      (:func:`..runtime.native.sketch`): records are host-born bytes and
      the sketch is a tiny table, so digesting where the bytes already
      live is the data-plane route — no H2D of the log, no per-record
      interpreter cost (round-3 verdict weak #3: 26-65k records/s
      end-to-end; the native pass measures ~2M records/s on one core).
    * ``'device'`` — hash -> scatter-add sketch jit-fused on the
      accelerator; per record only its 4-byte slot index crosses D2H.
      For pipelines whose record bytes are already device-resident.
    * ``'auto'`` (default) — ``'host'`` when the native library is
      available, else ``'device'``.  Every engine produces the identical
      table (byte-exact; tested).
    """

    def __init__(self, records: list[bytes], keys: list[bytes],
                 log2_slots: int, engine: str = "auto"):
        if len(records) != len(keys):
            raise ValueError("records and keys must align")
        if not 0 < log2_slots <= 31:
            raise ValueError("log2_slots must be in [1, 31]")
        if engine not in ("auto", "host", "device"):
            raise ValueError(f"unknown engine {engine!r}")
        n = len(records)
        if n == 0:  # a fresh replica reconciling against a populated one
            self.slots = np.empty((0,), dtype=np.int64)
            self.table = np.zeros((1 << log2_slots, DIGEST_WORDS),
                                  dtype=np.uint32)
            self.keys = []
            return
        blob = b"".join(records) + b"".join(keys)
        buf = np.frombuffer(blob, np.uint8)
        lens = np.array([len(r) for r in records]
                        + [len(k) for k in keys], dtype=np.int64)
        offs = np.cumsum(lens) - lens
        if engine != "device":
            from ..runtime import native

            with span("reconcile.sketch"):
                out = native.sketch(buf, offs[:n], lens[:n], offs[n:],
                                    lens[n:], log2_slots)
            if out is not None:
                table, slots = out
                self.table = table
                self.slots = slots.astype(np.int64)
                self.keys = keys
                return
            if engine == "host":  # no native lib: hashlib keeps the
                import hashlib  # contract on toolchain-less hosts

                nslots = 1 << log2_slots
                table = np.zeros((nslots, DIGEST_WORDS), dtype=np.uint32)
                slots = np.empty(n, dtype=np.int64)
                for i in range(n):
                    rd = hashlib.blake2b(records[i], digest_size=32).digest()
                    kd = hashlib.blake2b(keys[i], digest_size=32).digest()
                    slot = int.from_bytes(kd[:4], "little") & (nslots - 1)
                    slots[i] = slot
                    table[slot] += np.frombuffer(rd, np.uint32)
                self.table = table
                self.slots = slots
                self.keys = keys
                return
        self._init_device(buf, offs, lens, len(records), keys, log2_slots)

    def _init_device(self, buf, offs, lens, n: int, keys: list[bytes],
                     log2_slots: int) -> None:
        import jax

        from ..batch.feed import hash_extents_device

        with span("reconcile.hash"):
            all_hh, all_hl = hash_extents_device(buf, offs, lens)
        global _SUMMARIZE_JIT
        if _SUMMARIZE_JIT is None:  # one wrapper, so jit caching applies
            _SUMMARIZE_JIT = jax.jit(_summarize, static_argnums=(2, 3))
        with span("reconcile.sketch"):
            self.table, slots = _SUMMARIZE_JIT(all_hh, all_hl, n, log2_slots)
        self.slots = np.asarray(slots).astype(np.int64)
        self.keys = keys


def reconcile(a: "LogSummary", b: "LogSummary") -> dict:
    """Keys each side must exchange to converge.

    Returns ``{"slots": differing_slots, "a_keys": [...], "b_keys": [...]}``
    — every truly differing/inserted/deleted record's key is included
    (no false negatives: its cell must differ unless a collision sums to
    an identical cell value, a ~2**-256-grade event); false positives
    are co-resident keys of differing cells, bounded by the load factor.
    """
    slots = diff_sketches(a.table, b.table)
    slot_set = np.isin(a.slots, slots)
    a_keys = [a.keys[i] for i in np.nonzero(slot_set)[0]]
    slot_set_b = np.isin(b.slots, slots)
    b_keys = [b.keys[i] for i in np.nonzero(slot_set_b)[0]]
    return {"slots": slots, "a_keys": a_keys, "b_keys": b_keys}
