"""Batched BLAKE2b on device (JAX/XLA, TPU-first).

The reference does no hashing at all; content-addressing lives above it in
dat core.  The TPU-native framework pulls it into the data plane
(BASELINE.json north star: "batched BLAKE2b ... thousands of blobs per XLA
dispatch").  Design:

* 64-bit words are (hi, lo) uint32 lane pairs (:mod:`.u64`) — byte-exact
  RFC 7693 BLAKE2b without 64-bit integer lanes.
* The batch dim is the vector dim, in SoA layout: the 16 working-vector
  lanes are 16 separate (hi, lo) pairs of ``(B,)`` vectors, selected by
  Python indexing.  Every 64-bit op is a full-width elementwise VPU op
  over all B items; there are no gathers or dynamic-update-slices in the
  round function.  The 12 rounds are Python-unrolled (static) so XLA sees
  one straight fused elementwise pipeline per block.
* Variable lengths inside one padded batch: a `lax.scan` over the padded
  block axis with per-item ``active`` / ``final`` masks and byte counters —
  no data-dependent shapes, no recompiles across batches of the same padded
  shape.
* Host edge: :func:`blake2b_batch` packs ``list[bytes]`` into padded uint32
  arrays (bucketed by power-of-two block count to bound padding waste and
  compile count) and unpacks digests, preserving submit order — the
  completion-queue contract the session backend relies on
  (reference semantics: decode.js:87-99 pending accounting).

Per-item payloads are limited to < 2 GiB (byte counters carried in uint32;
larger streams go through the Rabin chunker first, mirroring the
reference's "blobs are streamed, never materialized" discipline,
reference: README.md:73).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.device import jit_site as _jit_site
from ..obs.device import note_engine as _note_engine
from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter
from .u64 import U32, add64, add64_3, ror64

# device-transfer attribution (OBSERVABILITY.md device-telemetry
# catalog): message words staged host->device per batch dispatch, and
# digest bytes fetched device->host at collect
_M_H2D = _counter("device.h2d.bytes")
_M_D2H = _counter("device.d2h.bytes")

DIGEST_SIZE = 32  # BLAKE2b-256 default, dat's content-hash size
BLOCK_BYTES = 128

_IV = (
    0x6A09E667F3BCC908,
    0xBB67AE8584CAA73B,
    0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1,
    0x510E527FADE682D1,
    0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B,
    0x5BE0CD19137E2179,
)
_IV_HI = np.array([w >> 32 for w in _IV], dtype=np.uint32)
_IV_LO = np.array([w & 0xFFFFFFFF for w in _IV], dtype=np.uint32)

_SIGMA = np.array(
    [
        [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
        [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
        [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
        [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
        [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
        [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
        [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
        [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
        [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
        [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
    ],
    dtype=np.int32,
)
# rounds 10, 11 reuse schedules 0, 1
_ROUND_SIGMA = [_SIGMA[r % 10] for r in range(12)]

# the 8 G applications per round: (a, b, c, d) working-vector lane indices,
# columns then diagonals (RFC 7693 §3.2)
_G_LANES = (
    (0, 4, 8, 12),
    (1, 5, 9, 13),
    (2, 6, 10, 14),
    (3, 7, 11, 15),
    (0, 5, 10, 15),
    (1, 6, 11, 12),
    (2, 7, 8, 13),
    (3, 4, 9, 14),
)


def _g(v, a, b, c, d, x, y):
    """One G mix on SoA state: ``v`` is a list of 16 (hi, lo) pairs of (B,)
    vectors; lane selection is Python indexing, so the whole mix lowers to
    full-width elementwise VPU ops — no gathers, no dynamic-update-slices.
    (The earlier (B, 16) array-of-struct layout spent its time in per-lane
    scatter updates and 16-wide minor-dim padding; SoA is ~3 orders of
    magnitude faster on the VPU.)
    """
    (ah, al), (bh, bl), (ch, cl), (dh, dl) = v[a], v[b], v[c], v[d]
    xh, xl = x
    yh, yl = y

    ah, al = add64_3(ah, al, bh, bl, xh, xl)
    dh, dl = ror64(dh ^ ah, dl ^ al, 32)
    ch, cl = add64(ch, cl, dh, dl)
    bh, bl = ror64(bh ^ ch, bl ^ cl, 24)
    ah, al = add64_3(ah, al, bh, bl, yh, yl)
    dh, dl = ror64(dh ^ ah, dl ^ al, 16)
    ch, cl = add64(ch, cl, dh, dl)
    bh, bl = ror64(bh ^ ch, bl ^ cl, 63)

    v[a], v[b], v[c], v[d] = (ah, al), (bh, bl), (ch, cl), (dh, dl)


def _rounds_unrolled(v, m):
    """All 12 rounds Python-unrolled: one straight ~5k-op elementwise DAG.

    Best runtime on TPU (XLA fuses the whole chain, zero loop or gather
    overhead) but pathological to *compile* on the CPU backend's LLVM
    pipeline — hence the scanned variant below for host runs.
    """
    for sigma in _ROUND_SIGMA:
        for gi, (a, b, c, d) in enumerate(_G_LANES):
            _g(v, a, b, c, d, m[sigma[2 * gi]], m[sigma[2 * gi + 1]])
    return v


def _g_stage4(v, quads, ms):
    """Four independent G mixes emitted stage-by-stage in lockstep.

    Semantically identical to calling :func:`_g` on each quad in turn
    (the 4 column Gs touch disjoint lanes, as do the 4 diagonal Gs); the
    only difference is SSA emission order — each of the 8 G stages is
    issued for all four quads before the next stage, so ~4 independent
    ops sit between every dependent pair in the instruction stream.  A
    scheduling experiment: a perfect scheduler would make this a no-op.
    """
    regs = [[v[a], v[b], v[c], v[d]] for (a, b, c, d) in quads]

    def stage_add3(operand):
        # a = a + b + m: destination lane 0, addend lane 1 — both fixed
        # by the G function's shape (advisor r4: a parameterized dst
        # with a hardcoded addend invited miscalls)
        for k in range(4):
            (ah, al) = regs[k][0]
            (bh, bl) = regs[k][1]
            (xh, xl) = operand[k]
            regs[k][0] = add64_3(ah, al, bh, bl, xh, xl)

    def stage_xor_ror(dst, src, r):
        for k in range(4):
            (dh, dl) = regs[k][dst]
            (sh, sl) = regs[k][src]
            regs[k][dst] = ror64(dh ^ sh, dl ^ sl, r)

    def stage_add(dst, src):
        for k in range(4):
            (ch, cl) = regs[k][dst]
            (dh, dl) = regs[k][src]
            regs[k][dst] = add64(ch, cl, dh, dl)

    xs = [p[0] for p in ms]
    ys = [p[1] for p in ms]
    stage_add3(xs)
    stage_xor_ror(3, 0, 32)
    stage_add(2, 3)
    stage_xor_ror(1, 2, 24)
    stage_add3(ys)
    stage_xor_ror(3, 0, 16)
    stage_add(2, 3)
    stage_xor_ror(1, 2, 63)
    for k, (a, b, c, d) in enumerate(quads):
        v[a], v[b], v[c], v[d] = regs[k]


def _rounds_unrolled_interleaved(v, m):
    """The 12 rounds with columns/diagonals emitted in 4-way lockstep."""
    for sigma in _ROUND_SIGMA:
        _g_stage4(
            v, _G_LANES[:4],
            [(m[sigma[2 * gi]], m[sigma[2 * gi + 1]]) for gi in range(4)],
        )
        _g_stage4(
            v, _G_LANES[4:],
            [(m[sigma[2 * gi]], m[sigma[2 * gi + 1]]) for gi in range(4, 8)],
        )
    return v


def _rounds_scanned(v, m, sigma=None):
    """The 12 rounds as a lax.scan with runtime sigma gathers.

    ~12x smaller HLO than the unrolled form: the body is one round (8 G
    mixes) and the per-round message schedule is a 16-row gather from the
    stacked message words.  Used on the CPU backend where compile time,
    not VPU throughput, is the binding constraint (tests, virtual-mesh
    dry runs).  ``sigma`` overrides the (12, 16) schedule table — pallas
    kernels must pass it in as an input (no closure constants allowed).
    """
    vh = jnp.stack([p[0] for p in v])
    vl = jnp.stack([p[1] for p in v])
    mh = jnp.stack([p[0] for p in m])
    ml = jnp.stack([p[1] for p in m])
    sig = jnp.asarray(np.stack(_ROUND_SIGMA)) if sigma is None else sigma

    def round_body(carry, sig_r):
        vh, vl = carry
        xh = jnp.take(mh, sig_r, axis=0)
        xl = jnp.take(ml, sig_r, axis=0)
        vv = [(vh[i], vl[i]) for i in range(16)]
        for gi, (a, b, c, d) in enumerate(_G_LANES):
            _g(vv, a, b, c, d, (xh[2 * gi], xl[2 * gi]), (xh[2 * gi + 1], xl[2 * gi + 1]))
        return (
            jnp.stack([p[0] for p in vv]),
            jnp.stack([p[1] for p in vv]),
        ), None

    (vh, vl), _ = jax.lax.scan(round_body, (vh, vl), sig)
    return [(vh[i], vl[i]) for i in range(16)]


def compress_soa(h, m, t_lo, is_final, unroll: bool | None = None, sigma=None,
                 t_hi=None, lanes=None, g_interleave: bool = False):
    """One BLAKE2b compression in SoA layout.

    ``h``: list of 8 (hi, lo) pairs of (B,) uint32 vectors; ``m``: list of
    16 such pairs (message words); ``t_lo``: (B,) uint32 byte counter after
    this block; ``t_hi``: optional (B,) high counter word for streams past
    4 GiB (None = zero, the single-dispatch case); ``is_final``: (B,) bool
    last-block flags.  Returns the new h.

    ``unroll=None`` picks per backend: unrolled rounds on accelerators,
    scanned rounds on CPU (see the two round helpers).  Both are
    byte-exact RFC 7693.

    ``lanes``: optional mutable container for the 16 working-vector
    lanes (indexable get/set of (hi, lo) pairs — e.g. the Pallas
    kernel's VMEM-scratch view).  The compression schedule then runs
    against that storage instead of Python-list registers; unrolled
    rounds only (the scanned form stacks arrays).
    """
    if unroll is None:
        unroll = jax.default_backend() != "cpu"
    if lanes is not None and not unroll:
        raise ValueError("a lanes container requires unrolled rounds")
    shape = t_lo.shape  # any batch shape: (B,) under scan, (8, B/8) in pallas
    v = lanes if lanes is not None else [None] * 16
    for i in range(8):
        v[i] = h[i]
        v[8 + i] = (
            jnp.full(shape, _IV_HI[i], U32),
            jnp.full(shape, _IV_LO[i], U32),
        )
    v12_hi = v[12][0] if t_hi is None else v[12][0] ^ t_hi
    v[12] = (v12_hi, v[12][1] ^ t_lo)
    f = jnp.where(is_final, U32(0xFFFFFFFF), U32(0))
    v[14] = (v[14][0] ^ f, v[14][1] ^ f)

    if unroll:
        rounds = _rounds_unrolled_interleaved if g_interleave else _rounds_unrolled
        v = rounds(v, m)
    else:
        v = _rounds_scanned(v, m, sigma)

    return [
        (hh ^ v[i][0] ^ v[i + 8][0], hl ^ v[i][1] ^ v[i + 8][1])
        for i, (hh, hl) in enumerate(h)
    ]


def compress(hh, hl, mh, ml, t_lo, is_final, unroll: bool | None = None):
    """Array-of-struct wrapper over :func:`compress_soa`.

    state (B, 8) hi/lo pairs, block (B, 16) pairs — the layout the packers
    and the Merkle level op exchange.  Unpacking to SoA costs 24 strided
    slices + 2 stacks per block, negligible against the ~4k elementwise ops
    of the 12 rounds.
    """
    h = [(hh[:, i], hl[:, i]) for i in range(8)]
    m = [(mh[:, i], ml[:, i]) for i in range(16)]
    h = compress_soa(h, m, t_lo, is_final, unroll=unroll)
    return (
        jnp.stack([p[0] for p in h], axis=1),
        jnp.stack([p[1] for p in h], axis=1),
    )


def initial_state(batch: int, digest_size: int = DIGEST_SIZE):
    """h0 = IV ^ parameter block (sequential mode, no key)."""
    hh = jnp.broadcast_to(jnp.asarray(_IV_HI), (batch, 8))
    hl = jnp.broadcast_to(jnp.asarray(_IV_LO), (batch, 8))
    param_lo = U32(0x01010000 ^ digest_size)  # digest | key<<8 | fanout | depth
    hl = hl.at[:, 0].set(hl[:, 0] ^ param_lo)
    return hh, hl


def _blake2b_packed_impl(mh, ml, lengths, digest_size: int = DIGEST_SIZE):
    """Hash a padded batch: mh/ml (B, nblocks, 16) uint32, lengths (B,).

    Padding bytes in the final partial block MUST be zero (the host packer
    guarantees this).  Returns digest words as (hh, hl), each (B, 8).
    """
    B, nblocks, _ = mh.shape
    hh, hl = initial_state(B, digest_size)
    lengths = lengths.astype(U32)
    # ceil(len/128), minimum 1: an empty message still compresses one block
    item_blocks = jnp.maximum((lengths + U32(127)) >> U32(7), U32(1))

    # carry in SoA layout — 16 flat (B,) vectors — so the scan body is a
    # pure elementwise DAG with no per-block stack/unstack
    carry0 = tuple(hh[:, i] for i in range(8)) + tuple(hl[:, i] for i in range(8))

    # message words to (nblocks, 16, B): each word a contiguous (B,) row in
    # the lane dim (the (B, 16) minor-dim layout pads 16 -> 128 lanes and
    # turns every per-word slice into a strided read)
    mh = jnp.transpose(mh, (1, 2, 0))
    ml = jnp.transpose(ml, (1, 2, 0))

    def step(carry, xs):
        h = [(carry[i], carry[i + 8]) for i in range(8)]
        bmh, bml, k = xs
        m = [(bmh[i], bml[i]) for i in range(16)]
        active = k < item_blocks
        final = k == item_blocks - U32(1)
        t_lo = jnp.minimum(lengths, (k + U32(1)) << U32(7))
        nh = compress_soa(h, m, t_lo, final)
        out = tuple(
            jnp.where(active, nh[i][0], h[i][0]) for i in range(8)
        ) + tuple(jnp.where(active, nh[i][1], h[i][1]) for i in range(8))
        return out, None

    ks = jnp.arange(nblocks, dtype=jnp.uint32)
    carry, _ = jax.lax.scan(step, carry0, (mh, ml, ks))
    return jnp.stack(carry[:8], axis=1), jnp.stack(carry[8:], axis=1)


blake2b_packed = functools.partial(jax.jit, static_argnames=("digest_size",))(
    _blake2b_packed_impl
)
# donated twin: the staged mh/ml message buffers are throwaway (packed
# on the host, consumed by exactly one dispatch), so donating them lets
# the allocator hand their HBM straight to the NEXT batch's staging —
# the "two donated input buffers" of the double-buffered upload path
# (ISSUE 7): dispatch N+1's h2d streams into memory dispatch N just
# released instead of growing the live set.  CPU jax ignores donation
# (and warns), so callers route here only when the backend honors it.
blake2b_packed_donated = functools.partial(
    jax.jit, static_argnames=("digest_size",), donate_argnums=(0, 1)
)(_blake2b_packed_impl)

# recompile sentinel (obs.device): jit specializes per (B, nblocks) —
# this is THE site the power-of-two bucketing below exists to protect
blake2b_packed = _jit_site("ops.blake2b.packed", blake2b_packed)
blake2b_packed_donated = _jit_site("ops.blake2b.packed_donated",
                                   blake2b_packed_donated)


def donation_supported() -> bool:
    """Whether this backend honors buffer donation: the ONE owner of the
    donated-vs-plain dispatch decision (CPU jax silently ignores
    donation and logs a warning per call).  ``DAT_DONATE=1/0``
    overrides, for tests and experiments."""
    import os

    force = os.environ.get("DAT_DONATE")
    if force == "1":
        return True
    if force == "0":
        return False
    return jax.default_backend() in ("tpu", "gpu")


@jax.jit
def blake2b_update(hh, hl, t_hi, t_lo, mh, ml, seg_lengths, is_last):
    """Advance chaining states over one packed segment per item.

    The resumable core of streaming hashing: a message is split into
    segments dispatched one at a time, so a blob of any size is hashed in
    bounded device memory — the device-scale analogue of the reference's
    "blobs are streamed, never materialized" (reference: README.md:73).

    ``hh``/``hl``: (B, 8) chaining state; ``t_hi``/``t_lo``: (B,) uint32
    pair = bytes already compressed (a multiple of 128 per RFC 7693
    block chaining); ``mh``/``ml``: (B, nblocks, 16) packed segment
    words; ``seg_lengths``: (B,) bytes in this segment — non-final
    segments must be full-block multiples; ``is_last``: (B,) bool.

    Returns ``(hh, hl, t_hi, t_lo)`` advanced past the segment.  The
    empty-message case (zero-length last segment with zero counter)
    compresses the mandatory single zero block.
    """
    B, nblocks, _ = mh.shape
    seg_lengths = seg_lengths.astype(U32)
    is_last = is_last.astype(bool)
    raw_blocks = (seg_lengths + U32(127)) >> U32(7)
    t_zero = (t_hi == U32(0)) & (t_lo == U32(0))
    item_blocks = jnp.where(
        is_last & (raw_blocks == U32(0)) & t_zero, U32(1), raw_blocks
    )

    carry0 = tuple(hh[:, i] for i in range(8)) + tuple(hl[:, i] for i in range(8))
    mh_t = jnp.transpose(mh, (1, 2, 0))
    ml_t = jnp.transpose(ml, (1, 2, 0))

    def step(carry, xs):
        h = [(carry[i], carry[i + 8]) for i in range(8)]
        bmh, bml, k = xs
        m = [(bmh[i], bml[i]) for i in range(16)]
        active = k < item_blocks
        final = is_last & (k == item_blocks - U32(1))
        inc = jnp.minimum(seg_lengths, (k + U32(1)) << U32(7))
        bt_hi, bt_lo = add64(t_hi, t_lo, jnp.zeros_like(inc), inc)
        nh = compress_soa(h, m, bt_lo, final, t_hi=bt_hi)
        out = tuple(
            jnp.where(active, nh[i][0], h[i][0]) for i in range(8)
        ) + tuple(jnp.where(active, nh[i][1], h[i][1]) for i in range(8))
        return out, None

    ks = jnp.arange(nblocks, dtype=jnp.uint32)
    carry, _ = jax.lax.scan(step, carry0, (mh_t, ml_t, ks))
    nt_hi, nt_lo = add64(t_hi, t_lo, jnp.zeros_like(seg_lengths), seg_lengths)
    return (
        jnp.stack(carry[:8], axis=1),
        jnp.stack(carry[8:], axis=1),
        nt_hi,
        nt_lo,
    )


blake2b_update = _jit_site("ops.blake2b.update", blake2b_update)


class Blake2bStream:
    """Incremental BLAKE2b over bounded device dispatches (one stream).

    ``update(bytes)`` buffers until a full segment is available, then
    advances the on-device (h, t) chaining state via
    :func:`blake2b_update`; ``digest()`` flushes the tail.  Peak host
    memory is O(segment_bytes) regardless of stream length, and the
    64-bit byte counter supports streams past 4 GiB — this removes the
    session backend's whole-blob host buffering and the < 2 GiB item cap.

    Middle segments all share one padded shape (one XLA compile); the
    final partial segment is bucketed to a power-of-two block count.
    """

    def __init__(self, digest_size: int = DIGEST_SIZE,
                 segment_bytes: int = 1 << 22, max_inflight: int = 2):
        if segment_bytes % BLOCK_BYTES:
            raise ValueError(f"segment_bytes must be a multiple of {BLOCK_BYTES}")
        self._digest_size = digest_size
        self._seg = segment_bytes
        self._max_inflight = max(1, max_inflight)
        self._fences: list = []  # oldest-first in-flight segment counters
        hh, hl = initial_state(1, digest_size)
        z = jnp.zeros((1,), U32)
        self._state = (hh, hl, z, z)
        self._pending = bytearray()
        self._digest: bytes | None = None
        self.length = 0

    def update(self, data) -> "Blake2bStream":
        if self._digest is not None:
            raise RuntimeError("update() after digest()")
        self._pending += bytes(data)
        self.length += len(data)
        # strictly '>' — the final block must go out WITH the final flag,
        # so when pending lands exactly on a segment boundary it is held
        # for digest() (an empty non-final segment can't set the flag)
        while len(self._pending) > self._seg:
            seg = bytes(self._pending[: self._seg])
            del self._pending[: self._seg]
            self._advance(seg, last=False)
        return self

    def _advance(self, seg: bytes, last: bool) -> None:
        import jax

        hh, hl, thi, tlo = self._state
        nblocks = max(1, -(-len(seg) // BLOCK_BYTES))
        if last:
            nblocks = _bucket_nblocks(nblocks)  # bound tail-shape compiles
        mh, ml, lengths = pack_payloads([seg], nblocks=nblocks)
        # stage the upload explicitly: device_put returns immediately and
        # the transfer streams while the device is still compressing the
        # previous segments — H2D rides under compute instead of after it
        mh_d = jax.device_put(mh)
        ml_d = jax.device_put(ml)
        self._state = blake2b_update(
            hh, hl, thi, tlo,
            mh_d, ml_d, jnp.asarray(lengths),
            jnp.asarray([last]),
        )
        # bounded async dispatch: without a periodic barrier the host can
        # outrun the device and queue every segment's message arrays in
        # RAM — the O(chunk) discipline would silently become O(blob).
        # Fetching a (tiny) counter word is the completion barrier that
        # works on platforms where block_until_ready returns early.  The
        # fence targets the OLDEST in-flight segment, not the newest:
        # waiting on the newest would drain the whole pipeline and stall
        # the next segment's upload behind it (round-3 verdict weak #5).
        self._fences.append(self._state[3])
        while len(self._fences) >= self._max_inflight:
            np.asarray(self._fences.pop(0))

    def digest(self) -> bytes:
        if self._digest is None:
            self._advance(bytes(self._pending), last=True)
            self._pending.clear()
            hh, hl, _, _ = self._state
            self._digest = digests_to_bytes(hh, hl, self._digest_size)[0]
        return self._digest


# ---------------------------------------------------------------------------
# host edge: bytes <-> padded uint32 batches
# ---------------------------------------------------------------------------


def pack_payloads(payloads, nblocks: int | None = None):
    """Pack byte strings into padded (B, nblocks, 16) hi/lo uint32 arrays.

    Little-endian 64-bit message words: u32-word index 2k is word k's low
    half, 2k+1 its high half.  Zero padding satisfies the blake2b_packed
    contract.
    """
    B = len(payloads)
    max_len = max((len(p) for p in payloads), default=0)
    need = max(1, -(-max_len // BLOCK_BYTES))
    if nblocks is None:
        nblocks = need
    elif nblocks < need:
        raise ValueError(f"nblocks={nblocks} < required {need}")
    buf = np.zeros((B, nblocks * BLOCK_BYTES), dtype=np.uint8)
    lengths = np.empty((B,), dtype=np.uint32)
    for i, p in enumerate(payloads):
        if len(p) >= 1 << 31:
            raise ValueError("per-item payload limit is < 2 GiB; chunk first")
        buf[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        lengths[i] = len(p)
    words = buf.view("<u4").reshape(B, nblocks, 32)
    return words[:, :, 1::2].copy(), words[:, :, 0::2].copy(), lengths


def digests_to_bytes(hh, hl, digest_size: int = DIGEST_SIZE) -> list[bytes]:
    """Interleave (hi, lo) word pairs back into little-endian digest bytes."""
    hh = np.asarray(hh, dtype=np.uint32)
    hl = np.asarray(hl, dtype=np.uint32)
    B = hh.shape[0]
    out = np.empty((B, 16), dtype=np.uint32)
    out[:, 0::2] = hl
    out[:, 1::2] = hh
    raw = out.astype("<u4").view(np.uint8).reshape(B, 64)
    return [raw[i, :digest_size].tobytes() for i in range(B)]


def _bucket_nblocks(n: int) -> int:
    """Round a block count up to a power of two to bound compile count."""
    from ..utils.num import next_pow2

    return next_pow2(n)


# below this bucket size the pallas kernel's pad-to-1024-items overhead
# outweighs its throughput edge over the XLA-scan path
_PALLAS_MIN_ITEMS = 512


def blake2b_batch_begin(
    payloads, digest_size: int = DIGEST_SIZE, use_pallas: bool | None = None
):
    """Dispatch batched hashing; return a zero-arg ``collect()`` closure.

    JAX dispatch is asynchronous: the device starts compressing as soon
    as this returns, while the host goes back to parsing.  ``collect()``
    blocks on the transfers and yields digests in submit order — the
    split the async DigestPipeline uses to overlap parse and hash.

    Items are grouped into power-of-two block-count buckets; each bucket
    is one padded XLA dispatch.  ``use_pallas=None`` selects, per bucket,
    the Pallas kernel on TPU backends when the bucket is large enough to
    amortize its 1024-item tile padding, and the portable XLA-scan path
    otherwise.
    """
    on_tpu = jax.default_backend() == "tpu"
    donate = donation_supported()
    buckets: dict[int, list[int]] = {}
    for i, p in enumerate(payloads):
        nb = _bucket_nblocks(max(1, -(-len(p) // BLOCK_BYTES)))
        buckets.setdefault(nb, []).append(i)
    handles = []
    for nb, idxs in buckets.items():
        pallas_bucket = (
            use_pallas
            if use_pallas is not None
            else on_tpu and len(idxs) >= _PALLAS_MIN_ITEMS
        )
        if pallas_bucket:
            if donate:
                from .blake2b_pallas import (
                    blake2b_packed_pallas_donated as packed_fn,
                )
            else:
                from .blake2b_pallas import blake2b_packed_pallas as packed_fn
        else:
            packed_fn = blake2b_packed_donated if donate else blake2b_packed
        if _OBS.on:
            # keyed per bucket: the engine choice is per block-count
            # bucket, and the change-only memo must not flap when a
            # payload mix straddles the pallas item floor
            _note_engine("blake2b.batch",
                         "pallas" if pallas_bucket else "xla-scan",
                         key=nb, items=len(idxs), nblocks=nb)
        # pad the batch axis to a power of two as well: jit specializes
        # per (B, nblocks), so unbucketed batch sizes recompile every
        # distinct count (minutes each on the CPU scanned path).  Empty
        # payloads are valid; their digests are dropped in collect().
        batch = [payloads[i] for i in idxs]
        Bp = _bucket_nblocks(len(batch))
        batch += [b""] * (Bp - len(batch))
        mh, ml, lengths = pack_payloads(batch, nblocks=nb)
        if _OBS.on:
            _M_H2D.inc(mh.nbytes + ml.nbytes + lengths.nbytes)
        # stage explicitly (device_put returns immediately): the upload
        # streams while earlier batches compress, and — when donation is
        # supported — the staged buffers are DONATED to the dispatch, so
        # successive batches double-buffer through recycled staging HBM
        # instead of growing the live set
        mh_d = jax.device_put(mh)
        ml_d = jax.device_put(ml)
        hh, hl = packed_fn(
            mh_d, ml_d, jnp.asarray(lengths), digest_size
        )
        handles.append((idxs, hh[: len(idxs)], hl[: len(idxs)]))

    def start_d2h() -> None:
        # begin the digest readback WITHOUT blocking: by collect() time
        # the words are local (or in flight under newer batches'
        # compute).  Idempotent; the DigestPipeline calls this when a
        # NEWER batch is dispatched so deliver never serializes a cold
        # D2H behind the next submit (ISSUE 7 part 3).
        for _, hh, hl in handles:
            for arr in (hh, hl):
                copy_async = getattr(arr, "copy_to_host_async", None)
                if copy_async is not None:
                    copy_async()

    def collect() -> list[bytes]:
        out: list[bytes | None] = [None] * len(payloads)
        for idxs, hh, hl in handles:
            if _OBS.on:
                # two (B, 8) u32 halves fetched per bucket = 64 B/item
                _M_D2H.inc(64 * len(idxs))
            for i, d in zip(idxs, digests_to_bytes(hh, hl, digest_size)):
                out[i] = d
        return out  # type: ignore[return-value]

    collect.start_d2h = start_d2h  # type: ignore[attr-defined]
    return collect


def blake2b_batch(
    payloads, digest_size: int = DIGEST_SIZE, use_pallas: bool | None = None
) -> list[bytes]:
    """Hash a list of byte strings on device; digests in submit order."""
    if not payloads:
        return []
    return blake2b_batch_begin(payloads, digest_size, use_pallas)()
