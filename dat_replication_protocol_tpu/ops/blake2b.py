"""Batched BLAKE2b on device (JAX/XLA, TPU-first).

The reference does no hashing at all; content-addressing lives above it in
dat core.  The TPU-native framework pulls it into the data plane
(BASELINE.json north star: "batched BLAKE2b ... thousands of blobs per XLA
dispatch").  Design:

* 64-bit words are (hi, lo) uint32 lane pairs (:mod:`.u64`) — byte-exact
  RFC 7693 BLAKE2b without 64-bit integer lanes.
* The batch dim is the vector dim: state is ``(B, 8)`` word pairs, message
  blocks ``(B, 16)`` word pairs.  Every G mixes 4 lanes of all B items at
  once; the 12 rounds are Python-unrolled (static) so XLA sees one straight
  fused elementwise pipeline per block.
* Variable lengths inside one padded batch: a `lax.scan` over the padded
  block axis with per-item ``active`` / ``final`` masks and byte counters —
  no data-dependent shapes, no recompiles across batches of the same padded
  shape.
* Host edge: :func:`blake2b_batch` packs ``list[bytes]`` into padded uint32
  arrays (bucketed by power-of-two block count to bound padding waste and
  compile count) and unpacks digests, preserving submit order — the
  completion-queue contract the session backend relies on
  (reference semantics: decode.js:87-99 pending accounting).

Per-item payloads are limited to < 2 GiB (byte counters carried in uint32;
larger streams go through the Rabin chunker first, mirroring the
reference's "blobs are streamed, never materialized" discipline,
reference: README.md:73).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .u64 import U32, add64_3, ror64

DIGEST_SIZE = 32  # BLAKE2b-256 default, dat's content-hash size
BLOCK_BYTES = 128

_IV = (
    0x6A09E667F3BCC908,
    0xBB67AE8584CAA73B,
    0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1,
    0x510E527FADE682D1,
    0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B,
    0x5BE0CD19137E2179,
)
_IV_HI = np.array([w >> 32 for w in _IV], dtype=np.uint32)
_IV_LO = np.array([w & 0xFFFFFFFF for w in _IV], dtype=np.uint32)

_SIGMA = np.array(
    [
        [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
        [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
        [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
        [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
        [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
        [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
        [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
        [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
        [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
        [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
    ],
    dtype=np.int32,
)
# rounds 10, 11 reuse schedules 0, 1
_ROUND_SIGMA = [_SIGMA[r % 10] for r in range(12)]

# column then diagonal lane groups for the vectorized quad-G
_COL = (
    np.array([0, 1, 2, 3]),
    np.array([4, 5, 6, 7]),
    np.array([8, 9, 10, 11]),
    np.array([12, 13, 14, 15]),
)
_DIAG = (
    np.array([0, 1, 2, 3]),
    np.array([5, 6, 7, 4]),
    np.array([10, 11, 8, 9]),
    np.array([15, 12, 13, 14]),
)


def _quad_g(vh, vl, lanes, xh, xl, yh, yl):
    """One vectorized G over 4 disjoint lanes of all batch items.

    vh/vl: (B, 16); xh/xl/yh/yl: (B, 4) message words for these lanes.
    """
    ai, bi, ci, di = lanes
    ah, al = vh[:, ai], vl[:, ai]
    bh, bl = vh[:, bi], vl[:, bi]
    ch, cl = vh[:, ci], vl[:, ci]
    dh, dl = vh[:, di], vl[:, di]

    ah, al = add64_3(ah, al, bh, bl, xh, xl)
    dh, dl = ror64(dh ^ ah, dl ^ al, 32)
    ch, cl = add64_3(ch, cl, dh, dl, jnp.zeros_like(ch), jnp.zeros_like(cl))
    bh, bl = ror64(bh ^ ch, bl ^ cl, 24)
    ah, al = add64_3(ah, al, bh, bl, yh, yl)
    dh, dl = ror64(dh ^ ah, dl ^ al, 16)
    ch, cl = add64_3(ch, cl, dh, dl, jnp.zeros_like(ch), jnp.zeros_like(cl))
    bh, bl = ror64(bh ^ ch, bl ^ cl, 63)

    vh = vh.at[:, ai].set(ah).at[:, bi].set(bh).at[:, ci].set(ch).at[:, di].set(dh)
    vl = vl.at[:, ai].set(al).at[:, bi].set(bl).at[:, ci].set(cl).at[:, di].set(dl)
    return vh, vl


def compress(hh, hl, mh, ml, t_lo, is_final):
    """One BLAKE2b compression: state (B,8) pairs, block (B,16) pairs.

    ``t_lo``: (B,) uint32 byte counter after this block (items < 2 GiB, so
    the high counter words t0_hi/t1 are constant zero).  ``is_final``: (B,)
    bool last-block flags.
    """
    B = hh.shape[0]
    iv_h = jnp.broadcast_to(jnp.asarray(_IV_HI), (B, 8))
    iv_l = jnp.broadcast_to(jnp.asarray(_IV_LO), (B, 8))
    vh = jnp.concatenate([hh, iv_h], axis=1)
    vl = jnp.concatenate([hl, iv_l], axis=1)

    vl = vl.at[:, 12].set(vl[:, 12] ^ t_lo)
    f = jnp.where(is_final, U32(0xFFFFFFFF), U32(0))
    vh = vh.at[:, 14].set(vh[:, 14] ^ f)
    vl = vl.at[:, 14].set(vl[:, 14] ^ f)

    for sigma in _ROUND_SIGMA:
        cx, cy = sigma[0:8:2], sigma[1:8:2]
        dx, dy = sigma[8:16:2], sigma[9:16:2]
        vh, vl = _quad_g(vh, vl, _COL, mh[:, cx], ml[:, cx], mh[:, cy], ml[:, cy])
        vh, vl = _quad_g(vh, vl, _DIAG, mh[:, dx], ml[:, dx], mh[:, dy], ml[:, dy])

    return hh ^ vh[:, :8] ^ vh[:, 8:], hl ^ vl[:, :8] ^ vl[:, 8:]


def initial_state(batch: int, digest_size: int = DIGEST_SIZE):
    """h0 = IV ^ parameter block (sequential mode, no key)."""
    hh = jnp.broadcast_to(jnp.asarray(_IV_HI), (batch, 8))
    hl = jnp.broadcast_to(jnp.asarray(_IV_LO), (batch, 8))
    param_lo = U32(0x01010000 ^ digest_size)  # digest | key<<8 | fanout | depth
    hl = hl.at[:, 0].set(hl[:, 0] ^ param_lo)
    return hh, hl


@functools.partial(jax.jit, static_argnames=("digest_size",))
def blake2b_packed(mh, ml, lengths, digest_size: int = DIGEST_SIZE):
    """Hash a padded batch: mh/ml (B, nblocks, 16) uint32, lengths (B,).

    Padding bytes in the final partial block MUST be zero (the host packer
    guarantees this).  Returns digest words as (hh, hl), each (B, 8).
    """
    B, nblocks, _ = mh.shape
    hh, hl = initial_state(B, digest_size)
    lengths = lengths.astype(U32)
    # ceil(len/128), minimum 1: an empty message still compresses one block
    item_blocks = jnp.maximum((lengths + U32(127)) >> U32(7), U32(1))

    def step(carry, xs):
        hh, hl = carry
        bmh, bml, k = xs
        active = k < item_blocks
        final = k == item_blocks - U32(1)
        t_lo = jnp.minimum(lengths, (k + U32(1)) << U32(7))
        nh, nl = compress(hh, hl, bmh, bml, t_lo, final)
        keep = active[:, None]
        return (jnp.where(keep, nh, hh), jnp.where(keep, nl, hl)), None

    ks = jnp.arange(nblocks, dtype=jnp.uint32)
    (hh, hl), _ = jax.lax.scan(
        step, (hh, hl), (mh.swapaxes(0, 1), ml.swapaxes(0, 1), ks)
    )
    return hh, hl


# ---------------------------------------------------------------------------
# host edge: bytes <-> padded uint32 batches
# ---------------------------------------------------------------------------


def pack_payloads(payloads, nblocks: int | None = None):
    """Pack byte strings into padded (B, nblocks, 16) hi/lo uint32 arrays.

    Little-endian 64-bit message words: u32-word index 2k is word k's low
    half, 2k+1 its high half.  Zero padding satisfies the blake2b_packed
    contract.
    """
    B = len(payloads)
    max_len = max((len(p) for p in payloads), default=0)
    need = max(1, -(-max_len // BLOCK_BYTES))
    if nblocks is None:
        nblocks = need
    elif nblocks < need:
        raise ValueError(f"nblocks={nblocks} < required {need}")
    buf = np.zeros((B, nblocks * BLOCK_BYTES), dtype=np.uint8)
    lengths = np.empty((B,), dtype=np.uint32)
    for i, p in enumerate(payloads):
        if len(p) >= 1 << 31:
            raise ValueError("per-item payload limit is < 2 GiB; chunk first")
        buf[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        lengths[i] = len(p)
    words = buf.view("<u4").reshape(B, nblocks, 32)
    return words[:, :, 1::2].copy(), words[:, :, 0::2].copy(), lengths


def digests_to_bytes(hh, hl, digest_size: int = DIGEST_SIZE) -> list[bytes]:
    """Interleave (hi, lo) word pairs back into little-endian digest bytes."""
    hh = np.asarray(hh, dtype=np.uint32)
    hl = np.asarray(hl, dtype=np.uint32)
    B = hh.shape[0]
    out = np.empty((B, 16), dtype=np.uint32)
    out[:, 0::2] = hl
    out[:, 1::2] = hh
    raw = out.astype("<u4").view(np.uint8).reshape(B, 64)
    return [raw[i, :digest_size].tobytes() for i in range(B)]


def _bucket_nblocks(n: int) -> int:
    """Round a block count up to a power of two to bound compile count."""
    b = 1
    while b < n:
        b <<= 1
    return b


def blake2b_batch(payloads, digest_size: int = DIGEST_SIZE) -> list[bytes]:
    """Hash a list of byte strings on device; digests in submit order.

    Items are grouped into power-of-two block-count buckets; each bucket is
    one padded XLA dispatch.  This is the ``hash_batch`` engine the
    ``backend='tpu'`` session pipeline plugs in.
    """
    if not payloads:
        return []
    buckets: dict[int, list[int]] = {}
    for i, p in enumerate(payloads):
        nb = _bucket_nblocks(max(1, -(-len(p) // BLOCK_BYTES)))
        buckets.setdefault(nb, []).append(i)
    out: list[bytes | None] = [None] * len(payloads)
    for nb, idxs in buckets.items():
        mh, ml, lengths = pack_payloads([payloads[i] for i in idxs], nblocks=nb)
        hh, hl = blake2b_packed(
            jnp.asarray(mh), jnp.asarray(ml), jnp.asarray(lengths), digest_size
        )
        for i, d in zip(idxs, digests_to_bytes(hh, hl, digest_size)):
            out[i] = d
    return out  # type: ignore[return-value]
