"""Merkle tree build + two-snapshot diff as jitted device ops.

The reference has no Merkle machinery — resumable replication lives in dat
core above the wire protocol (reference: messages/schema.proto:4-5 carries
``from``/``to`` version fields for it).  The TPU-native framework pulls set
reconciliation into the data plane (BASELINE.json north star: "Merkle-tree
diff of two 1M-leaf change-log snapshots", target >= 10M diff entries/sec).

Design (TPU-first):

* A node digest is BLAKE2b-256 of the 64-byte concatenation of its two
  children's 32-byte digests — exactly one BLAKE2b compression per parent,
  so level ``k -> k+1`` is a single batched :func:`..ops.blake2b.compress`
  call over ``N/2`` items.  No data-dependent shapes: a tree over ``2**L``
  leaves is ``L`` static level steps under one jit.
* Digests stay on device in the (hi, lo) uint32 lane-pair layout of
  :mod:`.u64` — ``(N, 4)`` word pairs per level — so building a tree from
  the batched leaf hasher's output involves no host round-trip and no
  byte re-packing.
* The diff is **tree-guided and fully vectorized**: walking top-down, a
  level's inequality mask is AND-ed with its parent's mask repeated over
  children.  Equal subtrees are masked out in O(1) vector work per level
  rather than skipped via control flow — the XLA-friendly formulation of
  the classic "descend only into differing nodes" walk.  The kernel
  returns a leaf mask; dynamic-shape index extraction happens on the host.

Host-reference implementations (``host_*``) back the property tests.
"""

from __future__ import annotations

import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.device import jit_site as _jit_site

from .blake2b import compress, initial_state
from .u64 import U32

DIGEST_SIZE = 32
_DIGEST_WORDS = 4  # 32 bytes = 4 x u64 lane pairs


def merkle_parent(ahh, ahl, bhh, bhl):
    """Hash pairs of sibling digests into parents: all (N, 4) uint32.

    Parent = BLAKE2b-256(child_left || child_right): a 64-byte message,
    one compression block per parent, vectorized over all N pairs.

    Uses the scanned-rounds compression: a tree build instantiates this
    op once per level, and the unrolled ~5k-op variant makes 20-level
    tree programs pathologically slow to compile (XLA chokes past ~100k
    ops); the scanned form keeps a whole build+diff program around ~3k
    ops for a ~2x runtime cost that the fixed-width scan below already
    amortizes.
    """
    n = ahh.shape[0]
    zeros = jnp.zeros((n, 16), dtype=U32)
    mh = zeros.at[:, :4].set(ahh).at[:, 4:8].set(bhh)
    ml = zeros.at[:, :4].set(ahl).at[:, 4:8].set(bhl)
    hh, hl = initial_state(n, DIGEST_SIZE)
    t_lo = jnp.full((n,), 2 * DIGEST_SIZE, dtype=U32)
    final = jnp.ones((n,), dtype=bool)
    hh, hl = compress(hh, hl, mh, ml, t_lo, final, unroll=False)
    return hh[:, :_DIGEST_WORDS], hl[:, :_DIGEST_WORDS]


def merkle_level(hh, hl):
    """One tree level: (N, 4) digests -> (N//2, 4) parent digests.

    Left/right children are even/odd rows (leaf ``i`` pairs with ``i^1``,
    dat's flat in-order convention).
    """
    return merkle_parent(hh[0::2], hl[0::2], hh[1::2], hl[1::2])


# below this parent count the Pallas kernel's pad-to-1024-items overhead
# outweighs its edge over the scanned XLA path (and small levels are a
# rounding error of the tree's total work anyway)
_PALLAS_MIN_PARENTS = 8192


def _merkle_level_opt(hh, hl):
    """Level step routed to the fastest available engine.

    Large levels on TPU go through the dedicated single-block Pallas
    kernel (:mod:`.merkle_pallas`), which retires the scanned-rounds
    compile-time compromise of :func:`merkle_parent` exactly where its
    ~2x runtime cost was actually felt; small levels and other backends
    keep the portable path.
    """
    if (
        hh.shape[0] // 2 >= _PALLAS_MIN_PARENTS
        and jax.default_backend() == "tpu"
    ):
        from .merkle_pallas import merkle_level_pallas

        return merkle_level_pallas(hh, hl)
    return merkle_level(hh, hl)


@jax.jit
def build_tree(leaf_hh, leaf_hl):
    """All levels leaves -> root. Leaf count must be a power of two.

    Returns (levels_hh, levels_lo): tuples of per-level arrays ordered
    leaves first, root (shape (1, 4)) last.  The level count is static, so
    the whole build is one fused jit program of log2(N) batched
    compressions.
    """
    n = leaf_hh.shape[0]
    if n == 0 or n & (n - 1):
        raise ValueError(f"leaf count {n} is not a power of two; pad first")
    levels_hh, levels_hl = [leaf_hh], [leaf_hl]
    while leaf_hh.shape[0] > 1:
        leaf_hh, leaf_hl = _merkle_level_opt(leaf_hh, leaf_hl)
        levels_hh.append(leaf_hh)
        levels_hl.append(leaf_hl)
    return tuple(levels_hh), tuple(levels_hl)


build_tree = _jit_site("ops.merkle.build_tree", build_tree)


def root(leaf_hh, leaf_hl):
    """Root digest only: (1, 4) hi/lo word pairs."""
    hhs, hls = build_tree(leaf_hh, leaf_hl)
    return hhs[-1], hls[-1]


def _node_neq(ahh, ahl, bhh, bhl):
    """(N,) bool: per-node digest inequality."""
    return jnp.any((ahh != bhh) | (ahl != bhl), axis=1)


@jax.jit
def diff_root_guided(a_leaf_hh, a_leaf_hl, b_leaf_hh, b_leaf_hl):
    """Build both trees and diff them in one jitted program.

    Returns (mask, a_root_pair, b_root_pair).  This is the bench config-5
    kernel: two snapshots' leaf digests in, differing-leaf mask out.

    Both trees are built as ONE concatenated tree: with a power-of-two
    leaf width, the even/odd sibling pairing never crosses the midpoint
    of ``concat(a, b)``, so each combined level's halves are exactly the
    two trees' levels.  One level-op chain instead of two halves the
    per-level dispatch overhead, doubles every batch (the small top
    levels were pure fixed cost), and lifts twice as many levels over
    the Pallas kernel's minimum-parents threshold.
    """
    n = a_leaf_hh.shape[0]
    if n == 0 or n & (n - 1):
        raise ValueError(f"leaf count {n} is not a power of two; pad first")
    if b_leaf_hh.shape[0] != n:
        raise ValueError(
            f"snapshot widths differ: {n} vs {b_leaf_hh.shape[0]}; pad first"
        )
    hh = jnp.concatenate([a_leaf_hh, b_leaf_hh])
    hl = jnp.concatenate([a_leaf_hl, b_leaf_hl])
    levels = []
    while hh.shape[0] > 2:
        levels.append((hh, hl))
        hh, hl = _merkle_level_opt(hh, hl)
    # hh/hl is now (2, 4): row 0 = A's root, row 1 = B's root
    mask = _node_neq(hh[:1], hl[:1], hh[1:], hl[1:])
    for lhh, lhl in reversed(levels):
        half = lhh.shape[0] // 2
        mask = jnp.repeat(mask, 2) & _node_neq(
            lhh[:half], lhl[:half], lhh[half:], lhl[half:]
        )
    return mask, (hh[:1], hl[:1]), (hh[1:], hl[1:])


diff_root_guided = _jit_site("ops.merkle.diff_root_guided", diff_root_guided)


@jax.jit
def update_leaves(levels_hh, levels_hl, idx, new_hh, new_hl):
    """Incrementally apply K leaf updates to a built tree.

    The replication data plane's steady state is "a small change batch
    lands on a big snapshot": rebuilding a 2**20-leaf tree for a K-leaf
    batch wastes N/K of the work.  This op scatters the new leaf digests
    and recomputes only the K root-paths — K compressions per level,
    log2(N) levels, all fixed shapes (duplicate parents among the K
    paths are recomputed redundantly and scattered to the same value, so
    no host-side dedup or dynamic shapes are needed).

    ``levels_hh/hl``: tuples from :func:`build_tree` (leaves first, root
    last); ``idx``: (K,) int32 leaf positions; ``new_hh/hl``: (K, 4)
    replacement digests.  Returns new level tuples.  Cost: O(K log N)
    vs O(N) rebuild — at K=1024, N=2**20 that is ~50x less hashing.
    """
    idx = jnp.asarray(idx, dtype=jnp.int32)
    new_levels_hh = [levels_hh[0].at[idx].set(new_hh)]
    new_levels_hl = [levels_hl[0].at[idx].set(new_hl)]
    for lvl in range(1, len(levels_hh)):
        child_hh = new_levels_hh[-1]
        child_hl = new_levels_hl[-1]
        pidx = idx >> 1
        left = pidx * 2
        p_hh, p_hl = merkle_parent(
            child_hh[left], child_hl[left],
            child_hh[left + 1], child_hl[left + 1],
        )
        new_levels_hh.append(levels_hh[lvl].at[pidx].set(p_hh))
        new_levels_hl.append(levels_hl[lvl].at[pidx].set(p_hl))
        idx = pidx
    return tuple(new_levels_hh), tuple(new_levels_hl)


update_leaves = _jit_site("ops.merkle.update_leaves", update_leaves)


@jax.jit
def diff_root_guided_packed(a_leaf_hh, a_leaf_hl, b_leaf_hh, b_leaf_hl):
    """:func:`diff_root_guided` with the leaf mask packed 32 bools/word.

    The D2H transfer is the tail of the diff's critical path (1 bit per
    leaf instead of numpy's byte-per-bool — 8x less wire volume, which
    on a tunneled device link is the difference between the transfer
    hiding under compute and dominating it).  Expand on the host with
    :func:`unpack_mask`.
    """
    mask, root_a, root_b = diff_root_guided(
        a_leaf_hh, a_leaf_hl, b_leaf_hh, b_leaf_hl
    )
    n = mask.shape[0]
    if n % 32:
        mask = jnp.pad(mask, (0, 32 - n % 32))
    bits = jnp.sum(
        mask.reshape(-1, 32).astype(U32) << jnp.arange(32, dtype=U32)[None, :],
        axis=1,
    )
    return bits, root_a, root_b


diff_root_guided_packed = _jit_site(
    "ops.merkle.diff_root_guided_packed", diff_root_guided_packed
)


# ---------------------------------------------------------------------------
# host edge
# ---------------------------------------------------------------------------


def unpack_mask(bits, n: int) -> np.ndarray:
    """Expand a packed device mask (uint32 words, LSB-first) to (n,) bools.

    The single host-side decode for every packed-mask producer
    (:func:`diff_root_guided_packed`, the reconcile sketch diff, the CDC
    occupancy transfer): one place owns the bit order.
    """
    dense = np.unpackbits(
        np.asarray(bits, dtype=np.uint32).view(np.uint8), bitorder="little"
    )
    return dense[:n]


def digests_to_device(digests: list[bytes]):
    """Pack 32-byte digests into (N, 4) hi/lo uint32 device arrays.

    Inverse of :func:`digests_to_words` / the first 4 word pairs of
    :func:`..ops.blake2b.digests_to_bytes`'s layout (little-endian u64
    words as (hi, lo) u32 pairs).
    """
    raw = np.frombuffer(b"".join(digests), dtype="<u4").reshape(-1, 8)
    return jnp.asarray(raw[:, 1::2].copy()), jnp.asarray(raw[:, 0::2].copy())


def digest_matrix(hh, hl) -> np.ndarray:
    """(N, 4) hi/lo word pairs -> (N, 32) uint8 digest bytes — the ONE
    owner of the little-endian lo/hi word interleave (word k's low half
    at byte 8k, high half at 8k+4)."""
    hh = np.asarray(hh, dtype=np.uint32)
    hl = np.asarray(hl, dtype=np.uint32)
    out = np.empty((hh.shape[0], 8), dtype="<u4")
    out[:, 0::2] = hl
    out[:, 1::2] = hh
    return out.view(np.uint8).reshape(hh.shape[0], 32)


def digests_from_device(hh, hl) -> list[bytes]:
    """(N, 4) hi/lo word pairs -> list of 32-byte digests."""
    raw = digest_matrix(hh, hl)
    return [raw[i].tobytes() for i in range(raw.shape[0])]


def root_host(digests: np.ndarray) -> bytes:
    """Merkle root of (N, 32) uint8 leaf digests on the HOST engine.

    Byte-identical to ``digests_from_device(*root(*pad_leaves(...)))``
    (same zero-digest padding, same pair convention — tested), but the
    level fold runs through the native thread-parallel BLAKE2b engine
    instead of an XLA program: on a CPU-backed jax the device fold's
    scanned-rounds compression measured ~0.01 GiB/s end-to-end, turning
    the single-pass :func:`..runtime.content.content_address` host route
    back into a two-order-of-magnitude cliff.  "Batch or stay home"
    applies to the tree fold too.
    """
    from ..runtime import native

    n = len(digests)
    if n == 0:
        return b"\0" * DIGEST_SIZE
    p = 1
    while p < n:
        p <<= 1
    level = np.zeros((p, DIGEST_SIZE), dtype=np.uint8)
    level[:n] = digests
    while len(level) > 1:
        pairs = np.ascontiguousarray(level).reshape(-1)
        half = len(level) // 2
        offs = np.arange(half, dtype=np.int64) * (2 * DIGEST_SIZE)
        lens = np.full(half, 2 * DIGEST_SIZE, dtype=np.int64)
        out = native.hash_many(pairs, offs, lens)
        if out is None:  # no native library: hashlib loop
            out = np.empty((half, DIGEST_SIZE), dtype=np.uint8)
            for i in range(half):
                out[i] = np.frombuffer(
                    host_parent(level[2 * i].tobytes(),
                                level[2 * i + 1].tobytes()),
                    dtype=np.uint8,
                )
        level = out
    return level[0].tobytes()


def pad_leaves(hh, hl):
    """Zero-pad the leaf axis up to the next power of two.

    Zero digests act as the empty-subtree sentinel; both snapshots of a
    diff must be padded to the same width (the bench and the parallel
    layer always compare equal-width snapshots).
    """
    n = hh.shape[0]
    p = 1
    while p < n:
        p <<= 1
    if p == n:
        return hh, hl
    pad = ((0, p - n), (0, 0))
    return jnp.pad(hh, pad), jnp.pad(hl, pad)


def diff_leaves(a_digests: list[bytes], b_digests: list[bytes]) -> list[int]:
    """Host-friendly wrapper: digests in, differing leaf indices out."""
    if len(a_digests) != len(b_digests):
        raise ValueError("snapshots must have equal leaf counts; pad first")
    if not a_digests:
        return []
    a_hh, a_hl = pad_leaves(*digests_to_device(a_digests))
    b_hh, b_hl = pad_leaves(*digests_to_device(b_digests))
    mask, _, _ = diff_root_guided(a_hh, a_hl, b_hh, b_hl)
    return np.nonzero(np.asarray(mask)[: len(a_digests)])[0].tolist()


def diff_snapshots(a_hh, a_hl, b_hh, b_hl) -> np.ndarray:
    """Differing leaf indices between two LOCAL equal-width snapshots,
    routed by backend ("batch or stay home", DESIGN.md §2 rule 0):

    * accelerator-backed jax — the tree-guided packed diff
      (:func:`diff_root_guided_packed`): compare work stays in HBM and
      one bit per leaf crosses D2H;
    * CPU-backed jax — one vectorized elementwise compare: when both
      snapshots already sit in host memory the tree build buys nothing
      locally (the O(diff · log n) walk is the *device* and *remote*
      story — :mod:`..runtime.tree_sync` for the wire).

    ``DAT_DEVICE_MERKLE=1/0`` overrides.  Both paths return identical
    indices (tested).
    """
    from ..utils.routing import prefer_host

    n = a_hh.shape[0]
    if b_hh.shape[0] != n:
        raise ValueError("snapshots must have equal (padded) leaf counts")
    if n & (n - 1):
        # enforce the device branch's precondition on BOTH paths: code
        # developed against the host compare must not start crashing the
        # moment it runs on an accelerator
        raise ValueError(f"leaf count {n} is not a power of two; pad first")
    if prefer_host("DAT_DEVICE_MERKLE"):
        a1, a2 = np.asarray(a_hh), np.asarray(a_hl)
        b1, b2 = np.asarray(b_hh), np.asarray(b_hl)
        dense = ((a1 != b1) | (a2 != b2)).any(axis=1)
        return np.nonzero(dense)[0]
    bits, _, _ = diff_root_guided_packed(a_hh, a_hl, b_hh, b_hl)
    return np.nonzero(unpack_mask(bits, n))[0]


def prove(levels_hh, levels_hl, idx: int) -> list[bytes]:
    """Inclusion proof for leaf ``idx``: the sibling digest per level.

    ``levels_hh/hl``: the tuples from :func:`build_tree`.  The path has
    log2(N) 32-byte siblings, bottom-up; verification needs only the
    root (:func:`verify_proof`) — the content-addressed audit primitive
    a replica uses to check a single record against a snapshot root
    without holding the snapshot (the reference leaves all verification
    to dat core above the wire; here it rides the device-built tree).
    Only the log2(N) sibling rows cross D2H.
    """
    n = levels_hh[0].shape[0]
    if not 0 <= idx < n:
        raise IndexError(f"leaf {idx} out of range [0, {n})")
    nlev = len(levels_hh) - 1
    if nlev == 0:
        return []
    # gather all log2(N) sibling rows on device, one D2H transfer (per-
    # level fetches would pay one round trip each — latency-dominant on
    # a tunneled link)
    sib_hh = jnp.concatenate(
        [levels_hh[lvl][((idx >> lvl) ^ 1)][None] for lvl in range(nlev)]
    )
    sib_hl = jnp.concatenate(
        [levels_hl[lvl][((idx >> lvl) ^ 1)][None] for lvl in range(nlev)]
    )
    return digests_from_device(sib_hh, sib_hl)


def verify_proof(root: bytes, leaf: bytes, idx: int,
                 path: list[bytes], nleaves: int) -> bool:
    """Check an inclusion proof against a 32-byte root (host, hashlib).

    ``nleaves`` is the tree width the verifier expects (it knows the
    snapshot's size alongside its root) and is load-bearing, not
    advisory: without it, (a) an attacker-chosen shorter path would
    bind against the *subtree* an interior node roots — any interior
    digest would "verify" as a leaf (second-preimage aliasing; the
    depth check pins len(path) to the padded tree height) — and (b)
    indices would alias mod 2**len(path), verifying forged claims at
    positions outside the snapshot.
    """
    if nleaves <= 0 or not 0 <= idx < nleaves:
        return False
    depth = max(0, (int(nleaves) - 1)).bit_length()  # padded tree height
    if len(path) != depth:
        return False
    node = leaf
    for lvl, sib in enumerate(path):
        bit = (idx >> lvl) & 1
        node = host_parent(sib, node) if bit else host_parent(node, sib)
    return node == root


# ---------------------------------------------------------------------------
# host reference (for tests)
# ---------------------------------------------------------------------------


def host_parent(left: bytes, right: bytes) -> bytes:
    return hashlib.blake2b(left + right, digest_size=DIGEST_SIZE).digest()


def host_tree(leaves: list[bytes]) -> list[list[bytes]]:
    levels = [list(leaves)]
    while len(levels[-1]) > 1:
        prev = levels[-1]
        levels.append(
            [host_parent(prev[i], prev[i + 1]) for i in range(0, len(prev), 2)]
        )
    return levels


def host_diff(a: list[bytes], b: list[bytes]) -> list[int]:
    """Recursive descend-on-difference reference diff."""
    out: list[int] = []

    def walk(ta, tb, lvl, idx):
        if ta[lvl][idx] == tb[lvl][idx]:
            return
        if lvl == 0:
            out.append(idx)
            return
        walk(ta, tb, lvl - 1, 2 * idx)
        walk(ta, tb, lvl - 1, 2 * idx + 1)

    ta, tb = host_tree(a), host_tree(b)
    walk(ta, tb, len(ta) - 1, 0)
    return out
