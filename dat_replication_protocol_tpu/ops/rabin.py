"""Content-defined chunking: gear rolling hash over tiled streams.

The reference streams blobs in O(chunk) memory but never content-chunks
them (chunking lives above the wire protocol in dat core; reference:
README.md:73 "blobs are streamed, never buffered").  The TPU framework
adds content-defined chunking as a device kernel per BASELINE.json
config 4 ("Rabin rolling-hash content-defined chunking over 10 GiB
blob").

Algorithm (designed for SPMD, not translated from anything):

* **Gear-style rolling hash** ``h_{i} = (h_{i-1} << 1) + g(b_i)`` over a
  64-bit state carried as (hi, lo) uint32 lane pairs.  A byte's
  contribution is shifted out after 64 positions, so the hash at any
  position depends only on the trailing 64-byte window — which makes the
  stream *tileable*: tiles recompute a 64-byte overlap instead of
  serializing (SURVEY.md §7 hard part (b)).
* The stream is defined to be **seeded with WINDOW zero bytes**: position
  0's hash state is the state after processing 64 zero bytes.  This makes
  every tile identical in shape — each one carries a 64-byte prefix (the
  preceding stream bytes, or the zero seed at the stream head) — so tile
  construction is a uniform vectorized layout op with no first-tile
  special case.
* ``g(b) = ((b+1) * C1, (b+1) * C2)`` — a table-free multiplicative
  scramble (two 32-bit odd constants), chosen over the classic 256-entry
  gear table because TPU vector lanes have no cheap gather; two u32
  multiplies replace a table lookup.
* A position is a **candidate boundary** when the top hash word masked by
  ``(1 << avg_bits) - 1`` is zero → average chunk size 2**avg_bits.
* The kernel scans byte groups (outer `lax.scan`, inner unrolled; the
  Pallas variant in :mod:`.rabin_pallas` for TPU) over all tiles in
  parallel and emits **packed bitmasks** (1 bit per byte).  Candidate
  *positions* are then extracted **on device** with a two-level sparse
  pass (nonzero packed words -> nonzero bits), so the host transfer is
  O(candidates) — ~4 bytes per ~2**avg_bits input bytes — instead of the
  dense 1-bit-per-byte mask.  This matters doubly on tunneled device
  links where D2H bandwidth is orders of magnitude below HBM.
* Min/max chunk-size constraints are applied by a greedy pass over the
  sparse candidates (sequential by nature): the native C loop in
  ``native/dat_native.cpp`` when available, else the Python fallback.

Memory discipline: tiles stream through the device; a 10 GiB blob is
processed in bounded slabs (`chunk_stream`), never resident at once —
the device-scale analogue of the reference's O(chunk) streaming.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.trace import span
from .u64 import U32
from ..obs.device import jit_site as _jit_site
from ..obs.device import note_engine as _note_engine
from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter

# fused1p extractions refused by the on-chip cross-check (each one
# recomputes on the bitmask route; OBSERVABILITY.md single-pass catalog)
_M_FUSED_REFUSED = _counter("cdc.fused.crosscheck.refused")

WINDOW = 64  # bytes: contributions shift out of the 64-bit state after this
# golden-ratio odd constants — datlint's wire-constant-parity rule
# cross-checks these against both native scan loops (a fork silently
# forks the cut sequence between routes)
_GEAR_C1 = 0x9E3779B1
_GEAR_C2 = 0x85EBCA77
_C1 = np.uint32(_GEAR_C1)
_C2 = np.uint32(_GEAR_C2)

PACK = 32  # bit positions per packed uint32 output word
GROUP = 256  # bytes per outer scan step: large enough that per-step scan
# overhead (xs slicing, carry threading — ~30us/step through XLA) is
# amortized against the ~12 ops/byte of hash work

# Per-tile prefix bytes: one whole GROUP.  Only the last WINDOW bytes of
# it are real context (the hash forgets everything older); padding the
# prefix to a full GROUP makes every tile's valid byte range start on a
# group boundary, so the first-hit-per-group kernel output maps to
# aligned absolute windows with no cross-group straddling.
_PREFIX = GROUP
_PREFIX_WORDS = _PREFIX // 4


def _gear_step(hh, hl, byte_u32):
    """One rolling-hash update on (T,) lanes; returns new (hh, hl)."""
    v = byte_u32 + U32(1)
    gl = v * _C1
    gh = v * _C2
    # h = (h << 1) + g  (64-bit via lane pairs)
    sh = (hh << U32(1)) | (hl >> U32(31))
    sl = hl << U32(1)
    lo = sl + gl
    carry = (lo < sl).astype(U32)
    hi = sh + gh + carry
    return hi, lo


@functools.partial(jax.jit, static_argnames=("avg_bits",))
def gear_candidates_tiled(words, avg_bits: int = 13):
    """Candidate-boundary bitmask for tiled byte streams.

    ``words``: (T, S/4) uint32 — T tiles of S bytes, little-endian packed
    (byte j of a tile is ``(words[t, j//4] >> (8*(j%4))) & 0xFF``).  The
    hash state is seeded from zero at each tile start; the caller
    arranges tiles so each one carries its preceding ``WINDOW`` stream
    bytes (or the zero seed) as a prefix, and drops the prefix bits.

    Returns ``bits``: (T, S/PACK) uint32 — bit ``j%32`` of word ``j//32``
    set iff position j is a candidate (hash top word & mask == 0).
    """
    T, nwords = words.shape
    if (nwords * 4) % GROUP:
        raise ValueError(f"tile bytes must be a multiple of {GROUP}")
    mask = U32((1 << avg_bits) - 1)

    groups = words.reshape(T, (nwords * 4) // GROUP, GROUP // 4)
    groups = jnp.transpose(groups, (1, 0, 2))  # (ngroups, T, GROUP/4)

    def group_step(carry, grp):
        hh, hl = carry
        packed = []
        acc = jnp.zeros((T,), dtype=U32)
        bit = 0
        for w in range(GROUP // 4):
            word = grp[:, w]
            for s in range(4):
                byte = (word >> U32(8 * s)) & U32(0xFF)
                hh, hl = _gear_step(hh, hl, byte)
                hit = (hh & mask) == U32(0)
                acc = acc | (hit.astype(U32) << U32(bit))
                bit += 1
                if bit == PACK:
                    packed.append(acc)
                    acc = jnp.zeros((T,), dtype=U32)
                    bit = 0
        return (hh, hl), jnp.stack(packed, axis=1)  # (T, GROUP/PACK)

    h0 = (jnp.zeros((T,), U32), jnp.zeros((T,), U32))
    _, bits = jax.lax.scan(group_step, h0, groups)  # (ngroups, T, GROUP/PACK)
    return jnp.transpose(bits, (1, 0, 2)).reshape(T, -1)


gear_candidates_tiled = _jit_site("ops.rabin.candidates_tiled", gear_candidates_tiled)


NO_HIT = GROUP  # first-hit sentinel: no candidate in this group


@functools.partial(jax.jit, static_argnames=("avg_bits",))
def gear_first_tiled(words, avg_bits: int = 13):
    """First candidate offset per GROUP-byte group (portable XLA path).

    Same scan as :func:`gear_candidates_tiled` but each group emits one
    uint32 — the group-local offset of its *first* candidate, or
    :data:`NO_HIT` — instead of GROUP/PACK packed mask words.  This is
    the thinned-extraction kernel: 1/8 the output volume of the bitmask
    and a GROUP-granular head start on window thinning, at the cost of
    only seeing one candidate per group (callers thin at windows >= one
    GROUP, where that is exactly the information they keep anyway).

    Returns (T, S/GROUP) uint32.
    """
    T, nwords = words.shape
    if (nwords * 4) % GROUP:
        raise ValueError(f"tile bytes must be a multiple of {GROUP}")
    mask = U32((1 << avg_bits) - 1)

    groups = words.reshape(T, (nwords * 4) // GROUP, GROUP // 4)
    groups = jnp.transpose(groups, (1, 0, 2))  # (ngroups, T, GROUP/4)
    sent = U32(NO_HIT)

    def group_step(carry, grp):
        hh, hl = carry
        first = jnp.full((T,), sent, U32)
        pos = 0
        for w in range(GROUP // 4):
            word = grp[:, w]
            for s in range(4):
                byte = (word >> U32(8 * s)) & U32(0xFF)
                hh, hl = _gear_step(hh, hl, byte)
                hit = (hh & mask) == U32(0)
                first = jnp.where(hit & (first == sent), U32(pos), first)
                pos += 1
        return (hh, hl), first  # (T,)

    h0 = (jnp.zeros((T,), U32), jnp.zeros((T,), U32))
    _, firsts = jax.lax.scan(group_step, h0, groups)  # (ngroups, T)
    return jnp.transpose(firsts, (1, 0))


gear_first_tiled = _jit_site("ops.rabin.first_tiled", gear_first_tiled)


# ---------------------------------------------------------------------------
# device-resident candidate extraction
# ---------------------------------------------------------------------------


def _first_bit_per_window(wins):
    """First set-bit offset per window row of packed uint32 words, or
    ``1 << 30`` for empty windows — the ONE owner of the windowed
    first-candidate reduction (the thinning fast path and the exact
    extractor's small-window mode both ride it)."""
    wnz = wins != U32(0)
    first_w = jnp.argmax(wnz, axis=1).astype(jnp.int32)
    wval = jnp.take_along_axis(wins, first_w[:, None], axis=1)[:, 0]
    lsb = wval & (U32(0) - wval)
    bitpos = _popcount32(lsb - U32(1)).astype(jnp.int32)
    return jnp.where(jnp.any(wnz, axis=1), first_w * PACK + bitpos, 1 << 30)


def _build_rows(words_padded, pre_row, T: int, stride: int):
    """[context GROUP | payload] rows, (T, _PREFIX_WORDS + stride/4).

    Row t covers stream bytes [t*stride - _PREFIX, (t+1)*stride): one
    whole warm-up GROUP (its last WINDOW bytes are the real preceding
    context — earlier bytes are don't-cares the hash forgets; the stream
    head gets the zero seed) followed by the payload.  The valid byte
    range of every row is [_PREFIX, _PREFIX + stride) — absolute stream
    position ``t*stride + j - _PREFIX`` — which starts on a GROUP
    boundary, so group-granular kernel outputs map onto aligned absolute
    windows.  Pure layout ops on device: no flat prefixed copy of the
    whole buffer is materialized.
    """
    sw = stride // 4
    payload = words_padded.reshape(T, sw)
    ctx = jnp.concatenate(
        [pre_row[None, :], payload[:-1, -_PREFIX_WORDS:]], axis=0
    )
    return jnp.concatenate([ctx, payload], axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("T", "stride", "avg_bits", "cap2", "use_pallas",
                     "thin_bits", "route"),
)
def _extract_first_occ(words_padded, pre_row, T: int, stride: int,
                       avg_bits: int, cap2: int, use_pallas: bool,
                       thin_bits: int = 11, route: str = "bitmask"):
    """Thinned candidate extraction: occupancy bitmap + in-window offsets.

    **Candidate thinning**: at most the *first* candidate in each aligned
    ``2**thin_bits``-byte window survives.  Chunking callers pass
    ``thin_bits = log2(min_size)``: two candidates closer than min_size
    can never both become cuts, so thinning only shifts the occasional
    cut to an equivalent in-window neighbor.  Deterministic for a given
    stream; documented policy, not an approximation knob.

    Three equivalent kernel routes (``route``; all produce identical
    candidate sets — tested):

    * ``"bitmask"`` (default) — the BITMASK kernel + a vectorized
      first-set-bit reduction per window.  The first-hit kernel's
      per-byte ``where`` chain lengthens the gear loop's serial
      dependency (the scan's actual binder), while the bitmask kernel's
      ``or``-accumulate does not — the reduction over packed words is
      ~1 op per 32 bytes, off the critical path.  8x the kernel OUTPUT
      volume, but that output never leaves the device.
    * ``"first"`` — the first-hit-per-GROUP kernel + a min over groups
      (1/8 the kernel output volume; kept for measurement comparison —
      DAT_CDC_FIRST_KERNEL=1 / DAT_CDC_ROUTE=first).
    * ``"fused"`` — the window-first reduction fused INTO the gear
      kernel (per-packed-word tracking in registers, one u32 flushed
      per window): no 1-bit/byte mask ever lands in HBM and no second
      reduction dispatch runs.  Pallas-only; falls back to "bitmask"
      off-TPU.  DAT_CDC_ROUTE=fused.

    The host result rides in two dense-free pieces —

    * ``occ``: (ceil(nwin/32),) uint32 — bit w set iff window w holds a
      candidate (fixed 1 bit per window: 64 KiB/GiB at 2 KiB windows);
    * ``offs``: (cap2,) uint16 — the in-window byte offset of each
      occupied window's candidate, compacted in window order —

    so the transfer is O(windows)/8 + O(candidates)*2 bytes with **no
    device->host count round-trip**: the host derives the candidate
    count (and the cap2-overflow check) from popcounting ``occ``.
    """
    rows = _build_rows(words_padded, pre_row, T, stride)
    if route in ("fused", "fused1p") and not use_pallas:
        route = "bitmask"  # the fused kernels have no XLA formulation
    viol = None
    if route == "fused1p":
        # single-pass route: the window-first kernel with the on-chip
        # occupancy cross-check; ``viol`` rides out so candidates_begin
        # can REFUSE divergent cuts and recompute on the bitmask route
        from .fused_cdc_hash_pallas import gear_window_first_checked

        first, viol = gear_window_first_checked(rows, avg_bits, thin_bits)
    elif route == "fused":
        from .rabin_pallas import gear_window_first_pallas

        first = gear_window_first_pallas(rows, avg_bits, thin_bits)
    elif route == "first":
        if use_pallas:
            from .rabin_pallas import gear_first_pallas

            firsts = gear_first_pallas(rows, avg_bits)
        else:
            firsts = gear_first_tiled(rows, avg_bits)
        vg = firsts[:, 1:]  # drop warm-up group 0; (T, stride/GROUP)
        flatg = vg.reshape(-1).astype(jnp.int32)
        gpw = (1 << thin_bits) // GROUP  # groups per window
        wins = flatg.reshape(-1, gpw)
        gidx = jnp.arange(gpw, dtype=jnp.int32) * GROUP
        hitpos = jnp.where(wins < NO_HIT, wins + gidx[None, :], 1 << 30)
        first = jnp.min(hitpos, axis=1)  # in-window first-candidate offset
    else:
        if use_pallas:
            from .rabin_pallas import gear_candidates_pallas

            bits = gear_candidates_pallas(rows, avg_bits)
        else:
            bits = gear_candidates_tiled(rows, avg_bits)
        vw = bits[:, _PREFIX // PACK : _PREFIX // PACK + stride // PACK]
        wpw = (1 << thin_bits) // PACK  # packed words per window
        first = _first_bit_per_window(vw.reshape(-1, wpw))
    nwin = first.shape[0]
    has = first < (1 << 30)
    hasp = has
    if nwin % 32:
        hasp = jnp.pad(has, (0, 32 - nwin % 32))
    occ = jnp.sum(
        hasp.reshape(-1, 32).astype(U32)
        << jnp.arange(32, dtype=U32)[None, :],
        axis=1,
    )
    (widx,) = jnp.nonzero(has, size=cap2, fill_value=0)
    offs = first[widx].astype(jnp.uint16)
    if viol is not None:  # fused1p: the cross-check flag rides along
        return occ, offs, viol
    return occ, offs


_extract_first_occ = _jit_site("ops.rabin.extract_first_occ", _extract_first_occ)


@functools.partial(
    jax.jit,
    static_argnames=("T", "stride", "avg_bits", "cap", "cap2", "use_pallas",
                     "thin_bits"),
)
def _extract_candidates(words_padded, pre_row, T: int, stride: int,
                        avg_bits: int, cap: int, cap2: int,
                        use_pallas: bool, thin_bits: int | None = None):
    """Tile + scan + sparse-extract, all on device (see :func:`_build_rows`
    for the layout).

    Sparse extraction keeps the D2H volume O(candidates) — ~4 bytes per
    2**avg_bits input bytes instead of the dense 1-bit-per-byte mask.

    Two modes:

    * ``thin_bits=None`` — exact: every candidate position, via two-level
      nonzero (words, then bits).  The full-width ``jnp.nonzero`` lowers
      to a scatter over the whole word mask (~0.3 s/GiB measured on
      v5e-1), so this mode is for correctness tests and modest inputs.
      (The fast path for chunking is :func:`_extract_first_occ`.)
    * ``thin_bits=k`` (< 8) — small-window thinning over the packed
      bitmask: argmax per window + a small nonzero.

    Returns ``(positions, ncand, nover)``: ``positions`` (cap2,) int32
    absolute byte positions (first ``ncand`` entries valid, ascending);
    ``nover`` > cap means overflow — retry with a larger cap.
    """
    rows = _build_rows(words_padded, pre_row, T, stride)

    if use_pallas:
        from .rabin_pallas import gear_candidates_pallas

        bits = gear_candidates_pallas(rows, avg_bits)
    else:
        bits = gear_candidates_tiled(rows, avg_bits)

    # valid packed words: everything after the warm-up prefix's bit-words
    # [0, _PREFIX/PACK)
    vw = bits[:, _PREFIX // PACK : _PREFIX // PACK + stride // PACK]
    flat = vw.reshape(-1)

    if thin_bits is not None:
        W = 1 << thin_bits  # window bytes; PACK-aligned power of two
        wins = flat.reshape(-1, W // PACK)  # (nwin, wpw)
        inwin = _first_bit_per_window(wins)
        has = inwin < (1 << 30)
        nwin = wins.shape[0]
        pos = jnp.arange(nwin, dtype=jnp.int32) * W + inwin
        ncand = jnp.sum(has.astype(jnp.int32))
        (widx,) = jnp.nonzero(has, size=cap2, fill_value=0)
        return pos[widx], ncand, ncand

    nz = flat != U32(0)
    nword = jnp.sum(nz.astype(jnp.int32))
    (widx,) = jnp.nonzero(nz, size=cap, fill_value=0)
    wvals = flat[widx]
    # level 2: expand selected words into absolute byte positions
    wpt = stride // PACK  # valid words per tile
    t = widx // wpt
    w = widx % wpt
    base = (t * stride + w * PACK).astype(jnp.int32)
    live = (jnp.arange(cap) < nword)[:, None]
    bitsel = ((wvals[:, None] >> jnp.arange(PACK, dtype=U32)[None, :])
              & U32(1)).astype(bool) & live
    pos = base[:, None] + jnp.arange(PACK, dtype=jnp.int32)[None, :]
    ncand = jnp.sum(bitsel.astype(jnp.int32))
    (pidx,) = jnp.nonzero(bitsel.reshape(-1), size=cap2, fill_value=0)
    positions = pos.reshape(-1)[pidx]
    return positions, ncand, nword


_extract_candidates = _jit_site("ops.rabin.extract_candidates", _extract_candidates)


def _popcount32(x):
    """Bit population count on uint32 lanes (SWAR, 12 elementwise ops)."""
    x = x - ((x >> U32(1)) & U32(0x55555555))
    x = (x & U32(0x33333333)) + ((x >> U32(2)) & U32(0x33333333))
    x = (x + (x >> U32(4))) & U32(0x0F0F0F0F)
    return (x * U32(0x01010101)) >> U32(24)


def _clamp_thin_bits(thin_bits: int | None, stride: int) -> int | None:
    """One owner of the thinning-policy clamps: the host scan and the
    device tiles must produce IDENTICAL candidate sets, so both routes
    apply exactly these rules.  None = no thinning.

    * windows below 32 bytes can't cover a packed word: no thinning;
    * the window must divide the tile (stride's largest power-of-two
      divisor) and fit the u16 in-window offset range (<= 16).
    """
    if thin_bits is None or thin_bits < 5:
        return None
    tz = (stride & -stride).bit_length() - 1
    thin_bits = min(thin_bits, tz, 16)
    return thin_bits if thin_bits >= 5 else None


def pallas_active() -> bool:
    """The ONE owner of the "do Pallas kernels run here" decision —
    candidates_begin's route dispatch, effective_route's fused->bitmask
    aliasing, and the bench's calibration/label all consult this, so
    they can never disagree about which kernel actually executes."""
    return jax.default_backend() == "tpu"


def effective_route(use_pallas: bool | None = None) -> str:
    """The ONE owner of extraction-route resolution: consult
    ``DAT_CDC_ROUTE`` (values ``bitmask``/``first``/``fused``/
    ``fused1p``), fall back to the legacy ``DAT_CDC_FIRST_KERNEL`` knob,
    and alias ``fused``/``fused1p`` to ``bitmask`` off-Pallas (neither
    fused kernel has an XLA formulation; fused1p's HOST engine is routed
    separately by :func:`..runtime.content.content_digests`, which
    consults the raw env value).  Both the dispatch path and the bench
    artifact label use this, so the recorded route is always the route
    that actually ran.  ``use_pallas=None`` consults
    :func:`pallas_active`."""
    import os

    route = os.environ.get("DAT_CDC_ROUTE")
    if route not in ("bitmask", "first", "fused", "fused1p"):
        route = ("first" if os.environ.get("DAT_CDC_FIRST_KERNEL") == "1"
                 else "bitmask")
    if use_pallas is None:
        use_pallas = pallas_active()
    if route in ("fused", "fused1p") and not use_pallas:
        route = "bitmask"
    return route


def _start_d2h(arrays) -> None:
    """Start D2H transfers for the extraction outputs now, concurrently:
    by collect() time they are local (or in flight under the next slab's
    compute).  Serializing them inside collect cost two full link
    round-trips per slab (~66 ms each on the dev tunnel, measured round
    4) on the fast path's critical path."""
    for arr in arrays:
        copy_async = getattr(arr, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()


def candidates_begin(words, nbytes: int, avg_bits: int = 13,
                     tile_bytes: int = 1 << 17,
                     prefix: np.ndarray | None = None,
                     thin_bits: int | None = None):
    """Candidate positions for a (device- or host-resident) word buffer.

    ``words``: flat uint32 array (jax or numpy), little-endian packed
    stream bytes; ``nbytes``: true stream length (trailing bytes of the
    last word beyond it must be zero).  ``prefix``: the WINDOW bytes
    preceding this buffer in the stream as 16 uint32 words (None = the
    zero seed, i.e. this buffer is the stream head).  ``thin_bits``: keep
    at most the first candidate per aligned ``2**thin_bits``-byte window
    (see :func:`_extract_candidates`; chunkers pass log2(min_size)).
    Returns sorted absolute candidate positions (int64, < nbytes) on the
    host.

    This is the device-resident fast path: when ``words`` already lives
    in HBM, the only host traffic is the O(candidates) position list.

    Returns a zero-arg ``collect()`` closure: the device scan is
    dispatched asynchronously here, and ``collect()`` blocks on the
    result transfer — so a caller streaming multiple slabs can overlap
    slab N's D2H with slab N+1's compute (:func:`chunk_stream` and the
    bench both do; the transfer is ~40%% of a slab's wall time on a
    tunneled device link, all of it hidden by depth-2 pipelining).
    """
    if nbytes == 0:
        return lambda: np.empty((0,), dtype=np.int64)
    if nbytes > 1 << 31:
        raise ValueError("per-call limit is 2 GiB; slab your stream")
    if tile_bytes % GROUP:
        raise ValueError(f"tile_bytes must be a multiple of {GROUP}")
    stride = tile_bytes
    T = -(-nbytes // stride)
    sw = stride // 4
    words = jnp.asarray(words).reshape(-1)
    if words.shape[0] != -(-nbytes // 4):
        raise ValueError(
            f"word buffer holds {words.shape[0] * 4} bytes; nbytes={nbytes} "
            f"needs exactly {-(-nbytes // 4)} words (zero-pad the tail)"
        )
    # prefix is the WINDOW real context bytes; the GROUP-wide row prefix
    # is zero-filled in front of them (don't-care bytes, see _build_rows)
    pre = jnp.zeros((_PREFIX_WORDS,), U32)
    if prefix is not None:
        ctx = jnp.asarray(prefix, dtype=U32).reshape(-1)
        if ctx.shape[0] != WINDOW // 4:
            raise ValueError(f"prefix must be {WINDOW} bytes")
        pre = pre.at[-(WINDOW // 4):].set(ctx)
    pad = T * sw - words.shape[0]
    if pad > 0:
        words = jnp.concatenate([words, jnp.zeros((pad,), U32)])

    thin_bits = _clamp_thin_bits(thin_bits, stride)

    use_pallas = pallas_active()
    # expected candidates ~= nbytes / 2**avg_bits (sparse).  4x margin,
    # then grow geometrically on the (rare) overflow.
    cap0 = max(256, (T * stride) >> max(avg_bits - 2, 0))
    if thin_bits is not None:
        cap0 = min(cap0, (T * stride) >> thin_bits)

    if thin_bits is not None and thin_bits >= 8:
        # fast path: windowed first-candidate extraction + occ/offsets
        # transfer (kernel route per _extract_first_occ; the env knobs
        # are for on-device measurement comparison / bench calibration)
        route = effective_route(use_pallas)
        with span("cdc.dispatch"):
            first = _extract_first_occ(
                words, pre, T, stride, avg_bits, cap0, use_pallas,
                thin_bits, route=route,
            )
            _start_d2h(first)

        def checked(ext, rt, cap):
            """Refuse a fused1p extraction whose on-chip cross-check
            tripped (the two independent in-kernel reductions disagree)
            and recompute AT THE SAME CAP on the bitmask route — EVERY
            extraction consults this, the cap-growth retries included
            (each retry is a different compiled program instance, so a
            clean first pass proves nothing about them)."""
            if len(ext) == 3 and int(ext[2]) != 0:
                if _OBS.on:
                    _M_FUSED_REFUSED.inc()
                rt = "bitmask"
                ext = _extract_first_occ(
                    words, pre, T, stride, avg_bits, cap, use_pallas,
                    thin_bits, route=rt,
                )
            return ext, rt

        def collect() -> np.ndarray:
            with span("cdc.collect"):
                from .merkle import unpack_mask

                ext, rt = checked(first, route, cap0)
                occ, offs = ext[0], ext[1]
                winidx = np.nonzero(
                    unpack_mask(occ, T * stride >> thin_bits)
                )[0]
                cap = cap0
                while len(winidx) > cap:
                    cap *= 4
                    ext, rt = checked(_extract_first_occ(
                        words, pre, T, stride, avg_bits, cap, use_pallas,
                        thin_bits, route=rt,
                    ), rt, cap)
                    offs = ext[1]
                offs_np = np.asarray(offs)
                out = (winidx << thin_bits) + offs_np[: len(winidx)].astype(
                    np.int64
                )
                return out[out < nbytes]

        return collect

    with span("cdc.dispatch"):
        first = _extract_candidates(
            words, pre, T, stride, avg_bits, cap0, cap0, use_pallas,
            thin_bits,
        )
        _start_d2h(first)

    def collect() -> np.ndarray:
        with span("cdc.collect"):
            positions, ncand, nover = first
            cap = cap0
            while int(nover) > cap or int(ncand) > cap:
                cap *= 4
                positions, ncand, nover = _extract_candidates(
                    words, pre, T, stride, avg_bits, cap, cap, use_pallas,
                    thin_bits,
                )
            out = np.asarray(positions[: int(ncand)], dtype=np.int64)
            return out[out < nbytes]

    return collect


def candidates_words(words, nbytes: int, avg_bits: int = 13,
                     tile_bytes: int = 1 << 17,
                     prefix: np.ndarray | None = None,
                     thin_bits: int | None = None) -> np.ndarray:
    """Synchronous :func:`candidates_begin`: positions, sorted, < nbytes."""
    return candidates_begin(
        words, nbytes, avg_bits, tile_bytes, prefix, thin_bits
    )()


# ---------------------------------------------------------------------------
# host edge
# ---------------------------------------------------------------------------


def _greedy_select_py(candidates: np.ndarray, length: int, min_size: int,
                      max_size: int) -> list[int]:
    """Pure-Python min/max pass (fallback when the native lib is absent)."""
    out: list[int] = []
    start = 0
    i = 0
    n = len(candidates)
    while length - start > max_size:
        lo = start + min_size
        hi = start + max_size
        while i < n and candidates[i] < lo:
            i += 1
        if i < n and candidates[i] <= hi:
            cut = int(candidates[i])
            i += 1
        else:
            cut = hi
        out.append(cut)
        start = cut
    out.append(length)
    return out


def _greedy_select(candidates: np.ndarray, length: int, min_size: int,
                   max_size: int) -> list[int]:
    """Sequential min/max pass over sorted candidate byte offsets.

    Returns chunk end-offsets (exclusive), always ending with ``length``.
    A cut is taken at the first candidate >= min_size after the previous
    cut; if none lands before max_size, a forced cut at max_size.

    The pass is inherently sequential (each cut shifts the min/max
    horizon), so it runs as a native C loop
    (``native/dat_native.cpp:dat_greedy_select``) — at ~10ns/cut it is
    invisible next to the device scan; the Python loop fallback costs
    ~1us/cut, which at 1M cuts would dominate the whole pipeline.
    """
    from ..runtime import native

    lib = native.get_lib()
    if lib is None:
        return _greedy_select_py(candidates, length, min_size, max_size)
    with span("cdc.greedy"):
        cands = np.ascontiguousarray(candidates, dtype=np.int64)
        cap = length // max(min_size, 1) + 2
        out = np.empty(cap, dtype=np.int64)
        n = lib.dat_greedy_select(
            cands, len(cands), length, min_size, max_size, out, cap
        )
    if n < 0:  # capacity can't trip given the bound above; be safe anyway
        return _greedy_select_py(candidates, length, min_size, max_size)
    return out[:n].tolist()


def host_candidates(data: bytes, avg_bits: int = 13) -> list[int]:
    """Pure-Python reference for the device candidate kernel (tests).

    Implements the seeded-stream definition: the hash state at position 0
    is the state after processing WINDOW zero bytes.
    """
    mask = (1 << avg_bits) - 1
    h = 0
    g0 = (1 * int(_C1) & 0xFFFFFFFF) | ((1 * int(_C2) & 0xFFFFFFFF) << 32)
    for _ in range(WINDOW):
        h = ((h << 1) + g0) & 0xFFFFFFFFFFFFFFFF
    out = []
    for j, b in enumerate(data):
        g = ((b + 1) * int(_C1) & 0xFFFFFFFF) | (
            ((b + 1) * int(_C2) & 0xFFFFFFFF) << 32
        )
        h = ((h << 1) + g) & 0xFFFFFFFFFFFFFFFF
        if (h >> 32) & mask == 0:
            out.append(j)
    return out


def chunk_stream(
    data,
    avg_bits: int = 13,
    min_size: int | None = None,
    max_size: int | None = None,
    tile_bytes: int = 1 << 17,
    slab_tiles: int = 8192,
) -> list[int]:
    """Content-defined chunk end-offsets for a byte stream.

    ``data``: bytes or uint8 numpy array.  Processes ``slab_tiles`` tiles
    of ``tile_bytes`` per device dispatch (bounded memory regardless of
    blob size).  The library default slab is 1 GiB: with depth-2
    pipelining TWO slabs are in flight, each holding the input words
    plus the ``_build_rows`` copy (and the bitmask route's mask), so
    HBM high-water is roughly 4x the slab size — 1 GiB slabs fit any
    current backend.  Callers on a >= 16 GiB-HBM device (the bench's
    10 GiB config) should pass ``slab_tiles=16384`` (2 GiB): round-4
    phase attribution measured ~63 ms fixed per-dispatch cost against
    ~5 ms/GiB marginal, so fewer, larger slabs win until memory does.
    Host-resident data pays one H2D transfer per slab; for data
    already on device use :func:`candidates_words` +
    :func:`_greedy_select` directly (the bench's 10 GiB config does).
    """
    if min_size is None:
        min_size = 1 << (avg_bits - 2)
    if max_size is None:
        max_size = 1 << (avg_bits + 2)
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)
    ) else np.asarray(data, dtype=np.uint8)
    length = len(buf)
    if length == 0:
        return []

    thin_bits = max(min_size, 1).bit_length() - 1  # floor log2: W <= min_size

    # "batch or stay home": on a CPU-only jax the XLA-scan formulation
    # of the gear loop is catastrophically slow (~0.0002 GiB/s e2e
    # measured), while the native C table-driven scan does ~1.2 GiB/s
    # per core — same seeded-stream definition, identical candidates
    # (tested).  DAT_DEVICE_CDC=1/0 overrides.
    from ..utils.routing import prefer_host

    if prefer_host("DAT_DEVICE_CDC"):
        from ..runtime import native

        # the SAME thinning clamps as the device tiles (one owner:
        # _clamp_thin_bits) so host and device produce identical
        # candidate sets and therefore identical cuts for any tile_bytes
        clamped = _clamp_thin_bits(thin_bits, tile_bytes)
        cands = native.gear_candidates(
            buf, avg_bits, -1 if clamped is None else clamped
        )
        if cands is not None:
            if _OBS.on:
                _note_engine("cdc.chunk", "native-host", bytes=length)
            return _greedy_select(cands, length, min_size, max_size)

    if _OBS.on:
        _note_engine("cdc.chunk", effective_route(), bytes=length)
    candidates = _device_candidates(
        buf, avg_bits, tile_bytes, slab_tiles, thin_bits
    )
    return _greedy_select(candidates, length, min_size, max_size)


def host_thin(candidates, thin_bits: int) -> list[int]:
    """First-candidate-per-aligned-window thinning (host reference)."""
    out: list[int] = []
    last_win = -1
    for p in candidates:
        win = p >> thin_bits
        if win != last_win:
            out.append(int(p))
            last_win = win
    return out


def _device_candidates(buf: np.ndarray, avg_bits: int, tile_bytes: int,
                       slab_tiles: int,
                       thin_bits: int | None = None) -> np.ndarray:
    """All candidate positions (sorted, absolute) via tiled device scans.

    One vectorized host copy per slab (into a zero-padded word-aligned
    staging array) and one H2D transfer; candidate positions come back
    via the sparse on-device extraction, so there is no dense-bitmask
    readback and no per-tile host loop (both killed the round-2 number:
    VERDICT.md round 2, "What's weak" #1).
    """
    length = len(buf)
    slab_bytes = tile_bytes * slab_tiles
    out: list[np.ndarray] = []
    pending: list[tuple] = []  # depth-2: overlap slab N's D2H with N+1's scan

    def drain() -> None:
        collect, base = pending.pop(0)
        out.append(collect() + base)

    for begin in range(0, length, slab_bytes):
        end = min(begin + slab_bytes, length)
        nb = end - begin
        staged = np.zeros(-(-nb // 4), dtype="<u4")
        staged.view(np.uint8)[:nb] = buf[begin:end]
        if begin == 0:
            prefix = None
        else:
            pre = np.zeros(WINDOW, dtype=np.uint8)
            pre[:] = buf[begin - WINDOW : begin]
            prefix = pre.view("<u4")
        pending.append((
            candidates_begin(staged, nb, avg_bits, tile_bytes, prefix,
                             thin_bits),
            begin,
        ))
        if len(pending) >= 2:
            drain()
    while pending:
        drain()
    if not out:
        return np.empty((0,), dtype=np.int64)
    return np.concatenate(out)
