"""Content-defined chunking: gear rolling hash over tiled streams.

The reference streams blobs in O(chunk) memory but never content-chunks
them (chunking lives above the wire protocol in dat core; reference:
README.md:73 "blobs are streamed, never buffered").  The TPU framework
adds content-defined chunking as a device kernel per BASELINE.json
config 4 ("Rabin rolling-hash content-defined chunking over 10 GiB
blob").

Algorithm (designed for SPMD, not translated from anything):

* **Gear-style rolling hash** ``h_{i} = (h_{i-1} << 1) + g(b_i)`` over a
  64-bit state carried as (hi, lo) uint32 lane pairs.  A byte's
  contribution is shifted out after 64 positions, so the hash at any
  position depends only on the trailing 64-byte window — which makes the
  stream *tileable*: tiles recompute a 64-byte overlap instead of
  serializing (SURVEY.md §7 hard part (b)).
* ``g(b) = ((b+1) * C1, (b+1) * C2)`` — a table-free multiplicative
  scramble (two 32-bit odd constants), chosen over the classic 256-entry
  gear table because TPU vector lanes have no cheap gather; two u32
  multiplies replace a table lookup.
* A position is a **candidate boundary** when the top hash word masked by
  ``(1 << avg_bits) - 1`` is zero → average chunk size 2**avg_bits.
* The kernel scans byte groups (outer `lax.scan`, inner unrolled; the
  Pallas variant in :mod:`.rabin_pallas` for TPU) over all tiles in
  parallel and emits **packed bitmasks** (1 bit per byte, 1/8 the input
  volume); candidate positions are recovered on the host with
  ``np.unpackbits`` + ``nonzero`` over the sparse mask.
* Min/max chunk-size constraints are applied by a greedy host pass over
  the candidates (sequential by nature, but over ~1/2**avg_bits of the
  data).  `max_size` inserts forced cuts when no candidate lands in
  range.

Memory discipline: tiles stream through the device; a 10 GiB blob is
processed in bounded slabs (`chunk_stream`), never resident at once —
the device-scale analogue of the reference's O(chunk) streaming.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .u64 import U32

WINDOW = 64  # bytes: contributions shift out of the 64-bit state after this
_C1 = np.uint32(0x9E3779B1)  # golden-ratio odd constants
_C2 = np.uint32(0x85EBCA77)

PACK = 32  # bytes per packed output word
GROUP = 256  # bytes per outer scan step: large enough that per-step scan
# overhead (xs slicing, carry threading — ~30us/step through XLA) is
# amortized against the ~12 ops/byte of hash work


def _gear_step(hh, hl, byte_u32):
    """One rolling-hash update on (T,) lanes; returns new (hh, hl)."""
    v = byte_u32 + U32(1)
    gl = v * _C1
    gh = v * _C2
    # h = (h << 1) + g  (64-bit via lane pairs)
    sh = (hh << U32(1)) | (hl >> U32(31))
    sl = hl << U32(1)
    lo = sl + gl
    carry = (lo < sl).astype(U32)
    hi = sh + gh + carry
    return hi, lo


@functools.partial(jax.jit, static_argnames=("avg_bits",))
def gear_candidates_tiled(words, avg_bits: int = 13):
    """Candidate-boundary bitmask for tiled byte streams.

    ``words``: (T, S/4) uint32 — T tiles of S bytes, little-endian packed
    (byte j of a tile is ``(words[t, j//4] >> (8*(j%4))) & 0xFF``).  The
    caller arranges tiles so each one carries the previous tile's last
    ``WINDOW`` bytes as a prefix (overlap); bits for those positions are
    reported like any other and must be dropped by the host wrapper.

    Returns ``bits``: (T, S/PACK) uint32 — bit ``j%32`` of word ``j//32``
    set iff position j is a candidate (hash top word & mask == 0, hash
    state seeded from zero at tile start).
    """
    T, nwords = words.shape
    if (nwords * 4) % GROUP:
        raise ValueError(f"tile bytes must be a multiple of {GROUP}")
    mask = U32((1 << avg_bits) - 1)

    groups = words.reshape(T, (nwords * 4) // GROUP, GROUP // 4)
    groups = jnp.transpose(groups, (1, 0, 2))  # (ngroups, T, GROUP/4)

    def group_step(carry, grp):
        hh, hl = carry
        packed = []
        acc = jnp.zeros((T,), dtype=U32)
        bit = 0
        for w in range(GROUP // 4):
            word = grp[:, w]
            for s in range(4):
                byte = (word >> U32(8 * s)) & U32(0xFF)
                hh, hl = _gear_step(hh, hl, byte)
                hit = (hh & mask) == U32(0)
                acc = acc | (hit.astype(U32) << U32(bit))
                bit += 1
                if bit == PACK:
                    packed.append(acc)
                    acc = jnp.zeros((T,), dtype=U32)
                    bit = 0
        return (hh, hl), jnp.stack(packed, axis=1)  # (T, GROUP/PACK)

    h0 = (jnp.zeros((T,), U32), jnp.zeros((T,), U32))
    _, bits = jax.lax.scan(group_step, h0, groups)  # (ngroups, T, GROUP/PACK)
    return jnp.transpose(bits, (1, 0, 2)).reshape(T, -1)


# ---------------------------------------------------------------------------
# host edge
# ---------------------------------------------------------------------------


def _greedy_select(candidates: np.ndarray, length: int, min_size: int,
                   max_size: int) -> list[int]:
    """Sequential min/max pass over sorted candidate byte offsets.

    Returns chunk end-offsets (exclusive), always ending with ``length``.
    A cut is taken at the first candidate >= min_size after the previous
    cut; if none lands before max_size, a forced cut at max_size.
    """
    out: list[int] = []
    start = 0
    i = 0
    n = len(candidates)
    while length - start > max_size:
        # skip candidates before the min-size horizon
        lo = start + min_size
        hi = start + max_size
        while i < n and candidates[i] < lo:
            i += 1
        if i < n and candidates[i] <= hi:
            cut = int(candidates[i])
            i += 1
        else:
            cut = hi
        out.append(cut)
        start = cut
    out.append(length)
    return out


def host_candidates(data: bytes, avg_bits: int = 13) -> list[int]:
    """Pure-Python reference for the device candidate kernel (tests)."""
    mask = (1 << avg_bits) - 1
    h = 0
    out = []
    for j, b in enumerate(data):
        g = ((b + 1) * int(_C1) & 0xFFFFFFFF) | (
            ((b + 1) * int(_C2) & 0xFFFFFFFF) << 32
        )
        h = ((h << 1) + g) & 0xFFFFFFFFFFFFFFFF
        if (h >> 32) & mask == 0:
            out.append(j)
    return out


def chunk_stream(
    data,
    avg_bits: int = 13,
    min_size: int | None = None,
    max_size: int | None = None,
    tile_bytes: int = 1 << 17,
    slab_tiles: int = 8192,
) -> list[int]:
    """Content-defined chunk end-offsets for a byte stream.

    ``data``: bytes or uint8 numpy array.  Processes ``slab_tiles`` tiles
    of ``tile_bytes`` per device dispatch (bounded memory regardless of
    blob size).  Tiles overlap by ``WINDOW`` bytes so every position sees
    its full 64-byte context except the first WINDOW bytes of the stream,
    matching :func:`host_candidates` exactly.
    """
    if min_size is None:
        min_size = 1 << (avg_bits - 2)
    if max_size is None:
        max_size = 1 << (avg_bits + 2)
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)
    ) else np.asarray(data, dtype=np.uint8)
    length = len(buf)
    if length == 0:
        return []

    candidates = _device_candidates(buf, avg_bits, tile_bytes, slab_tiles)
    return _greedy_select(candidates, length, min_size, max_size)


def _device_candidates(buf: np.ndarray, avg_bits: int, tile_bytes: int,
                       slab_tiles: int) -> np.ndarray:
    """All candidate positions (sorted, absolute) via tiled device scans.

    The device returns the packed bitmask (1/8 of the input volume); bit
    positions are recovered on the host with ``np.unpackbits`` — the
    candidate set is sparse, the bitmask transfer is the only volume.
    On TPU backends the Pallas kernel does the scan; elsewhere the
    portable XLA path (:func:`gear_candidates_tiled`).
    """
    length = len(buf)
    stride = tile_bytes  # payload bytes per tile (excluding overlap)
    ntiles = -(-length // stride)
    use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from .rabin_pallas import gear_candidates_pallas
    out: list[np.ndarray] = []
    for slab_start in range(0, ntiles, slab_tiles):
        rows = []
        bases = []
        for t in range(slab_start, min(slab_start + slab_tiles, ntiles)):
            begin = t * stride
            lead = WINDOW if begin >= WINDOW else begin
            seg = buf[begin - lead : begin + stride]
            # [warm-up prefix | payload] at row start, zero pad at the
            # TAIL only: the hash is causal, so tail zeros are harmless,
            # while a zero *prefix* would corrupt the warm-up of the
            # stream's first tile (host seeds h=0 with no prefix at all)
            width = -(-(WINDOW + stride) // GROUP) * GROUP
            row = np.zeros(width, dtype=np.uint8)
            row[: len(seg)] = seg
            rows.append(row)
            bases.append((begin, lead, min(stride, length - begin)))
        block = np.stack(rows)  # (rows, width) u8
        words = jnp.asarray(block.view("<u4"))
        if use_pallas:
            bits = gear_candidates_pallas(words, avg_bits)
        else:
            bits = gear_candidates_tiled(words, avg_bits)
        bits_np = np.ascontiguousarray(np.asarray(bits))
        for r, (begin, lead, valid) in enumerate(bases):
            dense = np.nonzero(
                np.unpackbits(bits_np[r].view(np.uint8), bitorder="little")
            )[0]
            # positions are tile-local: [0, lead) is the warm-up prefix
            # (already reported by the previous tile), then the payload
            local = dense - lead
            keep = (local >= 0) & (local < valid)
            out.append((local[keep] + begin).astype(np.int64))
    if not out:
        return np.empty((0,), dtype=np.int64)
    return np.concatenate(out)
