"""Rateless coded-symbol set reconciliation (ISSUE 10, ROADMAP item 2).

The sketch protocol (:mod:`.reconcile`) exchanges an O(nslots) table —
wire cost scales with the *dataset*; the tree-guided refinement
(:mod:`..runtime.tree_sync`) costs O(diff · log n) bytes in log n round
trips.  This module implements the rateless-IBLT idea ("Practical
Rateless Set Reconciliation", PAPERS.md): **coded symbols** whose
communication cost is O(k) for a k-record symmetric difference, with no
prior estimate of k.

* An **element** is a 32-byte record digest (the same BLAKE2b output the
  sketch sums into cells).  Identity is the digest value itself, so the
  mapping below is recomputable from a *recovered* element alone —
  nothing out-of-band.
* Element x participates in an infinite pseudorandom sequence of coded-
  symbol indices: index 0 always, then gaps drawn so the marginal
  participation probability at index i decays as ``1/(1 + i/2)`` (the
  paper's density).  Given participation at i and a uniform draw
  ``u = (r+1)/2**32``, the next index is
  ``i + ceil((i + 1.5) * (2**16/sqrt(r+1) - 1))`` — the inverse-CDF of
  the renewal process (see :class:`IndexCursor`).  The per-element draw
  stream is splitmix64 seeded by the digest's first 8 bytes (LE) — the
  same first-word convention :func:`.reconcile.sketch_table` keys its
  slots by.
* A **coded symbol** is 11 little-endian u32 words:
  ``[count | checksum lo | checksum hi | sum[0..8)]`` — word-wise
  wrapping-u32 sums of the participating elements' rows (count 1,
  64-bit checksum of the digest, the 8 digest words).  Word-wise
  arithmetic (no cross-word carries) is what makes the build a plain
  u32 scatter-add on any backend, byte-identical everywhere.
* **Reconciliation**: A streams its coded-symbol prefix; B subtracts
  its own symbols for the same indices.  The difference describes
  exactly the symmetric difference: a cell with count ±1 whose checksum
  matches its sum is **pure** — the sum IS an element held only by A
  (+1) or only by B (−1).  Peeling subtracts recovered elements from
  their other cells, exposing new pure cells, until every cell is zero
  (decode complete) or no pure cell remains (more symbols needed).
  ~1.35·k symbols suffice for large k (paper, Fig. 6); a false-pure
  cell needs a 64-bit checksum collision.

Engines: the scatter-add build runs as a batched JAX op
(:func:`build_symbols_device` — gather + scatter-add over digest
columns, the device route for feeds whose digests are already columns)
or as the numpy reference (:func:`build_symbols_host`); both produce
byte-identical cells (tested).  Index generation is host-side numpy in
both routes — one owner of the float math, so engine choice can never
fork the mapping.  Peeling is host work (:class:`PeelDecoder`):
vectorized numpy rounds, with the sequential tail riding the same round
loop as it shrinks.

Elements are a SET: callers dedupe digests first (a duplicated record
adds 2 to its cells and can never peel); :func:`dedupe_digests` is the
shared helper.
"""

from __future__ import annotations

import threading

import numpy as np

from ..obs.metrics import OBS as _OBS, counter as _counter
from ..utils.trace import span

DIGEST_BYTES = 32
DIGEST_WORDS = 8
SYMBOL_WORDS = 11  # count + 2 checksum words + 8 sum words
SYMBOL_BYTES = SYMBOL_WORDS * 4

# Weighted (variable-size element) cells — the "Rateless Bloom Filters"
# extension (PAPERS.md) the snapshot bootstrap reconciles CDC chunk
# sets with (ISSUE 12): an element is a (digest, byte length) pair and
# the cell grows one wrapping-u32 LENGTH word, so a recovered element
# carries its size — the joiner learns exactly how many bytes each
# missing chunk is, and the participation density below can be
# recomputed from the recovered value alone (nothing out-of-band, the
# same recoverability invariant as the unweighted construction).
WSYMBOL_WORDS = 12  # count + 2 checksum words + 8 sum words + length
WSYMBOL_BYTES = WSYMBOL_WORDS * 4

# telemetry (OBSERVABILITY.md "reconcile.*"): symbols built (cells
# produced into a local prefix) and elements recovered by peeling
_M_SYMBOLS = _counter("reconcile.symbols")
_M_PEELED = _counter("reconcile.peeled")

# splitmix64 constants — written down independently in the native
# engine (native/dat_native.cpp dat_rateless_build); a fork is a ROUTE
# fork (two engines mapping elements to different coded symbols), so
# the copies are parity-watched by datlint wire-constant-parity exactly
# like GEAR_C1/GEAR_C2.
RATELESS_GAMMA = 0x9E3779B97F4A7C15
RATELESS_MIX1 = 0xBF58476D1CE4E5B9
RATELESS_MIX2 = 0x94D049BB133111EB

# weighted-participation constants (same parity story — the native
# dat_rateless_build_w twin carries `// wire:` markers): an element's
# weight class is ``min(W_CAP, bit_length(len >> W_SHIFT))`` and its
# index gaps divide by ``class + 1``, so a 1 MiB chunk participates in
# ~9x the cells of a 4 KiB one — heavy chunks decode first, which is
# what makes the WANT set's wire cost track BYTES of divergence, not
# just element count ("Rateless Bloom Filters", PAPERS.md).  A fork
# here maps elements to DIFFERENT cells per engine: the GEAR
# route-fork class.
RATELESS_W_SHIFT = 12
RATELESS_W_CAP = 8

_GAMMA = np.uint64(RATELESS_GAMMA)
_MIX1 = np.uint64(RATELESS_MIX1)
_MIX2 = np.uint64(RATELESS_MIX2)

_BUILD_JIT = None  # lazy: keep jax out of module import


def _mix64(z: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer: the one bit-mixing primitive this module
    uses (PRNG draws and checksums both ride it)."""
    z = z.astype(np.uint64, copy=True)
    z ^= z >> np.uint64(30)
    z *= _MIX1
    z ^= z >> np.uint64(27)
    z *= _MIX2
    z ^= z >> np.uint64(31)
    return z


def _digest_words(digests: np.ndarray) -> np.ndarray:
    """(n, 32) u8 digests -> (n, 8) u32 LE words (zero-copy view)."""
    d = np.ascontiguousarray(digests, dtype=np.uint8)
    if d.ndim != 2 or d.shape[1] != DIGEST_BYTES:
        raise ValueError(f"digests must be (n, {DIGEST_BYTES}) bytes")
    return d.view("<u4")


def checksum_words(sum_words: np.ndarray) -> np.ndarray:
    """64-bit checksum of each digest row, as (n, 2) u32 words.

    Computed from the 8 sum words alone, so a peel candidate's checksum
    is recomputable from the recovered value.  Four u64 lanes chained
    through :func:`_mix64` — NOT the identity on the seed word, so a
    corrupted cell whose sum and checksum were perturbed together still
    fails the pure test (the fault-injection arm's flip class).
    """
    w = np.ascontiguousarray(sum_words, dtype=np.uint32)
    lanes = w.view("<u8")  # (n, 4) u64: adjacent word pairs
    acc = _mix64(lanes[:, 0] + _GAMMA)
    for k in range(1, 4):
        acc = _mix64(acc ^ lanes[:, k])
    out = np.empty((len(w), 2), dtype=np.uint32)
    out[:, 0] = (acc & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out[:, 1] = (acc >> np.uint64(32)).astype(np.uint32)
    return out


def element_rows(digests: np.ndarray) -> np.ndarray:
    """(n, 32) u8 digests -> (n, 11) u32 symbol rows (count=1)."""
    words = _digest_words(digests)
    rows = np.empty((len(words), SYMBOL_WORDS), dtype=np.uint32)
    rows[:, 0] = 1
    rows[:, 1:3] = checksum_words(words)
    rows[:, 3:] = words
    return rows


def dedupe_digests(digests: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Unique digest rows (first-occurrence order) + their source rows.

    Coded symbols reconcile SETS: a digest present twice on one side
    adds 2 to every cell it touches and can never peel.  Returns
    ``(unique (m,32) u8, first_index (m,) int64)``.

    Sorts by the digests' first u64 word (the cheap discriminant — a
    full 32-byte lexicographic unique costs ~20x at feed scale) and
    resolves only the colliding runs against the full rows, so a
    first-word collision between DISTINCT digests is handled exactly,
    never silently merged.
    """
    d = np.ascontiguousarray(digests, dtype=np.uint8)
    n = len(d)
    if n == 0:
        return d.reshape(0, DIGEST_BYTES), np.empty(0, np.int64)
    k0 = d.view("<u8")[:, 0]
    order = np.argsort(k0, kind="stable").astype(np.int64)
    sk = k0[order]
    bounds = np.nonzero(np.concatenate(([True], sk[1:] != sk[:-1])))[0]
    if len(bounds) == n:  # every first word unique: nothing to resolve
        return d, np.arange(n, dtype=np.int64)
    keep = np.ones(n, dtype=bool)
    bounds = np.append(bounds, n)
    for ri in np.nonzero(np.diff(bounds) > 1)[0]:
        run = order[bounds[ri]:bounds[ri + 1]]  # ascending (stable sort)
        seen: dict[bytes, int] = {}
        for i in run:
            b = d[i].tobytes()
            if b in seen:
                keep[i] = False
            else:
                seen[b] = i
    first = np.nonzero(keep)[0].astype(np.int64)
    return d[first], first


class IndexCursor:
    """Vectorized per-element cursor along the coded-symbol index line.

    Every element's first participation is index 0 (the paper's
    construction: coded symbol 0 sums the whole set).  :meth:`advance`
    yields all (element, index) participations below a bound and leaves
    each element's cursor at its first index >= the bound, so repeated
    calls with growing bounds enumerate each participation exactly once
    — the incremental shape both the builder (extend the prefix) and
    the peeler (recompute a recovered element's cells) need.
    """

    def __init__(self, digests: np.ndarray):
        words = _digest_words(digests)
        self._state = words.view("<u8")[:, 0].astype(np.uint64, copy=True)
        self._next = np.zeros(len(words), dtype=np.uint64)

    def advance(self, bound: int) -> tuple[np.ndarray, np.ndarray]:
        """All pending participations with index < ``bound``:
        ``(element_rows, symbol_indices)`` as int64 arrays."""
        out_e: list[np.ndarray] = []
        out_i: list[np.ndarray] = []
        b = np.uint64(bound)
        active = np.nonzero(self._next < b)[0]
        while active.size:
            idx = self._next[active]
            out_e.append(active.astype(np.int64))
            out_i.append(idx.astype(np.int64))
            # splitmix64 step per active element; the draw's top 32 bits
            # are the uniform r of the gap formula
            st = self._state[active] + _GAMMA
            self._state[active] = st
            r = (_mix64(st) >> np.uint64(32)).astype(np.float64)
            cur = idx.astype(np.float64)
            # inverse-CDF gap for marginal density 1/(1 + i/2):
            # P(next > j | at i) = ((i+1.5)/(j+1.5))^2, u = (r+1)/2^32
            gap = np.ceil(
                (cur + 1.5) * (np.float64(1 << 16) / np.sqrt(r + 1.0) - 1.0)
            )
            self._next[active] = idx + np.maximum(gap, 1.0).astype(np.uint64)
            active = active[self._next[active] < b]
        if not out_e:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        return np.concatenate(out_e), np.concatenate(out_i)


def build_symbols_host(rows: np.ndarray, elems: np.ndarray,
                       idxs: np.ndarray, m: int,
                       base: int = 0) -> np.ndarray:
    """Pure-numpy reference build: scatter-add ``rows[elems]`` into an
    ``(m - base, 11)`` u32 cell block at ``idxs - base``."""
    cells = np.zeros((m - base, SYMBOL_WORDS), dtype=np.uint32)
    np.add.at(cells, idxs - base, rows[elems])
    return cells


def build_symbols_device(rows: np.ndarray, elems: np.ndarray,
                         idxs: np.ndarray, m: int,
                         base: int = 0) -> np.ndarray:
    """The JAX build: one jitted gather + scatter-add over the digest
    columns (u32 adds — byte-identical to the host reference; the
    device story is the same scatter-add shape as
    :func:`.reconcile.sketch_table`).  Update count is bucketed to the
    next power of two (padding aimed at a dump row past the block) so
    batch-size drift cannot recompile per call."""
    import jax

    global _BUILD_JIT
    if _BUILD_JIT is None:
        from ..obs.device import jit_site as _jit_site

        def _build(rows, elems, idxs, nsym: int):
            import jax.numpy as jnp

            # one dump row past the block swallows the padding updates;
            # clip keeps every index in-range regardless of backend OOB
            # semantics.  Cell width comes from the rows themselves
            # (static at trace time), so the SAME program serves both
            # the 11-word unweighted and 12-word weighted layouts.
            table = jnp.zeros((nsym + 1, rows.shape[1]), dtype=jnp.uint32)
            idxs = jnp.minimum(idxs, nsym)
            return table.at[idxs].add(rows[elems])[:nsym]

        _BUILD_JIT = _jit_site("ops.rateless.build",
                               jax.jit(_build, static_argnums=(3,)))
    width = rows.shape[1] if getattr(rows, "ndim", 0) == 2 else SYMBOL_WORDS
    if len(elems) == 0 or len(rows) == 0:
        # nothing to scatter (an empty set, or a fully-covered cursor):
        # the gather below must never index a 0-row array
        return np.zeros((m - base, width), dtype=np.uint32)
    k = len(elems)
    cap = max(16, 1 << (k - 1).bit_length()) if k else 16
    pe = np.zeros(cap, dtype=np.int32)
    pi = np.full(cap, m - base, dtype=np.int32)  # -> the dump row
    pe[:k] = elems
    pi[:k] = idxs - base
    out = _BUILD_JIT(rows, pe, pi, m - base)
    return np.asarray(out)


class CodedSymbols:
    """One replica's incrementally-extended coded-symbol prefix.

    ``extend(m)`` grows the prefix to ``m`` cells, paying only the NEW
    participations (the cursor is incremental), and returns the whole
    ``(m, 11)`` u32 prefix.  Engines (the :class:`.reconcile.LogSummary`
    doctrine — every engine byte-identical, tested):

    * ``'host'`` — the native C one-pass walk+scatter
      (``dat_rateless_build``): digests are host-born bytes and the
      cell block is tiny, so mapping where the bytes live is the
      data-plane route; falls back to the numpy reference without the
      toolchain.
    * ``'numpy'`` — the pure-numpy reference build (the parity oracle).
    * ``'device'`` — the jitted JAX gather + scatter-add over digest
      columns, for pipelines whose digests are already device columns
      (``_when_tpu_returns.sh`` leg 7 captures this at 1M+1M).
    * ``'auto'`` (default) — ``'host'`` when the native library is
      available, else ``'numpy'``.

    The index mapping is ONE implementation per engine pair: numpy and
    device share :class:`IndexCursor`; the native engine advances the
    SAME cursor arrays in place, so engines can even alternate
    mid-stream without forking the sequence.
    """

    def __init__(self, digests: np.ndarray, engine: str = "auto"):
        if engine not in ("auto", "host", "numpy", "device"):
            raise ValueError(f"unknown engine {engine!r}")
        self.digests = np.ascontiguousarray(digests, dtype=np.uint8)
        self.n = len(self.digests)
        self._rows = None  # numpy/device routes build lazily
        self._cursor = IndexCursor(self.digests)
        self._cells = np.zeros((0, SYMBOL_WORDS), dtype=np.uint32)
        self._engine = engine

    @property
    def rows(self) -> np.ndarray:
        if self._rows is None:
            self._rows = element_rows(self.digests)
        return self._rows

    def _extend_block(self, have: int, m: int) -> np.ndarray:
        if self._engine in ("auto", "host"):
            from ..runtime import native

            block = native.rateless_build(
                self.digests, self._cursor._state, self._cursor._next,
                m, have)
            if block is not None:
                return block
        if self._engine == "device":
            elems, idxs = self._cursor.advance(m)
            return build_symbols_device(self.rows, elems, idxs, m, have)
        elems, idxs = self._cursor.advance(m)
        return build_symbols_host(self.rows, elems, idxs, m, have)

    def extend(self, m: int) -> np.ndarray:
        have = len(self._cells)
        if m <= have:
            return self._cells[:m]
        with span("reconcile.build"):
            block = self._extend_block(have, m)
        self._cells = np.concatenate([self._cells, block]) \
            if have else block
        if _OBS.on:
            _M_SYMBOLS.inc(m - have)
        return self._cells


def _neg(cells: np.ndarray) -> np.ndarray:
    """Word-wise negation mod 2**32."""
    return (np.uint32(0) - cells).astype(np.uint32)


def _counts_i32(cells: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(cells[:, 0]).view(np.int32)


def peel(work: np.ndarray,
         max_rounds: int = 1 << 20,
         ) -> tuple[np.ndarray, np.ndarray, bool]:
    """Peel a combined (remote − local) cell block IN PLACE.

    Returns ``(digests (k, 32) u8, signs (k,) int8, complete)`` —
    ``sign +1``: element held only by the remote (symbol-sending) side,
    ``−1``: only by the local side.  ``complete`` is True iff every
    cell is zero after peeling: the decoded set IS the symmetric
    difference (a nonzero residue means more symbols are needed).
    Each round is vectorized over all currently-pure cells; the
    sequential tail is just the same loop at small widths.
    """
    m = len(work)
    rec_digests: list[np.ndarray] = []
    rec_signs: list[np.ndarray] = []
    with span("reconcile.peel"):
        for _ in range(max_rounds):
            cnt = _counts_i32(work)
            cand = np.nonzero((cnt == 1) | (cnt == -1))[0]
            if not cand.size:
                break
            signs = np.where(cnt[cand] == 1, 1, -1).astype(np.int8)
            sums = work[cand, 3:]
            css = work[cand, 1:3]
            negm = signs == -1
            if negm.any():
                sums = sums.copy()
                css = css.copy()
                sums[negm] = _neg(sums[negm])
                css[negm] = _neg(css[negm])
            ok = (checksum_words(sums) == css).all(axis=1)
            if not ok.any():
                break
            vals = np.ascontiguousarray(sums[ok], dtype=np.uint32)
            signs = signs[ok]
            digests = vals.view(np.uint8).reshape(-1, DIGEST_BYTES)
            # the same element is often pure in several cells at once
            digests, first = dedupe_digests(digests)
            signs = signs[first]
            rows = element_rows(digests)
            srows = rows.copy()
            if (signs == -1).any():
                srows[signs == -1] = _neg(rows[signs == -1])
            elems, idxs = IndexCursor(digests).advance(m)
            np.subtract.at(work, idxs, srows[elems])
            rec_digests.append(digests)
            rec_signs.append(signs)
    if rec_digests:
        digests = np.concatenate(rec_digests)
        signs = np.concatenate(rec_signs)
    else:
        digests = np.empty((0, DIGEST_BYTES), np.uint8)
        signs = np.empty(0, np.int8)
    complete = not work.any()
    if _OBS.on and len(digests):
        _M_PEELED.inc(len(digests))
    return digests, signs, complete


class PeelDecoder:
    """The receiving half of a rateless reconciliation.

    Accumulates the remote side's coded-symbol runs, maintains the
    matching local prefix, and :meth:`try_decode` attempts a full peel
    of the combined cells.  Decode state is monotone — runs must arrive
    contiguously from index 0 (the wire framing enforces ordering; a
    gap is a caller bug and raises)."""

    def __init__(self, local_digests: np.ndarray, engine: str = "auto",
                 assume_unique: bool = False):
        digests = np.ascontiguousarray(local_digests, dtype=np.uint8)
        if not assume_unique:  # a caller with deduped state skips the sort
            digests, _ = dedupe_digests(digests)
        self.local = CodedSymbols(digests, engine=engine)
        self._remote = np.zeros((0, SYMBOL_WORDS), dtype=np.uint32)
        self.symbols_seen = 0

    def add_symbols(self, start: int, cells: np.ndarray) -> None:
        cells = np.ascontiguousarray(cells, dtype=np.uint32)
        if cells.ndim != 2 or cells.shape[1] != SYMBOL_WORDS:
            raise ValueError("cells must be (k, 11) u32")
        if start != self.symbols_seen:
            raise ValueError(
                f"symbol run starts at {start}, expected {self.symbols_seen}"
            )
        self._remote = np.concatenate([self._remote, cells]) \
            if self.symbols_seen else cells
        self.symbols_seen = len(self._remote)

    def try_decode(self):
        """One decode attempt over everything received.

        ``None`` when more symbols are needed; otherwise
        ``(digests, signs)`` — sign +1: remote-only, −1: local-only."""
        m = self.symbols_seen
        if m == 0:
            return None
        local = self.local.extend(m)
        work = (self._remote - local).astype(np.uint32)
        digests, signs, complete = peel(work)
        if not complete:
            return None
        return digests, signs


# -- weighted (variable-size element) extension ------------------------------
#
# The snapshot bootstrap (ISSUE 12) reconciles CDC chunk SETS, whose
# elements carry a byte length.  The construction below is the
# "Rateless Bloom Filters" variable-size extension of everything above:
# same splitmix64 draw stream, same index line, but (a) the cell grows
# a wrapping-u32 LENGTH word (and the checksum chain covers it), and
# (b) index gaps divide by ``weight_class + 1`` so heavy chunks
# participate more densely and decode earlier.  Both additions preserve
# the recoverability invariant: a pure cell's sum IS (digest, length),
# and the weighted cursor is recomputable from that pair alone.


def weight_classes(lens) -> np.ndarray:
    """Weight class per element: ``min(RATELESS_W_CAP,
    bit_length(len >> RATELESS_W_SHIFT))`` as uint64 — pure integer
    math, bit-identical across engines (the native twin runs the same
    shift loop)."""
    v = np.asarray(lens, dtype=np.uint64) >> np.uint64(RATELESS_W_SHIFT)
    c = np.zeros(len(v), dtype=np.uint64)
    for _ in range(RATELESS_W_CAP):
        nz = v > 0
        if not nz.any():
            break
        c[nz] += np.uint64(1)
        v = v >> np.uint64(1)
    return c


def _as_len_words(lens) -> np.ndarray:
    lens = np.asarray(lens)
    arr = lens.astype(np.int64, copy=False)
    if len(arr) and (arr < 0).any():
        raise ValueError("element lengths must be >= 0")
    if len(arr) and (arr >> 32).any():
        raise ValueError("element lengths must fit in u32")
    return arr.astype(np.uint32)


def weighted_checksum_words(sum_words: np.ndarray,
                            len_words: np.ndarray) -> np.ndarray:
    """64-bit checksum of each (digest, length) row as (n, 2) u32 words:
    the :func:`checksum_words` chain extended by one mix over the
    length word, so a cell whose sum and length were perturbed together
    still fails the pure test."""
    w = np.ascontiguousarray(sum_words, dtype=np.uint32)
    lanes = w.view("<u8")
    acc = _mix64(lanes[:, 0] + _GAMMA)
    for k in range(1, 4):
        acc = _mix64(acc ^ lanes[:, k])
    acc = _mix64(acc ^ np.asarray(len_words, np.uint32).astype(np.uint64))
    out = np.empty((len(w), 2), dtype=np.uint32)
    out[:, 0] = (acc & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out[:, 1] = (acc >> np.uint64(32)).astype(np.uint32)
    return out


def weighted_element_rows(digests: np.ndarray, lens) -> np.ndarray:
    """(n, 32) u8 digests + lengths -> (n, 12) u32 weighted symbol rows
    (count=1)."""
    words = _digest_words(digests)
    lw = _as_len_words(lens)
    if len(lw) != len(words):
        raise ValueError("digests and lens must align")
    rows = np.empty((len(words), WSYMBOL_WORDS), dtype=np.uint32)
    rows[:, 0] = 1
    rows[:, 1:3] = weighted_checksum_words(words, lw)
    rows[:, 3:11] = words
    rows[:, 11] = lw
    return rows


class WeightedIndexCursor:
    """:class:`IndexCursor` for (digest, length) elements: the SAME
    splitmix64 draw stream and gap formula, with the drawn gap divided
    (integer division, then clamped to >= 1) by ``weight_class + 1`` —
    the one owner of the weighted float math, shared by the numpy and
    device routes; the native engine advances the same arrays in
    place."""

    def __init__(self, digests: np.ndarray, lens):
        words = _digest_words(digests)
        lw = _as_len_words(lens)
        if len(lw) != len(words):
            raise ValueError("digests and lens must align")
        self._state = words.view("<u8")[:, 0].astype(np.uint64, copy=True)
        self._next = np.zeros(len(words), dtype=np.uint64)
        self._div = weight_classes(lw) + np.uint64(1)

    def advance(self, bound: int) -> tuple[np.ndarray, np.ndarray]:
        out_e: list[np.ndarray] = []
        out_i: list[np.ndarray] = []
        b = np.uint64(bound)
        active = np.nonzero(self._next < b)[0]
        while active.size:
            idx = self._next[active]
            out_e.append(active.astype(np.int64))
            out_i.append(idx.astype(np.int64))
            st = self._state[active] + _GAMMA
            self._state[active] = st
            r = (_mix64(st) >> np.uint64(32)).astype(np.float64)
            cur = idx.astype(np.float64)
            gap = np.ceil(
                (cur + 1.5) * (np.float64(1 << 16) / np.sqrt(r + 1.0) - 1.0)
            )
            gap_u = np.maximum(gap, 1.0).astype(np.uint64)
            gap_u = np.maximum(gap_u // self._div[active], np.uint64(1))
            self._next[active] = idx + gap_u
            active = active[self._next[active] < b]
        if not out_e:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        return np.concatenate(out_e), np.concatenate(out_i)


class WeightedSymbols:
    """One replica's weighted coded-symbol prefix over a chunk set —
    the :class:`CodedSymbols` shape for (digest, length) elements, same
    three byte-identical engines (native ``dat_rateless_build_w``,
    numpy reference, jitted JAX scatter-add — the device build is the
    SAME cached program, specialized to the 12-word row width)."""

    def __init__(self, digests: np.ndarray, lens, engine: str = "auto"):
        if engine not in ("auto", "host", "numpy", "device"):
            raise ValueError(f"unknown engine {engine!r}")
        self.digests = np.ascontiguousarray(digests, dtype=np.uint8)
        self.lens = np.ascontiguousarray(
            np.asarray(lens, dtype=np.int64))
        self.n = len(self.digests)
        self._rows = None
        self._cursor = WeightedIndexCursor(self.digests, self.lens)
        self._cells = np.zeros((0, WSYMBOL_WORDS), dtype=np.uint32)
        self._engine = engine
        # unlike CodedSymbols (one per reconcile session), a weighted
        # prefix is SHARED per snapshot manifest across concurrent
        # responder sessions — extend() is a read-modify-write of the
        # in-place cursor arrays (the native engine mutates them too),
        # so it must serialize
        self._lock = threading.Lock()

    @property
    def rows(self) -> np.ndarray:
        if self._rows is None:
            self._rows = weighted_element_rows(self.digests, self.lens)
        return self._rows

    def _extend_block(self, have: int, m: int) -> np.ndarray:
        if self._engine in ("auto", "host"):
            from ..runtime import native

            block = native.rateless_build_w(
                self.digests, self.lens, self._cursor._state,
                self._cursor._next, m, have)
            if block is not None:
                return block
        elems, idxs = self._cursor.advance(m)
        if self._engine == "device":
            return build_symbols_device(self.rows, elems, idxs, m, have)
        cells = np.zeros((m - have, WSYMBOL_WORDS), dtype=np.uint32)
        np.add.at(cells, idxs - have, self.rows[elems])
        return cells

    def extend(self, m: int) -> np.ndarray:
        with self._lock:
            have = len(self._cells)
            if m <= have:
                return self._cells[:m]
            with span("reconcile.build"):
                # holding the prefix lock ACROSS the build is the
                # design (see __init__): extension is a read-modify-
                # write of shared cursor arrays, and every concurrent
                # responder needs exactly this block's result — there
                # is nothing useful to do but wait.  Includes the
                # first caller's one-time native-engine build.
                # datlint: allow-blocking-under-lock
                block = self._extend_block(have, m)
            self._cells = np.concatenate([self._cells, block]) \
                if have else block
            if _OBS.on:
                _M_SYMBOLS.inc(m - have)
            return self._cells


def peel_weighted(work: np.ndarray, max_rounds: int = 1 << 20,
                  ) -> tuple[np.ndarray, np.ndarray, np.ndarray, bool]:
    """:func:`peel` for weighted cells, IN PLACE.  Returns
    ``(digests (k, 32) u8, lens (k,) int64, signs (k,) int8, complete)``
    — the recovered elements carry their byte lengths."""
    m = len(work)
    rec_digests: list[np.ndarray] = []
    rec_lens: list[np.ndarray] = []
    rec_signs: list[np.ndarray] = []
    with span("reconcile.peel"):
        for _ in range(max_rounds):
            cnt = _counts_i32(work)
            cand = np.nonzero((cnt == 1) | (cnt == -1))[0]
            if not cand.size:
                break
            signs = np.where(cnt[cand] == 1, 1, -1).astype(np.int8)
            sums = work[cand, 3:11]
            lenw = work[cand, 11]
            css = work[cand, 1:3]
            negm = signs == -1
            if negm.any():
                sums = sums.copy()
                css = css.copy()
                lenw = lenw.copy()
                sums[negm] = _neg(sums[negm])
                css[negm] = _neg(css[negm])
                lenw[negm] = (np.uint32(0) - lenw[negm]).astype(np.uint32)
            ok = (weighted_checksum_words(sums, lenw) == css).all(axis=1)
            if not ok.any():
                break
            vals = np.ascontiguousarray(sums[ok], dtype=np.uint32)
            signs = signs[ok]
            lens = lenw[ok].astype(np.int64)
            digests = vals.view(np.uint8).reshape(-1, DIGEST_BYTES)
            digests, first = dedupe_digests(digests)
            signs = signs[first]
            lens = lens[first]
            rows = weighted_element_rows(digests, lens)
            srows = rows.copy()
            if (signs == -1).any():
                srows[signs == -1] = _neg(rows[signs == -1])
            elems, idxs = WeightedIndexCursor(digests, lens).advance(m)
            np.subtract.at(work, idxs, srows[elems])
            rec_digests.append(digests)
            rec_lens.append(lens)
            rec_signs.append(signs)
    if rec_digests:
        digests = np.concatenate(rec_digests)
        lens = np.concatenate(rec_lens)
        signs = np.concatenate(rec_signs)
    else:
        digests = np.empty((0, DIGEST_BYTES), np.uint8)
        lens = np.empty(0, np.int64)
        signs = np.empty(0, np.int8)
    complete = not work.any()
    if _OBS.on and len(digests):
        _M_PEELED.inc(len(digests))
    return digests, lens, signs, complete


class WeightedPeelDecoder:
    """The receiving half of a weighted (chunk-set) reconciliation —
    :class:`PeelDecoder` over (digest, length) elements."""

    def __init__(self, local_digests: np.ndarray, local_lens,
                 engine: str = "auto", assume_unique: bool = False):
        digests = np.ascontiguousarray(local_digests, dtype=np.uint8)
        lens = np.ascontiguousarray(np.asarray(local_lens, dtype=np.int64))
        if not assume_unique:
            digests, first = dedupe_digests(digests)
            lens = lens[first]
        self.local = WeightedSymbols(digests, lens, engine=engine)
        self._remote = np.zeros((0, WSYMBOL_WORDS), dtype=np.uint32)
        self.symbols_seen = 0

    def add_symbols(self, start: int, cells: np.ndarray) -> None:
        cells = np.ascontiguousarray(cells, dtype=np.uint32)
        if cells.ndim != 2 or cells.shape[1] != WSYMBOL_WORDS:
            raise ValueError(f"cells must be (k, {WSYMBOL_WORDS}) u32")
        if start != self.symbols_seen:
            raise ValueError(
                f"symbol run starts at {start}, expected {self.symbols_seen}"
            )
        self._remote = np.concatenate([self._remote, cells]) \
            if self.symbols_seen else cells
        self.symbols_seen = len(self._remote)

    def try_decode(self):
        """``None`` when more symbols are needed; otherwise
        ``(digests, lens, signs)`` — sign +1: remote-only (the chunks
        this side is missing), −1: local-only."""
        m = self.symbols_seen
        if m == 0:
            return None
        local = self.local.extend(m)
        work = (self._remote - local).astype(np.uint32)
        digests, lens, signs, complete = peel_weighted(work)
        if not complete:
            return None
        return digests, lens, signs
