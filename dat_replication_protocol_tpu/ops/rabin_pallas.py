"""Gear CDC rolling hash as a Pallas TPU kernel.

Same algorithm as :func:`.rabin.gear_candidates_tiled` (the portable
XLA-scan path), restructured like :mod:`.blake2b_pallas`: the 64-bit
rolling-hash state lives in VMEM scratch across a tile's whole byte
range, message words stream HBM -> VMEM via pipelined block fetches, and
the per-group byte loop is straight-line unrolled VPU code — XLA's scan
scheduling leaves the serial gear chain ~30x slower than Mosaic's.

Layouts mirror the BLAKE2b kernel: the tile axis is split ``(8, T/8)``
to fill (8, 128) uint32 vregs, inputs are word-major
``(ngroups, GROUP/4, 8, T/8)``, outputs are packed candidate bitmasks
``(ngroups, GROUP/32, 8, T/8)``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jax_compat import COMPILER_PARAMS as _COMPILER_PARAMS

from .rabin import GROUP, NO_HIT, PACK, _gear_step, _popcount32
from .u64 import U32
from ..obs.device import jit_site as _jit_site

_SUBLANE = 8
_LANE = 128


def _kernel(wref, oref, sth_ref, stl_ref, *, avg_bits: int, ilp: int,
            diag: str = ""):
    """``ilp`` independent lane-chunks are updated per unrolled byte step.

    The gear chain is strictly serial per lane (each byte's state update
    depends on the previous byte's), so a single chain runs at VPU
    *latency*, not throughput.  Interleaving K independent chunks in the
    instruction stream pipelines K chains through the VPU — classic
    software ILP, done manually because Mosaic schedules within, not
    across, whole-array ops.

    ``diag`` (measurement-only; output is WRONG under any non-empty
    value) carves one suspect out of the loop so a device sweep can
    attribute the kernel's ceiling by elimination:
    ``'nomul'`` replaces the two u32 multiplies with adds, ``'nostore'``
    drops the packed-mask stores and their lane concatenates,
    ``'noextract'`` skips the byte shift/mask unpack.
    """
    j = pl.program_id(1)
    mask = U32((1 << avg_bits) - 1)
    btl = sth_ref.shape[-1] // ilp

    @pl.when(j == 0)
    def _init():
        sth_ref[0] = jnp.zeros(sth_ref.shape[1:], U32)
        stl_ref[0] = jnp.zeros(stl_ref.shape[1:], U32)

    def chunk(a, k):
        return a[:, k * btl : (k + 1) * btl]

    def step(hh, hl, byte):
        if diag == "nomul":
            from .rabin import _C1, _C2

            v = byte + U32(1)
            gl = v + U32(int(_C1))
            gh = v + U32(int(_C2))
            sh = (hh << U32(1)) | (hl >> U32(31))
            sl = hl << U32(1)
            lo = sl + gl
            carry = (lo < sl).astype(U32)
            return sh + gh + carry, lo
        return _gear_step(hh, hl, byte)

    hh = [chunk(sth_ref[0], k) for k in range(ilp)]
    hl = [chunk(stl_ref[0], k) for k in range(ilp)]
    acc = [jnp.zeros_like(hh[0]) for _ in range(ilp)]
    bit = 0
    pword = 0
    for w in range(GROUP // 4):
        word = wref[0, w]
        for s in range(4):
            for k in range(ilp):
                if diag == "noextract":
                    byte = chunk(word, k)
                else:
                    byte = (chunk(word, k) >> U32(8 * s)) & U32(0xFF)
                hh[k], hl[k] = step(hh[k], hl[k], byte)
                hit = (hh[k] & mask) == U32(0)
                acc[k] = acc[k] | (hit.astype(U32) << U32(bit))
            bit += 1
            if bit == PACK:
                if diag != "nostore":
                    oref[0, pword] = jnp.concatenate(acc, axis=-1)
                acc = [jnp.zeros_like(hh[0]) for _ in range(ilp)]
                bit = 0
                pword += 1
    if diag == "nostore":  # one write keeps the block defined
        oref[0, 0] = jnp.concatenate(acc, axis=-1)
    sth_ref[0] = jnp.concatenate(hh, axis=-1)
    stl_ref[0] = jnp.concatenate(hl, axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=("avg_bits", "block_tiles", "interpret", "ilp", "diag"),
)
def gear_candidates_native(words, avg_bits: int = 13,
                           block_tiles: int = 8192, interpret: bool = False,
                           ilp: int = 8, diag: str = ""):
    """``words``: (ngroups, GROUP/4, 8, T/8) uint32 -> packed bitmask
    ``(ngroups, GROUP/PACK, 8, T/8)``; bit for byte j of tile t is word
    ``j//PACK`` bit ``j%PACK`` at the tile's (sublane, lane) slot.
    """
    if diag not in ("", "nomul", "nostore", "noextract"):
        # a typo'd diag silently timing the baseline would poison the
        # by-elimination sweep captured in a scarce TPU window
        raise ValueError(f"unknown diag variant {diag!r}")
    ng, gw, s, tl = words.shape
    if gw != GROUP // 4 or s != _SUBLANE:
        raise ValueError(f"expected (ng, {GROUP // 4}, 8, T/8); got {words.shape}")
    if block_tiles % (_SUBLANE * _LANE):
        raise ValueError(f"block_tiles must be a multiple of {_SUBLANE * _LANE}")
    btl = block_tiles // _SUBLANE
    if tl % btl:
        raise ValueError(f"T/8={tl} not a multiple of tile width {btl}")

    if btl % ilp or (btl // ilp) % _LANE:
        raise ValueError(
            f"block_tiles/8={btl} must split into {ilp} lane-multiples"
        )
    grid = (tl // btl, ng)
    kernel = functools.partial(_kernel, avg_bits=avg_bits, ilp=ilp, diag=diag)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, gw, _SUBLANE, btl), lambda i, j: (j, 0, 0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, GROUP // PACK, _SUBLANE, btl), lambda i, j: (j, 0, 0, i)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (ng, GROUP // PACK, _SUBLANE, tl), jnp.uint32
        ),
        scratch_shapes=[
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(words)


_SENT_OFF = 1 << 30  # "window has no candidate" (matches rabin's sentinel)


def _to_native_layout(words, block_tiles: int | None, ilp: int | None):
    """Shared wrapper boilerplate for the three kernel routes: pick
    block_tiles/ilp defaults, pad the tile count, and transpose (T, S/4)
    rows into the word-major (ng, GROUP/4, 8, Tp/8) kernel layout.
    Returns (native, Tp, ng, block_tiles, ilp)."""
    T, nwords = words.shape
    if block_tiles is None:
        block_tiles = 1024
        while block_tiles < min(T, 8192):
            block_tiles <<= 1
    if ilp is None:
        ilp = max(1, block_tiles // 1024)
    S = nwords * 4
    if S % GROUP:
        raise ValueError(f"tile bytes must be a multiple of {GROUP}")
    Tp = -(-T // block_tiles) * block_tiles
    if Tp != T:
        words = jnp.pad(words, ((0, Tp - T), (0, 0)))
    ng = S // GROUP
    # (T, ng, GROUP/4) -> (ng, GROUP/4, T) word-major -> split tile axis
    native = jnp.transpose(
        words.reshape(Tp, ng, GROUP // 4), (1, 2, 0)
    ).reshape(ng, GROUP // 4, _SUBLANE, Tp // _SUBLANE)
    return native, Tp, ng, block_tiles, ilp


def _kernel_wfirst(wref, oref, sth_ref, stl_ref, fidx_ref, fval_ref, *,
                   avg_bits: int, ilp: int, gpw: int):
    """Gear scan with the per-window first-candidate reduction FUSED in.

    Same byte loop as :func:`_kernel`, but instead of storing the packed
    bitmask (1 bit/byte, re-read by a separate reduction dispatch), the
    kernel tracks — per tile lane, in registers — the first nonzero
    packed word of the current ``2**thin_bits``-byte window
    (``gpw`` = groups per window) and flushes ONE u32 per window: the
    in-window byte offset of the first candidate, or ``_SENT_OFF``.
    Output volume drops 8x vs the bitmask (4 B per window vs 4 B per 32
    bytes) and the mask never round-trips through HBM.  The tracking
    cost is ~5 ops per packed word (per 32 bytes), off the gear chain's
    serial path; the lsb/popcount runs once per window flush.

    Window accounting: group 0 is the warm-up prefix (excluded); window
    w covers groups [1 + w*gpw, 1 + (w+1)*gpw).  ``fidx`` holds the
    window-word index (0..gpw*8-1) of the first hit, ``fval`` that
    word's bits; both persist across grid steps in VMEM scratch.
    """
    j = pl.program_id(1)
    mask = U32((1 << avg_bits) - 1)
    btl = sth_ref.shape[-1] // ilp
    sent = U32(0xFFFFFFFF)

    @pl.when(j == 0)
    def _init():
        sth_ref[0] = jnp.zeros(sth_ref.shape[1:], U32)
        stl_ref[0] = jnp.zeros(stl_ref.shape[1:], U32)
        fidx_ref[0] = jnp.full(fidx_ref.shape[1:], sent, U32)
        fval_ref[0] = jnp.zeros(fval_ref.shape[1:], U32)

    def chunk(a, k):
        return a[:, k * btl : (k + 1) * btl]

    hh = [chunk(sth_ref[0], k) for k in range(ilp)]
    hl = [chunk(stl_ref[0], k) for k in range(ilp)]
    fidx = [chunk(fidx_ref[0], k) for k in range(ilp)]
    fval = [chunk(fval_ref[0], k) for k in range(ilp)]
    valid = j > 0  # group 0 is warm-up context: hits there never count
    wphase = jnp.mod(j - 1, gpw).astype(U32)  # window-local group index

    acc = [jnp.zeros_like(hh[0]) for _ in range(ilp)]
    bit = 0
    pword = 0
    for w in range(GROUP // 4):
        word = wref[0, w]
        for s in range(4):
            for k in range(ilp):
                byte = (chunk(word, k) >> U32(8 * s)) & U32(0xFF)
                hh[k], hl[k] = _gear_step(hh[k], hl[k], byte)
                hit = (hh[k] & mask) == U32(0)
                acc[k] = acc[k] | (hit.astype(U32) << U32(bit))
            bit += 1
            if bit == PACK:
                word_idx = wphase * U32(GROUP // PACK) + U32(pword)
                for k in range(ilp):
                    new = (fidx[k] == sent) & (acc[k] != U32(0)) & valid
                    fidx[k] = jnp.where(new, word_idx, fidx[k])
                    fval[k] = jnp.where(new, acc[k], fval[k])
                acc = [jnp.zeros_like(hh[0]) for _ in range(ilp)]
                bit = 0
                pword += 1

    sth_ref[0] = jnp.concatenate(hh, axis=-1)
    stl_ref[0] = jnp.concatenate(hl, axis=-1)

    is_flush = valid & (wphase == U32(gpw - 1))

    @pl.when(is_flush)
    def _flush():
        outs = []
        for k in range(ilp):
            lsb = fval[k] & (U32(0) - fval[k])
            bitpos = _popcount32(lsb - U32(1))
            outs.append(jnp.where(
                fidx[k] != sent,
                fidx[k] * U32(PACK) + bitpos,
                U32(_SENT_OFF),
            ))
        oref[0] = jnp.concatenate(outs, axis=-1)
        fidx_ref[0] = jnp.full(fidx_ref.shape[1:], sent, U32)

    @pl.when(jnp.logical_not(is_flush))
    def _keep():
        fidx_ref[0] = jnp.concatenate(fidx, axis=-1)
        fval_ref[0] = jnp.concatenate(fval, axis=-1)


@functools.partial(
    jax.jit,
    static_argnames=("avg_bits", "thin_bits", "block_tiles", "interpret",
                     "ilp"),
)
def gear_window_first_native(words, avg_bits: int, thin_bits: int,
                             block_tiles: int = 8192,
                             interpret: bool = False, ilp: int = 8):
    """``words``: (ng, GROUP/4, 8, T/8) uint32 (group 0 = warm-up) ->
    per-window first-candidate byte offsets ``(nwin_per_tile, 8, T/8)``
    uint32 (``_SENT_OFF`` = empty window)."""
    ng, gw, s, tl = words.shape
    if gw != GROUP // 4 or s != _SUBLANE:
        raise ValueError(f"expected (ng, {GROUP // 4}, 8, T/8); got {words.shape}")
    gpw = (1 << thin_bits) // GROUP
    if gpw < 1 or (ng - 1) % gpw:
        raise ValueError(
            f"window of 2**{thin_bits} B needs payload groups {ng - 1} "
            f"divisible by {gpw}"
        )
    btl = block_tiles // _SUBLANE
    if tl % btl:
        raise ValueError(f"T/8={tl} not a multiple of tile width {btl}")
    if btl % ilp or (btl // ilp) % _LANE:
        raise ValueError(
            f"block_tiles/8={btl} must split into {ilp} lane-multiples"
        )
    nwpt = (ng - 1) // gpw
    grid = (tl // btl, ng)
    kernel = functools.partial(_kernel_wfirst, avg_bits=avg_bits, ilp=ilp,
                               gpw=gpw)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, gw, _SUBLANE, btl), lambda i, j: (j, 0, 0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, _SUBLANE, btl),
            # groups [1 + w*gpw, 1 + (w+1)*gpw) -> window block w; the
            # warm-up step j=0 aliases harmlessly onto block 0 (clamped),
            # which it never writes
            lambda i, j: (jnp.maximum((j - 1) // gpw, 0), 0, i),
        ),
        out_shape=jax.ShapeDtypeStruct((nwpt, _SUBLANE, tl), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(words)


@functools.partial(
    jax.jit,
    static_argnames=("avg_bits", "thin_bits", "block_tiles", "interpret",
                     "ilp"),
)
def gear_window_first_pallas(words, avg_bits: int, thin_bits: int,
                             block_tiles: int | None = None,
                             interpret: bool = False, ilp: int | None = None):
    """Fused-extraction route: (T, S/4) prefixed tile rows in (group 0 =
    warm-up, as built by rabin._build_rows), stream-ordered per-window
    first-candidate offsets out — ``(T * nwin_per_tile,)`` int32 with
    ``_SENT_OFF`` for empty windows."""
    T, _ = words.shape
    native, Tp, ng, block_tiles, ilp = _to_native_layout(
        words, block_tiles, ilp
    )
    firsts = gear_window_first_native(
        native, avg_bits, thin_bits, block_tiles, interpret, ilp
    )
    nwpt = firsts.shape[0]
    # (nwpt, 8, Tp/8) -> (8, Tp/8, nwpt) -> flat (t, w) stream order
    out = jnp.transpose(firsts, (1, 2, 0)).reshape(Tp * nwpt)
    return out[: T * nwpt].astype(jnp.int32)


gear_window_first_pallas = _jit_site("ops.rabin_pallas.window_first", gear_window_first_pallas)


def _kernel_first(wref, oref, sth_ref, stl_ref, *, avg_bits: int, ilp: int):
    """First-hit-per-group variant of :func:`_kernel`: emits one u32 per
    GROUP (the group-local offset of the first candidate, or NO_HIT)
    instead of GROUP/PACK packed mask words — 1/8 the output traffic.
    Same ILP interleave; see :func:`.rabin.gear_first_tiled` for the
    semantics."""
    j = pl.program_id(1)
    mask = U32((1 << avg_bits) - 1)
    btl = sth_ref.shape[-1] // ilp
    sent = U32(NO_HIT)

    @pl.when(j == 0)
    def _init():
        sth_ref[0] = jnp.zeros(sth_ref.shape[1:], U32)
        stl_ref[0] = jnp.zeros(stl_ref.shape[1:], U32)

    def chunk(a, k):
        return a[:, k * btl : (k + 1) * btl]

    hh = [chunk(sth_ref[0], k) for k in range(ilp)]
    hl = [chunk(stl_ref[0], k) for k in range(ilp)]
    first = [jnp.full(hh[0].shape, sent, U32) for _ in range(ilp)]
    pos = 0
    for w in range(GROUP // 4):
        word = wref[0, w]
        for s in range(4):
            for k in range(ilp):
                byte = (chunk(word, k) >> U32(8 * s)) & U32(0xFF)
                hh[k], hl[k] = _gear_step(hh[k], hl[k], byte)
                hit = (hh[k] & mask) == U32(0)
                first[k] = jnp.where(
                    hit & (first[k] == sent), U32(pos), first[k]
                )
            pos += 1
    oref[0] = jnp.concatenate(first, axis=-1)
    sth_ref[0] = jnp.concatenate(hh, axis=-1)
    stl_ref[0] = jnp.concatenate(hl, axis=-1)


@functools.partial(
    jax.jit, static_argnames=("avg_bits", "block_tiles", "interpret", "ilp")
)
def gear_first_native(words, avg_bits: int = 13, block_tiles: int = 8192,
                      interpret: bool = False, ilp: int = 8):
    """``words``: (ngroups, GROUP/4, 8, T/8) uint32 -> first-hit offsets
    ``(ngroups, 8, T/8)`` uint32 (NO_HIT = none)."""
    ng, gw, s, tl = words.shape
    if gw != GROUP // 4 or s != _SUBLANE:
        raise ValueError(f"expected (ng, {GROUP // 4}, 8, T/8); got {words.shape}")
    if block_tiles % (_SUBLANE * _LANE):
        raise ValueError(f"block_tiles must be a multiple of {_SUBLANE * _LANE}")
    btl = block_tiles // _SUBLANE
    if tl % btl:
        raise ValueError(f"T/8={tl} not a multiple of tile width {btl}")
    if btl % ilp or (btl // ilp) % _LANE:
        raise ValueError(
            f"block_tiles/8={btl} must split into {ilp} lane-multiples"
        )
    grid = (tl // btl, ng)
    kernel = functools.partial(_kernel_first, avg_bits=avg_bits, ilp=ilp)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, gw, _SUBLANE, btl), lambda i, j: (j, 0, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, _SUBLANE, btl), lambda i, j: (j, 0, i)),
        out_shape=jax.ShapeDtypeStruct((ng, _SUBLANE, tl), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
            pltpu.VMEM((1, _SUBLANE, btl), jnp.uint32),
        ],
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(words)


@functools.partial(
    jax.jit, static_argnames=("avg_bits", "block_tiles", "interpret", "ilp")
)
def gear_first_pallas(words, avg_bits: int = 13,
                      block_tiles: int | None = None,
                      interpret: bool = False, ilp: int | None = None):
    """Drop-in for :func:`.rabin.gear_first_tiled`: (T, S/4) uint32 tiles
    in, (T, S/GROUP) first-hit offsets out, Pallas-accelerated."""
    T, _ = words.shape
    native, Tp, ng, block_tiles, ilp = _to_native_layout(
        words, block_tiles, ilp
    )
    firsts = gear_first_native(native, avg_bits, block_tiles, interpret, ilp)
    out = jnp.transpose(firsts.reshape(ng, Tp), (1, 0))
    return out[:T]


gear_first_pallas = _jit_site("ops.rabin_pallas.first", gear_first_pallas)


@functools.partial(
    jax.jit, static_argnames=("avg_bits", "block_tiles", "interpret", "ilp")
)
def gear_candidates_pallas(words, avg_bits: int = 13,
                           block_tiles: int | None = None,
                           interpret: bool = False, ilp: int | None = None):
    """Drop-in for :func:`.rabin.gear_candidates_tiled`: (T, S/4) uint32
    tiles in, (T, S/PACK) packed bitmask out, Pallas-accelerated.

    Pads the tile count up to ``block_tiles`` (zero tiles are discarded
    on output).  Defaults pick the measured sweet spot — 8192-tile blocks
    with 8 interleaved chains: 13.8-14.1 GiB/s kernel-only on v5e-1 at
    the 1 GiB/128 KiB-tile bench shape (round-3 driver runs; 2x the
    un-interleaved kernel; ilp=16 with 16k-tile blocks and a 32-bit-state
    gear variant both measured within noise of this, so the kernel is not
    ALU- or ILP-bound at this rate) — scaled down for small batches so
    padding never exceeds one power-of-two step.
    """
    T, _ = words.shape
    native, Tp, ng, block_tiles, ilp = _to_native_layout(
        words, block_tiles, ilp
    )
    bits = gear_candidates_native(native, avg_bits, block_tiles, interpret, ilp)
    # (ng, GROUP/PACK, 8, Tp/8) -> (T, S/PACK)
    out = jnp.transpose(
        bits.reshape(ng, GROUP // PACK, Tp), (2, 0, 1)
    ).reshape(Tp, ng * GROUP // PACK)
    return out[:T]


gear_candidates_pallas = _jit_site("ops.rabin_pallas.candidates", gear_candidates_pallas)
