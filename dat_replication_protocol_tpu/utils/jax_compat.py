"""jax version-drift shims — the ONE owner of every rename adaptation.

The repo must run on the jax the image ships AND the newer jax the TPU
pods run; two renames currently differ between them:

* ``pltpu.TPUCompilerParams`` -> ``pltpu.CompilerParams`` — bound here
  as :data:`COMPILER_PARAMS` for every Pallas kernel module.
* ``jax.experimental.shard_map.shard_map`` -> ``jax.shard_map``, whose
  ``check_rep`` kwarg became ``check_vma`` — bound here as
  :func:`shard_map` accepting the NEW spelling and translating for the
  old function.

A new drift gets its shim HERE, not a copy per consumer (five modules
shared these verbatim before this file existed).
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as _pltpu

COMPILER_PARAMS = getattr(_pltpu, "CompilerParams", None) or getattr(
    _pltpu, "TPUCompilerParams"
)

try:
    from jax import shard_map
except ImportError:  # older jax: experimental home, check_rep kwarg

    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_compat(*args, **kwargs)
