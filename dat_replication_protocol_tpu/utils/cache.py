"""Persistent XLA compile-cache setup (one owner for all entry points).

The scanned-BLAKE2b / tree programs take minutes to compile cold on the
CPU backend and tens of seconds on TPU; a persistent cache turns reruns
(tests, bench, examples, driver re-runs) into cache hits.  Scope rules:

* keyed by platform + processor + jax version: AOT artifacts from a
  host with different CPU features can SIGILL when loaded;
* per-user path under the system temp dir: a predictable world-shared
  path would let another local user pre-seed attacker-controlled
  compiled artifacts (deserialized XLA programs execute).
"""

from __future__ import annotations

import hashlib
import os
import platform
import tempfile


def enable_compile_cache(tag: str, env_var: str | None = None) -> None:
    """Point jax at a persistent, scoped compile-cache directory.

    ``tag`` separates entry points (tests/bench/examples); ``env_var``
    optionally names an environment variable that overrides the path.
    Never raises: the cache is an optimization.
    """
    try:
        import jax

        override = os.environ.get(env_var) if env_var else None
        if override:
            path = override
        else:
            scope = hashlib.blake2b(
                f"{platform.platform()}-{platform.processor()}-"
                f"{jax.__version__}".encode(),
                digest_size=6,
            ).hexdigest()
            user = f"u{os.getuid()}" if hasattr(os, "getuid") else "u0"
            path = os.path.join(
                tempfile.gettempdir(),
                f"dat_jax_cache-{user}-{tag}-{scope}",
            )
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass
