"""Engine routing: device batches when an accelerator backs jax, host
engines otherwise — "batch or stay home" (DESIGN.md §2 rule 0).

One owner of the hang-safe backend decision: reading the CONFIGURED
platform string decides without initializing any backend (an in-process
init on a wedged device tunnel hangs with no timeout — observed >6h);
only when nothing is configured (jax picks from locally present
plugins, nothing to wedge on) is the initialized backend consulted.
"""

from __future__ import annotations

import os


def prefer_host(force_env: str) -> bool:
    """True when host engines should take batch work on this host.

    ``force_env`` names an override variable: ``"1"`` forces the device
    path, ``"0"`` forces the host path (tests / experiments).
    """
    force = os.environ.get(force_env)
    if force == "0":
        return True
    if force == "1":
        return False
    try:
        import jax  # noqa: PLC0415

        cfg = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS")
        if cfg:
            return cfg.split(",")[0].strip().lower() == "cpu"
        return jax.default_backend() == "cpu"
    except Exception:
        return True
