"""Profiling spans around host->device dispatch boundaries.

The reference has no tracing at all — only passive byte/frame counters
(reference: encode.js:51-53, decode.js:68-70).  At device scale that is
not enough: round 2 shipped a ~2000x CDC regression that a single trace
would have localized in minutes (the cost was H2D staging, not the
kernel).  SURVEY.md §5 therefore promises `jax.profiler` spans around
every dispatch; this module is that hook.

* :func:`span` — named annotation context.  Wrap host-side phases
  (packing, dispatch, collect) so they show up on the TraceViewer
  timeline next to the device ops.  Uses
  ``jax.profiler.TraceAnnotation``; ~ns overhead when no trace is
  active, so call sites leave it on unconditionally.
* :func:`trace_to` — whole-program capture into a profile directory
  (``bench.py --trace=DIR`` uses it; open with TensorBoard or Perfetto).

JAX is imported lazily: the session layer must stay importable (and
fast) in processes that never touch a device.

When the obs gate is on, :func:`span` ALSO records into the obs span
ring (``obs.tracing.SPANS``, field ``src="jax"``) so device-dispatch
phases appear in the exported Chrome trace next to the wire-offset
frame spans — one timeline for host wire work and device work
(ISSUE 4).  With the gate off, behavior is byte-identical to before:
the bound factory is returned directly.
"""
# datlint: disable-file=obs-discipline  — this module IS span plumbing:
# it forwards caller-supplied span names into jax.profiler and the obs
# span ring by design; its callers are the greppable sites.

from __future__ import annotations

import contextlib
import sys

from ..obs import tracing as _obs_tracing
from ..obs.metrics import OBS as _OBS


class _NullSpan:
    def __init__(self, *_a, **_k):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


# Lazily bound span factory: jax.profiler.TraceAnnotation, or _NullSpan
# when JAX is unavailable.  Bound ONCE at first use (the _fastpath_gate
# trick): span() sits on dispatch hot paths, and re-attempting the
# import on every call costs ~1.8us of import machinery per span even
# on the cache-hit path.  Availability of jax cannot change mid-process
# (unlike an env-var gate), so a permanent bind is safe;
# _reset_span_binding_for_tests() exists for test isolation only.
_span_factory = None


def _bind_span_factory():
    global _span_factory
    try:
        from jax.profiler import TraceAnnotation as factory
    except Exception:
        factory = _NullSpan
    _span_factory = factory
    return factory


def _reset_span_binding_for_tests() -> None:
    global _span_factory
    _span_factory = None


class _JoinedSpan:
    """jax TraceAnnotation + an obs span record of the same name, so
    device-phase annotations land in the exported Chrome trace next to
    the wire-offset spans (``src="jax"`` distinguishes them)."""

    __slots__ = ("_span", "_inner")

    def __init__(self, name: str, inner):
        self._span = _obs_tracing.trace_span(name, src="jax")
        self._inner = inner

    def __enter__(self):
        self._span.__enter__()
        try:
            self._inner.__enter__()
        except BaseException:
            # unwind the obs span: a raising jax annotation means the
            # with-statement never runs __exit__, and an unpopped id
            # would corrupt the thread's span-parent stack for good
            self._span.__exit__(*sys.exc_info())
            raise
        return self

    def __exit__(self, *exc):
        try:
            return self._inner.__exit__(*exc) or False
        finally:
            self._span.__exit__(*exc)


def span(name: str):
    """Named profiler annotation; inert if jax is unavailable.  With
    the obs gate on, the span is additionally recorded into the obs
    span ring (see module docstring)."""
    factory = _span_factory
    if factory is None:
        factory = _bind_span_factory()
    if _OBS.on:
        return _JoinedSpan(name, factory(name))
    return factory(name)


@contextlib.contextmanager
def trace_to(log_dir: str | None):
    """Capture a jax profiler trace into ``log_dir`` (no-op if None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
