"""Exclusive chip mutex shared by ``bench.py`` and every diagnostic script.

Round-4 lesson: the round's only pipelined full-bench hash capture
recorded 22.76 GiB/s because an ad-hoc diagnostic ran concurrently on
the same chip — the uncontended rate (37.9–39.1 GiB/s) was measured
separately, and the one driver-shaped artifact carried the polluted
number.  Nothing coordinated the two processes.

This module is that coordination: one ``flock(2)``-style mutex that
every device-touching entry point (the bench harness and the experiment
scripts) takes before initializing the backend.  flock is released by
the kernel when the holder dies, so a crashed diagnostic can never
leave the chip wedged-locked; no stale-lock sweeper is needed.

Artifact contract: device legs record ``uncontended: bool`` — True iff
this process acquired the lock *without waiting* and held it for the
whole leg.  A wait means another cooperating process was just on the
chip (its queues/clocks may not have drained); running lockless after
``max_wait`` expires records False, never silence.

The lock scopes a *chip*, not a repo: the default path lives in /tmp so
two checkouts driving the same tunneled device still exclude each
other.  Override with ``DAT_CHIP_LOCK`` (e.g. per-device paths on a
multi-chip host).
"""

from __future__ import annotations

import errno
import fcntl
import os
import time
from contextlib import contextmanager

from ..obs.metrics import OBS as _OBS
from ..obs.metrics import counter as _counter
from ..obs.metrics import histogram as _histogram

# chip-mutex contention telemetry (device-telemetry catalog): every
# acquisition's wait lands in the histogram, so `bench --metrics`
# artifacts carry the contention story from the registry instead of
# only the ad-hoc per-leg `waited_s` field
_M_WAIT = _histogram("device.chiplock.wait")
_M_ACQUIRES = _counter("device.chiplock.acquires")
_M_CONTENDED = _counter("device.chiplock.contended")
_M_LOCKLESS = _counter("device.chiplock.lockless")

DEFAULT_LOCK_PATH = "/tmp/dat_tpu_chip.lock"


def lock_path() -> str:
    return os.environ.get("DAT_CHIP_LOCK", DEFAULT_LOCK_PATH)


class ChipLease:
    """What ``chip_lock`` yields: did we get it, and did we have to wait."""

    def __init__(self, held: bool, waited_s: float, path: str) -> None:
        self.held = held
        self.waited_s = waited_s
        self.path = path

    @property
    def uncontended(self) -> bool:
        """True iff the chip was free the moment we asked for it."""
        return self.held and self.waited_s == 0.0

    def as_fields(self) -> dict:
        """The artifact-record form (merged into device-leg results).

        When the lock IS held, the flock itself certifies the whole leg
        (no cooperating peer can run until release) so the values frozen
        at acquisition stay valid.  When it is NOT held (ran lockless
        after ``max_wait``), acquisition-time state says nothing about
        now — re-probe so each config's record reflects contention at
        the moment it was stamped.
        """
        contended_now = False
        if not self.held:
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o666)
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    fcntl.flock(fd, fcntl.LOCK_UN)
                except OSError:
                    contended_now = True
                finally:
                    os.close(fd)
            except OSError:
                pass
        return {
            "uncontended": self.uncontended and not contended_now,
            "chip_lock": {
                "held": self.held,
                "waited_s": round(self.waited_s, 1),
                **({"peer_active": contended_now} if not self.held else {}),
            },
        }


@contextmanager
def chip_lock(max_wait: float | None = None, poll_s: float = 2.0):
    """Hold the exclusive chip mutex for the duration of the block.

    * acquired immediately  -> lease.uncontended is True;
    * acquired after a wait -> held=True, uncontended=False;
    * still contended after ``max_wait`` seconds -> the block runs
      WITHOUT the lock (held=False) so a stuck peer cannot blank a
      bench run — the artifact just says so.  ``max_wait=None`` waits
      forever (the right mode for diagnostics, which have no deadline
      and must never run concurrently with a capture).
    """
    path = lock_path()
    try:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
    except OSError:
        # e.g. the lock file belongs to another user (umask strips the
        # 0o666): degrade to lockless-with-a-record rather than blank
        # the run this lock exists to protect
        if _OBS.on:
            _M_LOCKLESS.inc()
        yield ChipLease(False, 0.0, path)
        return
    held = False
    waited = 0.0
    try:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            held = True
        except OSError as e:
            if e.errno not in (errno.EAGAIN, errno.EACCES):
                raise
            t0 = time.monotonic()
            while True:
                if max_wait is not None and time.monotonic() - t0 >= max_wait:
                    break
                time.sleep(poll_s if max_wait is None
                           else min(poll_s, max_wait / 10 + 0.01))
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    held = True
                    break
                except OSError as e2:
                    if e2.errno not in (errno.EAGAIN, errno.EACCES):
                        raise
            waited = time.monotonic() - t0
        if _OBS.on:
            _M_WAIT.observe(waited)
            if held:
                _M_ACQUIRES.inc()
            else:
                _M_LOCKLESS.inc()
            if waited > 0.0:
                _M_CONTENDED.inc()
        if held:
            # best-effort breadcrumb for a human inspecting a contended
            # window; failures (read-only fs) must not break the lock
            try:
                os.ftruncate(fd, 0)
                os.write(fd, f"pid={os.getpid()}\n".encode())
            except OSError:
                pass
        yield ChipLease(held, waited, path)
    finally:
        try:
            if held:
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)
