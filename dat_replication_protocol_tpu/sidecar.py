"""The literal sidecar endpoint: a daemon foreign clients pipe wire bytes to.

The reference's deployment shape is a stream piped into a socket
(reference: example.js:53 ``encode.pipe(decode)``, README.md's
``encode.pipe(socket)``): any process that speaks the dat replication
wire format can connect.  This module makes the TPU data plane
reachable the same way — no Python client required:

    python -m dat_replication_protocol_tpu.sidecar --stdio
    python -m dat_replication_protocol_tpu.sidecar --tcp 127.0.0.1:7531

A client pipes a session (changes + blobs) in; the sidecar decodes it
with the ``backend='tpu'`` decoder (content-hashing every change
payload and blob through the device/host digest engine the routing
layer picks) and streams a *reply session* back on the same connection:

* one ``Change`` per digest, in digest-completion order (submit order
  per the pipeline's completion queue);
* ``key``   = ``"change-<seq>"`` or ``"blob-<seq>"`` (<seq> is the
  0-based arrival index of that kind — self-describing, so the reply
  needs no state from the request stream);
* ``subset`` = ``"digest:change"`` / ``"digest:blob"``;
* ``change`` = <seq>, ``from`` = 0, ``to`` = 1;
* ``value`` = the 32-byte BLAKE2b-256 digest.

Flush-before-finalize holds end-to-end: when the client finalizes its
stream, every digest for submitted work is encoded onto the reply
before the reply stream finalizes (TpuDecoder._maybe_finalize flushes
the pipeline first).  A protocol error destroys both directions, so a
malformed client observes EOF rather than a hang.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time

from .obs import device as obs_device
from .obs import events as obs_events
from .obs import flight as obs_flight
from .obs import http as obs_http
from .obs import metrics as obs_metrics
from .obs import tracing as obs_tracing
from .obs.events import emit as _emit
from .obs.metrics import OBS as _OBS, counter as _counter
from .obs.tracing import trace_span as _trace_span
from .obs.propagation import PROPAGATION as _PROPAGATION
from .obs.wirecost import WIRECOST as _WIRECOST
from .obs.watermarks import WATERMARKS as _WATERMARKS
from .session import pump as session_pump
from .session.transport import recv_over, send_over
# one owner for the blocking write-all loop (session/transport.py; the
# pump module's Python fallback binds the same function)
from .session.transport import write_all as _write_all

DIGEST_SUBSET_CHANGE = "digest:change"
DIGEST_SUBSET_BLOB = "digest:blob"

# reply-drain defaults: a client that finished sending but never reads
# its reply must not park a session thread forever (ADVICE.md round 5).
DEFAULT_DRAIN_TIMEOUT = 600.0
_DRAIN_POLL = 0.25

DEFAULT_STATS_INTERVAL = 5.0

_M_SESSIONS = _counter("sidecar.sessions")
_M_STALLS = _counter("sidecar.stalls")

# hub mode (ISSUE 8): ONE shared ReplicationHub across every accepted
# connection; snapshot_stats() carries its per-session breakdown so
# --stats-fd lines attribute traffic per peer
_ACTIVE_HUB = None

# fan-out mode (ISSUE 9): ONE shared FanoutServer broadcasting the
# source session's wire to every subscriber connection
_ACTIVE_FANOUT = None

# replica mode (ISSUE 15): the gossip node (or its driver) whose
# round/peer/quarantine counters --stats-fd and /snapshot carry — the
# fleet plane's per-replica convergence input
_ACTIVE_GOSSIP = None

# edge mode (ISSUE 17): the event-driven EdgeLoop whose session-table
# aggregate --stats-fd and /snapshot carry, and whose admission stage
# fronts /healthz (it composes the hub's — edge wins the precedence)
_ACTIVE_EDGE = None


def set_active_edge(loop) -> None:
    """Install the :class:`~.edge.EdgeLoop` whose session-table
    aggregate ``--stats-fd`` snapshots carry (None detaches)."""
    global _ACTIVE_EDGE
    _ACTIVE_EDGE = loop


def set_active_gossip(driver) -> None:
    """Install the gossip driver/node whose snapshot() record
    ``--stats-fd`` snapshots carry (None detaches)."""
    global _ACTIVE_GOSSIP
    _ACTIVE_GOSSIP = driver


def set_active_hub(hub) -> None:
    """Install the hub whose per-session breakdown ``--stats-fd``
    snapshots carry (None detaches)."""
    global _ACTIVE_HUB
    _ACTIVE_HUB = hub


def set_active_fanout(server) -> None:
    """Install the fan-out server whose per-peer breakdown
    ``--stats-fd`` snapshots carry (None detaches)."""
    global _ACTIVE_FANOUT
    _ACTIVE_FANOUT = server


def run_session(read_bytes, write_bytes, close_write=None,
                drain_timeout: float | None = DEFAULT_DRAIN_TIMEOUT,
                hub=None, session_key: str | None = None,
                rx_fd: int | None = None, tx_fd: int | None = None,
                publish=None) -> dict:
    """Serve one wire session over a blocking byte pair.

    ``read_bytes(n)`` / ``write_bytes(data)`` follow the
    :mod:`..session.transport` contract (block on congestion, ``b''``
    at EOF).  Returns counters for observability:
    ``{"changes": n, "blobs": n, "bytes": n, "digests": n, "ok": bool}``.

    ``rx_fd`` / ``tx_fd`` (ISSUE 14): the raw descriptors behind the
    byte pair, when the caller has them.  With the native pump routed
    (``DAT_PUMP``, :func:`~..session.pump.effective_pump_route`) the
    session's byte loops run through the C extension's batched-syscall
    pumps instead of ``read_bytes``/``write_bytes`` — byte-identical
    deliveries, digests, and errors, an order less interpreter work.
    Callable-only callers (tests, custom transports) get the Python
    pumps unchanged.  ``publish`` observes every received chunk on
    EITHER route (the fan-out source's broadcast tap).

    ``drain_timeout`` bounds every reply-stall wait: when the reply
    stream makes no write progress for that many seconds — whether the
    stall surfaces in the end-of-session drain join or mid-session in
    the digest-flush backpressure wait — the encoder is destroyed and
    ``close_write`` invoked (best-effort) so the connection tears down
    instead of leaking a parked thread per stalled client; ``None``
    waits forever (the pre-round-6 behavior).  In hub mode the deadline
    is PER SESSION by construction: each connection's thread owns its
    own progress clock, so one draining session's deadline neither
    extends nor cuts short another's.

    ``hub`` (a :class:`~.hub.ReplicationHub`) switches this session
    onto the shared device engine: the decoder's digest work registers
    under ``session_key`` and coalesces with every co-resident
    session's into single XLA dispatches, completions routing back
    here by key.  Admission rejection (:class:`~.hub.HubBusy`) returns
    a structured ``{"ok": False, "rejected": True, ...}`` record
    without consuming any wire bytes; a mid-session shed
    (:class:`~.hub.SessionShed`) tears this session down like any
    other session-fatal error — co-residents never notice either.

    The decoder is ALWAYS the digest-capable ``backend='tpu'`` one —
    the plain host :class:`Decoder` has no digest surface and would
    make the sidecar silently useless.  Which engine actually hashes
    (device batches vs the native host engine) is the routing layer's
    call; the CLI's ``--backend host`` forces the host engine via the
    routing override env var (see :func:`main`) — process-wide, which
    is why the override does not live here.
    """
    from . import decode, encode

    hub_session = None
    if hub is not None:
        from .hub import HubBusy

        try:
            hub_session = hub.register(session_key)
        except HubBusy as e:
            # structured rejection, bounded state: no decoder, no reply
            # thread, no queue growth — the client observes EOF
            out = {"changes": 0, "blobs": 0, "bytes": 0, "digests": 0,
                   "ok": False, "rejected": True,
                   "sessions": e.sessions, "parked_bytes": e.parked_bytes}
            if close_write is not None:
                try:
                    # a shutdown syscall (every caller's close_write is
                    # shutdown/os.close) — bounded
                    # datlint: allow-callback-escape
                    close_write()
                except OSError:
                    pass
            if _OBS.on:
                _emit("sidecar.session", **out)
            return out

    enc = encode()  # reply stream: plain host encoder (digest payloads)
    if hub_session is not None:
        dec = decode(backend="tpu", pipeline=hub_session)
    else:
        dec = decode(backend="tpu")
    stats = {"digests": 0}
    # fleet-plane watermarks: this session's receive cursors, one link
    # per connection (untracked on exit — dead sessions vanish)
    wm_link = session_key if session_key else "stdio"
    dec.watermark(wm_link)
    # wire cost plane (ISSUE 20): name this session's ledger link after
    # the same key the watermark plane uses, so `obs fleet` can join
    # cost rows against cursors without a translation table.  Plain
    # attribute writes — the boards only see them when the lit helpers
    # run, so the dark path is untouched.
    enc.cost_link = wm_link
    dec.cost_link = wm_link

    # reply write progress, shared by every stall check: refreshed each
    # time a reply byte actually reaches the transport
    progress = {"t": time.monotonic()}

    def _stalled(now: float) -> bool:
        return (drain_timeout is not None
                and now - progress["t"] > drain_timeout)

    def _teardown_stalled() -> None:
        # the drain deadline fired: the client stopped reading its reply
        # (ADVICE.md round 5 low) — record it as a structured stall
        # event so the leak class is visible at runtime, then tear down
        if _OBS.on:
            _M_STALLS.inc()
            _emit("sidecar.stall", kind="reply-drain",
                  seconds=drain_timeout, reply_bytes=enc.bytes)
        enc.destroy(TimeoutError(
            f"reply stream stalled for {drain_timeout}s"))
        if close_write is not None:
            try:
                # unblocks a sender parked in a socket write (shutdown
                # wakes it with EPIPE); best-effort — the caller's
                # close is the backstop
                close_write()
            except OSError:
                pass

    def on_digest(kind: str, seq: int, digest: bytes) -> None:
        stats["digests"] += 1
        flushed = threading.Event()
        below_hw = enc.change({
            "key": f"{kind}-{seq}",
            "change": seq,
            "from": 0,
            "to": 1,
            "value": digest,
            "subset": DIGEST_SUBSET_CHANGE if kind == "change"
            else DIGEST_SUBSET_BLOB,
        }, on_flush=flushed.set)
        if not below_hw:
            # reply-side backpressure: this callback runs on the decoder's
            # consume path, so blocking here stalls request consumption —
            # the client that won't read its reply eventually can't send
            # either, and reply memory stays bounded by the high-water
            # mark instead of growing with the session.  Same stall
            # deadline as the drain join below: a client that parked the
            # reply mid-session would otherwise hang this wait forever
            # and the drain teardown could never be reached
            progress["t"] = time.monotonic()  # stall measured from HERE:
            # a long reply-quiet stretch before this wait (one huge blob,
            # digests batched) is not the client's fault
            while not (flushed.wait(0.1) or enc.destroyed):
                if _stalled(time.monotonic()):
                    _teardown_stalled()
                    break

    # on_digest's flush wait is bounded (flushed.wait(0.1) ladder with
    # the drain-timeout teardown above) — audited, ISSUE 17 satellite
    # datlint: allow-callback-escape
    dec.on_digest(on_digest)
    # change/blob handlers stay unregistered: the decoder's defaults
    # (drop changes, drain blobs) are exactly the sidecar's behavior,
    # with no per-frame ack bookkeeping
    # all digests are flushed (and encoded) before this hook runs;
    # finalizing the reply inside it seals the ordering guarantee
    dec.finalize(lambda done: (enc.finalize(), done()))
    # a malformed request must tear down the reply sender too (EOF at
    # the client), and a reply-side failure must stop consuming;
    # destroy() flips state and wakes watchers — never blocks
    # datlint: allow-callback-escape
    dec.on_error(lambda _e: enc.destroy())
    # datlint: allow-callback-escape
    enc.on_error(lambda _e: None if dec.destroyed else dec.destroy())

    # pump route selection (ISSUE 14): fds + a native route take the
    # batched-syscall loops; anything else is the Python reference pump
    native_route = ((rx_fd is not None or tx_fd is not None)
                    and session_pump.effective_pump_route() == "native")

    def _write(data) -> None:
        write_bytes(data)
        progress["t"] = time.monotonic()  # reply byte reached the client

    def _mark_progress() -> None:
        progress["t"] = time.monotonic()  # reply batch reached the client

    def _send() -> None:
        try:
            if native_route and tx_fd is not None:
                session_pump.send_pump(enc, tx_fd, close=close_write,
                                       on_progress=_mark_progress)
            else:
                send_over(enc, _write, close_write)
        except Exception as e:  # EPIPE/ECONNRESET from a vanished client
            if not enc.destroyed:
                enc.destroy(e)
            if not dec.destroyed:
                dec.destroy(e)

    if publish is not None and not (native_route and rx_fd is not None):
        # the Python route's broadcast tap: wrap the reader so the
        # published stream is byte-identical to the native pump's tap
        def read_bytes(n, _r=read_bytes):
            data = _r(n)
            if data:
                publish(data)
            return data

    sender = threading.Thread(target=_send, name="sidecar-send",
                              daemon=True)
    sender.start()
    try:
        # span brackets the request-consumption phase; the per-frame
        # wire-offset instants the decoder records nest under it
        with _trace_span("sidecar.session.recv"):
            if native_route and rx_fd is not None:
                session_pump.recv_pump(dec, rx_fd, tap=publish)
            else:
                recv_over(dec, read_bytes)
    except Exception as e:  # ECONNRESET etc.: transport died mid-read —
        # or, in hub mode, SessionShed/HubError surfacing from the
        # decoder's digest submits: session-fatal either way, and the
        # destroy cascade below keeps it THIS session's problem
        if not dec.destroyed:
            dec.destroy(e)
        if not enc.destroyed:
            enc.destroy(e)
    if dec.destroyed and not enc.destroyed:
        enc.destroy()
    if enc.destroyed:
        # the sender may sit in a blocking write to a dead peer; the
        # caller's socket close unblocks it — don't wait on it here
        sender.join(timeout=5)
    else:
        # healthy path: the reply is still draining to the client;
        # truncating it early would corrupt a correct session
        # mid-frame, but a bare join() would park this thread forever
        # behind a client that stopped reading (ADVICE.md round 5) —
        # so join in bounded steps and tear the session down once the
        # reply makes no progress for drain_timeout seconds
        progress["t"] = time.monotonic()  # idle clock starts at drain
        while True:
            sender.join(timeout=_DRAIN_POLL)
            if not sender.is_alive():
                break
            if _stalled(time.monotonic()):
                _teardown_stalled()
                sender.join(timeout=5)
                break
    out = {
        "changes": dec.changes,
        "blobs": dec.blobs,
        "bytes": dec.bytes,
        "digests": stats["digests"],
        "ok": (dec.finished and not dec.destroyed and not enc.destroyed
               and not sender.is_alive()),
    }
    if hub_session is not None:
        out["session"] = hub_session.key
        out["shed"] = hub_session.shed_reason
        # release the hub slot LAST: queued work is dropped, in-flight
        # completions discard on arrival — a torn-down session cannot
        # park bytes against the shared budget
        hub_session.close()
    _WATERMARKS.untrack(wm_link)
    if _OBS.on:
        _M_SESSIONS.inc()
        _emit("sidecar.session", **out)
    return out


# a refusal goes to a peer we are about to drop: it must never park the
# session thread on sendall against a receiver that stopped draining
# (the blocking-reachability certifier's first true positive — the
# kernel buffer absorbs the ~200-byte record instantly from any healthy
# peer, so the bound only ever fires on a dead one)
_REFUSAL_SEND_TIMEOUT = 5.0


def _send_refusal(conn: socket.socket, out: dict) -> None:
    """Best-effort structured-refusal write with a hard bound.

    ``settimeout`` flips the socket to timeout mode for the remaining
    sends; that is fine here — every caller drops ``conn`` right after.
    ``socket.timeout`` is an ``OSError`` subclass, so the one except
    clause covers refused, reset, AND wedged receivers.
    """
    try:
        conn.settimeout(_REFUSAL_SEND_TIMEOUT)
        # bounded by the settimeout above (invisible to the certifier,
        # which reads call shapes, not socket modes).
        # datlint: allow-blocking-reachable(socket)
        conn.sendall((json.dumps(out) + "\n").encode())
        conn.shutdown(socket.SHUT_WR)
    except OSError:
        pass


def run_subscriber(conn: socket.socket, fanout, key: str) -> dict:
    """Serve one fan-out subscriber connection (ISSUE 9): attach the
    socket as a downstream peer of the shared :class:`BroadcastLog` and
    stream the broadcast until the sealed log is fully delivered or the
    peer is shed.  The subscriber never decodes and never hashes — the
    digest work happened ONCE on the source session.

    A joiner asking below the retained window gets a structured
    ``{"snapshot_needed": true, "retained": [start, end]}`` record and
    EOF — plus a ``"hint"`` naming the snapshot bootstrap port when
    the deployment serves it (``--snapshot``, ISSUE 12), so the joiner
    redirects without out-of-band config; admission rejection gets
    ``{"rejected": true}`` — bounded
    state, never queue growth (the hub's contract, restated for peers).
    A subscriber that SENDS data is a misrouted source (it raced a
    connection holding the source claim): it gets a structured
    ``{"not_source": true}`` record and EOF instead of having its
    uploaded session silently discarded.
    """
    from .fanout import FanoutBusy, SnapshotNeeded

    try:
        # a wire subscriber needs the stream FROM BYTE 0 to parse it;
        # once the log trimmed past 0 only a snapshot can help
        peer = fanout.attach_peer(key, fd=conn.fileno(), offset=0)
    except SnapshotNeeded as e:
        out = {"fanout_peer": key, "ok": False, "snapshot_needed": True,
               "retained": list(e.retained)}
        if e.hint is not None:
            # the deployment serves the snapshot bootstrap (ISSUE 12):
            # the refusal record carries the redirect — port +
            # capability — so the joiner needs no out-of-band config
            out["hint"] = dict(e.hint)
        _send_refusal(conn, out)
        if _OBS.on:
            _emit("sidecar.session", **out)
        return out
    except FanoutBusy as e:
        out = {"fanout_peer": key, "ok": False, "rejected": True,
               "peers": e.peers, "max_peers": e.max_peers}
        # the structured record IS the rejection: a bare EOF would be
        # indistinguishable from an empty sealed broadcast
        _send_refusal(conn, out)
        if _OBS.on:
            _emit("sidecar.session", **out)
        return out
    try:
        # bounded waits interleaved with an EOF probe on the (non-
        # blocking) socket: a subscriber that disconnects while the
        # broadcast is idle would otherwise never surface an EPIPE —
        # no bytes are in flight to it — and its peer slot plus this
        # thread would leak until new bytes happened to flow
        done = False
        not_source = False
        while True:
            if peer.wait_done(timeout=0.5):
                done = True
                break
            if peer.shed_reason is not None:
                break
            try:
                # bounded: the fd is O_NONBLOCK (attach_peer's dup
                # shares the open file description, and the fan-out
                # flips it for its writev path) — a silent subscriber
                # answers EAGAIN immediately, never a sleeping read
                # datlint: allow-blocking-reachable(socket)
                probe = conn.recv(4096)
            except (BlockingIOError, InterruptedError):
                continue  # still connected, nothing sent (the normal)
            except OSError:
                break
            if probe == b"":
                break  # client went away: release the slot
            # a subscriber has nothing to say — inbound bytes mean a
            # SOURCE got routed here (it raced a connection holding
            # the source claim).  Fail LOUDLY with a structured record
            # instead of silently discarding its uploaded session.
            not_source = True
            break
        stats = peer.stats()
    finally:
        peer.close()
    if not_source:
        out = {"fanout_peer": key, "ok": False, "not_source": True,
               "detail": "subscriber connections must not send data; "
                         "the broadcast source slot was already claimed "
                         "— reconnect to retry as source"}
        _send_refusal(conn, out)
        if _OBS.on:
            _emit("sidecar.session", **out)
        return out
    try:
        conn.shutdown(socket.SHUT_WR)  # subscriber observes clean EOF
    except OSError:
        pass
    out = {"fanout_peer": key, "sent_bytes": stats["sent_bytes"],
           "shed": stats["shed"], "ok": done and stats["shed"] is None}
    if _OBS.on:
        _M_SESSIONS.inc()
        _emit("sidecar.session", **out)
    return out


def run_reconcile_session(conn_read, conn_write, close_write,
                          replica, peer: str = "?") -> dict:
    """Serve one anti-entropy session (ISSUE 10): the client is the
    reconcile *initiator* streaming coded-symbol frames; this side
    responds from ``replica`` (the ``--reconcile LOGFILE`` change log)
    and the two exchange exactly the differing records.  Connecting to
    a ``--reconcile`` sidecar IS the out-of-band capability
    advertisement (WIRE.md): both directions speak
    ``CAP_RECONCILE | CAP_CHANGE_BATCH``.

    A failed decode (corrupt stream, exhausted symbols) surfaces as the
    driver's ONE structured ProtocolError; the client observes the FAIL
    frame + EOF, never a hang."""
    from .runtime.reconcile_driver import run_responder
    from .wire.framing import ProtocolError

    try:
        stats = run_responder(replica, conn_read, conn_write,
                              close_write=close_write)
        out = {"reconcile": True, "ok": stats["ok"],
               "symbols": stats["symbols"], "rounds": stats["rounds"],
               "records_sent": stats["records_sent"],
               "records_received": len(stats["received"])}
    except (ProtocolError, OSError) as e:
        out = {"reconcile": True, "ok": False, "peer": peer,
               "error": f"{type(e).__name__}: {e}"}
    if _OBS.on:
        _M_SESSIONS.inc()
        _emit("sidecar.session", **out)
    return out


def run_replica_session(conn_read, conn_write, close_write,
                        node, peer: str = "?") -> dict:
    """Serve one gossip responder session (ISSUE 15): like
    ``--reconcile``, but against the LIVE :class:`~.cluster.ReplicaNode`
    — records the initiator ships are absorbed into the node's log, so
    every inbound session advances convergence instead of answering
    from a frozen file."""
    from .cluster import serve_responder_session
    from .wire.framing import ProtocolError

    try:
        stats = serve_responder_session(node, conn_read, conn_write,
                                        close_write=close_write)
        out = {"replica": node.key, "ok": stats["ok"],
               "symbols": stats["symbols"], "rounds": stats["rounds"],
               "records_sent": stats["records_sent"],
               "applied": stats["applied"]}
    except (ProtocolError, OSError) as e:
        out = {"replica": node.key, "ok": False, "peer": peer,
               "error": f"{type(e).__name__}: {e}"}
    if _OBS.on:
        _M_SESSIONS.inc()
        _emit("sidecar.session", **out)
    return out


def load_replica_node(path: str, key: str):
    """Build the ``--replica`` gossip node from a change-log wire file
    (same input contract as ``--reconcile``; an absent/empty file is a
    cold replica that converges entirely from its peers)."""
    from .cluster import ReplicaNode

    wire = b""
    if os.path.exists(path):
        with open(path, "rb") as f:
            wire = f.read()
    # delivered_form: the live mesh's record identity is the per-record
    # DELIVERED materialization (absent optionals as ''/b'') — the form
    # every decoder delivery produces, so shipped records keep their
    # digests and the mesh actually reaches diff 0 (see ReplicaNode)
    return ReplicaNode(key, wire, delivered_form=True)


def load_reconcile_replica(path: str):
    """Build the sidecar's replica from a change-log wire file
    (per-record and/or ChangeBatch frames — ``replay.replay_log``'s
    input contract)."""
    from .runtime.reconcile_driver import RatelessReplica

    with open(path, "rb") as f:
        return RatelessReplica(f.read())


def run_snapshot_session(conn_read, conn_write, close_write,
                         source, peer: str = "?") -> dict:
    """Serve one snapshot bootstrap session (ISSUE 12): the client is a
    *joiner* — it receives the manifest, reconciles its chunk set (or
    WANTs everything when cold), and is streamed exactly the chunks it
    is missing from the shared :class:`~.runtime.snapshot_driver.
    SnapshotSource` (hashed ONCE, however many joiners connect).
    Connecting to a ``--snapshot`` sidecar IS the out-of-band
    capability advertisement (WIRE.md): both directions speak
    ``CAP_SNAPSHOT``.

    A failed session (corrupt stream, chunk budget, byzantine WANT)
    surfaces as the driver's ONE structured ProtocolError; the client
    observes the FAIL frame + EOF, never a hang."""
    from .runtime.snapshot_driver import run_snapshot_responder
    from .wire.framing import ProtocolError

    try:
        stats = run_snapshot_responder(source, conn_read, conn_write,
                                       close_write=close_write)
        out = {"snapshot": True, "ok": stats["ok"],
               "cold": stats["cold"], "chunks_sent": stats["chunks_sent"],
               "chunk_bytes_sent": stats["chunk_bytes_sent"],
               "symbols": stats["symbols"], "rounds": stats["rounds"]}
    except (ProtocolError, OSError) as e:
        out = {"snapshot": True, "ok": False, "peer": peer,
               "error": f"{type(e).__name__}: {e}"}
    if _OBS.on:
        _M_SESSIONS.inc()
        _emit("sidecar.session", **out)
    return out


def load_snapshot_source(path: str, wire_offset: int = 0):
    """Materialize the ``--snapshot DATAFILE`` dataset once: CDC cuts +
    fused digests + manifest, shared by every responder session
    (hash-once across the whole flash crowd)."""
    from .runtime.snapshot_driver import SnapshotSource

    with open(path, "rb") as f:
        return SnapshotSource(f.read(), wire_offset=wire_offset)


class SnapshotListener:
    """The dedicated snapshot bootstrap port (the ``--fanout`` +
    ``--snapshot`` composition): a tiny accept loop serving each
    connection as one responder session off the shared source.  The
    bound ``port`` rides the fan-out's ``snapshot_hint``, so the
    structured snapshot-needed record a trimmed-past subscriber gets
    names exactly where to bootstrap from."""

    def __init__(self, source, host: str, port: int = 0):
        self.source = source
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        # kernel-bounded accept (ISSUE 17 satellite): the periodic
        # socket.timeout below re-checks liveness instead of parking
        # the accept thread forever on a silent listener
        self._srv.settimeout(1.0)
        self.port = self._srv.getsockname()[1]
        self._served = 0
        self._thread = threading.Thread(
            target=self._loop, name="sidecar-snapshot", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            try:
                # bounded by the settimeout(1.0) set at construction
                # datlint: allow-blocking-reachable(socket)
                conn, peer = self._srv.accept()
            except socket.timeout:
                continue  # periodic liveness re-check
            except OSError:
                return  # closed: the daemon is shutting down
            self._served += 1
            n = self._served

            def _one(conn=conn, peer=peer, n=n):
                try:
                    rd, wr = session_pump.io_for_socket(conn)
                    stats = run_snapshot_session(
                        rd, wr,
                        lambda: conn.shutdown(socket.SHUT_WR),
                        self.source, peer=f"{peer[0]}:{peer[1]}")
                    print(f"sidecar: snapshot {peer} {stats}",
                          file=sys.stderr, flush=True)
                finally:
                    conn.close()

            threading.Thread(target=_one, name=f"sidecar-snap-{n}",
                             daemon=True).start()

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass


def serve_stdio(drain_timeout: float | None = DEFAULT_DRAIN_TIMEOUT) -> dict:
    """One session over stdin/stdout (logs go to stderr only)."""
    # close_write can fire from the session thread (drain-timeout
    # teardown) while the sender thread sits mid-write on fd 1, so a
    # bare os.close(1) has a reuse hazard: once fd 1 is free, any
    # thread's next open() can be handed 1, and _write_all's
    # partial-write retry loop would then write reply bytes into an
    # unrelated descriptor.  dup2 of /dev/null atomically releases the
    # pipe write end (the reader still sees EOF) while keeping fd 1
    # occupied — a late retry write lands in /dev/null instead.  A
    # writer currently blocked in write(2) is NOT woken by this (unlike
    # the TCP twin's shutdown-EPIPE); it unblocks only when the peer
    # reads or exits, which the bounded drain join tolerates.  Once-only
    # (transport.once) so the second caller (send_over's finally)
    # doesn't reopen devnull.
    from .session.transport import once

    def _swap_stdout_for_devnull() -> None:
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, 1)
        os.close(devnull)

    _close_stdout = once(_swap_stdout_for_devnull)

    stats = run_session(
        read_bytes=lambda n: os.read(0, n),
        write_bytes=lambda d: _write_all(1, d),
        close_write=_close_stdout,
        drain_timeout=drain_timeout,
        rx_fd=0, tx_fd=1,
    )
    print(f"sidecar: stdio session {stats}", file=sys.stderr, flush=True)
    return stats




def serve_tcp(host: str, port: int,
              max_sessions: int | None = None,
              ready_cb=None,
              drain_timeout: float | None = DEFAULT_DRAIN_TIMEOUT,
              retry_policy=None, hub=None, fanout=None,
              reconcile_replica=None, snapshot_source=None,
              replica_node=None) -> None:
    """Accept loop: one concurrent session per connection.

    ``max_sessions`` bounds the loop for tests; ``ready_cb(port)`` fires
    once the socket is bound+listening (the test/race-free handshake).

    ``hub`` (ISSUE 8): a shared :class:`~.hub.ReplicationHub` every
    accepted session registers with — one device pipeline multiplexed
    across all concurrent connections, admission-controlled, with
    per-session keys ``c<n>:<peer>`` in the stats breakdown.

    ``fanout`` (ISSUE 9): a shared :class:`~.fanout.FanoutServer`.  The
    first connection to CLAIM the source slot is the broadcast
    *source*: it is served like any normal session (decoded once —
    with ``hub`` set its digest work rides the shared engine — and its
    digest reply streamed back), while every wire byte it sends is
    also published into the shared :class:`~.fanout.BroadcastLog`.  A
    claimant that closes without publishing a byte (healthcheck, port
    scan) RELEASES the claim — the next connection can be the source.
    Every other connection is a subscriber: it receives the source's
    raw wire bytes via the zero-copy windowed ``writev`` fan-out path,
    keyed ``p<n>:<peer>`` in the stats breakdown.  Digest/hash cost is
    O(1) in subscribers.

    ``retry_policy`` (a :class:`~.session.reconnect.BackoffPolicy`, CLI
    flags ``--max-retries`` / ``--backoff-base``) governs the daemon's
    transient-failure behavior: binding retries through a lingering
    ``EADDRINUSE`` (the restart-while-old-socket-drains race) and the
    accept loop rides out bursts of ``EMFILE``/``ECONNABORTED`` with
    backoff instead of crashing the daemon; sustained failure surfaces
    as one structured ProtocolError (see ROBUSTNESS.md).
    """
    from .session.reconnect import BackoffPolicy, retrying

    policy = retry_policy if retry_policy is not None else BackoffPolicy()

    def _bind() -> socket.socket:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind((host, port))
            s.listen(8)
        except OSError:
            s.close()
            raise
        return s

    srv = retrying(_bind, policy, retry_on=(OSError,),
                   describe=f"bind {host}:{port}")
    # fan-out source election: the source slot is CLAIMED, not simply
    # "connection #1" — a stray first connection that closes without
    # publishing a byte (load-balancer healthcheck, port scan) releases
    # the claim instead of sealing an empty log and bricking the
    # broadcast for the daemon's lifetime
    src_claim = {"taken": False}
    src_lock = threading.Lock()
    bound = srv.getsockname()[1]
    print(f"sidecar: listening on {host}:{bound}",
          file=sys.stderr, flush=True)
    if ready_cb is not None:
        # one-shot bound-port handshake, fired BEFORE any session
        # exists — a slow callback delays startup, never a session
        # datlint: allow-callback-escape
        ready_cb(bound)
    served = 0
    try:
        while max_sessions is None or served < max_sessions:
            # transient accept failures (fd exhaustion, aborted
            # handshakes) back off instead of killing the daemon; each
            # retrying() call is one fresh consecutive-failure budget,
            # so a successful accept resets the count
            conn, peer = retrying(srv.accept, policy, retry_on=(OSError,),
                                  describe="accept")
            served += 1

            def _one(conn=conn, peer=peer, n=served):
                try:
                    if snapshot_source is not None:
                        # bootstrap mode (ISSUE 12): every connection is
                        # one joiner served off the shared materialized
                        # source (read-only after construction: sessions
                        # never step on each other, hashing happened
                        # once).  The --fanout composition does NOT pass
                        # this — there the snapshot protocol lives on
                        # its own SnapshotListener port and this loop
                        # keeps serving the broadcast.
                        rd, wr = session_pump.io_for_socket(conn)
                        stats = run_snapshot_session(
                            rd, wr,
                            lambda: conn.shutdown(socket.SHUT_WR),
                            snapshot_source,
                            peer=f"{peer[0]}:{peer[1]}")
                        print(f"sidecar: {peer} {stats}", file=sys.stderr,
                              flush=True)
                        return
                    if replica_node is not None:
                        # gossip replica mode (ISSUE 15): every
                        # connection is one reconcile initiator against
                        # the LIVE node — received records are absorbed,
                        # so inbound sessions advance convergence
                        rd, wr = session_pump.io_for_socket(conn)
                        stats = run_replica_session(
                            rd, wr,
                            lambda: conn.shutdown(socket.SHUT_WR),
                            replica_node,
                            peer=f"{peer[0]}:{peer[1]}")
                        print(f"sidecar: {peer} {stats}", file=sys.stderr,
                              flush=True)
                        return
                    if reconcile_replica is not None:
                        # anti-entropy mode (ISSUE 10): every connection
                        # is one reconcile initiator against the shared
                        # replica (read-only state: sessions never step
                        # on each other)
                        rd, wr = session_pump.io_for_socket(conn)
                        stats = run_reconcile_session(
                            rd, wr,
                            lambda: conn.shutdown(socket.SHUT_WR),
                            reconcile_replica,
                            peer=f"{peer[0]}:{peer[1]}")
                        print(f"sidecar: {peer} {stats}", file=sys.stderr,
                              flush=True)
                        return
                    is_source = False
                    if fanout is not None and not fanout.log.sealed:
                        with src_lock:
                            if not src_claim["taken"]:
                                src_claim["taken"] = True
                                is_source = True
                    if fanout is not None and not is_source:
                        stats = run_subscriber(
                            conn, fanout, key=f"p{n}:{peer[0]}:{peer[1]}")
                    elif fanout is not None:
                        # the source session: every wire byte it sends
                        # is published into the broadcast log as it is
                        # consumed (the pump's tap on either route);
                        # EOF (or teardown) seals the log so
                        # subscribers complete
                        try:
                            stats = run_session(
                                read_bytes=conn.recv,
                                write_bytes=conn.sendall,
                                close_write=lambda: conn.shutdown(
                                    socket.SHUT_WR),
                                drain_timeout=drain_timeout,
                                hub=hub,
                                session_key=f"c{n}:{peer[0]}:{peer[1]}",
                                rx_fd=conn.fileno(), tx_fd=conn.fileno(),
                                publish=fanout.publish,
                            )
                        finally:
                            if fanout.log.end > fanout.log.start:
                                fanout.seal()
                            else:
                                # nothing published: a probe connection,
                                # not the feed — give the slot back
                                with src_lock:
                                    src_claim["taken"] = False
                    else:
                        stats = run_session(
                            read_bytes=conn.recv,
                            write_bytes=conn.sendall,
                            close_write=lambda: conn.shutdown(
                                socket.SHUT_WR),
                            drain_timeout=drain_timeout,
                            hub=hub,
                            session_key=f"c{n}:{peer[0]}:{peer[1]}",
                            rx_fd=conn.fileno(), tx_fd=conn.fileno(),
                        )
                    print(f"sidecar: {peer} {stats}", file=sys.stderr,
                          flush=True)
                finally:
                    conn.close()

            threading.Thread(target=_one, name=f"sidecar-{peer}",
                             daemon=True).start()
    finally:
        srv.close()


class StatsEmitter:
    """Periodic registry snapshots on a file descriptor.

    The ``--stats-fd`` machinery: a daemon thread dumps one snapshot
    every ``interval`` seconds; :meth:`kick` forces an immediate dump
    (the SIGUSR1 one-shot — the handler just sets an event, so the dump
    work never runs in signal context).  ``fmt="json"`` (default)
    writes self-contained JSON lines, so a supervisor can ``tail -f``
    the pipe and parse each line independently; ``fmt="prom"``
    (``--stats-format prom``) writes Prometheus text-exposition blocks
    (``obs.metrics.to_prom_text``) instead — each dump is one complete
    scrape body, for a node-exporter-style textfile collector.
    """

    def __init__(self, fd: int, interval: float = DEFAULT_STATS_INTERVAL,
                 fmt: str = "json"):
        if fmt not in ("json", "prom"):
            raise ValueError(f"unknown stats format {fmt!r}")
        self._fd = fd
        # the EAGAIN/deadline machinery in dump_once only ever engages
        # on a NONBLOCKING fd: on a blocking pipe with a stopped
        # consumer, os.write parks the emitter thread forever (stop()
        # then reports False and the process leaks the thread).  Flip
        # the fd up front so the 2 s grace bound is real — the
        # blocking-reachability certifier's second true positive.
        try:
            os.set_blocking(fd, False)
        except OSError:
            pass  # closed/odd fd: the first write will surface it
        self._fmt = fmt
        self._interval = interval
        self._wake = threading.Event()
        self._stopped = False
        self._dead = False  # fd failed or a line tore: never write again
        # monotonic per-emitter line sequence (ISSUE 11): every dump
        # ATTEMPT consumes a number, so a file-based fleet target can
        # detect dropped lines (EAGAIN skip, torn-line latch) as seq
        # gaps instead of silently reading a thinner history
        self._emit_seq = 0
        self._thread = threading.Thread(
            target=self._run, name="sidecar-stats", daemon=True)

    def start(self) -> "StatsEmitter":
        self._thread.start()
        return self

    def kick(self) -> None:
        """Request an immediate snapshot dump (signal-safe: only sets
        an event; the emitter thread does the I/O)."""
        self._wake.set()

    def stop(self) -> bool:
        """Stop the emitter thread; returns True once it has actually
        exited.  False means it is still blocked (e.g. inside a write
        to a pipe nobody drains) — the caller must NOT write the fd
        itself then, or the two writers interleave past PIPE_BUF."""
        self._stopped = True
        self._wake.set()
        self._thread.join(timeout=5)
        return not self._thread.is_alive()

    def dump_once(self) -> bool:
        """Write one snapshot line now (from the calling thread);
        returns False when the fd is dead or persistently blocked.
        Once a record TORE (partial write, then the pipe stayed full
        past the grace period) the emitter latches dead: appending any
        later record to the torn fragment would merge two lines and
        break the one-JSON-object-per-line contract."""
        import errno

        if self._dead:
            return False
        seq = self._emit_seq
        self._emit_seq += 1
        if self._fmt == "prom":
            body = snapshot_stats_prom()
        else:
            snap = snapshot_stats()
            snap["emit_seq"] = seq
            body = json.dumps(snap) + "\n"
        line = body.encode("utf-8")
        view = memoryview(line)
        deadline = time.monotonic() + 2.0
        while view:
            try:
                # bounded: __init__ flipped the fd nonblocking, so this
                # either progresses or raises EAGAIN into the deadline
                # arm below.  datlint: allow-blocking-reachable(os-io)
                view = view[os.write(self._fd, view):]
            except OSError as e:
                # EAGAIN is a momentarily-full pipe, not a dead one: a
                # bounded retry finishes the record (a half-written
                # line would corrupt the JSONL stream).  Skip the tick
                # if nothing was written yet; a pipe still full after
                # the grace period counts as a dead consumer.
                if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                    if time.monotonic() < deadline:
                        time.sleep(0.01)
                        continue
                    if len(view) == len(line):
                        return True  # clean skip: nothing written yet
                self._dead = True  # torn line or hard error
                return False
        return True

    def _run(self) -> None:
        while not self._stopped:
            self._wake.wait(self._interval)
            self._wake.clear()
            if self._stopped:
                return
            if not self.dump_once():
                return  # consumer closed the stats pipe: stop quietly


def snapshot_stats() -> dict:
    """One self-describing stats record: the full metrics registry
    snapshot plus event-ring health and per-site jit-cache traffic
    (the recompile sentinel: a long-lived sidecar recompiling per
    request is the device-path pathology --stats-fd exists to catch).
    In hub mode the record also carries the per-session ``sessions``
    breakdown and the hub's aggregate state, keyed by session — the
    supervisor-visible answer to "which peer is parking bytes".
    JSON-able as-is."""
    out = {
        "ts": time.time(),
        "monotonic": time.monotonic(),
        "metrics": obs_metrics.snapshot(),
        "events_dropped": obs_events.EVENTS.dropped,
        "jit_sites": obs_device.SENTINEL.snapshot(),
        # the fleet plane's join input (ISSUE 11): per-link wire
        # cursors + append marks — the SAME dict /snapshot serves
        "watermarks": _WATERMARKS.snapshot(),
        # the active wire-pump route + syscall tier (ISSUE 14): which
        # byte mover this daemon's sessions actually ride
        "pump": session_pump.probe_caps(),
    }
    if _ACTIVE_HUB is not None:
        out["hub"] = _ACTIVE_HUB.snapshot()
        out["sessions"] = _ACTIVE_HUB.sessions_snapshot()
    if _ACTIVE_FANOUT is not None:
        out["fanout"] = _ACTIVE_FANOUT.snapshot()
        out["peers"] = _ACTIVE_FANOUT.peers_snapshot()
    if _ACTIVE_GOSSIP is not None:
        # replica mode (ISSUE 15): gossip round / repair / quarantine
        # counters + the content digest — what `obs fleet` derives the
        # per-replica rounds-behind convergence column from
        out["gossip"] = _ACTIVE_GOSSIP.snapshot()
        # the mesh convergence plane (ISSUE 19): per-link exchange
        # provenance + divergence watermarks + frontier — the fleet
        # matrix join input.  Empty boards (plane dark) are omitted so
        # the loud-failure rule in `obs fleet` can tell "plane off"
        # from "no exchanges yet".
        prop = _PROPAGATION.snapshot()
        if prop["links"] or prop["frontier"]:
            out["propagation"] = prop
    # the wire cost plane (ISSUE 20): per-link byte ledger + goodput /
    # overhead / amplification watermarks.  Presence-gated like the
    # propagation board above — an empty ledger (plane dark, or lit but
    # no traffic yet) is omitted entirely, so `obs fleet` can apply the
    # loud-failure rule to cost SLO keys instead of averaging zeros.
    wc = _WIRECOST.snapshot()
    if wc["links"] or wc["amplification"]:
        out["wirecost"] = wc
    if _ACTIVE_EDGE is not None:
        # edge mode (ISSUE 17): the unified session-table aggregate —
        # per-QoS-class and per-kind session counts, admission/shed
        # tallies, the active pump route
        out["edge"] = _ACTIVE_EDGE.snapshot()
    # staged health rides every snapshot record, so file-based fleet
    # targets (tailing --stats-fd lines) can evaluate require_healthz
    # — not just endpoint targets with a /healthz route
    out["healthz"] = obs_http.default_healthz(_active_admission_fn())
    return out


def _active_admission_fn():
    """The lock-free admission view of whichever shared engine this
    daemon runs.  The edge wins when set (ISSUE 17): its admission
    stage COMPOSES the hub's (edge table state + the hub's open/parked
    verdict), so /healthz reports the decision connections actually
    face; otherwise hub wins over fanout (fanout composes with it as
    the broadcast layer, admission is the hub's)."""
    if _ACTIVE_EDGE is not None:
        return _ACTIVE_EDGE.admission_state
    if _ACTIVE_HUB is not None:
        return _ACTIVE_HUB.admission_state
    if _ACTIVE_FANOUT is not None:
        return _ACTIVE_FANOUT.admission_state
    return None


def snapshot_stats_prom() -> str:
    """The same stats record in Prometheus text exposition: the
    registry via ``to_prom_text`` plus ring-health gauges."""
    extra = (
        "# TYPE dat_obs_events_dropped gauge\n"
        f"dat_obs_events_dropped {obs_events.EVENTS.dropped}\n"
        "# TYPE dat_obs_spans_dropped gauge\n"
        f"dat_obs_spans_dropped {obs_tracing.SPANS.dropped}\n"
        "# TYPE dat_obs_scrape_ts gauge\n"
        f"dat_obs_scrape_ts {time.time()}\n"
    )
    return obs_metrics.to_prom_text() + extra


def _install_sigusr1(emitter: StatsEmitter) -> bool:
    """SIGUSR1 -> one-shot stats dump; returns False when not on the
    main thread (signal registration would raise there)."""
    import signal

    if threading.current_thread() is not threading.main_thread():
        return False
    signal.signal(signal.SIGUSR1, lambda _sig, _frm: emitter.kick())
    return True


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m dat_replication_protocol_tpu.sidecar",
        description="dat replication wire-protocol digest sidecar",
    )
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--stdio", action="store_true",
                      help="serve ONE session over stdin/stdout")
    mode.add_argument("--tcp", metavar="HOST:PORT",
                      help="listen and serve a session per connection")
    p.add_argument("--backend", default="tpu", choices=("tpu", "host"),
                   help="digest engine routing: 'tpu' (default) lets the "
                        "routing layer pick device batches or the host "
                        "engine; 'host' forces the host engine.  Digests "
                        "are produced either way")
    p.add_argument("--drain-timeout", type=float,
                   default=DEFAULT_DRAIN_TIMEOUT, metavar="SECONDS",
                   help="tear a session down when its reply stream makes "
                        "no progress for this long (a client that stops "
                        "reading); <= 0 waits forever "
                        f"(default: {DEFAULT_DRAIN_TIMEOUT:.0f})")
    p.add_argument("--edge", action="store_true",
                   help="event-driven edge (ISSUE 17, --tcp only): serve "
                        "every leg — hub sessions, --fanout broadcast "
                        "peers, --reconcile/--snapshot responders, "
                        "--replica gossip exchanges — from ONE epoll "
                        "session table instead of a thread per "
                        "connection (C10k), with the staged overload "
                        "ladder preserved verbatim; implies --hub for "
                        "session/broadcast-source legs (see DESIGN.md "
                        "event-driven edge)")
    p.add_argument("--hub", action="store_true",
                   help="multiplex every accepted session onto ONE shared "
                        "device engine (hub mode, --tcp only): cross-"
                        "session digest batching, admission control, "
                        "per-session QoS windows, load shedding (see "
                        "ROBUSTNESS.md overload behavior)")
    p.add_argument("--hub-max-sessions", type=int, default=1024,
                   metavar="N",
                   help="hub admission bound: concurrent session count "
                        "past which new connections get a structured "
                        "rejection (default: 1024)")
    p.add_argument("--hub-parked-budget", type=int, default=256 << 20,
                   metavar="BYTES",
                   help="hub admission + shedding bound on global parked "
                        "bytes (queued + in-flight + undelivered work; "
                        "default: 256 MiB)")
    p.add_argument("--hub-mesh", default=None, metavar="N|auto",
                   help="shard the hub's cross-session hash batch over "
                        "the device mesh: 'auto' uses every local "
                        "device, an integer pins the count (default: "
                        "single-device engine)")
    p.add_argument("--fanout", action="store_true",
                   help="broadcast mode (--tcp only): the FIRST "
                        "connection is the source session (decoded and "
                        "digested ONCE); every later connection is a "
                        "subscriber streamed the source's wire bytes "
                        "via the zero-copy windowed writev fan-out "
                        "(see DESIGN.md fan-out, ROBUSTNESS.md "
                        "peer-shed contract)")
    p.add_argument("--fanout-retention", type=int, default=64 << 20,
                   metavar="BYTES",
                   help="broadcast-log retention budget: how much wire "
                        "history stays servable for late joiners and "
                        "laggards; a peer trimmed past gets a "
                        "structured snapshot-needed record "
                        "(default: 64 MiB)")
    p.add_argument("--fanout-window", type=int, default=1 << 20,
                   metavar="BYTES",
                   help="per-peer fan-out flow-control window (bytes "
                        "in flight; sized for lossy high-latency "
                        "links; default: 1 MiB)")
    p.add_argument("--fanout-stall-timeout", type=float, default=30.0,
                   metavar="SECONDS",
                   help="shed a fan-out peer making no delivery "
                        "progress for this long (default: 30)")
    p.add_argument("--reconcile", metavar="LOGFILE", default=None,
                   help="anti-entropy mode: serve every connection as a "
                        "rateless-reconciliation responder against the "
                        "change-log wire file LOGFILE — the client "
                        "streams coded symbols, both sides exchange "
                        "exactly the differing records (O(diff) wire "
                        "bytes; see DESIGN.md anti-entropy, WIRE.md "
                        "Reconcile)")
    p.add_argument("--replica", metavar="LOGFILE", default=None,
                   help="gossip replica mode (ISSUE 15, --tcp only): "
                        "serve every connection as a live anti-entropy "
                        "responder whose received records are ABSORBED "
                        "into the replica (unlike --reconcile's frozen "
                        "file), and — with --gossip-peers — dial out on "
                        "a jittered timer so N such sidecars converge "
                        "from any divergence with no distinguished "
                        "source (see DESIGN.md gossip, ROBUSTNESS.md "
                        "convergence contract)")
    p.add_argument("--replica-key", default="replica", metavar="KEY",
                   help="this replica's name in gossip telemetry "
                        "(default: replica)")
    p.add_argument("--gossip-peers", default=None, metavar="HOST:PORT,...",
                   help="comma list of peer --replica sidecars to "
                        "gossip with (requires --replica)")
    p.add_argument("--gossip-interval", type=float, default=1.0,
                   metavar="SECONDS",
                   help="mean seconds between gossip dials (jittered "
                        "full-spread via BackoffPolicy; consecutive "
                        "all-peer failures back off; default: 1)")
    p.add_argument("--snapshot", metavar="DATAFILE", default=None,
                   help="snapshot bootstrap mode (ISSUE 12): materialize "
                        "DATAFILE once as content-addressed CDC chunks "
                        "and serve every connection as a snapshot "
                        "responder — a stale joiner reconciles its chunk "
                        "set first and moves O(diff) bytes, a cold one "
                        "streams the shared full-manifest log.  With "
                        "--fanout the protocol is served on its own "
                        "--snapshot-port and the structured "
                        "snapshot-needed record carries the redirect "
                        "hint (see WIRE.md Snapshot, DESIGN.md "
                        "bootstrap)")
    p.add_argument("--snapshot-port", type=int, default=0, metavar="PORT",
                   help="dedicated snapshot listener port for the "
                        "--fanout composition (default: 0 = ephemeral; "
                        "the bound port rides the snapshot-needed "
                        "hint)")
    p.add_argument("--snapshot-offset", type=int, default=0,
                   metavar="BYTES",
                   help="live-log wire offset the --snapshot dataset "
                        "materializes — where an assembled joiner "
                        "attaches its live session (default: 0)")
    p.add_argument("--max-retries", type=int, default=5, metavar="N",
                   help="transient-failure budget: bind/accept errors are "
                        "retried with backoff at most N times before the "
                        "daemon fails with a structured error (default: 5)")
    p.add_argument("--backoff-base", type=float, default=0.05,
                   metavar="SECONDS",
                   help="base of the exponential-backoff-with-full-jitter "
                        "retry delay: attempt k sleeps uniform(0, "
                        "min(cap, base * 2^k)) (default: 0.05)")
    p.add_argument("--stats-fd", type=int, default=None, metavar="FD",
                   help="enable telemetry and write one JSON metrics "
                        "snapshot line to this file descriptor every "
                        "--stats-interval seconds; SIGUSR1 forces an "
                        "immediate one-shot dump (see OBSERVABILITY.md)")
    p.add_argument("--stats-interval", type=float,
                   default=DEFAULT_STATS_INTERVAL, metavar="SECONDS",
                   help="period between --stats-fd snapshots "
                        f"(default: {DEFAULT_STATS_INTERVAL:.0f})")
    p.add_argument("--stats-format", choices=("json", "prom"),
                   default="json",
                   help="--stats-fd output format: self-contained JSON "
                        "lines (default) or Prometheus text exposition "
                        "blocks (obs.metrics.to_prom_text)")
    p.add_argument("--obs-http", type=int, default=None, metavar="PORT",
                   help="enable telemetry and serve the read-only scrape "
                        "endpoint on 127.0.0.1:PORT — /metrics (Prometheus "
                        "text), /snapshot (the --stats-fd JSON record), "
                        "/healthz (staged health, 503 when degraded), "
                        "/events (bounded JSONL tail); 0 binds an "
                        "ephemeral port (see OBSERVABILITY.md fleet plane)")
    p.add_argument("--flight-dir", metavar="DIR", default=None,
                   help="arm the flight recorder: on any protocol error "
                        "or retry exhaustion, dump an atomic post-mortem "
                        "bundle (event/span rings, metrics, checkpoint) "
                        "into DIR for offline attribution (enables "
                        "telemetry; see OBSERVABILITY.md)")
    p.add_argument("--trace-jsonl", metavar="PATH", default=None,
                   help="enable telemetry and mirror every event AND "
                        "wire-offset span as JSONL into PATH — the "
                        "per-peer log `python -m "
                        "dat_replication_protocol_tpu.obs timeline` "
                        "merges")
    args = p.parse_args(argv)
    drain = args.drain_timeout if args.drain_timeout > 0 else None
    from .session.reconnect import BackoffPolicy

    policy = BackoffPolicy(base=args.backoff_base,
                           max_retries=args.max_retries)
    emitter = None
    trace_sink = None
    if args.flight_dir:
        # arming enables telemetry: a dark ring has nothing to dump
        obs_flight.FLIGHT.arm(args.flight_dir)
    if args.trace_jsonl:
        obs_metrics.enable()
        trace_sink = obs_tracing.attach_jsonl_sink(args.trace_jsonl)
    if args.stats_fd is not None:
        obs_metrics.enable()  # --stats-fd IS the telemetry opt-in
        emitter = StatsEmitter(args.stats_fd, args.stats_interval,
                               fmt=args.stats_format).start()
        _install_sigusr1(emitter)
    if args.backend == "host":
        os.environ["DAT_DEVICE_HASH"] = "0"  # routing-layer override:
        # force the host digest engine for this daemon's lifetime
    if args.snapshot and (args.hub or args.reconcile):
        p.error("--snapshot cannot combine with --hub/--reconcile "
                "(it composes with --fanout, where it answers the "
                "broadcast's snapshot-needed refusals)")
    if args.replica and (args.hub or args.fanout or args.reconcile
                         or args.snapshot):
        p.error("--replica is its own session mode; it cannot combine "
                "with --hub/--fanout/--reconcile/--snapshot")
    if args.replica and args.stdio:
        p.error("--replica gossips with many peers; it needs --tcp")
    if args.gossip_peers and not args.replica:
        p.error("--gossip-peers requires --replica")
    if args.edge and args.stdio:
        p.error("--edge is the event-driven TCP front; it needs --tcp")
    hub = None
    if args.edge and not args.hub and not (args.reconcile or args.replica
                                           or args.snapshot):
        # --edge implies --hub for session legs: the unified table's
        # hub sessions ride the shared engine's admission/window/shed
        # ladder — without a hub there is no stage to preserve
        args.hub = True
    if args.hub:
        if args.stdio:
            p.error("--hub multiplexes many connections; it needs --tcp")
        from .hub import ReplicationHub

        mesh = args.hub_mesh
        if mesh is not None and mesh != "auto":
            mesh = int(mesh)
        hub = ReplicationHub(mesh=mesh,
                             max_sessions=args.hub_max_sessions,
                             parked_budget=args.hub_parked_budget)
        set_active_hub(hub)
    fanout = None
    if args.fanout:
        if args.stdio:
            p.error("--fanout broadcasts to many connections; it needs "
                    "--tcp")
        from .fanout import FanoutServer

        fanout = FanoutServer(
            retention_budget=args.fanout_retention,
            window_bytes=args.fanout_window,
            stall_timeout=args.fanout_stall_timeout)
        set_active_fanout(fanout)
    replica = None
    if args.reconcile:
        if args.hub or args.fanout:
            p.error("--reconcile is its own session mode; it cannot "
                    "combine with --hub/--fanout")
        replica = load_reconcile_replica(args.reconcile)
    replica_node = None
    gossip_driver = None
    if args.replica:
        replica_node = load_replica_node(args.replica, args.replica_key)
        if args.gossip_peers:
            from .cluster import GossipDriver

            gossip_driver = GossipDriver(
                replica_node,
                [p_.strip() for p_ in args.gossip_peers.split(",")],
                interval=args.gossip_interval).start()
            set_active_gossip(gossip_driver)
        else:
            set_active_gossip(replica_node)
    snapshot_source = None
    if args.snapshot:
        snapshot_source = load_snapshot_source(
            args.snapshot, wire_offset=args.snapshot_offset)
    obs_srv = None
    if args.obs_http is not None:
        obs_metrics.enable()  # a dark endpoint would serve zeros
        obs_srv = obs_http.ObsHttpServer(
            args.obs_http, snapshot_fn=snapshot_stats,
            admission_fn=_active_admission_fn()).start()
        print(f"sidecar: obs endpoint on {obs_srv.url}",
              file=sys.stderr, flush=True)
    snap_listener = None
    try:
        if args.stdio:
            if snapshot_source is not None:
                from .session.transport import once

                def _swap_stdout_snap() -> None:
                    devnull = os.open(os.devnull, os.O_WRONLY)
                    os.dup2(devnull, 1)
                    os.close(devnull)

                stats = run_snapshot_session(
                    lambda n: os.read(0, n),
                    lambda d: _write_all(1, d),
                    once(_swap_stdout_snap), snapshot_source,
                    peer="stdio")
                print(f"sidecar: stdio session {stats}", file=sys.stderr,
                      flush=True)
                return 0 if stats["ok"] else 1
            if replica is not None:
                from .session.transport import once

                def _swap_stdout() -> None:
                    devnull = os.open(os.devnull, os.O_WRONLY)
                    os.dup2(devnull, 1)
                    os.close(devnull)

                stats = run_reconcile_session(
                    lambda n: os.read(0, n),
                    lambda d: _write_all(1, d),
                    once(_swap_stdout), replica, peer="stdio")
                print(f"sidecar: stdio session {stats}", file=sys.stderr,
                      flush=True)
                return 0 if stats["ok"] else 1
            stats = serve_stdio(drain_timeout=drain)
            return 0 if stats["ok"] else 1
        host, _, port = args.tcp.rpartition(":")
        host = host or "127.0.0.1"
        if fanout is not None and snapshot_source is not None:
            # the composition (ISSUE 12): snapshot sessions get their
            # own port; the broadcast's snapshot-needed refusals carry
            # the redirect hint to it
            from .wire.framing import CAP_SNAPSHOT

            snap_listener = SnapshotListener(
                snapshot_source, host, args.snapshot_port)
            fanout.snapshot_hint = {"port": snap_listener.port,
                                    "cap": CAP_SNAPSHOT}
            print(f"sidecar: snapshot bootstrap on "
                  f"{host}:{snap_listener.port}",
                  file=sys.stderr, flush=True)
            snapshot_source = None  # the main loop keeps broadcasting
        if args.edge:
            from .edge import EdgeLoop

            edge_loop = EdgeLoop(
                hub, fanouts={"main": fanout} if fanout else None,
                reconcile_replica=replica,
                snapshot_source=snapshot_source,
                replica_node=replica_node, drain_timeout=drain,
                # a stable per-process loop label: the fleet joins
                # edge.loop.lag{loop=} across targets by this name
                name=f"edge:{host}:{int(port)}")
            set_active_edge(edge_loop)
            try:
                edge_loop.bind(host, int(port))
                edge_loop.serve()
            finally:
                set_active_edge(None)
            return 0
        serve_tcp(host, int(port), drain_timeout=drain,
                  retry_policy=policy, hub=hub, fanout=fanout,
                  reconcile_replica=replica,
                  snapshot_source=snapshot_source,
                  replica_node=replica_node)
        return 0
    finally:
        if gossip_driver is not None:
            gossip_driver.close()
        if replica_node is not None:
            set_active_gossip(None)
        if snap_listener is not None:
            snap_listener.close()
        if obs_srv is not None:
            obs_srv.close()
        if fanout is not None:
            set_active_fanout(None)
            fanout.close()
        if hub is not None:
            set_active_hub(None)
            hub.close()
        if emitter is not None and emitter.stop():
            # final snapshot — ONLY once the periodic thread really
            # exited: two concurrent writers on one fd can interleave
            # past PIPE_BUF and corrupt the one-JSON-object-per-line
            # contract (an emitter still blocked on a never-drained
            # pipe keeps sole ownership of the fd instead)
            emitter.dump_once()
        if trace_sink is not None:
            obs_events.EVENTS.detach_sink()
            obs_tracing.SPANS.detach_sink()
            trace_sink.close()


if __name__ == "__main__":
    sys.exit(main())
