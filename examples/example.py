"""Canonical end-to-end wiring of both session ends in one process.

Python analogue of the reference's example (reference: example.js:1-53):
two changes, an 11-byte blob written in two chunks, a third change whose
flush callback fires when the consumer pulls it, and a decoder printing
everything it receives.  Run with::

    python examples/example.py
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import dat_replication_protocol_tpu as protocol

encode = protocol.encode()
decode = protocol.decode()

encode.change({"key": "lol1", "change": 1, "from_": 0, "to": 1, "value": b"val"})
encode.change({"key": "lol", "change": 1, "from_": 0, "to": 1, "value": b"val"})

b1 = encode.blob(11, on_flush=lambda: print("blob was flushed"))
b1.write(b"hello ")
b1.end(b"world")

encode.change(
    {"key": "lol", "change": 1, "from_": 0, "to": 1, "value": b"val"},
    on_flush=lambda: print("change was flushed"),
)


def on_change(change, done):
    print(change)
    done()


def on_blob(blob, done):
    blob.on_data(lambda data: print(data))
    blob.on_end(done)


decode.change(on_change)
decode.blob(on_blob)

encode.finalize()
protocol.pipe(encode, decode)
