"""A foreign client piping into the sidecar daemon.

The "client" below writes RAW wire bytes to a TCP socket — no package
Encoder — exactly what a non-Python process speaking the dat
replication wire format would send (the reference's deployment shape,
reference: example.js:53 `encode.pipe(socket)`).  The sidecar decodes
the session, content-hashes the change payload and the blob through
the routed digest engine, and streams a digest session back.

Run: python examples/example_sidecar.py
"""

import socket
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import dat_replication_protocol_tpu as protocol  # noqa: E402
from dat_replication_protocol_tpu import sidecar  # noqa: E402


def main() -> None:
    ready = threading.Event()
    port = {}
    threading.Thread(
        target=sidecar.serve_tcp,
        args=("127.0.0.1", 0),
        kwargs=dict(max_sessions=1,
                    ready_cb=lambda p: (port.__setitem__("p", p),
                                        ready.set())),
        daemon=True,
    ).start()
    ready.wait(10)

    # hand-framed wire bytes (varint(len+1) | id | payload):
    # one change {key:'key', change:1, from:0, to:1, value:'hello'}
    # and one 11-byte blob, as a foreign client would emit them
    change_payload = bytes.fromhex(
        "12036b6579" "1801" "2000" "2801" "320568656c6c6f")
    wire = (bytes([len(change_payload) + 1, 0x01]) + change_payload
            + bytes([0x0C, 0x02]) + b"hello world")

    c = socket.create_connection(("127.0.0.1", port["p"]), timeout=10)
    c.sendall(wire)
    c.shutdown(socket.SHUT_WR)
    raw = b""
    while True:
        d = c.recv(65536)
        if not d:
            break
        raw += d
    c.close()

    dec = protocol.decode()
    dec.change(lambda ch, done: (
        print(f"digest reply: {ch.key} ({ch.subset}) = "
              f"{ch.value.hex()[:16]}…"),
        done(),
    ))
    dec.write(raw)
    dec.end()
    assert dec.finished


if __name__ == "__main__":
    main()
