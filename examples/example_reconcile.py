"""Reconciling two divergent replicas with key-addressed sketches.

The reference delegates divergent-replica resume to dat core via the
Change.from/to version fields (reference: messages/schema.proto:4-5);
this framework reconciles in the data plane: each replica summarizes its
log into a key-addressed sketch on device, the sketches diff through the
Merkle tree, and only the records in differing cells are exchanged —
O(diff), independent of where inserts landed.

Run: JAX_PLATFORMS=cpu python examples/example_reconcile.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import os  # noqa: E402

import jax  # noqa: E402

# honor JAX_PLATFORMS even where a sitecustomize re-forces the device
# platform after env vars are read (jax.config wins over both)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
# repeat runs skip the multi-minute cold XLA compiles (CPU scanned path)
from dat_replication_protocol_tpu.utils.cache import (  # noqa: E402
    enable_compile_cache,
)

enable_compile_cache("examples")

from dat_replication_protocol_tpu.ops import reconcile  # noqa: E402


def main() -> None:
    keys_a = [b"row-%03d" % i for i in range(300)]
    records_a = [b"value-of:" + k for k in keys_a]

    # replica B diverged: an insert in the middle (misaligning every
    # later position), a delete, and a value flip
    keys_b = list(keys_a)
    records_b = list(records_a)
    keys_b.insert(140, b"row-new")
    records_b.insert(140, b"value-of:row-new")
    del keys_b[250], records_b[250]
    records_b[100] = records_b[100] + b"~updated"

    a = reconcile.LogSummary(records_a, keys_a, log2_slots=10)
    b = reconcile.LogSummary(records_b, keys_b, log2_slots=10)
    out = reconcile.reconcile(a, b)

    print(f"replica A: {len(keys_a)} records, B: {len(keys_b)} records")
    print(f"differing sketch cells: {len(out['slots'])}")
    print(f"A must send {len(out['a_keys'])} records: {out['a_keys'][:5]}...")
    print(f"B must send {len(out['b_keys'])} records: {out['b_keys'][:5]}...")


if __name__ == "__main__":
    main()
