"""Content-addressed blob sync: ship only the chunks an edit touched.

The dat workflow the wire protocol exists to serve (reference:
README.md:73 — blobs stream as content-addressed pieces): CDC chunk a
blob on device, BLAKE2b every chunk in batched dispatches, fold a Merkle
root, and after an edit exchange only the chunks the other side lacks.

Run: JAX_PLATFORMS=cpu python examples/example_content.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import os  # noqa: E402

import jax  # noqa: E402

# the dev image's sitecustomize re-forces the tunneled device platform
# after env vars are read (jax.config wins over both)
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402

from dat_replication_protocol_tpu.runtime import (  # noqa: E402
    content_address,
    delta,
    reassemble,
)


def main() -> None:
    rng = np.random.default_rng(0)
    v1 = rng.integers(0, 256, 1 << 18, dtype=np.uint8).tobytes()
    v2 = v1[:5000] + b"--edited--" + v1[5000:]  # insert near the front

    a = content_address(v1, avg_bits=10)
    b = content_address(v2, avg_bits=10)
    print(f"v1: {a.nchunks} chunks, root {a.root.hex()[:16]}…")
    print(f"v2: {b.nchunks} chunks, root {b.root.hex()[:16]}…")

    need = delta(a, b)
    offs, lens = b.extents()
    sent = {i: v2[int(offs[i]):int(offs[i]) + int(lens[i])] for i in need}
    moved = sum(len(p) for p in sent.values())
    print(
        f"delta: {len(need)}/{b.nchunks} chunks, {moved} bytes "
        f"({100 * moved / len(v2):.1f}% of the blob)"
    )
    assert reassemble(b, v1, a, sent) == v2
    print("receiver reassembled v2 from v1 + delta, digests verified")


if __name__ == "__main__":
    main()
