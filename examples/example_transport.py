"""The reference example over a REAL byte transport.

Same session as examples/example.py (reference: example.js), but the two
ends talk through an OS socketpair with pump threads — every byte
crosses the kernel, and backpressure propagates sender <- socket <-
decoder exactly as the reference's `encode.pipe(socket)` /
`socket.pipe(decode)` deployment shape (reference: example.js:53,
README.md:20-33).

Run: python examples/example_transport.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import dat_replication_protocol_tpu as protocol  # noqa: E402
from dat_replication_protocol_tpu.session import transport  # noqa: E402


def main() -> None:
    enc = protocol.encode()
    dec = protocol.decode()

    dec.change(lambda change, done: (
        print(f"change: {change.key} v{change.from_}->v{change.to}"), done()
    ))
    dec.blob(lambda blob, done: blob.collect(
        lambda data: (print(f"blob: {data!r}"), done())
    ))
    dec.finalize(lambda done: (print("finalize"), done()))

    sess = transport.session_over_socketpair(enc, dec)

    enc.change({"key": "hello", "change": 1, "from": 0, "to": 1,
                "value": b"world"})
    ws = enc.blob(11, lambda: print("blob flushed to the socket"))
    ws.write(b"hello ")
    ws.end(b"world")
    enc.change({"key": "bye", "change": 2, "from": 1, "to": 2})
    enc.finalize()

    sess.wait()
    print(f"done: {enc.bytes} bytes through the kernel, "
          f"{dec.changes} changes, {dec.blobs} blobs")


if __name__ == "__main__":
    main()
