"""Remote diff via interactive Merkle descent, metered.

Two replicas hold versions of a blob.  Each content-addresses its copy
(CDC chunks + per-chunk digests), builds a Merkle tree over the chunk
digests, and the initiator walks both trees top-down with explicit wire
messages — locating the changed chunks in O(diff · log n) transferred
bytes, without either side shipping its chunk list.

Run: JAX_PLATFORMS=cpu python examples/example_tree_sync.py
"""

import random
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import os  # noqa: E402

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from dat_replication_protocol_tpu.ops import merkle  # noqa: E402
from dat_replication_protocol_tpu.runtime import (  # noqa: E402
    TreeSyncSession,
    content_address,
    tree_sync,
)


def _session(summary, width):
    # both replicas must pad to a SHARED width (chunk counts that
    # straddle a power-of-two boundary would otherwise build trees of
    # different heights and sync() rejects them); in a real deployment
    # the width rides with the root in the handshake
    import jax.numpy as jnp

    digs = [summary.digests[i].tobytes() for i in range(summary.nchunks)]
    hh, hl = merkle.digests_to_device(digs)
    pad = ((0, width - summary.nchunks), (0, 0))
    return TreeSyncSession(
        *merkle.build_tree(jnp.pad(hh, pad), jnp.pad(hl, pad))
    )


def main() -> None:
    rng = random.Random(7)
    v1 = rng.randbytes(1 << 18)
    v2 = bytearray(v1)
    v2[100_000:100_008] = b"CHANGED!"  # in-place edit, cuts unchanged
    s1 = content_address(v1, avg_bits=10)
    s2 = content_address(bytes(v2), avg_bits=10)
    print(f"replica A: {s1.nchunks} chunks; replica B: {s2.nchunks} chunks")

    from dat_replication_protocol_tpu.utils.num import next_pow2

    width = next_pow2(max(s1.nchunks, s2.nchunks))
    transcript = []
    diff = tree_sync(_session(s1, width), _session(s2, width), transcript)
    moved = sum(nb for _, nb in transcript)
    naive = s1.nchunks * 32
    print(
        f"descent found chunks {diff} changed in {len(transcript)} messages, "
        f"{moved} bytes (naive digest-list exchange: {naive} bytes)"
    )


if __name__ == "__main__":
    main()
