"""A session over asyncio streams: the event-loop transport.

The asyncio analogue of examples/example_transport.py (reference
semantics: example.js pipes both ends through any async stream).

Run: JAX_PLATFORMS=cpu python examples/example_aio.py
"""

import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import dat_replication_protocol_tpu as protocol  # noqa: E402
from dat_replication_protocol_tpu.session.aio import (  # noqa: E402
    session_over_asyncio,
)


async def main() -> None:
    enc, dec = protocol.encode(), protocol.decode()
    dec.change(lambda c, done: (print(f"change: {c.key} v{c.from_}->{c.to}"),
                                done()))
    dec.blob(lambda b, done: b.collect(
        lambda d: (print(f"blob: {d!r}"), done())))
    dec.finalize(lambda done: (print("finalize"), done()))

    enc.change({"key": "hello", "change": 1, "from": 0, "to": 1,
                "value": b"world"})
    ws = enc.blob(11)
    ws.write(b"hello ")
    ws.end(b"world")
    enc.finalize()

    await session_over_asyncio(enc, dec)
    print(f"done: {dec.bytes} bytes, {dec.changes} changes, {dec.blobs} blobs")


if __name__ == "__main__":
    asyncio.run(main())
