#!/bin/bash
# Fire the full device measurements the moment the tunnel answers.
# Round-4 agenda (VERDICT items 1 and 4): BLAKE2b variant sweep first
# (it decides the headline kernel), then the full bench capture, then
# the CDC ceiling diagnosis, then a profiler trace.
cd "$(dirname "$0")"
set -x
# 0) insurance first: a minimal quick TPU capture (~3 min) so even a
#    window that dies mid-sweep leaves a backend=tpu artifact
BENCH_CONFIGS=3 BENCH_DEADLINE=400 timeout 420 python bench.py --quick 2>&1 | tail -3
# 1) hash kernel variant sweep: msg_loads x block_items x vmem_state,
#    interleaved twice to denoise the shared chip
timeout 900 python - <<'PY' 2>&1 | grep -v WARNING
import time, statistics, numpy as np, jax, jax.numpy as jnp
from dat_replication_protocol_tpu.ops.blake2b_pallas import blake2b_native
from dat_replication_protocol_tpu.utils.cache import enable_compile_cache
enable_compile_cache("bench", env_var="BENCH_COMPILE_CACHE")
item_bytes = 1 << 20
nblocks = item_bytes // 128
def mk(chunk):
    kh, kl = jax.random.split(jax.random.PRNGKey(0))
    shape = (nblocks, 16, 8, chunk // 8)
    return (jax.random.bits(kh, shape, dtype=jnp.uint32),
            jax.random.bits(kl, shape, dtype=jnp.uint32),
            jnp.full((8, chunk // 8), item_bytes, dtype=jnp.uint32))
data = {4096: mk(4096)}
def run(tag, chunk, bi, ml, vs=False, sl=False):
    mh, mlo, lens = data[chunk]
    f = lambda: blake2b_native(mh, mlo, lens, block_items=bi, msg_loads=ml,
                               vmem_state=vs, state_loads=sl)
    np.asarray(f()[0][:1, :1])
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        hh, hl = f()
        np.asarray(hh[:1, :1]); np.asarray(hl[:1, :1])
        dts.append(time.perf_counter() - t0)
    g = chunk * item_bytes / statistics.median(dts) / (1 << 30)
    print(f"{tag}: {g:.2f} GiB/s (median of 3)", flush=True)
variants = [("A c4096 bi1024 ml0", 4096, 1024, False, False, False),
            ("K c4096 bi1024 ml1", 4096, 1024, True, False, False),
            ("K2 c4096 bi2048 ml1", 4096, 2048, True, False, False),
            ("S c4096 bi1024 ml1 sl1", 4096, 1024, True, False, True),
            ("V c4096 bi1024 vmem", 4096, 1024, True, True, False),
            ("V2 c4096 bi2048 vmem", 4096, 2048, True, True, False),
            ("VS c4096 bi1024 vmem sl1", 4096, 1024, True, True, True),
            ("VS2 c4096 bi2048 vmem sl1", 4096, 2048, True, True, True)]
# correctness cross-check of the vmem_state variant on the real chip:
# MIXED lengths below the 4-block input so the active/final/t_lo masks
# all take both values under Mosaic
kh, kl = jax.random.split(jax.random.PRNGKey(9))
xh = jax.random.bits(kh, (4, 16, 8, 256), dtype=jnp.uint32)
xl = jax.random.bits(kl, (4, 16, 8, 256), dtype=jnp.uint32)
mixed = jnp.arange(2048, dtype=jnp.uint32).reshape(8, 256) % jnp.uint32(513)
ra = blake2b_native(xh, xl, mixed, msg_loads=True)
for kw in ({"vmem_state": True}, {"state_loads": True},
           {"vmem_state": True, "state_loads": True}):
    rb = blake2b_native(xh, xl, mixed, msg_loads=True, **kw)
    assert np.array_equal(np.asarray(ra[0]), np.asarray(rb[0])), kw
    assert np.array_equal(np.asarray(ra[1]), np.asarray(rb[1])), kw
print("variant cross-checks ok (mixed lengths, on-chip)", flush=True)
for rnd in range(2):
    for tag, c, bi, ml, vs, sl in variants:
        run(f"r{rnd} {tag}", c, bi, ml, vs, sl)
PY
# 2) full bench configs 3,4,5 (the headline artifacts; a re-wedge
#    mid-script must not cost these)
BENCH_CONFIGS=3,4,5 timeout 1800 python bench.py 2>&1 | grep -v WARNING | tail -8
# 3) CDC ceiling diagnosis by elimination: each diag variant carves one
#    suspect out of the inner loop (output wrong by design) — the delta
#    vs baseline prices that suspect.  Plus ilp/block_tiles spread.
timeout 900 python - <<'PY' 2>&1 | grep -v WARNING
import time, statistics, numpy as np, jax, jax.numpy as jnp
from dat_replication_protocol_tpu.ops.rabin_pallas import gear_candidates_native
from dat_replication_protocol_tpu.utils.cache import enable_compile_cache
enable_compile_cache("bench", env_var="BENCH_COMPILE_CACHE")
stride = 1 << 17
T = (2 << 30) // stride  # 2 GiB of tiles so bt16384 divides T
ng, gw = stride // 256, 64
w = jax.random.bits(jax.random.PRNGKey(3), (ng, gw, 8, T // 8), dtype=jnp.uint32)
jax.block_until_ready(w)
def run(tag, **kw):
    f = jax.jit(lambda x: jnp.sum(gear_candidates_native(x, 13, **kw)))
    np.asarray(f(w))
    dts = []
    for _ in range(3):
        t0 = time.perf_counter(); np.asarray(f(w))
        dts.append(time.perf_counter() - t0)
    g = w.nbytes / statistics.median(dts) / (1 << 30)
    print(f"cdc {tag}: {g:.2f} GiB/s (median of 3)", flush=True)
for rnd in range(2):
    run(f"r{rnd} base ilp8 bt8192")
    run(f"r{rnd} nomul", diag="nomul")
    run(f"r{rnd} nostore", diag="nostore")
    run(f"r{rnd} noextract", diag="noextract")
    run(f"r{rnd} ilp4", ilp=4)
    run(f"r{rnd} ilp16 bt16384", ilp=16, block_tiles=16384)
    run(f"r{rnd} bt4096 ilp4", ilp=4, block_tiles=4096)

# e2e route comparison: bitmask+window-reduce (new default) vs the
# first-hit kernel (old fast path) through the real candidates_begin ->
# greedy pipeline on a 1 GiB device-resident slab
import os
from dat_replication_protocol_tpu.ops import rabin
slab_b = 1 << 30
words_s = jax.random.bits(jax.random.PRNGKey(5), (slab_b // 4,),
                          dtype=jnp.uint32)
jax.block_until_ready(words_s)
for env in ("0", "1"):
    os.environ["DAT_CDC_FIRST_KERNEL"] = env
    def e2e():
        c = rabin.candidates_begin(words_s, slab_b, 13, thin_bits=11)
        return rabin._greedy_select(c(), slab_b, 1 << 11, 1 << 15)
    e2e()
    dts = []
    for _ in range(3):
        t0 = time.perf_counter(); e2e()
        dts.append(time.perf_counter() - t0)
    g = slab_b / statistics.median(dts) / (1 << 30)
    print(f"cdc e2e first_kernel={env}: {g:.2f} GiB/s (median of 3)",
          flush=True)
os.environ.pop("DAT_CDC_FIRST_KERNEL", None)
PY
# 4) profiler trace of the device configs (quick shapes; diagnostic)
BENCH_CONFIGS=3,4,5 timeout 900 python bench.py --quick --trace=/tmp/dat_trace 2>&1 | tail -3
ls -la /tmp/dat_trace 2>/dev/null | head -5
