#!/bin/bash
# Fire the full device measurements the moment the tunnel answers.
cd "$(dirname "$0")"
set -x
# 1) block_items sweep for the hash kernel (the open question)
timeout 580 python - <<'PY' 2>&1 | grep -v WARNING
import time, numpy as np, jax, jax.numpy as jnp
from dat_replication_protocol_tpu.ops.blake2b_pallas import blake2b_native
from dat_replication_protocol_tpu.utils.cache import enable_compile_cache
enable_compile_cache("bench", env_var="BENCH_COMPILE_CACHE")
item_bytes = 1 << 20
nblocks = item_bytes // 128
def bench(chunk, block_items, reps=4):
    kh, kl = jax.random.split(jax.random.PRNGKey(0))
    shape = (nblocks, 16, 8, chunk // 8)
    mh = jax.random.bits(kh, shape, dtype=jnp.uint32)
    ml = jax.random.bits(kl, shape, dtype=jnp.uint32)
    lengths = jnp.full((8, chunk // 8), item_bytes, dtype=jnp.uint32)
    run = lambda: blake2b_native(mh, ml, lengths, block_items=block_items)
    np.asarray(run()[0][:1,:1])
    t0 = time.perf_counter()
    outs = [run() for _ in range(reps)]
    for hh, hl in outs:
        np.asarray(hh[:1,:1]); np.asarray(hl[:1,:1])
    dt = time.perf_counter() - t0
    print(f"chunk={chunk} bi={block_items}: {reps*chunk*item_bytes/dt/(1<<30):.2f} GiB/s", flush=True)
bench(2048, 1024)
bench(2048, 2048)
PY
# 2) full bench configs 3,4,5
BENCH_CONFIGS=3,4,5 timeout 1500 python bench.py 2>&1 | grep -v WARNING | tail -6
