#!/bin/bash
# Fire the round-5 device agenda when the tunnel answers.
# VERDICT r4 #1: every capture leg lands in a COMMITTED artifact path.
# Legs are RESUMABLE: each marks itself done only when it produced a
# device-backend artifact, so a window that dies mid-agenda (rounds 3
# AND 4 both did) leaves the finished legs committed and a later window
# re-runs only what is missing.  bench.py takes the chip flock, so a
# concurrent diagnostic cannot contaminate any of this.
cd "$(dirname "$0")"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
OUT=artifacts/r05_watch
mkdir -p "$OUT"
set -x

commit_out() {
  # the builder may be committing concurrently: retry through transient
  # index.lock collisions; never let git failure kill the agenda.
  # Paths added SEPARATELY: `git add a b` with b missing stages NOTHING.
  for i in 1 2 3; do
    git add "$OUT" 2>/dev/null
    [ -f BENCH_watch_r05.json ] && git add BENCH_watch_r05.json 2>/dev/null
    git commit -m "$1" && return 0
    sleep 5
  done
  return 0
}

device_artifact() {  # $1 = json path -> exit 0 iff a device-backend artifact
  python - "$1" <<'EOF'
import json, sys
try:
    line = [l for l in open(sys.argv[1]) if l.strip().startswith("{")][-1]
    ok = json.loads(line).get("backend") not in ("cpu", None)
except Exception:
    ok = False
sys.exit(0 if ok else 1)
EOF
}

# 0) insurance first: a minimal quick TPU capture (~3 min) so even a
#    window that dies mid-run leaves a backend=tpu artifact in git
if [ ! -f "$OUT/.leg_quick_done" ]; then
  BENCH_CONFIGS=3 BENCH_DEADLINE=400 timeout 420 \
    python bench.py --quick >"$OUT/quick_$STAMP.json" 2>"$OUT/quick_$STAMP.log"
  tail -c 16384 "$OUT/quick_$STAMP.log" >"$OUT/quick_$STAMP.log.tail" \
    && rm -f "$OUT/quick_$STAMP.log"
  device_artifact "$OUT/quick_$STAMP.json" && touch "$OUT/.leg_quick_done"
  commit_out "r05 watch: insurance quick TPU hash capture ($STAMP)"
fi

# 1) THE round-5 evidence of record: one clean, uncontended, full
#    five-config bench with pipelined fencing.
if [ ! -f "$OUT/.leg_full_done" ]; then
  BENCH_DEADLINE=2600 timeout 2800 \
    python bench.py >"$OUT/full_$STAMP.json" 2>"$OUT/full_$STAMP.log"
  tail -c 32768 "$OUT/full_$STAMP.log" >"$OUT/full_$STAMP.log.tail" \
    && rm -f "$OUT/full_$STAMP.log"
  if device_artifact "$OUT/full_$STAMP.json"; then
    cp "$OUT/full_$STAMP.json" BENCH_watch_r05.json
    touch "$OUT/.leg_full_done"
  fi
  commit_out "r05 watch: full five-config TPU bench capture ($STAMP)"
fi

# 2) settle 50 GiB/s with observation (VERDICT r4 #2): roofline sweep
#    over chain length + bps amortization at the best point.
if [ ! -f "$OUT/.leg_observe_done" ] && [ -f _bps_experiment.py ]; then
  timeout 2400 python _bps_experiment.py --observe \
    >"$OUT/hash_observe_$STAMP.json" 2>"$OUT/hash_observe_$STAMP.log"
  tail -c 32768 "$OUT/hash_observe_$STAMP.log" \
    >"$OUT/hash_observe_$STAMP.log.tail" && rm -f "$OUT/hash_observe_$STAMP.log"
  # done iff the sweep emitted its summary (verdict field in the last line)
  grep -q '"verdict"' "$OUT/hash_observe_$STAMP.json" \
    && touch "$OUT/.leg_observe_done"
  commit_out "r05 watch: BLAKE2b issue-efficiency observation sweep ($STAMP)"
fi

# 3) reconcile at the config-5 snapshot scale on the device (VERDICT r4
#    #4); CPU evidence landed in-session, this leg is the TPU side.
if [ ! -f "$OUT/.leg_reconcile_done" ]; then
  BENCH_CONFIGS=5 BENCH_RECONCILE_ROWS=1000000 BENCH_DEADLINE=1200 timeout 1400 \
    python bench.py >"$OUT/reconcile1m_$STAMP.json" 2>"$OUT/reconcile1m_$STAMP.log"
  tail -c 16384 "$OUT/reconcile1m_$STAMP.log" \
    >"$OUT/reconcile1m_$STAMP.log.tail" && rm -f "$OUT/reconcile1m_$STAMP.log"
  device_artifact "$OUT/reconcile1m_$STAMP.json" \
    && touch "$OUT/.leg_reconcile_done"
  commit_out "r05 watch: 1M+1M reconcile TPU capture ($STAMP)"
fi

# 4) ISSUE 7: fused-route device capture — the fused1p extraction kernel
#    on config 4 and config 8's device-group A/B (single-residency
#    pipeline vs host-repack two-pass), so the next window records the
#    single-pass device story without hand-holding.  BENCH_FUSED_DEVICE
#    makes config 8 run its device leg (it initializes jax itself; this
#    script only fires when the tunnel answers, and the bench deadline
#    watchdog bounds a mid-run wedge).
if [ ! -f "$OUT/.leg_fused_done" ]; then
  BENCH_CONFIGS=4,8 BENCH_FUSED_DEVICE=1 DAT_CDC_ROUTE=fused1p \
    BENCH_DEADLINE=1200 timeout 1400 \
    python bench.py >"$OUT/fused_$STAMP.json" 2>"$OUT/fused_$STAMP.log"
  tail -c 16384 "$OUT/fused_$STAMP.log" >"$OUT/fused_$STAMP.log.tail" \
    && rm -f "$OUT/fused_$STAMP.log"
  device_artifact "$OUT/fused_$STAMP.json" && touch "$OUT/.leg_fused_done"
  commit_out "r06 watch: fused single-pass device capture ($STAMP)"
fi

# 5) ISSUE 8 / ROADMAP item 1 device legs: hub_soak on a real device
#    backend AND the mesh-sharded cross-session hash (the bench-side
#    twin of sidecar --hub-mesh auto).  Config 3 rides along so the
#    artifact records backend=tpu (configs 9/10 are host-group and do
#    not probe the backend themselves); CPU-host hub numbers
#    (~0.01 GiB/s, GIL-bound per-item path) say nothing about
#    device-batch scaling — these two captures are the open question.
if [ ! -f "$OUT/.leg_hub_done" ]; then
  BENCH_CONFIGS=3,9 BENCH_DEADLINE=900 timeout 1000 \
    python bench.py >"$OUT/hub_$STAMP.json" 2>"$OUT/hub_$STAMP.log"
  tail -c 16384 "$OUT/hub_$STAMP.log" >"$OUT/hub_$STAMP.log.tail" \
    && rm -f "$OUT/hub_$STAMP.log"
  device_artifact "$OUT/hub_$STAMP.json" && touch "$OUT/.leg_hub_done"
  commit_out "r06 watch: hub_soak device capture ($STAMP)"
fi
if [ ! -f "$OUT/.leg_hub_mesh_done" ]; then
  BENCH_CONFIGS=3,9 BENCH_HUB_MESH=auto BENCH_DEADLINE=900 timeout 1000 \
    python bench.py >"$OUT/hub_mesh_$STAMP.json" 2>"$OUT/hub_mesh_$STAMP.log"
  tail -c 16384 "$OUT/hub_mesh_$STAMP.log" >"$OUT/hub_mesh_$STAMP.log.tail" \
    && rm -f "$OUT/hub_mesh_$STAMP.log"
  device_artifact "$OUT/hub_mesh_$STAMP.json" \
    && touch "$OUT/.leg_hub_mesh_done"
  commit_out "r06 watch: mesh-sharded cross-session hash capture ($STAMP)"
fi

# 6) ISSUE 9 fan-out device leg: the hash-once matrix with the source
#    decode's digest work on the device engine (device.h2d.bytes /
#    device.submit.bytes must stay constant as peers grow, same as the
#    host counters do).  Config 3 rides along for the backend label.
if [ ! -f "$OUT/.leg_fanout_done" ]; then
  BENCH_CONFIGS=3,10 BENCH_DEADLINE=900 timeout 1000 \
    python bench.py >"$OUT/fanout_$STAMP.json" 2>"$OUT/fanout_$STAMP.log"
  tail -c 16384 "$OUT/fanout_$STAMP.log" >"$OUT/fanout_$STAMP.log.tail" \
    && rm -f "$OUT/fanout_$STAMP.log"
  device_artifact "$OUT/fanout_$STAMP.json" && touch "$OUT/.leg_fanout_done"
  commit_out "r06 watch: fan-out hash-once device capture ($STAMP)"
fi

# 7) ISSUE 10 rateless-reconcile device leg: the jitted scatter-add
#    symbol build + peel throughput at the 1M+1M shape.  The benchmark
#    itself is host-group (the wire A/B must not depend on a device),
#    so this leg drives the device engine directly: CodedSymbols
#    engine='device' build time + PeelDecoder round throughput at
#    k=1000 and k=100000, emitted as one JSON line.  Config 3 rides
#    along for the backend label.
if [ ! -f "$OUT/.leg_rateless_done" ]; then
  BENCH_CONFIGS=3 BENCH_DEADLINE=600 timeout 700 \
    python bench.py --quick >"$OUT/rateless_label_$STAMP.json" \
    2>"$OUT/rateless_label_$STAMP.log"
  timeout 1200 python - >"$OUT/rateless_dev_$STAMP.json" \
      2>"$OUT/rateless_dev_$STAMP.log" <<'EOF'
import json, time
import numpy as np
import jax
from dat_replication_protocol_tpu.ops import rateless as rl

out = {"backend": jax.default_backend(), "arms": {}}
rng = np.random.default_rng(1)
n = 1_000_000
for k in (1000, 100_000):
    base = rng.integers(0, 256, (n + k, 32), dtype=np.uint8)
    da, db = base[:n].copy(), np.concatenate([base[k:n], base[n:]])
    t0 = time.perf_counter()
    syms = rl.CodedSymbols(da, engine="device")
    dec = rl.PeelDecoder(db, engine="device")
    m, sent = 1024, 0
    while True:
        dec.add_symbols(sent, syms.extend(m)[sent:])
        sent = m
        got = dec.try_decode()
        if got is not None:
            break
        m *= 2
    dt = time.perf_counter() - t0
    assert len(got[0]) == 2 * k
    out["arms"][str(k)] = {
        "seconds": round(dt, 3), "symbols": sent,
        "peeled_per_s": round(2 * k / dt, 1),
        "records_per_s": round(2 * n / dt, 1)}
print(json.dumps(out))
EOF
  tail -c 16384 "$OUT/rateless_dev_$STAMP.log" \
    >"$OUT/rateless_dev_$STAMP.log.tail" \
    && rm -f "$OUT/rateless_dev_$STAMP.log"
  grep -q '"arms"' "$OUT/rateless_dev_$STAMP.json" \
    && device_artifact "$OUT/rateless_label_$STAMP.json" \
    && touch "$OUT/.leg_rateless_done"
  commit_out "r06 watch: rateless coded-symbol device build capture ($STAMP)"
fi

# 8) ISSUE 11 fleet-plane device leg: the scrape endpoint serving LIVE
#    device-leg telemetry — watermark links + jit_sites captured
#    THROUGH /snapshot and /metrics while a device hash runs, proving
#    the pull path works against real accelerator state (recompile
#    sentinel entries, device.* counters) and costs the hot path
#    nothing the overhead test didn't already bound on host.
if [ ! -f "$OUT/.leg_fleet_done" ]; then
  timeout 900 python - >"$OUT/fleet_dev_$STAMP.json" \
      2>"$OUT/fleet_dev_$STAMP.log" <<'EOF'
import json, time, urllib.request
import numpy as np
import jax
from dat_replication_protocol_tpu.obs import metrics
from dat_replication_protocol_tpu.obs.http import ObsHttpServer
from dat_replication_protocol_tpu.obs.watermarks import WATERMARKS
from dat_replication_protocol_tpu.runtime.content import content_digests

metrics.enable()
srv = ObsHttpServer(0).start()
out = {"backend": jax.default_backend()}
rng = np.random.default_rng(7)
blob = rng.integers(0, 256, 256 << 20, dtype=np.uint8).tobytes()
done = {"n": 0}
WATERMARKS.track("append", "devleg", lambda: len(blob))
WATERMARKS.track("parsed", "devleg", lambda: done["n"])
t0 = time.perf_counter()
cuts, digests = content_digests(blob)
done["n"] = len(blob)
dt = time.perf_counter() - t0
snap = json.loads(urllib.request.urlopen(
    srv.url + "/snapshot", timeout=10).read())
prom = urllib.request.urlopen(srv.url + "/metrics", timeout=10).read()
hz = json.loads(urllib.request.urlopen(
    srv.url + "/healthz", timeout=10).read())
srv.close()
out.update({
    "chunks": len(digests), "gib_s": round(len(blob) / dt / 2**30, 3),
    "jit_sites": snap.get("jit_sites"),
    "watermark_links": list((snap.get("watermarks") or {})
                            .get("links", {})),
    "prom_bytes": len(prom), "healthz_ok": hz.get("ok"),
})
print(json.dumps(out))
EOF
  grep -q '"watermark_links"' "$OUT/fleet_dev_$STAMP.json" \
    && python - "$OUT/fleet_dev_$STAMP.json" <<'EOF' \
    && touch "$OUT/.leg_fleet_done"
import json, sys
d = json.loads([l for l in open(sys.argv[1]) if l.strip()][-1])
sys.exit(0 if d.get("backend") not in ("cpu", None) else 1)
EOF
  tail -c 16384 "$OUT/fleet_dev_$STAMP.log" \
    >"$OUT/fleet_dev_$STAMP.log.tail" \
    && rm -f "$OUT/fleet_dev_$STAMP.log"
  commit_out "r06 watch: fleet-plane endpoint device capture ($STAMP)"
fi

# 9) ISSUE 12 snapshot-bootstrap device leg: manifest hashing at 2 GiB
#    through the fused1p route (the SnapshotSource materialize pass —
#    one read, one hash sweep, device single-residency pipeline), plus
#    the weighted chunk-set symbol build on the jitted device engine
#    (the SAME cached scatter-add program specialized to the 12-word
#    weighted row).  The protocol A/B itself is host-group (bench
#    config 12 runs in the tier-1 live gate); this leg prices the two
#    device-eligible stages at dataset scale.  Config 3 rides along
#    for the backend label.
if [ ! -f "$OUT/.leg_snapshot_done" ]; then
  BENCH_CONFIGS=3 BENCH_DEADLINE=600 timeout 700 \
    python bench.py --quick >"$OUT/snapshot_label_$STAMP.json" \
    2>"$OUT/snapshot_label_$STAMP.log"
  DAT_CDC_ROUTE=fused1p timeout 2400 python - \
      >"$OUT/snapshot_dev_$STAMP.json" \
      2>"$OUT/snapshot_dev_$STAMP.log" <<'EOF'
import json, time
import numpy as np
import jax
from dat_replication_protocol_tpu.ops import rateless as rl
from dat_replication_protocol_tpu.runtime.snapshot_driver import SnapshotSource

out = {"backend": jax.default_backend(), "arms": {}}
rng = np.random.default_rng(12)
data = rng.integers(0, 256, 2 << 30, dtype=np.uint8)  # 2 GiB

# arm 1: manifest materialize (fused1p cuts+digests, merkle root,
# unique set + assembly ranks) at dataset scale
t0 = time.perf_counter()
src = SnapshotSource(data)
dt = time.perf_counter() - t0
out["arms"]["materialize_2gib"] = {
    "seconds": round(dt, 3),
    "gib_s": round(data.nbytes / dt / 2**30, 3),
    "chunks": int(src.manifest.n_chunks),
}

# arm 2: weighted coded-symbol build over the chunk set on the device
# engine — the WANT-set reconcile's source-side cost per cold manifest
for m in (4096, 65536):
    t0 = time.perf_counter()
    ws = rl.WeightedSymbols(src.uniq_digests, src.uniq_lens,
                            engine="device")
    cells = ws.extend(m)
    dt = time.perf_counter() - t0
    out["arms"][f"wbuild_m{m}"] = {
        "seconds": round(dt, 3),
        "cells": int(len(cells)),
        "cells_per_s": round(m / dt, 1),
    }
print(json.dumps(out))
EOF
  tail -c 16384 "$OUT/snapshot_dev_$STAMP.log" \
    >"$OUT/snapshot_dev_$STAMP.log.tail" \
    && rm -f "$OUT/snapshot_dev_$STAMP.log"
  grep -q '"arms"' "$OUT/snapshot_dev_$STAMP.json" \
    && device_artifact "$OUT/snapshot_label_$STAMP.json" \
    && touch "$OUT/.leg_snapshot_done"
  commit_out "r06 watch: snapshot-bootstrap manifest + weighted-build device capture ($STAMP)"
fi

# 10) ISSUE 14 wire-pump device leg: the pump->DigestPipeline device
#     feed at dataset scale, plus the hub-aggregate scaling curve on a
#     host with real cores (the 2-core CI box caps the curve at ~1.0x;
#     the TPU host's CPU count is where "no longer GIL-flat" becomes a
#     measured number instead of an argument).  Config 13 at full size
#     with the 1/4/16/64 session ladder, native route, device backend
#     alive so session digests ride the device pipeline.
if [ ! -f "$OUT/.leg_pump_done" ]; then
  DAT_PUMP=native BENCH_CONFIGS=13 BENCH_PUMP_MIB=256 \
    BENCH_PUMP_SESSIONS=1,4,16,64 BENCH_PUMP_REPS=3 BENCH_DEADLINE=1200 \
    timeout 1500 python bench.py --metrics \
    >"$OUT/pump_dev_$STAMP.json" 2>"$OUT/pump_dev_$STAMP.log"
  tail -c 16384 "$OUT/pump_dev_$STAMP.log" \
    >"$OUT/pump_dev_$STAMP.log.tail" \
    && rm -f "$OUT/pump_dev_$STAMP.log"
  grep -q '"wire_pump"' "$OUT/pump_dev_$STAMP.json" \
    && touch "$OUT/.leg_pump_done"
  commit_out "r06 watch: wire-pump device feed + hub scaling ladder ($STAMP)"
fi

# 11) ISSUE 19 mesh-convergence device leg: bench config 14 with the
#     propagation plane lit (the bench lights it itself now) on the
#     device host — exchange_p99_s and rounds_to_converge at N=64
#     alongside the wall sweep, so the committed budget rows get a
#     device-host reference next to the CI-host one.  The sim is
#     host-group (in-process chaos transport), so config 3 rides along
#     for the backend label, same as legs 5/6/9.
if [ ! -f "$OUT/.leg_mesh_done" ]; then
  BENCH_CONFIGS=3,14 BENCH_DEADLINE=900 timeout 1000 \
    python bench.py --metrics >"$OUT/mesh_$STAMP.json" 2>"$OUT/mesh_$STAMP.log"
  tail -c 16384 "$OUT/mesh_$STAMP.log" >"$OUT/mesh_$STAMP.log.tail" \
    && rm -f "$OUT/mesh_$STAMP.log"
  grep -q '"exchange_p99_s"' "$OUT/mesh_$STAMP.json" \
    && device_artifact "$OUT/mesh_$STAMP.json" \
    && touch "$OUT/.leg_mesh_done"
  commit_out "r06 watch: gossip mesh propagation-plane device capture ($STAMP)"
fi

# 12) ISSUE 20 wire-cost device leg: the cost-bearing configs
#     (7 wire_batch, 10 fanout, 12 snapshot_bootstrap) with the wire
#     cost plane lit on the device host — goodput_ratio /
#     overhead_ratio next to the throughput numbers, so the committed
#     budget rows get a device-host reference and the fan-out leg's
#     amplification watermark rides a real device decode.  All three
#     are host-group; config 3 rides along for the backend label.
if [ ! -f "$OUT/.leg_wirecost_done" ]; then
  BENCH_CONFIGS=3,7,10,12 BENCH_DEADLINE=1200 timeout 1400 \
    python bench.py --metrics >"$OUT/wirecost_$STAMP.json" \
    2>"$OUT/wirecost_$STAMP.log"
  tail -c 16384 "$OUT/wirecost_$STAMP.log" \
    >"$OUT/wirecost_$STAMP.log.tail" \
    && rm -f "$OUT/wirecost_$STAMP.log"
  grep -q '"goodput_ratio"' "$OUT/wirecost_$STAMP.json" \
    && device_artifact "$OUT/wirecost_$STAMP.json" \
    && touch "$OUT/.leg_wirecost_done"
  commit_out "r06 watch: wire-cost plane device capture ($STAMP)"
fi
