#!/bin/bash
# Fire the round-5 device agenda the moment the tunnel answers.
# VERDICT r4 #1: the capture must land in a COMMITTED artifact path
# (round 3's parked sweep only fired because the builder was present;
# round 4's capture lived in /tmp and the builder's notes).  Every leg
# below tees into artifacts/r05_watch/ and commits immediately — a
# window that dies mid-agenda still leaves the finished legs in git.
# bench.py itself takes the chip flock (utils/chiplock.py), so a
# concurrent diagnostic can no longer contaminate these numbers.
cd "$(dirname "$0")"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
OUT=artifacts/r05_watch
mkdir -p "$OUT"
set -x

commit_out() {
  # the builder may be committing concurrently: retry through transient
  # index.lock collisions; never let git failure kill the agenda.
  # Paths are added SEPARATELY: `git add a b` with b missing stages
  # NOTHING (rc 128), which would silently drop every insurance commit
  # until the promotion step creates BENCH_watch_r05.json.
  for i in 1 2 3; do
    git add "$OUT" 2>/dev/null
    [ -f BENCH_watch_r05.json ] && git add BENCH_watch_r05.json 2>/dev/null
    git commit -m "$1" && return 0
    sleep 5
  done
  return 0
}

# 0) insurance first: a minimal quick TPU capture (~3 min) so even a
#    window that dies mid-run leaves a backend=tpu artifact in git
BENCH_CONFIGS=3 BENCH_DEADLINE=400 timeout 420 \
  python bench.py --quick >"$OUT/quick_$STAMP.json" 2>"$OUT/quick_$STAMP.log"
tail -c 16384 "$OUT/quick_$STAMP.log" >"$OUT/quick_$STAMP.log.tail" \
  && rm -f "$OUT/quick_$STAMP.log"
commit_out "r05 watch: insurance quick TPU hash capture ($STAMP)"

# 1) THE round-5 evidence of record: one clean, uncontended, full
#    five-config bench with pipelined fencing.  Extended deadline for
#    cold compiles (the window may start with an empty compile cache).
BENCH_DEADLINE=2600 timeout 2800 \
  python bench.py >"$OUT/full_$STAMP.json" 2>"$OUT/full_$STAMP.log"
tail -c 32768 "$OUT/full_$STAMP.log" >"$OUT/full_$STAMP.log.tail" \
  && rm -f "$OUT/full_$STAMP.log"
# promote to the canonical name iff the backend is a real device
python - "$OUT/full_$STAMP.json" <<'EOF'
import json, shutil, sys
path = sys.argv[1]
try:
    with open(path) as f:
        line = [l for l in f if l.strip().startswith("{")][-1]
    art = json.loads(line)
except Exception as e:
    sys.exit(f"no artifact parsed: {e}")
if art.get("backend") not in ("cpu", None):
    shutil.copy(path, "BENCH_watch_r05.json")
    print("promoted to BENCH_watch_r05.json")
EOF
commit_out "r05 watch: full five-config TPU bench capture ($STAMP)"

# 2) settle 50 GiB/s with observation (VERDICT r4 #2): roofline sweep
#    over message-block counts + the chain-length counter-experiment.
if [ -f _bps_experiment.py ]; then
  timeout 2400 python _bps_experiment.py --observe \
    >"$OUT/hash_observe_$STAMP.json" 2>"$OUT/hash_observe_$STAMP.log"
  tail -c 32768 "$OUT/hash_observe_$STAMP.log" \
    >"$OUT/hash_observe_$STAMP.log.tail" && rm -f "$OUT/hash_observe_$STAMP.log"
  commit_out "r05 watch: BLAKE2b issue-efficiency observation sweep ($STAMP)"
fi

# 3) reconcile at the config-5 snapshot scale on the device (VERDICT r4
#    #4); CPU-side scaling work runs in the main session, this leg is
#    the TPU evidence.
BENCH_CONFIGS=5 BENCH_RECONCILE_ROWS=1000000 BENCH_DEADLINE=1200 timeout 1400 \
  python bench.py >"$OUT/reconcile1m_$STAMP.json" 2>"$OUT/reconcile1m_$STAMP.log"
tail -c 16384 "$OUT/reconcile1m_$STAMP.log" \
  >"$OUT/reconcile1m_$STAMP.log.tail" && rm -f "$OUT/reconcile1m_$STAMP.log"
commit_out "r05 watch: 1M+1M reconcile TPU capture ($STAMP)"
