#!/bin/bash
# Fire the full device capture the moment the tunnel answers.
# Round-4 late agenda: the variant sweep, CDC diagnosis, and structural
# experiments already ran in the 03:30-05:20 UTC window (results in
# PERF.md + BENCH_builder_r04_tpu_{early,final}.json).  What remains is
# ONE clean, uncontended, full-bench capture with the pipelined-fence
# methodology — nothing else may run on the chip while this does.
cd "$(dirname "$0")"
set -x
# 0) insurance first: a minimal quick TPU capture (~3 min) so even a
#    window that dies mid-run leaves a backend=tpu artifact
BENCH_CONFIGS=3 BENCH_DEADLINE=400 timeout 420 python bench.py --quick 2>&1 | tail -3
# 1) the full five-config capture.  Extended deadline: the CDC leg now
#    calibrates three extraction routes at the 2 GiB shape and the fused
#    route's compiles are cold (everything else is warm from the earlier
#    window)
BENCH_DEADLINE=2200 timeout 2400 python bench.py 2>&1 | grep -v WARNING | tail -6
