"""Device experiment: blocks_per_step structural variant of the BLAKE2b
kernel (VERDICT round-3 item 1: "attempt one structural change").

Measures bps in {1, 2, 4, 8} interleaved twice (median of 3 each) on the
config-3 shape, cross-checks byte-exactness on-chip with mixed lengths,
and captures a profiler trace of the baseline and best variant.
"""
import statistics
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dat_replication_protocol_tpu.ops.blake2b_pallas import blake2b_native
from dat_replication_protocol_tpu.utils.cache import enable_compile_cache

enable_compile_cache("bench", env_var="BENCH_COMPILE_CACHE")

item_bytes = 1 << 20
nblocks = item_bytes // 128
chunk = 4096

kh, kl = jax.random.split(jax.random.PRNGKey(0))
shape = (nblocks, 16, 8, chunk // 8)
mh = jax.random.bits(kh, shape, dtype=jnp.uint32)
ml = jax.random.bits(kl, shape, dtype=jnp.uint32)
lens = jnp.full((8, chunk // 8), item_bytes, dtype=jnp.uint32)
jax.block_until_ready((mh, ml))

# on-chip byte-exactness first: mixed lengths below a 4-block input so
# active/final masks take both values at every sub-block position
xh = jax.random.bits(kh, (4, 16, 8, 256), dtype=jnp.uint32)
xl = jax.random.bits(kl, (4, 16, 8, 256), dtype=jnp.uint32)
mixed = jnp.arange(2048, dtype=jnp.uint32).reshape(8, 256) % jnp.uint32(513)
ra = blake2b_native(xh, xl, mixed, msg_loads=True)
for bps in (2, 4):
    for vs in (False, True):
        rb = blake2b_native(xh, xl, mixed, msg_loads=True, vmem_state=vs,
                            blocks_per_step=bps)
        assert np.array_equal(np.asarray(ra[0]), np.asarray(rb[0])), (bps, vs)
        assert np.array_equal(np.asarray(ra[1]), np.asarray(rb[1])), (bps, vs)
print("bps cross-checks ok (mixed lengths, on-chip)", flush=True)


def run(tag, **kw):
    f = lambda: blake2b_native(mh, ml, lens, **kw)
    np.asarray(f()[0][:1, :1])
    dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        hh, hl = f()
        np.asarray(hh[:1, :1]); np.asarray(hl[:1, :1])
        dts.append(time.perf_counter() - t0)
    g = chunk * item_bytes / statistics.median(dts) / (1 << 30)
    print(f"{tag}: {g:.2f} GiB/s (median of 3)", flush=True)
    return g


variants = [
    ("bps1 ml1", dict(msg_loads=True)),
    ("bps2 ml1", dict(msg_loads=True, blocks_per_step=2)),
    ("bps4 ml1", dict(msg_loads=True, blocks_per_step=4)),
    ("bps8 ml1", dict(msg_loads=True, blocks_per_step=8)),
    ("bps2 vmem", dict(msg_loads=True, vmem_state=True, blocks_per_step=2)),
    ("bps4 vmem", dict(msg_loads=True, vmem_state=True, blocks_per_step=4)),
]
best, best_g = None, 0.0
for rnd in range(2):
    for tag, kw in variants:
        g = run(f"r{rnd} {tag}", **kw)
        if g > best_g:
            best, best_g = (tag, kw), g
print(f"best: {best[0]} at {best_g:.2f} GiB/s", flush=True)

# profiler trace: baseline and best, 2 reps each
trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/blake2b_trace"
with jax.profiler.trace(trace_dir):
    for kw in (dict(msg_loads=True), best[1]):
        hh, hl = blake2b_native(mh, ml, lens, **kw)
        np.asarray(hh[:1, :1])
print(f"trace written to {trace_dir}", flush=True)
