"""Device experiment: settle the 50 GiB/s BLAKE2b question with DATA.

VERDICT round-4 #2: the ceiling analysis ("Mosaic scheduling of long
dependent chains binds at ~45% issue efficiency") rests on elimination
— 16 variants within noise — not observation.  This script runs the two
prescribed observations on an uncontended chip:

1. **Chain-length roofline sweep** (``--observe``): constant 2 GiB per
   dispatch, item size swept 128 KiB -> 2 MiB (the kernel's 1024-item
   tile floor caps the top), so the per-item dependent chain varies 16x
   (1024 -> 16384 blocks) while the batch (independent streams) varies
   16x the other way.  Total work is identical at every point.
     * flat curve  -> the bound is per-block issue rate; chain length /
       stream count don't matter, scheduling is NOT the binder at tile
       granularity, and 50 GiB/s needs a different inner loop;
     * rising as chains shorten -> scheduling IS the binder and the
       curve says how much a restructured kernel could recover.
2. **blocks_per_step amortization** at the best sweep point (1/2/4):
   whether per-block prologue/epilogue overhead is a material term.

Every rep is pipeline-fenced (depth 2) per the round-4 methodology;
the chip flock guarantees no concurrent diagnostic contaminates it
(round 4's one driver-shaped capture was polluted exactly that way).

Output: one JSON line per measurement plus a final summary JSON line
(the watch script commits stdout into artifacts/r05_watch/).
"""
import json
import statistics

import jax
import jax.numpy as jnp
import numpy as np

from bench import _timed_reps_pipelined  # the unit-tested fencing helper
from dat_replication_protocol_tpu.ops.blake2b_pallas import blake2b_native
from dat_replication_protocol_tpu.utils.cache import enable_compile_cache
from dat_replication_protocol_tpu.utils.chiplock import chip_lock


def _measure(mh, ml, lens, chunk, item_bytes, reps=4, **kw):
    """Median pipelined-fenced GiB/s over ``reps`` (depth-2 in flight)."""
    run = lambda: blake2b_native(mh, ml, lens, **kw)  # noqa: E731
    fence = lambda o: (np.asarray(o[0][:1, :1]),      # noqa: E731
                       np.asarray(o[1][:1, :1]))
    fence(run())  # compile + warm
    dts = _timed_reps_pipelined(run, fence, reps, depth=2)
    g = chunk * item_bytes / statistics.median(dts) / (1 << 30)
    return g, dts


def observe():
    out = {"experiment": "blake2b_chain_length_roofline", "points": []}
    DISPATCH_BYTES = 1 << 31  # 2 GiB per dispatch at every sweep point
    kh, kl = jax.random.split(jax.random.PRNGKey(0))
    # (item_KiB) sweep; chunk = DISPATCH_BYTES / item.  Capped at
    # 2 MiB items: at 4 MiB chunk would drop to 512, under the kernel's
    # 1024-item tile floor (B/8 must be a multiple of the 128-lane
    # tile).  Chain still varies 16x across the sweep.
    for item_kib in (128, 256, 512, 1024, 2048):
        item_bytes = item_kib << 10
        nblocks = item_bytes // 128
        chunk = DISPATCH_BYTES // item_bytes
        shape = (nblocks, 16, 8, chunk // 8)
        mh = ml = lens = None
        try:
            mh = jax.random.bits(kh, shape, dtype=jnp.uint32)
            ml = jax.random.bits(kl, shape, dtype=jnp.uint32)
            lens = jnp.full((8, chunk // 8), item_bytes, dtype=jnp.uint32)
            jax.block_until_ready((mh, ml))
            g, dts = _measure(mh, ml, lens, chunk, item_bytes,
                              msg_loads=True)
        except Exception as e:  # one bad point must not kill the sweep
            print(json.dumps({"item_bytes": item_bytes,
                              "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
            continue
        finally:
            # release the 2 GiB of HBM even when the point fails — a
            # leaked pair would cascade OOM into every later point
            del mh, ml, lens
        pt = {"item_bytes": item_bytes, "chain_blocks": nblocks,
              "streams": chunk, "gib_s": round(g, 2),
              "rep_s": [round(d, 4) for d in dts]}
        print(json.dumps(pt), flush=True)
        out["points"].append(pt)

    # interpretation from the data itself
    if not out["points"]:
        out["verdict"] = "no sweep point completed"
        print(json.dumps(out), flush=True)
        return out
    gs = [p["gib_s"] for p in out["points"]]
    spread = (max(gs) - min(gs)) / max(gs)
    out["spread_frac"] = round(spread, 3)
    out["verdict"] = (
        "chain-length-sensitive: scheduling binds; shortest chains fastest"
        if spread > 0.15 and gs[0] == max(gs) else
        "flat (<15% spread): per-block issue-rate bound, chain length "
        "and stream count immaterial at tile granularity"
        if spread <= 0.15 else
        "non-monotonic: neither pure issue-rate nor chain-schedule bound"
    )

    # blocks_per_step amortization at the best point
    best = max(out["points"], key=lambda p: p["gib_s"])
    item_bytes, chunk = best["item_bytes"], best["streams"]
    nblocks = item_bytes // 128
    shape = (nblocks, 16, 8, chunk // 8)
    mh = jax.random.bits(kh, shape, dtype=jnp.uint32)
    ml = jax.random.bits(kl, shape, dtype=jnp.uint32)
    lens = jnp.full((8, chunk // 8), item_bytes, dtype=jnp.uint32)
    jax.block_until_ready((mh, ml))
    out["bps_at_best"] = {}
    for bps in (1, 2, 4):
        g, _ = _measure(mh, ml, lens, chunk, item_bytes,
                        msg_loads=True, blocks_per_step=bps)
        out["bps_at_best"][str(bps)] = round(g, 2)
        print(json.dumps({"bps": bps, "item_bytes": item_bytes,
                          "gib_s": round(g, 2)}), flush=True)
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    enable_compile_cache("bench", env_var="BENCH_COMPILE_CACHE")
    # never run concurrently with a bench capture: block until the chip
    # is free (diagnostics have no deadline; captures do)
    with chip_lock() as lease:
        print(json.dumps({"chip_lock": lease.as_fields()}), flush=True)
        observe()
