"""Merkle tree build + tree-guided diff vs the host hashlib reference.

Mirrors the reference's testing philosophy (SURVEY.md §4: real objects,
loopback, exact-value asserts) at the kernel layer: every device result is
checked byte-exactly against an independent hashlib implementation.
"""

import hashlib
import os
import random

import numpy as np
import pytest

from dat_replication_protocol_tpu.ops import merkle


def _digest(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


def _leaves(n: int, seed: int = 0) -> list[bytes]:
    rng = random.Random(seed)
    return [_digest(rng.randbytes(24)) for _ in range(n)]


@pytest.mark.parametrize("n", [1, 2, 4, 16, 64])
def test_root_matches_host(n):
    leaves = _leaves(n)
    hh, hl = merkle.digests_to_device(leaves)
    rhh, rhl = merkle.root(hh, hl)
    (dev_root,) = merkle.digests_from_device(rhh, rhl)
    assert dev_root == merkle.host_tree(leaves)[-1][0]


def test_build_tree_all_levels_match_host():
    leaves = _leaves(32, seed=3)
    hh, hl = merkle.digests_to_device(leaves)
    hhs, hls = merkle.build_tree(hh, hl)
    host_levels = merkle.host_tree(leaves)
    assert len(hhs) == len(host_levels)
    for lvl_hh, lvl_hl, host_lvl in zip(hhs, hls, host_levels):
        assert merkle.digests_from_device(lvl_hh, lvl_hl) == host_lvl


def test_build_tree_rejects_non_power_of_two():
    leaves = _leaves(3)
    hh, hl = merkle.digests_to_device(leaves)
    with pytest.raises(ValueError, match="power of two"):
        merkle.build_tree(hh, hl)


def test_diff_identical_snapshots_is_empty():
    leaves = _leaves(64, seed=1)
    assert merkle.diff_leaves(leaves, list(leaves)) == []


@pytest.mark.parametrize("changed", [[0], [63], [5, 17, 40], list(range(64))])
def test_diff_finds_exactly_changed_leaves(changed):
    a = _leaves(64, seed=2)
    b = list(a)
    for i in changed:
        b[i] = _digest(b"changed-%d" % i)
    assert merkle.diff_leaves(a, b) == sorted(changed)
    assert merkle.diff_leaves(a, b) == merkle.host_diff(a, b)


def test_diff_non_power_of_two_padding():
    a = _leaves(13, seed=4)
    b = list(a)
    b[12] = _digest(b"x")
    b[0] = _digest(b"y")
    assert merkle.diff_leaves(a, b) == [0, 12]


def test_diff_random_against_host_reference():
    rng = random.Random(7)
    a = _leaves(128, seed=5)
    b = list(a)
    changed = sorted(rng.sample(range(128), 9))
    for i in changed:
        b[i] = _digest(b"r%d" % i)
    assert merkle.diff_leaves(a, b) == changed == merkle.host_diff(a, b)


def test_diff_mismatched_lengths_raise():
    with pytest.raises(ValueError, match="equal leaf counts"):
        merkle.diff_leaves(_leaves(4), _leaves(8))


def test_diff_empty():
    assert merkle.diff_leaves([], []) == []


def test_pad_leaves_sentinel_stability():
    # padding with zero digests must not create phantom diffs
    a = _leaves(5, seed=6)
    assert merkle.diff_leaves(a, list(a)) == []


def test_pallas_level_matches_scanned_interpret():
    import jax.numpy as jnp
    import numpy as np

    from dat_replication_protocol_tpu.ops.merkle_pallas import (
        merkle_level_pallas,
    )

    a = _leaves(64, seed=9)
    hh, hl = merkle.digests_to_device(a)
    ph, plo = merkle.merkle_level(hh, hl)
    qh, qlo = merkle_level_pallas(hh, hl, interpret=True)
    assert np.array_equal(np.asarray(ph), np.asarray(qh))
    assert np.array_equal(np.asarray(plo), np.asarray(qlo))


def test_packed_diff_matches_dense():
    import numpy as np

    a = _leaves(256, seed=10)
    b = list(a)
    for i in (3, 77, 200, 255):
        b[i] = _digest(b"p%d" % i)
    a_hh, a_hl = merkle.digests_to_device(a)
    b_hh, b_hl = merkle.digests_to_device(b)
    bits, ra, rb = merkle.diff_root_guided_packed(a_hh, a_hl, b_hh, b_hl)
    dense = np.unpackbits(np.asarray(bits).view(np.uint8), bitorder="little")
    got = np.nonzero(dense[:256])[0].tolist()
    assert got == [3, 77, 200, 255]


def test_update_leaves_matches_rebuild():
    import jax.numpy as jnp
    import numpy as np

    leaves = _leaves(64, seed=11)
    hh, hl = merkle.digests_to_device(leaves)
    levels_hh, levels_hl = merkle.build_tree(hh, hl)

    # update 5 leaves, two sharing a parent (0 and 1)
    upd = [0, 1, 17, 40, 63]
    new = [_digest(b"new-%d" % i) for i in upd]
    n_hh, n_hl = merkle.digests_to_device(new)
    u_hh, u_hl = merkle.update_leaves(
        levels_hh, levels_hl, jnp.asarray(upd), n_hh, n_hl
    )

    changed = list(leaves)
    for i, d in zip(upd, new):
        changed[i] = d
    r_hh, r_hl = merkle.build_tree(*merkle.digests_to_device(changed))
    for lvl, (a, b) in enumerate(zip(u_hh, r_hh)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), f"hh level {lvl}"
    for lvl, (a, b) in enumerate(zip(u_hl, r_hl)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), f"hl level {lvl}"


def test_diff_rejects_unequal_snapshot_widths():
    # the fused concat-tree build must not accept widths that merely sum
    # to a power of two (a 4+12 concat builds a "valid" 16-leaf tree
    # whose halves are not the two snapshots)
    a_hh, a_hl = merkle.digests_to_device(_leaves(4))
    b_hh, b_hl = merkle.digests_to_device(_leaves(12, seed=1))
    with pytest.raises(ValueError, match="widths differ"):
        merkle.diff_root_guided(a_hh, a_hl, b_hh, b_hl)


@pytest.mark.parametrize("n", [1, 2])
def test_diff_tiny_trees(n):
    a = _leaves(n)
    b = list(a)
    b[-1] = _digest(b"flipped")
    a_hh, a_hl = merkle.digests_to_device(a)
    b_hh, b_hl = merkle.digests_to_device(b)
    mask, (rahh, rahl), (rbhh, rbhl) = merkle.diff_root_guided(
        a_hh, a_hl, b_hh, b_hl
    )
    assert np.nonzero(np.asarray(mask))[0].tolist() == [n - 1]
    (ra,) = merkle.digests_from_device(rahh, rahl)
    (rb,) = merkle.digests_from_device(rbhh, rbhl)
    assert ra == merkle.host_tree(a)[-1][0]
    assert rb == merkle.host_tree(b)[-1][0]


def test_inclusion_proofs_verify_and_reject_tampering():
    leaves = _leaves(64, seed=9)
    hh, hl = merkle.digests_to_device(leaves)
    levels = merkle.build_tree(hh, hl)
    (root_bytes,) = merkle.digests_from_device(levels[0][-1], levels[1][-1])
    for idx in (0, 1, 31, 62, 63):
        path = merkle.prove(levels[0], levels[1], idx)
        assert len(path) == 6
        assert merkle.verify_proof(root_bytes, leaves[idx], idx, path, 64)
        # wrong leaf, wrong index, tampered sibling all fail
        assert not merkle.verify_proof(root_bytes, leaves[idx ^ 1], idx, path, 64)
        assert not merkle.verify_proof(root_bytes, leaves[idx], idx ^ 1, path, 64)
        bad = list(path)
        bad[3] = bytes(32)
        assert not merkle.verify_proof(root_bytes, leaves[idx], idx, bad, 64)


def test_proof_single_leaf_tree():
    leaves = _leaves(1)
    hh, hl = merkle.digests_to_device(leaves)
    levels = merkle.build_tree(hh, hl)
    (root_bytes,) = merkle.digests_from_device(levels[0][-1], levels[1][-1])
    assert merkle.prove(levels[0], levels[1], 0) == []
    assert merkle.verify_proof(root_bytes, leaves[0], 0, [], 1)
    with pytest.raises(IndexError):
        merkle.prove(levels[0], levels[1], 1)


def test_proof_rejects_out_of_range_index():
    leaves = _leaves(64, seed=13)
    hh, hl = merkle.digests_to_device(leaves)
    levels = merkle.build_tree(hh, hl)
    (root_bytes,) = merkle.digests_from_device(levels[0][-1], levels[1][-1])
    path = merkle.prove(levels[0], levels[1], 0)
    assert merkle.verify_proof(root_bytes, leaves[0], 0, path, 64)
    # aliasing indices (0 mod 64) and negatives must NOT verify
    assert not merkle.verify_proof(root_bytes, leaves[0], 64, path, 64)
    assert not merkle.verify_proof(root_bytes, leaves[0], 128, path, 64)
    assert not merkle.verify_proof(root_bytes, leaves[63], -1, path, 64)
    # second-preimage aliasing: an INTERIOR node presented as a "leaf"
    # with a truncated path must not verify (depth is pinned to nleaves)
    interior = merkle.host_parent(leaves[0], leaves[1])
    assert not merkle.verify_proof(root_bytes, interior, 0, path[1:], 64)


def test_diff_snapshots_routes_identically(monkeypatch):
    """The routed local diff must return the same indices from both the
    host compare and the tree-guided device path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dat_replication_protocol_tpu.ops import merkle

    keys = jax.random.split(jax.random.PRNGKey(2), 3)
    n = 1 << 10
    a_hh = jax.random.bits(keys[0], (n, 4), dtype=jnp.uint32)
    a_hl = jax.random.bits(keys[1], (n, 4), dtype=jnp.uint32)
    flip = jax.random.bernoulli(keys[2], 0.02, (n, 1))
    flip_lo = jax.random.bernoulli(jax.random.PRNGKey(7), 0.02, (n, 1))
    b_hh = jnp.where(flip, a_hh ^ 1, a_hh)
    b_hl = jnp.where(flip_lo, a_hl ^ 1, a_hl)  # differences in BOTH halves
    monkeypatch.setenv("DAT_DEVICE_MERKLE", "0")
    host = merkle.diff_snapshots(a_hh, a_hl, b_hh, b_hl)
    monkeypatch.setenv("DAT_DEVICE_MERKLE", "1")
    tree = merkle.diff_snapshots(a_hh, a_hl, b_hh, b_hl)
    assert np.array_equal(host, tree)
    assert len(host) == int((flip | flip_lo).sum())
    # unpadded widths must fail identically on both paths
    import pytest

    for env in ("0", "1"):
        monkeypatch.setenv("DAT_DEVICE_MERKLE", env)
        with pytest.raises(ValueError, match="power of two"):
            merkle.diff_snapshots(a_hh[:1000], a_hl[:1000],
                                  b_hh[:1000], b_hl[:1000])
