"""Gear CDC kernel vs the pure-Python reference.

Small tile sizes force multi-tile paths so the 64-byte overlap warm-up,
first-tile seeding, and slab iteration are all exercised (SURVEY.md §7
hard part (b): rolling-hash tile boundaries).
"""

import random

import numpy as np
import pytest

from dat_replication_protocol_tpu.ops import rabin


def _data(n, seed=0):
    return random.Random(seed).randbytes(n)


def _device_candidates(data, avg_bits=8, tile_bytes=1 << 12, slab_tiles=4):
    return rabin._device_candidates(
        np.frombuffer(data, dtype=np.uint8), avg_bits, tile_bytes, slab_tiles
    ).tolist()


def test_candidates_match_host_single_tile():
    data = _data(2000, seed=1)
    assert _device_candidates(data) == rabin.host_candidates(data, 8)


def test_candidates_match_host_multi_tile_and_slab():
    # 5 tiles of 4 KiB across 2 slabs; non-multiple tail
    data = _data(5 * 4096 - 123, seed=2)
    assert _device_candidates(data) == rabin.host_candidates(data, 8)


def test_candidates_first_window_of_stream():
    # the stream head has no 64-byte context; device must match host there
    data = _data(4096, seed=3)
    got = _device_candidates(data)
    exp = rabin.host_candidates(data, 8)
    assert [p for p in got if p < 64] == [p for p in exp if p < 64]
    assert got == exp


def test_tile_boundary_positions_identical():
    # candidates in the WINDOW bytes around a tile edge must be identical
    # to a single-tile run over the same data
    data = _data(8192, seed=4)
    multi = _device_candidates(data, tile_bytes=1 << 12)
    single = _device_candidates(data, tile_bytes=1 << 13)
    assert multi == single


def test_greedy_select_min_max():
    # candidates at 10,20,30,... ; min 15 skips near ones, max 25 forces
    cands = np.array([10, 20, 30, 50, 90])
    cuts = rabin._greedy_select(cands, 100, min_size=15, max_size=25)
    # from 0: first cand >=15 and <=25 -> 20; from 20: >=35,<=45 -> none
    # in [30..] within? 30<35 skip, 50>45 -> forced 45; from 45: >=60,<=70
    # -> none (50<60, 90>70) -> forced 70; from 70: >=85,<=95 -> 90; rest
    assert cuts == [20, 45, 70, 90, 100]


def test_chunk_stream_end_to_end():
    data = _data(100_000, seed=5)
    cuts = rabin.chunk_stream(data, avg_bits=8, tile_bytes=1 << 13)
    assert cuts[-1] == len(data)
    assert cuts == sorted(set(cuts))
    sizes = np.diff([0] + cuts)
    assert (sizes >= 1).all() and (sizes <= 1 << 10).all()
    # every non-final cut is either a true candidate or a forced max cut
    cands = set(rabin.host_candidates(data, 8))
    min_size, max_size = 1 << 6, 1 << 10
    start = 0
    for c in cuts[:-1]:
        assert (c in cands) or (c - start == max_size)
        assert c - start >= min_size or c - start == max_size
        start = c


def test_chunk_stream_empty_and_tiny():
    assert rabin.chunk_stream(b"") == []
    assert rabin.chunk_stream(b"abc") == [3]


def test_greedy_select_native_matches_python():
    rng = np.random.default_rng(11)
    cands = np.sort(rng.choice(1 << 20, size=4000, replace=False)).astype(
        np.int64
    )
    for min_size, max_size in [(256, 4096), (1, 1 << 20), (100, 200)]:
        native = rabin._greedy_select(cands, 1 << 20, min_size, max_size)
        py = rabin._greedy_select_py(cands, 1 << 20, min_size, max_size)
        assert native == py


def test_candidates_words_device_path_matches_host():
    data = _data(10_000, seed=6)
    buf = np.zeros(-(-len(data) // 4) * 4, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    got = rabin.candidates_words(buf.view("<u4"), len(data), avg_bits=8,
                                 tile_bytes=1 << 12)
    assert got.tolist() == rabin.host_candidates(data, 8)


def test_thinned_candidates_match_host_thin():
    data = _data(6 * 4096 - 55, seed=7)
    buf = np.zeros(-(-len(data) // 4) * 4, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    for thin in (5, 6, 8):
        got = rabin.candidates_words(
            buf.view("<u4"), len(data), avg_bits=8, tile_bytes=1 << 12,
            thin_bits=thin,
        )
        exp = rabin.host_thin(rabin.host_candidates(data, 8), thin)
        assert got.tolist() == exp, f"thin_bits={thin}"


def test_chunk_stream_thinned_cuts_are_candidates():
    # chunk_stream thins candidates to one per min_size-aligned window;
    # every non-forced cut must still be a true content candidate
    data = _data(120_000, seed=8)
    cuts = rabin.chunk_stream(data, avg_bits=8, tile_bytes=1 << 13)
    cands = set(rabin.host_candidates(data, 8))
    min_size, max_size = 1 << 6, 1 << 10
    start = 0
    for c in cuts[:-1]:
        assert (c in cands) or (c - start == max_size)
        assert min_size <= c - start <= max_size
        start = c
    assert cuts[-1] == len(data)


def test_pallas_kernel_matches_scan_path_interpret():
    import jax.numpy as jnp

    from dat_replication_protocol_tpu.ops.rabin_pallas import (
        gear_candidates_pallas,
    )

    data = _data(3 * 1024, seed=9)
    words = jnp.asarray(
        np.frombuffer(data, dtype=np.uint8).reshape(3, 1024).view("<u4")
    )
    scan_bits = np.asarray(rabin.gear_candidates_tiled(words, 8))
    pallas_bits = np.asarray(
        gear_candidates_pallas(words, 8, interpret=True)
    )
    assert np.array_equal(scan_bits, pallas_bits)


def test_first_hit_tiled_matches_bitmask():
    import jax.numpy as jnp

    data = _data(4 * 1024, seed=10)
    words = jnp.asarray(
        np.frombuffer(data, dtype=np.uint8).reshape(4, 1024).view("<u4")
    )
    bits = np.asarray(rabin.gear_candidates_tiled(words, 8))
    firsts = np.asarray(rabin.gear_first_tiled(words, 8))
    T, ng = firsts.shape
    for t in range(T):
        dense = np.nonzero(
            np.unpackbits(bits[t].view(np.uint8), bitorder="little")
        )[0]
        for g in range(ng):
            in_group = dense[(dense >= g * 256) & (dense < (g + 1) * 256)]
            exp = in_group[0] - g * 256 if len(in_group) else rabin.NO_HIT
            assert firsts[t, g] == exp, (t, g)


def test_fused_window_first_interpret_matches_bitmask_route():
    """The fused-extraction kernel (window-first reduction inside the
    gear scan) must produce exactly the bitmask route's per-window first
    offsets, including empty-window sentinels and warm-up exclusion."""
    import jax.numpy as jnp

    from dat_replication_protocol_tpu.ops.rabin_pallas import (
        gear_window_first_pallas,
    )

    T, stride, thin_bits = 2, 2048, 9  # W=512 B -> gpw=2, 4 windows/tile
    data = _data(T * stride, seed=14)
    words = jnp.asarray(
        np.frombuffer(data, dtype=np.uint8).view("<u4")
    )
    rows = rabin._build_rows(
        words, jnp.zeros((rabin._PREFIX_WORDS,), jnp.uint32), T, stride
    )
    # reference: the bitmask route's window reduction
    bits = rabin.gear_candidates_tiled(rows, 8)
    vw = bits[:, rabin._PREFIX // rabin.PACK:
              rabin._PREFIX // rabin.PACK + stride // rabin.PACK]
    wpw = (1 << thin_bits) // rabin.PACK
    ref = np.asarray(rabin._first_bit_per_window(
        np.asarray(vw).reshape(-1, wpw)
    ))
    fused = np.asarray(
        gear_window_first_pallas(rows, 8, thin_bits, interpret=True)
    )
    assert np.array_equal(ref, fused)
    assert (fused < (1 << 30)).any(), "no candidates at all — weak fixture"
    # multi-chunk ILP interleave (the bench shape runs ilp=8): the
    # fidx/fval chunk slicing + concat order must not permute lanes
    fused_ilp = np.asarray(gear_window_first_pallas(
        rows, 8, thin_bits, block_tiles=2048, ilp=2, interpret=True
    ))
    assert np.array_equal(ref, fused_ilp)


def test_first_hit_pallas_interpret_matches_tiled():
    import jax.numpy as jnp

    from dat_replication_protocol_tpu.ops.rabin_pallas import gear_first_pallas

    data = _data(2 * 2048, seed=12)
    words = jnp.asarray(
        np.frombuffer(data, dtype=np.uint8).reshape(2, 2048).view("<u4")
    )
    tiled = np.asarray(rabin.gear_first_tiled(words, 8))
    pallas = np.asarray(gear_first_pallas(words, 8, interpret=True))
    assert np.array_equal(tiled, pallas)


def test_host_and_device_chunk_stream_identical(monkeypatch):
    """The CPU-routed native gear scan must produce the exact cuts the
    device slab path produces — same seeded-stream candidates, same
    thinning policy, same greedy select."""
    import numpy as np
    import pytest

    from dat_replication_protocol_tpu.ops import rabin
    from dat_replication_protocol_tpu.runtime import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(21)
    # ~640 KiB: crosses several 128 KiB tiles (prefix/thinning seams)
    # while keeping the deliberately-slow forced XLA leg affordable
    data = rng.integers(0, 256, 5 * (1 << 17) + 777, dtype=np.uint8)
    monkeypatch.setenv("DAT_DEVICE_CDC", "0")  # force host scan
    host_cuts = rabin.chunk_stream(data, avg_bits=10)
    monkeypatch.setenv("DAT_DEVICE_CDC", "1")  # force device slab path
    dev_cuts = rabin.chunk_stream(data, avg_bits=10)
    assert list(host_cuts) == list(dev_cuts)
    assert host_cuts[-1] == len(data)
    # tiny min_size exercises the no-thinning clamp on both paths
    monkeypatch.setenv("DAT_DEVICE_CDC", "0")
    h2 = rabin.chunk_stream(data[: 1 << 17], avg_bits=6, min_size=16,
                            max_size=1 << 12)
    monkeypatch.setenv("DAT_DEVICE_CDC", "1")
    d2 = rabin.chunk_stream(data[: 1 << 17], avg_bits=6, min_size=16,
                            max_size=1 << 12)
    assert list(h2) == list(d2)


def test_parallel_gear_scan_matches_serial(monkeypatch):
    """The thread-parallel host scan (range seeding from the preceding
    WINDOW bytes + seam-resolving thinned merge) must be byte-identical
    to the serial scan, incl. windows straddling range boundaries."""
    import numpy as np
    import pytest

    from dat_replication_protocol_tpu.runtime import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(31)
    # > 16 MiB so pick_threads engages multiple ranges at DAT_NTHREADS=4
    data = rng.integers(0, 256, (24 << 20) + 999, dtype=np.uint8)
    for thin in (-1, 8, 11):
        serial = native.gear_candidates(data, 12, thin,
                                        serial_reference=True)
        monkeypatch.setenv("DAT_NTHREADS", "4")
        par = native.gear_candidates(data, 12, thin)
        assert np.array_equal(serial, par), f"thin_bits={thin}"


def test_first_occ_kernel_routes_identical(monkeypatch):
    """All _extract_first_occ kernel routes must produce identical
    occ/offs — and the cuts must match the host reference either way.
    (Off-TPU, "fused" aliases the bitmask route by design; the fused
    kernel itself is pinned to the bitmask reduction by the interpret
    test above.)"""
    import numpy as np

    from dat_replication_protocol_tpu.ops import rabin

    # a stray route knob from a bench session must not make this test
    # vacuous: DAT_CDC_ROUTE takes precedence over DAT_CDC_FIRST_KERNEL
    monkeypatch.delenv("DAT_CDC_ROUTE", raising=False)
    data = _data(6 * 4096 + 321, seed=13)
    buf = np.frombuffer(data, dtype=np.uint8)
    ref = rabin.host_thin(rabin.host_candidates(data, 8), 8)
    for env in ("0", "1"):
        monkeypatch.setenv("DAT_CDC_FIRST_KERNEL", env)
        got = rabin._device_candidates(buf, 8, 1 << 12, 4, thin_bits=8)
        assert got.tolist() == ref, f"first_kernel={env}"
    monkeypatch.delenv("DAT_CDC_FIRST_KERNEL")
    for route in ("bitmask", "first", "fused"):
        monkeypatch.setenv("DAT_CDC_ROUTE", route)
        assert rabin.effective_route(use_pallas=False) == (
            "bitmask" if route == "fused" else route
        )
        got = rabin._device_candidates(buf, 8, 1 << 12, 4, thin_bits=8)
        assert got.tolist() == ref, f"route={route}"
    # invalid values resolve to the default, not a crash or a lie
    monkeypatch.setenv("DAT_CDC_ROUTE", "Fused")
    assert rabin.effective_route() == "bitmask"
