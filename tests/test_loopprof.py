"""The event-loop flight deck (ISSUE 18): per-turn phase accounting,
loop-lag watermarks, and the sampling turn profiler.

Three contracts under test:

* **Span tiling** — recorded ``edge.turn`` spans tile the loop's wall
  time exactly (``span[i+1].ts == span[i].ts + span[i].dur``, float
  equality): idle turns coalesce into the next active span, and the
  shutdown flush closes the trailing idle stretch.
* **The dark path** — with the obs gate off the dispatcher runs the
  certified dark twin: ONE attribute load, no profiler names in its
  bytecode, zero ``edge.turn`` spans, zero ``edge.loop.turns``.
* **Lag semantics** — ``lag = max(0, work_s - tick)``: a clean turn is
  *exactly* 0.0 (the selector's wait is sanctioned, not lag), a stalled
  turn reads its overrun, the live view extrapolates mid-turn, and the
  watermark board exports ``edge.loop.lag{loop=}`` only while live.
"""

import socket
import threading
import time

from dat_replication_protocol_tpu.edge import EdgeLoop
from dat_replication_protocol_tpu.hub import ReplicationHub
from dat_replication_protocol_tpu.obs.loopprof import LoopProfiler, PHASES
from dat_replication_protocol_tpu.obs.tracing import SPANS

from test_wire_fixtures import SESSION_1


def _recv_all(sock: socket.socket) -> bytes:
    parts = []
    while True:
        d = sock.recv(65536)
        if not d:
            return b"".join(parts)
        parts.append(d)


def _run_sessions(loop: EdgeLoop, n: int) -> None:
    """Serve ``n`` reference sessions through a bound loop thread and
    join it (max_sessions must equal ``n``)."""
    port = loop.bind("127.0.0.1", 0)
    t = threading.Thread(target=loop.serve, daemon=True)
    t.start()
    try:
        for _ in range(n):
            c = socket.create_connection(("127.0.0.1", port), timeout=10)
            c.sendall(SESSION_1)
            c.shutdown(socket.SHUT_WR)
            assert _recv_all(c)
            c.close()
    finally:
        loop.close()
        t.join(timeout=10)
    assert not t.is_alive()


# -- span tiling -------------------------------------------------------------

def test_edge_turn_spans_tile_exactly(obs_enabled):
    """Consecutive recorded spans for one loop leave no gap and no
    overlap: each span's ts is the previous span's ts + dur, exactly —
    the anchor the profiler carries IS the previous span's end."""
    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub, max_sessions=3, tick=0.01, profile_every=1)
    try:
        _run_sessions(loop, 3)
    finally:
        hub.close()
    spans = [r for r in SPANS.spans("edge.turn")
             if r["fields"]["loop"] == loop.profiler.name]
    assert len(spans) >= 3  # at least one active span per session
    for prev, nxt in zip(spans, spans[1:]):
        assert nxt["ts"] == prev["ts"] + prev["dur"]  # float-exact
    # every span carries the full phase vocabulary as _s fields
    for r in spans:
        f = r["fields"]
        if f["work_s"] == 0.0:
            continue  # trailing idle flush carries the short shape
        for name in PHASES:
            assert name.replace("-", "_") + "_s" in f
        assert f["lag_s"] >= 0.0 and f["tick"] == 0.01


def test_idle_turns_coalesce_and_flush_covers_the_tail(obs_enabled):
    """An idle stretch after the last session still reaches the span
    log: detach() flushes a trailing idle span whose poll time covers
    the quiet turns, keeping the tiling complete to shutdown."""
    hub = ReplicationHub(linger_s=0.002)
    loop = EdgeLoop(hub, tick=0.005, profile_every=1)
    port = loop.bind("127.0.0.1", 0)
    t = threading.Thread(target=loop.serve, daemon=True)
    t.start()
    try:
        c = socket.create_connection(("127.0.0.1", port), timeout=10)
        c.sendall(SESSION_1)
        c.shutdown(socket.SHUT_WR)
        assert _recv_all(c)
        c.close()
        time.sleep(0.1)  # the loop idles: >= a dozen quiet turns
    finally:
        loop.close()
        t.join(timeout=10)
    spans = [r for r in SPANS.spans("edge.turn")
             if r["fields"]["loop"] == loop.profiler.name]
    assert spans, "no spans recorded"
    tail = spans[-1]
    # the flush span: multiple coalesced turns, zero work, poll covers
    assert tail["fields"]["turns"] >= 2
    assert tail["fields"]["work_s"] == 0.0
    assert tail["fields"]["poll_wait_s"] > 0.0
    for prev, nxt in zip(spans, spans[1:]):
        assert nxt["ts"] == prev["ts"] + prev["dur"]


# -- the dark path -----------------------------------------------------------

def test_dark_turn_never_touches_the_profiler():
    """Bytecode contract: the dark twin's code object references no
    profiler name at all; the per-turn gate fork lives in
    _dispatch_loop."""
    dark = EdgeLoop._dark_turn.__code__
    assert "profiler" not in dark.co_names
    assert not any("prof" in n for n in dark.co_names + dark.co_varnames)
    dispatch = EdgeLoop._dispatch_loop.__code__
    assert "_OBS" in dispatch.co_names and "on" in dispatch.co_names
    assert "_lit_turn" in dispatch.co_names
    assert "_dark_turn" in dispatch.co_names


def test_gate_off_records_nothing():
    """Behavioral dark-path check: gate off, a full session runs, and
    neither the span log nor the turn counter nor the profiler's own
    turn count moves."""
    from dat_replication_protocol_tpu.obs import metrics
    from dat_replication_protocol_tpu.obs.watermarks import WATERMARKS

    was_on = metrics.OBS.on
    metrics.OBS.on = False
    try:
        before = len(SPANS.spans("edge.turn"))
        hub = ReplicationHub(linger_s=0.002)
        loop = EdgeLoop(hub, max_sessions=1, tick=0.01)
        try:
            _run_sessions(loop, 1)
        finally:
            hub.close()
        assert len(SPANS.spans("edge.turn")) == before
        assert loop.profiler.turns == 0
        assert loop.profiler.lag_max_s == 0.0
    finally:
        metrics.OBS.on = was_on
        WATERMARKS.untrack_loop(loop.profiler.name)


# -- lag semantics (unit level: the profiler drives itself) ------------------

def test_clean_turn_lag_is_exactly_zero():
    prof = LoopProfiler("unit", tick=0.05)
    t0 = 100.0
    prof.turn_begin(t0)
    prof.poll_done(t0 + 0.05, 0)          # full-tick quiet poll
    prof.turn_done(t0 + 0.0501)           # 100us of sweep work
    assert prof.lag_s == 0.0              # EXACTLY zero, not epsilon
    assert prof.lag_max_s == 0.0
    assert prof.turns == 1 and prof.active_turns == 0


def test_stalled_turn_reads_its_overrun():
    prof = LoopProfiler("unit", tick=0.05)
    t0 = 100.0
    prof.turn_begin(t0)
    prof.poll_done(t0 + 0.001, 1)
    prof.account("read", "c1:peer", 0.3, 4096)
    prof.turn_done(t0 + 0.001 + 0.35, sessions=1)
    assert abs(prof.lag_s - 0.30) < 1e-9  # 0.35 work - 0.05 tick
    assert prof.lag_max_s == prof.lag_s
    assert prof.active_turns == 1


def test_live_lag_extrapolates_mid_turn():
    prof = LoopProfiler("unit", tick=0.05)
    prof.turn_begin(100.0)
    prof.poll_done(100.001, 1)            # work begins, never ends
    assert prof.live_lag(now=100.001 + 0.5) > 0.4
    assert prof.oldest_ready_s(now=100.001 + 0.5) > 0.4
    # the export flags it behind (gate state only names live vs dark)
    assert prof.export()["behind"]
    prof.turn_done(100.001 + 0.5, sessions=1)
    assert prof.live_lag(now=200.0) == prof.lag_s  # no extrapolation idle


def test_turn_profiler_top_k_ranks_heaviest_sessions():
    """Every overrun turn carries a top-K capture ranked by (seconds,
    bytes), each entry naming its dominant phase."""
    prof = LoopProfiler("unit", tick=0.01, top_k=2)
    t0 = 50.0
    prof.turn_begin(t0)
    prof.poll_done(t0 + 0.001, 3)
    prof.account("read", "c1:a", 0.002, 100)
    prof.account("read", "c2:b", 0.200, 9000)
    prof.account("tx", "c2:b", 0.010, 500)
    prof.account("tx", "c3:c", 0.050, 50)
    prof.turn_done(t0 + 0.001 + 0.262, sessions=3)
    spans = [r for r in SPANS.spans("edge.turn")
             if r["fields"]["loop"] == "unit"]
    top = spans[-1]["fields"]["top"]
    assert [e["session"] for e in top] == ["c2:b", "c3:c"]  # top_k=2
    assert top[0]["phase"] == "read"      # 0.200 read vs 0.010 tx
    assert top[0]["bytes"] == 9500
    assert top[1]["phase"] == "tx"


def test_sampling_gates_top_capture_on_clean_turns():
    """Without lag, only every sample_every-th ACTIVE turn carries the
    top field — the capture is amortized, not per-turn."""
    prof = LoopProfiler("unit2", tick=10.0, sample_every=4)
    t = 0.0
    for i in range(8):
        prof.turn_begin(t)
        prof.poll_done(t + 0.001, 1)
        prof.account("read", "c1:a", 0.001, 10)
        t += 0.01
        prof.turn_done(t, sessions=1)
    spans = [r for r in SPANS.spans("edge.turn")
             if r["fields"]["loop"] == "unit2"]
    assert len(spans) == 8
    with_top = [i for i, r in enumerate(spans) if "top" in r["fields"]]
    assert with_top == [3, 7]  # active turns 4 and 8


# -- the watermark board + /healthz ------------------------------------------

def test_loop_lag_gauges_ride_the_watermark_board(obs_enabled):
    from dat_replication_protocol_tpu.obs.watermarks import WATERMARKS

    prof = LoopProfiler("wmtest", tick=0.05)
    prof.attach()
    try:
        prof.turn_begin(10.0)
        prof.poll_done(10.001, 1)
        prof.turn_done(10.001 + 0.25, sessions=1)  # 0.2s lag
        snap = obs_enabled.REGISTRY.snapshot()["gauges"]
        assert snap["edge.loop.lag{loop=wmtest}"] == prof.lag_s
        assert snap["edge.loop.lag_max{loop=wmtest}"] == prof.lag_max_s
        board = WATERMARKS.snapshot()
        assert board["loops"]["wmtest"]["state"] == "live"
        assert board["loops"]["wmtest"]["behind"]
    finally:
        prof.detach()
    assert "loops" not in WATERMARKS.snapshot() or \
        "wmtest" not in WATERMARKS.snapshot().get("loops", {})


def test_dark_loop_exports_state_not_gauges(obs_enabled):
    from dat_replication_protocol_tpu.obs import metrics
    from dat_replication_protocol_tpu.obs.watermarks import WATERMARKS

    prof = LoopProfiler("darkwm", tick=0.05)
    prof.attach()
    try:
        metrics.OBS.on = False
        snap = metrics.REGISTRY.snapshot()["gauges"]
        assert "edge.loop.lag{loop=darkwm}" not in snap
        assert WATERMARKS.snapshot()["loops"]["darkwm"]["state"] == "dark"
    finally:
        metrics.enable()
        prof.detach()


def test_healthz_loop_lag_stage_flips_and_recovers(obs_enabled):
    """/healthz grows a loop_lag stage: behind => ok False naming the
    loop, caught up => ok True — and a process with no loops at all
    has no stage (host-only legs stay unchanged)."""
    from dat_replication_protocol_tpu.obs.http import default_healthz

    hz = default_healthz()
    assert "loop_lag" not in hz["stages"]

    prof = LoopProfiler("hz", tick=0.05)
    prof.attach()
    try:
        # mid-stall: work began long ago and never finished
        prof.turn_begin(time.monotonic() - 1.0)
        prof.poll_done(time.monotonic() - 1.0, 1)
        hz = default_healthz()
        assert not hz["ok"]
        assert hz["stages"]["loop_lag"]["behind"] == ["hz"]
        assert hz["stages"]["loop_lag"]["lag_s"]["hz"] > 0.5
        # the stall ends; the next clean turn recovers the probe
        prof.turn_done(time.monotonic())
        prof.turn_begin(time.monotonic())
        prof.poll_done(time.monotonic(), 0)
        prof.turn_done(time.monotonic())
        hz = default_healthz()
        assert hz["ok"] and hz["stages"]["loop_lag"]["ok"]
    finally:
        prof.detach()
