"""Tracing hooks: spans wrap work transparently, trace_to captures."""

import os
import tempfile

import jax.numpy as jnp
import numpy as np

from dat_replication_protocol_tpu.utils.trace import span, trace_to


def test_span_is_transparent_and_reentrant():
    with span("outer"):
        with span("inner"):
            x = int(np.asarray(jnp.arange(8).sum()))
    assert x == 28


def test_trace_to_none_is_noop():
    with trace_to(None):
        assert int(np.asarray(jnp.ones((4,)).sum())) == 4


def test_trace_to_captures_profile_dir():
    with tempfile.TemporaryDirectory() as d:
        with trace_to(d):
            with span("traced-work"):
                np.asarray(jnp.arange(128).sum())
        # a plugins/profile/<ts>/ tree with at least one artifact
        found = [
            os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs
        ]
        assert found, "profiler produced no trace artifacts"
