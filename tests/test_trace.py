"""Tracing hooks: spans wrap work transparently, trace_to captures."""

import os
import tempfile

import jax.numpy as jnp
import numpy as np

from dat_replication_protocol_tpu.utils.trace import span, trace_to


def test_span_is_transparent_and_reentrant():
    with span("outer"):
        with span("inner"):
            x = int(np.asarray(jnp.arange(8).sum()))
    assert x == 28


def test_trace_to_none_is_noop():
    with trace_to(None):
        assert int(np.asarray(jnp.ones((4,)).sum())) == 4


def test_span_factory_binds_once_and_is_cached():
    """ISSUE 3 satellite: span() must not re-attempt the jax.profiler
    import per call — the factory binds at first use (the
    _fastpath_gate trick) and every later span() call is one module
    attribute load plus the construction."""
    from dat_replication_protocol_tpu.utils import trace

    trace._reset_span_binding_for_tests()
    assert trace._span_factory is None
    with trace.span("bind-me"):
        pass
    bound = trace._span_factory
    assert bound is not None
    with trace.span("again"):
        pass
    assert trace._span_factory is bound  # cached, not re-derived


def test_span_falls_back_to_null_span_when_import_fails(monkeypatch):
    """With the import broken, the binding latches _NullSpan — and the
    cache means the broken import is attempted exactly once."""
    import builtins

    from dat_replication_protocol_tpu.utils import trace

    trace._reset_span_binding_for_tests()
    real_import = builtins.__import__
    calls = {"n": 0}

    def breaking_import(name, *a, **k):
        if name.startswith("jax"):
            calls["n"] += 1
            raise ImportError("jax unavailable in this process")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", breaking_import)
    try:
        with trace.span("no-jax") as s:
            assert isinstance(s, trace._NullSpan)
        with trace.span("still-no-jax"):
            pass
        assert calls["n"] == 1  # bound once; second span pays no import
        assert trace._span_factory is trace._NullSpan
    finally:
        monkeypatch.undo()
        trace._reset_span_binding_for_tests()


def test_trace_to_captures_profile_dir():
    with tempfile.TemporaryDirectory() as d:
        with trace_to(d):
            with span("traced-work"):
                np.asarray(jnp.arange(128).sum())
        # a plugins/profile/<ts>/ tree with at least one artifact
        found = [
            os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs
        ]
        assert found, "profiler produced no trace artifacts"
