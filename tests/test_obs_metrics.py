"""The metrics core itself: bucket edges, quantile ring wraparound,
concurrent increments, snapshot shape, gate semantics, and the
disabled-path overhead budget (ISSUE 3 satellite + acceptance).

The budget test is deliberately COARSE (tier-1 safe on a loaded CI
box): it pins the disabled path to the gate-check shape — no registry
lookup, no allocation — by bounding it against a deliberately heavier
reference, not by asserting absolute nanoseconds.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from dat_replication_protocol_tpu.obs import events as obs_events
from dat_replication_protocol_tpu.obs import metrics as obs_metrics
from dat_replication_protocol_tpu.obs.metrics import (
    OBS,
    Counter,
    Gauge,
    Histogram,
    Registry,
)


# -- counters / gauges -------------------------------------------------------


def test_counter_inc_and_reset():
    c = Counter("t.c")
    c.inc()
    c.inc(41)
    assert c.value == 42
    c._reset()
    assert c.value == 0


def test_gauge_set_inc_dec():
    g = Gauge("t.g")
    g.set(10.0)
    g.inc(5)
    g.dec(2.5)
    assert g.value == 12.5


# -- histogram bucket edges --------------------------------------------------


def test_histogram_bucket_edges_are_inclusive_upper():
    h = Histogram("t.h", buckets=(1.0, 10.0, 100.0))
    # exactly on an edge lands IN that bucket (le semantics)
    for v in (0.5, 1.0):
        h.observe(v)
    for v in (1.00001, 10.0):
        h.observe(v)
    for v in (99.9, 100.0):
        h.observe(v)
    h.observe(1000.0)  # overflow -> +inf bucket
    snap = h._snapshot()
    assert snap["buckets"] == [
        [1.0, 2], [10.0, 2], [100.0, 2], ["+inf", 1]]
    assert snap["count"] == 7
    assert snap["sum"] == pytest.approx(0.5 + 1.0 + 1.00001 + 10.0
                                        + 99.9 + 100.0 + 1000.0)


def test_histogram_rejects_unsorted_or_duplicate_buckets():
    with pytest.raises(ValueError):
        Histogram("t.bad", buckets=(10.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("t.bad2", buckets=(1.0, 1.0, 2.0))


# -- quantile ring wraparound ------------------------------------------------


def test_quantile_ring_wraparound_keeps_recent_window():
    h = Histogram("t.ring", buckets=(1e9,), ring=8)
    # fill the ring with large values, then overwrite with small ones:
    # quantiles must reflect ONLY the recent window (the old samples
    # were wrapped over), while bucket counts keep the full history
    for _ in range(8):
        h.observe(1000.0)
    for _ in range(8):
        h.observe(1.0)
    assert h.quantile(0.5) == 1.0
    assert h.quantile(0.99) == 1.0
    assert h.count == 16  # buckets/count keep the full history

    # partial overwrite: window holds a mix
    h2 = Histogram("t.ring2", buckets=(1e9,), ring=4)
    for v in (1.0, 2.0, 3.0, 4.0, 5.0):  # 5th wraps over the 1.0
        h2.observe(v)
    assert h2.quantile(0.0) == 2.0
    assert h2.quantile(1.0) == 5.0


def test_quantile_empty_and_bounds():
    h = Histogram("t.q", ring=4)
    assert h.quantile(0.5) is None
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_quantile_nearest_rank():
    h = Histogram("t.nr", ring=16)
    for v in (10.0, 20.0, 30.0, 40.0):
        h.observe(v)
    assert h.quantile(0.25) == 10.0
    assert h.quantile(0.5) == 20.0
    assert h.quantile(0.75) == 30.0
    assert h.quantile(1.0) == 40.0


# -- concurrency -------------------------------------------------------------


def test_snapshot_under_concurrent_increment_loses_nothing():
    reg = Registry()
    c = reg.counter("t.conc")
    h = reg.histogram("t.conc.h", buckets=(0.5, 1.5), ring=32)
    stop = threading.Event()
    snaps = []

    def snapshotter():
        while not stop.is_set():
            snaps.append(reg.snapshot())

    N, T = 2000, 4
    threads = [threading.Thread(target=snapshotter)]
    for _ in range(T):
        threads.append(threading.Thread(
            target=lambda: [c.inc() or h.observe(1.0) for _ in range(N)]))
    for t in threads:
        t.start()
    for t in threads[1:]:
        t.join()
    stop.set()
    threads[0].join()
    # locked mutation: no increment is ever lost to a torn read-modify-write
    assert c.value == N * T
    assert h.count == N * T
    # every mid-flight snapshot was internally sane
    for s in snaps:
        assert 0 <= s["counters"]["t.conc"] <= N * T


# -- registry ----------------------------------------------------------------


def test_registry_get_or_create_is_idempotent_and_type_checked():
    reg = Registry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")


def test_snapshot_is_plain_json_able_dict():
    reg = Registry()
    reg.counter("a.b").inc(3)
    reg.gauge("c.d").set(1.5)
    reg.histogram("e.f").observe(0.01)
    snap = reg.snapshot()
    parsed = json.loads(json.dumps(snap))
    assert parsed["counters"]["a.b"] == 3
    assert parsed["gauges"]["c.d"] == 1.5
    assert parsed["histograms"]["e.f"]["count"] == 1
    assert parsed["histograms"]["e.f"]["p50"] == pytest.approx(0.01)


def test_registry_reset_zeroes_values_but_keeps_handles():
    reg = Registry()
    c = reg.counter("keep.me")
    c.inc(7)
    reg.reset()
    assert c.value == 0
    assert reg.counter("keep.me") is c  # the hoisted handle stays live


# -- gate semantics ----------------------------------------------------------


def test_gate_disabled_suppresses_events(obs_enabled):
    obs_events.emit("gate.test", x=1)
    assert obs_events.EVENTS.count("gate.test") == 1
    obs_metrics.disable()
    obs_events.emit("gate.test", x=2)
    assert obs_events.EVENTS.count("gate.test") == 1


def test_event_ring_bounds_and_drop_accounting():
    log = obs_events.EventLog(capacity=4)
    was_on = OBS.on
    obs_metrics.enable()
    try:
        for i in range(6):
            log.emit("ring.test", i=i)
    finally:
        OBS.on = was_on
    records = log.events("ring.test")
    assert [r["fields"]["i"] for r in records] == [2, 3, 4, 5]
    assert log.dropped == 2
    # seq is monotonic and ts is monotonic-clock based
    assert [r["seq"] for r in records] == sorted(r["seq"] for r in records)


def test_event_jsonl_sink_receives_parseable_lines():
    log = obs_events.EventLog(capacity=8)

    class Sink:
        def __init__(self):
            self.lines = []

        def write(self, s):
            self.lines.append(s)

    sink = Sink()
    log.attach_sink(sink)
    was_on = OBS.on
    obs_metrics.enable()
    try:
        log.emit("sink.test", a=1, b="two")
    finally:
        OBS.on = was_on
    log.detach_sink()
    assert len(sink.lines) == 1
    rec = json.loads(sink.lines[0])
    assert rec["event"] == "sink.test"
    assert rec["fields"] == {"a": 1, "b": "two"}


# -- disabled-path overhead budget (ISSUE 3 acceptance) ----------------------


def _timed(fn, n: int) -> float:
    t0 = time.perf_counter()
    fn(n)
    return time.perf_counter() - t0


def test_disabled_path_is_gate_bound():
    """The disabled instrumented path (`if OBS.on: metric.inc()`) must
    cost no more than a few attribute loads: bound it against the SAME
    loop doing one locked counter increment per iteration (what the
    path would cost without the gate).  Coarse on purpose — a loaded CI
    box must not flake this, but a registry lookup or dict allocation
    sneaking into the gated path would still blow the ratio."""
    from dat_replication_protocol_tpu.obs.metrics import OBS as gate

    c = Counter("budget.test")
    was_on = gate.on
    gate.on = False
    try:
        def gated(n):
            for _ in range(n):
                if gate.on:
                    c.inc()

        def enabled_cost(n):
            for _ in range(n):
                c.inc()

        N = 200_000
        gated(N)  # warm
        enabled_cost(1000)
        t_gated = min(_timed(gated, N) for _ in range(3))
        t_inc = min(_timed(enabled_cost, N) for _ in range(3))
    finally:
        gate.on = was_on
    # the gate check must be clearly cheaper than actually incrementing
    # (lock + add).  2x headroom on the ratio keeps this robust to CI
    # noise while still catching any allocation/lookup on the gated path.
    assert t_gated < t_inc * 2.0, (
        f"disabled path too slow: gated={t_gated:.4f}s vs "
        f"locked-inc={t_inc:.4f}s over 200k iterations"
    )


def test_disabled_path_coarse_absolute_budget():
    """Belt to the ratio test's suspenders: 200k disabled gate checks
    must finish in well under a second on anything that can run the
    suite at all (~50ns/check expected; budget 5us/check)."""
    from dat_replication_protocol_tpu.obs.metrics import OBS as gate

    c = Counter("budget.abs")
    was_on = gate.on
    gate.on = False
    try:
        N = 200_000
        t0 = time.perf_counter()
        for _ in range(N):
            if gate.on:
                c.inc()
        dt = time.perf_counter() - t0
    finally:
        gate.on = was_on
    assert dt < N * 5e-6, f"disabled path {dt / N * 1e9:.0f}ns/check"


# -- Prometheus text exposition (ISSUE 4 satellite) --------------------------


def test_prom_text_counters_gauges_and_names():
    reg = Registry()
    reg.counter("decoder.blob.bytes").inc(7)
    reg.gauge("queue.depth").set(2.5)
    text = obs_metrics.to_prom_text(reg.snapshot())
    assert "# TYPE dat_decoder_blob_bytes counter\n" \
           "dat_decoder_blob_bytes 7" in text
    assert "# TYPE dat_queue_depth gauge\ndat_queue_depth 2.5" in text


def test_prom_text_histogram_buckets_are_cumulative_with_inf():
    reg = Registry()
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    text = obs_metrics.to_prom_text(reg.snapshot())
    # snapshot stores per-bucket counts (1, 2, 1); exposition must be
    # cumulative (1, 3, 4) with the overflow as le="+Inf"
    assert 'dat_lat_bucket{le="0.1"} 1' in text
    assert 'dat_lat_bucket{le="1.0"} 3' in text
    assert 'dat_lat_bucket{le="+Inf"} 4' in text
    assert "dat_lat_count 4" in text
    assert "dat_lat_sum 6.05" in text


def test_prom_text_of_live_registry_parses_line_shaped():
    obs_metrics.REGISTRY.counter("decoder.bytes")  # ensure present
    text = obs_metrics.to_prom_text()
    for ln in text.strip().splitlines():
        assert ln.startswith("#") or len(ln.split(" ")) == 2, ln
    assert text.endswith("\n")


def test_registry_histogram_param_mismatch_raises():
    reg = Registry()
    reg.histogram("h.par", buckets=(1.0, 2.0), ring=8)
    assert reg.histogram("h.par", buckets=(1.0, 2.0), ring=8) is not None
    with pytest.raises(ValueError):
        reg.histogram("h.par", buckets=(1.0, 3.0), ring=8)
    with pytest.raises(ValueError):
        reg.histogram("h.par", buckets=(1.0, 2.0), ring=16)


# -- snapshot collectors (ISSUE 8: bounded per-entity telemetry) -------------


def test_collector_entries_merge_into_snapshot():
    reg = Registry()
    reg.counter("plain.counter").inc(3)
    reg.register_collector("owner", lambda: {
        "counters": {"owner.item.count{session=a}": 7},
        "gauges": {"owner.item.bytes{session=a}": 42.0},
    })
    snap = reg.snapshot()
    assert snap["counters"]["plain.counter"] == 3
    assert snap["counters"]["owner.item.count{session=a}"] == 7
    assert snap["gauges"]["owner.item.bytes{session=a}"] == 42.0
    # unregistering removes the contribution (bounded cardinality)
    reg.unregister_collector("owner")
    snap2 = reg.snapshot()
    assert "owner.item.count{session=a}" not in snap2["counters"]


def test_collector_failure_never_breaks_snapshot():
    reg = Registry()
    reg.counter("survives").inc()

    def dying():
        raise RuntimeError("collector mid-close")

    reg.register_collector("dying", dying)
    snap = reg.snapshot()  # must not raise
    assert snap["counters"]["survives"] == 1


def test_registry_reset_drops_collectors():
    reg = Registry()
    reg.register_collector("stale", lambda: {
        "counters": {"stale.x{session=z}": 1}})
    reg.reset()
    assert "stale.x{session=z}" not in reg.snapshot()["counters"]


def test_labeled_names_render_as_prom_label_sets():
    snap = {"counters": {"hub.session.submitted{session=k1}": 5},
            "gauges": {'hub.session.parked_bytes{session=we"ird}': 2.0},
            "histograms": {}}
    text = obs_metrics.to_prom_text(snap)
    assert 'dat_hub_session_submitted{session="k1"} 5' in text
    # label values are escaped, names sanitized
    assert 'dat_hub_session_parked_bytes{session="we\\"ird"} 2.0' in text


def test_prom_text_emits_one_type_line_per_labeled_metric():
    # two label sets of one base name: exactly ONE '# TYPE' line — a
    # duplicate makes the whole scrape invalid exposition
    snap = {"counters": {"hub.session.submitted{session=a}": 5,
                         "hub.session.submitted{session=b}": 7},
            "gauges": {}, "histograms": {}}
    text = obs_metrics.to_prom_text(snap)
    assert text.count("# TYPE dat_hub_session_submitted counter") == 1
    assert 'dat_hub_session_submitted{session="a"} 5' in text
    assert 'dat_hub_session_submitted{session="b"} 7' in text


def test_unregister_collector_is_owner_checked():
    reg = Registry()
    old = lambda: {"counters": {"x{session=old}": 1}}  # noqa: E731
    new = lambda: {"counters": {"x{session=new}": 2}}  # noqa: E731
    reg.register_collector("hub", old)
    reg.register_collector("hub", new)  # restart: replaces old
    reg.unregister_collector("hub", old)  # old owner closing LATE
    assert "x{session=new}" in reg.snapshot()["counters"]
    reg.unregister_collector("hub", new)
    assert "x{session=new}" not in reg.snapshot()["counters"]
