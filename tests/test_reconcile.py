"""Key-addressed reconciliation of DIVERGENT logs.

The round-2 gap (VERDICT round 2, missing #2): the positional Merkle
diff degenerates under insertion because every later leaf shifts.  These
tests build two genuinely divergent logs — inserts, deletes, AND value
flips at arbitrary positions — and assert the key-addressed sketch
recovers every affected key with collision-bounded overhead.
"""

import random

import numpy as np

from dat_replication_protocol_tpu.ops import reconcile


def _mk_log(keys):
    return [b"record:" + k * 3 for k in keys], list(keys)


def _summ(keys, log2_slots=10):
    recs, ks = _mk_log(keys)
    return reconcile.LogSummary(recs, ks, log2_slots)


def test_identical_logs_no_diff():
    keys = [b"k%04d" % i for i in range(500)]
    a = _summ(keys)
    b = _summ(keys)
    out = reconcile.reconcile(a, b)
    assert len(out["slots"]) == 0
    assert out["a_keys"] == [] and out["b_keys"] == []


def test_insert_delete_and_flip_detected():
    rng = random.Random(5)
    keys = [b"key-%05d" % i for i in range(800)]
    a_keys = list(keys)
    b_keys = list(keys)
    # b inserts 5 new keys at arbitrary positions (misaligns everything)
    inserted = [b"new-%d" % i for i in range(5)]
    for k in inserted:
        b_keys.insert(rng.randrange(len(b_keys)), k)
    # b deletes 4 keys
    deleted = [b_keys.pop(rng.randrange(len(b_keys))) for _ in range(4)]
    deleted = [k for k in deleted if k not in inserted]
    # b flips 3 values (same key, different record bytes)
    a_recs, _ = _mk_log(a_keys)
    b_recs, _ = _mk_log(b_keys)
    flipped = []
    for _ in range(3):
        i = rng.randrange(len(b_keys))
        if b_keys[i] in inserted:
            continue
        b_recs[i] = b_recs[i] + b"~v2"
        flipped.append(b_keys[i])

    a = reconcile.LogSummary(a_recs, a_keys, 11)
    b = reconcile.LogSummary(b_recs, b_keys, 11)
    out = reconcile.reconcile(a, b)

    # no false negatives: every affected key is surfaced on the side
    # that has it
    affected_b = set(inserted) | set(flipped)
    affected_a = set(deleted) | set(flipped)
    assert affected_b <= set(out["b_keys"]), affected_b - set(out["b_keys"])
    assert affected_a <= set(out["a_keys"]), affected_a - set(out["a_keys"])

    # collision-bounded overhead: differing slots ~ diff size, so the
    # exchanged set is a small fraction of the 800-record log
    assert len(out["slots"]) <= 3 * (len(affected_a | affected_b))
    assert len(out["a_keys"]) < len(a_keys) // 4
    assert len(out["b_keys"]) < len(b_keys) // 4


def test_reorder_is_invisible():
    # same content, different log order: sketches must be identical
    keys = [b"o%03d" % i for i in range(300)]
    rng = random.Random(9)
    shuffled = list(keys)
    rng.shuffle(shuffled)
    recs_a, _ = _mk_log(keys)
    perm = {k: r for r, k in zip(recs_a, keys)}
    recs_b = [perm[k] for k in shuffled]
    a = reconcile.LogSummary(recs_a, keys, 10)
    b = reconcile.LogSummary(recs_b, shuffled, 10)
    assert np.array_equal(np.asarray(a.table), np.asarray(b.table))
    assert len(reconcile.reconcile(a, b)["slots"]) == 0


def test_empty_replica_bootstrap():
    # fresh replica vs populated one (round-3 review finding): must not
    # crash and must surface every key the empty side is missing
    keys = [b"e%03d" % i for i in range(100)]
    full = _summ(keys)
    empty = reconcile.LogSummary([], [], 10)
    out = reconcile.reconcile(empty, full)
    assert out["a_keys"] == []
    assert set(out["b_keys"]) == set(keys)


def test_log2_slots_bounds():
    import pytest

    recs, ks = _mk_log([b"a", b"b"])
    for bad in (0, -1, 32, 40):
        with pytest.raises(ValueError, match="log2_slots"):
            reconcile.LogSummary(recs, ks, bad)


def test_remote_sketch_diff_via_tree_sync():
    # the fully-remote reconciliation: two replicas locate differing
    # sketch CELLS over metered tree-sync messages (no O(nslots) table
    # exchange), and the located cells equal the local diff_sketches
    from dat_replication_protocol_tpu.ops import merkle
    from dat_replication_protocol_tpu.runtime.tree_sync import (
        TreeSyncSession,
        sync,
    )

    keys = [b"k%04d" % i for i in range(400)]
    a = _summ(keys, log2_slots=10)
    b_keys = list(keys)
    b_keys.insert(17, b"inserted-a")
    b_keys.insert(333, b"inserted-b")
    b = _summ(b_keys, log2_slots=10)

    local = reconcile.diff_sketches(a.table, b.table).tolist()

    def sess(summary):
        hh, hl = reconcile.table_leaves(summary.table)
        return TreeSyncSession(*merkle.build_tree(hh, hl))

    transcript = []
    remote = sync(sess(a), sess(b), transcript)
    assert remote == local and len(local) >= 2
    moved = sum(nb for _, nb in transcript)
    table_bytes = (1 << 10) * 32
    assert moved < table_bytes // 4, (moved, table_bytes)


def test_engines_byte_identical():
    """host (native C), device (jax), and the hashlib fallback must build
    the IDENTICAL sketch — table and slots — for the same log."""
    import numpy as np

    from dat_replication_protocol_tpu.ops.reconcile import LogSummary
    from dat_replication_protocol_tpu.runtime import native

    keys = [b"k-%04d" % i for i in range(257)]
    recs = [b"record-value:" + k * (1 + i % 3) for i, k in enumerate(keys)]
    dev = LogSummary(recs, keys, 10, engine="device")
    host = LogSummary(recs, keys, 10, engine="host")
    assert np.array_equal(np.asarray(dev.table), np.asarray(host.table))
    assert np.array_equal(dev.slots, host.slots)
    if native.available():
        # the no-toolchain fallback too (force it by bypassing native)
        import dat_replication_protocol_tpu.ops.reconcile as rmod
        orig = native.sketch
        try:
            native.sketch = lambda *a, **k: None
            fb = rmod.LogSummary(recs, keys, 10, engine="host")
        finally:
            native.sketch = orig
        assert np.array_equal(np.asarray(host.table), np.asarray(fb.table))
        assert np.array_equal(host.slots, fb.slots)


def test_reconcile_rate_floor():
    """The data-plane bar (round-3 verdict item 3): the default engine
    must summarize+reconcile well above the old 26k records/s cliff.
    Conservative floor so congested CI can't flake: 300k/s (measured ~2M)."""
    import time

    import pytest

    from dat_replication_protocol_tpu.ops import reconcile
    from dat_replication_protocol_tpu.runtime import native

    if not native.available():
        pytest.skip("native engine unavailable (no toolchain): the rate "
                    "floor guards the native path, not the XLA fallback")

    n = 50_000
    keys_a = [b"row-%07d" % i for i in range(n)]
    recs_a = [b"value-of:" + k for k in keys_a]
    keys_b = list(keys_a)
    recs_b = list(recs_a)
    keys_b.insert(1234, b"new-row")
    recs_b.insert(1234, b"new-value")
    log2 = (n * 2).bit_length()
    reconcile.reconcile(  # warm (jit-free on host engine, but be fair)
        reconcile.LogSummary(recs_a[:64], keys_a[:64], 8),
        reconcile.LogSummary(recs_b[:64], keys_b[:64], 8),
    )
    t0 = time.perf_counter()
    sa = reconcile.LogSummary(recs_a, keys_a, log2)
    sb = reconcile.LogSummary(recs_b, keys_b, log2)
    out = reconcile.reconcile(sa, sb)
    dt = time.perf_counter() - t0
    rate = 2 * n / dt
    assert b"new-row" in out["b_keys"]
    assert rate > 300_000, f"reconcile at {rate:,.0f} records/s"


def test_native_blake2b_fuzz_vs_hashlib():
    """Property fuzz: the native RFC 7693 implementation must agree with
    hashlib on arbitrary sizes incl. block-boundary straddles."""
    import hashlib

    import numpy as np
    import pytest

    from dat_replication_protocol_tpu.runtime import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(11)
    sizes = [0, 1, 63, 64, 65, 127, 128, 129, 255, 256, 257, 4095, 4096,
             10_000] + [int(rng.integers(0, 20_000)) for _ in range(40)]
    payloads = [rng.integers(0, 256, s, dtype=np.uint8).tobytes()
                for s in sizes]
    buf = np.frombuffer(b"".join(payloads), np.uint8)
    lens = np.array([len(p) for p in payloads], dtype=np.int64)
    offs = np.cumsum(lens) - lens
    out = native.hash_many(buf, offs, lens)
    for i, p in enumerate(payloads):
        assert out[i].tobytes() == hashlib.blake2b(
            p, digest_size=32).digest(), f"size {len(p)}"
