"""FaultPlan chaos arm over the unified session table (ISSUE 17).

The 20-seed tier-1 sweep: N mixed-QoS sessions through ONE
:class:`~dat_replication_protocol_tpu.edge.EdgeLoop`, with the
FaultPlan-elected session misbehaving per its deterministic scenario
(``stall`` / ``truncate`` / ``flip``).  The contract under test is
neighbor isolation: the faulted session tears down STRUCTURALLY (a
not-ok record, never a hang), resumes cleanly on reconnect, and every
healthy neighbor's reply stays byte-exact with a flat completion-time
tail — one bad socket never perturbs another session's bytes or p99.
"""

import hashlib
import socket
import threading
import time

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.edge import EdgeLoop
from dat_replication_protocol_tpu.hub import ReplicationHub
from dat_replication_protocol_tpu.session.faults import FaultPlan

from test_wire_fixtures import CHANGE_PAYLOAD, SESSION_4

N_SESSIONS = 4
SEEDS = range(20)

# one bad session must never stretch a healthy neighbor's completion
# into the same order as the fault's own lifetime: the stall scenario
# parks its socket ~0.3s, the teardown ladder runs on the loop's tick —
# a neighbor contaminated by either would blow well past this
P99_BUDGET_S = 5.0

_BLOB_DIGEST = hashlib.blake2b(b"hello world", digest_size=32).digest()
_CHANGE_DIGEST = hashlib.blake2b(CHANGE_PAYLOAD, digest_size=32).digest()


def _decode_reply(raw: bytes) -> list:
    out = []
    dec = protocol.decode()
    dec.change(lambda ch, done: (out.append(ch), done()))
    dec.write(raw)
    dec.end()
    assert dec.finished
    return out


def _recv_all(sock: socket.socket) -> bytes:
    parts = []
    while True:
        try:
            d = sock.recv(65536)
        except OSError:
            return b"".join(parts)
        if not d:
            return b"".join(parts)
        parts.append(d)


def _healthy_client(addr, results, i):
    t0 = time.monotonic()
    c = socket.create_connection(addr, timeout=10)
    c.settimeout(15)
    c.sendall(SESSION_4)
    c.shutdown(socket.SHUT_WR)
    reply = _decode_reply(_recv_all(c))
    c.close()
    results[i] = (reply, time.monotonic() - t0)


def _faulty_client(addr, scenario: str):
    """One connection misbehaving per its FaultPlan scenario — client
    bytes seen by the loop match the plan's session-axis vocabulary."""
    c = socket.create_connection(addr, timeout=10)
    c.settimeout(15)
    half = len(SESSION_4) // 2
    if scenario == "flip":
        # one bit of wire corruption mid-stream: the decoder must
        # destroy with a structured error, reply answered with EOF
        bad = bytearray(SESSION_4)
        bad[half] ^= 0x40
        c.sendall(bytes(bad))
        c.shutdown(socket.SHUT_WR)
        _recv_all(c)
    elif scenario == "truncate":
        # a clean-looking EOF mid-frame
        c.sendall(SESSION_4[:half])
        c.shutdown(socket.SHUT_WR)
        _recv_all(c)
    else:  # stall: park mid-wire, then die without a clean shutdown
        c.sendall(SESSION_4[:half])
        time.sleep(0.3)
    c.close()


@pytest.mark.parametrize("seed", SEEDS)
def test_chaos_sweep_faulted_session_never_perturbs_neighbors(seed):
    faulty = FaultPlan.faulty_session(seed, N_SESSIONS)
    scenario = FaultPlan.session_scenario(seed, N_SESSIONS)
    hub = ReplicationHub(linger_s=0.002)
    qos_of = lambda n, peer, mode: \
        "latency" if n % 2 else "throughput"  # noqa: E731
    # +1: the faulted session RECONNECTS after its teardown (resume)
    loop = EdgeLoop(hub, qos_of=qos_of, max_sessions=N_SESSIONS + 1,
                    drain_timeout=2.0, tick=0.02)
    results = {}
    try:
        port = loop.bind("127.0.0.1", 0)
        t = threading.Thread(target=loop.serve, daemon=True)
        t.start()
        addr = ("127.0.0.1", port)
        threads = []
        for i in range(N_SESSIONS):
            if i == faulty:
                th = threading.Thread(target=_faulty_client,
                                      args=(addr, scenario), daemon=True)
            else:
                th = threading.Thread(target=_healthy_client,
                                      args=(addr, results, i), daemon=True)
            threads.append(th)
            th.start()
            time.sleep(0.02)  # deterministic admission order
        for th in threads:
            th.join(20)
            assert not th.is_alive(), f"client HANG (seed {seed})"
        # the faulted session RESUMES structurally: a fresh connection
        # from the same peer completes a full clean session
        resume = {}
        _healthy_client(addr, resume, "resume")
        t.join(timeout=15)
        assert not t.is_alive(), f"loop HANG (seed {seed})"
    finally:
        hub.close()
    # every healthy neighbor: byte-exact digests, flat completion tail
    for i, (reply, elapsed) in results.items():
        by_key = {ch.key: ch for ch in reply}
        assert set(by_key) == {"blob-0", "change-0"}, (
            f"seed {seed} ({scenario}): neighbor {i} reply perturbed")
        assert by_key["blob-0"].value == _BLOB_DIGEST
        assert by_key["change-0"].value == _CHANGE_DIGEST
        assert elapsed < P99_BUDGET_S, (
            f"seed {seed} ({scenario}): neighbor {i} p99 blown "
            f"({elapsed:.2f}s)")
    reply, _ = resume["resume"]
    assert {ch.key for ch in reply} == {"blob-0", "change-0"}, (
        f"seed {seed} ({scenario}): faulted session did not resume")


def test_chaos_mixed_modes_fault_isolated_across_legs(tmp_path):
    """A faulted HUB session next to a live RECONCILE responder in the
    same table: the responder's exchange stays exact while the hub
    neighbor is torn down — isolation holds ACROSS leg kinds, not just
    between hub sessions."""
    from dat_replication_protocol_tpu import sidecar
    from dat_replication_protocol_tpu.runtime import replay
    from dat_replication_protocol_tpu.runtime.reconcile_driver import (
        RatelessReplica,
        run_initiator,
    )

    logfile = tmp_path / "log.bin"
    logfile.write_bytes(replay.encode_change_log(
        [{"key": "srv-only", "change": 0, "from": 0, "to": 1,
          "value": b"v"}]))
    replica = sidecar.load_reconcile_replica(str(logfile))
    client = RatelessReplica([])
    hub = ReplicationHub(linger_s=0.002)
    mode_of = lambda n, peer: \
        "hub" if n in (1, 3) else "reconcile"  # noqa: E731
    loop = EdgeLoop(hub, reconcile_replica=replica, mode_of=mode_of,
                    max_sessions=3, drain_timeout=2.0, tick=0.02)
    try:
        port = loop.bind("127.0.0.1", 0)
        t = threading.Thread(target=loop.serve, daemon=True)
        t.start()
        addr = ("127.0.0.1", port)
        # n=1: the faulted hub session (corrupt wire)
        fth = threading.Thread(target=_faulty_client,
                               args=(addr, "flip"), daemon=True)
        fth.start()
        time.sleep(0.05)
        # n=2: the reconcile responder, concurrent with the fault
        c = socket.create_connection(addr, timeout=10)
        out = run_initiator(
            client, c.recv, c.sendall,
            close_write=lambda: c.shutdown(socket.SHUT_WR))
        c.close()
        assert out["ok"]
        assert {ch.key for ch in out["received"]} == {"srv-only"}
        fth.join(15)
        assert not fth.is_alive()
        # n=3: a clean hub session after the fault — the table recovered
        results = {}
        _healthy_client(addr, results, "after")
        t.join(timeout=15)
        assert {ch.key for ch in results["after"][0]} == {"blob-0",
                                                          "change-0"}
    finally:
        hub.close()
