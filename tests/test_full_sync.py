"""Full replica sync over the wire protocol: the composed dat story.

Two replicas hold divergent change logs (inserts + value flips).  They
reconcile via key-addressed sketches (ops.reconcile), then each ships
the records the other lacks as real Change frames through an
encode→socketpair→decode session (session + transport layers).  Both
replicas must converge to the same record set — every layer of the
framework exercised in one flow.
"""

import threading

import numpy as np

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.ops import reconcile
from dat_replication_protocol_tpu.session.transport import (
    session_over_socketpair,
)
from dat_replication_protocol_tpu.wire.change_codec import Change


def _store(n, seed, mutate=()):
    """{key: Change} with optional (key, new_value) mutations."""
    rng = np.random.default_rng(seed)
    out = {}
    for i in range(n):
        k = f"row-{i:05d}"
        out[k] = Change(key=k, change=i, from_=i, to=i + 1,
                        value=bytes(rng.integers(0, 256, 24, dtype=np.uint8)))
    for k, v in mutate:
        c = out[k]
        out[k] = Change(key=k, change=c.change + 1, from_=c.to,
                        to=c.to + 1, value=v)
    return out


def _summary(store):
    keys = sorted(store)
    recs = [b"%d:%d:%d:" % (store[k].change, store[k].from_, store[k].to)
            + bytes(store[k].value) for k in keys]
    return reconcile.LogSummary(recs, [k.encode() for k in keys], 12)


def _ship(sender_store, keys, receiver_store):
    """Send `keys` of sender_store as wire frames; apply at receiver."""
    enc, dec = protocol.encode(), protocol.decode()
    applied = []

    def on_change(c, done):
        old = receiver_store.get(c.key)
        # last-writer-wins on the change counter: a reconciling replica
        # keeps its own newer version (the superset exchange may carry
        # records the receiver already superseded)
        if old is None or c.change > old.change:
            receiver_store[c.key] = Change(
                key=c.key, change=c.change, from_=c.from_, to=c.to,
                value=bytes(c.value),
            )
        applied.append(c.key)
        done()

    dec.change(on_change)
    dec.finalize(lambda done: done())
    sess = session_over_socketpair(enc, dec)

    def produce():
        for k in keys:
            c = sender_store[k]
            enc.change({"key": c.key, "change": c.change, "from": c.from_,
                        "to": c.to, "value": bytes(c.value)})
        enc.finalize()

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    t.join(30)
    sess.wait(30)
    return applied


def test_divergent_replicas_converge_over_wire():
    # A and B share 600 rows; A mutates 3, B mutates 2 and inserts 4 new
    base = _store(600, seed=1)
    a = dict(base)
    for k, v in [("row-00010", b"a-edit-1"), ("row-00200", b"a-edit-2"),
                 ("row-00599", b"a-edit-3")]:
        c = a[k]
        a[k] = Change(key=k, change=c.change + 1, from_=c.to, to=c.to + 1,
                      value=v)
    b = dict(base)
    for k, v in [("row-00010", b"b-edit"), ("row-00300", b"b-edit-2")]:
        c = b[k]
        b[k] = Change(key=k, change=c.change + 2, from_=c.to, to=c.to + 2,
                      value=v)
    for j in range(4):
        k = f"new-{j}"
        b[k] = Change(key=k, change=1, from_=0, to=1, value=b"fresh-%d" % j)

    plan = reconcile.reconcile(_summary(a), _summary(b))
    a_send = sorted(k.decode() for k in plan["a_keys"])
    b_send = sorted(k.decode() for k in plan["b_keys"])
    # every truly differing key is in the exchange (no false negatives)
    truly = {k for k in set(a) | set(b)
             if a.get(k) != b.get(k)}
    assert truly <= set(a_send) | set(b_send)
    # superset overhead is bounded by slot collisions (load factor ~0.15
    # at 4096 slots / 604 keys): the exchange stays O(diff), not O(n)
    assert len(a_send) + len(b_send) < 10 * max(1, len(truly))

    _ship(a, a_send, b)
    _ship(b, b_send, a)

    assert set(a) == set(b)
    for k in a:
        assert a[k] == b[k], k
    # converged: rebuilt sketches now diff empty
    assert reconcile.reconcile(_summary(a), _summary(b))["slots"].size == 0
