"""The offline costdoctor (ISSUE 20): rebuilding the per-link wire
cost ledger from frame instants and naming the doctored link on every
seeded anomaly — unattributed bytes, overhead anomalies, amplification
regressions — while flagging NOTHING on clean lit logs.
"""

from __future__ import annotations

import json
import os

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu import CAP_CHANGE_BATCH
from dat_replication_protocol_tpu.obs import events as obs_events
from dat_replication_protocol_tpu.obs import tracing
from dat_replication_protocol_tpu.obs.__main__ import main as obs_main
from dat_replication_protocol_tpu.session.resume import WireJournal


def _detach():
    obs_events.EVENTS.detach_sink()
    tracing.SPANS.detach_sink()


def _session_log(tmp_path, name: str = "peer.jsonl") -> tuple[str, int]:
    """One lit sender session mirrored into a JSONL log; returns the
    log path and the total wire length."""
    log = str(tmp_path / name)
    sink = tracing.attach_jsonl_sink(log)
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    for i in range(40):
        e.change({"key": f"k{i}", "change": i, "from": i, "to": i + 1,
                  "value": b"v" * (i % 25)})
    e.negotiate(CAP_CHANGE_BATCH)
    e.change_many([{"key": f"b{i}", "change": i, "from": 0, "to": 1,
                    "value": b"w" * (i % 7),
                    "subset": "dataset/tag"} for i in range(20)])
    e.flush_batch()
    b = e.blob(150)
    b.write(b"x" * 150)
    b.end()
    e.finalize()
    while e.read(4096) is not None:
        pass
    wire = j.read_from(0)
    _detach()
    sink.close()
    return log, len(wire)


def test_clean_log_flags_nothing_and_exits_zero(obs_enabled, tmp_path,
                                                capsys):
    log, total = _session_log(tmp_path)
    rc = obs_main(["costdoctor", log, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["flags"] == []
    led = report["ledgers"]["peer.jsonl|tx"]
    # the rebuilt ledger covers the whole wire, split across the
    # classes the session actually emitted
    assert led["wire_bytes"] == total
    assert set(led["classes"]) == {"change", "change_batch", "blob"}
    assert led["unattributed_bytes"] == 0
    assert led["overhead_ratio"] < 0.5


def test_dropped_frame_names_the_link_as_unattributed(obs_enabled,
                                                      tmp_path, capsys):
    log, _total = _session_log(tmp_path)
    lines = open(log, encoding="utf-8").read().splitlines()
    idx = [i for i, ln in enumerate(lines) if '"encoder.frame"' in ln]
    doctored = str(tmp_path / "doctored.jsonl")
    drop = idx[len(idx) // 2]
    with open(doctored, "w", encoding="utf-8") as f:
        f.write("\n".join(ln for i, ln in enumerate(lines) if i != drop)
                + "\n")
    rc = obs_main(["costdoctor", doctored, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    flags = [f for f in report["flags"]
             if f["flag"] == "unattributed-bytes"]
    assert flags and all(f["link"] == "doctored.jsonl|tx" for f in flags)
    # the flagged byte count is exactly the dropped frame's wire_len
    dropped = json.loads(lines[drop])["fields"]["wire_len"]
    assert f"{dropped} wire byte(s)" in flags[0]["detail"]


def test_overhead_anomaly_fires_on_threshold(obs_enabled, tmp_path,
                                             capsys):
    log, _total = _session_log(tmp_path)
    # every real session log has SOME framing; an absurdly low
    # threshold must trip the anomaly and name the stream
    rc = obs_main(["costdoctor", log, "--max-overhead", "0.0001",
                   "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    flags = [f for f in report["flags"] if f["flag"] == "overhead-anomaly"]
    assert flags and flags[0]["link"] == "peer.jsonl|tx"


def test_min_goodput_floor(obs_enabled, tmp_path, capsys):
    log, _total = _session_log(tmp_path)
    rc = obs_main(["costdoctor", log, "--min-goodput", "0.9999",
                   "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["flag"] == "overhead-anomaly" and "goodput" in f["detail"]
               for f in report["flags"])


def _stats_log(tmp_path, amps: list[float], link: str = "fanout") -> str:
    path = str(tmp_path / "stats.jsonl")
    with open(path, "w", encoding="utf-8") as f:
        for a in amps:
            f.write(json.dumps({"wirecost": {"links": {}, "amplification": {
                link: {"source_bytes": 1000,
                       "delivered_bytes": int(1000 * a),
                       "peers": {}, "amplification": a}}}}) + "\n")
    return path


def test_amplification_regression_names_the_link(obs_enabled, tmp_path,
                                                 capsys):
    path = _stats_log(tmp_path, [3.0, 3.1, 1.0])
    rc = obs_main(["costdoctor", path, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    flags = [f for f in report["flags"]
             if f["flag"] == "amplification-regression"]
    assert flags and flags[0]["link"] == "fanout"


def test_steady_amplification_is_clean(obs_enabled, tmp_path, capsys):
    path = _stats_log(tmp_path, [2.8, 3.0, 2.9])
    rc = obs_main(["costdoctor", path, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["flags"] == []
    assert report["amplification"]["fanout"] == [2.8, 3.0, 2.9]


def test_nonzero_live_residual_flags_unattributed(obs_enabled, tmp_path,
                                                  capsys):
    path = str(tmp_path / "stats.jsonl")
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"wirecost": {"amplification": {}, "links": {
            "s1|rx": {"residual_bytes": 37}}}}) + "\n")
    rc = obs_main(["costdoctor", path, "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["flag"] == "unattributed-bytes" and f["link"] == "s1|rx"
               and "37" in f["detail"] for f in report["flags"])


def test_dark_log_reports_plane_dark_and_exits_zero(tmp_path, capsys):
    empty = str(tmp_path / "dark.jsonl")
    open(empty, "w").close()
    rc = obs_main(["costdoctor", empty])
    out = capsys.readouterr().out
    assert rc == 0
    assert "never ran lit" in out


def test_human_output_prints_ledger_and_flags(obs_enabled, tmp_path,
                                              capsys):
    log, _total = _session_log(tmp_path)
    rc = obs_main(["costdoctor", log])
    out = capsys.readouterr().out
    assert rc == 0
    assert "peer.jsonl|tx" in out and "clean" in out
