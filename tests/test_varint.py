import pytest

from dat_replication_protocol_tpu.wire.varint import (
    NeedMoreData,
    decode_uvarint,
    encode_uvarint,
    uvarint_length,
)


@pytest.mark.parametrize(
    "value,expected",
    [
        (0, b"\x00"),
        (1, b"\x01"),
        (127, b"\x7f"),
        (128, b"\x80\x01"),
        (300, b"\xac\x02"),
        (16384, b"\x80\x80\x01"),
        (2**32 - 1, b"\xff\xff\xff\xff\x0f"),
        (2**64 - 1, b"\xff" * 9 + b"\x01"),
    ],
)
def test_known_encodings(value, expected):
    assert encode_uvarint(value) == expected
    got, used = decode_uvarint(expected)
    assert (got, used) == (value, len(expected))
    assert uvarint_length(value) == len(expected)


def test_roundtrip_sweep():
    for v in list(range(0, 4097)) + [2**k for k in range(63)] + [2**k - 1 for k in range(1, 64)]:
        enc = encode_uvarint(v)
        got, used = decode_uvarint(enc)
        assert got == v and used == len(enc)


def test_decode_with_offset_and_trailing():
    buf = b"\xff" + encode_uvarint(300) + b"tail"
    got, used = decode_uvarint(buf, 1)
    assert got == 300 and used == 2


def test_truncated_raises_needmoredata():
    with pytest.raises(NeedMoreData):
        decode_uvarint(b"\x80")


def test_overlong_rejected():
    with pytest.raises(ValueError):
        decode_uvarint(b"\x80" * 10 + b"\x01")


def test_negative_rejected():
    with pytest.raises(ValueError):
        encode_uvarint(-1)
