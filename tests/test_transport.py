"""Session conformance across real OS transport boundaries.

The reference's L0 is any byte stream — its example pipes through
whatever stream you hand it (reference: example.js:53), and backpressure
propagates end-to-end through the transport (reference:
decode.js:87-99,168).  These tests re-run the 4-test conformance suite
(reference: test/basic.js) with every byte crossing a kernel socketpair
between two pump threads, verify that a withheld app ``done`` stalls the
*sender* through the socket, and cross a real process boundary (encoder
in a child process, decoder in this one, wire bytes over a pipe).
"""

import os
import subprocess
import sys
import threading
import time

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.session import transport
from dat_replication_protocol_tpu.wire.change_codec import Change


def _run_session(e, d, setup):
    """Wire e -> socketpair -> d, run the producer-side setup, wait."""
    sess = transport.session_over_socketpair(e, d)
    setup(e)
    sess.wait()
    return sess


def test_changes_over_socketpair():
    e, d = protocol.encode(), protocol.decode()
    got = []
    d.change(lambda change, done: (got.append(change), done()))

    def produce(e):
        e.change({"key": "key", "from": 0, "to": 1, "change": 1, "value": b"hello"})
        e.finalize()

    _run_session(e, d, produce)
    assert got == [
        Change(key="key", from_=0, to=1, change=1, value=b"hello", subset="")
    ]


def test_blob_over_socketpair():
    e, d = protocol.encode(), protocol.decode()
    got = []
    d.blob(lambda blob, done: blob.collect(lambda data: (got.append(data), done())))

    def produce(e):
        blob = e.blob(11)
        blob.write(b"hello ")
        blob.write(b"world")
        blob.end()
        e.finalize()

    _run_session(e, d, produce)
    assert got == [b"hello world"]


def test_mixed_blobs_over_socketpair():
    e, d = protocol.encode(), protocol.decode()
    got = []
    d.blob(lambda blob, done: blob.collect(lambda data: (got.append(data), done())))

    def produce(e):
        b1 = e.blob(11)
        b2 = e.blob(11)
        b1.write(b"hello ")
        b2.write(b"HELLO ")
        b1.write(b"world")
        b2.write(b"WORLD")
        b1.end()
        b2.end()
        e.finalize()

    _run_session(e, d, produce)
    assert got == [b"hello world", b"HELLO WORLD"]


def test_blob_and_changes_over_socketpair():
    e, d = protocol.encode(), protocol.decode()
    order = []
    d.blob(lambda blob, done: blob.collect(
        lambda data: (order.append(("blob", data)), done())))
    d.change(lambda change, done: (order.append(("change", change)), done()))

    def produce(e):
        blob = e.blob(11)
        blob.write(b"hello ")
        blob.write(b"world")
        e.change({"key": "key", "from": 0, "to": 1, "change": 1, "value": b"x"})
        blob.end()
        e.finalize()

    _run_session(e, d, produce)
    assert order == [
        ("blob", b"hello world"),
        ("change", Change(key="key", from_=0, to=1, change=1, value=b"x", subset="")),
    ]


def test_backpressure_stalls_sender_through_socket():
    """A withheld app ``done`` must stall the *producing* end through the
    kernel socket — the reference's end-to-end valve (decode.js:168 ->
    pipe pause -> encode.js:139-151) with OS buffers as the pipe."""
    e, d = protocol.encode(), protocol.decode()
    total = 4 << 20  # far larger than socket buffers + encoder high water
    release = threading.Event()
    received = {"bytes": 0}
    done_box = {}

    def on_blob(blob, done):
        done_box["done"] = done

        def on_data(chunk):
            received["bytes"] += len(chunk)

        blob.on_data(on_data)
        blob.on_end(lambda: None)

    d.blob(on_blob)
    # park the first change's ack: everything after it must stall
    first = threading.Event()
    d.change(lambda change, done: (done_box.setdefault("chg", done), first.set()))

    sess = transport.session_over_socketpair(e, d, chunk_size=4096, sndbuf=65536)
    e.change({"key": "go", "from": 0, "to": 1, "change": 1})
    writer = e.blob(total)

    wrote = {"bytes": 0}

    def produce():
        chunk = b"x" * 65536
        sent = 0
        while sent < total:
            writer.write(chunk[: min(65536, total - sent)])
            sent += len(chunk)
            wrote["bytes"] = sent
            if not e.writable():
                # producer honors encoder backpressure like the reference
                # app would honor `false` from write()
                drained = threading.Event()
                e.on_drain(drained.set)
                drained.wait(30)
        writer.end()
        e.finalize()

    producer = threading.Thread(target=produce, daemon=True)
    producer.start()

    assert first.wait(10), "first change never arrived"
    # with the change ack withheld, the whole pipeline must wedge: socket
    # buffers + encoder queue fill, producer blocks well short of total
    time.sleep(0.5)
    stalled_at = wrote["bytes"]
    assert stalled_at < total, "producer finished despite a withheld done"
    time.sleep(0.3)
    assert wrote["bytes"] == stalled_at, "producer advanced while stalled"
    assert received["bytes"] == 0, "blob bytes delivered before change ack"

    done_box["chg"]()  # release the valve
    producer.join(30)
    assert not producer.is_alive()
    # blob done never gated blob payload parsing (reference pairing:
    # decode.js:171-177); ack it so the session can finish
    assert "done" in done_box
    done_box["done"]()
    sess.wait()
    assert received["bytes"] == total
    assert d.finished


_CHILD = r"""
import sys, os
sys.path.insert(0, {repo!r})
import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.session import transport

e = protocol.encode()
e.change({{"key": "a", "from": 0, "to": 1, "change": 1, "value": b"v"}})
b = e.blob(12)
b.write(b"hello ")
b.end(b"world!")
e.change({{"key": "b", "from": 1, "to": 2, "change": 2}})
e.finalize()
transport.send_over_fd(e, sys.stdout.fileno())
"""


def test_process_boundary_pipe():
    """Encoder in a child process, decoder here: the wire format crosses a
    real process boundary, the reference's deployment shape
    (reference: README.md:20-33 — two ends on two machines)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    d = protocol.decode()
    got = []
    d.change(lambda change, done: (got.append(("change", change.key)), done()))
    d.blob(lambda blob, done: blob.collect(
        lambda data: (got.append(("blob", data)), done())))

    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(repo=repo)],
        stdout=subprocess.PIPE,
        cwd=repo,
    )
    transport.recv_over_fd(d, proc.stdout.fileno())
    proc.wait(30)
    assert proc.returncode == 0
    assert got == [("change", "a"), ("blob", b"hello world!"), ("change", "b")]
    assert d.finished


def test_tpu_backend_over_socketpair():
    """decode(backend='tpu') across a real byte transport: the digest
    pipeline's flush-before-finalize barrier must hold when wire bytes
    arrive through the kernel instead of an in-process pipe."""
    import hashlib

    enc = protocol.encode()
    dec = protocol.decode(backend="tpu")
    got = {"digests": [], "blobs": [], "changes": []}
    dec.on_digest(lambda kind, seq, digest: got["digests"].append(
        (kind, seq, digest)))
    dec.change(lambda change, done: (got["changes"].append(change.key), done()))
    dec.blob(lambda blob, done: blob.collect(
        lambda d: (got["blobs"].append(d), done())))
    fin = {"done": False}
    dec.finalize(lambda done: (fin.__setitem__("done", True), done()))

    sess = transport.session_over_socketpair(enc, dec)
    enc.change({"key": "k", "change": 1, "from": 0, "to": 1, "value": b"VV"})
    ws = enc.blob(6)
    ws.write(b"abc")
    ws.end(b"def")
    enc.finalize()
    sess.wait()

    assert fin["done"] and dec.finished
    assert got["changes"] == ["k"] and got["blobs"] == [b"abcdef"]
    # all digests delivered before finalize, byte-exact vs hashlib
    # (change digests cover the serialized payload, blob digests the body)
    from dat_replication_protocol_tpu.wire.change_codec import encode_change

    payload = encode_change(
        {"key": "k", "change": 1, "from": 0, "to": 1, "value": b"VV"})
    kinds = {(k, s): d for k, s, d in got["digests"]}
    assert kinds[("change", 0)] == hashlib.blake2b(
        payload, digest_size=32).digest()
    assert kinds[("blob", 0)] == hashlib.blake2b(
        b"abcdef", digest_size=32).digest()


def test_tpu_backend_bulk_write_digests_every_change():
    """>= 16 changes in one large write go through the decoder's native
    bulk index; the digest hook must still fire for every change (the
    bulk path bypasses _finish_change's re-parse)."""
    import hashlib

    from dat_replication_protocol_tpu.wire.change_codec import encode_change
    from dat_replication_protocol_tpu.wire.framing import TYPE_CHANGE, frame

    payloads = [encode_change({
        "key": f"bk{i}", "change": i, "from": i, "to": i + 1,
        "value": b"val-%d" % i,
    }) for i in range(40)]
    wire = b"".join(frame(TYPE_CHANGE, p) for p in payloads)

    dec = protocol.decode(backend="tpu")
    digests = {}
    dec.on_digest(lambda kind, seq, d: digests.__setitem__((kind, seq), d))
    dec.change(lambda change, done: done())
    dec.write(wire)
    dec.end()
    assert dec.finished
    for i, p in enumerate(payloads):
        assert digests[("change", i)] == hashlib.blake2b(
            p, digest_size=32).digest(), i
