"""Randomized session scripts: ordering/content invariants under fuzz.

The fixed conformance suite pins the reference's documented orderings;
this property test drives randomly generated producer scripts (blob
creations, interleaved chunk writes, changes submitted at arbitrary
moments) through randomly chunked decoder feeds and checks the
invariants that hold for every schedule:

* changes arrive exactly once, in submission order among themselves;
* blobs arrive intact and in creation order (FIFO framing,
  reference: encode.js:87-95);
* a change submitted while no blob was open precedes any blob created
  after it;
* byte/frame counters agree on both ends and the finalize hook fires
  last.
"""

import random

import dat_replication_protocol_tpu as protocol


def _run_script(seed: int) -> None:
    rng = random.Random(seed)
    enc, dec = protocol.encode(), protocol.decode()

    events = []
    dec.change(lambda c, done: (events.append(("change", c.key)), done()))
    dec.blob(
        lambda b, done: b.collect(
            lambda d, _b=b: (events.append(("blob", d)), done())
        )
    )
    dec.finalize(lambda done: (events.append(("finalize",)), done()))

    sent_changes = []
    blob_payloads = []
    open_blobs = []  # (writer, payload, written)
    clear_points = []  # change keys submitted while no blob was open
    n_actions = rng.randrange(10, 40)
    ci = 0
    for _ in range(n_actions):
        act = rng.random()
        if act < 0.35:  # submit a change
            key = f"c{ci}"
            ci += 1
            enc.change(
                {"key": key, "change": ci, "from": ci, "to": ci + 1,
                 "value": bytes(rng.randrange(0, 30))}
            )
            sent_changes.append(key)
            if not open_blobs:
                clear_points.append((key, len(blob_payloads)))
        elif act < 0.65:  # open a blob
            size = rng.randrange(1, 2000)
            # unique prefix: duplicate payloads would make the
            # events.index ordering assertions below ambiguous
            uid = len(blob_payloads).to_bytes(2, "little")  # low byte
            # first, so even 1-byte blobs stay unique within a script
            payload = uid[: min(2, size)] + rng.randbytes(size - min(2, size))
            ws = enc.blob(size)
            open_blobs.append([ws, payload, 0])
            blob_payloads.append(payload)
        elif open_blobs:  # write a chunk into a random open blob
            slot = rng.choice(open_blobs)
            ws, payload, written = slot
            n = rng.randrange(1, len(payload) - written + 1)
            ws.write(payload[written:written + n])
            slot[2] += n
            if slot[2] == len(payload):
                ws.end()
                open_blobs.remove(slot)
    for ws, payload, written in open_blobs:
        ws.end(payload[written:])
    enc.finalize()

    # pump with randomly sized decoder feeds (1..4096 bytes)
    wire = bytearray()
    while True:
        piece = enc.read(rng.randrange(1, 4096))
        if piece is None:
            break
        if piece:
            wire += piece
    i = 0
    while i < len(wire):
        n = rng.randrange(1, 4096)
        assert dec.write(bytes(wire[i:i + n]))
        i += n
    dec.end()

    assert events[-1] == ("finalize",)
    got_changes = [k for t, k in events[:-1] if t == "change"]
    got_blobs = [d for t, d in events[:-1] if t == "blob"]
    assert got_changes == sent_changes, seed
    assert got_blobs == blob_payloads, seed
    for key, n_blobs_before in clear_points:
        # a change submitted while no blob was open must precede every
        # blob created after it
        c_at = events.index(("change", key))
        for payload in blob_payloads[n_blobs_before:]:
            assert c_at < events.index(("blob", payload)), seed
    assert enc.bytes == dec.bytes, seed
    assert enc.changes == dec.changes == len(sent_changes), seed
    assert enc.blobs == dec.blobs == len(blob_payloads), seed


def test_random_session_scripts():
    for seed in range(60):
        _run_script(seed)
