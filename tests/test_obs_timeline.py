"""The offline timeline CLI (ISSUE 4 tentpole): merging two peers'
JSONL logs into one causally-ordered timeline keyed on wire offset,
with zero spurious gap/reorder/duplicate flags on clean runs — a clean
RESUMED run included (resume must never look like duplicate delivery)
— and true positives on doctored logs.
"""

from __future__ import annotations

import json
import os

import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.obs import events as obs_events
from dat_replication_protocol_tpu.obs import tracing
from dat_replication_protocol_tpu.obs.__main__ import main as obs_main
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    FaultyReader,
    bytes_reader,
)
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    run_resumable,
)
from dat_replication_protocol_tpu.session.resume import WireJournal


def _detach():
    obs_events.EVENTS.detach_sink()
    tracing.SPANS.detach_sink()


def _peer_logs(tmp_path, drop: bool):
    """Run a sender phase then a receiver phase, each mirroring its
    telemetry into its own JSONL file — the two-peer log pair the CLI
    merges.  ``drop`` injects a mid-session disconnect + resume."""
    send_log = str(tmp_path / "sender.jsonl")
    recv_log = str(tmp_path / "receiver.jsonl")

    sink = tracing.attach_jsonl_sink(send_log)
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    for i in range(50):
        e.change({"key": f"k{i}", "change": i, "from": i, "to": i + 1,
                  "value": b"v" * (i % 20)})
    b = e.blob(100)
    b.write(b"x" * 100)
    b.end()
    e.finalize()
    while e.read(4096) is not None:
        pass
    wire = j.read_from(0)
    _detach()
    sink.close()

    sink = tracing.attach_jsonl_sink(recv_log)
    dec = protocol.decode()
    dec.change(lambda c, done: done())
    dec.blob(lambda blob, done: blob.collect(lambda _d: done()))

    def source(ckpt, failures):
        plan = FaultPlan(
            seed=failures, max_segment=64,
            drop_at=(len(wire) // 2 - ckpt.wire_offset)
            if (drop and failures == 0) else None)
        return FaultyReader(bytes_reader(wire[ckpt.wire_offset:]), plan)

    stats = run_resumable(source, dec,
                          BackoffPolicy(base=0, max_retries=3, seed=1),
                          expected_total=len(wire))
    _detach()
    sink.close()
    assert stats["reconnects"] == (1 if drop else 0)
    return send_log, recv_log


def test_clean_run_merges_with_zero_flags(obs_enabled, tmp_path, capsys):
    send_log, recv_log = _peer_logs(tmp_path, drop=False)
    rc = obs_main(["timeline", send_log, recv_log, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["flags"] == []
    assert out["sender"]["covered"] == out["receiver"]["covered"] > 0
    # causal order: at any shared offset, the sender's emission row
    # precedes the receiver's dispatch row
    seen_roles_at: dict[int, list[str]] = {}
    for row in out["timeline"]:
        if row["name"] in ("encoder.frame", "decoder.frame"):
            seen_roles_at.setdefault(row["offset"], []).append(row["role"])
    for off, roles in seen_roles_at.items():
        assert roles == ["sender", "receiver"], (off, roles)


def test_resumed_run_still_flags_nothing(obs_enabled, tmp_path, capsys):
    """A drop + reconnect + journal replay delivers every frame exactly
    once — the timeline must NOT read recovery as duplication."""
    send_log, recv_log = _peer_logs(tmp_path, drop=True)
    rc = obs_main(["timeline", send_log, recv_log, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["flags"]
    assert out["flags"] == []
    # the fault and the resumed connection are ON the timeline
    names = [row["name"] for row in out["timeline"]]
    assert "fault.drop" in names and "session.connect" in names


def _doctor(path: str, mutate) -> str:
    lines = open(path).read().splitlines()
    out = path + ".doctored"
    with open(out, "w") as f:
        f.write("\n".join(mutate(lines)) + "\n")
    return out


def test_duplicate_delivery_is_flagged(obs_enabled, tmp_path, capsys):
    send_log, recv_log = _peer_logs(tmp_path, drop=False)
    dup = _doctor(recv_log, lambda lines: lines + [
        next(ln for ln in lines if '"decoder.frame"' in ln)])
    rc = obs_main(["timeline", send_log, dup, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["flag"] == "duplicate"
               and f["role"] == "receiver:dispatch" for f in out["flags"])


def test_gap_is_flagged_with_missing_byte_count(obs_enabled, tmp_path,
                                                capsys):
    send_log, recv_log = _peer_logs(tmp_path, drop=False)

    def drop_one(lines):
        victim = [ln for ln in lines if '"decoder.frame"' in ln][3]
        missing = json.loads(victim)["fields"]["wire_len"]
        drop_one.missing = missing
        return [ln for ln in lines if ln != victim]

    gap = _doctor(recv_log, drop_one)
    rc = obs_main(["timeline", send_log, gap, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    flags = [f for f in out["flags"] if f["flag"] == "gap"]
    assert flags and flags[0]["missing"] == drop_one.missing
    # losing a frame also diverges the peers' totals
    assert any(f["flag"] == "peer-divergence" for f in out["flags"])


def test_reorder_is_flagged(obs_enabled, tmp_path, capsys):
    send_log, recv_log = _peer_logs(tmp_path, drop=False)

    def swap(lines):
        idx = [i for i, ln in enumerate(lines) if '"decoder.frame"' in ln]
        a, b = idx[2], idx[3]
        lines[a], lines[b] = lines[b], lines[a]
        return lines

    swapped = _doctor(recv_log, swap)
    rc = obs_main(["timeline", send_log, swapped, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["flag"] == "reorder"
               and f["role"] == "receiver:dispatch" for f in out["flags"])


def test_duplex_peer_log_does_not_self_collide(obs_enabled, tmp_path,
                                               capsys):
    """A sidecar-shaped peer mirrors BOTH its request-side dispatch
    tags and its reply-side emission tags into one log; the two wire
    streams' offsets both start at 0 and must be audited separately —
    a clean duplex session flags nothing."""
    client_log = str(tmp_path / "client.jsonl")
    sidecar_log = str(tmp_path / "sidecar.jsonl")

    # client phase: emit the request wire
    sink = tracing.attach_jsonl_sink(client_log)
    e = protocol.encode()
    j = WireJournal()
    e.attach_journal(j)
    for i in range(10):
        e.change({"key": f"req{i}", "change": i, "from": i, "to": i + 1})
    e.finalize()
    while e.read(4096) is not None:
        pass
    wire = j.read_from(0)
    _detach()
    sink.close()

    # "sidecar" phase: dispatch the request AND emit a reply, one log
    sink = tracing.attach_jsonl_sink(sidecar_log)
    dec = protocol.decode()
    reply = protocol.encode()
    seq = [0]

    def on_change(c, done):
        reply.change({"key": f"digest-{seq[0]}", "change": seq[0],
                      "from": 0, "to": 1})
        seq[0] += 1
        done()

    dec.change(on_change)
    dec.write(wire)
    dec.end()
    reply.finalize()
    while reply.read(4096) is not None:
        pass
    _detach()
    sink.close()

    rc = obs_main(["timeline", client_log, sidecar_log, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0, out["flags"]
    assert out["flags"] == []


def test_torn_final_line_is_tolerated(obs_enabled, tmp_path, capsys):
    """A sink that latched dead leaves an unterminated last line; the
    CLI must keep it visible without corrupting the merge."""
    send_log, recv_log = _peer_logs(tmp_path, drop=False)
    with open(recv_log, "a") as f:
        f.write('{"seq": 99999, "span": "decoder.fra')  # torn
    rc = obs_main(["timeline", send_log, recv_log, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0  # the torn fragment is not a frame record
    assert out["flags"] == []


def test_text_output_summarizes_and_orders(obs_enabled, tmp_path, capsys):
    send_log, recv_log = _peer_logs(tmp_path, drop=True)
    rc = obs_main(["timeline", send_log, recv_log])
    text = capsys.readouterr().out
    assert rc == 0
    assert "no gaps, reorders, or duplicate deliveries" in text
    # offsets in the rendered merge never go backwards
    offs = [int(ln[1:].split()[0]) for ln in text.splitlines()
            if ln.startswith(("@", "~"))]
    assert offs == sorted(offs)


def test_export_trace_from_jsonl_and_bundle(obs_enabled, tmp_path, capsys):
    send_log, recv_log = _peer_logs(tmp_path, drop=False)
    out_path = str(tmp_path / "recv.trace.json")
    rc = obs_main(["export-trace", recv_log, "-o", out_path])
    capsys.readouterr()
    assert rc == 0
    doc = json.load(open(out_path))
    assert doc["traceEvents"]
    assert {ev["ph"] for ev in doc["traceEvents"]} <= {"X", "i"}
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert "decoder.frame" in names and "reconnect.attempt" in names

    # bundle form: dump one and export it
    from dat_replication_protocol_tpu.obs import flight

    flight.FLIGHT.arm(str(tmp_path / "fl"), enable_telemetry=False)
    bundle = flight.dump("timeline-test")
    rc = obs_main(["export-trace", bundle])
    capsys.readouterr()
    assert rc == 0
    assert json.load(open(os.path.join(bundle, "trace.json")))


def test_dump_subcommand_renders_bundle(obs_enabled, tmp_path, capsys):
    from dat_replication_protocol_tpu.obs import flight

    flight.FLIGHT.arm(str(tmp_path), enable_telemetry=False)
    dec = protocol.decode()
    dec.on_error(lambda _e: None)
    dec.write(b"\x05\x09zzzz")  # unknown type id -> protocol error
    assert dec.destroyed and flight.FLIGHT.last_bundle
    rc = obs_main(["dump", flight.FLIGHT.last_bundle])
    text = capsys.readouterr().out
    assert rc == 0
    assert "protocol-error" in text and "ProtocolError" in text
    assert "offset=" in text
    rc = obs_main(["dump", flight.FLIGHT.last_bundle, "--json"])
    blob = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert blob["manifest"]["error"]["type"] == "ProtocolError"


def test_timeline_cli_module_entrypoint_runs(obs_enabled, tmp_path):
    """`python -m dat_replication_protocol_tpu.obs` is the documented
    invocation — exercise the real subprocess once."""
    import subprocess
    import sys

    send_log, recv_log = _peer_logs(tmp_path, drop=False)
    r = subprocess.run(
        [sys.executable, "-m", "dat_replication_protocol_tpu.obs",
         "timeline", send_log, recv_log],
        capture_output=True, text=True, timeout=60,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stderr
    assert "no gaps" in r.stdout


@pytest.mark.parametrize("bad", ["missing.jsonl"])
def test_timeline_missing_file_errors_cleanly(tmp_path, bad):
    with pytest.raises(FileNotFoundError):
        obs_main(["timeline", str(tmp_path / bad), str(tmp_path / bad)])
