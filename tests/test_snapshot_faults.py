"""Snapshot bootstrap chaos (ISSUE 12): the joiner wire under faults.

The responder->joiner stream of one stale-joiner bootstrap session
(BEGIN, SYMBOLS rounds, CHUNKS, DONE) is recorded once through the real
encoder + journal, then replayed into a fresh joiner through the
deterministic fault injector (session/faults.py) and the resumable
reconnect driver.  The contract (the exactly-once-resume face of
ROBUSTNESS.md's snapshot section): for every seed, a disconnect-class
fault (drop / truncation / stall / re-segmentation) ends in the
byte-exact assembled dataset with every wanted chunk verified EXACTLY
once — never a re-verified chunk, never a gap — and a corruption-class
fault (flip) yields ONE structured ProtocolError, never a silently
wrong dataset.  Tier-1 sweeps seeds 0..19; the ``slow`` soak covers
100 more.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

import dat_replication_protocol_tpu as protocol
from dat_replication_protocol_tpu.runtime.snapshot_driver import (
    SnapshotJoiner,
    SnapshotResponder,
    SnapshotSource,
)
from dat_replication_protocol_tpu.session.faults import (
    FaultPlan,
    FaultyReader,
    bytes_reader,
)
from dat_replication_protocol_tpu.session.reconnect import (
    BackoffPolicy,
    run_resumable,
)
from dat_replication_protocol_tpu.session.resume import WireJournal
from dat_replication_protocol_tpu.wire import snapshot_codec as sn
from dat_replication_protocol_tpu.wire.framing import (
    CAP_SNAPSHOT,
    ProtocolError,
    iter_frames,
)

HARD_TIMEOUT = 30.0  # per-case watchdog: "never a hang", enforced


def _build_wire():
    """Record the responder->joiner stream of one stale bootstrap: the
    driving joiner's replies steer the responder (symbol rounds, the
    WANT set, the chunk stream), but only the responder's direction is
    journaled — the replay side reconstructs everything from it."""
    rng = np.random.default_rng(0)
    # small on purpose: the sweep's re-segmentation arm delivers this
    # wire BYTE AT A TIME, so its length prices every seed
    data = rng.integers(0, 256, 48 << 10, dtype=np.uint8)
    src = SnapshotSource(data, avg_bits=9, wire_offset=1234)
    stale = data.copy()
    stale[src.offs[:: max(1, len(src.offs) // 6)]] ^= 0x5A
    resp = SnapshotResponder(src)
    pilot = SnapshotJoiner(stale.tobytes())
    e = protocol.encode(peer_caps=CAP_SNAPSHOT)
    j = WireJournal()
    e.attach_journal(j)
    pending = list(resp.begin_payloads())
    guard = 0
    while pending and not pilot.done:
        replies = []
        for payload in pending:
            e.snapshot_frame(payload)
            replies.extend(pilot.handle(sn.decode_snapshot(payload)))
        pending = []
        for r in replies:
            pending.extend(resp.handle(sn.decode_snapshot(r)))
        guard += 1
        assert guard < 1000
    e.finalize()
    while e.read(4096) is not None:
        pass
    assert pilot.result()["data"] == data.tobytes()
    wanted = pilot.chunks_verified
    assert wanted > 0  # the stream really carries chunk frames
    return j.read_from(0), data.tobytes(), stale.tobytes(), wanted


_WIRE, _DATA, _STALE, _WANTED = _build_wire()


def _frames(wire: bytes):
    """(start, payload_start, end, subtype) per TYPE_SNAPSHOT frame."""
    return [(start, p0, end, wire[p0])
            for start, _tid, p0, end in iter_frames(wire)]


def _fresh_joiner():
    joiner = SnapshotJoiner(_STALE)
    dec = protocol.decode()
    dec.snapshot(lambda msg, done: (joiner.handle(msg), done()))
    return dec, joiner


def _with_watchdog(fn):
    box: dict = {}

    def run():
        try:
            box["ret"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the test
            box["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(HARD_TIMEOUT)
    assert not t.is_alive(), f"HANG: case still running after {HARD_TIMEOUT}s"
    if "err" in box:
        raise box["err"]
    return box["ret"]


def _replay(seed=None, plan=None, max_retries=8):
    """Replay the recorded wire through a fault plan; returns
    (stats_or_None, joiner).  ``seed`` uses the sweep generator per
    attempt; ``plan`` pins one plan on attempt 0 and runs clean
    reconnects after."""
    dec, joiner = _fresh_joiner()

    def source(ckpt, failures):
        remaining = _WIRE[ckpt.wire_offset:]
        if plan is not None:
            p = plan if failures == 0 else FaultPlan(seed=failures)
        else:
            p = FaultPlan.for_sweep(seed, len(remaining), attempt=failures)
        return FaultyReader(bytes_reader(remaining), p)

    def drive():
        return run_resumable(
            source, dec,
            BackoffPolicy(base=0.0005, cap=0.005,
                          max_retries=max_retries, seed=seed or 1),
            chunk_size=256,  # small chunks: faults land mid-frame
            expected_total=len(_WIRE),
            stall_timeout=HARD_TIMEOUT / 2,
        )

    try:
        stats = _with_watchdog(drive)
    except ProtocolError as e:
        assert e.offset is not None, f"unstructured ProtocolError: {e}"
        return None, joiner
    return stats, joiner


def _assert_exactly_once(joiner):
    out = joiner.result()
    assert out["data"] == _DATA  # byte-exact assembly
    # exactly-once: every wanted chunk verified once — a resumed wire
    # never re-verifies (or double-counts) a chunk a previous
    # connection already delivered
    assert joiner.chunks_verified == _WANTED
    assert out["wire_offset"] == 1234


@pytest.mark.parametrize("seed", range(20))
def test_sweep_snapshot_resumes_exactly_once(seed):
    """Disconnect-class faults anywhere in the bootstrap stream: every
    seed must converge after resume to the byte-exact dataset with
    exactly-once chunk verification — never an error, never a hang."""
    stats, joiner = _replay(seed=seed)
    assert stats is not None, "disconnect-class fault must resume, not error"
    _assert_exactly_once(joiner)


@pytest.mark.slow
def test_sweep_snapshot_soak_100_seeds():
    wrong = []
    for seed in range(20, 120):
        stats, joiner = _replay(seed=seed)
        if stats is None:
            continue  # structured-error arm: allowed for double faults
        try:
            out = joiner.result()
        except ProtocolError:
            continue
        if out["data"] != _DATA or joiner.chunks_verified != _WANTED:
            wrong.append(seed)  # the one outcome the contract forbids
    assert not wrong, f"seeds {wrong} assembled a WRONG dataset"


def _first_frame(subtype):
    for start, p0, end, sub in _frames(_WIRE):
        if sub == subtype:
            return start, p0, end
    raise AssertionError(f"no subtype-{subtype} frame in the wire")


def test_truncate_mid_chunk_resumes_exactly_once():
    """A clean EOF inside a CHUNKS frame body is the silent-truncation
    fault: expected_total turns it into a reconnect, the torn frame was
    never delivered (whole-frame doctrine), and the resumed connection
    re-sends it without a single chunk verifying twice."""
    start, p0, end = _first_frame(sn.SN_CHUNKS)
    cut = p0 + (end - p0) // 2  # mid-body: digest+payload territory
    stats, joiner = _replay(plan=FaultPlan(truncate_at=cut))
    assert stats is not None and stats["reconnects"] >= 1
    _assert_exactly_once(joiner)


def test_drop_between_chunk_frames_resumes_exactly_once():
    start, p0, end = _first_frame(sn.SN_CHUNKS)
    stats, joiner = _replay(plan=FaultPlan(drop_at=end))
    assert stats is not None and stats["reconnects"] >= 1
    _assert_exactly_once(joiner)


def test_flip_inside_chunk_body_is_one_structured_error():
    """A flipped byte inside a chunk BODY passes the frame layer (the
    structure is intact) and MUST die at the joiner's per-chunk digest
    verification: one structured ProtocolError, never a silently wrong
    dataset."""
    start, p0, end = _first_frame(sn.SN_CHUNKS)
    # skip subtype byte + count varint + the 32-byte digest: land in
    # the first chunk's length/body region, far from frame headers
    flip = p0 + 40
    assert flip < end
    stats, joiner = _replay(plan=FaultPlan(flip_at=flip),
                            max_retries=0)
    if stats is None:
        # the flip landed structurally (length varint): the session
        # decoder's ProtocolError arm — equally structured, also fine
        assert joiner.data is None
        return
    with pytest.raises(ProtocolError) as ei:
        joiner.result()
    assert joiner.data is None  # nothing assembled
    assert ei.value.offset is not None


def test_flip_inside_symbols_never_yields_wrong_dataset():
    """A flipped coded-symbol cell perturbs the reconcile: whatever
    the peel concludes, the end state is either a correct dataset
    (the flip peeled into a spurious WANT the responder answered) or
    ONE structured error — never silent corruption."""
    start, p0, end = _first_frame(sn.SN_SYMBOLS)
    stats, joiner = _replay(plan=FaultPlan(flip_at=p0 + 16),
                            max_retries=0)
    if stats is None:
        return  # structured at the wire layer
    try:
        out = joiner.result()
    except ProtocolError as e:
        assert e.offset is not None
        return
    assert out["data"] == _DATA  # assembled => must be byte-exact


def test_stall_during_want_window_completes():
    """A long read stall at the symbols/chunks boundary — the window
    where the live joiner would be sending its WANT — must ride the
    bounded waits to completion, not hang and not error."""
    start, p0, end = _first_frame(sn.SN_CHUNKS)
    stats, joiner = _replay(
        plan=FaultPlan(stall_at=start, stall_s=1.5))
    assert stats is not None and stats["reconnects"] == 0
    _assert_exactly_once(joiner)


def test_chaos_ground_truth_counters_agree(obs_enabled):
    """The injector's ground-truth counters vs the snapshot session's
    own story: a truncate-then-resume run fires exactly one injected
    truncation and the joiner's verified-chunk counter matches its
    stats (the conformance-oracle face of OBSERVABILITY.md)."""
    from dat_replication_protocol_tpu.obs.metrics import REGISTRY

    start, p0, end = _first_frame(sn.SN_CHUNKS)
    stats, joiner = _replay(plan=FaultPlan(truncate_at=p0 + 8))
    assert stats is not None
    _assert_exactly_once(joiner)
    assert REGISTRY.counter("fault.injected.truncate").value == 1
    assert REGISTRY.counter(
        "snapshot.chunks.verified").value == joiner.chunks_verified
